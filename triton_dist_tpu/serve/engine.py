"""The continuous-batching step loop over a paged KV cache.

Each :meth:`ServeEngine.step` is one scheduler iteration (Orca's
iteration-level scheduling):

1. **Admit** waiting requests into free batch slots while the block
   manager can cover their prompt (+1 decode block of headroom).
2. **Prefill** admitted prompts in chunks against a per-request scratch
   cache (``Generator._chunk_jit`` — the chunked-prefill machinery),
   metered by the scheduler's token budget so long prompts interleave
   with in-flight decode; a completed prompt's K/V scatter into the
   request's pool pages and the request joins the decode batch.
3. **Decode** all running rows — with a decode ``horizon`` H > 1, up to
   H steps FUSE into one device dispatch (``_paged_decode_horizon``: a
   traced scan with on-device sampling and KV commit, pipelined so the
   host commits horizon N's token burst while the device runs horizon
   N+1 — docs/serving.md "Decode horizon"); at H=1, one batched forward
   per step through
   ``kernels/flash_decode.gqa_decode_paged_shard`` — per-row lengths,
   per-row block tables, the r5 ``active`` mask semantics (retired/free
   rows freeze; their dummy K/V writes redirect to the reserved null
   block so freed pages can never be corrupted — the paged twin of the
   ``_write_rows`` overflow rule).  With a draft model attached, the
   decode step becomes a speculative round: the draft proposes ``k``
   tokens per row and ONE multi-token verify pass scores every row at
   its own length (the r5 ``q_lens`` batched-verify contract), accepts
   applying per row.  Since PR 7 the WHOLE round — draft k-step scan,
   verify, seeded accept, closing decode for both models — fuses into
   one traced program (``_spec_round_fused``) chained ``pipeline`` deep
   on a device-resident carry, with adaptive per-row ``k`` bucketed
   down a pow2 k-ladder; sampled requests ride the same seeded accept
   chain (docs/serving.md "Speculative decoding").

Requests retire individually (their blocks free immediately); when a
running request cannot extend its allocation, the scheduler preempts the
latest-admitted request (recompute-style: emitted tokens are kept and the
victim re-prefills ``prompt + generated``).

Compilation is BOUNDED and observable (PR 2): prefill always runs the
one fixed ``prefill_chunk`` shape (final residual padded, its K/V writes
zero-masked via ``n_valid``), scratch extents and the page scatter
bucket to a powers-of-two ladder, :meth:`ServeEngine.warmup`
pre-compiles the lot, and every program's trace-cache hit/miss/stall
counters ride ``ServeMetrics`` (docs/serving.md "bucket ladder").

Failures are CONTAINED (PR 3, docs/serving.md "Failure containment"):
requests carry optional deadlines (expired WAITING/PREFILL requests are
swept each step), ``submit()`` enforces an optional queue bound with a
shed-or-raise policy, a poison request — a raising ``on_token``
callback, a failing forward, a failed mid-decode block grow — is
quarantined (retired ``FinishReason.ERROR``, blocks freed) while its
slot-mates keep decoding (batched-forward failures bisect over the
batch to isolate the poison row), every device dispatch runs under an
optional step watchdog, and the step loop drives a synchronous
:class:`runtime.watchdog.Heartbeat` so an external supervisor sees a
wedged engine as a stale file.  A ``runtime.faults.FaultInjector``
threads through the engine/block-manager seams so every containment
path is exercised by deterministic chaos tests.

The process itself is EXPENDABLE (PR 5, docs/serving.md "Crash
recovery"): with ``snapshot_dir=`` every submit/commit/retire appends
to a durable token journal and ``snapshot_every=N`` captures the paged
KV pools + a state manifest through the ``runtime/checkpoint`` Orbax
path; :meth:`ServeEngine.restore` rebuilds a fresh engine whose every
resumed stream is bit-identical to the uninterrupted run — tokens are
emitted exactly once across the crash (journal-matching rows resume in
place, journal-ahead rows replay through the exact-recompute
preemption path; serve/recovery.py holds the argument).

The engine is MESH-AWARE (PR 12, docs/serving.md "Sharded serving"):
``mesh=``/``tp_axis=``/``kv_shard=`` rebuild every device program above
as a ``shard_map`` body (serve/mesh.py) — TP weights + head-sharded
pools (``"heads"``: Megatron attention, per-rank paged decode, spec
rounds included) or replicated weights + block-sharded pools through
``sp_gqa_decode_paged_shard`` (``"seq"``: SP flash-decode with a
partitioned block allocator).  The scheduler, block tables, journal,
and step loop are unchanged host machinery; streams stay bit-identical
to the world-1 engine and snapshots restore across mesh shapes.

KV pools are float by default, or INT8 with per-page scale planes
(ISSUE 17, docs/serving.md "Quantized serving"): construct the
``Generator`` with ``kv_dtype=jnp.int8`` and every pool layer becomes a
``{"q": int8 [NB, Hkv, page, D], "s": f32 [NB, Hkv, page]}`` pair —
``_scatter_kv`` quantizes rows as they land (``flash_decode.quantize_kv``,
the contiguous cache's recipe), the scale plane moves WITH its page
through fill/gather/COW/snapshot/migration (never a dequant/requant
round trip — quantization is not idempotent, so bit-reproducibility
demands the bytes move as bytes), and attention dequantizes inside
``gqa_decode_paged_shard``'s fused int8 path.  The emitted stream is
bit-reproducible (same stream every run; snapshot/restore/migrate
bit-exact; mesh bit-identical to quantized world-1) and tracked against
the fp oracle by an explicit acceptance metric — the two-gate split
ROADMAP #3 prescribes.  Speculative decoding over int8 pools is a
recorded follow-up (rejected loudly at construction).

Scope: dense-Llama-family ``Generator`` (the same envelope as the r5
batched speculative verify; batch-1 SP serving keeps the contiguous
`Generator.generate` path).
"""

from __future__ import annotations

import contextlib
import functools
import math
import os
import sys
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.kernels.flash_decode import (
    gqa_decode_paged_shard,
    quantize_kv,
)
from triton_dist_tpu.models.generate import (
    GenerationState,
    Generator,
    _multitoken_forward,
    _token_forward,
    _write_rows,
)
from triton_dist_tpu.models.sampling import (
    sample_logits,
    sample_logits_rowwise,
    sample_positions_rowwise,
)
from triton_dist_tpu.models.speculative import (
    accept_chain_rowwise,
    greedy_accept_chain_batched,
)
from triton_dist_tpu.runtime import dump as ir_dump
from triton_dist_tpu.runtime.faults import FaultInjector
from triton_dist_tpu.runtime.jit_cache import (
    CountingJit,
    bucket_down,
    pow2_ladder,
)
from triton_dist_tpu.runtime.watchdog import (
    Heartbeat,
    WatchdogTimeout,
    run_with_watchdog,
)
from triton_dist_tpu.serve.block_manager import BlockExhausted, BlockManager
from triton_dist_tpu.serve.metrics import RequestMetrics, ServeMetrics
from triton_dist_tpu.serve.recovery import (
    JOURNAL_NAME,
    TokenJournal,
    has_restorable_state,
)
from triton_dist_tpu.serve.request import (
    SLO_CLASSES,
    FinishReason,
    Request,
    RequestOutput,
    SamplingParams,
    slo_rank,
)
from triton_dist_tpu.serve.scheduler import FCFSScheduler, ReqState, Status
from triton_dist_tpu.serve.trace import MIGRATE_EVENT_TAIL, FlightRecorder


class QueueFull(RuntimeError):
    """``submit()`` rejected a request: the waiting queue is at
    ``max_queue`` and the engine runs the ``"raise"`` overload policy
    (the ``"shed"`` policy retires the request ``FinishReason.SHED``
    instead of raising)."""


class ChainCommitted(RuntimeError):
    """A pipelined decode-horizon chain failed AFTER some of its token
    bursts were already committed: the retry/bisect machinery must NOT
    re-run it (a retry would double-emit the committed bursts), so it
    escalates out of ``step()`` like a consumed-pool failure."""


# Exceptions containment must NEVER swallow: a tripped step watchdog is
# an engine-level stall (the caller decides whether to checkpoint or
# abort), and interrupts/exits belong to the process.
_FATAL = (WatchdogTimeout, KeyboardInterrupt, SystemExit)


# ---------------------------------------------------------------------------
# Paged model forwards (jitted once per engine; dense Llama family)
# ---------------------------------------------------------------------------


def _page_slots(tables, kv_lens, active, *, page):
    """Physical (pool row, in-page row) for each batch row's next write.
    Inactive rows redirect to the null block (pool row 0, row 0): their
    table entries may be stale — a freed page can already belong to
    another request, and a clamped write there would corrupt it."""
    n_pages = tables.shape[1]
    logical = jnp.minimum(kv_lens // page, n_pages - 1)[:, None]
    pool_row = jnp.take_along_axis(tables, logical, axis=1)[:, 0]
    in_page = kv_lens % page
    return (jnp.where(active, pool_row, 0),
            jnp.where(active, in_page, 0))


def _scatter_kv(pool, k, v, pool_row, in_page):
    """The ONE paged K/V write: scatter new rows into pool pages at
    (pool_row, in_page) — [B] indices for a decode token, [B, T] for a
    verify chunk.  Both paged forwards use it, so the write can never
    diverge between decode and verify.

    Quantized pools (``{"q", "s"}`` dicts) quantize each new row HERE —
    ``quantize_kv``'s per-(head, position) absmax over D, the identical
    recipe the contiguous quantized cache uses — so a row's int8 bytes
    and its scale land together and never drift apart."""
    k_pool, v_pool = pool
    if isinstance(k_pool, dict):
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        return ({"q": k_pool["q"].at[pool_row, :, in_page, :].set(kq),
                 "s": k_pool["s"].at[pool_row, :, in_page].set(ks)},
                {"q": v_pool["q"].at[pool_row, :, in_page, :].set(vq),
                 "s": v_pool["s"].at[pool_row, :, in_page].set(vs)})
    return (k_pool.at[pool_row, :, in_page, :].set(k.astype(k_pool.dtype)),
            v_pool.at[pool_row, :, in_page, :].set(v.astype(v_pool.dtype)))


def _pool_views(pool):
    """``(k, v, k_scale, v_scale)`` kernel views of one pool layer: bare
    float pools give ``(k, v, None, None)``; int8 dict pools expose
    their quant and scale planes so attend closures pass them straight
    to the paged kernels without branching on layout anywhere else."""
    k_pool, v_pool = pool
    if isinstance(k_pool, dict):
        return k_pool["q"], v_pool["q"], k_pool["s"], v_pool["s"]
    return k_pool, v_pool, None, None


def _paged_decode_forward(params, pools, tables, kv_lens, token, active, *,
                          cfg, page, impl, interpret, fwd_cfg=None,
                          ffn=None, out_proj=None):
    """One decode token for every batch row over the paged pools.

    ``generate._token_forward`` (the same math as ``_step_impl`` — the
    greedy stream must be bit-identical to the contiguous oracle) with
    the contiguous append swapped for a pool-page scatter and attention
    through the paged block-table kernel.

    ``fwd_cfg``/``ffn``/``out_proj`` are the tensor-parallel seams
    (serve/mesh.py): the layer math runs under ``fwd_cfg`` (the
    local-head shard view) with row-parallel psum hooks, while the page
    addressing and the attention kernel's soft-cap/window stay on the
    global ``cfg`` — ONE copy of the block-table addressing serves the
    world-1 engine and every head-sharded rank."""
    inc = active.astype(kv_lens.dtype)
    pool_row, in_page = _page_slots(tables, kv_lens, active, page=page)

    def write_kv(li, pool, k, v):
        return _scatter_kv(pool, k, v, pool_row, in_page)

    def attend(li, q, pool):
        kq, vq, ks, vs = _pool_views(pool)
        o, _ = gqa_decode_paged_shard(
            q, kq, vq, tables, kv_lens + inc, impl=impl,
            interpret=interpret, soft_cap=cfg.attn_soft_cap,
            window=cfg.attn_window, k_scale=ks, v_scale=vs)
        return o

    return _token_forward(params, pools, token, kv_lens,
                          cfg=fwd_cfg or cfg, write_kv=write_kv,
                          attend=attend, ffn=ffn, out_proj=out_proj)


def _paged_verify_forward(params, pools, tables, kv_lens, chunk, active, *,
                          cfg, page, impl, interpret, fwd_cfg=None,
                          ffn=None, out_proj=None):
    """Score ``chunk`` [B, T] draft tokens per row at PER-ROW lengths over
    the paged pools — ``generate._multitoken_forward`` (the same math as
    ``_verify_forward``) re-addressed through block tables (K/V rows
    scatter into each request's pages, the multi-token decode kernel
    reads them back through the table).  Returns (new_pools,
    logits [B, T, V]).  ``fwd_cfg``/``ffn``/``out_proj`` as in
    :func:`_paged_decode_forward` — the TP seams."""
    T = chunk.shape[1]
    n_pages = tables.shape[1]
    pos = kv_lens[:, None] + jnp.arange(T, dtype=jnp.int32)[None]  # [B, T]
    logical = jnp.minimum(pos // page, n_pages - 1)
    pool_row = jnp.take_along_axis(tables, logical, axis=1)       # [B, T]
    in_page = pos % page
    pool_row = jnp.where(active[:, None], pool_row, 0)
    in_page = jnp.where(active[:, None], in_page, 0)

    def write_kv(li, pool, k, v):
        return _scatter_kv(pool, k, v, pool_row, in_page)

    def attend(li, q, pool):
        kq, vq, ks, vs = _pool_views(pool)
        o, _ = gqa_decode_paged_shard(
            q, kq, vq, tables, kv_lens + T, impl=impl,
            interpret=interpret, soft_cap=cfg.attn_soft_cap,
            window=cfg.attn_window, k_scale=ks, v_scale=vs)
        return o

    return _multitoken_forward(params, pools, chunk, pos,
                               cfg=fwd_cfg or cfg, write_kv=write_kv,
                               attend=attend, ffn=ffn,
                               out_proj=out_proj)


def _paged_decode_horizon(params, pools, tables, kv_lens, token, active,
                          eos_done, limits, counts, base_keys, temps,
                          top_ks, top_ps, greedy, eos_ids, *, H,
                          all_greedy, cfg, page, impl, interpret,
                          decode_fwd=None):
    """Up to ``H`` decode steps for every batch row in ONE traced program:
    a ``lax.scan`` over :func:`_paged_decode_forward` (bit-identical
    per-step math) with ON-DEVICE sampling and on-device KV/length
    commit.  The host dispatches once and drains a ``[B, H]`` token burst
    instead of paying a dispatch + logits sync + host sample per token —
    the per-token fixed tax the decode horizon exists to remove
    (docs/serving.md "Decode horizon").

    Per-row early exit rides the masks, never the scan length: row ``b``
    executes ``min(limits[b], steps-to-EOS)`` steps, then freezes exactly
    like an inactive row (K/V writes redirect to the null block, length
    pinned) while its slot-mates run the full horizon.  ``limits`` is the
    host's per-row step budget — remaining max-tokens AND the allocated
    page capacity (the page-boundary early exit: a row may never write
    past the blocks the host reserved for it).  ``eos_done`` carries
    ACROSS chained dispatches: the async pipeline launches horizon N+1
    before horizon N drains, so the device itself must remember who
    already hit EOS.

    Token choice matches the host path bit for bit: greedy rows argmax;
    sampled rows draw through ``sampling.sample_logits_rowwise`` with a
    ``fold_in(key(seed), emitted_index)`` stream — the same stream
    ``_choose_token`` folds on host, so a preempted-and-recomputed or
    H=1-served request emits identical tokens.  ``all_greedy`` (static)
    drops the sampling machinery from the trace for greedy-only batches.

    Returns ``(pools, tokens [B, H], emitted [B, H] bool, kv_lens,
    last_token, eos_done, counts)`` — the trailing carries re-enter the
    next chained dispatch without touching the host.
    """
    # ``base_keys`` are HOST-built per-row typed keys (the engine stacks
    # jax.random.key(p.seed) — the exact call `_choose_token` makes, so
    # any seed the host path accepts, e.g. >= 2**31, streams identically
    # here instead of overflowing an int32 seed array).
    #
    # ``decode_fwd`` swaps the per-step forward: the default world-1
    # paged decode, or serve/mesh.py's TP/SP shard body when the scan
    # runs inside a mesh engine's shard_map (same signature, sharded
    # pools) — sampling and the carries stay replicated either way.
    if decode_fwd is None:
        decode_fwd = functools.partial(_paged_decode_forward, cfg=cfg,
                                       page=page, impl=impl,
                                       interpret=interpret)
    has_eos = eos_ids >= 0

    def step(carry, t):
        pools, kv_lens, token, eos_done, counts = carry
        live = active & ~eos_done & (t < limits)
        pools, logits = decode_fwd(params, pools, tables, kv_lens,
                                   token, live)
        kv_lens = kv_lens + live.astype(kv_lens.dtype)
        if all_greedy:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            keys = jax.vmap(jax.random.fold_in)(base_keys, counts)
            nxt = sample_logits_rowwise(logits, keys, temperature=temps,
                                        top_k=top_ks, top_p=top_ps,
                                        greedy=greedy)
        nxt = jnp.where(live, nxt, token)
        counts = counts + live.astype(counts.dtype)
        eos_done = eos_done | (live & has_eos & (nxt == eos_ids))
        return (pools, kv_lens, nxt, eos_done, counts), (nxt, live)

    carry = (pools, kv_lens, token, eos_done, counts)
    (pools, kv_lens, token, eos_done, counts), (toks, mask) = jax.lax.scan(
        step, carry, jnp.arange(H, dtype=jnp.int32))
    return (pools, toks.T, mask.T, kv_lens, token, eos_done, counts)


def _draft_decode_forward(params, caches, kv_lens, token, active, *,
                          cfg, impl, interpret):
    """One draft decode token over the slot-indexed contiguous batch
    caches — ``Generator._step_impl``'s math (the same
    ``_token_forward``) with MESH-FREE addressing: the per-row append
    rides ``_write_rows`` (overflow rows skipped, the dead-slot rule)
    and attention the bare ``gqa_decode_shard`` kernel.  The fused spec
    round traces THIS instead of the draft's own ``step`` because the
    layer path routes through ``cached_shard_jit`` shard_map closures:
    a world-1 engine gains nothing from the mesh, but mesh-placed
    program outputs would carry ``NamedSharding`` while host-built
    round openers carry ``SingleDeviceSharding`` — forking the
    executable cache into flavors warmup cannot enumerate.  Mesh-free,
    one program per (K rung, sampler mix) covers every call.  Frozen
    rows (``active`` False) keep their length; their dummy write lands
    in the dead row at ``kv_lens[b]``."""
    from triton_dist_tpu.kernels.flash_decode import gqa_decode_shard

    inc = active.astype(kv_lens.dtype)

    def write_kv(li, cache, k, v):
        k_c, v_c = cache
        return (_write_rows(k_c, k[:, :, None, :], kv_lens),
                _write_rows(v_c, v[:, :, None, :], kv_lens))

    def attend(li, q, cache):
        o, _ = gqa_decode_shard(q, cache[0], cache[1], kv_lens + inc,
                                impl=impl, interpret=interpret,
                                soft_cap=cfg.attn_soft_cap,
                                window=cfg.attn_window)
        return o

    new_caches, logits = _token_forward(params, caches, token, kv_lens,
                                        cfg=cfg, write_kv=write_kv,
                                        attend=attend)
    return new_caches, kv_lens + inc, logits


def _spec_round_fused(params, draft_params, pools, dcaches, tables,
                      kv_lens, active, done, last_logits, dlast_logits,
                      counts, limits, k_rows, base_keys, temps, top_ks,
                      top_ps, greedy, eos_ids, *, K, all_greedy, cfg,
                      page, impl, interpret, draft_step,
                      decode_fwd=None, verify_fwd=None):
    """One WHOLE speculative round in ONE traced program — the spec twin
    of :func:`_paged_decode_horizon` (docs/serving.md "Speculative
    decoding").  The unfused round pays 3+k host round trips (k draft
    steps, the verify, the accept sync, the closing decode); here the
    draft's k-step ``lax.scan``, the target's multi-token verify, the
    on-device accept, and the round-closing target+draft decode all run
    in one dispatch, and the trailing carries re-enter the next chained
    round without touching the host (``pipeline=N``).

    Acceptance is SEEDED-STREAM matching: ``expected[b, j]`` is the
    target's OWN next-token choice at emission index ``counts[b] + j``
    (greedy argmax, or ``sample_positions_rowwise`` — the exact
    ``fold_in(key(seed), index)`` draw every other decode path makes),
    and a proposal is accepted iff it EQUALS it
    (``speculative.accept_chain_rowwise`` holds the correctness
    argument: the emitted chain is always a prefix of the target's own
    stream, so spec serving is bit-identical to draft-less serving —
    sampled requests included, which is what lifts the old greedy-only
    engine assert).  Draft proposals draw with the SAME per-index keys
    (rejection sampling under shared randomness), so a draft that
    tracks the target accepts long chains.

    Per-row adaptive k rides ``k_rows`` as a traced array (positions
    past a row's budget auto-reject) while the scan length ``K`` is
    static and buckets down the ``jit_cache.pow2_ladder`` — one trace
    per (rung, greedy-or-mixed), all swept by ``warmup()``.  ``limits``
    is each row's remaining emission budget (max-tokens AND reserved
    page capacity); ``done`` carries EOS/budget exits ACROSS chained
    dispatches exactly like the horizon's ``eos_done`` (a retired row's
    pages may be freed at drain time, so the device itself must stop
    writing them).  Rows frozen by budget (not EOS) still consume their
    round-closing token on device, keeping the spec-mode cache
    invariant (``kv_len`` rows hold exactly the emitted history) for
    the next chain.

    Returns ``(pools, dcaches, toks [B, K+1], n_emit [B], m [B],
    kv_lens, last_logits, dlast_logits, counts, limits, done)`` — row
    ``b`` emits ``toks[b, :n_emit[b]]``; ``m`` is the raw accept count
    feeding the adaptive-k window.

    ``decode_fwd``/``verify_fwd`` swap the target's per-token and
    multi-token forwards — the world-1 defaults, or serve/mesh.py's
    head-sharded TP bodies when the round runs inside a mesh engine's
    shard_map (the draft steps replicated per rank either way)."""
    if decode_fwd is None:
        decode_fwd = functools.partial(_paged_decode_forward, cfg=cfg,
                                       page=page, impl=impl,
                                       interpret=interpret)
    if verify_fwd is None:
        verify_fwd = functools.partial(_paged_verify_forward, cfg=cfg,
                                       page=page, impl=impl,
                                       interpret=interpret)
    live = active & ~done & (limits > 0)
    has_eos = eos_ids >= 0

    # 1. Draft k-step scan: propose K tokens per row, consuming each
    # into the draft's slot-indexed batch cache (frozen rows' dummy
    # writes land in their dead slot, masked by length).
    def propose(carry, t):
        dcaches, dlens, dlogits = carry
        if all_greedy:
            tok = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
        else:
            keys = jax.vmap(jax.random.fold_in)(base_keys, counts + t)
            tok = sample_logits_rowwise(dlogits, keys, temperature=temps,
                                        top_k=top_ks, top_p=top_ps,
                                        greedy=greedy)
        dcaches, dlens, dlogits = draft_step(draft_params, dcaches,
                                             dlens, tok, live)
        return (dcaches, dlens, dlogits), tok

    (dcaches, _, _), props = jax.lax.scan(
        propose, (dcaches, kv_lens, dlast_logits),
        jnp.arange(K, dtype=counts.dtype))
    proposals = props.T                                     # [B, K]

    # 2. ONE multi-token verify scores every row's K proposals at its
    # own length (writes land in the row's pages; entries past the
    # allocation are dead padded-table slots pointing at block 0).
    pools, logits_all = verify_fwd(params, pools, tables, kv_lens,
                                   proposals, live)

    # 3. On-device accept against the target's own stream.
    allv = jnp.concatenate([last_logits[:, None], logits_all], axis=1)
    if all_greedy:
        expected = jnp.argmax(allv, axis=-1).astype(jnp.int32)
    else:
        expected = sample_positions_rowwise(
            allv, base_keys, counts, temperature=temps, top_k=top_ks,
            top_p=top_ps, greedy=greedy)
    m = accept_chain_rowwise(proposals, expected, k_rows)
    m_used = jnp.clip(jnp.minimum(m, limits - 1), 0, K)
    idx = jnp.arange(K + 1, dtype=jnp.int32)[None]
    in_chain = (has_eos[:, None] & (expected == eos_ids[:, None])
                & (idx <= m_used[:, None]))
    any_eos = in_chain.any(axis=1)
    n_emit = jnp.where(any_eos, jnp.argmax(in_chain, axis=1) + 1,
                       m_used + 1)
    n_emit = jnp.where(live, n_emit, 0)

    # 4. Consume the round-closing token (toks[m_used] — the first
    # non-accepted target choice, or the bonus past a full accept) via
    # one target decode + one draft step at the rolled-back lengths —
    # refreshing both models' round-opening logits for the next round.
    # EOS rows skip (they retire at drain); budget-frozen rows do NOT
    # (their cache must stay consistent with the emitted history).
    cont = live & ~any_eos
    kv_mid = kv_lens + jnp.where(live, m_used, 0)
    closing = jnp.take_along_axis(
        expected, jnp.where(live, m_used, 0)[:, None], axis=1)[:, 0]
    pools, t_logits = decode_fwd(params, pools, tables, kv_mid,
                                 closing, cont)
    dcaches, _, d_logits = draft_step(draft_params, dcaches, kv_mid,
                                      closing, cont)
    last_logits = jnp.where(cont[:, None], t_logits, last_logits)
    dlast_logits = jnp.where(cont[:, None], d_logits, dlast_logits)
    kv_lens = kv_lens + n_emit
    counts = counts + n_emit
    limits = jnp.maximum(limits - n_emit, 0)
    done = done | (live & (any_eos | (limits <= 0)))
    return (pools, dcaches, expected, n_emit, m, kv_lens, last_logits,
            dlast_logits, counts, limits, done)


def _gather_pool_pages(pools, block_ids, *, page):
    """Inverse of :func:`_fill_pool_pages`: assemble contiguous scratch
    caches ([1, Hkv, n*page, D] per layer) from pool pages.

    The warm-prefix prefill path (docs/serving.md "Prefix caching")
    reads the request's SHARED prefix blocks back into its prefill
    scratch, so the residual chunks attend to the cached K/V exactly as
    if earlier chunks had computed it — the rows are bit-identical (the
    pool pages were filled from a scratch of the same dtype), so the
    stream cannot differ from a cold prefill.  ``block_ids`` covers
    every scratch page (trace keyed by the s_ext bucket): entries past
    the cached prefix hold the null block, whose junk rows are all
    overwritten by the residual chunks or causally masked."""
    n = block_ids.shape[0]
    out = []
    for k_pool, v_pool in pools:
        def as_scratch(p):
            pages = p[block_ids]                    # [n, Hkv, page, D]
            Hkv, D = pages.shape[1], pages.shape[3]
            return (pages.transpose(1, 0, 2, 3)
                    .reshape(1, Hkv, n * page, D))

        def as_scratch_s(sp):
            pages = sp[block_ids]                   # [n, Hkv, page]
            Hkv = pages.shape[1]
            return pages.transpose(1, 0, 2).reshape(1, Hkv, n * page)

        if isinstance(k_pool, dict):
            # int8 pages travel as bytes + their scale plane — never a
            # dequant/requant round trip (quantization isn't idempotent)
            out.append(({"q": as_scratch(k_pool["q"]),
                         "s": as_scratch_s(k_pool["s"])},
                        {"q": as_scratch(v_pool["q"]),
                         "s": as_scratch_s(v_pool["s"])}))
        else:
            out.append((as_scratch(k_pool), as_scratch(v_pool)))
    return out


def _copy_pool_block(pools, src, dst):
    """Copy one pool page ``src`` → ``dst`` across every layer's K and V
    — the device half of a copy-on-write split (``BlockManager.cow``
    swaps the table entry; this lands the bytes before any write)."""
    def copy(p):
        if isinstance(p, dict):
            return {"q": p["q"].at[dst].set(p["q"][src]),
                    "s": p["s"].at[dst].set(p["s"][src])}
        return p.at[dst].set(p[src])

    return [(copy(k_pool), copy(v_pool)) for k_pool, v_pool in pools]


def _fill_pool_pages(pools, scratch, block_ids, *, page):
    """Scatter a completed prefill's K/V (contiguous scratch caches
    [1, Hkv, n*page, D] per layer) into the request's pool pages.

    ``block_ids`` covers EVERY scratch page (n = s_ext // page): entries
    past the prompt's allocation hold the null block, so a bucketed
    scratch scatters its zero-masked padding pages into block 0 (written
    by every inactive row anyway) instead of forcing one trace per
    prompt-page count — the trace is keyed by the s_ext bucket alone."""
    n = block_ids.shape[0]
    new_pools = []
    for (k_pool, v_pool), (kc, vc) in zip(pools, scratch):
        def as_pages(c):
            Hkv, S_ext, D = c.shape[1:]
            return c[0].reshape(Hkv, n, page, D).transpose(1, 0, 2, 3)

        def as_pages_s(s):
            Hkv = s.shape[1]
            return s[0].reshape(Hkv, n, page).transpose(1, 0, 2)

        if isinstance(k_pool, dict):
            # the quantized scratch's int8 bytes + scales scatter AS-IS:
            # the pool rows are bit-identical to the scratch rows, so a
            # warm-prefix gather-back reproduces the cold prefill exactly
            k_pool = {"q": k_pool["q"].at[block_ids].set(as_pages(kc["q"])),
                      "s": k_pool["s"].at[block_ids].set(as_pages_s(kc["s"]))}
            v_pool = {"q": v_pool["q"].at[block_ids].set(as_pages(vc["q"])),
                      "s": v_pool["s"].at[block_ids].set(as_pages_s(vc["s"]))}
        else:
            k_pool = k_pool.at[block_ids].set(
                as_pages(kc).astype(k_pool.dtype))
            v_pool = v_pool.at[block_ids].set(
                as_pages(vc).astype(v_pool.dtype))
        new_pools.append((k_pool, v_pool))
    return new_pools


def _splice_draft_rows(bcaches, blens, blogits, tcaches, slot, s0, last):
    """Splice one freshly-prefilled draft row into the slot-indexed batch
    state: ``tcaches`` are extent-wide per-layer ``[1, Hkv, ext, D]``
    temp caches from the padded chunked draft prefill (rows >= ``s0``
    are exact zeros — ``n_valid``-masked — and land in the dead region
    past the row's cache length, so copying the FULL extent keeps the
    trace keyed by the draft-ladder rung alone, never the prompt
    length).  ``slot``/``s0``/``last`` are traced, so joins at any slot
    or length share one program per rung."""
    out = []
    for (kb, vb), (kt, vt) in zip(bcaches, tcaches):
        w = min(kt.shape[2], kb.shape[2])
        kb = kb.at[slot, :, :w, :].set(kt[0, :, :w, :].astype(kb.dtype))
        vb = vb.at[slot, :, :w, :].set(vt[0, :, :w, :].astype(vb.dtype))
        out.append((kb, vb))
    return out, blens.at[slot].set(s0), blogits.at[slot].set(last)


def build_bucket_ladder(base: int, cap: int, page: int) -> list[int]:
    """The powers-of-two scratch-extent ladder: rungs double from
    ``base`` (rounded up to a page multiple) until ``cap`` (the largest
    extent any admissible prompt needs), which always closes the ladder.
    Every rung is a multiple of ``page`` so a bucketed scratch reshapes
    cleanly into pool pages."""
    if base < 1 or cap < 1:
        raise ValueError(f"ladder needs base, cap >= 1; got {base}, {cap}")
    cap = -(-cap // page) * page
    rungs = []
    r = -(-base // page) * page
    while r < cap:
        rungs.append(r)
        r *= 2
    rungs.append(cap)
    return rungs


# ---------------------------------------------------------------------------
# The engine
# ---------------------------------------------------------------------------


class ServeEngine:
    """Continuous-batching serving over one :class:`Generator`.

    Usage::

        engine = ServeEngine(gen, params, num_blocks=64, page_size=16,
                             max_batch=8)
        engine.warmup()                 # pre-compile the bucket ladder
        engine.submit(Request("r0", prompt_tokens,
                              SamplingParams(max_new_tokens=32)))
        outputs = engine.run()          # step() until drained

    ``draft``/``draft_params`` + ``spec_k`` turn every decode step into a
    speculative round: up to ``spec_k + 1`` tokens per row per round,
    same emitted stream as serving without the draft (greedy AND seeded
    sampled — the accept chain scores proposals against the target's own
    per-index stream).  With ``spec_fused=True`` (default) the whole
    round is ONE device dispatch chained ``pipeline`` deep, and
    ``spec_adaptive=W`` picks each row's k from a W-round acceptance
    window (docs/serving.md "Speculative decoding"); ``spec_fused=False``
    keeps the unfused PR-1 round (greedy only).

    ``horizon=H`` fuses up to H decode steps into ONE device dispatch
    (on-device sampling, per-row EOS/max-token/page-boundary early exit)
    and ``pipeline=N`` chains N such dispatches with a device-resident
    carry — the host drains token bursts instead of paying a round trip
    per token.  Streams are bit-identical at every H (docs/serving.md
    "Decode horizon"); the scheduler clamps fused decode back to
    single-step whenever prefill interleaving, waiting-queue deadlines,
    or speculative rounds need iteration-level scheduling.

    **Shape bucketing** (docs/serving.md): prefill always runs the ONE
    fixed ``prefill_chunk`` shape (the final residual pads, its K/V
    writes zero-masked by ``n_valid``), and each prompt's scratch extent
    rounds up a powers-of-two ``bucket_ladder`` — so O(len(ladder))
    compiled programs cover EVERY prompt length, and :meth:`warmup`
    pre-compiles them all so steady-state serving never compiles.
    Trace-cache hit/miss/compile-stall counters live in
    ``metrics.summary()["compilation"]``.
    """

    def __init__(self, gen: Generator, params, *, num_blocks: int,
                 page_size: int, max_batch: int = 8,
                 mesh=None, tp_axis: str = "tp",
                 sp_axis: str = "sp",
                 kv_shard: str = "heads",
                 w8a8: bool = False,
                 prefill_chunk: int = 64,
                 prefill_budget: Optional[int] = None,
                 bucket_ladder: Optional[list] = None,
                 horizon: int = 1, pipeline: int = 2,
                 draft: Optional[Generator] = None, draft_params=None,
                 spec_k: int = 0, spec_fused: bool = True,
                 spec_adaptive: int = 8, clock=time.monotonic,
                 max_queue: Optional[int] = None, overload: str = "shed",
                 class_aware: bool = False,
                 brownout: Optional[dict] = None,
                 step_timeout_s: Optional[float] = None,
                 heartbeat: Optional[str] = None,
                 heartbeat_interval_s: float = 10.0,
                 faults: Optional[FaultInjector] = None,
                 fault_retries: int = 1,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every: Optional[int] = None,
                 journal_fsync: bool = False,
                 journal_fsync_interval_s: Optional[float] = None,
                 journal_rotate_bytes: Optional[int] = None,
                 journal_retain_done: Optional[int] = 4096,
                 prefix_cache: bool = True,
                 trace_level: int = 1, trace_events: int = 4096):
        assert gen.attn.world == 1, (
            "ServeEngine owns its own mesh placement (pass mesh=/"
            "tp_axis=/kv_shard= — docs/serving.md 'Sharded serving'); "
            "the Generator itself must stay world-1 (it only provides "
            "the model cfg and, off-mesh, the chunked-prefill program)")
        # int8 paged KV (docs/serving.md "Quantized serving"): a
        # Generator built with kv_dtype=jnp.int8 switches every pool
        # layer to {"q", "s"} dicts; the stream is bit-reproducible but
        # NOT the fp stream, so speculative decode (whose accept chain
        # assumes the target's own fp logits) is a recorded follow-up.
        self.kv_quant = bool(gen.attn.quantized)
        if self.kv_quant and spec_k:
            raise ValueError(
                "int8 KV pools cannot drive speculative decoding yet "
                "(recorded follow-up, ROADMAP #3): the draft/verify "
                "round assumes fp target logits — serve with spec_k=0 "
                "or a float kv_dtype")
        if draft is not None and draft.attn.quantized:
            raise ValueError(
                "the draft Generator must keep float KV (its contiguous "
                "caches are served unquantized); only the target's "
                "paged pools quantize")
        # w8a8 weights (docs/serving.md "Quantized serving"): the two
        # hook seams (out_proj / ffn) run int8 GEMMs; QKV, norms and the
        # KV pools are orthogonal (w8a8 composes with either kv dtype).
        self.w8a8 = bool(w8a8)
        if self.w8a8 and spec_k:
            raise ValueError(
                "w8a8 weights cannot drive speculative decoding yet "
                "(recorded follow-up, ROADMAP #3): the draft/verify "
                "round's target forwards are unhooked — serve with "
                "spec_k=0 or float weights")
        if self.w8a8 and mesh is not None and kv_shard != "heads":
            raise ValueError(
                "w8a8 is a tensor-parallel weight layout: supported "
                "world-1 and kv_shard='heads' (the seq and heads+seq "
                "layouts keep float weights on their sp bodies; "
                "recorded follow-up)")
        cfg = gen.cfg
        # mesh serving (docs/serving.md "Sharded serving"): with mesh=,
        # every device program below is rebuilt as a shard_map over the
        # tp_axis — TP weights + head-sharded pools (kv_shard="heads"),
        # replicated weights + block-sharded pools with SP flash-decode
        # (kv_shard="seq"), or BOTH on a 2D mesh (kv_shard="heads+seq":
        # heads over tp_axis, blocks over sp_axis).  Geometry that
        # cannot divide the mesh is rejected HERE, loudly, instead of
        # as a shape error inside a traced forward.
        self.mesh = mesh
        self.tp_axis = tp_axis
        self.sp_axis = sp_axis
        self.kv_shard = kv_shard
        self.mesh_world = 1
        self.sp_world = 1
        self._pool_sharding = None
        if mesh is None and kv_shard not in ("heads", "seq",
                                             "heads+seq"):
            # validated even off-mesh: a typo'd layout must not ride
            # silently until a mesh= is added later
            raise ValueError(
                f"kv_shard must be 'heads', 'seq' or 'heads+seq', "
                f"got {kv_shard!r}")
        if mesh is not None:
            from triton_dist_tpu.serve import mesh as serve_mesh

            self.mesh_world = serve_mesh.validate_mesh_geometry(
                mesh=mesh, tp_axis=tp_axis, kv_shard=kv_shard, cfg=cfg,
                max_seq=gen.max_seq, num_blocks=num_blocks,
                page_size=page_size, spec_k=spec_k, sp_axis=sp_axis)
            if kv_shard == "seq":
                self.sp_world = self.mesh_world
            elif kv_shard == "heads+seq":
                self.sp_world = int(mesh.shape[sp_axis])
            if spec_k and not spec_fused:
                raise ValueError(
                    "mesh serving fuses every speculative round into "
                    "one shard_map dispatch; the legacy unfused round "
                    "(spec_fused=False) is world-1 only")
        if gen.max_seq % page_size:
            raise ValueError(
                f"max_seq {gen.max_seq} must divide by page_size "
                f"{page_size} (the block table is fixed-width)")
        if spec_k:
            assert draft is not None and draft_params is not None, (
                "spec_k needs draft + draft_params")
            assert draft.max_seq >= gen.max_seq, (
                "draft max_seq must cover the target's")
        if overload not in ("shed", "raise"):
            raise ValueError(
                f"overload must be 'shed' or 'raise', got {overload!r}")
        if max_queue is not None and max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        if horizon < 1:
            raise ValueError(f"horizon must be >= 1, got {horizon}")
        if pipeline < 1:
            raise ValueError(f"pipeline must be >= 1, got {pipeline}")
        if spec_adaptive < 0:
            raise ValueError(
                f"spec_adaptive must be >= 0 (0 disables adaptive k), "
                f"got {spec_adaptive}")
        self.gen = gen
        self.cfg = cfg
        self.params = params
        self.page = page_size
        self.max_batch = max_batch
        self.n_pages_max = gen.max_seq // page_size
        # prefix cache (docs/serving.md "Prefix caching"): paged blocks
        # are content-addressed and ref-counted — admission maps a
        # prompt's longest cached block-aligned prefix in read-only and
        # chunked prefill starts at the first divergent chunk; freed
        # committed blocks linger in an LRU cache tier until allocation
        # pressure reclaims them.
        self.prefix_cache = bool(prefix_cache)
        # kv_shard="seq" partitions the block-id space per rank (rank r
        # owns pool rows [r*NB/W, (r+1)*NB/W) = the pages of its
        # sequence span); the allocator places every logical page in
        # its owner's partition and reserves one null block per
        # partition (serve/block_manager.py).  Under "heads+seq" the
        # partition count is the SP world — the tp axis splits heads
        # inside each block, never the block-id space.
        seq_shards = self.sp_world
        self.bm = BlockManager(num_blocks, page_size, faults=faults,
                               prefix_cache=self.prefix_cache,
                               shards=seq_shards,
                               pages_per_shard=self.n_pages_max
                               // seq_shards)
        self.scheduler = FCFSScheduler(
            self.bm,
            prefill_budget=prefill_budget or 4 * prefill_chunk,
            prefill_chunk=prefill_chunk, class_aware=class_aware)
        self.class_aware = bool(class_aware)
        # Graceful-degradation ladder (docs/serving.md "Overload, SLO
        # classes & autoscaling"): brownout=dict(...) arms an ordered
        # response to SUSTAINED pressure — a smoothed (clock-driven EMA)
        # max of queue backlog and KV utilization climbs the rungs after
        # `dwell_steps` consecutive over-high steps and descends after
        # as many under-low steps:
        #   0 full service
        #   1 speculative k clamped to 1
        #   2 chunked-prefill token budget halved
        #   3 best_effort max_new_tokens capped (best_effort_cap)
        #   4 incoming best_effort shed
        #   5 incoming batch shed too
        #   6 incoming interactive refused (the old cliff, now last)
        # brownout=None (default) skips the evaluation entirely — the
        # ladder is provably inert (no state reads on the step path).
        self.brownout_cfg = None
        if brownout is not None:
            b = dict(brownout)
            high = float(b.pop("high", 0.85))
            low = float(b.pop("low", 0.55))
            window_s = float(b.pop("window_s", 1.0))
            dwell_steps = int(b.pop("dwell_steps", 4))
            best_effort_cap = int(b.pop("best_effort_cap", 4))
            if b:
                raise ValueError(
                    f"unknown brownout keys: {sorted(b)} (expected "
                    f"high/low/window_s/dwell_steps/best_effort_cap)")
            if not 0.0 < low < high:
                raise ValueError(
                    f"brownout needs 0 < low < high, got low={low} "
                    f"high={high}")
            if window_s < 0 or dwell_steps < 1 or best_effort_cap < 1:
                raise ValueError(
                    f"brownout needs window_s >= 0, dwell_steps >= 1, "
                    f"best_effort_cap >= 1; got {window_s}, "
                    f"{dwell_steps}, {best_effort_cap}")
            self.brownout_cfg = {
                "high": high, "low": low, "window_s": window_s,
                "dwell_steps": dwell_steps,
                "best_effort_cap": best_effort_cap,
            }
        self.brownout_rung = 0
        self._pressure_ema = 0.0
        self._pressure_t: Optional[float] = None
        self._brownout_dwell = 0
        self._base_prefill_budget = self.scheduler.prefill_budget
        self.metrics = ServeMetrics()
        # flight recorder (docs/observability.md): a bounded ring of
        # typed engine events — submit/admit/prefill/decode drains, spec
        # rounds, preemptions, COW splits, faults, retirements — that
        # exports per-request Perfetto spans, flushes to
        # flight_<step>.json on any fault/crash path, and rides
        # snapshots so a restored engine carries its previous life's
        # trail.  trace_level=0 turns the hot-path appends off entirely
        # (bench_serve --trace holds the on/off throughput ratio at
        # >= 0.95 via PERF_FLOORS.json's serve_trace_overhead).
        if trace_level < 0:
            raise ValueError(f"trace_level must be >= 0, got {trace_level}")
        self.trace = FlightRecorder(capacity=trace_events,
                                    level=trace_level)
        self.metrics.attach_recorder(self.trace)
        # per-program wall-time attribution (docs/observability.md
        # "Kernel observability"): behind the SAME trace_level knob as
        # the recorder, register_compiled below wires every program's
        # CountingJit timer into metrics.observe_program — step time
        # decomposes by device program (summary()["programs"],
        # serve_program_ms{program=}), and the bench_serve --trace
        # overhead gate measures the timers together with the ring.
        self.metrics.program_timing = trace_level >= 1
        self._trace_fault_idx = 0   # audit entries already mirrored
        self._last_flight_step = -1  # flush throttle: one file per step
        self.draft = draft
        self.draft_params = draft_params
        self.spec_k = int(spec_k)
        # fused speculative rounds (docs/serving.md "Speculative
        # decoding"): the whole draft-propose / verify / accept /
        # closing-decode round runs as ONE traced program, chained
        # `pipeline` deep on a device-resident carry; spec_fused=False
        # keeps the PR-1 unfused round (greedy-only — the fused path's
        # bit-exactness oracle).  spec_adaptive is the acceptance-rate
        # window behind the scheduler's per-row k (0 = fixed k).
        self.spec_fused = bool(spec_fused)
        self.spec_adaptive = int(spec_adaptive)
        # decode horizon (docs/serving.md "Decode horizon"): up to
        # `horizon` decode steps fuse into one device dispatch with
        # on-device sampling; `pipeline` chains that many dispatches
        # back-to-back with a device-resident carry, so the host commits
        # horizon N's burst while the device executes horizon N+1.
        self.horizon = int(horizon)
        self.pipeline = int(pipeline)
        self.h_ladder = pow2_ladder(self.horizon) if self.horizon > 1 else [1]
        # failure containment (docs/serving.md "Failure containment")
        self.max_queue = max_queue
        self.overload = overload
        self.step_timeout_s = step_timeout_s
        self.faults = faults
        self.fault_retries = int(fault_retries)
        self.heartbeat = (Heartbeat(heartbeat,
                                    interval_s=heartbeat_interval_s)
                          if heartbeat is not None else None)
        self._last_beat = float("-inf")
        self._spec_off = False  # latched by a failed speculative round
        if faults is not None:
            clock = faults.wrap_clock(clock)
        self._clock = clock
        # crash recovery (docs/serving.md "Crash recovery"): with a
        # snapshot_dir, every submit/commit/retire appends to the token
        # journal, and snapshot_every=N captures the KV pools + manifest
        # each N steps (the journal may run AHEAD of the KV snapshot;
        # restore replays the journal-ahead suffix through recompute).
        if snapshot_every is not None and snapshot_every < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {snapshot_every}")
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = snapshot_every
        # journal durability/size knobs (docs/serving.md "Crash
        # recovery"): fsync batching at a configurable interval, and
        # rotation/compaction at snapshot barriers once the file passes
        # the byte bound (:meth:`_rotate_journal`).
        if (journal_fsync_interval_s is not None
                and journal_fsync_interval_s < 0):
            raise ValueError(f"journal_fsync_interval_s must be >= 0, "
                             f"got {journal_fsync_interval_s}")
        if journal_rotate_bytes is not None and journal_rotate_bytes < 1:
            raise ValueError(f"journal_rotate_bytes must be >= 1, "
                             f"got {journal_rotate_bytes}")
        if journal_retain_done is not None and journal_retain_done < 0:
            raise ValueError(f"journal_retain_done must be >= 0, "
                             f"got {journal_retain_done}")
        self.journal_fsync_interval_s = journal_fsync_interval_s
        self.journal_rotate_bytes = journal_rotate_bytes
        # Rotation retention bound: without one, every finished request
        # ever served would be rewritten as a `done` record at every
        # rotation — the compacted file (and each rewrite's cost) would
        # still grow O(total requests), and a floor above
        # journal_rotate_bytes would re-trigger a full-history rewrite
        # at every snapshot barrier.  Keeping only the newest N finished
        # requests (and pruning the older ones from the engine's output
        # map with them) is what actually bounds a long-lived engine's
        # journal AND memory; None keeps the full history.
        self.journal_retain_done = journal_retain_done
        # file size right after the last rewrite: rotation re-triggers
        # only once the file at least doubles past it, so rewrite cost
        # stays amortized O(1) per appended byte even when the retained
        # floor sits above journal_rotate_bytes.
        self._journal_floor = 0
        self._snap_seq = 0
        self._last_snap_step = 0
        self._in_warmup = False
        self._journal: Optional[TokenJournal] = None
        self._snap_mgr = None  # CheckpointManager, cached per directory
        if snapshot_dir is not None:
            os.makedirs(snapshot_dir, exist_ok=True)
            jpath = os.path.join(snapshot_dir, JOURNAL_NAME)
            if has_restorable_state(snapshot_dir):
                # A FRESH engine appending a second life to an existing
                # journal would interleave reused request ids with the
                # previous run's records — replay keeps first
                # occurrences, so a later restore would resurrect OLD
                # prompts under new ids.  Only restore() may reopen a
                # populated directory.
                raise ValueError(
                    f"snapshot_dir {snapshot_dir!r} already holds "
                    f"serving state from a previous life; resume it "
                    f"with ServeEngine.restore(...) or point the fresh "
                    f"engine at a clean directory")
            self._journal = TokenJournal(
                jpath, fsync=journal_fsync,
                fsync_interval_s=journal_fsync_interval_s,
                faults=self.faults)

        # The scratch-extent bucket ladder: every prefill's s_ext (and
        # with it the _chunk_jit extent and the _fill_fn table width)
        # rounds up to a rung, so O(len(ladder)) traces cover every
        # prompt length instead of one per distinct shape.  The cap is
        # the largest extent an admissible prompt can need (submit()
        # holds prompt <= max_seq - 1).
        cap = self._scratch_need(gen.max_seq - 1)
        if bucket_ladder is None:
            self.ladder = build_bucket_ladder(
                max(page_size, prefill_chunk), cap, page_size)
        else:
            rungs = sorted({int(r) for r in bucket_ladder})
            bad = [r for r in rungs
                   if r % page_size or r < prefill_chunk]
            if bad:
                raise ValueError(
                    f"bucket_ladder rungs must be multiples of page_size "
                    f"{page_size} and hold one prefill_chunk "
                    f"{prefill_chunk}; got {bad}")
            if rungs[-1] < cap:
                rungs.append(-(-cap // page_size) * page_size)
            self.ladder = rungs

        impl = gen.attn.ctx.impl
        interpret = gen.attn.ctx.interpret
        if self.kv_quant:
            # int8 pools: the quant plane plus its per-(head, row) scale
            # plane — one scale per (block, head, in-page row), the exact
            # shape _scatter_kv's quantize_kv emits, living in the SAME
            # pool tuple so pages and scales can never travel separately.
            def _zpool():
                return {"q": jnp.zeros((num_blocks, cfg.n_kv_heads,
                                        page_size, cfg.head_dim),
                                       jnp.int8),
                        "s": jnp.zeros((num_blocks, cfg.n_kv_heads,
                                        page_size), jnp.float32)}
            self._pools = [(_zpool(), _zpool())
                           for _ in range(cfg.n_layers)]
        else:
            self._pools = [
                (jnp.zeros((num_blocks, cfg.n_kv_heads, page_size,
                            cfg.head_dim), cfg.dtype),
                 jnp.zeros((num_blocks, cfg.n_kv_heads, page_size,
                            cfg.head_dim), cfg.dtype))
                for _ in range(cfg.n_layers)]
        # w8a8 swaps the weight tree ONCE, host-side, before any program
        # captures it; the hooks ride the same ffn=/out_proj= seams the
        # mesh TP bodies use, so every program below stays one copy.
        w8a8_hooks = {}
        if self.w8a8:
            from triton_dist_tpu.models import llama_w8a8

            params = llama_w8a8.quantize_serve_params(
                params, cfg,
                world=self.mesh_world if mesh is not None else 1)
            self.params = params
            w8a8_hooks = {
                "ffn": functools.partial(
                    llama_w8a8.w8a8_serve_ffn, impl=impl,
                    interpret=interpret),
                "out_proj": functools.partial(
                    llama_w8a8.w8a8_serve_out_proj, impl=impl,
                    interpret=interpret),
            }
        # Every jitted program is wrapped for trace-cache accounting
        # (runtime/jit_cache.CountingJit): hit/miss/compile-stall
        # counters ride ServeMetrics onto the TDT_DUMP_IR dump path.
        if mesh is not None:
            # Mesh placement (docs/serving.md "Sharded serving"): every
            # program is the SAME traced math rebuilt as a shard_map
            # body, under the same names/ladders/donation — warmup, the
            # step loop, and the metrics plumbing below need no mesh
            # branches.  serve_mesh.ShardedProgram canonicalizes every
            # argument's sharding at the call seam, so host-built and
            # device-carried calls share one executable per program
            # (the PR-7 cache-fork problem, closed for good).
            from jax.sharding import NamedSharding

            from triton_dist_tpu.serve import mesh as serve_mesh

            progs = serve_mesh.build_programs(
                mesh=mesh, tp_axis=tp_axis, kv_shard=kv_shard, cfg=cfg,
                params=params, page_size=page_size,
                num_blocks=num_blocks, n_pages_max=self.n_pages_max,
                impl=impl, interpret=interpret, horizon=self.horizon,
                draft=draft, draft_params=draft_params,
                spec_fused=bool(spec_k) and self.spec_fused,
                prefix_cache=self.prefix_cache,
                kv_quant=self.kv_quant, w8a8=self.w8a8,
                sp_axis=sp_axis)
            self._mesh_progs = progs
            self._pool_sharding = NamedSharding(mesh, progs["pool_spec"])
            # Weights live TP-sharded (heads) / replicated (seq) on the
            # mesh for the engine's lifetime; the pools move onto their
            # shard layout once, here.
            self.params = progs["paged_decode"].place(0, params)
            self._pools = progs["paged_decode"].place(1, self._pools)
            self._decode_fn = CountingJit(progs["paged_decode"],
                                          "paged_decode")
            self._verify_fn = (
                CountingJit(progs["paged_verify"], "paged_verify")
                if "paged_verify" in progs else None)
            if self.horizon > 1:
                self._horizon_fn = CountingJit(progs["decode_horizon"],
                                               "decode_horizon",
                                               timed_statics=("H",))
            self._fill_fn = CountingJit(progs["fill_pages"],
                                        "fill_pages")
            self._load_fn = CountingJit(progs["load_pages"],
                                        "load_pages")
            self._cow_fn = CountingJit(progs["cow_copy"], "cow_copy")
            self._chunk_fn = CountingJit(progs["prefill_chunk"],
                                         "prefill_chunk")
        else:
            self._decode_fn = CountingJit(jax.jit(functools.partial(
                _paged_decode_forward, cfg=cfg, page=page_size,
                impl=impl, interpret=interpret, **w8a8_hooks),
                donate_argnums=(1,)), "paged_decode")
            self._verify_fn = CountingJit(jax.jit(functools.partial(
                _paged_verify_forward, cfg=cfg, page=page_size,
                impl=impl, interpret=interpret, **w8a8_hooks),
                donate_argnums=(1,)), "paged_verify")
            if self.horizon > 1:
                # One program per (horizon rung, greedy-or-mixed): the
                # scan length is static, so the ladder bounds the trace
                # count and warmup() sweeps every rung (the
                # prompt-extent ladder's twin for the decode side).
                horizon_kw = {}
                if self.w8a8:
                    # the scan's per-step forward must carry the hooks
                    horizon_kw["decode_fwd"] = functools.partial(
                        _paged_decode_forward, cfg=cfg, page=page_size,
                        impl=impl, interpret=interpret, **w8a8_hooks)
                self._horizon_fn = CountingJit(jax.jit(
                    functools.partial(
                        _paged_decode_horizon, cfg=cfg, page=page_size,
                        impl=impl, interpret=interpret, **horizon_kw),
                    static_argnames=("H", "all_greedy"),
                    donate_argnums=(1,)), "decode_horizon",
                    timed_statics=("H",))
            # scratch is not donatable (the page reshape transposes it);
            # pools are — the scatter updates them in place.
            self._fill_fn = CountingJit(jax.jit(functools.partial(
                _fill_pool_pages, page=page_size), donate_argnums=(0,)),
                "fill_pages")
            # Prefix-cache device programs: the warm-prefill gather
            # (pools read back into scratch — NOT donated, the pools
            # live on) keyed by the s_ext rung like fill_pages, and the
            # one-page COW copy (traced src/dst: one program total).
            self._load_fn = CountingJit(jax.jit(functools.partial(
                _gather_pool_pages, page=page_size)), "load_pages")
            self._cow_fn = CountingJit(jax.jit(
                _copy_pool_block, donate_argnums=(0,)), "cow_copy")
            # The Generator's chunked-prefill program; the trace cache
            # lives on the Generator (shared with prefill_chunked/
            # speculative), the counters here see this engine's calls.
            # w8a8 needs its own jit: the Generator's program has no
            # hook seams bound, and preemption recompute-exactness
            # requires the SAME hooked program for cold and re-prefill.
            if self.w8a8:
                from triton_dist_tpu.models.generate import _chunk_forward

                self._chunk_fn = CountingJit(jax.jit(
                    functools.partial(
                        _chunk_forward, cfg=cfg, impl=impl,
                        interpret=interpret, mesh=gen.mesh,
                        axis=gen.axis, **w8a8_hooks),
                    static_argnames=("quantized", "extent"),
                    donate_argnums=(2,)), "prefill_chunk")
            else:
                self._chunk_fn = CountingJit(gen._chunk_jit,
                                             "prefill_chunk")
        for c in (self._chunk_fn, self._fill_fn, self._decode_fn,
                  self._verify_fn):
            if c is not None:
                self.metrics.register_compiled(c)
        if self.horizon > 1:
            self.metrics.register_compiled(self._horizon_fn)
        if self.prefix_cache:
            self.metrics.register_compiled(self._load_fn)
            self.metrics.register_compiled(self._cow_fn)
        self.metrics.attach_block_manager(self.bm)
        # KV capacity observability (docs/observability.md "KV
        # capacity"): pool bytes are THE capacity currency — stamp the
        # real allocated footprint (quant + scale planes both) and the
        # token-slot count so bytes/token and fleet-wide sums fall out.
        self.metrics.set_kv_capacity(
            pool_bytes=sum(int(x.size) * x.dtype.itemsize
                           for x in jax.tree_util.tree_leaves(self._pools)),
            token_slots=num_blocks * page_size,
            quantized=self.kv_quant)
        # cache-tier reclaims happen inside the allocator; the hook puts
        # them on the flight-recorder timeline (an eviction storm under
        # allocation pressure is a classic tail-latency culprit)
        self.bm.on_evict = (
            lambda b: self.trace.emit("evict", None, block=int(b)))

        self.slots: list[Optional[ReqState]] = [None] * max_batch
        self._states: dict[str, ReqState] = {}
        self._outputs: dict[str, RequestOutput] = {}
        # terminal outputs produced OUTSIDE a step (class-aware
        # displacement sheds inside submit()): already retired, they
        # ride the next step()'s finished batch so polling controllers
        # see them exactly once
        self._shed_pending: list[RequestOutput] = []
        # distributed-tracing context per live request (docs/
        # observability.md "Fleet observability"): {"trace_id", "hop"} —
        # stamped by the fleet controller (or defaulted at submit),
        # carried by migration manifests and the journal, bumped one hop
        # per adopting life, so one request's journey is ONE trace
        # however many replicas serve it.
        self._trace_ctx: dict[str, dict] = {}
        # speculative-mode device state ([B]-shaped, slot-indexed)
        if self.spec_k:
            # The draft joins through the SAME padded fixed-chunk
            # machinery as the target (its own _chunk_jit + an extent
            # ladder of chunk multiples), so spec-mode admission is
            # fully compile-free after warmup — the ROADMAP follow-up
            # that used to leave draft.prefill compiling per prompt
            # length.  _splice_draft_rows lands the prefilled row in
            # the slot-indexed batch caches (traced slot/length: one
            # program per rung).
            # Rungs are multiples of lcm(chunk, page): one chunked
            # prefill trace per rung as before, AND the scratch
            # reshapes cleanly into DRAFT pool pages (the draft-side
            # prefix cache below).
            self._draft_ladder = build_bucket_ladder(
                prefill_chunk, gen.max_seq - 1,
                prefill_chunk * page_size
                // math.gcd(prefill_chunk, page_size))
            if mesh is not None:
                # On a mesh the draft runs REPLICATED per rank (its
                # slot-indexed batch caches are whole-batch host-managed
                # state), but its programs must still be shard_map
                # bodies so every array stays in one NamedSharding
                # world — a single-device draft program fed mesh-placed
                # carries would fork executables and bounce buffers
                # across placements every round.
                self._draft_chunk_fn = CountingJit(
                    self._mesh_progs["draft_prefill"], "draft_prefill")
                self._draft_join_fn = CountingJit(
                    self._mesh_progs["draft_join"], "draft_join")
            else:
                self._draft_chunk_fn = CountingJit(draft._chunk_jit,
                                                   "draft_prefill")
                # temp caches (arg 3) are NOT donatable: the splice
                # reads a sliced view of them into the batch caches
                self._draft_join_fn = CountingJit(
                    jax.jit(_splice_draft_rows, donate_argnums=(0, 1, 2)),
                    "draft_join")
            if not isinstance(draft._step_jit, CountingJit):
                # Wrap-once: a draft shared across engines keeps one
                # counter (re-registered here).
                draft._step_jit = CountingJit(draft._step_jit,
                                              "draft_step")
            self.metrics.register_compiled(self._draft_chunk_fn)
            self.metrics.register_compiled(self._draft_join_fn)
            self.metrics.register_compiled(draft._step_jit)
            self._last_logits = jnp.zeros((max_batch, cfg.vocab),
                                          jnp.float32)
            dcfg = draft.cfg
            self._draft_state = GenerationState(
                caches=[(jnp.zeros((max_batch, dcfg.n_kv_heads,
                                    draft.max_seq, dcfg.head_dim),
                                   dcfg.dtype),
                         jnp.zeros((max_batch, dcfg.n_kv_heads,
                                    draft.max_seq, dcfg.head_dim),
                                   dcfg.dtype))
                        for _ in range(dcfg.n_layers)],
                kv_lens=jnp.zeros((max_batch,), jnp.int32),
                last_logits=jnp.zeros((max_batch, dcfg.vocab),
                                      jnp.float32))
            # One-dispatch fused rounds (docs/serving.md "Speculative
            # decoding"): the k-ladder is the verify scan's static-K
            # bucket set (one trace per rung x {greedy, mixed}, swept
            # by warmup); pools (arg 2) and the draft batch caches
            # (arg 3) are donated like every decode-path program.
            self._k_ladder = pow2_ladder(self.spec_k)
            if self.spec_fused and mesh is not None:
                # The whole fused round as ONE shard_map body: target
                # verify/decode legs head-sharded TP, draft replicated,
                # seeded accept on replicated logits
                # (serve/mesh.tp_spec_round_shard).
                self._spec_fused_fn = CountingJit(
                    self._mesh_progs["spec_round"], "spec_round",
                    timed_statics=("K",))
                self.metrics.register_compiled(self._spec_fused_fn)
                self._draft_tail_fn = CountingJit(
                    self._mesh_progs["draft_tail_step"],
                    "draft_tail_step")
                self.metrics.register_compiled(self._draft_tail_fn)
            elif self.spec_fused:
                # The draft steps inside the trace through the
                # MESH-FREE _draft_decode_forward (see its docstring:
                # shard_map-placed carries would fork the executable
                # cache into flavors warmup cannot enumerate).
                draft_fwd = functools.partial(
                    _draft_decode_forward, cfg=dcfg,
                    impl=draft.attn.ctx.impl,
                    interpret=draft.attn.ctx.interpret)
                self._spec_fused_fn = CountingJit(jax.jit(
                    functools.partial(
                        _spec_round_fused, cfg=cfg, page=page_size,
                        impl=impl, interpret=interpret,
                        draft_step=draft_fwd),
                    static_argnames=("K", "all_greedy"),
                    donate_argnums=(2, 3)), "spec_round",
                    timed_statics=("K",))
                self.metrics.register_compiled(self._spec_fused_fn)
                # The k<=0 tail's closing draft step — the same
                # mesh-free forward, standalone (going through
                # draft.step would hand the next chain NamedSharding
                # draft caches and recompile every rung).
                self._draft_tail_fn = CountingJit(jax.jit(
                    draft_fwd, donate_argnums=(1,)), "draft_tail_step")
                self.metrics.register_compiled(self._draft_tail_fn)
            # Draft-side prefix cache (the ISSUE-7 warm-admit fix): the
            # draft's prompt K/V pages live in draft-geometry pools
            # UNDER THE SAME BLOCK IDS as the target's, validated
            # against the content index key at read time — a warm
            # target admit then skips the draft's already-known prefix
            # too instead of re-prefilling the full prompt draft-side.
            self._draft_pools = None
            self._draft_page_key: dict[int, tuple] = {}
            if self.prefix_cache:
                self._draft_pools = [
                    (jnp.zeros((num_blocks, dcfg.n_kv_heads, page_size,
                                dcfg.head_dim), dcfg.dtype),
                     jnp.zeros((num_blocks, dcfg.n_kv_heads, page_size,
                                dcfg.head_dim), dcfg.dtype))
                    for _ in range(dcfg.n_layers)]
                if mesh is not None:
                    self._draft_fill_fn = CountingJit(
                        self._mesh_progs["draft_fill_pages"],
                        "draft_fill_pages")
                    self._draft_load_fn = CountingJit(
                        self._mesh_progs["draft_load_pages"],
                        "draft_load_pages")
                else:
                    self._draft_fill_fn = CountingJit(jax.jit(
                        functools.partial(_fill_pool_pages,
                                          page=page_size),
                        donate_argnums=(0,)), "draft_fill_pages")
                    self._draft_load_fn = CountingJit(jax.jit(
                        functools.partial(_gather_pool_pages,
                                          page=page_size)),
                        "draft_load_pages")
                self.metrics.register_compiled(self._draft_fill_fn)
                self.metrics.register_compiled(self._draft_load_fn)

    # -- submission -------------------------------------------------------

    def submit(self, req: Request) -> Optional[RequestOutput]:
        """Queue a request.  Returns ``None`` on acceptance; under the
        ``"shed"`` overload policy a request arriving with the waiting
        queue at ``max_queue`` is retired immediately with
        ``FinishReason.SHED`` and its output returned (the ``"raise"``
        policy raises :class:`QueueFull` instead — backpressure the
        frontend can propagate)."""
        return self._submit(req, bounded=True)

    def _submit(self, req: Request,
                bounded: bool = True) -> Optional[RequestOutput]:
        if req.request_id in self._states:
            raise ValueError(f"duplicate request id {req.request_id!r}")
        total = int(req.prompt.shape[0]) + req.params.max_new_tokens
        if total > self.gen.max_seq:
            raise ValueError(
                f"{req.request_id}: prompt + max_new_tokens = {total} "
                f"exceeds max_seq {self.gen.max_seq}")
        fit = self.bm.fit_error(total)
        if fit is not None:
            raise ValueError(f"{req.request_id}: {fit}")
        if self.spec_k and not self.spec_fused and not req.params.greedy:
            # The fused round serves sampled rows through the seeded
            # accept chain (docs/serving.md "Speculative decoding");
            # only the legacy unfused PR-1 round is greedy-only.
            raise ValueError(
                "unfused speculative mode (spec_fused=False) serves "
                "greedy requests only")
        if req.arrival_time is None:
            req.arrival_time = self._clock()
        # Brownout ingress rungs (4/5/6): under a deep enough rung the
        # request's class is refused at the door regardless of queue
        # headroom — rung 4 sheds best_effort, 5 adds batch, 6 finally
        # refuses interactive (the old single cliff, now the LAST rung).
        browned_out = (bounded and self.brownout_cfg is not None
                       and self.brownout_rung >= 4
                       and slo_rank(req.slo_class)
                       >= 6 - self.brownout_rung)
        overloaded = (bounded and self.max_queue is not None
                      and self.scheduler.queue_depth >= self.max_queue)
        displaced: Optional[ReqState] = None
        if browned_out:
            msg = (f"brownout rung {self.brownout_rung}: "
                   f"{req.slo_class} ingress shed")
            overloaded = True
        elif overloaded:
            # Bounded admission: shedding at submit() keeps an overload
            # from growing an unbounded queue of requests that would
            # only expire later — the caller learns immediately.
            msg = (f"queue at bound ({self.scheduler.queue_depth} >= "
                   f"max_queue {self.max_queue})")
            if self.overload == "raise":
                # Raised BEFORE any journal record exists: the frontend
                # was told this request never entered the engine, so a
                # restore must not resurrect and serve it.
                raise QueueFull(f"{req.request_id}: {msg}")
            if self.class_aware:
                # Class-aware displacement: a full queue never sheds a
                # request while a WORSE class holds a queue slot — the
                # latest, lowest-tier waiting request is shed instead
                # and the arrival takes its place (so interactive is
                # only refused once the queue is all-interactive).
                displaced = self.scheduler.pick_shed_victim(
                    slo_rank(req.slo_class))
                if displaced is not None:
                    overloaded = False
        if req.trace is None:
            # a bare engine starts the journey itself: the request id is
            # fleet-unique within any one controller (duplicates are
            # rejected), and the fleet stamps richer ids before submit
            req.trace = {"trace_id": req.request_id, "hop": 0}
        self._trace_ctx[req.request_id] = req.trace
        if self._journal_on(req.request_id):
            # Journaled before the shed retirement below: a shed writes
            # its finish record right after, so restore accounts it.
            self._journal.submit(req)
            self._note_journal()
        rs = ReqState(req=req,
                      metrics=RequestMetrics(arrival_time=req.arrival_time))
        self.trace.emit("submit", req.request_id,
                        prompt=int(req.prompt.shape[0]),
                        max_new=req.params.max_new_tokens)
        self.metrics.observe_class_submit(req.slo_class)
        if overloaded:
            self._states[req.request_id] = rs
            self.metrics.shed += 1
            return self._retire(rs, FinishReason.SHED, free=False,
                                error=msg)
        if displaced is not None:
            # The victim's terminal output cannot return from THIS call
            # (submit answers for the arrival only): it retires now —
            # journal finish, metrics, trace, on_finish all fire here —
            # and the output joins the next step()'s finished batch so
            # a polling controller finalizes its stream too.
            self.scheduler.waiting.remove(displaced)
            self.metrics.shed += 1
            self._shed_pending.append(self._retire(
                displaced, FinishReason.SHED, free=False,
                error=(f"displaced by {req.request_id} "
                       f"({req.slo_class} over "
                       f"{displaced.req.slo_class})")))
        if (self.brownout_cfg is not None and self.brownout_rung >= 3
                and req.slo_class == "best_effort"):
            # rung 3 caps best_effort output length at the door too —
            # a cap that only touched in-flight rows would leak full-
            # length best_effort admitted during the brownout
            rs.new_cap = self.brownout_cfg["best_effort_cap"]
        self._states[req.request_id] = rs
        self.scheduler.add(rs)
        return None

    def abort(self, request_id: str) -> Optional[RequestOutput]:
        """Cancel a request wherever it is; returns its (partial) output.
        Safe mid-step (e.g. from an ``on_token`` callback): the commit
        loops skip rows that retired under them."""
        rs = self._states.get(request_id)
        if rs is None or rs.status is Status.FINISHED:
            return self._outputs.get(request_id)
        if rs.status is Status.WAITING:
            self.scheduler.waiting.remove(rs)
            return self._retire(rs, FinishReason.ABORT, free=False)
        return self._retire(rs, FinishReason.ABORT)

    def has_work(self) -> bool:
        return bool(self.scheduler.waiting) or any(
            s is not None for s in self.slots)

    def has_request(self, request_id: str) -> bool:
        """True when the engine knows this id (queued, running, or
        finished) — a resuming frontend uses it to skip re-submitting
        requests the restored journal already carries."""
        return request_id in self._states

    def unfinished_rids(self) -> list[str]:
        """Ids still in flight (WAITING / PREFILL / RUNNING) — what a
        no-argument :meth:`drain` would hand off.  The network drain
        endpoint (serve/net.py) filters retried rids through this, so a
        drain whose first attempt already landed is a no-op, never an
        error."""
        return [rid for rid, rs in self._states.items()
                if rs.status is not Status.FINISHED
                and not rid.startswith("__warmup_")]

    # -- crash recovery ---------------------------------------------------

    def _journal_on(self, rid: str) -> bool:
        return self._journal is not None and not rid.startswith("__warmup_")

    def _note_journal(self) -> None:
        self.metrics.journal_records = self._journal.records
        self.metrics.journal_bytes = self._journal.bytes

    def _place_pools(self, pools: list) -> list:
        """Lay restored/imported pool arrays out on this engine's mesh
        (no-op off-mesh).  Snapshots hold GLOBAL arrays — orbax
        assembles them regardless of the writer's mesh — so restore
        onto a different mesh shape is one ``device_put`` per leaf
        (docs/serving.md "Sharded serving": recovery across meshes)."""
        if self._pool_sharding is None:
            return pools
        s = self._pool_sharding
        # tree_map covers both pool layouts: bare float arrays and the
        # quantized {"q", "s"} dicts (one sharding leaf fits every plane
        # — P(None, axis) shards the Hkv axis of 4D quant and 3D scale
        # arrays alike; P(axis) shards their block axis).
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, s), pools)

    def snapshot(self, directory: Optional[str] = None) -> dict:
        """Durably capture the FULL serving state — paged KV pools +
        block tables (via the ``runtime/checkpoint`` Orbax path) and
        per-request journal records (prompt, sampling params + PRNG
        stream position, emitted tokens, kv_lens, status, deadline
        timestamps) — such that :meth:`restore` rebuilds an engine whose
        every resumed stream is bit-identical to the uninterrupted run.

        Call between steps (the engine auto-snapshots there with
        ``snapshot_every=N``).  ``directory`` defaults to the engine's
        ``snapshot_dir``.  Returns ``{"step", "ms"}``; latency and
        journal overhead ride ``metrics.summary()["recovery"]``.
        See serve/recovery.py for the format and the exactly-once
        argument; docs/serving.md "Crash recovery" for the recipe."""
        from triton_dist_tpu.serve import recovery

        d = directory or self.snapshot_dir
        if d is None:
            raise ValueError("snapshot() needs a directory: pass one or "
                             "construct the engine with snapshot_dir=")
        info = recovery.snapshot_engine(self, d)
        self.metrics.hist_snapshot.observe(info["ms"] / 1e3)
        self.trace.emit("snapshot", None, step=info["step"],
                        ms=round(info["ms"], 3))
        # A one-shot capture to a foreign directory must not delay the
        # next periodic home-directory snapshot.
        if (self.snapshot_dir is not None
                and os.path.abspath(d) == os.path.abspath(self.snapshot_dir)):
            self._last_snap_step = self.metrics.steps
            if (self.journal_rotate_bytes is not None
                    and self._journal is not None
                    and self._journal.file_bytes
                    > self.journal_rotate_bytes
                    and self._journal.file_bytes
                    >= 2 * self._journal_floor):
                self._rotate_journal()
        return info

    def _rotate_journal(self) -> None:
        """Compact the token journal at a snapshot barrier (docs/
        serving.md "Crash recovery"): each finished request's
        submit/tok/fin record train collapses into ONE ``done`` line
        (prompt, params, tokens, finish — everything a restore rebuilds
        from, so replay semantics are unchanged), and in-flight requests
        rewrite as fresh submit/tok records.  The rewrite is atomic
        (tmp + rename), runs only AFTER the barrier's KV snapshot
        published (a crash mid-rotation leaves a journal some snapshot
        fully covers), and bounds the file: without it a long-lived
        engine's journal grows with every token it ever served
        (ROADMAP #5a).  ``journal_retain_done=N`` caps the rewrite at
        the N most recently finished requests — the older ones leave
        the journal AND the engine's request/output maps (so
        ``get_output`` forgets them; a restore never resurrects a
        finished request either way)."""
        if self.journal_retain_done is not None:
            done = sorted(
                (rid for rid, rs in self._states.items()
                 if rs.status is Status.FINISHED
                 and not rid.startswith("__warmup_")),
                key=lambda rid: (
                    self._states[rid].metrics.finish_time or 0.0,
                    self._states[rid].seq))
            n_drop = len(done) - self.journal_retain_done
            for rid in done[:max(0, n_drop)]:
                del self._states[rid]
                self._outputs.pop(rid, None)
                # the per-request metrics map grows with every request
                # ever retired; pruned history leaves it too, or
                # summary()/prefix_stats() iteration cost (and RSS)
                # would still grow O(total requests forever)
                self.metrics.requests.pop(rid, None)
        recs = []
        for rid, rs in self._states.items():
            if rid.startswith("__warmup_"):
                continue
            if rs.status is Status.FINISHED:
                out = self._outputs.get(rid)
                if out is None:
                    continue
                recs.append({
                    "t": "done", "rid": rid,
                    "prompt": [int(x) for x in np.asarray(rs.req.prompt)],
                    "params": rs.req.params.to_dict(),
                    "slo": rs.req.slo_class,
                    "arrival": rs.req.arrival_time,
                    # carried explicitly: the windowed tts None-pads its
                    # head on long streams, so "first retained ts" would
                    # inflate a restored TTFT by the whole decode
                    "ftt": rs.metrics.first_token_time,
                    "toks": [int(t) for t in out.token_ids],
                    # time_at: the bounded window's base shifts on long
                    # streams — never index the raw list (None pads
                    # forgotten entries, keeping toks[i] <-> tts[i])
                    "tts": [rs.metrics.time_at(i)
                            for i in range(len(out.token_ids))],
                    "reason": out.finish_reason.value,
                    "err": out.error,
                    "fts": rs.metrics.finish_time,
                })
            else:
                recs.append({
                    "t": "submit", "rid": rid,
                    "prompt": [int(x) for x in np.asarray(rs.req.prompt)],
                    "params": rs.req.params.to_dict(),
                    "slo": rs.req.slo_class,
                    "ts": rs.req.arrival_time,
                    "ftt": rs.metrics.first_token_time,
                    # in-flight rows keep their trace context across
                    # rotation: a crash-path manifest rebuilt from the
                    # compacted journal must still carry the journey
                    "trace": self._trace_ctx.get(rid)})
                for i, t in enumerate(rs.generated):
                    recs.append({
                        "t": "tok", "rid": rid, "i": i, "tok": int(t),
                        "ts": rs.metrics.time_at(i)})
        self._journal.rewrite(recs)
        self._journal_floor = self._journal.file_bytes
        self.metrics.journal_rotations += 1
        self._note_journal()

    @classmethod
    def restore(cls, directory, gen, params, **kwargs) -> "ServeEngine":
        """Rebuild an engine from :meth:`snapshot` state (plus the token
        journal) under ``directory``.  Requests whose journal matches
        the KV snapshot resume IN PLACE (pools, block table, pending
        token); journal-ahead or non-fitting requests re-queue through
        admission and replay via the exact-recompute preemption path —
        either way every resumed stream is bit-identical to the
        uninterrupted run.  See :func:`serve.recovery.restore_engine`
        for the knobs (``on_token=`` re-attachment, ``replay_tokens=``,
        geometry overrides)."""
        from triton_dist_tpu.serve import recovery

        return recovery.restore_engine(directory, gen, params, **kwargs)

    # -- live migration ---------------------------------------------------

    def drain(self, rids: Optional[list] = None, *,
              include_kv: bool = True, push: bool = False) -> dict:
        """Migrate-out: remove ``rids`` (default: every unfinished
        request) from this engine and return a migration manifest a
        peer replica's :meth:`migrate_in` continues from — the
        cooperative half of fleet live migration (docs/serving.md
        "Fleet serving"; serve/fleet.py drives it).

        Call between steps (no dispatch in flight).  Each request's
        journal-segment view rides the manifest (prompt, params, the
        emitted token prefix + timestamps); a plain RUNNING row with a
        pending token additionally carries its live KV pages (gathered
        through the warm-prefix ``load_pages`` program) so the target
        adopts it MID-STREAM with zero recompute — the same invariant
        the restore path's in-place resume checks.  ``include_kv=False``
        drops the pages (every row then replays through exact recompute
        on the target — still bit-exact, just not free).

        The source journal gets one ``mig`` record per request — the
        ownership receipt: a later restore of THIS directory never
        resurrects a handed-off request, so the cross-replica token
        union stays exactly-once.  The drained requests leave the
        engine's maps entirely (they are not retirements — no output,
        no finish accounting).

        ``push=True`` keeps the identical receipt/release semantics but
        frames the hand-off as a disaggregated prefill→decode PUSH
        (docs/serving.md "Disaggregated serving"): the ring records
        ``push_out`` instead of ``migrate_out`` and the
        ``pushed_out`` counter advances instead of ``migrated_out`` —
        tier hand-offs and failure migrations stay separately
        observable."""
        from triton_dist_tpu.serve.recovery import MANIFEST_FORMAT

        if rids is None:
            rids = self.unfinished_rids()
        rids = list(dict.fromkeys(rids))  # a duplicate would double-free
        now = self._clock()
        spec_live = bool(self.spec_k) and not self._spec_off
        # Two phases: build EVERY record (validation + KV gather — no
        # engine mutation, the gather only reads the pools) first, then
        # journal the receipts and release the state.  A bad rid or a
        # failed gather must leave the engine exactly as it was — a
        # partially-drained engine whose receipted requests never made
        # it into a manifest would lose their streams irrecoverably
        # (restore skips migrated rids by design).
        staged = []
        # per-request ring tails, gathered ONCE (before any migrate_out
        # event lands in the ring): the manifest carries each request's
        # recent event trail so the adopting replica's ring continues
        # the journey — the merged fleet timeline then shows one
        # connected track across replicas (docs/observability.md
        # "Fleet observability")
        tails: dict[str, list] = {}
        rid_set = set(rids)
        for ts, step, etype, r, data in self.trace.events():
            if r in rid_set:
                tails.setdefault(r, []).append([ts, step, etype, data])
        for rid in rids:
            rs = self._states.get(rid)
            if rs is None or rs.status is Status.FINISHED:
                raise ValueError(f"drain: {rid!r} is not an in-flight "
                                 f"request of this engine")
            rec = {
                "rid": rid,
                "prompt": [int(x) for x in np.asarray(rs.req.prompt)],
                "params": rs.req.params.to_dict(),
                "slo": rs.req.slo_class,
                "arrival": rs.req.arrival_time,
                "tokens": [int(t) for t in rs.generated],
                "tok_ts": [rs.metrics.time_at(i)
                           for i in range(len(rs.generated))],
                "first_tok": rs.metrics.first_token_time,
                "first_sched": rs.metrics.first_scheduled_time,
                "n_preempt": rs.metrics.n_preemptions,
                "cb_off": rs.callback_disabled,
                "trace": dict(self._trace_ctx.get(rid)
                              or {"trace_id": rid, "hop": 0}),
                "events": tails.get(rid, [])[-MIGRATE_EVENT_TAIL:],
            }
            # In-place eligibility is the restore invariant: a plain
            # RUNNING row between steps holds kv_len committed cache
            # rows and ONE emitted-but-unconsumed pending token
            # (kv_len == S0 + len(generated) - 1).  Spec rows have no
            # pending token (their round state is slot-indexed draft
            # caches that cannot leave this engine) — they replay.
            if (include_kv and not spec_live
                    and rs.status is Status.RUNNING
                    and rs.pending_token is not None):
                n_used = self.bm.blocks_for(rs.kv_len)
                ext = self._bucket_s_ext(rs.kv_len)
                ids = np.zeros((ext // self.page,), np.int32)
                ids[:n_used] = self.bm.table(rid)[:n_used]
                scratch = self._device_call(
                    "load_pages", (rid,), self._load_fn, self._pools,
                    jnp.asarray(ids))
                def _host(x):
                    # quantized scratch travels as int8 bytes + scales —
                    # HALF the fp wire bytes, and never requantized
                    if isinstance(x, dict):
                        return {"q": np.asarray(x["q"]),
                                "s": np.asarray(x["s"])}
                    return np.asarray(x)
                rec["kv"] = [(_host(k), _host(v)) for k, v in scratch]
                rec["kv_len"] = rs.kv_len
                rec["pending"] = int(rs.pending_token)
                rec["s_ext"] = ext
            staged.append((rid, rs, rec))
        reqs = []
        for rid, rs, rec in staged:
            if self._journal_on(rid):
                self._journal.migrate(rid, len(rs.generated), now)
                self._note_journal()
            ctx = rec["trace"]
            self.trace.emit("push_out" if push else "migrate_out", rid,
                            tokens=len(rs.generated),
                            in_place="kv" in rec,
                            trace=ctx["trace_id"], hop=ctx["hop"],
                            # flow id of the hand-off this record opens:
                            # the adopting replica's migrate_in closes
                            # the SAME id (its hop is ours + 1), and the
                            # merged Perfetto export draws the arrow
                            flow=f"{ctx['trace_id']}#{ctx['hop'] + 1}")
            self._trace_ctx.pop(rid, None)
            if rs.slot is not None:
                self.slots[rs.slot] = None
            if rs.status is Status.WAITING:
                self.scheduler.waiting.remove(rs)
            if rid in self.bm._tables:
                self.bm.free(rid)
            rs.scratch = None
            rs.status = Status.FINISHED  # terminal for the old object
            del self._states[rid]
            if push:
                self.metrics.pushed_out += 1
            else:
                self.metrics.migrated_out += 1
            reqs.append(rec)
        cfg = self.cfg
        return {
            "format": MANIFEST_FORMAT,
            "clock": now,
            "page_size": self.page,
            "kv_geom": {
                "n_layers": cfg.n_layers,
                "n_kv_heads": cfg.n_kv_heads,
                "head_dim": cfg.head_dim,
                "dtype": str(np.dtype(cfg.dtype)),
                # pool quantization is part of the geometry: int8 pages
                # cannot adopt into fp pools (or vice versa) in place —
                # a mismatched target requeues the request for exact
                # recompute instead
                "kv_quant": self.kv_quant,
            },
            "requests": reqs,
            "finished": [],
        }

    def migrate_in(self, manifest: dict, *,
                   on_token=None, replay_tokens: bool = False,
                   push: bool = False) -> dict:
        """Adopt a migration manifest's requests mid-stream — the target
        half of fleet live migration (docs/serving.md "Fleet serving").

        CAPACITY ADMISSION first, per request: a request whose
        ``prompt + max_new_tokens`` cannot ever fit this engine's
        geometry, whose id this engine already knows, or that would land
        on a waiting queue at ``max_queue`` is REJECTED (left for the
        caller to place elsewhere — nothing about it is journaled
        here).  Accepted requests split two ways:

        - **adopted in place**: the manifest carries live KV + a pending
          token, the page geometry matches, a batch slot is free, and
          the blocks fit — the pages scatter into this engine's pools
          (``fill_pages``), the block table is allocated fresh, and the
          row resumes RUNNING at its exact stream position (zero
          recompute; the Llumnix hand-off).
        - **requeued**: everything else replays through the
          exact-recompute admission path (``work_prompt = prompt +
          generated``) — bit-identical by the PR 5 argument, just not
          free.

        Exactly-once: ``generated`` pre-populates from the manifest's
        journal segment and ``journal_base`` records the carry, so this
        engine never re-emits a carried token; the carried submit/token
        records backfill THIS journal (the single-writer hand-off — the
        source's journal holds the matching ``mig`` receipts).
        ``on_token`` re-attaches streaming callbacks (one callable or a
        ``{rid: callable}`` map); ``replay_tokens=True`` re-fires them
        for the carried prefix.  ``push=True`` is the disaggregated
        prefill→decode admission framing (:meth:`admit_pushed`): the
        identical capacity-admission + in-place-adoption machinery, but
        the ring records ``push_in`` and ``pushed_in`` advances instead
        of the ``migrated_*`` counters.  Returns ``{"adopted",
        "requeued", "rejected"}`` (rejected maps rid -> reason)."""
        from triton_dist_tpu.serve.recovery import (
            MANIFEST_FORMAT,
            _resolve_callback,
            _shift,
        )

        if manifest.get("format") != MANIFEST_FORMAT:
            raise ValueError(
                f"migration manifest format {manifest.get('format')}; "
                f"this build reads format {MANIFEST_FORMAT}")
        offset = self._clock() - (manifest.get("clock") or 0.0)
        spec_live = bool(self.spec_k) and not self._spec_off
        geom_ok = (manifest.get("page_size") == self.page
                   and manifest.get("kv_geom") == {
                       "n_layers": self.cfg.n_layers,
                       "n_kv_heads": self.cfg.n_kv_heads,
                       "head_dim": self.cfg.head_dim,
                       "dtype": str(np.dtype(self.cfg.dtype)),
                       "kv_quant": self.kv_quant,
                   })
        adopted, requeued, rejected = [], [], {}
        for rec in manifest.get("requests", ()):
            rid = rec["rid"]
            if rid in self._states:
                rejected[rid] = "duplicate request id"
                continue
            params = SamplingParams.from_dict(rec["params"])
            prompt = np.asarray(rec["prompt"], np.int32)
            total = int(prompt.shape[0]) + params.max_new_tokens
            if total > self.gen.max_seq:
                rejected[rid] = (f"prompt + max_new_tokens = {total} "
                                 f"exceeds max_seq {self.gen.max_seq}")
                continue
            fit = self.bm.fit_error(total)
            if fit is not None:
                rejected[rid] = fit
                continue
            if (self.max_queue is not None
                    and self.scheduler.queue_depth >= self.max_queue):
                rejected[rid] = (f"queue at bound "
                                 f"({self.scheduler.queue_depth} >= "
                                 f"max_queue {self.max_queue})")
                continue
            tokens = [int(t) for t in rec.get("tokens", [])]
            rm = RequestMetrics(
                arrival_time=_shift(rec.get("arrival"), offset)
                or self._clock())
            rm.first_scheduled_time = _shift(rec.get("first_sched"),
                                             offset)
            rm.first_token_time = _shift(rec.get("first_tok"), offset)
            rm.seed_token_times(
                [_shift(t, offset) for t in (rec.get("tok_ts") or [])],
                total=len(tokens))
            rm.n_preemptions = rec.get("n_preempt", 0)
            # the source already fed its queue-wait into ITS histogram;
            # observing it again here would double-count the fleet SLO
            rm.queue_observed = rm.first_scheduled_time is not None
            # trace continuity: same trace id, one hop deeper — this
            # life's span of the journey.  The hop also names the flow
            # id the source's migrate_out opened (crash-path manifests
            # carry the ctx from the journal instead).
            prev = rec.get("trace") or {"trace_id": rid, "hop": 0}
            ctx = {"trace_id": prev.get("trace_id", rid),
                   "hop": int(prev.get("hop", 0)) + 1}
            req = Request(rid, prompt, params, arrival_time=rm.arrival_time,
                          on_token=_resolve_callback(on_token, rid),
                          trace=ctx,
                          slo_class=rec.get("slo", "interactive"))
            rs = ReqState(req=req, metrics=rm)
            rs.generated = tokens
            rs.journal_base = len(tokens)
            rs.callback_disabled = bool(rec.get("cb_off", False))
            self._trace_ctx[rid] = ctx
            if self.trace.level > 0 and rec.get("events"):
                # the carried ring tail precedes this engine's own
                # events: the adopting ring CONTINUES the journey, so a
                # postmortem (or the merged fleet timeline) here shows
                # the source-side lifecycle too.  Timestamps stay on
                # the source's wall clock — one monotonic domain for
                # in-process fleets; subprocess domains may skew
                # (docs/observability.md).
                self.trace.seed([[ts, step, et, rid, data]
                                 for ts, step, et, data in rec["events"]])
            # journal the carried segment BEFORE serving resumes (the
            # restore-backfill rule: every life's journal is
            # self-contained on its own)
            if self._journal_on(rid):
                self._journal.submit(req)
                for i, t in enumerate(tokens):
                    ts = rm.time_at(i)
                    self._journal.token(
                        rid, i, t,
                        ts if ts is not None else self._clock())
                self._note_journal()
            in_place = (geom_ok and not spec_live
                        and rec.get("pending") is not None
                        and rec.get("kv") is not None
                        and None in self.slots
                        and rec["kv_len"] + 1 <= self.gen.max_seq
                        and self.bm.can_allocate(rec["kv_len"] + 1))
            self._states[rid] = rs
            if in_place:
                slot = self.slots.index(None)
                self.bm.allocate(rid, rec["kv_len"] + 1)
                n_used = self.bm.blocks_for(rec["kv_len"])
                ids = np.zeros((rec["s_ext"] // self.page,), np.int32)
                ids[:n_used] = self.bm.table(rid)[:n_used]
                def _dev(x):
                    if isinstance(x, dict):
                        return {"q": jnp.asarray(x["q"]),
                                "s": jnp.asarray(x["s"])}
                    return jnp.asarray(x)
                scratch = [(_dev(k), _dev(v)) for k, v in rec["kv"]]
                self._pools = self._device_call(
                    "fill_pages", (rid,), self._fill_fn, self._pools,
                    scratch, jnp.asarray(ids))
                rs.status = Status.RUNNING
                rs.slot = slot
                rs.kv_len = rec["kv_len"]
                rs.pending_token = rec["pending"]
                rs.seq = self.scheduler._seq
                self.scheduler._seq += 1
                self.slots[slot] = rs
                if not push:
                    self.metrics.migrated_in_place += 1
                adopted.append(rid)
            else:
                if tokens:
                    rs.work_prompt = np.concatenate(
                        [prompt, np.asarray(tokens, np.int32)])
                rs.status = Status.WAITING
                self.scheduler.add(rs)
                requeued.append(rid)
            if push:
                self.metrics.pushed_in += 1
            else:
                self.metrics.migrated_in += 1
                self.metrics.migrated_tokens += len(tokens)
            self.trace.emit("push_in" if push else "migrate_in", rid,
                            tokens=len(tokens), in_place=in_place,
                            trace=ctx["trace_id"], hop=ctx["hop"],
                            flow=f"{ctx['trace_id']}#{ctx['hop']}")
            if (replay_tokens and req.on_token is not None
                    and not rs.callback_disabled):
                for t in tokens:
                    req.on_token(rid, t)
        return {"adopted": adopted, "requeued": requeued,
                "rejected": rejected}

    # -- disaggregated prefill -> decode hand-off --------------------------

    def push_ready(self) -> list[str]:
        """Requests whose prefill is complete and whose KV can leave
        RIGHT NOW: plain RUNNING rows holding a pending token between
        steps — exactly :meth:`drain`'s in-place hand-off eligibility.
        The disagg controller (serve/disagg.py) polls this after each
        step to find what a prefill-role replica should push.  Empty
        while speculative rounds are live (spec rows carry slot-indexed
        draft state that cannot leave this engine)."""
        if bool(self.spec_k) and not self._spec_off:
            return []
        return [rid for rid, rs in self._states.items()
                if not rid.startswith("__warmup_")
                and rs.status is Status.RUNNING
                and rs.pending_token is not None]

    def push_out(self, rid: str, target=None) -> dict:
        """Per-request prefill→decode hand-off: build the single-request
        PUSH manifest (journal segment + live KV pages — the same
        records :meth:`drain` emits) and release the request, with the
        ``mig`` receipt journaled so crash recovery never resurrects it
        (docs/serving.md "Disaggregated serving").

        With ``target=None`` (the fleet-controller path) the manifest is
        returned for the caller to deliver — the controller walks the
        decode ranking on a capacity rejection.  With a ``target`` (an
        object exposing ``admit_pushed`` — a peer :class:`ServeEngine`,
        or a ``serve.fleet.RemoteReplica`` over the wire) the hand-off
        is delivered directly and the admission result rides back:
        ``{"manifest", "adopted", "requeued", "rejected"}``."""
        m = self.drain([rid], include_kv=True, push=True)
        if target is None:
            return m
        res = target.admit_pushed(m)
        return {"manifest": m,
                "adopted": res.get("adopted", []),
                "requeued": res.get("requeued", []),
                "rejected": res.get("rejected", {})}

    def admit_pushed(self, manifest: dict, *, on_token=None,
                     replay_tokens: bool = False) -> dict:
        """Admit a prefill replica's PUSH manifest — :meth:`migrate_in`'s
        cheap sibling (docs/serving.md "Disaggregated serving"):
        capacity admission first (a rejected request is left for the
        caller to place elsewhere — nothing journaled here), then
        in-place adoption via the ``fill_pages`` scatter so the row
        resumes RUNNING at its exact stream position with the
        pending-token invariant intact.  Emits ``push_in`` and advances
        ``pushed_in``; otherwise identical semantics and return shape."""
        return self.migrate_in(manifest, on_token=on_token,
                               replay_tokens=replay_tokens, push=True)

    # -- the iteration ----------------------------------------------------

    def step(self) -> list[RequestOutput]:
        """One scheduler iteration; returns requests that finished.

        Failure containment: a request whose prefill or commit fails is
        quarantined (``FinishReason.ERROR``, blocks freed) without
        unwinding the step; batched decode failures retry then bisect
        (:meth:`_forward_contained`); a failed speculative round latches
        speculation off and degrades to plain decode.  Only ``_FATAL``
        (watchdog trips, interrupts) escapes.

        Observability wrapper: the step's wall time feeds the SLO
        histogram, new fault-injector audit entries mirror into the
        flight recorder each iteration, and ANYTHING escaping the step —
        an :class:`runtime.faults.InjectedKill` standing in for process
        death, a watchdog trip, an escalated containment failure — first
        flushes the ring to ``flight_<step>.json`` so the supervisor and
        the chaos harness get a postmortem trail (docs/observability.md;
        the re-raise is unconditional — this is a flight recorder, not a
        containment path)."""
        t0 = time.perf_counter()
        try:
            out = self._step_inner()
        except BaseException as e:
            self._trace_faults()
            self.trace.emit("fault", None, point="crash",
                            kind=type(e).__name__)
            self.flight_flush(f"crash: {type(e).__name__}", force=True)
            raise
        self._trace_faults()
        self.metrics.hist_step.observe(time.perf_counter() - t0)
        return out

    def _step_inner(self) -> list[RequestOutput]:
        self._beat()
        if self._journal is not None:
            # Group-commit deadline sweep: an fsync interval is only
            # checked inside append(), so a traffic pause would leave
            # the burst's last record un-fsynced indefinitely without
            # this per-step nudge.
            self._journal.maybe_sync()
        if self.faults is not None:
            # The audit log stamps every firing with the engine step so
            # a chaos schedule replays deterministically post-mortem.
            self.faults.set_step(self.metrics.steps)
        self.trace.set_step(self.metrics.steps)
        now = self._clock()
        finished: list[RequestOutput] = []
        if self._shed_pending:
            # displacement sheds retired inside submit(): deliver their
            # terminal outputs through the normal finished batch
            finished.extend(self._shed_pending)
            self._shed_pending.clear()
        if self.brownout_cfg is not None:
            self._brownout_step(now)

        # Deadline sweep BEFORE admission: expired WAITING/PREFILL
        # requests retire (DEADLINE) and their slots/blocks free for
        # live traffic this same iteration.  Rows already decoding run
        # to completion — their prefill is paid for.
        for rs in self.scheduler.pop_expired(now):
            finished.append(self._expire(rs, now, free=False))
        for rs in list(self.slots):
            if (rs is not None and rs.status is Status.PREFILL
                    and rs.expired(now)):
                finished.append(self._expire(rs, now, free=True))

        free = [i for i, s in enumerate(self.slots) if s is None]
        for rs in self.scheduler.admit(free, now):
            self.slots[rs.slot] = rs
            self.trace.emit("admit", rs.req.request_id, slot=rs.slot,
                            cached_prefix=rs.cached_prefix)
            # once per request: first_scheduled_time is first-write-wins,
            # so a preempted request's re-admissions would re-observe the
            # ORIGINAL wait and inflate the queue SLO exactly under the
            # overload it exists to diagnose
            qt = rs.metrics.queue_time
            if qt is not None and not rs.metrics.queue_observed:
                rs.metrics.queue_observed = True
                self.metrics.hist_queue.observe(qt)
            if rs.cached_prefix > 0:
                self.metrics.prefix_hits += 1
                self.metrics.prefix_hit_tokens += rs.cached_prefix
                rs.metrics.cached_prefix_tokens = rs.cached_prefix
            try:
                self._start_prefill(rs)
            except _FATAL:
                raise
            except Exception as e:
                if not self._state_intact():
                    raise  # pools consumed: engine-fatal
                # the warm-prefix gather is the only device call here;
                # it reads (never donates) the pools, so a failure is
                # per-request by construction — quarantine and serve on
                finished.append(self._quarantine(rs, f"prefill start: "
                                                     f"{e!r}"))

        prefilling = [s for s in self.slots
                      if s is not None and s.status is Status.PREFILL]
        for rs, n in self.scheduler.prefill_plan(prefilling):
            if rs.status is not Status.PREFILL:
                continue  # aborted mid-step (e.g. from an on_token
            try:          # callback fired earlier in this plan)
                out = self._run_prefill(rs, n, now)
            except _FATAL:
                raise
            except Exception as e:
                if not self._state_intact():
                    raise  # fill_pages donated the pools: engine-fatal
                # Prefill is already per-request (own scratch, own
                # chunk stream) — the poison is isolated by
                # construction; no retry or bisection needed.
                finished.append(self._quarantine(rs, f"prefill: {e!r}"))
                continue
            if out is not None:
                finished.append(out)

        running = [s for s in self.slots
                   if s is not None and s.status is Status.RUNNING]
        if running:
            if self.spec_k and not self._spec_off:
                if self.spec_fused:
                    finished.extend(self._spec_chain(running))
                else:
                    finished.extend(self._spec_round(running))
            else:
                finished.extend(self._decode_once(running))

        self.metrics.observe_step(
            queue_depth=self.scheduler.queue_depth,
            running=len([s for s in self.slots if s is not None]),
            kv_utilization=self.bm.utilization)
        if (self.snapshot_every is not None
                and self.snapshot_dir is not None
                and not self._in_warmup
                and self.metrics.steps - self._last_snap_step
                >= self.snapshot_every):
            # Incremental capture at the step boundary (no dispatch in
            # flight).  A snapshot failure ESCALATES — durability is the
            # contract, and serving on while silently not snapshotting
            # would turn the next crash into unbounded recompute.
            self.snapshot()
        return finished

    def run(self, max_steps: int = 100_000) -> dict[str, RequestOutput]:
        """Step until drained; returns {request_id: output}.  Drives the
        heartbeat (one beat per iteration via :meth:`step`); raises
        ``RuntimeError`` when ``max_steps`` iterations don't drain the
        queue — the backstop against a scheduling livelock."""
        self._beat()
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"engine not drained after {max_steps} "
                                   "steps")
        return dict(self._outputs)

    # -- warmup -----------------------------------------------------------

    def warmup(self) -> dict:
        """Pre-compile every program steady-state serving can hit, so no
        request ever eats an XLA compile stall on the admission path.

        Warmup drives REAL dummy traffic — one max-length request per
        bucket-ladder rung — through the production step loop, so every
        program compiles against exactly the buffers steady state will
        hand it (the executable cache keys on more than shapes: layouts
        and donation lineage matter, so hand-built dummy calls can leave
        the first production step compiling anyway).  The sweep repeats
        until a full round compiles nothing new (a compile fixed point,
        reached on the second round at the latest in practice), then all
        dummy bookkeeping is scrubbed: outputs, request states, and the
        step/latency metrics the dummies generated (the compile counters
        keep accumulating — they are the point).  KV pool pages touched
        by dummies are freed and fully overwritten by the next scatter
        before any read, so no request-visible state leaks.

        Call BEFORE submitting traffic (asserted).  A rung is skipped
        only when no admissible request can reach it (shorter prompts
        and max_new=1 are tried before giving up) — then production
        cannot hit it either.  With a decode ``horizon`` the sweep also
        drains one dummy per HORIZON rung (greedy and sampled variants,
        serially — co-scheduled rung dummies would all bucket to the
        largest limit), so fused decode never compiles under traffic
        either.  Spec mode: the draft prefills through
        its own padded chunk + extent ladder (``draft_prefill`` /
        ``draft_join`` counters), and warmup sweeps THAT ladder too —
        spec-mode admission is fully compile-free after warmup.  An
        attached ``FaultInjector`` is disabled for the duration (dummy
        traffic must not eat injected faults) and the queue bound does
        not apply to warmup dummies.

        Returns ``{"programs": <fresh compiles>, "seconds": <wall>}``;
        the same numbers accumulate in ``metrics.warmup_compiles`` /
        ``metrics.warmup_time`` and ride the ``TDT_DUMP_IR`` dump.
        """
        assert not self.has_work(), "warmup() must run before traffic"
        t0 = time.perf_counter()
        misses0 = self.metrics.compile_misses
        chunk = self.scheduler.prefill_chunk
        # dummy traffic must not pollute serving metrics; the CountingJit
        # wrappers are shared so compile accounting continues
        saved, self.metrics = self.metrics, ServeMetrics()
        self.metrics.compiled_fns = saved.compiled_fns
        guard = (self.faults.disabled() if self.faults is not None
                 else contextlib.nullcontext())
        self._in_warmup = True  # dummy traffic must not trigger snapshots
        # Dummy prompts must not seed or match the content index: their
        # zero-token chains would shadow real traffic's and park dummy
        # blocks in the cache tier past the scrub.  The load/cow device
        # programs are warmed by direct dispatch below instead.
        saved_pc = self.bm.prefix_cache
        self.bm.prefix_cache = False
        # dummy traffic must not pollute the flight recorder either —
        # a production ring starting with __warmup_ lifecycles would
        # waste its bounded capacity on events nobody can act on
        saved_lvl, self.trace.level = self.trace.level, 0
        # ... nor the per-program wall-time histograms: warmup calls ARE
        # compile stalls, and the timers are bound to ``saved`` (the
        # production metrics object), so pause at the master gate
        saved_pt, saved.program_timing = saved.program_timing, False
        try:
            with guard:
                prev, round_ = -1, 0
                while self.metrics.compile_misses != prev and round_ < 4:
                    prev = self.metrics.compile_misses
                    for i, rung in enumerate(self.ladder):
                        # Longest prompt whose _scratch_need fits this
                        # rung: n <= rung keeps the pool pages in, and
                        # n <= (rung // chunk) * chunk keeps the padded
                        # final chunk in.  If even that n buckets LOWER,
                        # no admissible prompt can reach this rung —
                        # skip it (production can't hit it either).
                        n_max = min(rung, (rung // chunk) * chunk,
                                    self.gen.max_seq - 1)
                        if n_max < 1 or self._bucket_s_ext(n_max) != rung:
                            continue
                        # n_min is the shortest prompt reaching this
                        # rung (one past what the rung below can hold);
                        # blocks_for is monotone, so if n_min + 1
                        # doesn't fit, nothing reaching this rung does.
                        if i == 0:
                            n_min = 1
                        else:
                            below = self.ladder[i - 1]
                            n_min = 1 + max(0, min(below,
                                                   (below // chunk)
                                                   * chunk))
                        self._warmup_try(f"w{round_}_{i}", n_max, n_min)
                    if self.spec_k:
                        # Sweep the DRAFT extent ladder too: its rungs
                        # (chunk multiples) need not align with the
                        # engine's scratch rungs, and a cold draft rung
                        # would compile on the admission path.
                        for i, rung in enumerate(self._draft_ladder):
                            n_max = min(rung, self.gen.max_seq - 1)
                            if (n_max < 1
                                    or self._draft_bucket(n_max) != rung):
                                continue
                            n_min = (1 if i == 0
                                     else self._draft_ladder[i - 1] + 1)
                            self._warmup_try(f"wd{round_}_{i}", n_max,
                                             n_min)
                    self.run()
                    if self.horizon > 1 and not self.spec_k:
                        # Horizon rungs compile one program per (scan
                        # length, greedy-or-mixed sampler).  Each rung
                        # drains SERIALLY: co-scheduled rung dummies
                        # would all bucket to the largest limit in the
                        # batch and leave the smaller rungs cold for the
                        # tail of every request's generation.
                        for r in self.h_ladder:
                            if r <= 1:
                                continue
                            for ti, temp in enumerate((0.0, 1.0)):
                                self._warmup_horizon_try(
                                    f"wh{round_}_{r}_{ti}", r, temp)
                                self.run()
                    if self.spec_k and self.spec_fused:
                        # Fused spec-round rungs: one program per
                        # (K rung, greedy-or-mixed).  The dummy traffic
                        # above only reaches the rung its adaptive k
                        # lands on, so the remaining rungs warm by
                        # direct dispatch over an ALL-INACTIVE batch —
                        # every write redirects to the null block /
                        # dead draft slots, and the donated pools +
                        # draft caches are reassigned exactly like a
                        # production call (same donation lineage).
                        for r in self._k_ladder:
                            for ag in (True, False):
                                self._warmup_spec_rung(r, ag)
                        if self._draft_pools is not None:
                            # Draft-side prefix programs: the draft
                            # pool gather + scatter per draft-ladder
                            # rung (all-null ids -> block 0 only).
                            dcfg = self.draft.cfg
                            for rung in self._draft_ladder:
                                ids = jnp.asarray(np.zeros(
                                    (rung // self.page,), np.int32))
                                self._device_call(
                                    "draft_load_pages", (),
                                    self._draft_load_fn,
                                    self._draft_pools, ids)
                                scratch = [
                                    (jnp.zeros((1, dcfg.n_kv_heads,
                                                rung, dcfg.head_dim),
                                               dcfg.dtype),
                                     jnp.zeros((1, dcfg.n_kv_heads,
                                                rung, dcfg.head_dim),
                                               dcfg.dtype))
                                    for _ in range(dcfg.n_layers)]
                                self._draft_pools = self._device_call(
                                    "draft_fill_pages", (),
                                    self._draft_fill_fn,
                                    self._draft_pools, scratch, ids)
                    if self.prefix_cache:
                        # Warm-prefix programs: the pool->scratch gather
                        # (one trace per ladder rung, like fill_pages)
                        # and the one-page COW copy (traced src/dst: one
                        # trace total).  All-null ids / the null block
                        # make the dispatches harmless.
                        for rung in self.ladder:
                            self._device_call(
                                "load_pages", (), self._load_fn,
                                self._pools,
                                jnp.asarray(np.zeros(
                                    (rung // self.page,), np.int32)))
                        self._pools = self._device_call(
                            "cow_copy", (), self._cow_fn, self._pools,
                            jnp.int32(0), jnp.int32(0))
                    for rid in [r for r in self._outputs
                                if r.startswith("__warmup_")]:
                        del self._outputs[rid]
                        del self._states[rid]
                        self._trace_ctx.pop(rid, None)
                    round_ += 1
        finally:
            self._in_warmup = False
            self.bm.prefix_cache = saved_pc
            self.trace.level = saved_lvl
            saved.program_timing = saved_pt
            self.metrics = saved
        dt = time.perf_counter() - t0
        fresh = self.metrics.compile_misses - misses0
        self.metrics.warmup_time += dt
        self.metrics.warmup_compiles += fresh
        return {"programs": fresh, "seconds": dt}

    def _warmup_try(self, tag: str, n_max: int, n_min: int) -> None:
        """Queue ONE warmup dummy for a rung, falling back to smaller
        totals before giving up: the pool may reject n_max + 2 while a
        production request (shorter prompt or max_new=1) reaching the
        same rung is still admittable.  Candidate order: longest first
        (covers the rung's full extent), max_new=2 before 1 (a 2-token
        dummy runs a decode step; a 1-token dummy retires on its
        prefill logits and would leave the decode program cold)."""
        for j, (n, new) in enumerate(
                ((n_max, min(2, self.gen.max_seq - n_max)),
                 (n_max, 1),
                 (n_min, min(2, self.gen.max_seq - n_min)),
                 (n_min, 1))):
            req = Request(f"__warmup_{tag}_{j}", np.zeros((n,), np.int32),
                          SamplingParams(max_new_tokens=new))
            try:
                self._submit(req, bounded=False)
                return
            except ValueError:
                continue

    def _warmup_horizon_try(self, tag: str, rung: int,
                            temperature: float) -> None:
        """Queue ONE warmup dummy reaching horizon rung ``rung``: a
        1-token prompt with ``max_new = rung + 1`` — after the
        prefill-path first token its remaining budget is exactly
        ``rung``, so the planner's bucketed horizon lands on the rung.
        A pool that cannot hold ``2 + rung`` tokens cannot admit ANY
        request able to reach the rung (remaining >= rung needs
        ``max_new >= rung + 1`` on top of a >= 1-token prompt), so a
        rejected dummy means production cannot hit it either.
        ``temperature`` 0/1 sweeps the greedy and mixed-sampler variants
        of the trace."""
        req = Request(f"__warmup_{tag}", np.zeros((1,), np.int32),
                      SamplingParams(max_new_tokens=rung + 1,
                                     temperature=temperature))
        try:
            self._submit(req, bounded=False)
        except ValueError:
            pass

    def _warmup_spec_rung(self, rung: int, all_greedy: bool) -> None:
        """Compile one fused spec-round variant (static K=``rung``,
        ``all_greedy``) by direct dispatch over an all-inactive batch:
        no row is live, so every K/V write redirects to the null block
        (target) or a dead slot row (draft) and no engine state can
        change — but the call's shapes, dtypes, and donation lineage
        (pools + draft caches donated, reassigned) match production
        exactly, so the executable cache key does too."""
        B = self.max_batch
        z32 = jnp.zeros((B,), jnp.int32)
        zb = jnp.zeros((B,), bool)
        sd = self._draft_state
        out = self._device_call(
            "spec_round", (), self._spec_fused_fn, self.params,
            self.draft_params, self._pools, sd.caches,
            jnp.zeros((B, self.n_pages_max), jnp.int32), z32, zb, zb,
            self._last_logits, sd.last_logits, z32, z32,
            jnp.ones((B,), jnp.int32),
            jnp.stack([jax.random.key(0)] * B),
            jnp.ones((B,), jnp.float32), z32,
            jnp.ones((B,), jnp.float32), jnp.ones((B,), bool),
            jnp.full((B,), -1, jnp.int32), K=int(rung),
            all_greedy=all_greedy)
        self._pools = out[0]
        self._draft_state = GenerationState(
            caches=out[1], kv_lens=sd.kv_lens,
            last_logits=sd.last_logits)

    # -- prefill ----------------------------------------------------------

    def _scratch_need(self, n_prompt: int) -> int:
        """Unbucketed scratch extent an ``n_prompt``-token prefill needs:
        its pool pages, OR the padded final chunk's write rounded up to
        prefill_chunk (dynamic_update_slice must never clamp), whichever
        is larger.  THE sizing formula — the ladder cap, the bucket
        lookup, and warmup's per-rung prompt picker all derive from it."""
        chunk = self.scheduler.prefill_chunk
        return max(self.bm.blocks_for(n_prompt) * self.page,
                   -(-n_prompt // chunk) * chunk)

    def _bucket_s_ext(self, n_prompt: int) -> int:
        """Scratch extent for an ``n_prompt``-token prefill, bucketed up
        the ladder."""
        need = self._scratch_need(n_prompt)
        for r in self.ladder:
            if r >= need:
                return r
        raise AssertionError(
            f"bucket ladder {self.ladder} cannot cover scratch extent "
            f"{need} (prompt {n_prompt})")

    def _draft_bucket(self, n_prompt: int) -> int:
        """Draft-side prefill extent for an ``n_prompt``-token prompt,
        bucketed up the draft's chunk-multiple ladder."""
        chunk = self.scheduler.prefill_chunk
        need = -(-n_prompt // chunk) * chunk
        for r in self._draft_ladder:
            if r >= need:
                return r
        raise AssertionError(
            f"draft ladder {self._draft_ladder} cannot cover extent "
            f"{need} (prompt {n_prompt})")

    def _start_prefill(self, rs: ReqState) -> None:
        cfg = self.cfg
        s_ext = self._bucket_s_ext(int(rs.prompt_tokens.shape[0]))
        rs.s_ext = s_ext
        cached = rs.cached_prefix if self.prefix_cache else 0
        chunk = self.scheduler.prefill_chunk
        # Warm prefix (docs/serving.md "Prefix caching"): admission
        # mapped `cached` block-aligned tokens of shared KV into the
        # table; chunked prefill starts at the chunk FLOOR of that (the
        # fixed-chunk trace contract needs chunk-aligned starts — the
        # few tokens between floor and hit recompute bit-identically
        # over the gathered rows) and only the residual pays compute.
        start = (cached // chunk) * chunk
        if start > 0:
            rs.prefill_pos = start
            ids = np.zeros((s_ext // self.page,), np.int32)
            n_hit = cached // self.page
            ids[:n_hit] = self.bm.table(rs.req.request_id)[:n_hit]
            rs.scratch = self._device_call(
                "load_pages", (rs.req.request_id,), self._load_fn,
                self._pools, jnp.asarray(ids))
            self.metrics.prefix_skipped_tokens += start
            return
        if self.kv_quant:
            # quantized scratch in the pool layout: chunked prefill
            # quantizes each chunk's rows as it writes them (the
            # generate._write_chunk convention), so fill_pages moves
            # finished bytes + scales into the pool verbatim.
            def _zs():
                return {"q": jnp.zeros((1, cfg.n_kv_heads, s_ext,
                                        cfg.head_dim), jnp.int8),
                        "s": jnp.zeros((1, cfg.n_kv_heads, s_ext),
                                       jnp.float32)}
            rs.scratch = [(_zs(), _zs()) for _ in range(cfg.n_layers)]
        else:
            rs.scratch = [
                (jnp.zeros((1, cfg.n_kv_heads, s_ext, cfg.head_dim),
                           cfg.dtype),
                 jnp.zeros((1, cfg.n_kv_heads, s_ext, cfg.head_dim),
                           cfg.dtype))
                for _ in range(cfg.n_layers)]

    def _run_prefill(self, rs: ReqState, n_tokens: int,
                     now: float) -> Optional[RequestOutput]:
        prompt = rs.prompt_tokens
        S0 = int(prompt.shape[0])
        end = min(rs.prefill_pos + n_tokens, S0)
        chunk_sz = self.scheduler.prefill_chunk
        logits = None
        n_last = 0
        while rs.prefill_pos < end:
            c = min(chunk_sz, end - rs.prefill_pos)
            # Every call is the ONE fixed chunk shape: the final residual
            # pads with zeros and n_valid masks its K/V writes, so the
            # trace is keyed by (chunk_sz, s_ext bucket) only — varied
            # prompt lengths never compile on the admission path.
            buf = np.zeros((1, chunk_sz), np.int32)
            buf[0, :c] = prompt[rs.prefill_pos:rs.prefill_pos + c]
            rs.scratch, logits = self._device_call(
                "prefill_chunk", (rs.req.request_id,), self._chunk_fn,
                self.params, jnp.asarray(buf), rs.scratch,
                jnp.int32(rs.prefill_pos), quantized=self.kv_quant,
                extent=rs.s_ext, n_valid=jnp.int32(c))
            rs.prefill_pos += c
            n_last = c
            self.metrics.prefill_tokens += c
            if self.trace.level >= 2:
                self.trace.emit("prefill_chunk", rs.req.request_id,
                                n=c, pos=rs.prefill_pos)
        if rs.prefill_pos < S0:
            return None
        return self._finish_prefill(rs, logits, n_last, now)

    def _finish_prefill(self, rs: ReqState, logits, n_last: int,
                        now: float) -> Optional[RequestOutput]:
        rid = rs.req.request_id
        S0 = int(rs.prompt_tokens.shape[0])
        n_prompt_pages = self.bm.blocks_for(S0)
        # One table entry per SCRATCH page (trace keyed by the s_ext
        # bucket, not the prompt's page count); pages past the prompt's
        # allocation scatter their zero-masked padding into the null
        # block.  SHARED prefix pages (a warm hit) scatter there too —
        # their pool pages already hold the exact K/V and are read-only
        # to this request (never write a block with refcount > 1).
        n_hit = (rs.cached_prefix // self.page if self.prefix_cache
                 else 0)
        ids = np.zeros((rs.s_ext // self.page,), np.int32)
        ids[n_hit:n_prompt_pages] = \
            self.bm.table(rid)[n_hit:n_prompt_pages]
        self._pools = self._device_call(
            "fill_pages", (rid,), self._fill_fn, self._pools, rs.scratch,
            jnp.asarray(ids))
        rs.scratch = None
        rs.kv_len = S0
        rs.status = Status.RUNNING
        self.trace.emit("prefill_done", rid, kv_len=S0)
        self._commit_full_blocks(rs)
        last = logits[:, n_last - 1]                       # [1, V]
        if self.spec_k and not self._spec_off:
            self._last_logits = self._last_logits.at[rs.slot].set(last[0])
            self._join_draft(rs)
            return None  # first token emitted by the next verify round
        token = self._choose_token(rs, last[0])
        return self._commit_token(rs, token)

    def _join_draft(self, rs: ReqState) -> None:
        """Prefill the draft model for a joining row (spec mode) through
        the SAME padded fixed-chunk machinery as the target: every chunk
        call is the one ``prefill_chunk`` shape (final residual padded,
        K/V zero-masked by ``n_valid``) against a temp cache whose
        extent buckets up the draft ladder, then one traced-slot splice
        lands the row in the batch caches — O(len(draft ladder))
        programs cover every prompt length, so spec-mode admission
        never compiles after warmup (the old ``draft.prefill`` path
        compiled per distinct length).

        Warm prefix (docs/serving.md "Speculative decoding"): the
        draft's K/V for every FULL prompt page is also scattered into
        draft-geometry pools under the request's block ids, each page
        tagged with the block's content-index key.  A later warm admit
        whose target prefix hit covers blocks with matching tags skips
        the draft prefill for them too — the gathered draft pages feed
        the residual chunks exactly like the target's warm path — so a
        shared system prompt no longer re-prefills the full prompt on
        the DRAFT side.  Tag validation is reuse-safe by construction:
        a reused block id's content-index key changes or vanishes, and
        the tag compare fails."""
        rid = rs.req.request_id
        prompt = np.asarray(rs.prompt_tokens)
        S0 = int(prompt.shape[0])
        chunk = self.scheduler.prefill_chunk
        page = self.page
        dcfg = self.draft.cfg
        ext = self._draft_bucket(S0)
        table = (self.bm.table(rid) if self._draft_pools is not None
                 else [])
        d_skip = 0
        if self._draft_pools is not None and rs.cached_prefix > 0:
            for logical in range(rs.cached_prefix // page):
                b = table[logical]
                key = self.bm.block_key(b)
                if key is None or self._draft_page_key.get(b) != key:
                    break
                d_skip += page
        start = (d_skip // chunk) * chunk
        if start > 0:
            # Gather the draft's cached prefix pages into the prefill
            # scratch; tokens between the chunk floor and the hit
            # recompute bit-identically over the gathered rows (the
            # target warm path's argument, draft-side).
            ids = np.zeros((ext // page,), np.int32)
            ids[:d_skip // page] = table[:d_skip // page]
            caches = self._device_call(
                "draft_load_pages", (rid,), self._draft_load_fn,
                self._draft_pools, jnp.asarray(ids))
            self.metrics.draft_prefix_skipped_tokens += start
        else:
            caches = [
                (jnp.zeros((1, dcfg.n_kv_heads, ext, dcfg.head_dim),
                           dcfg.dtype),
                 jnp.zeros((1, dcfg.n_kv_heads, ext, dcfg.head_dim),
                           dcfg.dtype))
                for _ in range(dcfg.n_layers)]
        logits = None
        n_last = 0
        for off in range(start, S0, chunk):
            c = min(chunk, S0 - off)
            buf = np.zeros((1, chunk), np.int32)
            buf[0, :c] = prompt[off:off + c]
            caches, logits = self._device_call(
                "draft_prefill", (rid,), self._draft_chunk_fn,
                self.draft_params, jnp.asarray(buf), caches,
                jnp.int32(off), quantized=False, extent=ext,
                n_valid=jnp.int32(c))
            n_last = c
        if self._draft_pools is not None:
            # Commit the draft's prompt pages (before the splice — the
            # join donates nothing of ``caches``, this fill only reads
            # it).  Shared blocks rewrite too: their draft content is a
            # deterministic function of the certified chain, so the
            # overwrite is idempotent.  Only FULL pages get a reuse tag.
            n_prompt_pages = self.bm.blocks_for(S0)
            ids = np.zeros((ext // page,), np.int32)
            lo = d_skip // page
            ids[lo:n_prompt_pages] = table[lo:n_prompt_pages]
            self._draft_pools = self._device_call(
                "draft_fill_pages", (rid,), self._draft_fill_fn,
                self._draft_pools, caches, jnp.asarray(ids))
            for logical in range(min(S0 // page, len(table))):
                key = self.bm.block_key(table[logical])
                if key is not None:
                    self._draft_page_key[table[logical]] = key
        sd = self._draft_state
        new_caches, kv_lens, last_logits = self._device_call(
            "draft_join", (rid,), self._draft_join_fn, sd.caches,
            sd.kv_lens, sd.last_logits, caches, jnp.int32(rs.slot),
            jnp.int32(S0), logits[0, n_last - 1])
        self._draft_state = GenerationState(
            caches=new_caches, kv_lens=kv_lens, last_logits=last_logits)

    # -- token choice / emission -----------------------------------------

    def _choose_token(self, rs: ReqState, logits_row) -> int:
        """HOST-side token choice — the prefill-first-token and
        single-step (H=1 / spec-verify fallback) path only; the fused
        decode horizon samples ON DEVICE through
        ``sampling.sample_logits_rowwise``, which is pinned bit-identical
        to this path (same filter math, same ``fold_in(key(seed),
        emission_index)`` stream — tests/test_sampling.py), so a stream
        may cross between the two mid-request (preemption, horizon
        clamps) without a token ever differing."""
        p = rs.req.params
        if p.greedy:
            return int(np.argmax(np.asarray(logits_row)))
        # Per-token PRNG stream keyed by (seed, emission index): a
        # preempted-and-recomputed request keeps drawing the same stream.
        key = jax.random.fold_in(jax.random.key(p.seed),
                                 len(rs.generated))
        tok = sample_logits(jnp.asarray(logits_row)[None], key,
                            temperature=p.temperature, top_k=p.top_k,
                            top_p=p.top_p)
        return int(tok[0])

    def _commit_token(self, rs: ReqState, token: int,
                      now: Optional[float] = None
                      ) -> Optional[RequestOutput]:
        """Emit one token; retire the request when it finishes.  The
        token stays ``pending`` (not yet in the cache) until the next
        decode step consumes it.  Timestamps are taken HERE (not at the
        step boundary) so TTFT/ITL separate tokens emitted within one
        iteration (prefill completion + same-step decode); a horizon
        burst commit passes explicit ``now`` values paced by the DEVICE
        step cadence (``RequestMetrics.burst_times``), since its tokens
        were produced steps apart but drain together.

        The ``on_token`` callback is CONTAINED: a raising frontend
        callback used to propagate out of ``step()`` with the token
        already committed, corrupting mid-step state — now it is logged
        once, the request's callback is disabled, and serving
        continues.  A callback may also ``abort()`` requests (including
        this one): commit re-checks status afterwards so a retired
        request is never retired twice."""
        if rs.status is Status.FINISHED:  # aborted mid-step by a callback
            return self._outputs.get(rs.req.request_id)
        if now is None:
            now = self._clock()
        rs.generated.append(token)
        rs.pending_token = token
        first = rs.metrics.first_token_time is None
        itl = rs.metrics.on_token(now)
        if first:
            ttft = rs.metrics.ttft
            if ttft is not None:
                self.metrics.hist_ttft.observe(ttft)
                self.metrics.class_ttft_hist(
                    rs.req.slo_class).observe(ttft)
        elif itl is not None:
            self.metrics.hist_itl.observe(itl)
        if self._journal_on(rs.req.request_id):
            # The journal append PRECEDES the on_token callback: a crash
            # in between re-derives nothing (the token is durable) and
            # re-delivers nothing (restore resumes past it) — the stream
            # is exactly-once; callback delivery for this one token is
            # at-most-once (restore(replay_tokens=True) flips that).
            self._journal.token(rs.req.request_id,
                                len(rs.generated) - 1, token, now)
            self._note_journal()
        if rs.req.on_token is not None and not rs.callback_disabled:
            try:
                if self.faults is not None:
                    self.faults.fire("callback", rid=rs.req.request_id)
                rs.req.on_token(rs.req.request_id, token)
            except _FATAL:
                raise
            except Exception as e:
                rs.callback_disabled = True
                self.metrics.callback_errors += 1
                print(f"[serve] {rs.req.request_id}: on_token callback "
                      f"raised ({e!r}); callback disabled, request "
                      f"keeps serving", file=sys.stderr)
        if rs.status is Status.FINISHED:  # callback aborted this request
            return self._outputs.get(rs.req.request_id)
        p = rs.req.params
        if p.eos_id is not None and token == p.eos_id:
            return self._retire(rs, FinishReason.EOS)
        if len(rs.generated) >= rs.effective_max_new:
            return self._retire(rs, FinishReason.LENGTH)
        return None

    def _retire(self, rs: ReqState, reason: FinishReason, *,
                free: bool = True, error: Optional[str] = None
                ) -> RequestOutput:
        now = self._clock()
        if free:
            self.bm.free(rs.req.request_id)
            self.slots[rs.slot] = None
        rs.status = Status.FINISHED
        rs.slot = None
        rs.scratch = None
        rs.pending_token = None
        rs.metrics.finish_time = now
        if self._journal_on(rs.req.request_id):
            self._journal.finish(rs.req.request_id, reason.value, error,
                                 len(rs.generated), now)
            self._note_journal()
        out = RequestOutput(request_id=rs.req.request_id,
                            prompt=rs.req.prompt,
                            token_ids=list(rs.generated),
                            finish_reason=reason, metrics=rs.metrics,
                            error=error)
        self._outputs[rs.req.request_id] = out
        self.metrics.observe_finish(rs.req.request_id, rs.metrics, reason,
                                    slo_class=rs.req.slo_class)
        self.trace.emit("retire", rs.req.request_id,
                        reason=reason.value, n_tokens=len(rs.generated))
        # the journey ends here: the per-request trace context must not
        # outlive the request (the maps above are pruned; this one is too)
        self._trace_ctx.pop(rs.req.request_id, None)
        if rs.req.on_finish is not None:
            # The terminal notification, fired on EVERY retirement path
            # (shed at submit, deadline sweep, quarantine, healthy
            # finish) — a zero-token retirement never touches on_token,
            # so without this a shed request's consumer waits forever.
            # Contained like on_token: a raising frontend must not
            # corrupt the retirement that already happened.
            try:
                rs.req.on_finish(out)
            except _FATAL:
                raise
            except Exception as e:
                self.metrics.callback_errors += 1
                print(f"[serve] {rs.req.request_id}: on_finish callback "
                      f"raised ({e!r}); ignored", file=sys.stderr)
        return out

    # -- flight recorder plumbing ----------------------------------------

    def _trace_faults(self) -> None:
        """Mirror NEW fault-injector audit entries into the ring (one
        ``fault`` event per firing, same (point, call, kind, who, step)
        tuple) — by construction every audit entry has a matching event,
        which is exactly what the completeness test cross-checks."""
        if self.faults is None or self.trace.level <= 0:
            return
        fired = self.faults.fired
        for point, call, kind, who, step in fired[self._trace_fault_idx:]:
            self.trace.emit("fault", who, point=point, call=call,
                            kind=kind, at_step=step)
        self._trace_fault_idx = len(fired)

    def flight_flush(self, reason: str,
                     force: bool = False) -> Optional[str]:
        """Write the event ring to ``flight_<step>.json`` — the
        postmortem trail.  Directory preference: the snapshot dir FIRST
        (the supervisor's postmortem globs exactly there — a
        ``TDT_DUMP_IR``-first rule would silently divert the trail the
        moment the IR switch is armed), else ``TDT_DUMP_IR``; no-op
        without either or with tracing off.  Throttled to one file per engine step so a quarantine
        storm cannot turn the fault path into an I/O loop.  Best-effort:
        a failing flush must never mask the fault being recorded."""
        if self.trace.level <= 0:
            return None
        d = self.snapshot_dir or ir_dump.dump_dir()
        if d is None or (not force
                         and self.trace.step == self._last_flight_step):
            return None
        self._last_flight_step = self.trace.step
        try:
            from triton_dist_tpu.serve.metrics import format_statline

            statline = format_statline(self.metrics.light_summary())
        except Exception:  # noqa: BLE001 — crash-path best effort
            statline = None
        try:
            return self.trace.flush(d, reason=reason, statline=statline)
        except Exception:  # noqa: BLE001 — crash-path best effort
            return None

    # -- failure containment ---------------------------------------------

    def _beat(self) -> None:
        """Synchronous heartbeat — deliberately not Heartbeat's daemon
        thread: a wedged forward must STOP the beats so an external
        supervisor sees the stall as a stale file.  Throttled to a
        quarter of the supervisor cadence (wall clock, independent of
        the — possibly fake — engine clock) so fast step loops don't
        pay a file write per iteration."""
        if self.heartbeat is None:
            return
        t = time.monotonic()
        if t - self._last_beat >= self.heartbeat.interval_s / 4:
            self.heartbeat.beat()
            self._last_beat = t

    def _state_intact(self) -> bool:
        """Containment precondition: the shared KV pools survived the
        failure.  The batched forwards DONATE the pools — an exception
        raised after dispatch (a genuine device error, as opposed to a
        pre-dispatch injector/seam failure) may have consumed them, and
        a retry over deleted buffers would cascade the fault onto every
        request while the engine kept reporting healthy steps.  When
        the pools are gone, containment escalates to the caller
        instead — a lost pool is an engine-level failure, like a
        tripped watchdog."""
        return not any(getattr(x, "is_deleted", lambda: False)()
                       for x in jax.tree_util.tree_leaves(self._pools))

    def _expire(self, rs: ReqState, now: float,
                *, free: bool) -> RequestOutput:
        """Retire a deadline-expired WAITING/PREFILL request."""
        self.metrics.deadline_expired += 1
        waited = now - (rs.req.arrival_time or now)
        return self._retire(
            rs, FinishReason.DEADLINE, free=free,
            error=(f"deadline {rs.req.params.deadline_s}s exceeded "
                   f"({waited:.3f}s since arrival, status "
                   f"{rs.status.value})"))

    # -- graceful-degradation ladder --------------------------------------

    def _brownout_step(self, now: float) -> None:
        """One evaluation of the brownout ladder (docs/serving.md
        "Overload, SLO classes & autoscaling"), called at the top of
        every step while ``brownout=`` is armed.

        Pressure is the worse of queue backlog (normalized by
        ``max_queue``, or ``4 * max_batch`` unbounded) and KV-pool
        utilization, smoothed by a clock-driven EMA over ``window_s``
        (deterministic under a fake clock — no wall reads).  The rung
        climbs ONE level after ``dwell_steps`` consecutive steps above
        ``high`` and descends one after as many below ``low``; the
        dwell counter is the hysteresis that keeps a bursty boundary
        from flapping the ladder every step."""
        cfg = self.brownout_cfg
        qd = self.scheduler.queue_depth
        denom = (self.max_queue if self.max_queue
                 else 4 * self.max_batch)
        pressure = max(qd / denom if denom else 0.0,
                       self.bm.utilization)
        if self._pressure_t is None or cfg["window_s"] <= 0:
            self._pressure_ema = pressure
        else:
            dt = max(now - self._pressure_t, 0.0)
            alpha = 1.0 - math.exp(-dt / cfg["window_s"])
            self._pressure_ema += alpha * (pressure - self._pressure_ema)
        self._pressure_t = now
        if self._pressure_ema > cfg["high"] and self.brownout_rung < 6:
            self._brownout_dwell = max(self._brownout_dwell, 0) + 1
            if self._brownout_dwell >= cfg["dwell_steps"]:
                self._brownout_dwell = 0
                self._set_brownout(self.brownout_rung + 1)
        elif self._pressure_ema < cfg["low"] and self.brownout_rung > 0:
            self._brownout_dwell = min(self._brownout_dwell, 0) - 1
            if -self._brownout_dwell >= cfg["dwell_steps"]:
                self._brownout_dwell = 0
                self._set_brownout(self.brownout_rung - 1)
        else:
            self._brownout_dwell = 0

    def _set_brownout(self, rung: int) -> None:
        """Move the ladder to ``rung``, applying/releasing each rung's
        effect (entering and leaving both land a ``brownout`` trace
        event and move the ``serve_brownout_rung`` gauge — a degrade
        decision is never silent)."""
        prev, self.brownout_rung = self.brownout_rung, rung
        if rung == prev:
            return
        self.metrics.observe_brownout(rung)
        self.trace.emit("brownout", None, rung=rung, prev=prev,
                        pressure=round(self._pressure_ema, 4))
        # rung 2: chunked-prefill budget halves (floor: one chunk, the
        # scheduler's own livelock floor); released on descent
        sched = self.scheduler
        sched.prefill_budget = (
            max(sched.prefill_chunk, self._base_prefill_budget // 2)
            if rung >= 2 else self._base_prefill_budget)
        # rung 3: best_effort emission caps (>= 1 token of headroom on
        # live rows so every capped row retires through a normal LENGTH
        # commit); released on descent — a request that outlived the
        # brownout serves its full budget
        cap = self.brownout_cfg["best_effort_cap"]
        for rs in self._states.values():
            if (rs.status is Status.FINISHED
                    or rs.req.slo_class != "best_effort"):
                continue
            if rung >= 3:
                rs.new_cap = max(len(rs.generated) + 1, cap)
            elif rs.new_cap is not None:
                rs.new_cap = None

    def _quarantine(self, rs: ReqState, msg: str) -> RequestOutput:
        """Retire a poison request (``FinishReason.ERROR``): its blocks
        free immediately so the pool stays whole, its partial output is
        preserved, and the rest of the batch keeps serving."""
        self.metrics.quarantined += 1
        print(f"[serve] {rs.req.request_id}: quarantined — {msg}",
              file=sys.stderr)
        out = self._retire(rs, FinishReason.ERROR,
                           free=rs.slot is not None, error=msg)
        self._trace_faults()
        self.flight_flush(f"quarantine: {rs.req.request_id}")
        return out

    # Decode-loop device programs: their dispatches count toward
    # metrics.dispatches (summary()["decode"] — the denominator of
    # tokens_per_dispatch).  Admission-path programs (prefill, page
    # scatter, draft join) do not.
    _DECODE_OPS = frozenset({"paged_decode", "paged_verify", "draft_step",
                             "decode_horizon", "spec_round",
                             "draft_tail_step"})

    def program_registry(self) -> list:
        """Every compiled device program behind this engine, as audit
        records for ``analysis.jaxpr_audit`` (docs/analysis.md): the
        ``CountingJit`` wrappers ``metrics.register_compiled`` collected
        at construction, each with its declared static-kwarg ladders
        (the horizon's ``H`` rides ``h_ladder``, the spec round's ``K``
        the pow2 k-ladder — off-ladder statics are the cache-fork
        class) and its allowed collective seams (world-1 programs allow
        none; mesh programs declare ``serve.mesh.collective_seams``)."""
        ladders = {
            "decode_horizon": {"H": tuple(self.h_ladder),
                               "all_greedy": (True, False)},
            "spec_round": {"K": tuple(getattr(self, "_k_ladder", ())),
                           "all_greedy": (True, False)},
            "draft_tail_step": {"K": tuple(getattr(self, "_k_ladder",
                                                   ()))},
        }
        if self.mesh is not None:
            from triton_dist_tpu.serve import mesh as serve_mesh

            seams = serve_mesh.collective_seams(
                self.cfg, kv_shard=self.kv_shard,
                draft_cfg=(self.draft.cfg if self.draft is not None
                           else None))
        else:
            seams = {}
        recs, seen = [], set()
        for fn in self.metrics.compiled_fns:
            name = getattr(fn, "name", None)
            if name is None or name in seen:
                continue
            seen.add(name)
            recs.append({"name": name, "fn": fn,
                         "ladders": ladders.get(name, {}),
                         "seams": seams.get(name, {})})
        return recs

    def _device_call(self, op: str, rids: tuple, fn, *args,
                     fire_injector: bool = True, **kwargs):
        """The ONE guarded device-dispatch seam: the ``forward`` fault
        point fires inside the watched thunk (an injected stall trips
        the watchdog exactly like a wedged device), and with
        ``step_timeout_s`` set the result is forced to ready under
        ``runtime.watchdog`` so a hung forward raises
        :class:`WatchdogTimeout` instead of wedging ``run()`` forever
        (the heartbeat file goes stale — the beats are synchronous).

        ``fire_injector=False`` skips the fault seam: links 2..N of a
        pipelined horizon chain dispatch through it — an injected fault
        AFTER link 1 donated the pools would otherwise leave a
        retry-looking state whose retry double-commits link 1's burst
        (the chain fires the injector exactly once, at its head)."""
        def call():
            if fire_injector and self.faults is not None:
                self.faults.fire("forward", op=op, rids=rids)
            # Counted AFTER the injector seam: an injector-aborted
            # attempt never reached the device and must not inflate
            # dispatches_per_token under chaos.
            if op in self._DECODE_OPS:
                self.metrics.dispatches += 1
            out = fn(*args, **kwargs)
            return (jax.block_until_ready(out)
                    if self.step_timeout_s is not None else out)
        if self.step_timeout_s is None:
            return call()
        try:
            return run_with_watchdog(call, self.step_timeout_s, name=op)
        except WatchdogTimeout:
            self.metrics.watchdog_trips += 1
            self.trace.emit("fault", None, point="watchdog", op=op)
            self.flight_flush(f"watchdog: {op}")
            raise

    def _forward_contained(self, rows: list[ReqState], runner, kind: str,
                           finished: list) -> None:
        """Run ``runner(rows)`` — ONE batched forward plus its per-row
        commits — containing failures: the whole set retries up to
        ``fault_retries`` times (transient faults), then bisects to
        isolate the poison row(s); a single row that still fails is
        quarantined and its slot-mates re-run clean.  ``runner`` must
        keep all engine-state mutation AFTER its device sync, so a
        failed attempt leaves nothing committed and the retry is safe
        (per-row commit errors are contained inside ``runner`` itself
        and never escape it).  Precondition for every retry: the
        donated pools survived (:meth:`_state_intact`) — a genuine
        post-dispatch device failure escalates instead of cascading
        over deleted buffers."""
        err = None
        for attempt in range(1 + max(self.fault_retries, 0)):
            try:
                runner(rows)
                return
            except _FATAL:
                raise
            except ChainCommitted:
                raise  # bursts already committed: a retry double-emits
            except Exception as e:
                if not self._state_intact():
                    raise  # donated pools consumed: engine-fatal
                err = e
                if attempt < self.fault_retries:
                    self.metrics.forward_retries += 1
        if len(rows) == 1:
            rs = rows[0]
            if rs.status is Status.RUNNING:
                finished.append(self._quarantine(
                    rs, f"{kind} forward failed after "
                        f"{1 + self.fault_retries} attempts: {err!r}"))
            return
        self.metrics.forward_bisections += 1
        mid = len(rows) // 2
        for half in (rows[:mid], rows[mid:]):
            live = [r for r in half if r.status is Status.RUNNING]
            if live:
                self._forward_contained(live, runner, kind, finished)

    # -- capacity / preemption -------------------------------------------

    def _ensure_capacity(self, rs: ReqState, n_tokens: int) -> None:
        """Grow ``rs``'s allocation to ``n_tokens`` rows, preempting
        later-admitted slot holders (running OR mid-prefill — both hold
        blocks) until it fits.  Victims never include ``rs`` itself;
        when none remain the pool is genuinely too small for this
        request and the engine raises.

        Capacity includes EXCLUSIVITY (docs/serving.md "Prefix
        caching"): every page the grown request may write must be owned
        by it alone, so shared pages in the write range copy-on-write
        split here — under the same preemption loop, since the split
        needs a fresh block too."""
        while True:
            try:
                self.bm.ensure(rs.req.request_id, n_tokens)
                self._cow_writable(rs)
                return
            except BlockExhausted:
                victim = self.scheduler.pick_victim(
                    [s for s in self.slots if s is not None
                     and s.status in (Status.RUNNING, Status.PREFILL)],
                    rs)
                if victim is None:
                    raise RuntimeError(
                        f"{rs.req.request_id}: cannot extend to "
                        f"{n_tokens} tokens and no preemption victim "
                        f"remains — the block pool ({self.bm.num_blocks}"
                        " blocks) is too small for this request")
                self._preempt(victim)

    def _preempt(self, victim: ReqState) -> None:
        self.trace.emit("preempt", victim.req.request_id,
                        kv_len=victim.kv_len,
                        generated=len(victim.generated))
        self.slots[victim.slot] = None
        victim.scratch = None
        self.scheduler.preempt(victim)
        self.metrics.preemptions += 1
        self.metrics.observe_class_preempt(victim.req.slo_class)

    # -- prefix sharing: copy-on-write + content commits ------------------

    def _cow_writable(self, rs: ReqState) -> None:
        """Copy-on-write guard (docs/serving.md "Prefix caching"): every
        logical page from ``rs``'s current length to the end of its
        allocation — the pages a decode/verify write may touch — must be
        exclusively owned.  A page still shared (refcount > 1: a
        partially-filled tail mapped into several tables by beam-style
        sharing or a restored overlapping snapshot) splits here: the
        block manager swaps in a fresh block and the device copies the
        page BEFORE any write can land.  Admission-shared prefix pages
        are full pages strictly below the write range, so steady-state
        traffic never pays a copy — the loop is a few dict lookups."""
        rid = rs.req.request_id
        table = self.bm.table(rid)
        for logical in range(rs.kv_len // self.page, len(table)):
            if self.bm.ref_of(table[logical]) <= 1:
                continue
            old, new = self.bm.cow(rid, logical)
            self.trace.emit("cow_split", rid, old=old, new=new,
                            logical=logical)
            self._pools = self._device_call(
                "cow_copy", (rid,), self._cow_fn, self._pools,
                jnp.int32(old), jnp.int32(new))

    def _commit_full_blocks(self, rs: ReqState) -> None:
        """Register every newly-FULL logical page of ``rs`` in the
        content index (``BlockManager.commit_block``) so later prompts —
        a multi-turn session's next turn, an identical system prompt, a
        preempted victim's recompute — map it read-only instead of
        re-prefilling.  Generated tokens commit too, the moment their
        page fills: cache row ``j`` holds the K/V of ``prompt[j]`` for
        ``j < S0`` and of ``generated[j - S0]`` past it (a recompute
        prompt is exactly that concatenation, so the indexing is
        invariant under preemption).  ``committed_pages`` is the
        watermark — each page commits once per admission."""
        if not self.bm.prefix_cache:
            return
        full = rs.kv_len // self.page
        if full <= rs.committed_pages:
            return
        rid = rs.req.request_id
        prompt = rs.req.prompt
        S0 = int(prompt.shape[0])
        for logical in range(rs.committed_pages, full):
            lo = logical * self.page
            toks = [int(prompt[j]) if j < S0 else rs.generated[j - S0]
                    for j in range(lo, lo + self.page)]
            self.bm.commit_block(rid, logical, toks)
        rs.committed_pages = full

    # -- plain decode -----------------------------------------------------

    def _decode_once(self,
                     running: list[ReqState]) -> list[RequestOutput]:
        """One decode pass for the running rows: a single per-token step
        (the PR-1 path) or, with ``horizon > 1`` and the scheduler's
        blessing, a fused multi-step horizon dispatch (pipelined when
        ``pipeline > 1``).  Capacity for the WHOLE planned horizon is
        reserved up front — a row that cannot grow quarantines here, per
        row, exactly like the single-step path."""
        finished: list[RequestOutput] = []
        h_plan = self.scheduler.plan_horizon(
            self.horizon,
            prefilling=any(s is not None and s.status is Status.PREFILL
                           for s in self.slots),
            spec=bool(self.spec_k),
            deadline_waiting=any(
                w.req.params.deadline_s is not None
                for w in self.scheduler.waiting))
        links = self.pipeline if h_plan > 1 else 1
        for rs in sorted(running, key=lambda r: r.seq):
            if rs.status is Status.RUNNING:  # may get preempted below
                want = rs.kv_len + min(max(h_plan, 1) * links,
                                       rs.remaining_new)
                want = min(want, rs.total_tokens)
                try:
                    self._ensure_capacity(rs, want)
                except _FATAL:
                    raise
                except Exception as e:
                    # No-victim RuntimeError or an injected alloc fault:
                    # this request cannot grow — quarantine it (its
                    # blocks come back) instead of unwinding the step.
                    finished.append(self._quarantine(
                        rs, f"kv grow to {want} rows: {e!r}"))
        live = [r for r in running if r.status is Status.RUNNING]
        if not live:
            return finished
        h_eff = 1
        if h_plan > 1:
            # The scan length is a STATIC trace parameter: bucket the
            # planned horizon down the ladder so tail-of-generation
            # batches reuse compiled rungs instead of tracing one
            # program per residual length.
            h_eff = bucket_down(
                self.h_ladder,
                min(h_plan, max(r.remaining_new for r in live)))
        if h_eff <= 1:
            self._forward_contained(
                live, lambda rows: self._decode_rows(rows, finished),
                "decode", finished)
        else:
            self._forward_contained(
                live,
                lambda rows: self._decode_horizon_rows(rows, h_eff,
                                                       finished),
                "decode horizon", finished)
        return finished

    def _decode_rows(self, rows: list[ReqState], finished: list) -> None:
        """ONE batched decode for ``rows`` (other slots inactive — their
        writes redirect to the null block) + per-row commits.  All
        engine-state mutation happens after the logits sync, so a
        failed dispatch leaves nothing committed and
        :meth:`_forward_contained` can retry or bisect safely."""
        B = self.max_batch
        tokens = np.zeros((B,), np.int32)
        lens = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        tables = np.zeros((B, self.n_pages_max), np.int32)
        for rs in rows:
            b = rs.slot
            tokens[b] = rs.pending_token
            lens[b] = rs.kv_len
            active[b] = True
            tables[b] = self.bm.padded_table(rs.req.request_id,
                                             self.n_pages_max)
        pools, logits = self._device_call(
            "paged_decode", tuple(r.req.request_id for r in rows),
            self._decode_fn, self.params, self._pools,
            jnp.asarray(tables), jnp.asarray(lens), jnp.asarray(tokens),
            jnp.asarray(active))
        logits_np = np.asarray(logits)  # sync BEFORE committing pools
        self._pools = pools
        self.metrics.decode_steps += 1
        self.metrics.host_syncs += 1
        toks0 = self.metrics.decode_tokens

        for rs in rows:
            if rs.status is not Status.RUNNING:
                continue  # aborted mid-loop by a slot-mate's callback
            rs.kv_len += 1
            rs.pending_token = None
            self._commit_full_blocks(rs)  # the write just landed
            try:
                token = self._choose_token(rs, logits_np[rs.slot])
                out = self._commit_token(rs, token)
            except _FATAL:
                raise
            except Exception as e:
                finished.append(self._quarantine(rs, f"commit: {e!r}"))
                continue
            self.metrics.decode_tokens += 1
            if out is not None:
                finished.append(out)
        self.trace.emit("decode_drain", None, h=1, rows=len(rows),
                        tokens=self.metrics.decode_tokens - toks0)

    def _decode_horizon_rows(self, rows: list[ReqState], h: int,
                             finished: list) -> None:
        """Fused multi-step decode for ``rows``: up to ``pipeline``
        chained ``_paged_decode_horizon`` dispatches of ``h`` steps each,
        then an in-order drain committing each link's token burst.

        The async pipeline is the point of the chaining: every link's
        carry (kv lengths, last token, EOS marks, PRNG counters) stays
        DEVICE-RESIDENT, so link N+1 dispatches before link N's results
        ever reach the host, and the host commits link N's burst (token
        bookkeeping, ``on_token`` callbacks) while the device executes
        link N+1 — ``block_until_ready`` is deferred to each link's drain
        point.  (With ``step_timeout_s`` set the watchdog forces every
        link ready at dispatch, so the links serialize and only the
        step-fusion win remains — stall detection and dispatch overlap
        are mutually exclusive by construction.)  A row that hits EOS
        mid-link is frozen by the device for the rest of the chain
        (``eos_done`` carry); its retire, block free, and the discard of
        any later-link output all happen at drain, guarded by the same
        status checks as the single-step path.

        Containment mirrors :meth:`_decode_rows`: nothing host-side
        mutates before the first drain, the injector seam fires once at
        the chain head (see ``_device_call(fire_injector=...)``), so
        :meth:`_forward_contained` can retry/bisect a failed chain whose
        pools survived; once any burst has committed, failures escalate
        as :class:`ChainCommitted` instead (a retry would double-emit)."""
        B = self.max_batch
        tokens = np.zeros((B,), np.int32)
        lens = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        tables = np.zeros((B, self.n_pages_max), np.int32)
        counts = np.zeros((B,), np.int32)
        temps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        greedy = np.ones((B,), bool)
        eos_ids = np.full((B,), -1, np.int32)
        rem = np.zeros((B,), np.int32)
        for rs in rows:
            b = rs.slot
            p = rs.req.params
            tokens[b] = rs.pending_token
            lens[b] = rs.kv_len
            active[b] = True
            tables[b] = self.bm.padded_table(rs.req.request_id,
                                             self.n_pages_max)
            counts[b] = len(rs.generated)
            temps[b] = p.temperature if not p.greedy else 1.0
            top_ks[b] = p.top_k or 0
            top_ps[b] = p.top_p if p.top_p is not None else 1.0
            greedy[b] = p.greedy
            eos_ids[b] = p.eos_id if p.eos_id is not None else -1
            # Per-row step budget: remaining max-tokens AND the pages the
            # host actually reserved (the page-boundary early exit).
            rem[b] = min(rs.remaining_new,
                         self.bm.capacity_tokens(rs.req.request_id)
                         - rs.kv_len)
        all_greedy = bool(greedy[active].all())
        rids = tuple(r.req.request_id for r in rows)

        # Host link plan: link j runs min(h, what's left after j-1) steps
        # per row; the device masks enforce it, EOS exits ride the carry.
        # Each link's scan length buckets DOWN the ladder from its own
        # max budget — a tail link covering a 2-step residual runs the
        # warmed H=2 program, not h-2 dead full-batch forwards on the
        # H=h one (every rung is warmup-swept, so no new traces).
        budgets = []
        left = rem.copy()
        for _ in range(max(self.pipeline, 1)):
            need = int(left[active].max()) if active.any() else 0
            if need <= 1:
                # A 1-step residual is NOT worth a link: warmup never
                # compiles the H=1 horizon variant (the planner routes
                # single steps to the legacy `_decode_rows` program), so
                # the next iteration picks it up there — same dispatch
                # count, no cold trace under traffic.
                break
            h_link = bucket_down(self.h_ladder, min(h, need))
            lim = np.minimum(left, h_link).astype(np.int32)
            budgets.append((h_link, lim))
            left = left - lim

        # Dispatch every link before draining any (async pipelining);
        # the carry arrays never touch the host between links.
        kv_d = jnp.asarray(lens)
        tok_d = jnp.asarray(tokens)
        done_d = jnp.zeros((B,), bool)
        cnt_d = jnp.asarray(counts)
        tables_d = jnp.asarray(tables)
        active_d = jnp.asarray(active)
        # Host-built per-row base keys — the SAME jax.random.key(p.seed)
        # call `_choose_token` makes, so seeds the int32 array route
        # would overflow (>= 2**31) stream identically at every H.
        key_rows = [jax.random.key(0)] * B
        if not all_greedy:
            for rs in rows:
                if not rs.req.params.greedy:
                    key_rows[rs.slot] = jax.random.key(rs.req.params.seed)
        samp = (jnp.stack(key_rows), jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps),
                jnp.asarray(greedy), jnp.asarray(eos_ids))
        outs = []
        t_prev = self._clock()
        for j, (h_link, lim) in enumerate(budgets):
            (pools, toks, mask, kv_d, tok_d, done_d,
             cnt_d) = self._device_call(
                "decode_horizon", rids, self._horizon_fn, self.params,
                self._pools, tables_d, kv_d, tok_d, active_d, done_d,
                jnp.asarray(lim), cnt_d, *samp, H=int(h_link),
                all_greedy=all_greedy, fire_injector=(j == 0))
            self._pools = pools
            outs.append((toks, mask))

        # Drain in order: committing link j's burst overlaps the device
        # executing links > j (nothing here forces their results).
        committed = False
        try:
            for toks, mask in outs:
                toks_np, mask_np = jax.device_get((toks, mask))
                self.metrics.host_syncs += 1
                now = self._clock()
                steps = int(mask_np.any(axis=0).sum())
                self.metrics.decode_steps += steps
                step_s = (now - t_prev) / max(steps, 1)
                t_prev = now
                toks0 = self.metrics.decode_tokens
                for rs in sorted(rows, key=lambda r: r.seq):
                    if rs.status is not Status.RUNNING:
                        continue  # retired mid-drain (EOS/abort/length)
                    b = rs.slot
                    n = int(mask_np[b].sum())
                    if n == 0:
                        continue
                    rs.kv_len += n  # the device already wrote these rows
                    times = rs.metrics.burst_times(now, n, step_s)
                    out = None
                    try:
                        for i in range(n):
                            out = self._commit_token(
                                rs, int(toks_np[b, i]), now=times[i])
                            committed = True
                            self.metrics.decode_tokens += 1
                            if (out is not None
                                    or rs.status is not Status.RUNNING):
                                break  # retired; rest of burst discarded
                    except _FATAL:
                        raise
                    except Exception as e:
                        finished.append(self._quarantine(
                            rs, f"commit: {e!r}"))
                        continue
                    if rs.status is Status.RUNNING:
                        # the burst's tokens are in `generated` now, so
                        # any page the device filled this link commits
                        self._commit_full_blocks(rs)
                    if out is not None:
                        finished.append(out)
                self.trace.emit(
                    "decode_drain", None, h=steps,
                    tokens=self.metrics.decode_tokens - toks0)
        except (*_FATAL, ChainCommitted):
            raise
        except Exception as e:
            if committed:
                raise ChainCommitted(
                    f"horizon chain failed after committing tokens: "
                    f"{e!r}") from e
            raise

    # -- fused speculative rounds (docs/serving.md "Speculative
    # decoding") ----------------------------------------------------------

    def _spec_chain(self,
                    running: list[ReqState]) -> list[RequestOutput]:
        """Up to ``pipeline`` chained ``_spec_round_fused`` dispatches —
        ONE device dispatch per whole speculative round (draft k-scan,
        verify, accept, closing decode) with the carry (kv lengths, both
        models' round-opening logits, emission counters, EOS/budget
        exits) staying device-resident between rounds, then an in-order
        drain committing each round's accepted burst.  The spec twin of
        :meth:`_decode_horizon_rows`: round j+1 dispatches before round
        j's results reach the host, and the host commits round j's
        tokens while the device runs j+1.

        Adaptive k: each row's depth comes from the scheduler's windowed
        acceptance estimate (``choose_spec_k``), the batch max buckets
        down the pow2 k-ladder (static scan length — one warmed trace
        per rung), and per-row depths ride the traced ``k_rows`` array.

        Containment keeps the PR-3 contract: capacity growth
        quarantines per request; a device failure latches speculation
        OFF via :meth:`_spec_bailout_fused` — already-drained tokens
        stand, undrained rows emit exactly what the round would have
        emitted first — and the engine degrades to plain decode
        bit-exactly."""
        finished: list[RequestOutput] = []
        live = [r for r in running if r.status is Status.RUNNING]
        top = max(r.kv_len for r in live)
        k_cap = min(self.spec_k, self.gen.max_seq - 1 - top,
                    self.draft.max_seq - 1 - top)
        if self.brownout_rung >= 1:
            # brownout rung 1: clamp speculation to k=1 — the cheapest
            # rung sheds DRAFT compute, not user tokens (the k=1 rung
            # is already on the warmed pow2 k-ladder, so no new traces)
            k_cap = min(k_cap, 1)
        if k_cap <= 0:
            return self._spec_tail(live)
        links = self.scheduler.plan_spec(
            self.pipeline,
            prefilling=any(s is not None and s.status is Status.PREFILL
                           for s in self.slots),
            deadline_waiting=any(
                w.req.params.deadline_s is not None
                for w in self.scheduler.waiting))
        # Capacity for the WHOLE chain up front (capped at the admitted
        # total; writes past the allocation land in dead padded-table
        # entries -> the null block, never a live page).
        for rs in sorted(live, key=lambda r: r.seq):
            if rs.status is Status.RUNNING:
                want = min(rs.kv_len + links * (k_cap + 1),
                           rs.total_tokens)
                try:
                    self._ensure_capacity(rs, want)
                except _FATAL:
                    raise
                except Exception as e:
                    finished.append(self._quarantine(
                        rs, f"kv grow (spec chain): {e!r}"))
        live = [r for r in live if r.status is Status.RUNNING]
        if not live:
            return finished

        B = self.max_batch
        lens = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        tables = np.zeros((B, self.n_pages_max), np.int32)
        counts = np.zeros((B,), np.int32)
        limits = np.zeros((B,), np.int32)
        k_rows = np.ones((B,), np.int32)
        temps = np.ones((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        greedy = np.ones((B,), bool)
        eos_ids = np.full((B,), -1, np.int32)
        key_rows = [jax.random.key(0)] * B
        for rs in live:
            b = rs.slot
            p = rs.req.params
            lens[b] = rs.kv_len
            active[b] = True
            tables[b] = self.bm.padded_table(rs.req.request_id,
                                             self.n_pages_max)
            counts[b] = len(rs.generated)
            # Per-row emission budget: remaining max-tokens AND the
            # reserved page capacity (never binds after a successful
            # _ensure_capacity — kept as the device-side safety net).
            limits[b] = min(rs.remaining_new,
                            self.bm.capacity_tokens(rs.req.request_id)
                            - rs.kv_len)
            k_rows[b] = (self.scheduler.choose_spec_k(
                             rs, k_cap, window=self.spec_adaptive)
                         if self.spec_adaptive else k_cap)
            temps[b] = p.temperature if not p.greedy else 1.0
            top_ks[b] = p.top_k or 0
            top_ps[b] = p.top_p if p.top_p is not None else 1.0
            greedy[b] = p.greedy
            eos_ids[b] = p.eos_id if p.eos_id is not None else -1
            if not p.greedy:
                # Host-built typed keys, like the horizon: any seed the
                # host path accepts (>= 2**31 included) streams
                # identically on device.
                key_rows[b] = jax.random.key(p.seed)
        all_greedy = bool(greedy[active].all())
        k_rung = bucket_down(self._k_ladder, int(k_rows[active].max()))
        chain_k = {rs.slot: min(int(k_rows[rs.slot]), k_rung)
                   for rs in live}
        rids = tuple(r.req.request_id for r in live)
        # A round emits >= 1 token per live row, so rounds beyond the
        # widest per-row budget would dispatch dead full-batch work.
        links = max(1, min(links, int(limits[active].max())))

        kv_d = jnp.asarray(lens)
        act_d = jnp.asarray(active)
        done_d = jnp.zeros((B,), bool)
        tables_d = jnp.asarray(tables)
        cnt_d = jnp.asarray(counts)
        lim_d = jnp.asarray(limits)
        k_rows_d = jnp.asarray(k_rows)
        samp = (jnp.stack(key_rows), jnp.asarray(temps),
                jnp.asarray(top_ks), jnp.asarray(top_ps),
                jnp.asarray(greedy), jnp.asarray(eos_ids))
        # The PRE-CHAIN round-opening logits: every live row's next
        # emission comes from these until its first burst commits, so
        # any bailout with uncommitted rows must sample HERE — never
        # from the chain's advanced carry (which already consumed
        # device-emitted tokens the host never saw).
        opening = self._last_logits
        last_d = opening
        dcaches = self._draft_state.caches
        dlast_d = self._draft_state.last_logits
        outs = []
        t_prev = self._clock()
        try:
            for j in range(links):
                (pools, dcaches, toks, n_emit, m_acc, kv_d, last_d,
                 dlast_d, cnt_d, lim_d, done_d) = self._device_call(
                    "spec_round", rids, self._spec_fused_fn,
                    self.params, self.draft_params, self._pools,
                    dcaches, tables_d, kv_d, act_d, done_d, last_d,
                    dlast_d, cnt_d, lim_d, k_rows_d, *samp,
                    K=int(k_rung), all_greedy=all_greedy,
                    fire_injector=(j == 0))
                self._pools = pools
                # Re-anchor the draft state per link: a LATER link's
                # dispatch failure must not leave _draft_state pointing
                # at buffers this link's donation already consumed (the
                # spec_off snapshot guard in recovery covers the
                # failed-dispatch-itself case).
                self._draft_state = GenerationState(
                    caches=dcaches, kv_lens=kv_d, last_logits=dlast_d)
                self.metrics.spec_dispatches += 1
                outs.append((toks, n_emit, m_acc))
        except _FATAL:
            raise
        except Exception as e:
            if not self._state_intact():
                raise  # donated pools consumed: engine-fatal
            # Nothing drained: the pre-chain opening logits are what
            # every live row's accept would have emitted from.
            return finished + self._spec_bailout_fused(live, set(), e,
                                                       opening)
        # The chain's final carry opens the next step's round.
        self._last_logits = last_d

        # Drain in order; committing round j overlaps rounds > j on
        # device.  Status checks guard every commit (abort/EOS/quarantine
        # mid-drain), exactly like the horizon drain.
        committed: set[int] = set()
        try:
            for toks, n_emit, m_acc in outs:
                toks_np, n_np, m_np = jax.device_get(
                    (toks, n_emit, m_acc))
                self.metrics.host_syncs += 1
                now = self._clock()
                burst = int(n_np.max())
                step_s = (now - t_prev) / max(burst, 1)
                t_prev = now
                round_live = False
                toks0 = self.metrics.spec_tokens
                for rs in sorted(live, key=lambda r: r.seq):
                    if rs.status is not Status.RUNNING:
                        continue
                    b = rs.slot
                    n = int(n_np[b])
                    if n == 0:
                        continue
                    round_live = True
                    prop = chain_k[b]
                    acc = min(int(m_np[b]), prop)
                    rs.spec_window.append((prop, acc))
                    # keep at least the configured adaptive window
                    del rs.spec_window[:-max(32, self.spec_adaptive)]
                    self.metrics.observe_spec_row(prop, acc, prop)
                    rs.kv_len += n  # the device already wrote the rows
                    times = rs.metrics.burst_times(now, n, step_s)
                    out = None
                    try:
                        for i in range(n):
                            out = self._commit_token(
                                rs, int(toks_np[b, i]), now=times[i])
                            committed.add(b)
                            self.metrics.decode_tokens += 1
                            self.metrics.spec_tokens += 1
                            if (out is not None
                                    or rs.status is not Status.RUNNING):
                                break  # retired; rest of burst dropped
                    except _FATAL:
                        raise
                    except Exception as e:
                        finished.append(self._quarantine(
                            rs, f"commit: {e!r}"))
                        continue
                    if rs.status is Status.RUNNING:
                        # spec-mode invariant: the round's closing decode
                        # already consumed the burst's last token — there
                        # is no pending token (commit_token set one)
                        rs.pending_token = None
                        self._commit_full_blocks(rs)
                    if out is not None:
                        finished.append(out)
                if round_live:
                    self.metrics.verify_rounds += 1
                    self.metrics.spec_rounds += 1
                    self.trace.emit(
                        "spec_round", None, k=int(k_rung),
                        tokens=self.metrics.spec_tokens - toks0)
        except _FATAL:
            raise
        except Exception as e:
            if not self._state_intact():
                raise
            # Rows with a drained burst re-open their last token as
            # pending; rows without one (only possible when the FIRST
            # drain failed) sample from the pre-chain opening logits.
            return finished + self._spec_bailout_fused(live, committed,
                                                       e, opening)
        return finished

    def _spec_tail(self, live: list[ReqState]) -> list[RequestOutput]:
        """No headroom to speculate (the last cache slots): one plain
        target token per row via the host sampler, consumed by one paged
        decode (which also refreshes the round-opening logits) with the
        draft stepping along — the fused path's k<=0 fallback,
        generalized from the unfused round's greedy-only one to sampled
        rows (:meth:`_choose_token` serves both).  This must never
        under-serve a draft-less engine."""
        finished: list[RequestOutput] = []
        for rs in sorted(live, key=lambda r: r.seq):
            if rs.status is Status.RUNNING:
                try:
                    self._ensure_capacity(
                        rs, min(rs.kv_len + 1, rs.total_tokens))
                except _FATAL:
                    raise
                except Exception as e:
                    finished.append(self._quarantine(
                        rs, f"kv grow (spec tail): {e!r}"))
        live = [r for r in live if r.status is Status.RUNNING]
        if not live:
            return finished
        B = self.max_batch
        lens = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        tables = np.zeros((B, self.n_pages_max), np.int32)
        toks_np = np.zeros((B,), np.int32)
        last_np = np.asarray(self._last_logits)
        self.metrics.host_syncs += 1
        for rs in live:
            b = rs.slot
            lens[b] = rs.kv_len
            active[b] = True
            tables[b] = self.bm.padded_table(rs.req.request_id,
                                             self.n_pages_max)
            toks_np[b] = self._choose_token(rs, last_np[b])
        rids = tuple(r.req.request_id for r in live)
        closing = jnp.asarray(toks_np)
        lens_d = jnp.asarray(lens)
        active_d = jnp.asarray(active)
        opening = self._last_logits  # the logits the tokens came from
        try:
            self._pools, logits = self._device_call(
                "paged_decode", rids, self._decode_fn, self.params,
                self._pools, jnp.asarray(tables), lens_d, closing,
                active_d)
            self.metrics.decode_steps += 1
            sd = self._draft_state
            dcaches, dlens, dlogits = self._device_call(
                "draft_tail_step", rids, self._draft_tail_fn,
                self.draft_params, sd.caches, lens_d, closing, active_d)
            # Commit the carry only once BOTH dispatches succeeded: a
            # draft-step failure bails out below, and the bailout must
            # re-derive each row's token from the ROUND-OPENING logits
            # — overwriting _last_logits first would hand it the
            # post-consumption logits and fork the stream.
            self._last_logits = logits
            self._draft_state = GenerationState(
                caches=dcaches, kv_lens=dlens, last_logits=dlogits)
        except _FATAL:
            raise
        except Exception as e:
            if not self._state_intact():
                raise
            # Nothing committed: the bailout re-derives the SAME token
            # per row from the still-intact round-opening logits.
            return finished + self._spec_bailout_fused(live, set(), e,
                                                       opening)
        for rs in sorted(live, key=lambda r: r.seq):
            if rs.status is not Status.RUNNING:
                continue
            rs.kv_len += 1
            out = None
            try:
                out = self._commit_token(rs, int(toks_np[rs.slot]))
                self.metrics.decode_tokens += 1
            except _FATAL:
                raise
            except Exception as e:
                finished.append(self._quarantine(rs, f"commit: {e!r}"))
                continue
            rs.pending_token = None  # the decode above consumed it
            if rs.status is Status.RUNNING:
                self._commit_full_blocks(rs)
            if out is not None:
                finished.append(out)
        return finished

    def _spec_bailout_fused(self, live: list[ReqState], committed: set,
                            err, opening) -> list[RequestOutput]:
        """A fused speculative chain failed mid-flight: latch
        speculation OFF (the device-resident carry and draft state can
        no longer be trusted) and convert every live row to plain-decode
        state, bit-exactly:

        - a row that already committed tokens from this chain keeps
          them and re-opens its LAST token as pending (``kv_len`` steps
          back one row): the next plain decode re-writes that token's
          K/V — an idempotent overwrite, the device already landed it —
          and re-derives the logits the chain was carrying on device;
        - a row that committed nothing emits one token from ``opening``
          — the caller's snapshot of the PRE-CHAIN round-opening logits
          (never the advanced device carry, which has already consumed
          tokens the host never saw) — via the host sampler: exactly
          what the round's accept chain would have emitted first
          (``expected[0]`` is the target's own choice at this emission
          index), so the stream cannot differ from the fault-free run.

        From here the engine serves through :meth:`_decode_once` (full
        retry/bisect containment) and joining prompts take the plain
        prefill path."""
        self._spec_off = True
        self.metrics.spec_bailouts += 1
        self.trace.emit("bailout", None, err=type(err).__name__,
                        fused=True)
        self.flight_flush("spec bailout (fused)")
        print(f"[serve] fused speculative chain failed ({err!r}); "
              f"speculation latched off, serving degrades to plain "
              f"decode", file=sys.stderr)
        finished: list[RequestOutput] = []
        last_np = np.asarray(opening)
        for rs in sorted(live, key=lambda r: r.seq):
            if rs.status is not Status.RUNNING:
                continue
            if rs.slot in committed:
                rs.pending_token = rs.generated[-1]
                rs.kv_len -= 1
                continue
            out = self._commit_token(
                rs, self._choose_token(rs, last_np[rs.slot]))
            if out is not None:
                finished.append(out)
        return finished

    def _spec_round(self,
                    running: list[ReqState]) -> list[RequestOutput]:
        """One speculative round (greedy): draft proposes ``k`` per row,
        one paged multi-token verify scores all rows at their own
        lengths, accepts apply per row, the closing token is consumed by
        a regular paged step — `speculative._generate_batched` re-hosted
        on the paged cache with per-request retirement.

        Containment: capacity growth quarantines per request (like plain
        decode); a device failure anywhere in the round bails out via
        :meth:`_spec_bailout` — the round's device calls are too
        entangled across rows (shared draft state, one verify, one
        closing decode) for mid-round bisection, so the engine commits
        whatever tokens the round had already proven, latches
        speculation OFF, and degrades to plain decode, which has full
        retry/bisect containment."""
        sd = self._draft_state
        finished: list[RequestOutput] = []
        live = [r for r in running if r.status is Status.RUNNING]
        top = max(r.kv_len for r in live)
        k = min(self.spec_k, self.gen.max_seq - 1 - top,
                self.draft.max_seq - 1 - top)
        for rs in sorted(live, key=lambda r: r.seq):
            if rs.status is Status.RUNNING:
                # Capacity capped at the request's admitted total:
                # emissions are clamped to remaining_new anyway, and
                # draft rows the verify writes past the allocation land
                # in the null block (dead padded-table entries) — never
                # read by an emission-eligible query.  Without the cap a
                # request that submit() admitted could demand blocks it
                # can never use and crash/preempt near its end.
                try:
                    self._ensure_capacity(
                        rs, min(rs.kv_len + max(k, 0) + 1,
                                rs.total_tokens))
                except _FATAL:
                    raise
                except Exception as e:
                    finished.append(self._quarantine(
                        rs, f"kv grow (spec round): {e!r}"))
        live = [r for r in live if r.status is Status.RUNNING]
        if not live:
            return finished

        B = self.max_batch
        lens = np.zeros((B,), np.int32)
        active = np.zeros((B,), bool)
        tables = np.zeros((B, self.n_pages_max), np.int32)
        for rs in live:
            lens[rs.slot] = rs.kv_len
            active[rs.slot] = True
            tables[rs.slot] = self.bm.padded_table(rs.req.request_id,
                                                   self.n_pages_max)
        lens_d = jnp.asarray(lens)
        active_d = jnp.asarray(active)
        tables_d = jnp.asarray(tables)
        rids = tuple(r.req.request_id for r in live)
        # Draft lengths track the target's committed lengths.
        sd = GenerationState(caches=sd.caches, kv_lens=lens_d,
                             last_logits=sd.last_logits)

        # Phase 1 — propose + verify + accept.  Engine-state mutation
        # (kv_len, emitted) happens only after the device_get sync, so a
        # failure anywhere here leaves every row exactly as the round
        # found it: the bailout emits one plain greedy token per row
        # from the round-opening logits (what a verify would have
        # emitted first anyway — streams stay bit-exact).
        try:
            if k <= 0:
                # No headroom to speculate (the last cache slots): one
                # plain greedy token via the accept machinery's fallback.
                toks_np = np.argmax(np.asarray(self._last_logits),
                                    axis=-1)
                self.metrics.host_syncs += 1
                closing = jnp.asarray(toks_np.astype(np.int32))
                emitted = {rs.slot: [int(toks_np[rs.slot])]
                           for rs in live}
            else:
                props = []
                for _ in range(k):
                    tok = jnp.argmax(sd.last_logits,
                                     axis=-1).astype(jnp.int32)
                    sd = self._device_call(
                        "draft_step", rids, self.draft.step,
                        self.draft_params, sd, tok, active=active_d)
                    props.append(tok)
                proposals = jnp.stack(props, axis=1)        # [B, k]
                self._pools, logits_all = self._device_call(
                    "paged_verify", rids, self._verify_fn, self.params,
                    self._pools, tables_d, lens_d, proposals, active_d)
                m_dev, toks = greedy_accept_chain_batched(
                    proposals, self._last_logits, logits_all)
                m_np, toks_np = jax.device_get((m_dev, toks))
                self.metrics.host_syncs += 1
                emitted = {}
                closing_np = np.zeros((B,), np.int32)
                for rs in live:
                    b = rs.slot
                    m_used = min(int(m_np[b]), rs.remaining_new - 1)
                    emitted[b] = [int(t) for t in toks_np[b, :m_used + 1]]
                    closing_np[b] = toks_np[b, m_used]
                    rs.kv_len += m_used
                    lens[b] = rs.kv_len
                closing = jnp.asarray(closing_np)
                lens_d = jnp.asarray(lens)
                # Draft rolls back to the per-row accepted lengths too.
                sd = GenerationState(caches=sd.caches, kv_lens=lens_d,
                                     last_logits=sd.last_logits)
        except _FATAL:
            raise
        except Exception as e:
            if not self._state_intact():
                raise  # donated pools consumed: engine-fatal
            return finished + self._spec_bailout(live, None, e)
        self.metrics.verify_rounds += 1
        self.trace.emit("spec_round", None, k=int(max(k, 0)),
                        rows=len(live))

        # Phase 2 — consume each row's closing token: one paged decode
        # step (also refreshes last_logits for the next round) + the
        # draft's step.  On failure the accepted chains are already
        # proven: the bailout commits them, the closing token stays
        # pending, and the next plain decode writes its K/V (an
        # idempotent overwrite when this decode had already landed it).
        try:
            self._pools, logits = self._device_call(
                "paged_decode", rids, self._decode_fn, self.params,
                self._pools, tables_d, lens_d, closing, active_d)
            self.metrics.decode_steps += 1
            self._last_logits = logits
            sd = self._device_call("draft_step", rids, self.draft.step,
                                   self.draft_params, sd, closing,
                                   active=active_d)
            self._draft_state = sd
        except _FATAL:
            raise
        except Exception as e:
            if not self._state_intact():
                raise  # donated pools consumed: engine-fatal
            return finished + self._spec_bailout(live, emitted, e)

        for rs in sorted(live, key=lambda r: r.seq):
            if rs.status is not Status.RUNNING:
                continue  # aborted mid-loop by a slot-mate's callback
            rs.kv_len += 1
            out = None
            for t in emitted[rs.slot]:
                out = self._commit_token(rs, t)
                self.metrics.decode_tokens += 1
                if out is not None or rs.status is not Status.RUNNING:
                    break  # retired mid-round; rest of the chain dropped
            rs.pending_token = None  # spec mode: cache already consumed it
            if rs.status is Status.RUNNING:
                self._commit_full_blocks(rs)
            if out is not None:
                finished.append(out)
        return finished

    def _spec_bailout(self, live: list[ReqState], emitted, err
                      ) -> list[RequestOutput]:
        """A speculative round failed mid-flight: latch speculation OFF
        (the shared draft state can no longer be trusted) and convert
        the live rows to plain-decode state — commit the tokens the
        round had already proven (the accepted chains when the verify
        completed, else one greedy token from the round-opening
        logits), leaving each row's last token PENDING so the next
        plain step writes its K/V.  From here the engine serves through
        :meth:`_decode_once` (full retry/bisect containment) and
        joining prompts take the plain prefill path; emitted streams
        stay bit-exact with the fault-free run."""
        self._spec_off = True
        self.metrics.spec_bailouts += 1
        self.trace.emit("bailout", None, err=type(err).__name__,
                        fused=False)
        self.flight_flush("spec bailout")
        print(f"[serve] speculative round failed ({err!r}); speculation "
              f"latched off, serving degrades to plain decode",
              file=sys.stderr)
        finished = []
        last_np = (np.argmax(np.asarray(self._last_logits), axis=-1)
                   if emitted is None else None)
        for rs in sorted(live, key=lambda r: r.seq):
            if rs.status is not Status.RUNNING:
                continue
            chain = (emitted[rs.slot] if emitted is not None
                     else [int(last_np[rs.slot])])
            out = None
            for t in chain:
                out = self._commit_token(rs, t)
                if out is not None or rs.status is not Status.RUNNING:
                    break
            if out is not None:
                finished.append(out)
        return finished
