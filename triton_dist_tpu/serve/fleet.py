"""Fleet serving: a multi-replica router with live request migration.

PRs 1-8 made ONE engine fast, observable, and crash-resilient — but the
stack still served from exactly one process, so one wedged replica was a
full outage.  This module runs N engine replicas behind an admission
router and makes the PR 5 journal + snapshot + ``BlockManager.adopt``
machinery do what it always was underneath: a *migration* primitive
(the Llumnix live-migration / MegaScale fast-hand-off insight — the TPU
analog of the reference's producer/consumer signal-and-put hand-off,
SURVEY.md §2.5).

Three cooperating layers:

- :class:`Router` — admission placement by queue-depth / deadline
  pressure read from each replica's ``ServeMetrics`` (direct engine
  state in-process; :func:`parse_prometheus` over a ``/metrics`` scrape
  for subprocess replicas — ``scripts/serve_supervisor.py --fleet``).
  SUSPECT and DEAD replicas are circuit-broken out of the candidate
  set, so the router can never place onto a replica that stopped
  making progress.

- **Health state machine** — per replica HEALTHY → SUSPECT → DEAD,
  layered on the existing liveness signals (heartbeat staleness,
  step-progress age, a ``WatchdogTimeout`` or process-death exception
  escaping ``step``).  A SUSPECT replica stops receiving admissions and
  recovers to HEALTHY the moment progress resumes; a DEAD one is killed
  and restarted under :class:`RestartBackoff` (exponential + jitter,
  healthy-uptime budget reset — shared with the supervisor).

- **Live migration** — a dying replica's in-flight requests move to
  healthy peers and finish there.  Cooperative path:
  ``ServeEngine.drain(rids)`` gathers live KV pages + the pending token
  and the target's ``migrate_in`` adopts the row MID-STREAM (zero
  recompute).  Crash path: the dead replica's durable token journal is
  the source of truth — :func:`serve.recovery.manifest_from_journal`
  rebuilds the journal segment and the target replays the remainder
  through the exact-recompute path, bit-identical by the PR 5
  argument.  Either way the source journal records a ``mig`` receipt
  per request, so the union of all replicas' journals holds every
  token of every stream EXACTLY ONCE (the fleet chaos harness in
  tests/test_serve_fleet.py pins this: kill a replica mid-decode under
  load — every stream finishes bit-identical to the single-engine
  oracle, zero lost, zero duplicated).

See docs/serving.md "Fleet serving" for the operator recipe.
"""

from __future__ import annotations

import enum
import os
import random
import sys
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from triton_dist_tpu.runtime.watchdog import WatchdogTimeout
from triton_dist_tpu.serve.metrics import RequestMetrics
from triton_dist_tpu.serve.request import (
    FinishReason,
    Request,
    RequestOutput,
)
from triton_dist_tpu.serve.trace import FlightRecorder


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"   # serving; admissible by the router
    SUSPECT = "suspect"   # progress stalled past suspect_after_s:
    #                       circuit-broken (no admissions), not yet dead
    DEAD = "dead"         # killed or crashed; restarting under backoff


# ---------------------------------------------------------------------------
# Restart backoff (shared by the FleetController and serve_supervisor)
# ---------------------------------------------------------------------------


class RestartBackoff:
    """Exponential restart backoff with jitter and a healthy-uptime
    budget reset.

    A crash-looping child used to restart instantly and burn its whole
    ``max_restarts`` budget in seconds; this paces restarts at
    ``base_s * 2^(attempt-1)`` capped at ``cap_s``, jittered by up to
    ``jitter`` of the delay (deterministic under ``seed`` — restarts
    across a fleet must not synchronize), and FORGIVES the attempt
    count once a life stays up ``healthy_reset_s`` (a process that ran
    healthy for an hour and then died is a fresh incident, not attempt
    #4 of a crash loop).

    Protocol: :meth:`on_start` when the process launches,
    :meth:`on_death` when it dies — returns the delay to wait before
    the next restart, or ``None`` when ``max_restarts`` is exhausted.
    """

    def __init__(self, *, base_s: float = 0.5, cap_s: float = 30.0,
                 jitter: float = 0.5, healthy_reset_s: float = 60.0,
                 max_restarts: Optional[int] = None, seed: int = 0):
        if base_s <= 0 or cap_s < base_s:
            raise ValueError(f"need 0 < base_s <= cap_s, got "
                             f"{base_s}, {cap_s}")
        if not 0 <= jitter <= 1:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.base_s = base_s
        self.cap_s = cap_s
        self.jitter = jitter
        self.healthy_reset_s = healthy_reset_s
        self.max_restarts = max_restarts
        self.attempts = 0
        self._rng = random.Random(seed)
        self._started: Optional[float] = None

    def on_start(self, now: float) -> None:
        self._started = now

    def on_death(self, now: float) -> Optional[float]:
        """Delay before the next restart, or ``None`` (budget spent)."""
        if (self._started is not None
                and now - self._started >= self.healthy_reset_s):
            self.attempts = 0
        self.attempts += 1
        if (self.max_restarts is not None
                and self.attempts > self.max_restarts):
            return None
        d = min(self.cap_s, self.base_s * 2.0 ** (self.attempts - 1))
        return d * (1.0 + self.jitter * self._rng.random())


# ---------------------------------------------------------------------------
# Router: queue-depth / deadline pressure placement
# ---------------------------------------------------------------------------


def parse_prometheus(text: str) -> dict:
    """Parse a Prometheus text exposition into ``{series: value}`` —
    the scrape half of the router's load signal for SUBPROCESS replicas
    (``ServeMetrics.to_prometheus`` is the other end; labeled series
    keep their full left-hand side as the key)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


@dataclass
class ReplicaLoad:
    """One replica's admission-pressure signal, however it was read
    (direct engine state in-process, Prometheus scrape out-of-process)."""

    queue_depth: int = 0
    running: int = 0
    max_batch: int = 1
    kv_util: float = 0.0

    @classmethod
    def from_engine(cls, engine) -> "ReplicaLoad":
        return cls(queue_depth=engine.scheduler.queue_depth,
                   running=sum(1 for s in engine.slots if s is not None),
                   max_batch=engine.max_batch,
                   kv_util=engine.bm.utilization)

    @classmethod
    def from_prometheus(cls, text: str,
                        max_batch: int = 1) -> "ReplicaLoad":
        """Load from a ``/metrics`` scrape (the subprocess path —
        docs/observability.md lists the series names)."""
        g = parse_prometheus(text)
        return cls(queue_depth=int(g.get("serve_queue_depth", 0)),
                   running=int(g.get("serve_running", 0)),
                   max_batch=max_batch,
                   kv_util=float(g.get("serve_kv_utilization", 0.0)))


class Router:
    """Least-pressure admission placement over HEALTHY replicas.

    Pressure is ``queue_weight * queue_depth + running / max_batch +
    kv_weight * kv_util`` — queued requests dominate (one queued
    request outweighs even a fully occupied batch: it is a whole
    request of delay ahead, where a running batch is already making
    progress), batch occupancy and KV pressure break the near-ties.  A
    deadline-carrying request weighs queue depth
    ``deadline_queue_weight``× harder: its TTL burns while it waits, so
    it must land on the emptiest queue even when occupancy says
    otherwise.  Exact pressure ties rotate round-robin so a cold fleet
    does not pile onto one replica."""

    def __init__(self, *, queue_weight: float = 2.0,
                 kv_weight: float = 0.5,
                 deadline_queue_weight: float = 4.0):
        self.queue_weight = queue_weight
        self.kv_weight = kv_weight
        self.deadline_queue_weight = deadline_queue_weight
        self._rr = 0

    def pressure(self, load: ReplicaLoad, *,
                 deadline: bool = False) -> float:
        qw = self.deadline_queue_weight if deadline else self.queue_weight
        return (qw * load.queue_depth
                + load.running / max(load.max_batch, 1)
                + self.kv_weight * load.kv_util)

    def rank(self, candidates: list, *, deadline: bool = False) -> list:
        """``[(name, load)]`` sorted best-first (the migration placer
        walks this to find capacity)."""
        n = max(len(candidates), 1)
        self._rr += 1
        scored = sorted(
            (self.pressure(load, deadline=deadline),
             (i + self._rr) % n, name)
            for i, (name, load) in enumerate(candidates))
        return [name for _, _, name in scored]

    def pick(self, candidates: list, *,
             deadline: bool = False) -> Optional[str]:
        """Best HEALTHY replica for one new request, or ``None``."""
        ranked = self.rank(candidates, deadline=deadline)
        return ranked[0] if ranked else None


# ---------------------------------------------------------------------------
# In-process replica
# ---------------------------------------------------------------------------


class EngineReplica:
    """One in-process engine replica under the :class:`FleetController`.

    Each LIFE gets its own snapshot directory (``root/life<N>``): the
    life's journal is its durable request ownership record, so a crash
    migrates from the dead life's journal and the restart opens a fresh
    one — nothing a previous life owned can leak into the next (the
    handed-off requests carry ``mig`` receipts besides; belt and
    suspenders)."""

    def __init__(self, name: str, factory: Callable, root: str):
        self.name = name
        self._factory = factory
        self.root = root
        self.engine = None
        self.life = 0
        self.state = ReplicaState.DEAD
        self.last_progress: Optional[float] = None
        self.restart_at: Optional[float] = None
        self.restarts = 0          # lives after the first
        self.death_reason: Optional[str] = None

    @property
    def life_dir(self) -> str:
        return os.path.join(self.root, f"life{self.life}")

    def start(self, now: float) -> None:
        self.life += 1
        os.makedirs(self.life_dir, exist_ok=True)
        self.engine = self._factory(self.life_dir)
        if self.engine._journal is None:
            raise ValueError(
                f"replica {self.name}: the factory must build engines "
                f"with snapshot_dir=<life dir> — the journal is what "
                f"crash migration hands off")
        self.state = ReplicaState.HEALTHY
        self.last_progress = now
        self.restart_at = None
        self.death_reason = None

    def load(self) -> ReplicaLoad:
        return ReplicaLoad.from_engine(self.engine)


# ---------------------------------------------------------------------------
# The fleet controller
# ---------------------------------------------------------------------------


class FleetController:
    """N in-process engine replicas behind a :class:`Router`, with
    health-checked circuit breaking, backoff restarts, and live request
    migration (module docstring; docs/serving.md "Fleet serving").

    ``factory(snapshot_dir) -> ServeEngine`` builds one replica life
    (it MUST pass ``snapshot_dir`` through — the journal is the
    migration substrate).  Drive it like an engine: :meth:`submit` then
    :meth:`step`/:meth:`run`; finished streams land in
    :attr:`outputs`, the exactly-once delivery record in
    :attr:`streams`, and per-request placement history (which replicas
    served it) in :attr:`history`.

    Exactly-once across the fleet: every token reaches the caller
    exactly once — live tokens through the wrapped ``on_token``, and on
    a migration the manifest's journal segment fills exactly the
    indices the dead replica journaled but never delivered (the
    commit→callback crash window).  The journal union argument lives in
    serve/recovery.py; the chaos harness asserts both.
    """

    def __init__(self, factory: Callable, n_replicas: int, *,
                 root: str, clock=time.monotonic,
                 router: Optional[Router] = None,
                 suspect_after_s: float = 5.0,
                 dead_after_s: float = 15.0,
                 probe: Optional[Callable] = None,
                 backoff_base_s: float = 0.25,
                 backoff_cap_s: float = 30.0,
                 backoff_jitter: float = 0.5,
                 healthy_reset_s: float = 60.0,
                 max_restarts: Optional[int] = None,
                 trace_events: int = 2048, seed: int = 0):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        if not suspect_after_s < dead_after_s:
            raise ValueError(
                f"need suspect_after_s < dead_after_s, got "
                f"{suspect_after_s}, {dead_after_s}")
        self._clock = clock
        self.router = router or Router()
        self.suspect_after_s = suspect_after_s
        self.dead_after_s = dead_after_s
        # progress age in seconds; replaceable so tests (and subprocess
        # drivers) can layer heartbeat-file staleness in
        self._probe = probe or (
            lambda r, now: now - (r.last_progress
                                  if r.last_progress is not None
                                  else now))
        self.trace = FlightRecorder(capacity=trace_events)
        os.makedirs(root, exist_ok=True)
        self.root = root
        now = self._clock()
        self.replicas: dict[str, EngineReplica] = {}
        self._backoff: dict[str, RestartBackoff] = {}
        for i in range(n_replicas):
            name = f"r{i}"
            rep = EngineReplica(name, factory, os.path.join(root, name))
            self.replicas[name] = rep
            self._backoff[name] = RestartBackoff(
                base_s=backoff_base_s, cap_s=backoff_cap_s,
                jitter=backoff_jitter, healthy_reset_s=healthy_reset_s,
                max_restarts=max_restarts, seed=seed + i)
            rep.start(now)
            self._backoff[name].on_start(now)
        self.steps = 0
        self.deaths = 0
        self.migrations = 0        # requests moved between replicas
        self.outputs: dict[str, RequestOutput] = {}
        self.streams: dict[str, list] = {}   # exactly-once delivery
        self.placement: dict[str, str] = {}  # rid -> current replica
        self.history: dict[str, list] = {}   # rid -> replicas that held it
        self._cbs: dict[str, Callable] = {}  # rid -> wrapped on_token
        self._pending_reqs: deque = deque()  # unplaced fresh requests
        self._pending_recs: deque = deque()  # (header, rec) to re-place

    # -- submission -------------------------------------------------------

    def _make_cb(self, rid: str, orig) -> Callable:
        stream = self.streams[rid]

        def cb(_rid, tok):
            stream.append(int(tok))
            if orig is not None:
                orig(_rid, tok)
        return cb

    def submit(self, req: Request) -> None:
        """Route one request onto the least-pressure HEALTHY replica.
        Fleet-queued while no healthy replica exists (an outage window
        is transient — deadlines still sweep the fleet queue); SHED
        when every healthy replica's waiting queue is at its bound (the
        PR 3 bounded-admission contract holds fleet-wide: the fleet
        sheds only when EVERY replica is full)."""
        rid = req.request_id
        if rid in self.streams:
            raise ValueError(f"duplicate request id {rid!r}")
        if req.arrival_time is None:
            req.arrival_time = self._clock()  # fleet-queue deadlines
        self.streams[rid] = []
        self.history[rid] = []
        self._cbs[rid] = self._make_cb(rid, req.on_token)
        req.on_token = self._cbs[rid]
        if not self._place_request(req):
            self._pending_reqs.append(req)

    def _healthy(self) -> list:
        return [(name, r.load()) for name, r in self.replicas.items()
                if r.state is ReplicaState.HEALTHY]

    def _place_request(self, req: Request) -> bool:
        from triton_dist_tpu.serve.engine import QueueFull

        healthy = self._healthy()
        # capacity-aware: never place onto a queue already at its bound
        # (the engine would shed it; a fleet with room elsewhere must
        # not)
        cands = [(n, l) for n, l in healthy
                 if (self.replicas[n].engine.max_queue is None
                     or l.queue_depth
                     < self.replicas[n].engine.max_queue)]
        deadline = req.params.deadline_s is not None
        for name in self.router.rank(cands, deadline=deadline):
            rep = self.replicas[name]
            try:
                shed = rep.engine.submit(req)
            except QueueFull:
                continue
            self.trace.emit("route", req.request_id, replica=name,
                            state=rep.state.value, deadline=deadline)
            self.placement[req.request_id] = name
            self.history[req.request_id].append(name)
            if shed is not None:   # raced to a full queue: final verdict
                self._finalize(shed, name)
            return True
        if healthy:
            # Healthy replicas exist and EVERY one is at its queue
            # bound: the fleet is genuinely full — shed now (the
            # bounded-admission contract, fleet-wide).  Nothing was
            # journaled anywhere for this request.  With NO healthy
            # replica the caller queues instead: that is a transient
            # outage window, not admission pressure.
            self._shed(req, f"every replica's queue at bound "
                            f"({len(healthy)} healthy)")
            return True
        return False

    def _shed(self, req: Request, msg: str) -> None:
        rm = RequestMetrics(arrival_time=req.arrival_time
                            or self._clock())
        rm.finish_time = self._clock()
        out = RequestOutput(request_id=req.request_id,
                            prompt=req.prompt, token_ids=[],
                            finish_reason=FinishReason.SHED,
                            metrics=rm, error=msg)
        self.trace.emit("retire", req.request_id, reason="shed")
        self._finalize(out, "fleet")

    def _place_rec(self, header: dict, rec: dict,
                   exclude: frozenset = frozenset()) -> bool:
        """Place one migration-manifest record onto a healthy replica
        via ``migrate_in`` (capacity admission: a rejecting replica
        passes it to the next candidate)."""
        rid = rec["rid"]
        cands = [(n, l) for n, l in self._healthy() if n not in exclude]
        params_deadline = rec.get("params", {}).get("deadline_s")
        for name in self.router.rank(cands,
                                     deadline=params_deadline is not None):
            rep = self.replicas[name]
            res = rep.engine.migrate_in(
                {**header, "requests": [rec]},
                on_token={rid: self._cbs.get(rid)})
            if rid in res["rejected"]:
                continue
            self.migrations += 1
            self.trace.emit("migrate_in", rid, replica=name,
                            state=rep.state.value,
                            in_place=rid in res["adopted"])
            self.placement[rid] = name
            self.history[rid].append(name)
            return True
        return False

    def _drain_pending(self, exclude: frozenset = frozenset()) -> None:
        for _ in range(len(self._pending_recs)):
            header, rec = self._pending_recs.popleft()
            if not self._place_rec(header, rec, exclude):
                self._pending_recs.append((header, rec))
        for _ in range(len(self._pending_reqs)):
            req = self._pending_reqs.popleft()
            if not self._place_request(req):
                self._pending_reqs.append(req)

    # -- the fleet tick ---------------------------------------------------

    def step(self) -> list:
        """One fleet iteration: due restarts → place pending work →
        step every live replica (a step that raises is a replica death:
        migrate + schedule restart) → health sweep.  Returns the
        requests that finished this tick."""
        now = self._clock()
        finished: list[RequestOutput] = []
        # deadline sweep over the FLEET queue: a request parked here
        # (no healthy replica when it arrived) is visible to no
        # engine's sweep, so its TTL must expire here or never
        for _ in range(len(self._pending_reqs)):
            req = self._pending_reqs.popleft()
            d = req.params.deadline_s
            if (d is not None and req.arrival_time is not None
                    and now - req.arrival_time > d):
                rm = RequestMetrics(arrival_time=req.arrival_time)
                rm.finish_time = now
                out = RequestOutput(
                    request_id=req.request_id, prompt=req.prompt,
                    token_ids=[], finish_reason=FinishReason.DEADLINE,
                    metrics=rm,
                    error=f"deadline {d}s exceeded in the fleet queue")
                self.trace.emit("retire", req.request_id,
                                reason="deadline")
                self._finalize(out, "fleet")
                finished.append(out)
            else:
                self._pending_reqs.append(req)
        for name, rep in self.replicas.items():
            if (rep.state is ReplicaState.DEAD
                    and rep.restart_at is not None
                    and now >= rep.restart_at):
                rep.start(now)
                rep.restarts += 1
                self._backoff[name].on_start(now)
                self.trace.emit("replica_state", None, replica=name,
                                state=rep.state.value,
                                life=rep.life)
        self._drain_pending()
        for name, rep in self.replicas.items():
            if rep.state is ReplicaState.DEAD or rep.engine is None:
                continue
            if not rep.engine.has_work():
                rep.last_progress = now  # idle is not a stall
                continue
            try:
                outs = rep.engine.step()
            except (KeyboardInterrupt, SystemExit):
                raise
            except WatchdogTimeout as e:
                # engine-level stall: the dispatch wedged past its
                # budget — the process is as good as gone
                self._on_replica_death(name, f"watchdog: {e}", now)
                continue
            except BaseException as e:  # noqa: BLE001 — InjectedKill /
                # engine-fatal escalations ARE the process-death seam
                self._on_replica_death(
                    name, f"{type(e).__name__}: {e}", now)
                continue
            rep.last_progress = now
            if rep.state is ReplicaState.SUSPECT:
                rep.state = ReplicaState.HEALTHY  # progress: recovered
                self.trace.emit("replica_state", None, replica=name,
                                state=rep.state.value)
            for out in outs:
                self._finalize(out, name)
                finished.append(out)
        # health sweep: probe-driven SUSPECT/DEAD (heartbeat staleness
        # for subprocess drivers; progress age in-process)
        for name, rep in self.replicas.items():
            if rep.state is ReplicaState.DEAD:
                continue
            age = self._probe(rep, now)
            if age > self.dead_after_s:
                self._on_replica_death(name, f"stalled {age:.1f}s", now)
            elif (age > self.suspect_after_s
                  and rep.state is ReplicaState.HEALTHY):
                rep.state = ReplicaState.SUSPECT
                self.trace.emit("replica_state", None, replica=name,
                                state=rep.state.value,
                                age=round(age, 3))
            elif (age <= self.suspect_after_s
                  and rep.state is ReplicaState.SUSPECT):
                # the probe says healthy again (an IDLE suspect replica
                # never re-proves itself through a step, so the sweep
                # must heal too, or it would stay circuit-broken
                # forever)
                rep.state = ReplicaState.HEALTHY
                self.trace.emit("replica_state", None, replica=name,
                                state=rep.state.value)
        self.steps += 1
        return finished

    def has_work(self) -> bool:
        return (bool(self._pending_reqs) or bool(self._pending_recs)
                or any(r.engine is not None and r.engine.has_work()
                       for r in self.replicas.values()))

    def run(self, max_steps: int = 100_000) -> dict:
        """Step until the fleet drains; returns ``dict(outputs)``.
        Raises when no replica is live and none will restart (budget
        exhausted with work pending) — the fleet-level outage."""
        steps = 0
        while self.has_work():
            if not any(r.state is not ReplicaState.DEAD
                       or r.restart_at is not None
                       for r in self.replicas.values()):
                raise RuntimeError(
                    "fleet outage: every replica is dead with its "
                    "restart budget exhausted and work is pending")
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"fleet not drained after {max_steps} steps")
        return dict(self.outputs)

    # -- failure handling + migration -------------------------------------

    def kill_replica(self, name: str, why: str = "killed") -> None:
        """Declare a replica dead NOW (the chaos / ops hook — the
        in-process stand-in for SIGKILL): its in-flight requests
        migrate from the durable journal and a restart is scheduled
        under backoff."""
        self._on_replica_death(name, why, self._clock())

    def drain_replica(self, name: str) -> int:
        """Cooperatively migrate every in-flight request OFF a live
        replica (maintenance drain / rebalance): ``ServeEngine.drain``
        hands off live KV + pending tokens, so RUNNING rows resume
        mid-stream on their new replica with zero recompute.  Returns
        the number of requests moved."""
        rep = self.replicas[name]
        if rep.engine is None:
            raise ValueError(f"replica {name} is dead; crash migration "
                             f"already ran")
        manifest = rep.engine.drain()
        n = len(manifest["requests"])
        self._absorb_manifest(manifest, source=name)
        self._drain_pending(exclude=frozenset((name,)))
        return n

    def _on_replica_death(self, name: str, why: str,
                          now: float) -> None:
        rep = self.replicas[name]
        if rep.state is ReplicaState.DEAD:
            return
        from triton_dist_tpu.serve.recovery import manifest_from_journal

        print(f"[fleet] replica {name} dead ({why}); migrating its "
              f"in-flight requests", file=sys.stderr)
        if rep.engine is not None and rep.engine._journal is not None:
            rep.engine._journal.close()  # single writer for the mark
        life_dir = rep.life_dir
        rep.engine = None  # the process is gone; durable state remains
        rep.state = ReplicaState.DEAD
        rep.death_reason = why
        self.deaths += 1
        self.trace.emit("replica_state", None, replica=name,
                        state=rep.state.value, why=why)
        manifest = manifest_from_journal(life_dir, mark=True)
        # retirements whose outputs the dying step swallowed: the
        # journal's fin records are the accounting of record
        for f in manifest["finished"]:
            if f["rid"] in self.streams and f["rid"] not in self.outputs:
                self._finalize_from_journal(f, name)
        self._absorb_manifest(manifest, source=name)
        self._drain_pending(exclude=frozenset((name,)))
        delay = self._backoff[name].on_death(now)
        if delay is None:
            rep.restart_at = None
            print(f"[fleet] replica {name}: restart budget exhausted; "
                  f"staying dead", file=sys.stderr)
        else:
            rep.restart_at = now + delay

    def _absorb_manifest(self, manifest: dict, source: str) -> None:
        """Fold a migration manifest into fleet accounting: fill each
        stream's delivery record from the journal segment (tokens the
        source journaled but never delivered — the commit→callback
        crash window — redeliver HERE, exactly the missing indices),
        then queue the records for placement."""
        header = {k: manifest[k] for k in
                  ("format", "clock", "page_size", "kv_geom")
                  if k in manifest}
        for rec in manifest.get("requests", ()):
            rid = rec["rid"]
            if rid not in self.streams:
                continue  # not fleet traffic (foreign journal entry)
            toks = rec.get("tokens", [])
            d = len(self.streams[rid])
            assert d <= len(toks), (
                f"{rid}: delivered {d} tokens but the journal only "
                f"holds {len(toks)} — the journal-precedes-callback "
                f"invariant broke")
            self.streams[rid].extend(int(t) for t in toks[d:])
            self.placement.pop(rid, None)
            self._pending_recs.append((header, rec))

    def _finalize(self, out: RequestOutput, name: str) -> None:
        rid = out.request_id
        self.outputs[rid] = out
        s = self.streams.get(rid)
        if s is not None and len(s) < len(out.token_ids):
            # a disabled/raising user callback starves the delivery
            # record; the retirement's authoritative token list
            # reconciles it
            s.extend(out.token_ids[len(s):])
        self.placement.pop(rid, None)

    def _finalize_from_journal(self, f: dict, name: str) -> None:
        rm = RequestMetrics(arrival_time=self._clock())
        out = RequestOutput(
            request_id=f["rid"],
            prompt=np.asarray(f.get("prompt", []), np.int32),
            token_ids=[int(t) for t in f["tokens"]],
            finish_reason=FinishReason(f["reason"]),
            metrics=rm, error=f.get("err"))
        self._finalize(out, name)

    # -- observability ----------------------------------------------------

    def fleet_summary(self) -> dict:
        """One dict of fleet state: per-replica health/lives/load plus
        the routing + migration counters (the fleet twin of
        ``ServeMetrics.summary``)."""
        reps = {}
        for name, rep in self.replicas.items():
            r = {
                "state": rep.state.value,
                "life": rep.life,
                "restarts": rep.restarts,
                "death_reason": rep.death_reason,
            }
            if rep.engine is not None:
                load = rep.load()
                r.update(queue_depth=load.queue_depth,
                         running=load.running,
                         kv_util=round(load.kv_util, 4),
                         completed=rep.engine.metrics.completed,
                         migrated_in=rep.engine.metrics.migrated_in,
                         migrated_out=rep.engine.metrics.migrated_out)
            reps[name] = r
        return {
            "replicas": reps,
            "steps": self.steps,
            "deaths": self.deaths,
            "migrations": self.migrations,
            "completed": len(self.outputs),
            "pending": len(self._pending_reqs) + len(self._pending_recs),
        }
