"""Fleet serving: a multi-replica router with live request migration.

PRs 1-8 made ONE engine fast, observable, and crash-resilient — but the
stack still served from exactly one process, so one wedged replica was a
full outage.  This module runs N engine replicas behind an admission
router and makes the PR 5 journal + snapshot + ``BlockManager.adopt``
machinery do what it always was underneath: a *migration* primitive
(the Llumnix live-migration / MegaScale fast-hand-off insight — the TPU
analog of the reference's producer/consumer signal-and-put hand-off,
SURVEY.md §2.5).

Three cooperating layers:

- :class:`Router` — admission placement by queue-depth / deadline
  pressure read from each replica's ``ServeMetrics`` (direct engine
  state in-process; :func:`parse_prometheus` over a ``/metrics`` scrape
  for subprocess replicas — ``scripts/serve_supervisor.py --fleet``).
  SUSPECT and DEAD replicas are circuit-broken out of the candidate
  set, so the router can never place onto a replica that stopped
  making progress.

- **Health state machine** — per replica HEALTHY → SUSPECT → DEAD,
  layered on the existing liveness signals (heartbeat staleness,
  step-progress age, a ``WatchdogTimeout`` or process-death exception
  escaping ``step``).  A SUSPECT replica stops receiving admissions and
  recovers to HEALTHY the moment progress resumes; a DEAD one is killed
  and restarted under :class:`RestartBackoff` (exponential + jitter,
  healthy-uptime budget reset — shared with the supervisor).

- **Live migration** — a dying replica's in-flight requests move to
  healthy peers and finish there.  Cooperative path:
  ``ServeEngine.drain(rids)`` gathers live KV pages + the pending token
  and the target's ``migrate_in`` adopts the row MID-STREAM (zero
  recompute).  Crash path: the dead replica's durable token journal is
  the source of truth — :func:`serve.recovery.manifest_from_journal`
  rebuilds the journal segment and the target replays the remainder
  through the exact-recompute path, bit-identical by the PR 5
  argument.  Either way the source journal records a ``mig`` receipt
  per request, so the union of all replicas' journals holds every
  token of every stream EXACTLY ONCE (the fleet chaos harness in
  tests/test_serve_fleet.py pins this: kill a replica mid-decode under
  load — every stream finishes bit-identical to the single-engine
  oracle, zero lost, zero duplicated).

See docs/serving.md "Fleet serving" for the operator recipe.
"""

from __future__ import annotations

import enum
import glob
import math
import os
import random
import sys
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from triton_dist_tpu.runtime.faults import CORRUPT_ACTIONS
from triton_dist_tpu.runtime.watchdog import WatchdogTimeout
from triton_dist_tpu.serve.metrics import (
    RequestMetrics,
    ServeMetrics,
    WindowedRate,
)
from triton_dist_tpu.serve.net import (
    ManifestCorrupt,
    NetClient,
    NetError,
    NetHTTPError,
    NetOverloaded,
    NetUnreachable,
    corrupt_wire_doc,
    decode_manifest,
    encode_manifest,
)
from triton_dist_tpu.serve.request import (
    SLO_CLASSES,
    FinishReason,
    Request,
    RequestOutput,
    slo_rank,
)
from triton_dist_tpu.serve.trace import (
    FLEET_PID,
    FLEET_REPLICA_PID_BASE,
    FlightRecorder,
    LogHistogram,
    events_to_perfetto,
    latest_flight,
    link_migration_flows,
    load_flight,
    write_trace,
)


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"   # serving; admissible by the router
    SUSPECT = "suspect"   # progress stalled past suspect_after_s:
    #                       circuit-broken (no admissions), not yet dead
    DEAD = "dead"         # killed or crashed; restarting under backoff


# ---------------------------------------------------------------------------
# Restart backoff (shared by the FleetController and serve_supervisor)
# ---------------------------------------------------------------------------


class RestartBackoff:
    """Exponential restart backoff with jitter and a healthy-uptime
    budget reset.

    A crash-looping child used to restart instantly and burn its whole
    ``max_restarts`` budget in seconds; this paces restarts at
    ``base_s * 2^(attempt-1)`` capped at ``cap_s``, jittered by up to
    ``jitter`` of the delay (deterministic under ``seed`` — restarts
    across a fleet must not synchronize), and FORGIVES the attempt
    count once a life stays up ``healthy_reset_s`` (a process that ran
    healthy for an hour and then died is a fresh incident, not attempt
    #4 of a crash loop).

    Protocol: :meth:`on_start` when the process launches,
    :meth:`on_death` when it dies — returns the delay to wait before
    the next restart, or ``None`` when ``max_restarts`` is exhausted.
    """

    def __init__(self, *, base_s: float = 0.5, cap_s: float = 30.0,
                 jitter: float = 0.5, healthy_reset_s: float = 60.0,
                 max_restarts: Optional[int] = None, seed: int = 0):
        if base_s <= 0 or cap_s < base_s:
            raise ValueError(f"need 0 < base_s <= cap_s, got "
                             f"{base_s}, {cap_s}")
        if not 0 <= jitter <= 1:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.base_s = base_s
        self.cap_s = cap_s
        self.jitter = jitter
        self.healthy_reset_s = healthy_reset_s
        self.max_restarts = max_restarts
        self.attempts = 0
        self._rng = random.Random(seed)
        self._started: Optional[float] = None

    def on_start(self, now: float) -> None:
        self._started = now

    def on_death(self, now: float) -> Optional[float]:
        """Delay before the next restart, or ``None`` (budget spent)."""
        if (self._started is not None
                and now - self._started >= self.healthy_reset_s):
            self.attempts = 0
        self.attempts += 1
        if (self.max_restarts is not None
                and self.attempts > self.max_restarts):
            return None
        d = min(self.cap_s, self.base_s * 2.0 ** (self.attempts - 1))
        return d * (1.0 + self.jitter * self._rng.random())


# ---------------------------------------------------------------------------
# Router decision audit: "why did this request land there / why did it
# move", answerable post-hoc
# ---------------------------------------------------------------------------


class DecisionAudit:
    """Bounded ring of fleet control decisions (docs/observability.md
    "Fleet observability").

    The flight recorder answers *what happened*; this ring answers *why
    the router did it*: every ``route``/``migrate`` placement records
    the candidate pressures it weighed and the replica it chose, every
    ``shed`` the reason, every ``replica_state``/``restart`` the health
    evidence.  Entries are small dicts ``{"ts", "step", "kind", "rid",
    ...}`` in a ``deque(maxlen=capacity)`` — same hot-path discipline as
    the recorder (append only, no I/O) and the same bounded-memory
    contract.  The ring rides the fleet's postmortem flight flush, so a
    supervisor reading the crash file sees the routing history that led
    up to it."""

    def __init__(self, capacity: int = 1024, enabled: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._ring: deque = deque(maxlen=capacity)
        self.enabled = enabled
        self.recorded = 0

    def record(self, ts: float, step: int, kind: str,
               rid: Optional[str] = None, **data) -> None:
        if not self.enabled:
            return
        self.recorded += 1
        self._ring.append({"ts": ts, "step": step, "kind": kind,
                           "rid": rid, **data})

    @property
    def dropped(self) -> int:
        return self.recorded - len(self._ring)

    def entries(self) -> list[dict]:
        return list(self._ring)

    def for_request(self, rid: str) -> list[dict]:
        """Every decision that touched ``rid`` still in the ring — the
        post-hoc "why is my request on r2" query
        (``FleetController.explain``)."""
        return [e for e in self._ring if e.get("rid") == rid]


#: Controller-level Prometheus series ``FleetController.to_prometheus``
#: emits ON TOP of the aggregated per-engine ``serve_*`` series.  Every
#: name here must appear in docs/observability.md — enforced by the
#: tier-1 fleet taxonomy meta-test (tests/test_serve_fleet.py), the
#: fleet twin of the PR-8 event/fault coverage test.
FLEET_SERIES = (
    "fleet_replicas",              # gauge, {state=...}: replica counts
    "fleet_replica_state",         # gauge, {replica=,state=}: one-hot
    #                                per-replica health (alerting sees
    #                                WHICH breaker is open, not just a
    #                                count)
    "fleet_replica_role",          # gauge, {replica=,role=}: one-hot
    #                                routing role (prefill/decode/both —
    #                                the disagg tier's shape, constant
    #                                "both" for homogeneous fleets)
    "fleet_lives_total",           # counter: replica lives ever started
    "fleet_deaths_total",          # counter: replica deaths
    "fleet_migrations_total",      # counter: requests moved between replicas
    "fleet_completed_total",       # counter: requests retired fleet-wide
    "fleet_steps_total",           # counter: fleet ticks
    "fleet_pending",               # gauge: unplaced work (fleet queue)
    "fleet_deadline_miss_window",  # gauge: deadline misses in the SLO window
    "fleet_shed_window",           # gauge: sheds in the SLO window
    "fleet_deadline_miss_per_s",   # gauge: deadline-miss burn rate
    "fleet_shed_per_s",            # gauge: shed burn rate
    "fleet_audit_records_total",   # counter: router decisions recorded
    "fleet_pressure_smoothed",     # gauge: the autoscaler's EMA pressure
    #                                signal (what the high/low water
    #                                marks compare against)
    "fleet_scale_ups_total",       # counter: replicas spawned by the
    #                                autoscaler
    "fleet_scale_downs_total",     # counter: replicas retired (drained)
    #                                by the autoscaler
    "fleet_ingress_shed_total",    # counter, {slo_class=}: requests the
    #                                token-bucket admission refused at
    #                                the door
)


# ---------------------------------------------------------------------------
# Router: queue-depth / deadline pressure placement
# ---------------------------------------------------------------------------


def parse_prometheus(text: str) -> dict:
    """Parse a Prometheus text exposition into ``{series: value}`` —
    the scrape half of the router's load signal for SUBPROCESS replicas
    (``ServeMetrics.to_prometheus`` is the other end; labeled series
    keep their full left-hand side as the key)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(None, 1)
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


@dataclass
class ReplicaLoad:
    """One replica's admission-pressure signal, however it was read
    (direct engine state in-process, Prometheus scrape out-of-process)."""

    queue_depth: int = 0
    running: int = 0
    max_batch: int = 1
    kv_util: float = 0.0

    @classmethod
    def from_engine(cls, engine) -> "ReplicaLoad":
        return cls(queue_depth=engine.scheduler.queue_depth,
                   running=sum(1 for s in engine.slots if s is not None),
                   max_batch=engine.max_batch,
                   kv_util=engine.bm.utilization)

    @classmethod
    def from_prometheus(cls, text: str,
                        max_batch: int = 1) -> "ReplicaLoad":
        """Load from a ``/metrics`` scrape (the subprocess path —
        docs/observability.md lists the series names)."""
        g = parse_prometheus(text)
        return cls(queue_depth=int(g.get("serve_queue_depth", 0)),
                   running=int(g.get("serve_running", 0)),
                   max_batch=max_batch,
                   kv_util=float(g.get("serve_kv_utilization", 0.0)))


def replica_state_lines(named_states) -> list[str]:
    """The ``fleet_replica_state{replica=,state=}`` one-hot exposition
    (docs/observability.md "Fleet observability") from ``[(name,
    ReplicaState), ...]`` — ONE renderer shared by
    ``FleetController.to_prometheus`` and the supervisor's subprocess
    aggregate, so the two expositions cannot drift.  The full 0/1
    matrix (not just the current state) keeps a PromQL
    ``max by (replica)`` well-defined across flips."""
    L = ["# TYPE fleet_replica_state gauge"]
    for name, state in named_states:
        for st in ReplicaState:
            L.append(f'fleet_replica_state{{replica="{name}",'
                     f'state="{st.value}"}} '
                     f'{1 if state is st else 0}')
    return L


#: Routing roles a replica can hold in a disaggregated tier
#: (docs/serving.md "Disaggregated serving").  A role is routing
#: POLICY, not capability — every replica can compute anything, so
#: availability fallbacks may cross role lines.
REPLICA_ROLES = ("prefill", "decode", "both")


def replica_role_lines(named_roles) -> list[str]:
    """The ``fleet_replica_role{replica=,role=}`` one-hot exposition
    from ``[(name, role), ...]`` — same full-matrix rendering rule as
    :func:`replica_state_lines` (a PromQL ``max by (replica)`` stays
    well-defined if roles ever flip)."""
    L = ["# TYPE fleet_replica_role gauge"]
    for name, role in named_roles:
        for r in REPLICA_ROLES:
            L.append(f'fleet_replica_role{{replica="{name}",'
                     f'role="{r}"}} {1 if role == r else 0}')
    return L


class Router:
    """Least-pressure admission placement over HEALTHY replicas.

    Pressure is ``queue_weight * queue_depth + running / max_batch +
    kv_weight * kv_util`` — queued requests dominate (one queued
    request outweighs even a fully occupied batch: it is a whole
    request of delay ahead, where a running batch is already making
    progress), batch occupancy and KV pressure break the near-ties.  A
    deadline-carrying request weighs queue depth
    ``deadline_queue_weight``× harder: its TTL burns while it waits, so
    it must land on the emptiest queue even when occupancy says
    otherwise.  Exact pressure ties rotate round-robin so a cold fleet
    does not pile onto one replica."""

    def __init__(self, *, queue_weight: float = 2.0,
                 kv_weight: float = 0.5,
                 deadline_queue_weight: float = 4.0):
        self.queue_weight = queue_weight
        self.kv_weight = kv_weight
        self.deadline_queue_weight = deadline_queue_weight
        self._rr = 0

    def pressure(self, load: ReplicaLoad, *,
                 deadline: bool = False) -> float:
        qw = self.deadline_queue_weight if deadline else self.queue_weight
        return (qw * load.queue_depth
                + load.running / max(load.max_batch, 1)
                + self.kv_weight * load.kv_util)

    def rank(self, candidates: list, *, deadline: bool = False) -> list:
        """``[(name, load)]`` sorted best-first (the migration placer
        walks this to find capacity)."""
        n = max(len(candidates), 1)
        self._rr += 1
        scored = sorted(
            (self.pressure(load, deadline=deadline),
             (i + self._rr) % n, name)
            for i, (name, load) in enumerate(candidates))
        return [name for _, _, name in scored]

    def pick(self, candidates: list, *,
             deadline: bool = False) -> Optional[str]:
        """Best HEALTHY replica for one new request, or ``None``."""
        ranked = self.rank(candidates, deadline=deadline)
        return ranked[0] if ranked else None


# ---------------------------------------------------------------------------
# In-process replica
# ---------------------------------------------------------------------------


class EngineReplica:
    """One in-process engine replica under the :class:`FleetController`.

    Each LIFE gets its own snapshot directory (``root/life<N>``): the
    life's journal is its durable request ownership record, so a crash
    migrates from the dead life's journal and the restart opens a fresh
    one — nothing a previous life owned can leak into the next (the
    handed-off requests carry ``mig`` receipts besides; belt and
    suspenders)."""

    def __init__(self, name: str, factory: Callable, root: str):
        self.name = name
        self._factory = factory
        self.root = root
        # routing role (REPLICA_ROLES) — "both" keeps homogeneous
        # fleets exactly as before; DisaggController splits the tier
        self.role = "both"
        self.engine = None
        self.life = 0
        self.state = ReplicaState.DEAD
        self.last_progress: Optional[float] = None
        self.restart_at: Optional[float] = None
        self.restarts = 0          # lives after the first
        self.death_reason: Optional[str] = None

    @property
    def life_dir(self) -> str:
        return os.path.join(self.root, f"life{self.life}")

    def start(self, now: float) -> None:
        self.life += 1
        os.makedirs(self.life_dir, exist_ok=True)
        self.engine = self._factory(self.life_dir)
        if self.engine._journal is None:
            raise ValueError(
                f"replica {self.name}: the factory must build engines "
                f"with snapshot_dir=<life dir> — the journal is what "
                f"crash migration hands off")
        self.state = ReplicaState.HEALTHY
        self.last_progress = now
        self.restart_at = None
        self.death_reason = None

    def load(self) -> ReplicaLoad:
        if hasattr(self.engine, "load"):   # RemoteReplica carries its
            return self.engine.load()      # own scrape-fed snapshot
        return ReplicaLoad.from_engine(self.engine)


# ---------------------------------------------------------------------------
# Remote replica: the engine protocol over the wire (serve/net.py)
# ---------------------------------------------------------------------------


def _manifest_header(manifest: dict) -> dict:
    """The placement-relevant manifest envelope (everything but the
    per-request records) — ONE extraction for every site that re-parks
    or re-places a rec, so a new header key cannot be silently
    stripped at one of them."""
    return {k: manifest[k] for k in
            ("format", "clock", "page_size", "kv_geom")
            if k in manifest}


class _RemoteKill:
    """``RemoteReplica._journal``: for a remote replica, "closing the
    journal" means making sure the remote WRITER is gone — the
    controller closes it right before the crash-path
    ``manifest_from_journal(mark=True)``, which must be the single
    writer on the dead life's journal.  ``kill`` is the SIGKILL hook
    the spawning factory provides (a subprocess's ``proc.kill()``; an
    :class:`serve.net.InProcessReplica`'s ``kill()``)."""

    def __init__(self, kill: Optional[Callable]):
        self._kill = kill

    def close(self) -> None:
        if self._kill is not None:
            self._kill()


class RemoteReplica:
    """A replica process over the wire, speaking the SAME protocol the
    :class:`FleetController` speaks to in-process engines — submit /
    step / drain / migrate_in / has_work / load — so a fleet of
    subprocesses drives through the identical controller code path
    (docs/serving.md "Network fleet serving").

    Fault tolerance is the client's half of the contract:

    - every call has a per-call timeout and bounded retries under
      jittered exponential backoff (:class:`serve.net.NetClient` on
      :class:`RestartBackoff`); each retry lands a ``net_retry`` event
      in this replica's ring and a ``net_retry`` entry in the fleet's
      :class:`DecisionAudit` (``attach_fleet``);
    - retries are IDEMPOTENT by protocol: submits key on the rid,
      drains/migrations on a client-generated idempotency key the
      server replays from its response cache — a retry whose first
      attempt landed is a no-op, never a duplicate stream;
    - a call that fails EVERY retry is ambiguous — it may have landed.
      The request stays optimistically BOUND to this replica
      (``_maybe``): the next successful contact re-sends it
      (idempotent, so landing twice is impossible), and if the replica
      instead dies, :meth:`unplaced` hands back exactly the ones the
      dead journal does not cover — the journal is the ownership
      record, so nothing is ever served from two replicas;
    - :meth:`step` raising :class:`~serve.net.NetUnreachable` (or
      :meth:`ping` returning ``False`` while idle) is NOT a death: the
      controller records no progress and the probe age walks the
      HEALTHY→SUSPECT→DEAD ladder — a partition is handled by the same
      machinery as a SIGKILL, just ``dead_after_s`` later.
    """

    def __init__(self, name: str, url: str, *,
                 kill: Optional[Callable] = None,
                 timeout_s: float = 5.0, retries: int = 2,
                 retry_base_s: float = 0.05, retry_cap_s: float = 2.0,
                 ping_interval_s: float = 0.2,
                 faults=None, trace_events: int = 512,
                 trace_level: int = 1, seed: int = 0):
        self.name = name
        self.url = url
        self.timeout_s = timeout_s
        self.trace = FlightRecorder(capacity=trace_events,
                                    level=trace_level)
        self.audit: Optional[DecisionAudit] = None
        self.client = NetClient(url, name=name, timeout_s=timeout_s,
                                retries=retries,
                                retry_base_s=retry_base_s,
                                retry_cap_s=retry_cap_s, seed=seed,
                                faults=faults,
                                on_retry=self._on_retry)
        self.metrics = ServeMetrics()   # client-side stub: the fleet
        #                                 aggregate for subprocesses is
        #                                 the scrape path (merge_scrapes)
        self._journal = _RemoteKill(kill)
        self.max_queue: Optional[int] = None
        self.last_contact: Optional[float] = None
        self.ping_interval_s = ping_interval_s
        self._last_ping: Optional[tuple] = None   # (mono_ts, ok)
        self._load = ReplicaLoad()
        self._live: dict[str, dict] = {}
        self._maybe_reqs: dict[str, dict] = {}
        self._maybe_migs: list[dict] = []
        self._bounced: list[tuple] = []   # (header, rec) to re-place
        self._drains = 0
        self._migs = 0
        self._pushes = 0
        # prefill-complete rids the remote engine reported on its last
        # health answer — the disagg controller's PUSH trigger
        self._push_ready: list[str] = []

    def attach_fleet(self, audit: DecisionAudit) -> None:
        """Wire this client's retry reporting into the fleet's decision
        audit (the controller calls it after every ``start``)."""
        self.audit = audit

    def _on_retry(self, op: str, attempt: int, delay: float,
                  err: str) -> None:
        self.trace.emit("net_retry", None, replica=self.name, op=op,
                        attempt=attempt, delay_s=round(delay, 4),
                        err=err)
        if self.audit is not None:
            self.audit.record(time.monotonic(), -1, "net_retry",
                              replica=self.name, op=op, attempt=attempt,
                              delay_s=round(delay, 4))

    # -- liveness / load ---------------------------------------------------

    def _absorb_health(self, h: dict) -> bool:
        from triton_dist_tpu.serve.net import NET_PROTOCOL

        p = h.get("protocol", NET_PROTOCOL)
        if p != NET_PROTOCOL:
            # fail LOUD, not quietly-unhealthy: a wire-version mismatch
            # is an operator error (stale replica binary), and treating
            # it as a partition would just burn the restart budget.
            # Plain RuntimeError deliberately — NetError handlers must
            # not swallow it.
            raise RuntimeError(
                f"replica {self.name} speaks net protocol {p}; this "
                f"client speaks {NET_PROTOCOL} — mismatched builds")
        if not h.get("ok"):
            return False
        self.last_contact = time.monotonic()
        self.max_queue = h.get("max_queue")
        self._load = ReplicaLoad(
            queue_depth=int(h.get("queue_depth", 0)),
            running=int(h.get("running", 0)),
            max_batch=int(h.get("max_batch", 1)),
            kv_util=float(h.get("kv_util", 0.0)))
        self._push_ready = [str(r) for r in h.get("push_ready", ())]
        return True

    def ping(self, force: bool = False) -> bool:
        """One health probe — a SINGLE short-timeout attempt, no retry
        ladder, throttled to ``ping_interval_s`` (the health ladder's
        granularity is ``suspect_after_s``, so the controller's
        per-tick idle pings need no finer resolution and a blackholed
        replica must not cost the single-threaded loop a timeout on
        EVERY tick).  ``False`` means unreachable OR the remote serve
        loop stopped pumping — either way, no progress to prove."""
        now = time.monotonic()
        if (not force and self._last_ping is not None
                and now - self._last_ping[0] < self.ping_interval_s):
            return self._last_ping[1]
        try:
            h = self.client.call("health", "/health", retries=0,
                                 timeout_s=min(self.timeout_s, 1.0))
            ok = self._absorb_health(h)
        except NetError:
            ok = False
        self._last_ping = (time.monotonic(), ok)
        return ok

    def wait_ready(self, deadline_s: float = 60.0,
                   poll_s: float = 0.1) -> "RemoteReplica":
        """Block until the replica answers /health (spawning factories
        call this so the controller never adopts a half-started child);
        raises :class:`NetError` past the bounded deadline."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline_s:
            if self.ping():
                return self
            time.sleep(poll_s)
        raise NetError(f"replica {self.name} at {self.url} not ready "
                       f"within {deadline_s}s")

    def load(self) -> ReplicaLoad:
        return self._load

    # -- the engine protocol ----------------------------------------------

    def submit(self, req: Request):
        from triton_dist_tpu.serve.engine import QueueFull

        rid = req.request_id
        doc = {"rid": rid,
               "prompt": [int(x) for x in np.asarray(req.prompt)],
               "params": req.params.to_dict(), "slo": req.slo_class,
               "trace": req.trace}
        self._live[rid] = {"acked": 0, "tokens": [], "cb": req.on_token,
                           "done": False,
                           "prompt": np.asarray(req.prompt, np.int32),
                           "req": req}
        try:
            resp = self.client.call("submit", "/submit", method="POST",
                                    body=doc)
        except NetOverloaded as e:
            # the replica answered 429 on every paced retry: admission
            # pressure is a DEFINITIVE verdict (never ambiguous — the
            # rid-keyed replay cache would have answered dup had any
            # attempt landed), and the fleet word for a full queue is
            # QueueFull: the controller walks to the next candidate or
            # sheds under the bounded-admission contract
            del self._live[rid]
            raise QueueFull(f"{self.name}: {e}") from e
        except NetHTTPError as e:
            # the replica ANSWERED with an error: definitive, not
            # ambiguous — same behavior as an in-process engine
            # raising at submit()
            del self._live[rid]
            raise ValueError(
                f"replica {self.name} rejected submit: {e}") from e
        except NetError:
            # ambiguous: it may have landed.  Bind it here (optimistic)
            # — reconciliation re-sends idempotently on the next
            # successful contact, and death resolves through the
            # journal (unplaced()).  Placing it elsewhere NOW could
            # serve one stream from two replicas.
            self._maybe_reqs[rid] = doc
            return None
        if resp.get("queue_full"):
            del self._live[rid]
            raise QueueFull(resp.get("why",
                                     f"{self.name}: queue at bound"))
        if resp.get("rejected"):
            del self._live[rid]
            raise ValueError(f"replica {self.name} rejected submit: "
                             f"{resp.get('why')}")
        if resp.get("shed"):
            del self._live[rid]
            rm = RequestMetrics(arrival_time=time.monotonic())
            rm.finish_time = rm.arrival_time
            return RequestOutput(
                request_id=rid, prompt=req.prompt, token_ids=[],
                finish_reason=FinishReason(resp["reason"]), metrics=rm,
                error=resp.get("error"))
        return None

    def migrate_in(self, manifest: dict, *, on_token=None) -> dict:
        self._migs += 1
        return self._send_manifest(manifest, on_token, op="migrate_in",
                                   key=f"{self.name}-mig-{self._migs}")

    def admit_pushed(self, manifest: dict, *, on_token=None) -> dict:
        """Adopt a disagg PUSH hand-off over the wire (``POST /push``
        — the engine-side ``admit_pushed``).  Same retry / idempotency
        / ambiguity discipline as :meth:`migrate_in`, under its own key
        namespace and server cache kind."""
        self._pushes += 1
        return self._send_manifest(manifest, on_token, op="push",
                                   key=f"{self.name}-push-{self._pushes}")

    def _send_manifest(self, manifest: dict, on_token, *, op: str,
                       key: str) -> dict:
        from triton_dist_tpu.serve.recovery import _resolve_callback

        enc = encode_manifest(manifest)
        # The integrity fault point's wire-blob site: damage a COPY of
        # the encoded doc in flight (the clean ``enc`` is what a later
        # ambiguous-call reconcile re-sends — transport rot must not
        # become persistent sender state).  The receiver's digest check
        # rejects with 400 → the rejected-path fallback below.
        wire = enc
        faults = getattr(self.client, "faults", None)
        if faults is not None:
            rids_hint = [rec.get("rid")
                         for rec in manifest.get("requests", ())]
            act = faults.fire("integrity", op=op,
                              rid=rids_hint[0] if rids_hint else None)
            if act in CORRUPT_ACTIONS:
                wire = corrupt_wire_doc(enc, act)
        rids = [rec["rid"] for rec in manifest.get("requests", ())]
        for rec in manifest.get("requests", ()):
            rid = rec["rid"]
            toks = [int(t) for t in rec.get("tokens", [])]
            self._live[rid] = {
                "acked": len(toks), "tokens": toks,
                "cb": _resolve_callback(on_token, rid), "done": False,
                "prompt": np.asarray(rec.get("prompt", []), np.int32),
                "req": None}
        try:
            resp = self.client.call(
                op, f"/{op}", method="POST",
                body={"manifest": wire, "key": key},
                timeout_s=max(self.timeout_s, 30.0))
        except NetHTTPError as e:
            # answered-with-error is definitive: nothing was adopted —
            # report every rec rejected so the placer walks on
            for rid in rids:
                self._live.pop(rid, None)
            return {"adopted": [], "requeued": [],
                    "rejected": {rid: str(e) for rid in rids}}
        except NetError:
            # ambiguous — bound here until reconciled or resolved by
            # the journal at death (same argument as submit)
            self._maybe_migs.append({"enc": enc, "key": key,
                                     "manifest": manifest, "op": op})
            return {"adopted": [], "requeued": rids, "rejected": {}}
        for rid in resp.get("rejected", {}):
            self._live.pop(rid, None)
        return {"adopted": resp.get("adopted", []),
                "requeued": resp.get("requeued", []),
                "rejected": resp.get("rejected", {})}

    def drain(self, rids: Optional[list] = None, *,
              include_kv: bool = True, push: bool = False) -> dict:
        """Cooperative migrate-out over the wire.  The idempotency key
        makes a retried drain return the CACHED manifest — the engine
        drains once however flaky the ack path was.  Raises
        :class:`NetError` when the replica is unreachable (a
        cooperative drain needs a live peer; the crash path is the
        journal).

        The key advances only on SUCCESS: a drain that raised may have
        LANDED (receipts written, state released, manifest cached) —
        the next :meth:`drain` call re-uses the outstanding key, so it
        recovers exactly that manifest instead of asking a drained
        engine for its (now empty) in-flight set and stranding the
        handed-off streams.  (The server keeps the cached response for
        ``cache_ttl_s`` — retry within it; past that, a dead replica's
        journal still has the receipts but the cooperative manifest is
        gone.)"""
        key = f"{self.name}-drain-{self._drains + 1}"
        faults = getattr(self.client, "faults", None)
        # A drain-RESPONSE corrupted in flight is recoverable without
        # re-draining: the server cached the clean manifest under this
        # key (the engine drained once), so a bounded retry with the
        # SAME key replays it.  Corruption that survives the retries is
        # a dead transport for state-bearing purposes: raise NetError so
        # the controller walks the death ladder and recovers from the
        # journal instead of adopting rot.
        last: Optional[ManifestCorrupt] = None
        for _ in range(3):
            resp = self.client.call(
                "drain", "/drain", method="POST",
                body={"rids": rids, "key": key, "include_kv": include_kv,
                      "push": push},
                timeout_s=max(self.timeout_s, 30.0))
            doc = resp["manifest"]
            if faults is not None:   # wire-blob site, receive direction
                act = faults.fire("integrity", op="drain")
                if act in CORRUPT_ACTIONS:
                    doc = corrupt_wire_doc(doc, act)
            try:
                m = decode_manifest(doc)
            except ManifestCorrupt as e:
                last = e
                continue
            self._drains += 1
            for rec in m.get("requests", ()):
                self._live.pop(rec["rid"], None)
            return m
        raise NetError(
            f"drain manifest from {self.name} corrupt after retries "
            f"({last}) — treating the replica as unrecoverable over "
            f"the wire; the journal crash path has the receipts")

    def push_ready(self) -> list[str]:
        """Prefill-complete rids from the last health answer — the
        remote twin of ``ServeEngine.push_ready`` (stale by at most one
        poll interval; the push itself re-validates via the drain)."""
        return list(self._push_ready)

    def push_out(self, rid: str) -> dict:
        """Extract ``rid``'s PUSH hand-off manifest (a single-request
        drain framed as ``push_out`` — ``/drain`` with ``push=true``).
        Raises :class:`NetError` when the replica is unreachable; the
        drain key replays a landed-but-unacked attempt."""
        return self.drain([rid], push=True)

    def has_work(self) -> bool:
        return (any(not s["done"] for s in self._live.values())
                or bool(self._maybe_reqs) or bool(self._maybe_migs))

    def _reconcile(self) -> None:
        """Re-send every ambiguous call on a proven-reachable replica.
        Idempotent by protocol: a maybe that landed answers ``dup`` /
        the cached response; one that never arrived lands now."""
        for rid, doc in list(self._maybe_reqs.items()):
            try:
                resp = self.client.call("submit", "/submit",
                                        method="POST", body=doc)
            except NetHTTPError:
                # answered-with-error: definitively not here — hand it
                # back for re-placement (a genuinely invalid request
                # then fails at its next placement exactly like an
                # in-process submit would)
                s = self._live.pop(rid, None)
                del self._maybe_reqs[rid]
                if s is not None and s.get("req") is not None:
                    self._bounced.append(("req", s["req"]))
                continue
            except NetError:
                return
            if resp.get("rejected"):
                s = self._live.pop(rid, None)
                del self._maybe_reqs[rid]
                if s is not None and s.get("req") is not None:
                    self._bounced.append(("req", s["req"]))
                continue
            if resp.get("queue_full"):
                # the replica ANSWERED queue_full, so the ambiguity is
                # resolved: the request is definitively NOT here (a
                # landed first attempt would have answered dup).  Hand
                # it back for fleet re-placement — pinning it to a
                # persistently-full replica would starve it while
                # others sit idle.
                s = self._live.pop(rid, None)
                del self._maybe_reqs[rid]
                if s is not None and s.get("req") is not None:
                    self._bounced.append(("req", s["req"]))
                continue
            del self._maybe_reqs[rid]
        for m in list(self._maybe_migs):
            op = m.get("op", "migrate_in")
            try:
                resp = self.client.call(
                    op, f"/{op}", method="POST",
                    body={"manifest": m["enc"], "key": m["key"]},
                    timeout_s=max(self.timeout_s, 30.0))
            except NetHTTPError:
                # definitive: nothing adopted — bounce every rec back
                # to the controller for re-placement elsewhere
                self._maybe_migs.remove(m)
                hdr = _manifest_header(m["manifest"])
                for rec in m["manifest"].get("requests", ()):
                    self._live.pop(rec["rid"], None)
                    self._bounced.append(("rec", hdr, rec))
                continue
            except NetError:
                return
            self._maybe_migs.remove(m)
            header = _manifest_header(m["manifest"])
            for rid, why in resp.get("rejected", {}).items():
                if "duplicate" in str(why):
                    continue   # the first attempt landed: a no-op
                # genuine capacity rejection — hand the rec back to the
                # controller for re-placement elsewhere
                self._live.pop(rid, None)
                for rec in m["manifest"].get("requests", ()):
                    if rec["rid"] == rid:
                        self._bounced.append(("rec", header, rec))

    def take_bounced(self) -> list:
        """Work the replica definitively rejected after an ambiguous
        window (``("req", Request)`` fresh submits, ``("rec", header,
        rec)`` migration records) — the controller drains this each
        tick and re-places them."""
        out, self._bounced = self._bounced, []
        return out

    def step(self) -> list:
        """One controller tick against this replica: prove liveness,
        reconcile ambiguous calls, poll every live stream since its
        acknowledged index, deliver the new tokens, and return the
        retirements.  ONE round trip when there is work — /poll's
        response carries the health/load snapshot, so a separate ping
        is only paid when there is nothing to poll.  Raises
        :class:`~serve.net.NetUnreachable` when the replica answers
        nothing — the controller counts that as missing progress, not
        death."""
        polls = {rid: s["acked"] for rid, s in self._live.items()
                 if not s["done"] and rid not in self._maybe_reqs}
        outs: list[RequestOutput] = []
        if not polls:
            if not self.ping():
                raise NetUnreachable(
                    f"replica {self.name} at {self.url}: "
                    f"no health answer")
            self._reconcile()
            return outs
        try:
            resp = self.client.call("poll", "/poll", method="POST",
                                    body={"streams": polls})
        except NetError as e:
            raise NetUnreachable(str(e)) from e
        if not self._absorb_health(resp.get("health", {"ok": True})):
            # answered, but the serve loop behind it stopped pumping:
            # tokens (if any) are still real, progress is not proven
            raise NetUnreachable(
                f"replica {self.name} at {self.url}: serve loop "
                f"not pumping")
        self._reconcile()
        now = time.monotonic()
        for rid, st in resp.get("streams", {}).items():
            s = self._live.get(rid)
            if s is None or st.get("missing"):
                continue
            for t in st.get("tokens", ()):
                s["tokens"].append(int(t))
                if s["cb"] is not None:
                    try:
                        s["cb"](rid, int(t))
                    except Exception:  # noqa: BLE001 — the engine-side
                        s["cb"] = None  # callback-containment rule
                # the ack advances only once the token is DELIVERED: a
                # poll response lost mid-delivery re-serves from here
                s["acked"] += 1
            if st.get("done") and not s["done"]:
                s["done"] = True
                rm = RequestMetrics(arrival_time=now)
                rm.finish_time = now
                outs.append(RequestOutput(
                    request_id=rid, prompt=s["prompt"],
                    token_ids=list(s["tokens"]),
                    finish_reason=FinishReason(st["reason"]),
                    metrics=rm, error=st.get("error")))
        for rid in [r for r, s in self._live.items() if s["done"]]:
            del self._live[rid]
        return outs

    def unplaced(self) -> tuple[list, list]:
        """What this client could never confirm landed — called at
        replica death, AFTER the journal manifest: the controller
        re-places exactly the rids the dead journal does not cover
        (anything journaled is owned; anything else never arrived)."""
        reqs = [self._live[rid]["req"] for rid in self._maybe_reqs
                if rid in self._live
                and self._live[rid].get("req") is not None]
        recs: list[tuple] = []
        for m in self._maybe_migs:
            header = _manifest_header(m["manifest"])
            for rec in m["manifest"].get("requests", ()):
                recs.append((header, rec))
        for b in self._bounced:
            if b[0] == "req":
                reqs.append(b[1])
            else:
                recs.append((b[1], b[2]))
        return reqs, recs


# ---------------------------------------------------------------------------
# The fleet controller
# ---------------------------------------------------------------------------


class FleetController:
    """N in-process engine replicas behind a :class:`Router`, with
    health-checked circuit breaking, backoff restarts, and live request
    migration (module docstring; docs/serving.md "Fleet serving").

    ``factory(snapshot_dir) -> ServeEngine`` builds one replica life
    (it MUST pass ``snapshot_dir`` through — the journal is the
    migration substrate).  Drive it like an engine: :meth:`submit` then
    :meth:`step`/:meth:`run`; finished streams land in
    :attr:`outputs`, the exactly-once delivery record in
    :attr:`streams`, and per-request placement history (which replicas
    served it) in :attr:`history`.

    Exactly-once across the fleet: every token reaches the caller
    exactly once — live tokens through the wrapped ``on_token``, and on
    a migration the manifest's journal segment fills exactly the
    indices the dead replica journaled but never delivered (the
    commit→callback crash window).  The journal union argument lives in
    serve/recovery.py; the chaos harness asserts both.
    """

    def __init__(self, factory: Callable, n_replicas: int, *,
                 root: str, clock=time.monotonic,
                 router: Optional[Router] = None,
                 suspect_after_s: float = 5.0,
                 dead_after_s: float = 15.0,
                 probe: Optional[Callable] = None,
                 backoff_base_s: float = 0.25,
                 backoff_cap_s: float = 30.0,
                 backoff_jitter: float = 0.5,
                 healthy_reset_s: float = 60.0,
                 max_restarts: Optional[int] = None,
                 trace_events: int = 2048, trace_level: int = 1,
                 audit_events: int = 1024,
                 slo_window_s: float = 60.0,
                 fleet_id: Optional[str] = None, seed: int = 0,
                 roles: Optional[dict] = None,
                 ingress: Optional[dict] = None,
                 autoscale: Optional[dict] = None):
        if n_replicas < 1:
            raise ValueError(f"need >= 1 replica, got {n_replicas}")
        # -- token-bucket ingress admission (per-SLO-class budgets) ------
        # ``{"rate": req/s, "burst": bucket_cap, "per_class": {class:
        # {"rate", "burst"}}}`` — rate/burst are the per-class defaults;
        # per_class overrides one class's budget.  None (the default)
        # admits everything: existing fleets are untouched.
        self.ingress_cfg: Optional[dict] = None
        self._buckets: dict[str, dict] = {}
        if ingress is not None:
            cfg = dict(ingress)
            rate = float(cfg.pop("rate", 0.0))
            burst = float(cfg.pop("burst", max(rate, 1.0)))
            per_class = dict(cfg.pop("per_class", None) or {})
            if cfg:
                raise ValueError(f"unknown ingress keys: {sorted(cfg)}")
            if rate <= 0:
                raise ValueError(f"ingress rate must be > 0, got {rate}")
            for klass in per_class:
                if klass not in SLO_CLASSES:
                    raise ValueError(
                        f"unknown SLO class in ingress per_class: "
                        f"{klass!r} (expected one of {SLO_CLASSES})")
            for klass in SLO_CLASSES:
                o = dict(per_class.get(klass, None) or {})
                r = float(o.pop("rate", rate))
                b = float(o.pop("burst", burst))
                if o:
                    raise ValueError(
                        f"unknown ingress per_class[{klass!r}] keys: "
                        f"{sorted(o)}")
                if r <= 0 or b < 1:
                    raise ValueError(
                        f"ingress class {klass!r}: need rate > 0 and "
                        f"burst >= 1, got {r}, {b}")
                self._buckets[klass] = {"rate": r, "burst": b,
                                        "tokens": b, "t": None}
            self.ingress_cfg = {"rate": rate, "burst": burst}
        self.ingress_shed_by_class: dict[str, int] = {}
        # -- pressure-driven autoscaling ---------------------------------
        # ``{"min", "max", "high", "low", "window_s", "dwell_steps"}`` —
        # smoothed fleet pressure above ``high`` for ``dwell_steps``
        # consecutive ticks spawns a replica (up to ``max``); below
        # ``low`` retires the least-loaded one through the exactly-once
        # drain path (down to ``min``).  None disables scaling.
        self.autoscale_cfg: Optional[dict] = None
        if autoscale is not None:
            cfg = dict(autoscale)
            a = {
                "min": int(cfg.pop("min", 1)),
                "max": int(cfg.pop("max", n_replicas)),
                "high": float(cfg.pop("high", 0.8)),
                "low": float(cfg.pop("low", 0.3)),
                "window_s": float(cfg.pop("window_s", 5.0)),
                "dwell_steps": int(cfg.pop("dwell_steps", 3)),
            }
            if cfg:
                raise ValueError(f"unknown autoscale keys: {sorted(cfg)}")
            if not 1 <= a["min"] <= n_replicas <= a["max"]:
                raise ValueError(
                    f"need 1 <= min <= n_replicas <= max, got "
                    f"min={a['min']}, n_replicas={n_replicas}, "
                    f"max={a['max']}")
            if not 0.0 < a["low"] < a["high"]:
                raise ValueError(
                    f"need 0 < low < high, got {a['low']}, {a['high']}")
            if a["window_s"] < 0:
                raise ValueError(
                    f"window_s must be >= 0, got {a['window_s']}")
            if a["dwell_steps"] < 1:
                raise ValueError(
                    f"dwell_steps must be >= 1, got {a['dwell_steps']}")
            self.autoscale_cfg = a
        # routing roles ({name: "prefill"|"decode"|"both"}, default
        # "both" for every replica — a homogeneous fleet routes exactly
        # as before; docs/serving.md "Disaggregated serving")
        roles = dict(roles or {})
        for rname, role in roles.items():
            if role not in REPLICA_ROLES:
                raise ValueError(
                    f"replica {rname!r}: unknown role {role!r} "
                    f"(expected one of {REPLICA_ROLES})")
        if not suspect_after_s < dead_after_s:
            raise ValueError(
                f"need suspect_after_s < dead_after_s, got "
                f"{suspect_after_s}, {dead_after_s}")
        if trace_level < 0:
            raise ValueError(f"trace_level must be >= 0, got {trace_level}")
        self._clock = clock
        self.router = router or Router()
        self.suspect_after_s = suspect_after_s
        self.dead_after_s = dead_after_s
        # progress age in seconds; replaceable so tests (and subprocess
        # drivers) can layer heartbeat-file staleness in
        self._probe = probe or (
            lambda r, now: now - (r.last_progress
                                  if r.last_progress is not None
                                  else now))
        self.trace = FlightRecorder(capacity=trace_events,
                                    level=trace_level)
        # the router decision audit ring (docs/observability.md "Fleet
        # observability"); gated by the same level knob as the recorder
        # so bench_serve --fleet --trace measures both off together
        self.audit = DecisionAudit(capacity=audit_events,
                                   enabled=trace_level > 0)
        os.makedirs(root, exist_ok=True)
        self.root = root
        # trace-id namespace: fleet-unique request journeys.  rids are
        # unique within one controller (duplicate submits raise), so the
        # fleet id only needs to distinguish controllers sharing a sink.
        self.fleet_id = fleet_id or (os.path.basename(
            os.path.abspath(root)) or "fleet")
        # fleet-level SLO burn windows: deadline misses and sheds over
        # the trailing slo_window_s, fed at finalization wherever the
        # retirement happened (an engine's sweep, the fleet queue's, or
        # an admission shed)
        self.slo_window_s = slo_window_s
        self._slo_deadline = WindowedRate(slo_window_s)
        self._slo_shed = WindowedRate(slo_window_s)
        # dead lives' metrics, folded in before each engine is
        # discarded (the in-process stand-in for a final scrape; a
        # subprocess SIGKILL loses whatever its last scrape missed);
        # their recorders ride along so trace-event totals survive too
        self._carry = ServeMetrics()
        self._carry_recorders: list = []
        now = self._clock()
        # kept for autoscale spawns — a scaled-up replica is built
        # exactly like the initial fleet (same factory, same backoff
        # shape, its own jitter seed)
        self._factory = factory
        self._seed = seed
        self._backoff_kw = dict(
            base_s=backoff_base_s, cap_s=backoff_cap_s,
            jitter=backoff_jitter, healthy_reset_s=healthy_reset_s,
            max_restarts=max_restarts)
        self.replicas: dict[str, EngineReplica] = {}
        self._backoff: dict[str, RestartBackoff] = {}
        for i in range(n_replicas):
            name = f"r{i}"
            rep = EngineReplica(name, factory, os.path.join(root, name))
            rep.role = roles.pop(name, "both")
            self.replicas[name] = rep
            self._backoff[name] = RestartBackoff(
                base_s=backoff_base_s, cap_s=backoff_cap_s,
                jitter=backoff_jitter, healthy_reset_s=healthy_reset_s,
                max_restarts=max_restarts, seed=seed + i)
            rep.start(now)
            if hasattr(rep.engine, "attach_fleet"):
                rep.engine.attach_fleet(self.audit)
            self._backoff[name].on_start(now)
        if roles:
            raise ValueError(
                f"roles for unknown replicas: {sorted(roles)} "
                f"(replicas are r0..r{n_replicas - 1})")
        self.steps = 0
        self.deaths = 0
        self.migrations = 0        # requests moved between replicas
        self.outputs: dict[str, RequestOutput] = {}
        self.streams: dict[str, list] = {}   # exactly-once delivery
        self.placement: dict[str, str] = {}  # rid -> current replica
        self.history: dict[str, list] = {}   # rid -> replicas that held it
        self._cbs: dict[str, Callable] = {}  # rid -> wrapped on_token
        # rid -> the user's terminal callback, stripped off the Request
        # at submit: the serving engine can change mid-stream
        # (migration) and a fleet-level shed never reaches ANY engine,
        # so the fleet is the only layer that can promise exactly-once
        # terminal delivery (_finalize pops it)
        self._finish_cbs: dict[str, Callable] = {}
        self._pending_reqs: deque = deque()  # unplaced fresh requests
        self._pending_recs: deque = deque()  # (header, rec) to re-place
        # autoscaler state: monotonic replica naming (a retired or dead
        # slot's name is NEVER reused — the double-adopt guard), the
        # smoothed-pressure tracker, and the retirement record
        self._next_index = n_replicas
        self._scale_state = {"ema": 0.0, "t": None, "dwell": 0}
        self.scale_ups = 0
        self.scale_downs = 0
        self.retired: set[str] = set()

    # -- submission -------------------------------------------------------

    def _make_cb(self, rid: str, orig) -> Callable:
        stream = self.streams[rid]

        def cb(_rid, tok):
            stream.append(int(tok))
            if orig is not None:
                orig(_rid, tok)
        return cb

    def submit(self, req: Request) -> None:
        """Route one request onto the least-pressure HEALTHY replica.
        Fleet-queued while no healthy replica exists (an outage window
        is transient — deadlines still sweep the fleet queue); SHED
        when every healthy replica's waiting queue is at its bound (the
        PR 3 bounded-admission contract holds fleet-wide: the fleet
        sheds only when EVERY replica is full)."""
        rid = req.request_id
        if rid in self.streams:
            raise ValueError(f"duplicate request id {rid!r}")
        if req.trace is None:
            # fleet-unique trace id, hop 0: one journey however many
            # replicas end up serving it (docs/observability.md
            # "Fleet observability")
            req.trace = {"trace_id": f"{self.fleet_id}/{rid}", "hop": 0}
        if req.arrival_time is None:
            req.arrival_time = self._clock()  # fleet-queue deadlines
        self.streams[rid] = []
        self.history[rid] = []
        self._cbs[rid] = self._make_cb(rid, req.on_token)
        req.on_token = self._cbs[rid]
        if req.on_finish is not None:
            self._finish_cbs[rid] = req.on_finish
            req.on_finish = None
        if self._buckets and not self._ingress_admit(req):
            self.ingress_shed_by_class[req.slo_class] = (
                self.ingress_shed_by_class.get(req.slo_class, 0) + 1)
            self.trace.emit("ingress_shed", rid, slo=req.slo_class)
            self.audit.record(self._clock(), self.steps, "ingress_shed",
                              rid, slo=req.slo_class)
            self._shed(req, f"ingress token bucket empty "
                            f"(class {req.slo_class!r})")
            return
        if not self._place_request(req):
            self._pending_reqs.append(req)

    def _ingress_admit(self, req: Request) -> bool:
        """Spend one ingress token for ``req``: its own class's bucket
        first, then BORROW downward — a class is never refused while a
        LOWER tier still holds budget (the interactive-never-shed-
        before-best-effort contract, generalized), and a lower class
        can never drain a higher one's budget."""
        now = self._clock()
        for klass in SLO_CLASSES[slo_rank(req.slo_class):]:
            b = self._buckets[klass]
            if b["t"] is not None:
                b["tokens"] = min(
                    b["burst"],
                    b["tokens"] + (now - b["t"]) * b["rate"])
            b["t"] = now
            if b["tokens"] >= 1.0:
                b["tokens"] -= 1.0
                return True
        return False

    def _healthy(self, role: Optional[str] = None) -> list:
        """HEALTHY ``(name, load)`` candidates, optionally filtered to
        replicas that can serve ``role`` (a ``"both"`` replica serves
        either role — role is routing preference, not capability)."""
        return [(name, r.load()) for name, r in self.replicas.items()
                if r.state is ReplicaState.HEALTHY
                and (role is None or r.role in (role, "both"))]

    def _place_request(self, req: Request) -> bool:
        from triton_dist_tpu.serve.engine import QueueFull

        healthy = self._healthy()
        # role-aware admission: fresh requests prefer the PREFILL pool
        # (least-pressure within it); with no prefill-capable replica
        # up, availability beats role policy and any healthy replica
        # serves.  All-"both" fleets: pool == healthy, routing exactly
        # as before (docs/serving.md "Disaggregated serving").
        pool = self._healthy("prefill") or healthy
        # capacity-aware: never place onto a queue already at its bound
        # (the engine would shed it; a fleet with room elsewhere must
        # not)
        def with_room(cs):
            return [(n, l) for n, l in cs
                    if (self.replicas[n].engine.max_queue is None
                        or l.queue_depth
                        < self.replicas[n].engine.max_queue)]
        cands = with_room(pool)
        if not cands and len(pool) < len(healthy):
            # the whole prefill tier is at its bound: spill to the rest
            # of the fleet rather than shed while decode queues idle
            cands = with_room(healthy)
        deadline = req.params.deadline_s is not None
        # candidate pressures, captured BEFORE the walk: the audit
        # entry answers "why did this request land there" with the
        # numbers the router actually weighed.  Gated on the audit knob
        # — the trace_level=0 "off" leg of bench_serve --fleet --trace
        # must not pay the O(replicas) capture either.
        pressures = ({n: round(self.router.pressure(l, deadline=deadline),
                               4) for n, l in cands}
                     if self.audit.enabled else None)
        skipped = []
        for name in self.router.rank(cands, deadline=deadline):
            rep = self.replicas[name]
            try:
                shed = rep.engine.submit(req)
            except QueueFull:
                skipped.append(name)
                continue
            self.trace.emit("route", req.request_id, replica=name,
                            state=rep.state.value, deadline=deadline)
            if self.audit.enabled:
                self.audit.record(self._clock(), self.steps, "route",
                                  req.request_id, chosen=name,
                                  deadline=deadline, pressures=pressures,
                                  skipped=skipped)
            self.placement[req.request_id] = name
            self.history[req.request_id].append(name)
            if shed is not None:   # raced to a full queue: final verdict
                self._finalize(shed, name)
            return True
        if healthy:
            # Healthy replicas exist and EVERY one is at its queue
            # bound: the fleet is genuinely full — shed now (the
            # bounded-admission contract, fleet-wide).  Nothing was
            # journaled anywhere for this request.  With NO healthy
            # replica the caller queues instead: that is a transient
            # outage window, not admission pressure.
            self._shed(req, f"every replica's queue at bound "
                            f"({len(healthy)} healthy)")
            return True
        return False

    def _shed(self, req: Request, msg: str) -> None:
        rm = RequestMetrics(arrival_time=req.arrival_time
                            or self._clock())
        rm.finish_time = self._clock()
        out = RequestOutput(request_id=req.request_id,
                            prompt=req.prompt, token_ids=[],
                            finish_reason=FinishReason.SHED,
                            metrics=rm, error=msg)
        self.trace.emit("retire", req.request_id, reason="shed")
        self.audit.record(self._clock(), self.steps, "shed",
                          req.request_id, why=msg)
        # a fleet-level shed reaches no engine, so no engine's metrics
        # ever see it — count it in the carry exactly as an engine-side
        # shed would (shed counter, finish reason, per-class split), or
        # the fleet aggregate under-reports precisely under overload
        self._carry.shed += 1
        self._carry.observe_finish(req.request_id, rm, FinishReason.SHED,
                                   slo_class=req.slo_class)
        self._finalize(out, "fleet")

    def _place_rec(self, header: dict, rec: dict,
                   exclude: frozenset = frozenset()) -> bool:
        """Place one migration-manifest record onto a healthy replica
        via ``migrate_in`` (capacity admission: a rejecting replica
        passes it to the next candidate)."""
        rid = rec["rid"]
        cands = [(n, l) for n, l in self._healthy() if n not in exclude]
        params_deadline = rec.get("params", {}).get("deadline_s")
        deadline = params_deadline is not None
        pressures = ({n: round(self.router.pressure(l, deadline=deadline),
                               4) for n, l in cands}
                     if self.audit.enabled else None)
        # decode-capable candidates first: a migrated/pushed record is
        # past (or resuming) its prefill, so it belongs on the decode
        # tier — prefill-role replicas stay as the availability
        # fallback.  All-"both" fleets: one rank() call, ordering (and
        # the round-robin tie state) exactly as before.
        dec = [(n, l) for n, l in cands
               if self.replicas[n].role != "prefill"]
        rest = [(n, l) for n, l in cands
                if self.replicas[n].role == "prefill"]
        order = self.router.rank(dec, deadline=deadline) if dec else []
        if rest:
            order += self.router.rank(rest, deadline=deadline)
        rejected = {}
        for name in order:
            rep = self.replicas[name]
            res = rep.engine.migrate_in(
                {**header, "requests": [rec]},
                on_token={rid: self._cbs.get(rid)})
            if rid in res["rejected"]:
                rejected[name] = res["rejected"][rid]
                continue
            self.migrations += 1
            self.trace.emit("migrate_in", rid, replica=name,
                            state=rep.state.value,
                            in_place=rid in res["adopted"])
            if self.audit.enabled:
                self.audit.record(self._clock(), self.steps, "migrate",
                                  rid, chosen=name,
                                  in_place=rid in res["adopted"],
                                  pressures=pressures,
                                  rejected=rejected)
            self.placement[rid] = name
            self.history[rid].append(name)
            return True
        return False

    def _drain_pending(self, exclude: frozenset = frozenset()) -> None:
        for _ in range(len(self._pending_recs)):
            header, rec, expires = self._pending_recs.popleft()
            if not self._place_rec(header, rec, exclude):
                self._pending_recs.append((header, rec, expires))
        for _ in range(len(self._pending_reqs)):
            req = self._pending_reqs.popleft()
            if not self._place_request(req):
                self._pending_reqs.append(req)

    # -- the fleet tick ---------------------------------------------------

    def step(self) -> list:
        """One fleet iteration: due restarts → place pending work →
        step every live replica (a step that raises is a replica death:
        migrate + schedule restart) → health sweep.  Returns the
        requests that finished this tick."""
        now = self._clock()
        self.trace.set_step(self.steps)
        finished: list[RequestOutput] = []
        # deadline sweep over the FLEET queue: a request parked here
        # (no healthy replica when it arrived) is visible to no
        # engine's sweep, so its TTL must expire here or never
        for _ in range(len(self._pending_reqs)):
            req = self._pending_reqs.popleft()
            d = req.params.deadline_s
            if (d is not None and req.arrival_time is not None
                    and now - req.arrival_time > d):
                rm = RequestMetrics(arrival_time=req.arrival_time)
                rm.finish_time = now
                out = RequestOutput(
                    request_id=req.request_id, prompt=req.prompt,
                    token_ids=[], finish_reason=FinishReason.DEADLINE,
                    metrics=rm,
                    error=f"deadline {d}s exceeded in the fleet queue")
                self.trace.emit("retire", req.request_id,
                                reason="deadline")
                # the fleet-queue sweep is this request's ONLY metrics
                # seam (no engine ever saw it) — count like an engine
                # deadline sweep would
                self._carry.deadline_expired += 1
                self._carry.observe_finish(
                    req.request_id, rm, FinishReason.DEADLINE,
                    slo_class=req.slo_class)
                self._finalize(out, "fleet")
                finished.append(out)
            else:
                self._pending_reqs.append(req)
        # ...and over the parked MIGRATION records: a deadline-carrying
        # rec stranded here during an outage is just as invisible to
        # every engine's sweep (engines expire WAITING rows whatever
        # their carried progress; the fleet queue must match)
        for _ in range(len(self._pending_recs)):
            header, rec, expires = self._pending_recs.popleft()
            if expires is not None and now > expires:
                rid = rec["rid"]
                ttl = rec["params"]["deadline_s"]
                # expires was arrival(rebased) + ttl: recover the
                # arrival so the retirement's latency is the >= ttl
                # wait it actually suffered, not zero
                rm = RequestMetrics(arrival_time=expires - ttl)
                rm.finish_time = now
                out = RequestOutput(
                    request_id=rid,
                    prompt=np.asarray(rec.get("prompt", []), np.int32),
                    token_ids=[int(t) for t in rec.get("tokens", [])],
                    finish_reason=FinishReason.DEADLINE, metrics=rm,
                    error=f"deadline "
                          f"{rec['params']['deadline_s']}s exceeded "
                          f"in the fleet queue (migrated)")
                self.trace.emit("retire", rid, reason="deadline")
                self._carry.deadline_expired += 1
                self._carry.observe_finish(
                    rid, rm, FinishReason.DEADLINE,
                    slo_class=rec.get("slo", "interactive"))
                self._finalize(out, "fleet")
                finished.append(out)
            else:
                self._pending_recs.append((header, rec, expires))
        for name, rep in self.replicas.items():
            if (rep.state is ReplicaState.DEAD
                    and rep.restart_at is not None
                    and now >= rep.restart_at):
                rep.start(now)
                if hasattr(rep.engine, "attach_fleet"):
                    rep.engine.attach_fleet(self.audit)
                rep.restarts += 1
                self._backoff[name].on_start(now)
                self.trace.emit("replica_state", None, replica=name,
                                state=rep.state.value,
                                life=rep.life)
                self.audit.record(now, self.steps, "restart",
                                  replica=name, life=rep.life)
        self._drain_pending()
        for name, rep in self.replicas.items():
            if rep.state is ReplicaState.DEAD or rep.engine is None:
                continue
            if not rep.engine.has_work():
                # idle is not a stall — but an idle REMOTE replica must
                # still answer a health probe, or a partition of an
                # idle process would never be noticed until the router
                # placed onto it
                ping = getattr(rep.engine, "ping", None)
                if ping is None or ping():
                    rep.last_progress = now
                continue
            try:
                outs = rep.engine.step()
            except (KeyboardInterrupt, SystemExit):
                raise
            except NetUnreachable:
                # the replica answered nothing this tick: NOT a death —
                # no progress is recorded, so the probe age walks the
                # SUSPECT→DEAD ladder (a partition is handled by the
                # same machinery as a SIGKILL, dead_after_s later)
                continue
            except WatchdogTimeout as e:
                # engine-level stall: the dispatch wedged past its
                # budget — the process is as good as gone
                self._on_replica_death(name, f"watchdog: {e}", now)
                continue
            except BaseException as e:  # noqa: BLE001 — InjectedKill /
                # engine-fatal escalations ARE the process-death seam
                self._on_replica_death(
                    name, f"{type(e).__name__}: {e}", now)
                continue
            rep.last_progress = now
            if rep.state is ReplicaState.SUSPECT:
                rep.state = ReplicaState.HEALTHY  # progress: recovered
                self.trace.emit("replica_state", None, replica=name,
                                state=rep.state.value)
                self.audit.record(now, self.steps, "replica_state",
                                  replica=name, state=rep.state.value,
                                  why="progress resumed")
            for out in outs:
                self._finalize(out, name)
                finished.append(out)
            # a remote replica's reconciliation can BOUNCE a migration
            # rec (genuine capacity rejection discovered late): re-place
            take = getattr(rep.engine, "take_bounced", None)
            if take is not None:
                for b in take():
                    if b[0] == "req":
                        req = b[1]
                        self.placement.pop(req.request_id, None)
                        if not self._place_request(req):
                            self._pending_reqs.append(req)
                    else:
                        _, header, rec = b
                        self.placement.pop(rec["rid"], None)
                        self._pending_recs.append(
                            (header, rec,
                             self._rec_expiry(header, rec)))
        # health sweep: probe-driven SUSPECT/DEAD (heartbeat staleness
        # for subprocess drivers; progress age in-process)
        for name, rep in self.replicas.items():
            if rep.state is ReplicaState.DEAD:
                continue
            age = self._probe(rep, now)
            if age > self.dead_after_s:
                self._on_replica_death(name, f"stalled {age:.1f}s", now)
            elif (age > self.suspect_after_s
                  and rep.state is ReplicaState.HEALTHY):
                rep.state = ReplicaState.SUSPECT
                self.trace.emit("replica_state", None, replica=name,
                                state=rep.state.value,
                                age=round(age, 3))
                self.audit.record(now, self.steps, "replica_state",
                                  replica=name, state=rep.state.value,
                                  age=round(age, 3))
            elif (age <= self.suspect_after_s
                  and rep.state is ReplicaState.SUSPECT):
                # the probe says healthy again (an IDLE suspect replica
                # never re-proves itself through a step, so the sweep
                # must heal too, or it would stay circuit-broken
                # forever)
                rep.state = ReplicaState.HEALTHY
                self.trace.emit("replica_state", None, replica=name,
                                state=rep.state.value)
                self.audit.record(now, self.steps, "replica_state",
                                  replica=name, state=rep.state.value,
                                  why="probe healthy")
        if self.autoscale_cfg is not None:
            self._autoscale_step(now)
        self.steps += 1
        return finished

    def has_work(self) -> bool:
        return (bool(self._pending_reqs) or bool(self._pending_recs)
                or any(r.engine is not None and r.engine.has_work()
                       for r in self.replicas.values()))

    def run(self, max_steps: int = 100_000) -> dict:
        """Step until the fleet drains; returns ``dict(outputs)``.
        Raises when no replica is live and none will restart (budget
        exhausted with work pending) — the fleet-level outage."""
        steps = 0
        while self.has_work():
            if not any(r.state is not ReplicaState.DEAD
                       or r.restart_at is not None
                       for r in self.replicas.values()):
                raise RuntimeError(
                    "fleet outage: every replica is dead with its "
                    "restart budget exhausted and work is pending")
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"fleet not drained after {max_steps} steps")
        return dict(self.outputs)

    # -- pressure-driven autoscaling --------------------------------------

    def _tier_pressure(self, reps: list) -> float:
        """Mean per-replica saturation over the live members of one
        tier: queue depth against its admission bound (``max_queue``,
        else ``4 * max_batch`` — the same denominator the engine's
        brownout ladder uses) or KV-pool utilization, whichever is
        tighter.  A tier with NO live replica is fully saturated."""
        live = [r for r in reps if r.engine is not None
                and r.state is not ReplicaState.DEAD]
        if not live:
            return 1.0

        def sat(rep) -> float:
            load = rep.load()
            mq = rep.engine.max_queue
            denom = mq if mq else 4 * max(load.max_batch, 1)
            return max(load.queue_depth / max(denom, 1), load.kv_util)

        return sum(sat(r) for r in live) / len(live)

    def _autoscale_tier(self, now: float, st: dict, reps: list, *,
                        role: str, pending: bool) -> None:
        """One tier's autoscale evaluation: smooth the raw pressure
        into ``st["ema"]`` (clock-driven EMA, ``alpha = 1 -
        exp(-dt/window_s)``), walk the signed dwell counter, and act at
        the water marks — spawn at sustained-high (to ``max``), retire
        the least-loaded healthy replica through the exactly-once drain
        path at sustained-low (to ``min``).  ``reps`` is ``[(name,
        EngineReplica)]``; ``pending`` marks unplaced fleet-queue work
        waiting on this tier (saturation wherever the replicas sit).
        Returns ``(spawned_name, retired_name)`` (either may be
        ``None``)."""
        cfg = self.autoscale_cfg
        raw = self._tier_pressure([r for _, r in reps])
        if pending:
            raw = max(raw, 1.0)
        if st["t"] is None or cfg["window_s"] <= 0:
            st["ema"] = raw
        else:
            dt = max(now - st["t"], 0.0)
            alpha = 1.0 - math.exp(-dt / cfg["window_s"])
            st["ema"] += alpha * (raw - st["ema"])
        st["t"] = now
        if st["ema"] >= cfg["high"]:
            st["dwell"] = max(st["dwell"], 0) + 1
        elif st["ema"] <= cfg["low"]:
            st["dwell"] = min(st["dwell"], 0) - 1
        else:
            st["dwell"] = 0
        spawned = retired = None
        if st["dwell"] >= cfg["dwell_steps"]:
            # a DEAD replica with a scheduled restart is capacity in
            # flight — spawning past it would overshoot max
            capacity = sum(1 for _, r in reps
                           if r.state is not ReplicaState.DEAD
                           or r.restart_at is not None)
            if capacity < cfg["max"]:
                spawned = self._spawn_replica(now, role=role,
                                              pressure=st["ema"])
            st["dwell"] = 0
        elif st["dwell"] <= -cfg["dwell_steps"]:
            healthy = [(self.router.pressure(r.load()), n)
                       for n, r in reps
                       if r.state is ReplicaState.HEALTHY]
            if len(healthy) > cfg["min"]:
                retired = min(healthy)[1]
                self.retire_replica(retired)
            st["dwell"] = 0
        return spawned, retired

    def _autoscale_step(self, now: float) -> None:
        self._autoscale_tier(
            now, self._scale_state, list(self.replicas.items()),
            role="both",
            pending=bool(self._pending_reqs or self._pending_recs))

    def _spawn_replica(self, now: float, role: str = "both",
                       pressure: Optional[float] = None) -> str:
        """Scale-up: bring ONE new replica into the fleet from the
        stored factory.  Names are monotonic (``r{next_index}``, never
        reused) — a retired or dead replica's name can never be
        double-adopted by a new life racing its crash migration."""
        idx = self._next_index
        self._next_index += 1
        name = f"r{idx}"
        rep = EngineReplica(name, self._factory,
                            os.path.join(self.root, name))
        rep.role = role
        self.replicas[name] = rep
        self._backoff[name] = RestartBackoff(**self._backoff_kw,
                                             seed=self._seed + idx)
        rep.start(now)
        if hasattr(rep.engine, "attach_fleet"):
            rep.engine.attach_fleet(self.audit)
        self._backoff[name].on_start(now)
        self.scale_ups += 1
        p = round(self._scale_state["ema"] if pressure is None
                  else pressure, 4)
        self.trace.emit("scale", None, action="up", replica=name,
                        role=role, pressure=p)
        self.audit.record(now, self.steps, "scale", replica=name,
                          action="up", role=role, pressure=p)
        return name

    def retire_replica(self, name: str) -> int:
        """Scale-down: cooperatively drain every in-flight request off
        ``name`` through the exactly-once path (``mig`` receipts land
        in the journal before the manifest leaves — the same argument
        as :meth:`drain_replica`), fold the life's metrics into the
        fleet carry, and retire the replica FOR GOOD: no restart is
        scheduled and the name is never reused (:attr:`retired`).
        Returns the number of requests moved."""
        rep = self.replicas[name]
        if rep.engine is None:
            raise ValueError(f"replica {name} is not live")
        now = self._clock()
        # circuit-break admissions FIRST: the drain re-places parked
        # work through _drain_pending, and a still-HEALTHY leaver could
        # win that placement and strand the request when its engine
        # drops a moment later
        rep.state = ReplicaState.SUSPECT
        moved = self.drain_replica(name)
        # same carry fold as a death, minus the crash migration: the
        # drain already moved everything, so only the accounting rides
        m = rep.engine.metrics
        self._carry.merge(m)
        self._carry.queue_depth_last = 0
        self._carry.running_last = 0
        self._carry.kv_util_last = 0.0
        self._carry.compiled_fns.extend(m.compiled_fns)
        if m.recorder is not None:
            self._carry_recorders.append(m.recorder)
        if rep.engine._journal is not None:
            rep.engine._journal.close()
        rep.engine = None
        rep.state = ReplicaState.DEAD
        rep.restart_at = None
        rep.death_reason = "retired (scaled down)"
        self.retired.add(name)
        self.scale_downs += 1
        self.trace.emit("scale", None, action="down", replica=name,
                        moved=moved,
                        pressure=round(self._scale_state["ema"], 4))
        self.audit.record(now, self.steps, "scale", replica=name,
                          action="down", moved=moved,
                          pressure=round(self._scale_state["ema"], 4))
        return moved

    # -- failure handling + migration -------------------------------------

    def kill_replica(self, name: str, why: str = "killed") -> None:
        """Declare a replica dead NOW (the chaos / ops hook — the
        in-process stand-in for SIGKILL): its in-flight requests
        migrate from the durable journal and a restart is scheduled
        under backoff."""
        self._on_replica_death(name, why, self._clock())

    def drain_replica(self, name: str) -> int:
        """Cooperatively migrate every in-flight request OFF a live
        replica (maintenance drain / rebalance): ``ServeEngine.drain``
        hands off live KV + pending tokens, so RUNNING rows resume
        mid-stream on their new replica with zero recompute.  Returns
        the number of requests moved."""
        rep = self.replicas[name]
        if rep.engine is None:
            raise ValueError(f"replica {name} is dead; crash migration "
                             f"already ran")
        manifest = rep.engine.drain()
        n = len(manifest["requests"])
        self._absorb_manifest(manifest, source=name)
        self._drain_pending(exclude=frozenset((name,)))
        return n

    def _on_replica_death(self, name: str, why: str,
                          now: float) -> None:
        rep = self.replicas[name]
        if rep.state is ReplicaState.DEAD:
            return
        from triton_dist_tpu.serve.recovery import manifest_from_journal

        print(f"[fleet] replica {name} dead ({why}); migrating its "
              f"in-flight requests", file=sys.stderr)
        # remote replicas: calls whose ack was lost and never
        # reconciled — captured BEFORE the engine ref drops, resolved
        # against the journal below (anything journaled is owned by the
        # dead life; anything else never arrived and re-places)
        lost_reqs: list = []
        lost_recs: list = []
        if rep.engine is not None and hasattr(rep.engine, "unplaced"):
            lost_reqs, lost_recs = rep.engine.unplaced()
        if rep.engine is not None and rep.engine._journal is not None:
            rep.engine._journal.close()  # single writer for the mark
            #                              (for a RemoteReplica this
            #                              SIGKILLs the child process —
            #                              a partitioned zombie must
            #                              stop writing before the
            #                              crash path reads)
        if rep.engine is not None:
            # fold the dying life's metrics into the fleet carry so the
            # aggregate histograms keep its samples (the in-process
            # stand-in for a subprocess replica's final scrape — a
            # SIGKILL there loses whatever the last scrape missed)
            m = rep.engine.metrics
            self._carry.merge(m)
            # ...but NOT its point-in-time gauges: a dead replica's
            # current queue/batch/KV state is zero, and carrying its
            # last readings would hold a pressure alert firing forever
            # (peaks stay — they are history, not state)
            self._carry.queue_depth_last = 0
            self._carry.running_last = 0
            self._carry.kv_util_last = 0.0
            # compile/trace counters have no additive field to merge
            # (compile_misses is a property over the registered
            # CountingJit wrappers; the recorder is an object) — carry
            # the frozen objects themselves so the in-process aggregate
            # reports the same totals the scrape path would sum
            self._carry.compiled_fns.extend(m.compiled_fns)
            if m.recorder is not None:
                self._carry_recorders.append(m.recorder)
        life_dir = rep.life_dir
        rep.engine = None  # the process is gone; durable state remains
        rep.state = ReplicaState.DEAD
        rep.death_reason = why
        self.deaths += 1
        self.trace.emit("replica_state", None, replica=name,
                        state=rep.state.value, why=why)
        self.audit.record(now, self.steps, "replica_state",
                          replica=name, state=rep.state.value, why=why)
        manifest = manifest_from_journal(life_dir, mark=True)
        # Journal salvage escalation: the dead life's journal carried
        # interior corruption — the salvaged prefix may be missing
        # committed tokens.  Count + trace it here (the dead engine's
        # own metrics are gone), then let _absorb_manifest reconcile
        # each stream against OUR delivery record: what the controller
        # delivered is committed truth the salvage cannot un-commit.
        jdamage = manifest.get("damage")
        if jdamage is not None:
            self._carry.journal_corrupt += 1
            self.trace.emit("corrupt", None, artifact="journal",
                            replica=name, **jdamage)
            self.audit.record(now, self.steps, "journal_corrupt",
                              replica=name,
                              quarantine=jdamage.get("quarantine"),
                              affected=jdamage.get("affected_rids"))
        # retirements whose outputs the dying step swallowed: the
        # journal's fin records are the accounting of record
        for f in manifest["finished"]:
            if f["rid"] in self.streams and f["rid"] not in self.outputs:
                self._finalize_from_journal(f, name)
        self._absorb_manifest(manifest, source=name)
        covered = ({r["rid"] for r in manifest.get("requests", ())}
                   | {f["rid"] for f in manifest.get("finished", ())})
        for req in lost_reqs:
            rid = req.request_id
            if rid in covered or rid in self.outputs:
                continue   # the ambiguous call DID land: the journal
                #            (or a retirement) owns it
            self.placement.pop(rid, None)
            self._pending_reqs.append(req)
        for header, rec in lost_recs:
            if rec["rid"] in covered or rec["rid"] in self.outputs:
                continue
            self.placement.pop(rec["rid"], None)
            self._pending_recs.append(
                (header, rec, self._rec_expiry(header, rec)))
        self._drain_pending(exclude=frozenset((name,)))
        delay = self._backoff[name].on_death(now)
        if delay is None:
            rep.restart_at = None
            print(f"[fleet] replica {name}: restart budget exhausted; "
                  f"staying dead", file=sys.stderr)
        else:
            rep.restart_at = now + delay
        # fleet postmortem: the controller ring + decision audit land
        # next to the replica dirs, where the supervisor's postmortem
        # glob (and any operator) finds them
        self.flight_flush(f"replica {name} dead: {why}")

    def _rec_expiry(self, header: dict, rec: dict) -> Optional[float]:
        """A parked migration rec's TTL, re-based from the source clock
        (``header["clock"]``) onto OURS — the fleet-queue deadline
        sweep covers parked recs with it, whatever path parked them
        (manifest absorption, a capacity bounce, death re-placement)."""
        ttl = rec.get("params", {}).get("deadline_s")
        arr = rec.get("arrival")
        if ttl is None or arr is None:
            return None
        return arr + (self._clock() - (header.get("clock") or 0.0)) + ttl

    def _absorb_manifest(self, manifest: dict, source: str) -> None:
        """Fold a migration manifest into fleet accounting: fill each
        stream's delivery record from the journal segment (tokens the
        source journaled but never delivered — the commit→callback
        crash window — redeliver HERE, exactly the missing indices),
        then queue the records for placement.

        A manifest carrying a journal-salvage ``damage`` report may
        hold FEWER tokens than we delivered (the corrupt tail was cut);
        the delivery record is then the authority — tokens the client
        already saw are committed whatever the rotted journal says, so
        the rec is extended back to the delivered prefix and recompute
        resumes from there.  Without damage, a shorter journal still
        means the journal-precedes-callback invariant broke: assert."""
        damaged = manifest.get("damage") is not None
        header = _manifest_header(manifest)
        for rec in manifest.get("requests", ()):
            rid = rec["rid"]
            if rid not in self.streams:
                continue  # not fleet traffic (foreign journal entry)
            if rid in self.outputs:
                continue  # finished-and-delivered: salvage must never
                #           resurrect a retired stream
            cur = self.placement.get(rid)
            if cur is not None and cur != source:
                other = self.replicas.get(cur)
                if (other is not None and other.engine is not None
                        and other.state is not ReplicaState.DEAD):
                    continue  # the stream is LIVE on another replica —
                    #           a salvaged journal missing its mig
                    #           receipt must not double-place it
            toks = rec.get("tokens", [])
            d = len(self.streams[rid])
            if damaged and d > len(toks):
                rec["tokens"] = toks = [int(t) for t in
                                        self.streams[rid]]
                # token timestamps past the salvaged prefix are gone
                # with the corrupt lines; the adopting engine treats a
                # short tok_ts like a pre-timestamp manifest (re-bases)
                if rec.get("tok_ts") is not None:
                    rec["tok_ts"] = rec["tok_ts"][:len(toks)]
            assert d <= len(toks), (
                f"{rid}: delivered {d} tokens but the journal only "
                f"holds {len(toks)} — the journal-precedes-callback "
                f"invariant broke")
            self.streams[rid].extend(int(t) for t in toks[d:])
            self.placement.pop(rid, None)
            self._pending_recs.append((header, rec,
                                       self._rec_expiry(header, rec)))

    def _finalize(self, out: RequestOutput, name: str) -> None:
        rid = out.request_id
        # SLO burn windows: every deadline miss / shed fleet-wide feeds
        # here, whichever layer retired it (engine sweep, fleet-queue
        # sweep, admission shed)
        if out.finish_reason is FinishReason.DEADLINE:
            self._slo_deadline.observe(self._clock())
        elif out.finish_reason is FinishReason.SHED:
            self._slo_shed.observe(self._clock())
        self.outputs[rid] = out
        s = self.streams.get(rid)
        if s is not None and len(s) < len(out.token_ids):
            # a disabled/raising user callback starves the delivery
            # record; the retirement's authoritative token list
            # reconciles it
            s.extend(out.token_ids[len(s):])
        self.placement.pop(rid, None)
        # the terminal callback, exactly once per rid (pop), whatever
        # path retired the stream — engine step, journal backfill,
        # fleet-queue sweep, or an admission shed that never reached an
        # engine.  Same containment rule as the engine's callbacks.
        cb = self._finish_cbs.pop(rid, None)
        if cb is not None:
            try:
                cb(out)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # noqa: BLE001 — callback containment
                self._carry.callback_errors += 1
                print(f"[fleet] on_finish callback for {rid} raised "
                      f"{type(e).__name__}: {e}", file=sys.stderr)

    def _finalize_from_journal(self, f: dict, name: str) -> None:
        rm = RequestMetrics(arrival_time=self._clock())
        out = RequestOutput(
            request_id=f["rid"],
            prompt=np.asarray(f.get("prompt", []), np.int32),
            token_ids=[int(t) for t in f["tokens"]],
            finish_reason=FinishReason(f["reason"]),
            metrics=rm, error=f.get("err"))
        self._finalize(out, name)

    # -- observability ----------------------------------------------------

    def aggregate_metrics(self) -> ServeMetrics:
        """The fleet as ONE ``ServeMetrics``: every live replica's
        metrics plus the dead lives' carry, merged via
        ``ServeMetrics.merge`` — counters add, the SLO histograms merge
        bucket-EXACTLY (``LogHistogram.merge``), so
        ``fleet_summary()["latency"]`` percentiles equal percentiles
        over the pooled per-replica samples (the chaos test pins the
        equality).  This is the in-process aggregation path; subprocess
        fleets get the same numbers from :func:`merge_scrapes` over the
        per-replica ``/metrics`` texts."""
        agg = ServeMetrics()
        agg.merge(self._carry)
        # compile-stall and trace-event totals ride as object
        # registries, not counters, so merge() skips them; re-register
        # dead lives' frozen wrappers + every live engine's so the
        # in-process exposition reports the same sums the subprocess
        # scrape-and-merge path would (serve_compile_misses,
        # serve_trace_events_total, serve_trace_dropped)
        agg.compiled_fns.extend(self._carry.compiled_fns)
        recorders = list(self._carry_recorders)
        for rep in self.replicas.values():
            if rep.engine is not None:
                m = rep.engine.metrics
                agg.merge(m)
                agg.compiled_fns.extend(m.compiled_fns)
                if m.recorder is not None:
                    recorders.append(m.recorder)
        if recorders:
            from types import SimpleNamespace
            agg.recorder = SimpleNamespace(
                emitted=sum(r.emitted for r in recorders),
                dropped=sum(r.dropped for r in recorders))
        return agg

    def explain(self, rid: str) -> list[dict]:
        """The decision-audit trail for one request — why it landed
        where it did and why it moved (route/migrate/shed entries still
        in the bounded ring)."""
        return self.audit.for_request(rid)

    def slo_stats(self) -> dict:
        """Windowed SLO burn (fleet_summary()["slo"]): deadline misses
        and sheds over the trailing ``slo_window_s`` — the burn-rate
        numbers an alert fires on, next to the all-time totals."""
        now = self._clock()
        return {
            "window_s": self.slo_window_s,
            "deadline_miss_window": self._slo_deadline.count(now),
            "shed_window": self._slo_shed.count(now),
            "deadline_miss_per_s": self._slo_deadline.rate(now),
            "shed_per_s": self._slo_shed.rate(now),
            "deadline_miss_total": self._slo_deadline.total,
            "shed_total": self._slo_shed.total,
        }

    def fleet_summary(self) -> dict:
        """One dict of fleet state: per-replica health/lives/load, the
        routing + migration counters, the MERGED SLO latency percentiles
        (``latency`` — exact histogram merge across replicas, dead lives
        included), the windowed SLO burn (``slo``), and the decision-
        audit occupancy (``audit``) — the fleet twin of
        ``ServeMetrics.summary``."""
        reps = {}
        for name, rep in self.replicas.items():
            r = {
                "state": rep.state.value,
                "role": rep.role,
                "life": rep.life,
                "restarts": rep.restarts,
                "death_reason": rep.death_reason,
            }
            if rep.engine is not None:
                load = rep.load()
                r.update(queue_depth=load.queue_depth,
                         running=load.running,
                         kv_util=round(load.kv_util, 4),
                         completed=rep.engine.metrics.completed,
                         migrated_in=rep.engine.metrics.migrated_in,
                         migrated_out=rep.engine.metrics.migrated_out,
                         pushed_in=rep.engine.metrics.pushed_in,
                         pushed_out=rep.engine.metrics.pushed_out)
            reps[name] = r
        return {
            "fleet_id": self.fleet_id,
            "replicas": reps,
            "steps": self.steps,
            "deaths": self.deaths,
            "migrations": self.migrations,
            "completed": len(self.outputs),
            "pending": len(self._pending_reqs) + len(self._pending_recs),
            "latency": self.aggregate_metrics().latency_stats(),
            "slo": self.slo_stats(),
            "pressure_smoothed": round(self._scale_state["ema"], 4),
            "scale": {"ups": self.scale_ups, "downs": self.scale_downs,
                      "retired": sorted(self.retired)},
            "ingress_shed": dict(sorted(
                self.ingress_shed_by_class.items())),
            "audit": {"recorded": self.audit.recorded,
                      "dropped": self.audit.dropped},
        }

    def to_prometheus(self) -> str:
        """The fleet's Prometheus exposition: the per-engine ``serve_*``
        series AGGREGATED across replicas (counters summed, histograms
        bucket-exactly merged — :meth:`aggregate_metrics`), plus the
        controller-level ``fleet_*`` series (:data:`FLEET_SERIES`,
        documented in docs/observability.md).  Subprocess fleets build
        the same serve_* aggregate with :func:`merge_scrapes`."""
        now = self._clock()
        states: dict[str, int] = {}
        for rep in self.replicas.values():
            states[rep.state.value] = states.get(rep.state.value, 0) + 1
        L = ["# TYPE fleet_replicas gauge"]
        for state in sorted(states):
            L.append(f'fleet_replicas{{state="{state}"}} {states[state]}')
        # per-replica one-hot health: pressure alone can look fine
        # while a breaker is open — alerting needs to see WHICH replica
        # is SUSPECT/DEAD
        L.extend(replica_state_lines(
            (name, self.replicas[name].state)
            for name in sorted(self.replicas)))
        # per-replica routing role — the disagg tier's shape next to
        # its health (constant "both" one-hots for homogeneous fleets)
        L.extend(replica_role_lines(
            (name, self.replicas[name].role)
            for name in sorted(self.replicas)))
        L.append("# TYPE fleet_lives_total counter")
        L.append(f"fleet_lives_total "
                 f"{sum(r.life for r in self.replicas.values())}")
        L.append("# TYPE fleet_deaths_total counter")
        L.append(f"fleet_deaths_total {self.deaths}")
        L.append("# TYPE fleet_migrations_total counter")
        L.append(f"fleet_migrations_total {self.migrations}")
        L.append("# TYPE fleet_completed_total counter")
        L.append(f"fleet_completed_total {len(self.outputs)}")
        L.append("# TYPE fleet_steps_total counter")
        L.append(f"fleet_steps_total {self.steps}")
        L.append("# TYPE fleet_pending gauge")
        L.append(f"fleet_pending "
                 f"{len(self._pending_reqs) + len(self._pending_recs)}")
        L.append("# TYPE fleet_deadline_miss_window gauge")
        L.append(f"fleet_deadline_miss_window "
                 f"{self._slo_deadline.count(now)}")
        L.append("# TYPE fleet_shed_window gauge")
        L.append(f"fleet_shed_window {self._slo_shed.count(now)}")
        L.append("# TYPE fleet_deadline_miss_per_s gauge")
        L.append(f"fleet_deadline_miss_per_s "
                 f"{self._slo_deadline.rate(now):.6g}")
        L.append("# TYPE fleet_shed_per_s gauge")
        L.append(f"fleet_shed_per_s {self._slo_shed.rate(now):.6g}")
        L.append("# TYPE fleet_audit_records_total counter")
        L.append(f"fleet_audit_records_total {self.audit.recorded}")
        L.append("# TYPE fleet_pressure_smoothed gauge")
        L.append(f"fleet_pressure_smoothed "
                 f"{self._scale_state['ema']:.6g}")
        L.append("# TYPE fleet_scale_ups_total counter")
        L.append(f"fleet_scale_ups_total {self.scale_ups}")
        L.append("# TYPE fleet_scale_downs_total counter")
        L.append(f"fleet_scale_downs_total {self.scale_downs}")
        L.append("# TYPE fleet_ingress_shed_total counter")
        for k in SLO_CLASSES:
            L.append(f'fleet_ingress_shed_total{{slo_class="{k}"}} '
                     f'{self.ingress_shed_by_class.get(k, 0)}')
        return "\n".join(L) + "\n" + self.aggregate_metrics().to_prometheus()

    # -- the merged fleet timeline ----------------------------------------

    def _trace_sources(self) -> list:
        """``[(name, pid, events), ...]`` — the controller ring plus one
        entry per replica: the live engine's ring, preceded by every
        dead life's postmortem flight events (the ring dies with the
        life; the crash-path ``force=True`` flush is where it
        survives)."""
        sources = [("fleet", FLEET_PID, self.trace.events())]
        for i, (name, rep) in enumerate(self.replicas.items()):
            evs: list = []
            for life in range(1, rep.life + 1):
                if rep.engine is not None and life == rep.life:
                    continue   # the live ring below covers this life
                fl = latest_flight(os.path.join(rep.root, f"life{life}"))
                if fl is None:
                    continue
                try:
                    evs.extend(tuple(e)
                               for e in load_flight(fl).get("events", ()))
                except (OSError, ValueError):
                    continue
            if rep.engine is not None:
                evs.extend(rep.engine.trace.events())
            sources.append((name, FLEET_REPLICA_PID_BASE + i, evs))
        return sources

    def to_perfetto(self) -> dict:
        """ONE fleet timeline as a Chrome trace: the controller's
        routing/health track plus every replica's engine timeline under
        its own replica-namespaced pid, with Perfetto flow arrows
        linking each ``migrate_out``→``migrate_in`` pair — a migrated
        request reads as one continuous journey across replica tracks
        (docs/observability.md "Fleet observability").  Dead lives'
        events come from their postmortem flight files; a request's
        carried ring tail also re-renders on its adopting replica (the
        same journey seen from both sides — intentional)."""
        srcs = self._trace_sources()
        events: list[dict] = []
        tids: dict[int, dict] = {}
        for name, pid, evs in srcs:
            pname = ("fleet controller" if pid == FLEET_PID
                     else f"replica {name} (serve engine)")
            tids[pid] = {}
            events.extend(events_to_perfetto(evs, pid=pid,
                                             process_name=pname,
                                             tids_out=tids[pid]))
        # flows bind replica-side events only: the controller also logs
        # migrate_in, and anchoring there would draw arrows to the
        # routing track instead of across replicas
        events.extend(link_migration_flows(
            [(pid, evs) for _, pid, evs in srcs if pid != FLEET_PID],
            tids))
        return {"traceEvents": events}

    def export_perfetto(self, path: str) -> str:
        """Write :meth:`to_perfetto` to ``path`` (gzipped on ``.gz``)."""
        return write_trace(self.to_perfetto(), path)

    def export_profile(self, job_dir: str, rank: int = 0) -> str:
        """Drop the merged fleet timeline where
        ``runtime.profiling.merge_rank_traces`` globs per-rank traces
        (``{job_dir}/rank{rank}/fleet.trace.json.gz``): run a
        ``group_profile`` capture into the same ``job_dir``, call this,
        then merge — ONE ui.perfetto.dev file holds the device timeline,
        the controller, and every replica side by side
        (docs/observability.md has the recipe)."""
        out = os.path.join(job_dir, f"rank{rank}", "fleet.trace.json.gz")
        return write_trace(self.to_perfetto(), out)

    def flight_flush(self, reason: str) -> Optional[str]:
        """Fleet postmortem: the controller ring + the decision audit to
        ``{root}/flight_<step>.json`` (the supervisor's postmortem glob
        and ``load_flight`` both read it).  Deliberately UNthrottled
        within a step: a second replica death in the same fleet step
        re-flushes — overwriting the same file with a superset of the
        ring — instead of silently losing the later death's record;
        flush volume is bounded by death count anyway.  Best-effort
        like the engine's."""
        if self.trace.level <= 0:
            return None
        self.trace.set_step(self.steps)
        try:
            return self.trace.flush(
                self.root, reason=reason,
                extra={"audit": self.audit.entries(),
                       "slo": self.slo_stats()})
        except Exception:  # noqa: BLE001 — crash-path best effort
            return None


# ---------------------------------------------------------------------------
# Subprocess fleets: scrape-and-merge metrics + flight-file timeline
# assembly (no in-process controller to ask)
# ---------------------------------------------------------------------------

#: Histogram base names in the ``serve_*`` exposition (the five SLO
#: histograms ``ServeMetrics.to_prometheus`` emits) — what
#: :func:`merge_scrapes` reconstructs bucket-exactly instead of summing
#: raw series.
SCRAPE_HISTOGRAMS = (
    "serve_ttft_seconds", "serve_itl_seconds",
    "serve_queue_time_seconds", "serve_step_time_seconds",
    "serve_snapshot_seconds",
)

#: The labeled per-program wall-time histogram family
#: (``serve_program_ms{program="..."}``, docs/observability.md "Kernel
#: observability"): :func:`merge_scrapes` discovers the program labels
#: per scrape and rebuilds each program's histogram bucket-exactly,
#: like the unlabeled SLO histograms above.
PROGRAM_HISTOGRAM = "serve_program_ms"


def _scrape_program_labels(series: dict) -> list:
    """Program names present in one scrape's ``serve_program_ms``
    family (from the ``_count{program="..."}`` series)."""
    prefix = PROGRAM_HISTOGRAM + '_count{program="'
    return [key[len(prefix):-2] for key in series
            if key.startswith(prefix)]


def merge_scrapes(texts: list) -> str:
    """Merge per-replica ``/metrics`` scrape texts into ONE fleet-level
    ``serve_*`` exposition — the subprocess twin of
    ``FleetController.aggregate_metrics`` (docs/observability.md "Fleet
    observability").

    Counters (and labeled counter families) sum per series; gauges sum
    except ``serve_kv_utilization`` (a ratio: the merged exposition
    reports the max — the pressure signal an operator actually wants)
    and ``serve_kv_bytes_per_token`` (re-derived from the summed
    pool-bytes / token-slots series, never summed as a quotient);
    the five SLO histograms are REBUILT per scrape
    (``LogHistogram.from_prom`` de-accumulates the dense cumulative
    buckets) and merged count-wise, so the merged percentiles equal the
    pooled-sample histogram bucket-exactly even when replicas reached
    different bucket depths — summing raw ``_bucket`` series per ``le``
    would undercount exactly there (the tier-1 merge-vs-pooled test
    pins this)."""
    hists = {h: LogHistogram() for h in SCRAPE_HISTOGRAMS}
    prog_hists: dict[str, LogHistogram] = {}
    sums: dict[str, float] = {}
    maxes: dict[str, float] = {}
    types: dict[str, str] = {}
    order: list[str] = []
    for text in texts:
        g = parse_prometheus(text)
        for h, acc in hists.items():
            acc.merge(LogHistogram.from_prom(g, h))
        # per-program wall-time family: rebuild each labeled member
        # bucket-exactly (a program only one replica ran still joins)
        for prog in _scrape_program_labels(g):
            prog_hists.setdefault(prog, LogHistogram()).merge(
                LogHistogram.from_prom(g, PROGRAM_HISTOGRAM,
                                       labels=f'program="{prog}"'))
        for key, v in g.items():
            base = key.split("{", 1)[0]
            if any(base == h or base.startswith(h + "_")
                   for h in SCRAPE_HISTOGRAMS + (PROGRAM_HISTOGRAM,)):
                continue   # histogram series: rebuilt above
            if key not in sums and key not in maxes:
                order.append(key)
            if base == "serve_kv_utilization":
                maxes[key] = max(maxes.get(key, 0.0), v)
            else:
                sums[key] = sums.get(key, 0.0) + v
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) == 4:
                    types.setdefault(parts[2], parts[3])
    # bytes/token is a RATIO: summing per-replica quotients is
    # meaningless — re-derive it from the summed pool-bytes and
    # token-slots series so a mixed int8/fp fleet reports its true
    # blended quotient (the serve-side twin of ServeMetrics.merge)
    if "serve_kv_bytes_per_token" in sums:
        slots = sums.get("serve_kv_token_slots", 0.0)
        sums["serve_kv_bytes_per_token"] = (
            sums.get("serve_kv_pool_bytes", 0.0) / slots if slots
            else 0.0)
    L: list[str] = []
    typed: set = set()
    for key in order:
        base = key.split("{", 1)[0]
        if base in types and base not in typed:
            typed.add(base)
            L.append(f"# TYPE {base} {types[base]}")
        v = maxes.get(key, sums.get(key, 0.0))
        L.append(f"{key} {v:.17g}")
    for h, acc in hists.items():
        L.extend(acc.prom_lines(h))
    for i, prog in enumerate(sorted(prog_hists)):
        L.extend(prog_hists[prog].prom_lines(
            PROGRAM_HISTOGRAM, labels=f'program="{prog}"', typed=i == 0))
    return "\n".join(L) + "\n"


def assemble_fleet_trace(sources: list, path: str) -> Optional[str]:
    """Assemble a merged fleet Perfetto file for a SUBPROCESS fleet from
    the per-replica artifacts the supervisor already knows: ``sources``
    is ``[(name, dir_or_path), ...]`` — a replica's snapshot directory
    (every ``flight_*.json`` under it is read, life subdirectories
    included, plus any exported ``*.trace.json[.gz]`` engine traces) or
    one such file directly.

    Flight-file events render under the replica's own pid
    (``FLEET_REPLICA_PID_BASE + index``) with migration flow arrows
    linked across replicas, exactly like the in-process
    ``FleetController.export_perfetto``; already-rendered engine-trace
    documents pass through re-pid'd onto the same replica pid — the
    supervisor's ``--fleet-trace-out`` writes this at exit.  Returns
    the written path, or ``None`` when no source held any events."""
    import gzip
    import json

    srcs = []
    rendered: list[dict] = []
    for i, (name, src) in enumerate(sources):
        pid = FLEET_REPLICA_PID_BASE + i
        flight_paths, trace_paths = [], []
        if os.path.isdir(src):
            # newest flight per directory level only (the replica dir
            # itself + each life subdir): successive flushes of one
            # life carry OVERLAPPING ring tails, and rendering them all
            # would duplicate every span — same dedupe rule as the
            # in-process _trace_sources
            flight_paths = [p for p in
                            [latest_flight(src)]
                            + [latest_flight(d) for d in sorted(
                                glob.glob(os.path.join(src, "life*")))]
                            if p is not None]
            trace_paths = sorted(
                glob.glob(os.path.join(src, "**", "*.trace.json"),
                          recursive=True)
                + glob.glob(os.path.join(src, "**", "*.trace.json.gz"),
                            recursive=True))
        elif os.path.exists(src):
            if src.endswith((".trace.json", ".trace.json.gz")):
                trace_paths = [src]
            else:
                flight_paths = [src]
        evs: list = []
        for p in flight_paths:
            try:
                evs.extend(tuple(e)
                           for e in load_flight(p).get("events", ()))
            except (OSError, ValueError):
                continue
        for p in trace_paths:
            try:
                opener = gzip.open if p.endswith(".gz") else open
                with opener(p, "rt") as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            for ev in doc.get("traceEvents", ()):
                if "pid" in ev:
                    ev = {**ev, "pid": pid}
                rendered.append(ev)
        srcs.append((name, pid, evs))
    if not any(evs for _, _, evs in srcs) and not rendered:
        return None
    events: list[dict] = []
    tids: dict[int, dict] = {}
    for name, pid, evs in srcs:
        if evs:
            tids[pid] = {}
            events.extend(events_to_perfetto(
                evs, pid=pid,
                process_name=f"replica {name} (serve engine)",
                tids_out=tids[pid]))
    events.extend(rendered)
    events.extend(link_migration_flows(
        [(pid, evs) for _, pid, evs in srcs], tids))
    return write_trace({"traceEvents": events}, path)
