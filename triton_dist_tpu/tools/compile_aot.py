"""AOT compiler: export kernels to Python-free deployable artifacts.

Reference analog: ``tools/compile_aot.py`` — the ``aot_compile_spaces``
decorator registers per-kernel signature / grid / algo-info spaces (:61-115),
codegen emits C sources with one entry point per (kernel, algo_info) and a
conditions-based dispatcher over algo infos (:392-460); the companion C
runtime (``tools/runtime/triton_aot_runtime.cc``) dlopens the CUDA driver
and loads cubins so the generated library runs without Python.

TPU-native design: the unit of AOT is a **jitted function**, not a single
kernel binary — XLA owns fusion and scheduling, so the deployable artifact
is serialized StableHLO from ``jax.export``:

- ``aot_compile_spaces`` registers, per kernel entry point, a list of
  *signatures* (input ShapeDtype tuples — the analog of the reference's
  ``"*fp16, i32:16, %BLOCK_SIZE"`` strings) and a list of *algo infos*
  (config kwargs baked in at trace time — the analog of
  num_warps/num_stages/BLOCK_SIZE metaparameters).
- ``export_kernel`` traces + lowers every (signature x algo_info) variant
  and writes, per variant: the full ``jax.export`` bundle (``.jaxexport``,
  reloadable in Python), the raw StableHLO bytecode (``.mlir.bc``, consumed
  by the native runtime), and a ``manifest.json`` entry carrying the
  signature, the algo-info condition values, and the artifact paths.  A
  serialized ``CompileOptionsProto`` sits beside them so the native runtime
  can hand PJRT exactly what jit would.
- The native runtime (``csrc/aot_runtime``) dlopens a **PJRT plugin**
  (``GetPjrtApi`` — the TPU analog of dlopening ``libcuda.so``), compiles
  the StableHLO, and executes it — no Python anywhere in the process.
  Variant selection = first manifest entry whose algo-info values match the
  request, mirroring the reference's generated condition chain (:392-431).
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax import export as jax_export

# Registry of AOT-exportable kernels: name -> (fn, spaces)
_REGISTRY: dict[str, tuple[Callable, dict]] = {}

MANIFEST_NAME = "manifest.json"
COMPILE_OPTIONS_NAME = "compile_options.pb"


def aot_compile_spaces(spaces: dict):
    """Register a function's AOT export spaces (reference :61-115).

    ``spaces`` maps export name -> {"signature": [ [(shape, dtype), ...],
    ... ], "algo_infos": [ {kwarg: value, ...}, ... ]}.  Each signature is
    one input list; each algo info is a set of keyword overrides baked in
    at trace time.  ``algo_infos`` may instead be a callable
    ``platforms -> [algo, ...]`` resolved at export time, for kernels whose
    variant set depends on the export target (registration must never
    touch the backend — importing a kernels module has to stay free of
    ``jax.devices()`` so it can precede ``jax.distributed.initialize``).
    """
    assert isinstance(spaces, dict)
    for name, sp in spaces.items():
        assert "signature" in sp and "algo_infos" in sp, sp
        assert callable(sp["algo_infos"]) or len(sp["algo_infos"]) > 0, name

    def decor(fn):
        fn.__aot_compile_spaces__ = spaces
        for name, sp in spaces.items():
            _REGISTRY[name] = (fn, sp)
        return fn

    return decor


def registered_kernels() -> dict[str, tuple[Callable, dict]]:
    return dict(_REGISTRY)


def _sds(sig) -> list[jax.ShapeDtypeStruct]:
    return [jax.ShapeDtypeStruct(tuple(s), np.dtype(d)) for s, d in sig]


def _spec_of(avals) -> list[dict]:
    return [{"shape": list(a.shape), "dtype": str(np.dtype(a.dtype))}
            for a in jax.tree.leaves(avals)]


def _default_platforms() -> list[str]:
    # Single-platform export: Pallas kernels lower per-backend, so the
    # artifact targets the platform doing the exporting (export on TPU for
    # TPU serving; the CPU-mesh test story exports CPU artifacts).
    return [jax.devices()[0].platform]


def export_kernel(fn: Callable, name: str, out_dir: str,
                  signature: Sequence, algo_infos: Sequence[dict],
                  platforms: Sequence[str] | None = None) -> list[dict]:
    """Export every (signature x algo_info) variant of ``fn``.

    Returns the manifest entries written.  Artifacts per variant ``i``:
    ``{name}.v{i}.jaxexport`` (full bundle) and ``{name}.v{i}.mlir.bc``
    (StableHLO bytecode for the native runtime).
    """
    os.makedirs(out_dir, exist_ok=True)
    platforms = list(platforms or _default_platforms())
    if callable(algo_infos):
        algo_infos = list(algo_infos(platforms))
    entries = []
    i = 0
    for sig in signature:
        args = _sds(sig)
        for algo in algo_infos:
            traced = jax.jit(functools.partial(fn, **algo))
            exp = jax_export.export(traced, platforms=platforms)(*args)
            stem = f"{name}.v{i}"
            with open(os.path.join(out_dir, stem + ".jaxexport"), "wb") as f:
                f.write(exp.serialize())
            with open(os.path.join(out_dir, stem + ".mlir.bc"), "wb") as f:
                f.write(exp.mlir_module_serialized)
            entries.append({
                "kernel": name,
                "variant": i,
                "algo_info": dict(algo),
                "inputs": _spec_of(args),
                "outputs": _spec_of(exp.out_avals),
                "platforms": platforms,
                "jaxexport": stem + ".jaxexport",
                "stablehlo": stem + ".mlir.bc",
                "main": "main",
            })
            i += 1
    return entries


def _write_compile_options(out_dir: str) -> None:
    from jax._src import compiler

    opts = compiler.get_compile_options(num_replicas=1, num_partitions=1)
    with open(os.path.join(out_dir, COMPILE_OPTIONS_NAME), "wb") as f:
        f.write(opts.SerializeAsString())


def export_registered(out_dir: str,
                      kernels: Sequence[str] | None = None,
                      platforms: Sequence[str] | None = None) -> dict:
    """Export all (or the named) registered kernels + write the manifest.

    The reference's driver is ``scripts/gen_aot_code.sh`` over
    ``scripts/aot_kernels.txt``; ours is this function / the CLI below over
    the ``aot_compile_spaces`` registry.
    """
    os.makedirs(out_dir, exist_ok=True)
    names = list(kernels) if kernels else list(_REGISTRY)
    manifest: dict[str, Any] = {"compile_options": COMPILE_OPTIONS_NAME,
                                "kernels": {}}
    for name in names:
        fn, sp = _REGISTRY[name]
        entries = export_kernel(fn, name, out_dir, sp["signature"],
                                sp["algo_infos"], platforms)
        manifest["kernels"][name] = entries
    _write_compile_options(out_dir)
    with open(os.path.join(out_dir, MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=1)
    return manifest


def load_exported(out_dir: str, name: str, algo_info: dict | None = None,
                  inputs: Sequence | None = None):
    """Reload an exported kernel in Python; returns a callable.

    Variant selection mirrors the native runtime (and the reference's
    generated dispatcher, :392-431): first manifest entry whose algo_info
    entries all match ``algo_info`` AND whose input signature matches
    ``inputs`` ([(shape, dtype), ...]) when given.
    """
    with open(os.path.join(out_dir, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    entries = manifest["kernels"][name]
    want_inputs = None
    if inputs is not None:
        want_inputs = [{"shape": list(s), "dtype": str(np.dtype(d))}
                       for s, d in inputs]
    chosen = None
    for e in entries:
        algo_ok = algo_info is None or all(
            e["algo_info"].get(k) == v for k, v in algo_info.items())
        sig_ok = want_inputs is None or e["inputs"] == want_inputs
        if algo_ok and sig_ok:
            chosen = e
            break
    if chosen is None:
        raise KeyError(f"{name}: no variant matches algo_info {algo_info} "
                       f"inputs {inputs}")
    with open(os.path.join(out_dir, chosen["jaxexport"]), "rb") as f:
        exp = jax_export.deserialize(f.read())
    return exp.call


def main(argv: Sequence[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="AOT-export registered kernels (gen_aot_code.sh analog)")
    p.add_argument("--out", required=True)
    p.add_argument("--kernels", nargs="*", default=None)
    p.add_argument("--platforms", nargs="*", default=None)
    args = p.parse_args(argv)
    # Importing the kernel library populates the registry.
    import triton_dist_tpu.kernels.flash_decode  # noqa: F401
    import triton_dist_tpu.kernels.gemm  # noqa: F401

    manifest = export_registered(args.out, args.kernels, args.platforms)
    n = sum(len(v) for v in manifest["kernels"].values())
    print(f"exported {len(manifest['kernels'])} kernels, {n} variants -> "
          f"{args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
