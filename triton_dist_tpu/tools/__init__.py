"""Deployment tooling: AOT export + native runtime glue.

Reference analog: ``python/triton_dist/tools/`` (compile_aot.py, the AOT C
runtime, and the generated libtriton_distributed_kernel).
"""

from triton_dist_tpu.tools.compile_aot import (  # noqa: F401
    aot_compile_spaces,
    export_kernel,
    export_registered,
    load_exported,
)
