"""Contextual autotuner for whole multi-kernel distributed ops.

Reference analog: ``python/triton_dist/autotuner.py`` — ``contextual_autotune``
monkey-patches ``Autotuner.run`` so that a *whole op* (which may invoke
several autotuned Triton kernels, each needing the op's surrounding context:
symm buffers, barriers, streams) is re-executed until every inner autotuner's
config sweep completes, one config-iteration per outer call (:105-127,
:160-245); in ``is_dist`` mode timings are all-reduced (MAX) so every rank
picks the same config (:225-231); per-rank logs go to ``.autotune_logs/``.

TPU-native design: same two-level protocol, with the measurement layer
re-based on JAX:

- A config is a plain dict of keyword overrides (``{"bm": 256, "bn": 512}``)
  merged into the wrapped function's kwargs — our Pallas kernels take block
  sizes as kwargs, not compile-time metaparameters.
- Timing is host-side ``perf_counter`` around ``jax.block_until_ready`` (no
  CUDA events on TPU; dispatch is async the same way, so the block is the
  fence).
- The lockstep property the reference gets from one-bench-iteration-per-
  outer-call is preserved: inside a ``contextual_autotune`` region each call
  of the outer thunk advances every unfinished inner tuner by exactly one
  (config, iteration) step, so multi-process shard_map collectives stay in
  step across ranks (same config order is guaranteed because configs are a
  static list and failures — Mosaic compile errors — are deterministic).
- Distributed agreement: after a tuner's sweep completes, per-config mean
  times are all-reduced with MAX across processes via a one-element global
  sum (``multihost_utils``) so every process selects the same config.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Sequence

import jax
import numpy as np

__all__ = ["autotune", "contextual_autotune", "Config"]


def Config(**kwargs) -> dict:
    """A tunable config: keyword overrides for the wrapped function.

    (Reference: ``triton.Config``; ours is a plain dict since Pallas block
    sizes are ordinary kwargs.)
    """
    return dict(kwargs)


def _allreduce_max(times: Sequence[float]) -> list[float]:
    """MAX-allreduce per-config times across processes (identity single-host).

    Reference: autotuner.py:225-231 (torch.distributed.all_reduce MAX).
    """
    if jax.process_count() == 1:
        return list(times)
    from jax.experimental import multihost_utils

    arr = np.asarray(times, np.float64)
    gathered = multihost_utils.process_allgather(arr)  # [n_proc, n_cfg]
    return np.max(gathered, axis=0).tolist()


class _TuningState:
    """Per-(tuner, key) sweep state. Reference: ``_TuningContext``."""

    def __init__(self, configs: list[dict]):
        self.configs = configs
        self.cfg_i = 0
        self.iter_j = 0
        self.cur_times: list[float] = []
        self.okay: list[tuple[int, dict]] = []
        self.times: list[float] = []
        self.finished = False


class ContextualAutotuner:
    """Callable wrapping a whole op; active instance gates inner tuners."""

    _INSTANCE: "ContextualAutotuner | None" = None

    def __init__(self, fn: Callable, is_dist: bool = False, n_repeat: int = 5,
                 n_warmup: int = 3, log_dir: str = ".autotune_logs"):
        self.fn = fn
        self.is_dist = is_dist
        self.n_repeat = n_repeat
        self.n_warmup = n_warmup
        self.log_dir = log_dir
        self._log_file = None
        # (owner AutotunedFunction, cache key, state) per active sweep.
        self._states: list[tuple] = []

    def log(self, *args):
        if self._log_file is None:
            os.makedirs(self.log_dir, exist_ok=True)
            rank = jax.process_index()
            self._log_file = open(
                os.path.join(self.log_dir, f"rank-{rank}.log"), "a")
        print(f"[rank-{jax.process_index()}]", *args, file=self._log_file,
              flush=True)

    def __call__(self, *args, **kwargs):
        if ContextualAutotuner._INSTANCE is not None:  # nested: run plainly
            return self.fn(*args, **kwargs)
        ContextualAutotuner._INSTANCE = self
        self._states = []
        try:
            ret = self.fn(*args, **kwargs)  # discovers inner tuners
            if not self._states:
                return ret  # nothing to tune (all cached already)
            while not all(st.finished for _, _, st in self._states):
                ret = self.fn(*args, **kwargs)
            # The sweep's last call ran whatever config came last, not the
            # winner; one more call hits every inner tuner's best-config
            # cache so the returned value matches the selected configs.
            return self.fn(*args, **kwargs)
        finally:
            # Purge unfinished sweeps from their owners so an aborted
            # region (kernel bug, no-valid-config) can't poison the next
            # one with stale per-key state.
            for owner, key, st in self._states:
                if not st.finished:
                    owner._states.pop(key, None)
            ContextualAutotuner._INSTANCE = None
            self._states = []


def contextual_autotune(is_dist: bool = False, n_repeat: int = 5,
                        n_warmup: int = 3):
    """Decorator: tune all inner ``@autotune`` functions within one op.

    Reference: autotuner.py:96-101.
    """

    def decor(fn):
        return ContextualAutotuner(fn, is_dist=is_dist, n_repeat=n_repeat,
                                   n_warmup=n_warmup)

    return decor


class AutotunedFunction:
    """``@autotune``-wrapped function with a per-key best-config cache."""

    def __init__(self, fn: Callable, configs: Sequence[dict],
                 key: Sequence[str] = (), prune: Callable | None = None,
                 measure: Callable | None = None):
        self.fn = fn
        self.configs = [dict(c) for c in configs]
        self.key_names = tuple(key)
        self.prune = prune
        self.measure = measure
        self.cache: dict[tuple, dict] = {}
        self._states: dict[tuple, _TuningState] = {}
        self.__name__ = getattr(fn, "__name__", "autotuned")

    # -- key: named kwargs + shape/dtype of array args + every scalar kwarg
    # (autotuner.py:173-183; scalar kwargs matter because e.g. interpret=True
    # timings must never be reused for hardware calls)
    def _key(self, args, kwargs) -> tuple:
        parts: list[Any] = [kwargs.get(k) for k in self.key_names]
        for a in args:
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                parts.append((tuple(a.shape), str(a.dtype)))
        for k in sorted(kwargs):
            if k in self.key_names:
                continue
            v = kwargs[k]
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                parts.append((tuple(v.shape), str(v.dtype)))
            else:
                parts.append((k, str(v)))
        return tuple(parts)

    def _configs_for(self, args, kwargs) -> list[dict]:
        if self.prune is None:
            return list(self.configs)
        pruned = self.prune(self.configs, args, kwargs)
        return list(pruned) if pruned else list(self.configs)

    def _run(self, args, kwargs, config):
        return self.fn(*args, **{**kwargs, **config})

    def _timed(self, args, kwargs, config) -> tuple[Any, float]:
        """(result, milliseconds) for one config invocation.

        The default fence is ``block_until_ready`` — correct on directly
        attached TPUs (the deployment case).  On the axon TUNNEL it is
        useless twice over: the fence returns early AND single-call times
        are swamped by the ~100 ms RTT with tens-of-ms jitter — pass a
        custom ``measure``
        (e.g. a dependent-chain protocol, scripts/autotune_onchip.py /
        scripts/benchlib.py) to tune through the tunnel.
        """
        if self.measure is not None:
            return self.measure(self.fn, args, kwargs, config)
        t0 = time.perf_counter()
        ret = self._run(args, kwargs, config)
        jax.block_until_ready(ret)
        return ret, (time.perf_counter() - t0) * 1e3

    def __call__(self, *args, **kwargs):
        if len(self.configs) <= 1:
            cfg = self.configs[0] if self.configs else {}
            return self._run(args, kwargs, cfg)
        key = self._key(args, kwargs)
        best = self.cache.get(key)
        if best is not None:
            return self._run(args, kwargs, best)
        tuner = ContextualAutotuner._INSTANCE
        if tuner is None:
            return self._tune_eager(key, args, kwargs)
        return self._tune_step(tuner, key, args, kwargs)

    # -- eager path: full sweep in one call (plain Autotuner.run analog).
    # No cross-process agreement here: eager calls need not be collective
    # (the contextual path with is_dist=True is the lockstep one).
    def _tune_eager(self, key, args, kwargs):
        configs = self._configs_for(args, kwargs)
        okay, times = [], []
        last = None
        last_exc = None
        for i, cfg in enumerate(configs):
            try:
                if self.measure is not None:
                    # Custom hooks own their warmup/compile handling; a
                    # second full protocol run would only replay identical
                    # inputs (which a content-caching backend elides).
                    last, ms = self._timed(args, kwargs, cfg)
                else:
                    for _ in range(2):  # warmup (compile) + 1 measure
                        last, ms = self._timed(args, kwargs, cfg)
                okay.append((i, cfg))
                times.append(ms)
            except Exception as e:  # bad config; keep cause for diagnosis
                last_exc = e
                continue
        if not okay:
            raise RuntimeError(
                f"{self.__name__}: no valid config among {configs}"
            ) from last_exc
        (_, best), _ = min(zip(okay, times), key=lambda t: t[-1])
        self.cache[key] = best
        return self._run(args, kwargs, best) if last is None else last

    # -- contextual path: one (config, iter) step per outer-thunk call
    def _tune_step(self, tuner: ContextualAutotuner, key, args, kwargs):
        st = self._states.get(key)
        if st is None:
            st = self._states[key] = _TuningState(
                self._configs_for(args, kwargs))
            tuner._states.append((self, key, st))

        n_iters = tuner.n_warmup + tuner.n_repeat
        while st.cfg_i < len(st.configs):
            cfg = st.configs[st.cfg_i]
            try:
                ret, ms = self._timed(args, kwargs, cfg)
                if ret is None:
                    # Measure hooks may time a surrogate (e.g. a chain) and
                    # return no result; the surrounding contextual op still
                    # needs a real output this iteration.
                    ret = self._run(args, kwargs, cfg)
            except Exception as e:  # bad config (e.g. Mosaic tiling error)
                tuner.log(f"func: {self.__name__} | config {st.cfg_i} "
                          f"{cfg} | error: {e}")
                self._advance_config(tuner, key, ok=False)
                if st.finished:
                    return self._run(args, kwargs, self.cache[key])
                continue
            if st.iter_j >= tuner.n_warmup:
                st.cur_times.append(ms)
            tuner.log(f"func: {self.__name__} | config {st.cfg_i} {cfg} | "
                      f"iter {st.iter_j} | {ms:.4f} ms")
            st.iter_j += 1
            if st.iter_j >= n_iters:
                self._advance_config(tuner, key, ok=True)
            return ret
        raise AssertionError("unreachable")

    def _advance_config(self, tuner, key, ok: bool):
        st = self._states[key]
        if ok:
            st.okay.append((st.cfg_i, st.configs[st.cfg_i]))
            st.times.append(float(np.mean(st.cur_times)))
        st.cur_times = []
        st.iter_j = 0
        st.cfg_i += 1
        if st.cfg_i < len(st.configs):
            return
        # sweep complete: agree on the best config
        if not st.okay:
            raise RuntimeError(
                f"{self.__name__}: no valid config among {st.configs}")
        times = _allreduce_max(st.times) if tuner.is_dist else st.times
        (best_i, best), best_ms = min(
            zip(st.okay, times), key=lambda t: t[-1])
        tuner.log(f"func: {self.__name__} | best-config-id: {best_i} | "
                  f"best-config: {best} | best-latency: {best_ms:.4f} ms")
        self.cache[key] = best
        st.finished = True
        del self._states[key]

    @property
    def best_config(self) -> dict | None:
        """Most recently selected config (None before any tuning)."""
        return next(iter(reversed(self.cache.values())), None)


def autotune(configs: Sequence[dict], key: Sequence[str] = (),
             prune: Callable | None = None, measure: Callable | None = None):
    """Decorator marking a function tunable over ``configs``.

    Reference: ``triton.autotune``; config kwargs are merged into the call's
    kwargs, later tuners pick per-``key`` cached bests.  ``prune(configs,
    args, kwargs)`` may drop redundant configs per call (reference:
    ``prune_configs_by``) — e.g. dedupe block sizes that clamp identically
    for a small shape.  ``measure(fn, args, kwargs, config) -> (ret, ms)``
    overrides the timing protocol (see ``_timed`` for when you must).
    """

    def decor(fn):
        return AutotunedFunction(fn, configs, key, prune, measure)

    return decor
