"""Kernel-layer observability: the overlap scoreboard.

The overlapped kernels (``ag_gemm``, ``gemm_rs``, ``moe_reduce_rs``, the
SP flash-decode combine) have been observable only as one end-to-end
bench number — ROADMAP #5b spent three PRs arguing whether a
99.8%→70.9% utilization slide was real precisely because nothing
attributed *where inside the kernel* time goes.  This module makes the
compute/communication overlap — the paper's headline claim — a
measured, attributable artifact:

- **Three whole-kernel legs.**  The FUSED kernel, its COMPUTE-ONLY leg
  (the same per-device MXU work with the ring deleted), and its
  COMM-ONLY leg (the same wire bytes with the MXU work deleted), each
  host-timed as its own dispatch.  ``overlap_efficiency =
  (T_compute + T_comm) / T_fused``: 1.0 means the fused kernel costs
  the serial sum (no overlap), values toward ``(Tc + Tm)/max(Tc, Tm)``
  mean the shorter phase fully hides under the longer one.

- **Phase-sliced per-ring-step replay.**  The ring schedule replayed
  one phase at a time — step s's compute tile and step s's wire
  transfer each dispatched SEPARATELY under ``profiling.annotate``
  spans (name#flops#bytes land in the device trace on hardware) with
  host timing.  The slices reconstruct a per-rank per-step
  compute-vs-comm timeline, name the critical phase per step
  (``max(compute_ms, comm_ms)`` is what a bulk-synchronous ring step
  costs), and pair every measured slice with its
  ``kernels/perf_model`` prediction — the roofline-vs-measured table
  that turns the next perf-trajectory dispute into reading a report.

- **Artifacts.**  :meth:`OverlapReport.to_dict`/:meth:`save` emit the
  JSON overlap report; :meth:`OverlapReport.export_profile` drops ONE
  reconstructed Perfetto track per rank (compute and comm threads
  under :data:`KPROBE_PID`) where ``profiling.merge_rank_traces``
  globs, so the scoreboard merges into the same ui.perfetto.dev file
  as the device, engine, and fleet timelines.  ``scripts/
  kernel_report.py`` is the CLI driver.

Caveat the report itself records: on a non-TPU backend the fused
kernels take their XLA fallbacks and the perf-model predictions use
TPU rate tables, so absolute numbers are structural/informational —
the report's value there is the schedule decomposition and the
artifact plumbing, which are exactly what runs on hardware.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import statistics
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.kernels import perf_model
from triton_dist_tpu.runtime import profiling

#: pid the per-rank scoreboard tracks claim in exported Chrome traces —
#: below the Linux pid cap (4194304) so ``merge_rank_traces``'s
#: per-rank re-namespacing stays injective, and distinct from the
#: serving plane's ``serve.trace.ENGINE_PID``/``FLEET_PID`` so one
#: merged file holds device + engine + fleet + kernel tracks.
KPROBE_PID = 3_999_997

#: Kernels the scoreboard covers (scripts/kernel_report.py --kernel).
KERNELS = ("ag_gemm", "gemm_rs", "moe_reduce_rs", "sp_decode")


# ---------------------------------------------------------------------------
# Timing
# ---------------------------------------------------------------------------


def _time_ms(fn: Callable, args: tuple, *, label: str,
             flops: Optional[int] = None,
             bytes_accessed: Optional[int] = None,
             trials: int = 3) -> float:
    """Median wall milliseconds of ``fn(*args)`` over ``trials`` after
    one untimed warmup call, each trial under a ``profiling.annotate``
    span (the launch-metadata hook: on hardware the span + name/flops/
    bytes land in the device trace a ``group_profile`` capture holds).
    ``block_until_ready`` bounds every trial — host-dispatch time alone
    would measure nothing on an async backend."""
    jax.block_until_ready(fn(*args))   # warm: compile outside the clock
    ts = []
    for _ in range(max(1, trials)):
        with profiling.annotate(label, flops=flops,
                                bytes_accessed=bytes_accessed):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            ts.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(ts)


def _sjit(body, mesh, in_specs, out_specs, **opts):
    """jit(shard_map(partial(body, **opts))) — the probe legs are built
    once per report, so no process-wide memo is needed."""
    return jax.jit(jax.shard_map(
        functools.partial(body, **opts), mesh=mesh, in_specs=in_specs,
        out_specs=out_specs, check_vma=False))


# ---------------------------------------------------------------------------
# Report structure
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StepSlice:
    """One phase of one ring step, dispatched standalone."""

    step: int
    phase: str                # "compute" | "comm"
    measured_ms: float
    predicted_ms: float       # kernels/perf_model roofline
    desc: str = ""
    #: rank -> segment/slot consumed at this step (ring schedules
    #: consume a different slot per rank; [] when not slot-addressed)
    slots: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class OverlapReport:
    """The scoreboard for ONE overlapped kernel at one shape."""

    kernel: str
    world: int
    shape: dict
    dtype: str
    fused_ms: float
    compute_ms: float         # compute-only leg (whole kernel)
    comm_ms: float            # comm-only leg (whole kernel)
    slices: list              # list[StepSlice]
    backend: str = ""
    trials: int = 3

    # -- derived ----------------------------------------------------------

    @property
    def overlap_efficiency(self) -> float:
        """``(T_compute + T_comm) / T_fused`` — 1.0 = no overlap (the
        fused kernel costs the serial sum), ``(Tc+Tm)/max(Tc,Tm)`` =
        perfect overlap (the shorter phase is free)."""
        if self.fused_ms <= 0:
            return 0.0
        return (self.compute_ms + self.comm_ms) / self.fused_ms

    @property
    def sliced_serial_ms(self) -> float:
        return sum(s.measured_ms for s in self.slices)

    def _per_step(self) -> dict:
        steps: dict[int, dict] = {}
        for s in self.slices:
            steps.setdefault(s.step, {})[s.phase] = s
        return steps

    @property
    def sliced_critical_ms(self) -> float:
        """Ideal fully-overlapped time of the replayed schedule: each
        bulk-synchronous ring step costs its slower phase."""
        return sum(max(ph.measured_ms for ph in by.values())
                   for by in self._per_step().values())

    def critical_path(self) -> dict:
        """Which phase the replayed schedule is bound by, step-wise:
        each step's critical phase is the slower one; the fractions say
        where an optimization dollar goes."""
        comp = comm = 0.0
        for by in self._per_step().values():
            crit = max(by.values(), key=lambda s: s.measured_ms)
            if crit.phase == "compute":
                comp += crit.measured_ms
            else:
                comm += crit.measured_ms
        total = comp + comm
        return {
            "compute_ms": round(comp, 4),
            "comm_ms": round(comm, 4),
            "compute_frac": round(comp / total, 4) if total else 0.0,
            "bound": "compute" if comp >= comm else "comm",
        }

    def model(self) -> dict:
        """The roofline-vs-measured table's totals: perf_model
        predictions summed per phase, the predicted fused time (sum of
        per-step maxima — the overlapped schedule's model), and
        ``model_vs_measured`` = predicted fused / measured fused (1.0 =
        the kernel runs at the model's speed of light; informational on
        non-TPU backends, where the model's rate tables do not describe
        the host)."""
        pred_comp = sum(s.predicted_ms for s in self.slices
                        if s.phase == "compute")
        pred_comm = sum(s.predicted_ms for s in self.slices
                        if s.phase == "comm")
        pred_fused = sum(
            max(ph.predicted_ms for ph in by.values())
            for by in self._per_step().values())
        return {
            "predicted_compute_ms": round(pred_comp, 4),
            "predicted_comm_ms": round(pred_comm, 4),
            "predicted_fused_ms": round(pred_fused, 4),
            "model_vs_measured": round(pred_fused / self.fused_ms, 4)
            if self.fused_ms > 0 else 0.0,
        }

    # -- artifacts --------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "kernel": self.kernel,
            "world": self.world,
            "shape": self.shape,
            "dtype": self.dtype,
            "backend": self.backend,
            "trials": self.trials,
            "timings_ms": {
                "fused": round(self.fused_ms, 4),
                "compute_only": round(self.compute_ms, 4),
                "comm_only": round(self.comm_ms, 4),
                "sliced_serial": round(self.sliced_serial_ms, 4),
                "sliced_critical": round(self.sliced_critical_ms, 4),
            },
            "overlap_efficiency": round(self.overlap_efficiency, 4),
            "critical_path": self.critical_path(),
            "model": self.model(),
            "steps": [
                {
                    "step": s.step, "phase": s.phase,
                    "measured_ms": round(s.measured_ms, 4),
                    "predicted_ms": round(s.predicted_ms, 4),
                    "desc": s.desc,
                    "slots": s.slots,
                }
                for s in sorted(self.slices,
                                key=lambda s: (s.step, s.phase))
            ],
        }

    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
        return path

    def perfetto_events(self, rank: int) -> list[dict]:
        """Rank ``rank``'s reconstructed timeline as Chrome-trace
        events: a compute thread and a comm thread under
        :data:`KPROBE_PID`, step phases laid out bulk-synchronously
        (step boundaries at the running sum of per-step maxima — the
        ring is bulk-synchronous, so that IS the step cadence) with
        each span named by step, this rank's consumed slot, and the
        predicted-vs-measured pair."""
        tids = {"compute": 1, "comm": 2}
        trace: list[dict] = [
            {"ph": "M", "pid": KPROBE_PID, "tid": 0,
             "name": "process_name",
             "args": {"name": f"kernel probe ({self.kernel})"}},
        ]
        for phase, tid in tids.items():
            trace.append({"ph": "M", "pid": KPROBE_PID, "tid": tid,
                          "name": "thread_name",
                          "args": {"name": f"{self.kernel}.{phase}"}})
        t = 0.0
        for step, by in sorted(self._per_step().items()):
            for phase, s in sorted(by.items()):
                slot = (s.slots[rank]
                        if 0 <= rank < len(s.slots) else None)
                name = f"{self.kernel} step{step}"
                if slot is not None:
                    name += f" slot{slot}"
                trace.append({
                    "ph": "X", "pid": KPROBE_PID, "tid": tids[phase],
                    "cat": "kprobe", "name": name,
                    "ts": t * 1e3,            # ms -> us
                    "dur": max(s.measured_ms * 1e3, 1.0),
                    "args": {"phase": phase, "desc": s.desc,
                             "measured_ms": round(s.measured_ms, 4),
                             "predicted_ms": round(s.predicted_ms, 4)},
                })
            t += max(ph.measured_ms for ph in by.values())
        return trace

    def export_profile(self, job_dir: str) -> list[str]:
        """One reconstructed track per rank, dropped where
        ``profiling.merge_rank_traces`` globs
        (``{job_dir}/rank{r}/kprobe_{kernel}.trace.json.gz``) — run a
        ``group_profile`` capture and/or a
        ``FlightRecorder.export_profile`` into the same ``job_dir``,
        then merge: ONE ui.perfetto.dev file holds device + engine +
        kernel-probe timelines (docs/observability.md)."""
        from triton_dist_tpu.serve.trace import write_trace

        paths = []
        for r in range(self.world):
            out = os.path.join(job_dir, f"rank{r}",
                               f"kprobe_{self.kernel}.trace.json.gz")
            paths.append(write_trace(
                {"traceEvents": self.perfetto_events(r)}, out))
        return paths


# ---------------------------------------------------------------------------
# Probe bodies (module level: shard_map bodies)
# ---------------------------------------------------------------------------


def _dot_leg(a, b, *, out_dtype):
    return jnp.dot(a, b,
                   preferred_element_type=jnp.float32).astype(out_dtype)


def _ag_leg(a_loc, *, axis):
    return jax.lax.all_gather(a_loc, axis, axis=0, tiled=True)


def _ring_fwd_leg(a_loc, *, axis, world):
    perm = [(i, (i + 1) % world) for i in range(world)]
    return jax.lax.ppermute(a_loc, axis, perm=perm)


def _own_rows_leg(a_loc, b_loc, *, axis, out_dtype):
    """Full local partial GEMM, then this rank's row band (the RS
    compute leg: all the MXU work, none of the wire)."""
    part = jnp.dot(a_loc, b_loc, preferred_element_type=jnp.float32)
    me = jax.lax.axis_index(axis)
    blk = part.shape[0] // jax.lax.axis_size(axis)
    return jax.lax.dynamic_slice_in_dim(
        part, me * blk, blk, axis=0).astype(out_dtype)


def _rs_leg(p_loc, *, axis):
    """Reduce-scatter of a per-rank partial (fed as [world, M, N]
    sharded on the leading axis so every rank's values are distinct)."""
    return jax.lax.psum_scatter(p_loc[0], axis, scatter_dimension=0,
                                tiled=True)


def _chunk_shift_add_leg(c_loc, *, axis, world):
    """One RS ring step: ship a chunk to the neighbor and add — the
    per-step comm slice."""
    perm = [(i, (i + 1) % world) for i in range(world)]
    return c_loc + jax.lax.ppermute(c_loc, axis, perm=perm)


def _local_decode_leg(q, k_loc, v_loc, kv_lens, *, axis, impl,
                      interpret):
    """SP flash-decode compute slice: each rank's local split-KV
    partials, NO combine (partials stack on a fresh leading axis so
    per-rank values assemble honestly)."""
    from triton_dist_tpu.kernels.flash_decode import gqa_decode_shard

    s_loc = k_loc.shape[2]
    me = jax.lax.axis_index(axis)
    local_lens = jnp.clip((kv_lens - me * s_loc).astype(jnp.int32),
                          0, s_loc)
    out, lse = gqa_decode_shard(q, k_loc, v_loc, local_lens, impl=impl,
                                interpret=interpret)
    return out[None], lse[None]


def _sp_combine_leg(out_all, lse_all, *, axis, impl, interpret):
    """SP flash-decode comm slice: the inter-rank LSE combine alone, on
    per-rank partials fed via a [world, ...] leading axis."""
    from triton_dist_tpu.kernels.flash_decode import _combine_across_ranks

    return _combine_across_ranks(out_all[0].astype(jnp.float32),
                                 lse_all[0].astype(jnp.float32),
                                 out_all.dtype, axis=axis, impl=impl,
                                 interpret=interpret)


def _sp_fused_leg(q, k_loc, v_loc, kv_lens, *, axis, impl, interpret):
    from triton_dist_tpu.kernels.flash_decode import sp_gqa_decode_shard

    return sp_gqa_decode_shard(q, k_loc, v_loc, kv_lens, axis=axis,
                               impl=impl, interpret=interpret)


def _group_gemm_leg(h_loc, w_loc, te, *, axis, block_m, out_dtype):
    """MoE compute leg: the grouped GEMM over every sorted row against
    the local F shard, then this rank's own segment band (all the MXU
    work, none of the ring)."""
    from triton_dist_tpu.kernels.group_gemm import group_gemm_xla

    ys = group_gemm_xla(h_loc, w_loc, te, block_m)
    me = jax.lax.axis_index(axis)
    blk = ys.shape[0] // jax.lax.axis_size(axis)
    return jax.lax.dynamic_slice_in_dim(
        ys, me * blk, blk, axis=0).astype(out_dtype)


def _seg_dot_leg(h_seg, w_loc, *, out_dtype):
    """One ring step's compute tile: the dense-equivalent segment GEMM
    (the grouped kernel's expert mixing happens inside the fused
    program; the tile's MXU work — rows x f_loc x D — is identical, and
    the perf model predicts exactly that)."""
    return jnp.dot(h_seg, w_loc,
                   preferred_element_type=jnp.float32).astype(out_dtype)


# ---------------------------------------------------------------------------
# Probes
# ---------------------------------------------------------------------------


def probe_ag_gemm(mesh: Mesh, *, axis: str = "tp", M: int = 512,
                  K: int = 256, n_loc: int = 128, dtype=jnp.float32,
                  impl: str = "auto", trials: int = 3,
                  seed: int = 0) -> OverlapReport:
    """Scoreboard for the flagship overlapped AllGather-GEMM.

    Legs: fused = ``ag_gemm`` (ring producer + persistent MXU
    pipeline); compute-only = the gathered [M, K] x [K, n_loc] GEMM
    with the ring deleted; comm-only = the ring allgather of A with the
    GEMM deleted.  Sliced replay: step s computes one [m_loc, K] x
    [K, n_loc] segment GEMM (rank r consumes slot ``(r - s) % world`` —
    the arrival-order schedule) and, for s < world-1, ring-forwards one
    [m_loc, K] segment.
    """
    from triton_dist_tpu.kernels.allgather_gemm import (
        ag_gemm, create_ag_gemm_context)

    world = int(mesh.shape[axis])
    if M % (world or 1):
        raise ValueError(f"M ({M}) must divide by world ({world})")
    m_loc = M // world
    N = n_loc * world
    el = jnp.dtype(dtype).itemsize
    k0, k1 = jax.random.split(jax.random.key(seed))
    a = (jax.random.normal(k0, (M, K), jnp.float32) * 0.1).astype(dtype)
    b = (jax.random.normal(k1, (K, N), jnp.float32) * 0.1).astype(dtype)

    ctx = create_ag_gemm_context(mesh, axis=axis, impl=impl)
    fused_ms = _time_ms(lambda: ag_gemm(a, b, ctx), (), trials=trials,
                        label="kprobe.ag_gemm.fused",
                        flops=2 * M * n_loc * K,
                        bytes_accessed=(M * K + K * n_loc
                                        + M * n_loc) * el)

    comp_fn = _sjit(_dot_leg, mesh, (P(), P(None, axis)),
                    P(None, axis), out_dtype=dtype)
    compute_ms = _time_ms(comp_fn, (a, b), trials=trials,
                          label="kprobe.ag_gemm.compute_only",
                          flops=2 * M * n_loc * K)
    comm_fn = _sjit(_ag_leg, mesh, (P(axis, None),), P(), axis=axis)
    comm_ms = (_time_ms(comm_fn, (a,), trials=trials,
                        label="kprobe.ag_gemm.comm_only",
                        bytes_accessed=m_loc * K * el * (world - 1))
               if world > 1 else 0.0)

    # each rank computes its held segment against its local B columns;
    # assembly keeps every rank's own [m_loc, n_loc] row band
    seg_fn = _sjit(_dot_leg, mesh, (P(axis, None), P(None, axis)),
                   P(axis, None), out_dtype=dtype)
    fwd_fn = _sjit(_ring_fwd_leg, mesh, (P(axis, None),),
                   P(axis, None), axis=axis, world=world)
    pred_comp = perf_model.estimate_gemm_sol_time_ms(
        m_loc, n_loc, K, dtype)
    pred_comm = (perf_model.estimate_allgather_time_ms(
        m_loc * K * el, world) / (world - 1) if world > 1 else 0.0)
    # arrival-order slot map — the shared contract with the static
    # schedule checker (analysis/comm_schedule.py), which also proves
    # its per-step bijectivity at every world size
    from triton_dist_tpu.analysis.comm_schedule import arrival_slots

    slices = []
    for s in range(world):
        slots = arrival_slots(s, world)
        slices.append(StepSlice(
            step=s, phase="compute",
            measured_ms=_time_ms(
                seg_fn, (a, b), trials=trials,
                label=f"kprobe.ag_gemm.step{s}.compute",
                flops=2 * m_loc * n_loc * K),
            predicted_ms=pred_comp,
            desc=f"[{m_loc}, {K}] x [{K}, {n_loc}] segment GEMM",
            slots=slots))
        if s < world - 1:
            slices.append(StepSlice(
                step=s, phase="comm",
                measured_ms=_time_ms(
                    fwd_fn, (a,), trials=trials,
                    label=f"kprobe.ag_gemm.step{s}.comm",
                    bytes_accessed=m_loc * K * el),
                predicted_ms=pred_comm,
                desc=f"ring-forward [{m_loc}, {K}] segment",
                slots=slots))
    return OverlapReport(
        kernel="ag_gemm", world=world,
        shape={"M": M, "K": K, "N": N, "n_loc": n_loc},
        dtype=str(jnp.dtype(dtype)), fused_ms=fused_ms,
        compute_ms=compute_ms, comm_ms=comm_ms, slices=slices,
        backend=jax.default_backend(), trials=trials)


def probe_gemm_rs(mesh: Mesh, *, axis: str = "tp", M: int = 256,
                  K: int = 256, N: int = 256, dtype=jnp.float32,
                  impl: str = "auto", trials: int = 3,
                  seed: int = 0) -> OverlapReport:
    """Scoreboard for the overlapped GEMM-ReduceScatter: fused =
    ``gemm_rs``; compute-only = the local [M, k_loc] x [k_loc, N]
    partial GEMM (own row band kept); comm-only = the ring
    reduce-scatter of a per-rank partial; sliced replay: one
    [m_blk, k_loc] x [k_loc, N] chunk GEMM per step + one
    [m_blk, N] chunk ship-and-add per ring hop."""
    from triton_dist_tpu.kernels.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)

    world = int(mesh.shape[axis])
    if M % (world or 1) or K % (world or 1):
        raise ValueError(f"M ({M}) and K ({K}) must divide by world "
                         f"({world})")
    k_loc = K // world
    m_blk = M // world
    el = jnp.dtype(dtype).itemsize
    k0, k1, k2 = jax.random.split(jax.random.key(seed), 3)
    a = (jax.random.normal(k0, (M, K), jnp.float32) * 0.1).astype(dtype)
    b = (jax.random.normal(k1, (K, N), jnp.float32) * 0.1).astype(dtype)
    parts = (jax.random.normal(k2, (world, M, N), jnp.float32)
             * 0.1).astype(dtype)
    chunk = parts[0]   # one rank-shaped partial, chunk-shipped per step

    ctx = create_gemm_rs_context(mesh, axis=axis, impl=impl)
    fused_ms = _time_ms(lambda: gemm_rs(a, b, ctx), (), trials=trials,
                        label="kprobe.gemm_rs.fused",
                        flops=2 * M * N * k_loc,
                        bytes_accessed=(M * k_loc + k_loc * N
                                        + M * N) * el)

    comp_fn = _sjit(_own_rows_leg, mesh, (P(None, axis), P(axis, None)),
                    P(axis, None), axis=axis, out_dtype=dtype)
    compute_ms = _time_ms(comp_fn, (a, b), trials=trials,
                          label="kprobe.gemm_rs.compute_only",
                          flops=2 * M * N * k_loc)
    comm_fn = _sjit(_rs_leg, mesh, (P(axis, None, None),),
                    P(axis, None), axis=axis)
    comm_ms = (_time_ms(comm_fn, (parts,), trials=trials,
                        label="kprobe.gemm_rs.comm_only",
                        bytes_accessed=M * N * el)
               if world > 1 else 0.0)

    # ONE ring step's compute tile, dispatched standalone: per rank the
    # [m_blk, k_loc] row band of A against the local [k_loc, N] shard
    # (each rank's [m_blk, N] partial band assembles distinctly)
    seg_fn = _sjit(_dot_leg, mesh, (P(None, axis), P(axis, None)),
                   P(axis, None), out_dtype=dtype)
    ship_fn = _sjit(_chunk_shift_add_leg, mesh, (P(axis, None),),
                    P(axis, None), axis=axis, world=world)
    a_step = a[:m_blk]
    pred_comp = perf_model.estimate_gemm_sol_time_ms(m_blk, N, k_loc,
                                                     dtype)
    pred_comm = (perf_model.estimate_reduce_scatter_time_ms(
        M * N * el, world) / (world - 1) if world > 1 else 0.0)
    slices = []
    for s in range(world):
        slices.append(StepSlice(
            step=s, phase="compute",
            measured_ms=_time_ms(
                seg_fn, (a_step, b), trials=trials,
                label=f"kprobe.gemm_rs.step{s}.compute",
                flops=2 * m_blk * N * k_loc),
            predicted_ms=pred_comp,
            desc=f"[{m_blk}, {k_loc}] x [{k_loc}, {N}] chunk GEMM"))
        if s < world - 1:
            slices.append(StepSlice(
                step=s, phase="comm",
                measured_ms=_time_ms(
                    ship_fn, (chunk,), trials=trials,
                    label=f"kprobe.gemm_rs.step{s}.comm",
                    bytes_accessed=m_blk * N * el),
                predicted_ms=pred_comm,
                desc=f"ship + add [{m_blk}, {N}] partial chunk"))
    return OverlapReport(
        kernel="gemm_rs", world=world,
        shape={"M": M, "K": K, "N": N},
        dtype=str(jnp.dtype(dtype)), fused_ms=fused_ms,
        compute_ms=compute_ms, comm_ms=comm_ms, slices=slices,
        backend=jax.default_backend(), trials=trials)


def probe_moe_reduce_rs(mesh: Mesh, *, axis: str = "tp", T: int = 32,
                        D: int = 128, n_experts: int = 4, topk: int = 2,
                        block_m: int = 8, dtype=jnp.float32,
                        impl: str = "auto", trials: int = 3,
                        seed: int = 0) -> OverlapReport:
    """Scoreboard for the MoE GroupGEMM-ReduceScatter (F == D identity
    first layer, like tests/test_moe_reduce_rs.py): fused =
    ``moe_reduce_rs``; compute-only = the grouped GEMM over all sorted
    rows (own segment band kept); comm-only = the ring reduce-scatter
    of the per-rank segment partials; sliced replay: one dense-
    equivalent [m_pad, f_loc] x [f_loc, D] segment GEMM per step + one
    [m_pad, D] segment ship-and-add per ring hop."""
    from triton_dist_tpu.kernels.allgather_group_gemm import (
        _segment_plans)
    from triton_dist_tpu.kernels.moe_reduce_rs import (
        create_moe_rs_context, moe_reduce_rs)
    from triton_dist_tpu.kernels.moe_utils import (
        gather_sorted, topk_routing)

    world = int(mesh.shape[axis])
    if T % (world or 1) or D % (world or 1):
        raise ValueError(f"T ({T}) and D ({D}) must divide by world "
                         f"({world})")
    t_loc = T // world
    f_loc = D // world
    el = jnp.dtype(dtype).itemsize
    ks = jax.random.split(jax.random.key(seed), 3)
    x = (jax.random.normal(ks[0], (T, D), jnp.float32) * 0.1).astype(dtype)
    w = (jax.random.normal(ks[1], (n_experts, D, D), jnp.float32)
         / np.sqrt(D)).astype(dtype)
    logits = jax.random.normal(ks[2], (T, n_experts), jnp.float32)
    weights, experts = topk_routing(logits, topk)
    experts_all = experts.reshape(world, t_loc, topk)
    dest_all, te_all, m_pad = _segment_plans(experts_all, n_experts,
                                             block_m)
    xs = jax.vmap(functools.partial(gather_sorted, m_pad=m_pad))(
        x.reshape(world, t_loc, D), dest_all)
    h = xs.reshape(world * m_pad, D)
    rows = h.shape[0]

    ctx = create_moe_rs_context(mesh, n_experts=n_experts, topk=topk,
                                axis=axis, block_m=block_m, impl=impl)
    fused_ms = _time_ms(
        lambda: moe_reduce_rs(h, w, weights, experts, ctx), (),
        trials=trials, label="kprobe.moe_reduce_rs.fused",
        flops=2 * rows * f_loc * D,
        bytes_accessed=(rows * f_loc + rows * D) * el
        + w.size // max(world, 1) * el)

    te_flat = np.asarray(te_all).reshape(-1)
    comp_fn = _sjit(_group_gemm_leg, mesh,
                    (P(None, axis), P(None, axis, None), P()),
                    P(axis, None), axis=axis, block_m=block_m,
                    out_dtype=dtype)
    compute_ms = _time_ms(
        comp_fn, (h, w, jnp.asarray(te_flat)), trials=trials,
        label="kprobe.moe_reduce_rs.compute_only",
        flops=2 * rows * f_loc * D)
    parts = (jax.random.normal(ks[0], (world, rows, D), jnp.float32)
             * 0.1).astype(dtype)
    comm_fn = _sjit(_rs_leg, mesh, (P(axis, None, None),),
                    P(axis, None), axis=axis)
    comm_ms = (_time_ms(comm_fn, (parts,), trials=trials,
                        label="kprobe.moe_reduce_rs.comm_only",
                        bytes_accessed=rows * D * el)
               if world > 1 else 0.0)

    seg_fn = _sjit(_seg_dot_leg, mesh, (P(), P(None, axis)),
                   P(None, axis), out_dtype=dtype)
    h_seg = h[:m_pad]
    ship_fn = _sjit(_chunk_shift_add_leg, mesh, (P(axis, None),),
                    P(axis, None), axis=axis, world=world)
    seg_chunk = parts[0]   # [world*m_pad, D]: one [m_pad, D] per rank
    pred_comp = perf_model.estimate_gemm_sol_time_ms(m_pad, D, f_loc,
                                                     dtype)
    pred_comm = (perf_model.estimate_reduce_scatter_time_ms(
        rows * D * el, world) / (world - 1) if world > 1 else 0.0)
    slices = []
    for s in range(world):
        slices.append(StepSlice(
            step=s, phase="compute",
            measured_ms=_time_ms(
                seg_fn, (h_seg, w[0]), trials=trials,
                label=f"kprobe.moe_reduce_rs.step{s}.compute",
                flops=2 * m_pad * f_loc * D),
            predicted_ms=pred_comp,
            desc=f"dense-equivalent [{m_pad}, {f_loc}] x "
                 f"[{f_loc}, {D}] segment GEMM"))
        if s < world - 1:
            slices.append(StepSlice(
                step=s, phase="comm",
                measured_ms=_time_ms(
                    ship_fn, (seg_chunk,), trials=trials,
                    label=f"kprobe.moe_reduce_rs.step{s}.comm",
                    bytes_accessed=m_pad * D * el),
                predicted_ms=pred_comm,
                desc=f"ship + add [{m_pad}, {D}] segment partial"))
    return OverlapReport(
        kernel="moe_reduce_rs", world=world,
        shape={"T": T, "D": D, "n_experts": n_experts, "topk": topk,
               "block_m": block_m, "rows": rows},
        dtype=str(jnp.dtype(dtype)), fused_ms=fused_ms,
        compute_ms=compute_ms, comm_ms=comm_ms, slices=slices,
        backend=jax.default_backend(), trials=trials)


def probe_sp_decode(mesh: Mesh, *, axis: str = "sp", B: int = 4,
                    Hq: int = 8, Hkv: int = 2, S: int = 512,
                    D: int = 64, dtype=jnp.float32, impl: str = "auto",
                    trials: int = 3, seed: int = 0) -> OverlapReport:
    """Scoreboard for the SP flash-decode combine (the serving engine's
    ``kv_shard="seq"`` attention): fused = ``sp_gqa_decode_shard``
    (local split-KV partials + inter-rank LSE combine); compute-only =
    the local partials alone; comm-only = the combine alone on
    precomputed partials.  The schedule has one step with two phases
    (local decode, then the partial-plane exchange) — sliced the same
    way."""
    world = int(mesh.shape[axis])
    if S % (world or 1):
        raise ValueError(f"S ({S}) must divide by world ({world})")
    s_loc = S // world
    el = jnp.dtype(dtype).itemsize
    ks = jax.random.split(jax.random.key(seed), 4)
    q = (jax.random.normal(ks[0], (B, Hq, D), jnp.float32)
         * 0.1).astype(dtype)
    k = (jax.random.normal(ks[1], (B, Hkv, S, D), jnp.float32)
         * 0.1).astype(dtype)
    v = (jax.random.normal(ks[2], (B, Hkv, S, D), jnp.float32)
         * 0.1).astype(dtype)
    kv_lens = jnp.full((B,), S, jnp.int32)
    seq = P(None, None, axis)

    fused_fn = _sjit(_sp_fused_leg, mesh, (P(), seq, seq, P()), P(),
                     axis=axis, impl=impl, interpret=False)
    kv_bytes = 2 * B * Hkv * s_loc * D * el
    fused_ms = _time_ms(fused_fn, (q, k, v, kv_lens), trials=trials,
                        label="kprobe.sp_decode.fused",
                        flops=4 * B * Hq * s_loc * D,
                        bytes_accessed=kv_bytes)

    comp_fn = _sjit(_local_decode_leg, mesh, (P(), seq, seq, P()),
                    (P(axis), P(axis)), axis=axis, impl=impl,
                    interpret=False)
    compute_ms = _time_ms(comp_fn, (q, k, v, kv_lens), trials=trials,
                          label="kprobe.sp_decode.compute_only",
                          flops=4 * B * Hq * s_loc * D,
                          bytes_accessed=kv_bytes)
    out_all, lse_all = comp_fn(q, k, v, kv_lens)
    comb_fn = _sjit(_sp_combine_leg, mesh, (P(axis), P(axis)), P(),
                    axis=axis, impl=impl, interpret=False)
    payload = B * Hq * (D + 1) * 4
    comm_ms = (_time_ms(comb_fn, (out_all, lse_all), trials=trials,
                        label="kprobe.sp_decode.comm_only",
                        bytes_accessed=payload * (world - 1))
               if world > 1 else 0.0)

    # roofline: decode is HBM-bound (the KV read), the combine is the
    # partial-plane allgather
    gbps = perf_model.get_hbm_gbps()
    pred_comp = kv_bytes / (gbps * 1e6) if gbps else 0.0
    pred_comm = (perf_model.estimate_allgather_time_ms(payload, world)
                 if world > 1 else 0.0)
    slices = [StepSlice(
        step=0, phase="compute", measured_ms=compute_ms,
        predicted_ms=pred_comp,
        desc=f"local split-KV decode over [B={B}, Hkv={Hkv}, "
             f"S_loc={s_loc}, D={D}]")]
    if world > 1:
        slices.append(StepSlice(
            step=0, phase="comm", measured_ms=comm_ms,
            predicted_ms=pred_comm,
            desc="inter-rank LSE combine of (out ⊕ lse) partials"))
    return OverlapReport(
        kernel="sp_decode", world=world,
        shape={"B": B, "Hq": Hq, "Hkv": Hkv, "S": S, "D": D},
        dtype=str(jnp.dtype(dtype)), fused_ms=fused_ms,
        compute_ms=compute_ms, comm_ms=comm_ms, slices=slices,
        backend=jax.default_backend(), trials=trials)


PROBES = {
    "ag_gemm": probe_ag_gemm,
    "gemm_rs": probe_gemm_rs,
    "moe_reduce_rs": probe_moe_reduce_rs,
    "sp_decode": probe_sp_decode,
}


def run_probe(kernel: str, mesh: Mesh, **kw) -> OverlapReport:
    """Dispatch one scoreboard probe by kernel name (:data:`KERNELS`)."""
    try:
        fn = PROBES[kernel]
    except KeyError:
        raise ValueError(
            f"unknown kernel {kernel!r}; choose from {KERNELS}") from None
    return fn(mesh, **kw)
