"""Topology introspection: TPU generation, ICI/DCN layout, roofline numbers.

Reference analog: NVLink/PCIe/NUMA detection in ``utils.py``
(`get_has_fullmesh_nvlink` :761-773, `get_nvlink_max_speed` :621-625,
`calculate_pcie_bandwidth` :667-702, `get_numa_world_size` :776-786).

TPU-native design: the interesting topology facts are (a) device generation
(sets MXU TFLOPS + HBM bandwidth), (b) ICI link bandwidth and whether a mesh
axis rides ICI (intra-slice) or DCN (cross-slice), (c) whether the axis wraps
(torus) — determines whether a ring uses 1 or 2 hops per step.  These feed
the perf models (`triton_dist_tpu.kernels.perf_model`) and kernel variant
auto-selection, just as NVLink-vs-PCIe selects AG variants in the reference
(allgather.py:54-69).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

# Per-generation roofline tables (public figures; bf16 dense TFLOPS per chip,
# HBM GB/s per chip, ICI GB/s per link per direction).
# Analog of the tensor-core TFLOPS tables in gemm_perf_model.py:233+.
_TPU_SPECS = {
    # name-substring: (bf16 TFLOPS, HBM GB/s, ICI GB/s/link, ici links)
    "v6e": (918.0, 1640.0, 3584.0 / 8, 4),  # Trillium
    "v6": (918.0, 1640.0, 448.0, 4),
    "v5p": (459.0, 2765.0, 4800.0 / 48, 6),
    "v5e": (197.0, 819.0, 1600.0 / 4, 4),
    "v5 lite": (197.0, 819.0, 400.0, 4),
    "v4": (275.0, 1228.0, 2400.0 / 6, 6),
    "v3": (123.0, 900.0, 70.0, 4),
    "cpu": (0.5, 50.0, 10.0, 2),  # virtual-device test meshes
}


@dataclass(frozen=True)
class TopologyInfo:
    device_kind: str
    n_devices: int
    n_processes: int
    bf16_tflops: float
    hbm_gbps: float
    ici_gbps_per_link: float
    ici_links: int
    is_tpu: bool

    @property
    def ici_gbps(self) -> float:
        """Aggregate per-chip ICI bandwidth (all links, one direction)."""
        return self.ici_gbps_per_link * self.ici_links


def device_kind() -> str:
    return jax.devices()[0].device_kind


def is_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


def _lookup(kind: str):
    k = kind.lower()
    for sub, spec in _TPU_SPECS.items():
        if sub in k:
            return spec
    return _TPU_SPECS["cpu"]


def detect_topology() -> TopologyInfo:
    kind = device_kind()
    tflops, hbm, ici, links = _lookup(kind)
    return TopologyInfo(
        device_kind=kind,
        n_devices=jax.device_count(),
        n_processes=jax.process_count(),
        bf16_tflops=tflops,
        hbm_gbps=hbm,
        ici_gbps_per_link=ici,
        ici_links=links,
        is_tpu=is_tpu(),
    )


def peak_bf16_tflops() -> float:
    return detect_topology().bf16_tflops


def hbm_bandwidth_gbps() -> float:
    return detect_topology().hbm_gbps


def ici_bandwidth_gbps() -> float:
    return detect_topology().ici_gbps


def axis_is_dcn(mesh, axis: str) -> bool:
    """True when the mesh axis spans hosts via DCN rather than ICI.

    On multi-slice deployments an axis whose devices live in different
    processes crosses DCN.  (Analog: COMM_SCOPE INTER_NODE vs INTRA_NODE,
    DistributedAttrDefs.td:44-53.)
    """
    devs = mesh.devices
    import numpy as np

    ax = mesh.axis_names.index(axis)
    # Take a pencil of devices along `axis` and check their process indices.
    idx = [0] * devs.ndim
    pencil = [
        devs[tuple(idx[:ax] + [i] + idx[ax + 1:])] for i in range(devs.shape[ax])
    ]
    procs = {getattr(d, "process_index", 0) for d in pencil}
    return len(procs) > 1
