"""Topology introspection: TPU generation, ICI/DCN layout, roofline numbers.

Reference analog: NVLink/PCIe/NUMA detection in ``utils.py``
(`get_has_fullmesh_nvlink` :761-773, `get_nvlink_max_speed` :621-625,
`calculate_pcie_bandwidth` :667-702, `get_numa_world_size` :776-786).

TPU-native design: the interesting topology facts are (a) device generation
(sets MXU TFLOPS + HBM bandwidth), (b) ICI link bandwidth and whether a mesh
axis rides ICI (intra-slice) or DCN (cross-slice), (c) whether the axis wraps
(torus) — determines whether a ring uses 1 or 2 hops per step.  These feed
the perf models (`triton_dist_tpu.kernels.perf_model`) and kernel variant
auto-selection, just as NVLink-vs-PCIe selects AG variants in the reference
(allgather.py:54-69).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

# Per-generation roofline tables (public figures; bf16 dense TFLOPS per chip,
# HBM GB/s per chip, ICI GB/s per link per direction).
# Analog of the tensor-core TFLOPS tables in gemm_perf_model.py:233+.
_TPU_SPECS = {
    # name-substring: (bf16 TFLOPS, HBM GB/s, ICI GB/s/link, ici links)
    "v6e": (918.0, 1640.0, 3584.0 / 8, 4),  # Trillium
    "v6": (918.0, 1640.0, 448.0, 4),
    "v5p": (459.0, 2765.0, 4800.0 / 48, 6),
    "v5e": (197.0, 819.0, 1600.0 / 4, 4),
    "v5 lite": (197.0, 819.0, 400.0, 4),
    "v4": (275.0, 1228.0, 2400.0 / 6, 6),
    "v3": (123.0, 900.0, 70.0, 4),
    "cpu": (0.5, 50.0, 10.0, 2),  # virtual-device test meshes
}


@dataclass(frozen=True)
class TopologyInfo:
    device_kind: str
    n_devices: int
    n_processes: int
    bf16_tflops: float
    hbm_gbps: float
    ici_gbps_per_link: float
    ici_links: int
    is_tpu: bool

    @property
    def ici_gbps(self) -> float:
        """Aggregate per-chip ICI bandwidth (all links, one direction)."""
        return self.ici_gbps_per_link * self.ici_links


def device_kind() -> str:
    return jax.devices()[0].device_kind


def is_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


def _lookup(kind: str):
    k = kind.lower()
    for sub, spec in _TPU_SPECS.items():
        if sub in k:
            return spec
    return _TPU_SPECS["cpu"]


def detect_topology() -> TopologyInfo:
    kind = device_kind()
    tflops, hbm, ici, links = _lookup(kind)
    return TopologyInfo(
        device_kind=kind,
        n_devices=jax.device_count(),
        n_processes=jax.process_count(),
        bf16_tflops=tflops,
        hbm_gbps=hbm,
        ici_gbps_per_link=ici,
        ici_links=links,
        is_tpu=is_tpu(),
    )


def peak_bf16_tflops() -> float:
    return detect_topology().bf16_tflops


# Best *measured* dense-dot TFLOPS on each chip kind at the bench shape
# (M=8192 K=8192 N=3584 bf16; docs/perf.md "AG-GEMM").  bench.py uses this
# as a self-consistency bound: no honest chain that also pays AG dispatch
# can beat XLA's own dense dot on the same chip at the same shape, so any
# reading above it is elision/tunnel contamination, not performance.
_MEASURED_DOT_CEILING = {"v5e": 189.7, "v5 lite": 189.7}


def measured_dot_ceiling_tflops() -> float:
    """Measured XLA-dot ceiling for this chip kind (bench shape), falling
    back to 0.97x peak for chip kinds never measured on the tunnel."""
    kind = device_kind().lower()
    for sub, v in _MEASURED_DOT_CEILING.items():
        if sub in kind:
            return v
    return 0.97 * peak_bf16_tflops()


def hbm_bandwidth_gbps() -> float:
    return detect_topology().hbm_gbps


def ici_bandwidth_gbps() -> float:
    return detect_topology().ici_gbps


def slice_index(device) -> int:
    """Slice id of a TPU device (0 on single-slice / non-TPU).

    Multi-slice TPU deployments expose ``slice_index`` on each device; the
    DCN tier is "between different slice_index groups" (the reference's
    node boundary, COMM_SCOPE INTER_NODE).
    """
    return int(getattr(device, "slice_index", 0) or 0)


def n_slices() -> int:
    return len({slice_index(d) for d in jax.devices()})


def create_hybrid_mesh(ici_axes: dict[str, int] | None = None,
                       dcn_axis: str = "dcn", n_slow: int | None = None):
    """Build a (dcn, *ici) mesh where the leading axis crosses slices.

    Real multi-slice TPU: delegates to ``mesh_utils.create_hybrid_device_mesh``
    (DCN-aware device ordering).  Single-slice or CPU test meshes: the
    process boundary plays the slice boundary (processes are connected by
    gRPC/gloo, the test-world DCN), falling back to a plain split when
    single-process.

    ``n_slow`` overrides the slow-tier width — single-process virtual
    rigs (the driver's multichip gate) use it to SIMULATE a 2-slice
    deployment: the mesh then has the hybrid SHAPE and the hierarchical
    programs compile against it, with the actual slow wire absent.

    Reference analog: the nnodes x local_world topology of launch.sh +
    NVSHMEM teams; here it is just a mesh whose leading axis is the slow
    tier.
    """
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    slices = n_slices()
    n_proc = jax.process_count()
    # The slow tier is the slice boundary.  On non-TPU backends the process
    # boundary plays that role (gRPC/gloo between procs).  A single-slice
    # multi-host TPU pod has NO slow tier — all hosts share one ICI fabric —
    # so n_slow collapses to 1 there (keeps axis_is_dcn consistent).
    if n_slow is not None:
        pass  # caller-pinned (virtual-rig simulation)
    elif slices > 1:
        n_slow = slices
    elif devices[0].platform != "tpu":
        n_slow = max(n_proc, 1)
    else:
        n_slow = 1
    if ici_axes is None:
        ici_axes = {"tp": len(devices) // n_slow}
    n_fast = int(np.prod(list(ici_axes.values())))

    if slices > 1:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=tuple(ici_axes.values()),
            dcn_mesh_shape=(n_slow,) + (1,) * (len(ici_axes) - 1),
            devices=devices)
        dev_array = dev_array.reshape((n_slow,) + tuple(ici_axes.values()))
    else:
        # process-major ordering: jax.devices() already groups by process.
        # A prefix is only safe on a SINGLE-process virtual rig (the
        # driver gate's 2x interpreter-starvation headroom); in a real
        # multi-process world a short prefix would silently drop whole
        # processes from the mesh — keep the loud exact-match there.
        n_need = n_slow * n_fast
        if n_proc <= 1:
            assert n_need <= len(devices), (n_slow, n_fast, len(devices))
        else:
            assert n_need == len(devices), (n_slow, n_fast, len(devices))
        dev_array = np.asarray(devices[:n_need]).reshape(
            (n_slow,) + tuple(ici_axes.values()))
    return Mesh(dev_array, (dcn_axis,) + tuple(ici_axes.keys()))


def axis_is_dcn(mesh, axis: str) -> bool:
    """True when the mesh axis spans hosts via DCN rather than ICI.

    On multi-slice deployments an axis whose devices live in different
    processes crosses DCN.  (Analog: COMM_SCOPE INTER_NODE vs INTRA_NODE,
    DistributedAttrDefs.td:44-53.)
    """
    devs = mesh.devices
    import numpy as np

    ax = mesh.axis_names.index(axis)
    # Take a pencil of devices along `axis` and check their process indices.
    idx = [0] * devs.ndim
    pencil = [
        devs[tuple(idx[:ax] + [i] + idx[ax + 1:])] for i in range(devs.shape[ax])
    ]
    # A real multi-slice boundary (slice_index differs) is always DCN; a
    # process boundary is DCN on CPU/test backends (gRPC between procs) and
    # on multi-host TPU only when it also crosses slices (a v5p pod spans
    # many hosts on one ICI fabric).
    if len({slice_index(d) for d in pencil}) > 1:
        return True
    procs = {getattr(d, "process_index", 0) for d in pencil}
    return len(procs) > 1 and pencil[0].platform != "tpu"
