"""JAX version-compatibility shims.

The codebase targets the jax>=0.6 surface (``jax.shard_map``,
``pltpu.CompilerParams``).  Older runtimes (0.4.x) carry the same
functionality under the pre-stabilization names — ``jax.experimental.
shard_map.shard_map`` (with ``check_rep`` instead of ``check_vma``) and
``pltpu.TPUCompilerParams``.  :func:`apply` installs forward-compatible
aliases so one source tree runs on both; on a current jax it is a no-op.

Imported (and applied) from the package ``__init__`` — nothing here may
initialize a JAX backend (the late-CPU-pinning rule of runtime/testenv.py):
only module attributes are touched.
"""

from __future__ import annotations

import functools


def _shim_shard_map(jax):
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map


def _shim_axis_size(jax):
    if hasattr(jax.lax, "axis_size"):
        return

    def axis_size(axis_name):
        # psum of a Python literal constant-folds to the static axis size
        # (a concrete int) under shard_map tracing on 0.4.x.
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size


def _shim_pallas_tpu():
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(pltpu, "CompilerParams"):
        base = pltpu.TPUCompilerParams
        known = {f.name for f in dataclasses.fields(base)}

        def CompilerParams(**kw):
            # Fields the old dataclass lacks (e.g. has_side_effects) are
            # dropped: on 0.4.x the flag either has a different spelling
            # or no effect on the paths this tree exercises.
            return base(**{k: v for k, v in kw.items() if k in known})

        pltpu.CompilerParams = CompilerParams


def apply() -> None:
    """Install all shims (idempotent; no-op on jax>=0.6)."""
    import jax

    if hasattr(jax, "shard_map"):
        # jax >= 0.6 surface: every shimmed name already exists.  Early
        # out before _shim_pallas_tpu, whose pallas import costs ~0.3 s
        # of package-import time.
        return
    _shim_shard_map(jax)
    _shim_axis_size(jax)
    _shim_pallas_tpu()
