"""Failure detection: stall watchdogs and heartbeats for collective code.

The reference's only failure story is a 1800 s NCCL process-group timeout
plus hard asserts (SURVEY.md §5; reference ``utils.py:103``) — a hung
collective shows up as a silent 30-minute stall and an opaque NCCL abort.
Distributed TPU programs hang the same way (a mismatched psum, a peer that
never signals a semaphore, a dead host in the DCN ring), so the framework
ships its own detection:

- :func:`run_with_watchdog` — run a blocking thunk (typically
  ``jax.block_until_ready`` on a collective's outputs) under a deadline;
  on expiry dump every Python thread's stack to stderr and raise
  :class:`WatchdogTimeout` (computation keeps running in its thread — XLA
  dispatches cannot be cancelled — but the trainer regains control and can
  checkpoint/abort cleanly instead of stalling forever).
- :class:`Heartbeat` — a tiny mtime-based liveness file an external
  supervisor (or another rank's host code) can poll to detect a stalled
  process without any in-band communication.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Any, Callable

import jax


class WatchdogTimeout(TimeoutError):
    """A watched computation exceeded its deadline."""


def run_with_watchdog(fn: Callable[[], Any], timeout_s: float | None, *,
                      name: str = "computation",
                      dump_stacks: bool = True) -> Any:
    """Run ``fn()`` and return its result, raising :class:`WatchdogTimeout`
    if it does not finish within ``timeout_s`` seconds.

    ``fn`` runs in a daemon thread; on timeout the thread is left running
    (device work is not cancellable) but the caller regains control.  Any
    exception ``fn`` raises is re-raised here.  ``timeout_s=None`` runs
    ``fn`` inline with no watchdog — callers with an *optional* stall
    budget (the serving engine's ``step_timeout_s``) need no branch.
    """
    if timeout_s is None:
        return fn()
    result: list[Any] = []
    error: list[BaseException] = []
    done = threading.Event()

    def body():
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            error.append(e)
        finally:
            done.set()

    t = threading.Thread(target=body, name=f"watchdog:{name}", daemon=True)
    t.start()
    if not done.wait(timeout_s):
        if dump_stacks:
            print(f"[watchdog] '{name}' exceeded {timeout_s}s; "
                  f"thread stacks follow", file=sys.stderr, flush=True)
            faulthandler.dump_traceback(file=sys.stderr)
        raise WatchdogTimeout(
            f"'{name}' did not complete within {timeout_s}s "
            f"(process {jax.process_index()} of {jax.process_count()})")
    if error:
        raise error[0]
    return result[0]


def block_until_ready_with_timeout(tree: Any, timeout_s: float, *,
                                   name: str = "collective") -> Any:
    """``jax.block_until_ready`` under a deadline — the canonical guard for
    'did every peer show up for this collective'."""
    return run_with_watchdog(lambda: jax.block_until_ready(tree), timeout_s,
                             name=name)


class Heartbeat:
    """Liveness file: touch ``path`` every ``interval_s`` from a daemon
    thread; a supervisor treats ``now - mtime > k * interval_s`` as a stall.

    Use as a context manager around a training loop::

        with Heartbeat(f"/tmp/hb.{jax.process_index()}"):
            for step in ...: ...
    """

    def __init__(self, path: str | os.PathLike, interval_s: float = 10.0):
        self.path = os.fspath(path)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self) -> None:
        """One explicit beat (also called automatically by the thread)."""
        with open(self.path, "w") as f:
            f.write(f"{time.time()}\n")

    @staticmethod
    def age_s(path: str | os.PathLike) -> float | None:
        """Seconds since the last beat at ``path``; None if never beaten."""
        try:
            return time.time() - os.stat(path).st_mtime
        except FileNotFoundError:
            return None

    @staticmethod
    def is_stalled(path: str | os.PathLike, interval_s: float,
                   tolerance: float = 3.0) -> bool:
        age = Heartbeat.age_s(path)
        return age is None or age > tolerance * interval_s

    def __enter__(self) -> "Heartbeat":
        self.beat()
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.beat()

        self._thread = threading.Thread(target=loop, name="heartbeat",
                                        daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 1.0)
            self._thread = None
