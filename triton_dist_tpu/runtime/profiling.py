"""Profiling: per-process traces merged for whole-job timelines.

Reference analog: ``group_profile`` (utils.py:417-501) — per-rank
torch.profiler chrome traces gathered to rank 0, pid/tid re-namespaced per
rank, merged and gzipped.

TPU-native design: ``jax.profiler`` already captures device + host activity
per process into Perfetto/TensorBoard format, and on multi-host TPU each
process writes its own trace directory.  ``group_profile`` wraps
``jax.profiler.trace`` with rank-scoped output dirs so a whole-job profile is
a directory merge (Perfetto loads multi-process traces natively — no pid/tid
rewriting needed, which removes the reference's entire merge pipeline).
"""

from __future__ import annotations

import contextlib
import os

import jax


class group_profile:
    """Context manager: ``with group_profile("ag_gemm", do_prof=True): ...``.

    Writes traces to ``{base_dir}/{name}/rank{process_index}``; view with
    TensorBoard's profile plugin or ui.perfetto.dev.
    """

    def __init__(self, name: str = "trace", do_prof: bool = True, base_dir: str = "prof"):
        self.name = name
        self.do_prof = do_prof
        self.base_dir = base_dir
        self._cm = None

    def __enter__(self):
        if self.do_prof:
            out = os.path.join(self.base_dir, self.name, f"rank{jax.process_index()}")
            os.makedirs(out, exist_ok=True)
            self._cm = jax.profiler.trace(out)
            self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            self._cm.__exit__(*exc)
        return False


@contextlib.contextmanager
def annotate(name: str):
    """Named trace span (reference analog: launch_metadata proton hooks)."""
    with jax.profiler.TraceAnnotation(name):
        yield
