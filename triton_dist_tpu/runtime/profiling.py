"""Profiling: per-process traces merged for whole-job timelines.

Reference analog: ``group_profile`` (utils.py:417-501) — per-rank
torch.profiler chrome traces gathered to rank 0, pid/tid re-namespaced per
rank, merged and gzipped.

TPU-native design: ``jax.profiler`` captures device + host activity per
process into Perfetto/TensorBoard format; ``group_profile`` scopes each
rank's output dir, then rank 0 merges every rank's chrome events into ONE
gzipped timeline with per-rank pid re-namespacing — the same single-
artifact contract as the reference's merge pipeline, minus its
gather-to-rank-0 copy step (ranks write a shared filesystem directly).
The per-rank dirs also remain loadable individually.

The serving engine's flight recorder rides the same merge machinery:
``serve.trace.FlightRecorder.export_profile(job_dir)`` drops the engine
timeline as ``rank{i}/engine.trace.json.gz`` (its events claim
``serve.trace.ENGINE_PID`` — below the Linux pid cap, so the per-rank
pid re-namespacing in :func:`merge_rank_traces` stays injective), and
one merged ui.perfetto.dev file then holds the device timeline and the
engine's request lifecycle spans side by side (docs/observability.md
has the recipe).
"""

from __future__ import annotations

import contextlib
import os

import jax


class group_profile:
    """Context manager: ``with group_profile("ag_gemm", do_prof=True): ...``.

    Writes traces to ``{base_dir}/{name}/rank{process_index}``; view with
    TensorBoard's profile plugin or ui.perfetto.dev.  With ``merge=True``
    (the default), rank 0 additionally merges every rank's chrome trace
    into ONE gzipped timeline at ``{base_dir}/{name}/merged.trace.json.gz``
    — the reference's single-artifact job trace (utils.py:282-501), with
    pids re-namespaced per rank so a 32-chip job loads as one file in
    ui.perfetto.dev.
    """

    def __init__(self, name: str = "trace", do_prof: bool = True,
                 base_dir: str = "prof", merge: bool = True,
                 gather: bool = False):
        self.name = name
        self.do_prof = do_prof
        self.base_dir = base_dir
        self.merge = merge
        # ``gather=True``: ship every rank's trace files to rank 0 over
        # the jax.distributed fabric before merging — for multi-host
        # deployments where ranks write LOCAL disks (the reference
        # gathers over the torch process group for the same reason,
        # utils.py:417-501).  Off by default: single-host and shared-FS
        # jobs see every rank dir already.
        self.gather = gather
        self.merged_path = None
        self._cm = None

    def __enter__(self):
        if self.do_prof:
            out = os.path.join(self.base_dir, self.name, f"rank{jax.process_index()}")
            os.makedirs(out, exist_ok=True)
            self._cm = jax.profiler.trace(out)
            self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            self._cm.__exit__(*exc)
            if self.merge:
                if jax.process_count() > 1:
                    # Every rank must finish flushing its trace files
                    # before rank 0 reads them (same sync used by
                    # checkpoint.py).
                    from jax.experimental import multihost_utils

                    multihost_utils.sync_global_devices(
                        "group_profile_merge")
                    if self.gather:
                        gather_rank_traces(
                            os.path.join(self.base_dir, self.name))
                if jax.process_index() == 0:
                    try:
                        self.merged_path = merge_rank_traces(
                            os.path.join(self.base_dir, self.name))
                    except Exception:
                        self.merged_path = None  # per-rank dirs remain
        return False


def gather_rank_traces(job_dir: str) -> None:
    """Ship every rank's local trace dir to rank 0 over jax.distributed.

    Reference analog: ``group_profile`` gathers per-rank trace files to
    rank 0 over the torch process group (utils.py:417-501).  Here each
    process tars its own ``{job_dir}/rank{i}`` in memory, the tars ride a
    padded uint8 ``process_allgather`` (host collective over DCN), and
    rank 0 extracts the other ranks' tars under its local ``job_dir`` so
    :func:`merge_rank_traces` sees all of them.  No shared filesystem
    required; a no-op at process_count() == 1.
    """
    import io
    import tarfile

    import numpy as np
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return
    me = jax.process_index()
    rank_dir = os.path.join(job_dir, f"rank{me}")
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        if os.path.isdir(rank_dir):
            tar.add(rank_dir, arcname=f"rank{me}")
    blob = np.frombuffer(buf.getvalue(), np.uint8)

    sizes = multihost_utils.process_allgather(
        np.asarray([blob.size], np.int64))
    pad = int(sizes.max())
    # Chunked gather: allgather is the only host collective available,
    # and a single max-padded allgather would materialize
    # process_count * max_tar bytes on EVERY host (profiler tars run to
    # hundreds of MB).  Fixed 64 MiB slices bound the peak at
    # process_count * chunk regardless of tar size; ranks != 0 drop
    # each slice immediately.
    chunk = 64 * 2 ** 20
    parts = [io.BytesIO() for _ in range(jax.process_count())]
    for off in range(0, pad, chunk):
        ln = min(chunk, pad - off)
        piece = np.zeros((ln,), np.uint8)
        if off < blob.size:
            n = min(ln, blob.size - off)
            piece[:n] = blob[off:off + n]
        gathered = multihost_utils.process_allgather(piece)
        if me == 0:
            for r in range(1, jax.process_count()):
                # Keep only each rank's REAL bytes (skip rank 0's own
                # tar and the zero padding past sizes[r]) so rank 0's
                # accumulation is sum(tar sizes), not P * max_tar.
                keep = min(ln, max(int(sizes[r][0]) - off, 0))
                if keep:
                    parts[r].write(bytes(np.asarray(gathered[r][:keep])))
        del gathered

    if me != 0:
        return
    for r in range(1, jax.process_count()):
        data = parts[r].getvalue()
        with tarfile.open(fileobj=io.BytesIO(data), mode="r") as tar:
            # 'data' filter: strips absolute paths/symlinks — the tars
            # are self-produced, but stay safe anyway.
            tar.extractall(job_dir, filter="data")


def merge_rank_traces(job_dir: str) -> str | None:
    """Merge every ``rank*/`` chrome trace under ``job_dir`` into one
    gzipped timeline ``{job_dir}/merged.trace.json.gz``.

    Each rank's events keep their own pid space, prefixed into a distinct
    range (rank r's pid p becomes ``r * 10_000_000 + p`` — injective since
    Linux pids cap at 4194304) and its process
    names get a ``[rank r]`` suffix — the reference's pid/tid
    re-namespacing (utils.py:282-501) on the TPU trace layout
    (``plugins/profile/<run>/*.trace.json.gz`` per process).  Returns the
    merged path, or None when no per-rank traces exist (e.g. profiling
    was off).  NOTE: on multi-host, every rank must write under a SHARED
    filesystem for rank 0 to see the dirs; otherwise per-rank dirs stay
    separate (perfetto can still load several files side by side).
    """
    import glob
    import gzip
    import json

    merged_events = []
    ranks = sorted(glob.glob(os.path.join(job_dir, "rank*")))
    found = 0
    for rank_dir in ranks:
        m = os.path.basename(rank_dir).replace("rank", "")
        try:
            rank = int(m)
        except ValueError:
            continue
        traces = sorted(glob.glob(
            os.path.join(rank_dir, "**", "*.trace.json.gz"),
            recursive=True))
        for path in traces:
            with gzip.open(path, "rt") as f:
                data = json.load(f)
            found += 1
            for ev in data.get("traceEvents", []):
                if "pid" in ev:
                    ev = dict(ev)
                    ev["pid"] = rank * 10_000_000 + int(ev["pid"])
                    if (ev.get("ph") == "M"
                            and ev.get("name") == "process_name"):
                        args = dict(ev.get("args", {}))
                        args["name"] = (f"{args.get('name', '')} "
                                        f"[rank {rank}]")
                        ev["args"] = args
                merged_events.append(ev)
    if not found:
        return None
    out = os.path.join(job_dir, "merged.trace.json.gz")
    with gzip.open(out, "wt") as f:
        json.dump({"traceEvents": merged_events}, f)
    return out


@contextlib.contextmanager
def annotate(name: str, *, flops: int | None = None,
             bytes_accessed: int | None = None):
    """Named trace span carrying launch metadata (reference analog: the
    launch_metadata proton hooks — GEMMs report name/flops/bytes to the
    profiler, allgather_gemm.py:120-130).

    ``flops``/``bytes_accessed`` are per-device totals for the spanned
    op; they are embedded in the span label together with the derived
    roofline time (max of MXU-bound and HBM-bound, from the same chip
    tables ``kernels/perf_model`` estimates with, via ``topology``), so a
    profiler timeline read against the span directly yields
    achieved-vs-attainable.  The label
    rides BOTH ``TraceAnnotation`` (host timeline) and ``jax.named_scope``
    (baked into HLO op metadata at trace time → device timeline).
    """
    label = name
    if flops is not None or bytes_accessed is not None:
        parts = [name]
        if flops is not None:
            parts.append(f"flops={flops}")
        if bytes_accessed is not None:
            parts.append(f"bytes={bytes_accessed}")
        try:
            from triton_dist_tpu.runtime import topology

            tf = topology.peak_bf16_tflops()
            gbps = topology.hbm_bandwidth_gbps()
            sol_ms = max(
                (flops or 0) / (tf * 1e9),
                (bytes_accessed or 0) / (gbps * 1e6)) if (tf and gbps) else 0.0
            if sol_ms:
                parts.append(f"sol_ms={sol_ms:.3f}")
        except Exception:
            pass
        label = "#".join(parts)
    with jax.profiler.TraceAnnotation(label), jax.named_scope(label):
        yield
