"""Profiling: per-process traces merged for whole-job timelines.

Reference analog: ``group_profile`` (utils.py:417-501) — per-rank
torch.profiler chrome traces gathered to rank 0, pid/tid re-namespaced per
rank, merged and gzipped.

TPU-native design: ``jax.profiler`` captures device + host activity per
process into Perfetto/TensorBoard format; ``group_profile`` scopes each
rank's output dir, then rank 0 merges every rank's chrome events into ONE
gzipped timeline with per-rank pid re-namespacing — the same single-
artifact contract as the reference's merge pipeline, minus its
gather-to-rank-0 copy step (ranks write a shared filesystem directly).
The per-rank dirs also remain loadable individually.
"""

from __future__ import annotations

import contextlib
import os

import jax


class group_profile:
    """Context manager: ``with group_profile("ag_gemm", do_prof=True): ...``.

    Writes traces to ``{base_dir}/{name}/rank{process_index}``; view with
    TensorBoard's profile plugin or ui.perfetto.dev.  With ``merge=True``
    (the default), rank 0 additionally merges every rank's chrome trace
    into ONE gzipped timeline at ``{base_dir}/{name}/merged.trace.json.gz``
    — the reference's single-artifact job trace (utils.py:282-501), with
    pids re-namespaced per rank so a 32-chip job loads as one file in
    ui.perfetto.dev.
    """

    def __init__(self, name: str = "trace", do_prof: bool = True,
                 base_dir: str = "prof", merge: bool = True):
        self.name = name
        self.do_prof = do_prof
        self.base_dir = base_dir
        self.merge = merge
        self.merged_path = None
        self._cm = None

    def __enter__(self):
        if self.do_prof:
            out = os.path.join(self.base_dir, self.name, f"rank{jax.process_index()}")
            os.makedirs(out, exist_ok=True)
            self._cm = jax.profiler.trace(out)
            self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            self._cm.__exit__(*exc)
            if self.merge:
                if jax.process_count() > 1:
                    # Every rank must finish flushing its trace files
                    # before rank 0 reads them (same sync used by
                    # checkpoint.py).
                    from jax.experimental import multihost_utils

                    multihost_utils.sync_global_devices(
                        "group_profile_merge")
                if jax.process_index() == 0:
                    try:
                        self.merged_path = merge_rank_traces(
                            os.path.join(self.base_dir, self.name))
                    except Exception:
                        self.merged_path = None  # per-rank dirs remain
        return False


def merge_rank_traces(job_dir: str) -> str | None:
    """Merge every ``rank*/`` chrome trace under ``job_dir`` into one
    gzipped timeline ``{job_dir}/merged.trace.json.gz``.

    Each rank's events keep their own pid space, prefixed into a distinct
    range (rank r's pid p becomes ``r * 10_000_000 + p`` — injective since
    Linux pids cap at 4194304) and its process
    names get a ``[rank r]`` suffix — the reference's pid/tid
    re-namespacing (utils.py:282-501) on the TPU trace layout
    (``plugins/profile/<run>/*.trace.json.gz`` per process).  Returns the
    merged path, or None when no per-rank traces exist (e.g. profiling
    was off).  NOTE: on multi-host, every rank must write under a SHARED
    filesystem for rank 0 to see the dirs; otherwise per-rank dirs stay
    separate (perfetto can still load several files side by side).
    """
    import glob
    import gzip
    import json

    merged_events = []
    ranks = sorted(glob.glob(os.path.join(job_dir, "rank*")))
    found = 0
    for rank_dir in ranks:
        m = os.path.basename(rank_dir).replace("rank", "")
        try:
            rank = int(m)
        except ValueError:
            continue
        traces = sorted(glob.glob(
            os.path.join(rank_dir, "**", "*.trace.json.gz"),
            recursive=True))
        for path in traces:
            with gzip.open(path, "rt") as f:
                data = json.load(f)
            found += 1
            for ev in data.get("traceEvents", []):
                if "pid" in ev:
                    ev = dict(ev)
                    ev["pid"] = rank * 10_000_000 + int(ev["pid"])
                    if (ev.get("ph") == "M"
                            and ev.get("name") == "process_name"):
                        args = dict(ev.get("args", {}))
                        args["name"] = (f"{args.get('name', '')} "
                                        f"[rank {rank}]")
                        ev["args"] = args
                merged_events.append(ev)
    if not found:
        return None
    out = os.path.join(job_dir, "merged.trace.json.gz")
    with gzip.open(out, "wt") as f:
        json.dump({"traceEvents": merged_events}, f)
    return out


@contextlib.contextmanager
def annotate(name: str):
    """Named trace span (reference analog: launch_metadata proton hooks)."""
    with jax.profiler.TraceAnnotation(name):
        yield
