"""Kernel IR dumping — the reference's ``dump_ir`` observability hook.

Reference analog: ops take ``dump_ir`` and write ptx/ttir/ttgir/llir per
kernel (``moe_reduce_rs.py:1009-1015``), plus the ``MLIR_ENABLE_DUMP`` env
path (``test_ag_gemm.py:108-113``).  The TPU stack's compilation artifacts
are StableHLO (what jax.export ships) and the optimized HLO after XLA's
passes (where fusion/layout decisions — the usual "why is this slow /
why does this not compile" evidence — are visible; Mosaic kernels appear
as ``tpu_custom_call`` ops carrying their serialized module).

Two entry points:

- ``TDT_DUMP_IR=<dir>`` in the environment: every program built through
  ``cached_shard_jit`` (all host-level ops) writes
  ``<dir>/<name>.stablehlo.txt`` and ``<name>.hlo.txt`` on first call.
- ``dump_lowered(fn, *args, name=...)``: explicit one-shot dump of any
  jittable callable with example args.

For the full per-pass XLA pipeline (including Mosaic custom-call
payloads), additionally set ``XLA_FLAGS=--xla_dump_to=<dir>`` before the
first compile — that knob is the platform's own and subsumes the
reference's ``MLIR_ENABLE_DUMP``.
"""

from __future__ import annotations

import os
import re

ENV_VAR = "TDT_DUMP_IR"


def dump_dir() -> str | None:
    """The active dump directory, or None when dumping is off."""
    return os.environ.get(ENV_VAR) or None


def _safe(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", name)[:120]


def _write(path: str, text: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)


def dump_lowered(fn, *args, name: str, directory: str | None = None,
                 compiled: bool = True) -> list[str]:
    """Write ``fn``'s StableHLO (and optimized HLO) for ``args``.

    ``fn`` may be a jitted or plain callable (wrapped if needed).  Returns
    the list of files written.  Never raises on compile failure of the
    optimized text — the StableHLO alone is then written (it is exactly
    what a "fails to compile" bug report needs).
    """
    import jax

    directory = directory or dump_dir() or "."
    base = os.path.join(directory, _safe(name))
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    lowered = fn.lower(*args)
    out = [base + ".stablehlo.txt"]
    _write(out[0], lowered.as_text())
    if compiled:
        try:
            _write(base + ".hlo.txt", lowered.compile().as_text())
            out.append(base + ".hlo.txt")
        except Exception as e:  # compile failure IS the interesting case
            _write(base + ".compile_error.txt", repr(e))
            out.append(base + ".compile_error.txt")
    return out


def wrap_for_dump(jitted, name: str):
    """Wrap a jitted callable so its first invocation also dumps IR (the
    ``cached_shard_jit`` hook; no-op wrapper when dumping is off)."""
    if dump_dir() is None:
        return jitted

    state = {"done": False}

    def wrapper(*args, **kwargs):
        if not state["done"]:
            state["done"] = True
            try:
                dump_lowered(jitted, *args, name=name)
            except Exception:
                pass  # observability must never break the op itself
        return jitted(*args, **kwargs)

    return wrapper
