"""Deterministic fault injection for the failure-containment layer.

The serving engine's containment paths (docs/serving.md "Failure
containment") are only trustworthy if they are *exercised*: a quarantine
path that no test can reach is a crash waiting for production.  This
module provides the chaos half of that contract — a seeded
:class:`FaultInjector` whose hooks are threaded through the engine and
block-manager seams, so every containment path can be driven
deterministically by tier-1 tests (fixed schedules) and probabilistically
by the slow chaos soak (seeded rates).

Fault points the serving stack instruments (``fire(point, **ctx)``):

==============  =======================  ================================
point           context                  seam
==============  =======================  ================================
``forward``     ``op=<program>, rids``   every engine device dispatch
                                         (``ServeEngine._device_call``)
``block_alloc`` ``rid``                  ``BlockManager.ensure`` grow path
``callback``    ``rid``                  the ``on_token`` invocation seam
``clock``       —                        each reading of a
                                         ``wrap_clock()``-wrapped clock
==============  =======================  ================================

Actions: ``error=`` raises :class:`InjectedFault` at the point;
``stall_s=`` sleeps there (inside the engine's watchdog-watched thunk, so
an injected stall trips the step watchdog exactly like a wedged device);
``skew_s=`` jumps the wrapped clock forward (expires request deadlines);
``kill=True`` raises :class:`InjectedKill` — a BaseException standing in
for process death, which no containment path may swallow (the crash-
recovery tests catch it at the harness level, abandon the engine object
like the OS would, and restart from disk).

A spec fires when its filters match: ``at_call`` pins the nth *enabled*
arrival at the point, ``rid`` / ``op`` restrict to one request / program,
``rate`` draws from the seeded stream (deterministic given an identical
call sequence).  ``at_call`` faults are one-shot by default; everything
else fires every match (``max_fires`` overrides either).

Every audit-log entry records the engine's monotonic step index
(``set_step``, driven by ``ServeEngine.step``) alongside the per-point
call index, so a post-mortem can replay a chaos schedule
deterministically: the (step, point, call) triple pins each firing to
one seam arrival of one engine iteration.

Flight-recorder contract (serve/trace.py, docs/observability.md): every
injection point MUST be registered in ``serve.trace.FAULT_POINT_EVENTS``
— the engine mirrors each audit entry into its event ring, and a tier-1
meta-test greps the source for ``.fire("<point>"`` seams and fails on an
unregistered one, so a new failure path cannot silently skip the
recorder.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np


class InjectedFault(RuntimeError):
    """The error an armed fault point raises.  Deliberately NOT a
    :class:`serve.block_manager.BlockExhausted`: an injected allocation
    fault must exercise the engine's quarantine path, not the ordinary
    preemption machinery."""


class InjectedKill(BaseException):
    """Simulated process death (``inject(..., kill=True)``).  Derives
    from :class:`BaseException` so every ``except Exception`` containment
    path lets it through untouched — exactly like a SIGKILL, the only
    party that may handle it is the harness standing in for the OS
    (which abandons the engine object and restarts from the snapshot +
    token journal on disk; docs/serving.md "Crash recovery")."""


@dataclass
class _FaultSpec:
    point: str
    error: Optional[str] = None
    stall_s: float = 0.0
    skew_s: float = 0.0
    at_call: Optional[int] = None
    rate: float = 1.0
    rid: Optional[str] = None
    op: Optional[str] = None
    max_fires: Optional[int] = None
    kill: bool = False
    fires: int = 0


class FaultInjector:
    """Seeded, deterministic fault injection (see module docstring).

    Usage::

        inj = FaultInjector(seed=7)
        inj.inject("forward", rid="r3", op="paged_decode", error="boom")
        inj.inject("forward", at_call=5, stall_s=2.0)       # one-shot
        inj.inject("callback", rate=0.1, error="flaky ui")  # seeded
        inj.inject("clock", at_call=9, skew_s=120.0)
        engine = ServeEngine(..., faults=inj)

    ``fired`` is the audit log — ``(point, call_index, kind, who,
    step)`` tuples in firing order (``step`` is the engine iteration
    index fed through :meth:`set_step`) — so a test or a post-mortem can
    assert exactly which faults a run hit, at which seam arrival, on
    which engine step, and replay the schedule deterministically.
    ``disabled()`` gates everything off (engine warmup runs under it:
    dummy traffic must not eat injected faults, and call counts stay
    aligned with production traffic whether or not warmup ran).
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = np.random.default_rng(seed)
        self._specs: list[_FaultSpec] = []
        self.calls: dict[str, int] = {}   # per-point enabled arrivals
        self.fired: list[tuple] = []      # (point, call#, kind, who, step)
        self.step = 0                     # engine step index (set_step)
        self._skew = 0.0
        self._enabled = True

    # -- arming -----------------------------------------------------------

    def inject(self, point: str, *, error: Optional[str] = None,
               stall_s: float = 0.0, skew_s: float = 0.0,
               kill: bool = False,
               at_call: Optional[int] = None, rate: float = 1.0,
               rid: Optional[str] = None, op: Optional[str] = None,
               max_fires: Optional[int] = None) -> "FaultInjector":
        """Arm one fault spec; returns ``self`` so specs chain."""
        if error is None and not stall_s and not skew_s and not kill:
            raise ValueError("a fault needs an action: error=, stall_s=, "
                             "skew_s= or kill=")
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        if max_fires is None and at_call is not None:
            max_fires = 1
        self._specs.append(_FaultSpec(
            point, error, stall_s, skew_s, at_call, rate, rid, op,
            max_fires, kill))
        return self

    def set_step(self, step: int) -> None:
        """Record the engine's monotonic iteration index; every audit
        entry from here on carries it (the serving engine calls this at
        the top of each ``step()``)."""
        self.step = int(step)

    @contextlib.contextmanager
    def disabled(self):
        """Every fault point no-ops inside (arrivals are not counted)."""
        prev, self._enabled = self._enabled, False
        try:
            yield
        finally:
            self._enabled = prev

    # -- the fault points -------------------------------------------------

    def fire(self, point: str, *, rid: Optional[str] = None,
             rids: tuple = (), op: Optional[str] = None) -> None:
        """Called by an instrumented seam each time execution passes
        ``point``; may raise :class:`InjectedFault`, sleep, or no-op."""
        if not self._enabled:
            return
        n = self.calls[point] = self.calls.get(point, 0) + 1
        for f in self._specs:
            if f.point != point:
                continue
            if f.max_fires is not None and f.fires >= f.max_fires:
                continue
            if f.rid is not None and f.rid != rid and f.rid not in rids:
                continue
            if f.op is not None and f.op != op:
                continue
            if f.at_call is not None:
                if f.at_call != n:
                    continue
            elif f.rate < 1.0 and self._rng.random() >= f.rate:
                continue
            f.fires += 1
            kind = ("kill" if f.kill else "error" if f.error is not None
                    else "stall" if f.stall_s else "skew")
            who = rid or (f.rid if f.rid in rids else None) or op
            self.fired.append((point, n, kind, who, self.step))
            if f.skew_s:
                self._skew += f.skew_s
            if f.stall_s:
                time.sleep(f.stall_s)
            if f.kill:
                raise InjectedKill(
                    f"injected kill at {point} #{n} (step {self.step})"
                    f"{f' ({who})' if who else ''}")
            if f.error is not None:
                raise InjectedFault(
                    f"injected {point} fault #{n}"
                    f"{f' ({who})' if who else ''}: {f.error}")

    def wrap_clock(self, clock):
        """Wrap an engine clock: each reading passes the ``clock`` fault
        point (arm ``skew_s=`` specs there — never ``error=``) and adds
        the accumulated skew."""
        def skewed():
            self.fire("clock")
            return clock() + self._skew
        return skewed

    # -- accounting -------------------------------------------------------

    def fire_count(self, point: Optional[str] = None) -> int:
        return sum(1 for x in self.fired if point is None or x[0] == point)
