"""Deterministic fault injection for the failure-containment layer.

The serving engine's containment paths (docs/serving.md "Failure
containment") are only trustworthy if they are *exercised*: a quarantine
path that no test can reach is a crash waiting for production.  This
module provides the chaos half of that contract — a seeded
:class:`FaultInjector` whose hooks are threaded through the engine and
block-manager seams, so every containment path can be driven
deterministically by tier-1 tests (fixed schedules) and probabilistically
by the slow chaos soak (seeded rates).

Fault points the serving stack instruments (``fire(point, **ctx)``):

==============  =======================  ================================
point           context                  seam
==============  =======================  ================================
``forward``     ``op=<program>, rids``   every engine device dispatch
                                         (``ServeEngine._device_call``)
``block_alloc`` ``rid``                  ``BlockManager.ensure`` grow path
``callback``    ``rid``                  the ``on_token`` invocation seam
``clock``       —                        each reading of a
                                         ``wrap_clock()``-wrapped clock
``net``         ``op, target, where``    the network serving plane
                                         (serve/net.py): every client
                                         request and both server halves
                                         (``where`` = ``client`` /
                                         ``server_recv`` /
                                         ``server_resp``)
``integrity``   ``op, rid``              durable/wire artifact writes
                                         (``op`` = ``journal`` /
                                         ``snapshot`` / ``push`` /
                                         ``migrate_in`` / ``drain``):
                                         journal-line appends, the
                                         snapshot tmp-dir window, and
                                         wire manifest blobs
==============  =======================  ================================

Actions: ``error=`` raises :class:`InjectedFault` at the point;
``stall_s=`` sleeps there (inside the engine's watchdog-watched thunk, so
an injected stall trips the step watchdog exactly like a wedged device);
``skew_s=`` jumps the wrapped clock forward (expires request deadlines);
``kill=True`` raises :class:`InjectedKill` — a BaseException standing in
for process death, which no containment path may swallow (the crash-
recovery tests catch it at the harness level, abandon the engine object
like the OS would, and restart from disk).

Network actions (the ``net`` point; docs/serving.md "Network fleet
serving"): ``drop=True`` raises :class:`InjectedNetFault` at the seam —
the packet is lost (a client seam drop means the request never left; a
``server_recv`` drop means it never arrived; a ``server_resp`` drop
means the action LANDED but the ack was lost — the seam idempotent-retry
tests live on); ``delay_s=`` sleeps the call (drives client timeouts);
``duplicate=True`` makes the transport send the request twice (the
server must dedupe); ``partition=True`` is a PERSISTENT drop — every
matching call raises until :meth:`heal` clears it (the deterministic
stand-in for a network partition; pair with ``target=`` to cut one
replica off).

Corruption actions (the ``integrity`` point; docs/serving.md
"Durability & integrity"): ``corrupt="bitflip"|"truncate"|"zero"``
makes :meth:`fire` RETURN the action string (like ``"duplicate"``),
and the instrumented seam damages the artifact's bytes with
:func:`corrupt_bytes` — a journal line before its write, a snapshot
pool leaf inside the unrenamed tmp dir, a wire manifest KV blob before
the send / after the receive.  The seams write genuinely-damaged bytes
to disk/wire, so the VERIFIERS (journal CRC framing, snapshot leaf
digests, manifest digests) are what the chaos tests prove, not the
injection plumbing.

A spec fires when its filters match: ``at_call`` pins the nth *enabled*
arrival at the point, ``rid`` / ``op`` restrict to one request / program,
``target`` / ``where`` restrict a ``net`` spec to one peer / seam side,
``rate`` draws from the seeded stream (deterministic given an identical
call sequence).  ``at_call`` faults are one-shot by default; everything
else fires every match (``max_fires`` overrides either).

Every audit-log entry records the engine's monotonic step index
(``set_step``, driven by ``ServeEngine.step``) alongside the per-point
call index, so a post-mortem can replay a chaos schedule
deterministically: the (step, point, call) triple pins each firing to
one seam arrival of one engine iteration.

Flight-recorder contract (serve/trace.py, docs/observability.md): every
injection point MUST be registered in ``serve.trace.FAULT_POINT_EVENTS``
— the engine mirrors each audit entry into its event ring, and a tier-1
meta-test greps the source for ``.fire("<point>"`` seams and fails on an
unregistered one, so a new failure path cannot silently skip the
recorder.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np


class InjectedFault(RuntimeError):
    """The error an armed fault point raises.  Deliberately NOT a
    :class:`serve.block_manager.BlockExhausted`: an injected allocation
    fault must exercise the engine's quarantine path, not the ordinary
    preemption machinery."""


class InjectedKill(BaseException):
    """Simulated process death (``inject(..., kill=True)``).  Derives
    from :class:`BaseException` so every ``except Exception`` containment
    path lets it through untouched — exactly like a SIGKILL, the only
    party that may handle it is the harness standing in for the OS
    (which abandons the engine object and restarts from the snapshot +
    token journal on disk; docs/serving.md "Crash recovery")."""


class InjectedNetFault(RuntimeError):
    """A lost packet (``drop=``) or a severed link (``partition=``) at a
    ``net`` seam.  The network transport (serve/net.py) is the ONLY
    party that may catch it — it must treat the firing exactly like a
    real socket error: the client retries under backoff, the server
    aborts the connection without answering."""

    def __init__(self, msg: str, action: str):
        super().__init__(msg)
        self.action = action


#: the corruption vocabulary of the ``integrity`` fault point
CORRUPT_ACTIONS = ("bitflip", "truncate", "zero")


def corrupt_bytes(data: bytes, action: str) -> bytes:
    """Deterministically damage ``data`` per one ``integrity`` action:
    ``bitflip`` XORs one bit mid-payload (the classic silent-rot shape
    — the payload stays the same length and mostly plausible),
    ``truncate`` drops the second half (a torn write), ``zero``
    blanks everything (a lost extent).  Empty input passes through —
    there is nothing to damage."""
    if action not in CORRUPT_ACTIONS:
        raise ValueError(f"unknown corrupt action {action!r}; "
                         f"expected one of {CORRUPT_ACTIONS}")
    if not data:
        return data
    if action == "bitflip":
        i = len(data) // 2
        return data[:i] + bytes([data[i] ^ 0x01]) + data[i + 1:]
    if action == "truncate":
        return data[:len(data) // 2]
    return b"\x00" * len(data)


@dataclass
class _FaultSpec:
    point: str
    error: Optional[str] = None
    stall_s: float = 0.0
    skew_s: float = 0.0
    at_call: Optional[int] = None
    rate: float = 1.0
    rid: Optional[str] = None
    op: Optional[str] = None
    max_fires: Optional[int] = None
    kill: bool = False
    net: Optional[str] = None       # drop / duplicate / partition
    corrupt: Optional[str] = None   # bitflip / truncate / zero
    target: Optional[str] = None    # net peer filter (replica name)
    where: Optional[str] = None     # net seam side filter
    healed: bool = False            # heal() turned this spec off
    fires: int = 0


class FaultInjector:
    """Seeded, deterministic fault injection (see module docstring).

    Usage::

        inj = FaultInjector(seed=7)
        inj.inject("forward", rid="r3", op="paged_decode", error="boom")
        inj.inject("forward", at_call=5, stall_s=2.0)       # one-shot
        inj.inject("callback", rate=0.1, error="flaky ui")  # seeded
        inj.inject("clock", at_call=9, skew_s=120.0)
        engine = ServeEngine(..., faults=inj)

    ``fired`` is the audit log — ``(point, call_index, kind, who,
    step)`` tuples in firing order (``step`` is the engine iteration
    index fed through :meth:`set_step`) — so a test or a post-mortem can
    assert exactly which faults a run hit, at which seam arrival, on
    which engine step, and replay the schedule deterministically.
    ``disabled()`` gates everything off (engine warmup runs under it:
    dummy traffic must not eat injected faults, and call counts stay
    aligned with production traffic whether or not warmup ran).
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = np.random.default_rng(seed)
        self._specs: list[_FaultSpec] = []
        self.calls: dict[str, int] = {}   # per-point enabled arrivals
        self.fired: list[tuple] = []      # (point, call#, kind, who, step)
        self.step = 0                     # engine step index (set_step)
        self._skew = 0.0
        self._enabled = True

    # -- arming -----------------------------------------------------------

    def inject(self, point: str, *, error: Optional[str] = None,
               stall_s: float = 0.0, skew_s: float = 0.0,
               kill: bool = False, drop: bool = False,
               delay_s: float = 0.0, duplicate: bool = False,
               partition: bool = False, corrupt: Optional[str] = None,
               target: Optional[str] = None,
               where: Optional[str] = None,
               at_call: Optional[int] = None, rate: float = 1.0,
               rid: Optional[str] = None, op: Optional[str] = None,
               max_fires: Optional[int] = None) -> "FaultInjector":
        """Arm one fault spec; returns ``self`` so specs chain."""
        net = ("drop" if drop else "duplicate" if duplicate
               else "partition" if partition else None)
        if sum((drop, duplicate, partition)) > 1:
            raise ValueError("drop=, duplicate= and partition= are "
                             "mutually exclusive net actions")
        if corrupt is not None and corrupt not in CORRUPT_ACTIONS:
            raise ValueError(f"corrupt= must be one of {CORRUPT_ACTIONS},"
                             f" got {corrupt!r}")
        stall_s = stall_s or delay_s
        if (error is None and not stall_s and not skew_s and not kill
                and net is None and corrupt is None):
            raise ValueError("a fault needs an action: error=, stall_s=, "
                             "skew_s=, kill=, drop=, delay_s=, "
                             "duplicate=, partition= or corrupt=")
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        if max_fires is None and at_call is not None:
            max_fires = 1
        self._specs.append(_FaultSpec(
            point, error, stall_s, skew_s, at_call, rate, rid, op,
            max_fires, kill, net, corrupt, target, where))
        return self

    def heal(self, point: str = "net", *,
             target: Optional[str] = None) -> int:
        """Deactivate armed specs at ``point`` (optionally only those
        filtered to ``target``) — the deterministic end of a
        ``partition=`` window.  Returns how many specs it healed."""
        n = 0
        for f in self._specs:
            if f.point != point or f.healed:
                continue
            if target is not None and f.target != target:
                continue
            f.healed = True
            n += 1
        return n

    def set_step(self, step: int) -> None:
        """Record the engine's monotonic iteration index; every audit
        entry from here on carries it (the serving engine calls this at
        the top of each ``step()``)."""
        self.step = int(step)

    @contextlib.contextmanager
    def disabled(self):
        """Every fault point no-ops inside (arrivals are not counted)."""
        prev, self._enabled = self._enabled, False
        try:
            yield
        finally:
            self._enabled = prev

    # -- the fault points -------------------------------------------------

    def fire(self, point: str, *, rid: Optional[str] = None,
             rids: tuple = (), op: Optional[str] = None,
             target: Optional[str] = None,
             where: Optional[str] = None) -> Optional[str]:
        """Called by an instrumented seam each time execution passes
        ``point``; may raise :class:`InjectedFault` /
        :class:`InjectedNetFault`, sleep, or no-op.  Returns
        ``"duplicate"`` when a net duplicate spec fired (the transport
        must then send the request twice), a :data:`CORRUPT_ACTIONS`
        string when an ``integrity`` corrupt spec fired (the seam must
        then damage the artifact's bytes via :func:`corrupt_bytes`),
        else ``None``."""
        if not self._enabled:
            return None
        n = self.calls[point] = self.calls.get(point, 0) + 1
        result = None
        for f in self._specs:
            if f.point != point or f.healed:
                continue
            if f.max_fires is not None and f.fires >= f.max_fires:
                continue
            if f.rid is not None and f.rid != rid and f.rid not in rids:
                continue
            if f.op is not None and f.op != op:
                continue
            if f.target is not None and f.target != target:
                continue
            if f.where is not None and f.where != where:
                continue
            if f.at_call is not None:
                if f.at_call != n:
                    continue
            elif f.rate < 1.0 and self._rng.random() >= f.rate:
                continue
            f.fires += 1
            kind = (f.net if f.net is not None
                    else f.corrupt if f.corrupt is not None
                    else "kill" if f.kill
                    else "error" if f.error is not None
                    else "stall" if f.stall_s else "skew")
            who = (rid or (f.rid if f.rid in rids else None) or target
                   or op)
            self.fired.append((point, n, kind, who, self.step))
            if f.skew_s:
                self._skew += f.skew_s
            if f.stall_s:
                time.sleep(f.stall_s)
            if f.kill:
                raise InjectedKill(
                    f"injected kill at {point} #{n} (step {self.step})"
                    f"{f' ({who})' if who else ''}")
            if f.net in ("drop", "partition"):
                raise InjectedNetFault(
                    f"injected net {f.net} at {point} #{n}"
                    f"{f' ({who})' if who else ''}"
                    f"{f' [{where}]' if where else ''}", f.net)
            if f.net == "duplicate":
                result = "duplicate"
            if f.corrupt is not None:
                result = f.corrupt
            if f.error is not None:
                raise InjectedFault(
                    f"injected {point} fault #{n}"
                    f"{f' ({who})' if who else ''}: {f.error}")
        return result

    def wrap_clock(self, clock):
        """Wrap an engine clock: each reading passes the ``clock`` fault
        point (arm ``skew_s=`` specs there — never ``error=``) and adds
        the accumulated skew."""
        def skewed():
            self.fire("clock")
            return clock() + self._skew
        return skewed

    # -- accounting -------------------------------------------------------

    def fire_count(self, point: Optional[str] = None) -> int:
        return sum(1 for x in self.fired if point is None or x[0] == point)
