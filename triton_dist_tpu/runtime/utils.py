"""Benchmarking, test-data, printing and correctness-check utilities.

Reference analog: ``python/triton_dist/utils.py`` —
``perf_func`` (:186-198), ``dist_print`` (:201-230), ``_make_tensor``
(:134-166), ``generate_data`` (:169-171), ``assert_allclose`` (:789-818).
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def dist_print(*args, prefix: bool = True, allowed_ranks: Sequence[int] | str = (0,), **kwargs):
    """Rank-filtered printing (reference: utils.py:201-230).

    On TPU, "rank" at host level is ``jax.process_index()``.  Pass
    ``allowed_ranks="all"`` to print from every process, ordered by rank.
    """
    pid = jax.process_index()
    if allowed_ranks == "all":
        allowed = list(range(jax.process_count()))
    else:
        allowed = list(allowed_ranks)
    if pid in allowed:
        if prefix:
            print(f"[rank {pid}]", *args, **kwargs)
        else:
            print(*args, **kwargs)
        sys.stdout.flush()


def perf_func(
    func: Callable[[], jax.Array | Sequence[jax.Array]],
    iters: int = 100,
    warmup_iters: int = 10,
) -> tuple[object, float]:
    """Time ``func`` and return ``(last_output, avg_ms_per_iter)``.

    Reference analog: CUDA-event timed loop (utils.py:186-198).  TPU-native:
    dispatch is async, so we block on the final output with
    ``jax.block_until_ready`` — the XLA analog of event elapsed time.
    """
    out = None
    for _ in range(max(warmup_iters, 1)):
        out = func()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = func()
    jax.block_until_ready(out)
    t1 = time.perf_counter()
    return out, (t1 - t0) * 1e3 / iters


_INT_DTYPES = (jnp.int8, jnp.int16, jnp.int32, jnp.int64, jnp.uint8, jnp.uint32)


def make_tensor(
    key: jax.Array,
    shape: Sequence[int],
    dtype=jnp.bfloat16,
    init: str = "randn",
    scale: float = 1.0,
) -> jax.Array:
    """Seeded tensor factory incl. int8/fp8 (reference: _make_tensor utils.py:134-166).

    ``init``: "randn" | "uniform" | "ones" | "zeros" | "arange" | "randint".
    """
    shape = tuple(shape)
    if init == "ones":
        return jnp.ones(shape, dtype)
    if init == "zeros":
        return jnp.zeros(shape, dtype)
    if init == "arange":
        return jnp.arange(np.prod(shape)).reshape(shape).astype(dtype)
    if init == "randint" or dtype in _INT_DTYPES:
        return jax.random.randint(key, shape, -3, 4, dtype=jnp.int32).astype(dtype)
    if init == "uniform":
        x = jax.random.uniform(key, shape, jnp.float32, -1.0, 1.0)
    else:
        x = jax.random.normal(key, shape, jnp.float32)
    return (x * scale).astype(dtype)


def generate_data(key: jax.Array, configs: Sequence[tuple]) -> list[jax.Array]:
    """Generate a list of tensors from (shape, dtype, init) tuples."""
    keys = jax.random.split(key, len(configs))
    return [make_tensor(k, *cfg) for k, cfg in zip(keys, configs)]


def assert_allclose(
    x: jax.Array | np.ndarray,
    y: jax.Array | np.ndarray,
    atol: float = 1e-3,
    rtol: float = 1e-3,
    max_mismatch_to_print: int = 10,
    verbose: bool = True,
):
    """Verbose allclose with mismatch locations (reference: utils.py:789-818)."""
    xn = np.asarray(jax.device_get(x), dtype=np.float64)
    yn = np.asarray(jax.device_get(y), dtype=np.float64)
    if xn.shape != yn.shape:
        raise AssertionError(f"shape mismatch: {xn.shape} vs {yn.shape}")
    close = np.isclose(xn, yn, atol=atol, rtol=rtol)
    if close.all():
        return
    bad = np.argwhere(~close)
    n_bad = bad.shape[0]
    msg = [
        f"assert_allclose failed: {n_bad}/{xn.size} mismatched "
        f"({100.0 * n_bad / xn.size:.3f}%), atol={atol} rtol={rtol}"
    ]
    if verbose:
        for idx in bad[:max_mismatch_to_print]:
            t = tuple(int(i) for i in idx)
            msg.append(f"  at {t}: {xn[t]!r} vs {yn[t]!r} (diff {abs(xn[t]-yn[t]):.6g})")
        amax = np.unravel_index(np.abs(xn - yn).argmax(), xn.shape)
        msg.append(f"  max abs diff {np.abs(xn - yn).max():.6g} at {tuple(int(i) for i in amax)}")
    raise AssertionError("\n".join(msg))


def bitwise_equal(x: jax.Array, y: jax.Array) -> bool:
    """Exact comparison used by deterministic-reduction tests."""
    return bool(np.array_equal(np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y))))
