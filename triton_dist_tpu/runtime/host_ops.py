"""ctypes bindings for the native host planning ops (csrc/host_ops).

Reference analog: the pybind11 op registry over csrc CUDA host helpers
(csrc/lib/registry.cc, op_pybind.cc:36-41 exposing
``moe_ag_scatter_align_block_size``).  Ours binds a plain-C shared library
with ctypes (no pybind11 in the image) and auto-builds it with make on
first use; a numpy fallback keeps toolchain-less environments working.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "csrc", "host_ops")
_LIB_PATH = os.path.join(_SRC, "build", "libtdt_hostops.so")

_lock = threading.Lock()
_lib = None
_lib_tried = False


def _load():
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        if not os.path.exists(_LIB_PATH):
            if shutil.which("make") is None or shutil.which("g++") is None:
                return None
            # Build into a process-unique dir and publish with an atomic
            # rename so concurrent workers (one process per host) never
            # dlopen a half-written .so.
            tmp_build = f"build.tmp.{os.getpid()}"
            try:
                subprocess.run(["make", "-C", _SRC, f"BUILD={tmp_build}"],
                               check=True, capture_output=True)
                os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
                os.replace(os.path.join(_SRC, tmp_build,
                                        "libtdt_hostops.so"), _LIB_PATH)
            except (subprocess.CalledProcessError, OSError):
                if not os.path.exists(_LIB_PATH):  # a peer may have won
                    return None
            finally:
                shutil.rmtree(os.path.join(_SRC, tmp_build),
                              ignore_errors=True)
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.tdt_moe_ag_scatter_align_block_size.restype = ctypes.c_int
        lib.tdt_moe_ag_scatter_align_block_size.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            i32p, i32p, i32p, i32p, i32p]
        lib.tdt_stable_rank_in_group.restype = ctypes.c_int
        lib.tdt_stable_rank_in_group.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int32, i32p, i32p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _as_i32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.int32))


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _capacity(numel_per_rank: int, n_ranks: int, n_experts: int,
              block_m: int) -> int:
    per_rank = (numel_per_rank + n_experts * (block_m - 1)
                + block_m - 1) // block_m * block_m
    return per_rank * n_ranks


def moe_ag_scatter_align_block_size(topk_ids, n_ranks: int, n_experts: int,
                                    block_m: int, pad_value: int = -1,
                                    impl: str = "auto"):
    """Host planner for the AG-GroupGEMM feeder (see csrc/host_ops).

    ``topk_ids``: [n_ranks * numel_per_rank] (or [n_ranks, ...]) expert ids
    in gathered rank-major order.  Returns a dict with ``sorted_token_ids``
    [capacity], ``tile_expert`` / ``tile_src_rank`` [capacity // block_m],
    ``rank_block_num`` [n_ranks], ``total_padded`` int.
    """
    flat = _as_i32(topk_ids).reshape(-1)
    if n_ranks <= 0 or flat.size % n_ranks != 0:
        raise ValueError(
            f"topk_ids size {flat.size} not divisible by n_ranks {n_ranks}")
    numel_per_rank = flat.size // n_ranks
    cap = _capacity(numel_per_rank, n_ranks, n_experts, block_m)

    lib = _load() if impl in ("auto", "native") else None
    if impl == "native" and lib is None:
        raise RuntimeError("native host ops unavailable (no toolchain?)")

    sorted_ids = np.empty(cap, np.int32)
    tile_expert = np.full(cap // block_m, -1, np.int32)
    tile_src_rank = np.full(cap // block_m, -1, np.int32)
    rank_block_num = np.zeros(n_ranks, np.int32)
    total = np.zeros(1, np.int32)

    if lib is not None:
        rc = lib.tdt_moe_ag_scatter_align_block_size(
            _ptr(flat), numel_per_rank, n_ranks, n_experts, block_m,
            pad_value, cap, _ptr(sorted_ids), _ptr(tile_expert),
            _ptr(tile_src_rank), _ptr(rank_block_num), _ptr(total))
        if rc != 0:
            raise ValueError(f"moe_ag_scatter_align_block_size rc={rc}")
    else:  # numpy fallback, same semantics
        sorted_ids[:] = pad_value
        base = 0
        for r in range(n_ranks):
            seg = flat[r * numel_per_rank:(r + 1) * numel_per_rank]
            if seg.size and (seg.min() < 0 or seg.max() >= n_experts):
                raise ValueError("expert id out of range")
            counts = np.bincount(seg, minlength=n_experts)
            padded = (counts + block_m - 1) // block_m * block_m
            starts = np.concatenate([[0], np.cumsum(padded)[:-1]])
            order = np.argsort(seg, kind="stable")
            rank_in = np.arange(seg.size) - np.concatenate(
                [[0], np.cumsum(counts)[:-1]])[seg[order]]
            dst = base + starts[seg[order]] + rank_in
            sorted_ids[dst] = order + r * numel_per_rank
            for e in range(n_experts):
                t0 = (base + starts[e]) // block_m
                for t in range(padded[e] // block_m):
                    tile_expert[t0 + t] = e
                    tile_src_rank[t0 + t] = r
            rank_block_num[r] = padded.sum() // block_m
            base += int(padded.sum())
        total[0] = base

    return {"sorted_token_ids": sorted_ids, "tile_expert": tile_expert,
            "tile_src_rank": tile_src_rank, "rank_block_num": rank_block_num,
            "total_padded": int(total[0])}


def stable_rank_in_group_host(keys, n_groups: int):
    """Host twin of moe_utils.stable_rank_in_group (native when built)."""
    flat = _as_i32(keys).reshape(-1)
    rank = np.empty(flat.size, np.int32)
    counts = np.zeros(n_groups, np.int32)
    lib = _load()
    if lib is not None:
        rc = lib.tdt_stable_rank_in_group(_ptr(flat), flat.size, n_groups,
                                          _ptr(rank), _ptr(counts))
        if rc != 0:
            raise ValueError("key out of range")
        return rank, counts
    if flat.size and (flat.min() < 0 or flat.max() >= n_groups):
        raise ValueError("key out of range")
    counts_np = np.bincount(flat, minlength=n_groups)
    order = np.argsort(flat, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts_np)[:-1]])
    rank[order] = np.arange(flat.size) - starts[flat[order]]
    return rank, counts_np.astype(np.int32)
