"""Sharding-aware checkpoint / resume for distributed training state.

The reference has **no** checkpoint story (SURVEY.md §5: it is a kernel
library with no training state).  A standalone framework needs one: training
runs that use the overlapped kernels (models/llama.py, models/moe.py,
models/pp.py, models/cp.py) carry a params + opt-state pytree sharded over a
`jax.sharding.Mesh`, and that state must survive preemption and resume onto
a possibly *different* mesh layout.

Design (TPU/JAX-native, not a torch.save port):

- The durable format is **Orbax** (the JAX-ecosystem checkpointer): each
  jax.Array leaf is written as a sharded tensorstore array, so on multi-host
  pods every process writes only its addressable shards and restore can
  re-lay-out onto any mesh.  We wrap rather than re-implement: the wrapper
  pins down path handling, abstract-target construction, and a stable
  save/restore/latest API so callers never touch orbax types.
- ``restore`` takes either a concrete "like" tree (template arrays, e.g. a
  freshly initialised model) or an abstract tree of ShapeDtypeStruct; either
  way the restored leaves land directly in the template's shardings —
  resume does not round-trip through host memory on the hot path.
- ``CheckpointManager`` adds step numbering, retention (``max_to_keep``)
  and ``latest_step`` discovery for resumable training loops.

Typical loop::

    mgr = CheckpointManager(dir, max_to_keep=3)
    start = 0
    resumed = mgr.restore_latest(like=state)
    if resumed is not None:
        last_step, state = resumed
        start = last_step + 1
    for step in range(start, n_steps):
        state = train_step(state, batch)
        mgr.save(step, state)
"""

from __future__ import annotations

import os
import shutil
import sys
from typing import Any

import jax
import numpy as np


def _is_primary() -> bool:
    return jax.process_index() == 0


def _sync_hosts(name: str) -> None:
    """Barrier across processes (no-op single-process)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _abstract_like(tree: Any) -> Any:
    """Concrete-or-abstract tree -> abstract tree carrying shardings."""

    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        x = np.asarray(x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    return jax.tree.map(leaf, tree)


def save(path: str | os.PathLike, tree: Any, *, force: bool = True) -> None:
    """Write one pytree of (sharded) jax.Arrays to ``path`` (a directory).

    Blocking: when this returns the checkpoint is durable.  On multi-host,
    every process must call this collectively with its addressable shards
    (orbax coordinates the single logical write).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.fspath(path))
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=force)
    ckptr.wait_until_finished()
    ckptr.close()


def restore(path: str | os.PathLike, like: Any) -> Any:
    """Read a pytree written by :func:`save` into ``like``'s shardings.

    ``like`` may be a concrete tree (e.g. freshly-initialised params already
    placed via ``place_params``) or a tree of ``jax.ShapeDtypeStruct`` with
    ``.sharding`` set.  Leaves come back as jax.Arrays with exactly those
    shardings, regardless of the mesh the checkpoint was written under.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(os.fspath(path))
    ckptr = ocp.StandardCheckpointer()
    out = ckptr.restore(path, _abstract_like(like))
    ckptr.close()
    return out


class CheckpointManager:
    """Step-numbered checkpoints with retention and latest-step discovery.

    Layout: ``<directory>/<step>/`` per checkpoint, written via :func:`save`.
    Retention removes the oldest directories beyond ``max_to_keep`` after a
    successful save (newest are always kept).  Steps are discovered from the
    directory, so a fresh process can resume with no side state.
    """

    def __init__(self, directory: str | os.PathLike, *, max_to_keep: int = 3,
                 clean_tmp: bool = True):
        self.directory = os.path.abspath(os.fspath(directory))
        self.max_to_keep = int(max_to_keep)
        os.makedirs(self.directory, exist_ok=True)
        # Crash-window GC: a save killed between the tensorstore write
        # and the rename leaves an orphaned ``<step>.tmp`` that nothing
        # would ever reclaim (all_steps() ignores it, and the same step
        # number may never be saved again).  Only a WRITER may reclaim
        # it (``clean_tmp=True``, the default): a writer opening the
        # directory is by contract the only live writer, so any .tmp it
        # finds is garbage from a dead process.  A read-only consumer
        # (e.g. a standby loading the latest snapshot) must pass
        # ``clean_tmp=False`` — rmtree-ing here would tear a live
        # writer's in-flight save out from under it.
        if clean_tmp and _is_primary():
            for name in os.listdir(self.directory):
                if name.endswith(".tmp"):
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)
        _sync_hosts("tdt:ckpt:init")

    # -- discovery ---------------------------------------------------------
    def all_steps(self) -> list[int]:
        # Only all-digit directory names count: an interrupted save is a
        # ``<step>.tmp`` directory (renamed into place after the write
        # completes), which fails ``isdigit`` and stays invisible.
        steps = []
        for name in os.listdir(self.directory):
            full = os.path.join(self.directory, name)
            if name.isdigit() and os.path.isdir(full):
                steps.append(int(name))
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _step_path(self, step: int) -> str:
        return os.path.join(self.directory, str(int(step)))

    # -- save / restore ----------------------------------------------------
    def save(self, step: int, tree: Any, *,
             extras: dict[str, str] | None = None,
             on_before_finalize=None) -> str:
        """Durably write ``tree`` as checkpoint ``step``; prune old steps.

        The orbax write goes to ``<step>.tmp`` and is renamed into place
        only after it completes, so a preemption mid-save never corrupts
        the latest resumable checkpoint.  ``extras`` maps extra file
        names to string contents written into the tmp directory before
        the rename — host-side metadata (e.g. the serving engine's
        snapshot manifest) publishes atomically WITH the arrays, never
        before or after them.  ``on_before_finalize(tmp_path)`` runs
        last before the rename (the chaos tests inject a kill there to
        land exactly in the torn-snapshot window).

        Pruning runs BEFORE the rename barrier and always spares the
        current newest step: with the old prune-after ordering, a
        concurrent ``restore_latest`` that had just listed the previous
        latest could find its directory mid-``rmtree`` right after the
        new step appeared.  Now the step a reader can have picked stays
        on disk through the save that supersedes it; counting the
        incoming step, disk holds ``max(max_to_keep, 2)`` directories —
        the grace copy only exceeds ``max_to_keep`` when it is 1.

        The orbax write itself is collective (every process must call
        this); the surrounding directory mutations (clean / extras /
        prune / rename) run on process 0 only, bracketed by cross-host
        syncs, since all processes share one checkpoint directory.
        """
        final = self._step_path(step)
        tmp = final + ".tmp"
        if _is_primary():
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            if os.path.exists(final):
                shutil.rmtree(final)
        _sync_hosts("tdt:ckpt:pre_save")
        save(tmp, tree)
        if _is_primary():
            for name, content in (extras or {}).items():
                with open(os.path.join(tmp, name), "w") as f:
                    f.write(content)
                    f.flush()
                    os.fsync(f.fileno())
            if on_before_finalize is not None:
                on_before_finalize(tmp)
            self._prune()
            os.replace(tmp, final)
        _sync_hosts("tdt:ckpt:post_save")
        return final

    def restore(self, step: int, like: Any) -> Any:
        return restore(self._step_path(step), like)

    def restore_latest(self, like: Any) -> tuple[int, Any] | None:
        """(step, tree) for the newest readable checkpoint, or None if
        empty.  Walks newest → oldest: a step that fails to read (torn
        by a crash, or pruned by a concurrent writer between the listing
        and the read) falls back to the next-older one instead of
        failing a resume that an older intact checkpoint could serve.
        Raises only when steps exist but none restores."""
        steps = self.all_steps()
        err: Exception | None = None
        for step in reversed(steps):
            try:
                return step, self.restore(step, like)
            except Exception as e:  # noqa: BLE001 — fall back, re-raised
                err = e             # below when nothing was readable
                # Loud fallback: resuming from an older step silently
                # would hide a rollback (a transient read error on the
                # newest step costs real progress — the operator must
                # be able to tell it happened from the logs).
                print(f"[checkpoint] step {step} under {self.directory} "
                      f"failed to restore ({e!r}); falling back to the "
                      f"next older step", file=sys.stderr)
        if err is not None:
            raise err
        return None

    def _prune(self) -> None:
        """Remove steps beyond retention.  Called BEFORE the rename
        barrier publishes the incoming step, and always keeps the
        current newest existing step (the one a concurrent reader can
        have picked as latest) — with the incoming step, disk holds
        ``max(max_to_keep, 2)`` directories after a save."""
        if self.max_to_keep <= 0:
            return
        steps = self.all_steps()
        keep = max(self.max_to_keep - 1, 1)
        for s in steps[:-keep]:
            shutil.rmtree(self._step_path(s), ignore_errors=True)

    def wait(self) -> None:
        """Saves are blocking; kept for API symmetry with async backends."""
