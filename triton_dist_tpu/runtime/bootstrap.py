"""Bootstrap: process/mesh initialization for single- and multi-host TPU.

Reference analog: ``triton_dist.utils.initialize_distributed``
(/root/reference/python/triton_dist/utils.py:91-111) which does
torchrun env → ``torch.distributed.init_process_group("nccl")`` → seed →
``pynvshmem.init_nvshmem_by_uniqueid``.

TPU-native design: there is no separate SHMEM bootstrap — XLA's runtime owns
the ICI/DCN fabric.  ``initialize_distributed()``:

1. calls ``jax.distributed.initialize()`` when multi-host env vars are present
   (coordinator address via ``JAX_COORDINATOR_ADDRESS`` or TPU metadata),
2. builds the global device ``Mesh`` (1-D ``("tp",)`` by default, or an
   explicit multi-axis shape for tp/sp/dp/pp/ep),
3. seeds deterministic RNG per-process,
4. registers the mesh as the process-wide default used by the kernel library.

The "TP group over all ranks" of the reference maps to the mesh axis; rank =
``jax.lax.axis_index(axis)`` inside shard_map, or ``jax.process_index()`` on
the host.
"""

from __future__ import annotations

import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_MESH: Mesh | None = None
_INITIALIZED: bool = False


def init_seed(seed: int = 42) -> jax.Array:
    """Seeded, deterministic RNG key (reference: utils.py:75-88 init_seed).

    XLA is deterministic by construction for a fixed HLO; we only need a
    per-process base key.  Returns a ``jax.random.key``.
    """
    np.random.seed(seed)
    return jax.random.key(seed)


def initialize_distributed(
    mesh_shape: Mapping[str, int] | Sequence[int] | None = None,
    axis_names: Sequence[str] = ("tp",),
    seed: int = 42,
) -> Mesh:
    """Initialize the distributed runtime and return the global device mesh.

    Args:
      mesh_shape: either a dict ``{"dp": 2, "tp": 4}`` or a tuple matching
        ``axis_names``.  Default: all devices on a single ``"tp"`` axis.
      axis_names: names for the mesh axes when ``mesh_shape`` is a tuple/None.
      seed: deterministic seed (reference seeds torch/cuda with RANK-dependent
        seeds; XLA PRNG is counter-based so one base seed suffices).
    """
    global _MESH, _INITIALIZED
    if not _INITIALIZED:
        # Multi-host: initialize the JAX distributed system if a coordinator
        # is configured (GKE/TPU-VM set these; single-host runs skip it).
        # This MUST happen before any backend comes up — do not touch
        # jax.devices()/process_count() first.
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
            "COORDINATOR_ADDRESS"
        )
        if coord and "JAX_NUM_PROCESSES" in os.environ:
            try:
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
                    process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
                )
            except RuntimeError as e:
                # Already initialized (by the launcher) or backends already
                # up (single-host dev flow) — proceed with what we have.
                if "already" not in str(e) and "must be called before" not in str(e):
                    raise
        _INITIALIZED = True

    init_seed(seed)

    devices = jax.devices()
    if mesh_shape is None:
        shape = {axis_names[0]: len(devices)}
        for ax in axis_names[1:]:
            shape[ax] = 1
    elif isinstance(mesh_shape, Mapping):
        shape = dict(mesh_shape)
    else:
        shape = dict(zip(axis_names, mesh_shape))

    n = int(np.prod(list(shape.values())))
    if n > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {n} devices, have {len(devices)}"
        )
    dev_array = np.asarray(devices[:n]).reshape(tuple(shape.values()))
    mesh = Mesh(dev_array, tuple(shape.keys()))
    _MESH = mesh
    return mesh


def finalize_distributed() -> None:
    """Tear down the global mesh (reference: nvshmem finalize)."""
    global _MESH
    _MESH = None


def set_mesh(mesh: Mesh) -> None:
    """Register an externally-built mesh as the process default."""
    global _MESH
    _MESH = mesh


def get_mesh() -> Mesh:
    """Return the registered global mesh, initializing a default if needed."""
    if _MESH is None:
        return initialize_distributed()
    return _MESH


def default_mesh(n_devices: int | None = None, axis: str = "tp") -> Mesh:
    """Build (without registering) a 1-D mesh over the first ``n_devices``."""
    devices = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def rank(axis: str | Sequence[str] = "tp") -> jax.Array:
    """Device rank along ``axis``; only valid inside shard_map/pjit tracing.

    Reference analog: ``dl.rank()`` (language.py:84-88) →
    ``distributed.get_rank`` → ``nvshmem_my_pe``.
    """
    return jax.lax.axis_index(axis)


def num_ranks(axis: str | Sequence[str] = "tp") -> int:
    """World size along ``axis`` inside shard_map (reference: dl.num_ranks)."""
    return jax.lax.axis_size(axis)
