"""Canonical virtual-mesh ("fake cluster") environment recipe.

IMPORT-FREE ON PURPOSE: this module must be loadable before jax exists in
the process (conftest.py and tutorials/_common.py load it by file path with
importlib so the package __init__ — which imports jax — never runs).  Keep
it free of any imports beyond the stdlib ``os``.

One source of truth for every place that fabricates the multi-device CPU
test world: tests/conftest.py, tutorials/_common.py, scripts/launch.py.
"""

import os


def virtual_mesh_env(env: dict | None = None, n_devices: int = 16) -> dict:
    """Return ``env`` (default: a copy of os.environ) updated for an
    ``n_devices``-device virtual CPU mesh:

    - ``JAX_PLATFORMS=cpu`` — never touch a real accelerator;
    - drop ``PALLAS_AXON_POOL_IPS`` — a sitecustomize hook otherwise
      registers the single-holder TPU-tunnel backend;
    - append ``--xla_force_host_platform_device_count=N`` to XLA_FLAGS.
    """
    env = dict(os.environ) if env is None else env
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    if flag not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + flag).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def apply_virtual_mesh_env(n_devices: int = 16) -> None:
    """In-place variant for os.environ (call BEFORE any jax import)."""
    os.environ.update(
        {k: v for k, v in virtual_mesh_env(dict(os.environ),
                                           n_devices).items()})
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
