"""Process-wide cache for jitted shard_map entry points.

Host-level ops build ``jax.jit(jax.shard_map(partial(fn, **opts), ...))``
closures; a fresh closure per call would defeat jit's trace cache and
recompile every step.  ``cached_shard_jit`` memoizes the jitted callable on
the (builder, mesh, specs, opts) key so repeated calls hit the compiled
executable.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax


@functools.lru_cache(maxsize=256)
def _build(builder: Callable, mesh, in_specs, out_specs, opts: tuple, _noise_key):
    from triton_dist_tpu.runtime import dump

    fn = functools.partial(builder, **dict(opts))
    jitted = jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    )
    # TDT_DUMP_IR=<dir>: write this program's StableHLO + optimized HLO on
    # first call (the reference's per-kernel dump_ir hook; dump.py).  The
    # name carries a program discriminator (two programs from one builder
    # must not overwrite each other) and the rank (shared dump dirs).
    import hashlib

    disc = hashlib.sha1(repr((str(mesh), in_specs, out_specs,
                              opts)).encode()).hexdigest()[:8]
    name = f"{builder.__name__}.{disc}.r{jax.process_index()}"
    return dump.wrap_for_dump(jitted, name)


def cached_shard_jit(builder: Callable, mesh, in_specs, out_specs, **opts):
    """Return a cached ``jit(shard_map(partial(builder, **opts)))``.

    ``builder`` must be a module-level function (stable identity) and every
    opt value hashable.  The key includes ``race.trace_key()`` so ops traced
    inside ``for_correctness()`` (comm-noise injection) never share an
    executable with production traces.
    """
    from triton_dist_tpu.language import race

    return _build(builder, mesh, in_specs, out_specs,
                  tuple(sorted(opts.items())), race.trace_key())
