"""Process-wide cache for jitted shard_map entry points.

Host-level ops build ``jax.jit(jax.shard_map(partial(fn, **opts), ...))``
closures; a fresh closure per call would defeat jit's trace cache and
recompile every step.  ``cached_shard_jit`` memoizes the jitted callable on
the (builder, mesh, specs, opts) key so repeated calls hit the compiled
executable.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax


@functools.lru_cache(maxsize=256)
def _build(builder: Callable, mesh, in_specs, out_specs, opts: tuple, _noise_key):
    fn = functools.partial(builder, **dict(opts))
    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    )


def cached_shard_jit(builder: Callable, mesh, in_specs, out_specs, **opts):
    """Return a cached ``jit(shard_map(partial(builder, **opts)))``.

    ``builder`` must be a module-level function (stable identity) and every
    opt value hashable.  The key includes ``race.trace_key()`` so ops traced
    inside ``for_correctness()`` (comm-noise injection) never share an
    executable with production traces.
    """
    from triton_dist_tpu.language import race

    return _build(builder, mesh, in_specs, out_specs,
                  tuple(sorted(opts.items())), race.trace_key())
