"""Process-wide cache for jitted shard_map entry points.

Host-level ops build ``jax.jit(jax.shard_map(partial(fn, **opts), ...))``
closures; a fresh closure per call would defeat jit's trace cache and
recompile every step.  ``cached_shard_jit`` memoizes the jitted callable on
the (builder, mesh, specs, opts) key so repeated calls hit the compiled
executable.

Observability: :func:`cache_stats` exposes the memo cache's hit/miss/size
counters, and :class:`CountingJit` wraps any jitted callable with
per-call-site trace-cache accounting (hits, misses, cumulative time spent
inside miss calls — i.e. compile stalls).  The serving engine threads both
through ``serve.metrics.ServeMetrics`` onto the ``TDT_DUMP_IR`` dump path,
so "how many programs did this traffic compile" is a counter, not a guess.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Optional

import jax


def pow2_ladder(cap: int) -> list[int]:
    """Powers-of-two rungs ``[1, 2, 4, ...]`` closing at ``cap`` — the
    generic bucket ladder for STATIC trace parameters.  The serving
    engine keys its decode-horizon scan length to these rungs so a
    horizon clamped mid-generation (a row near its max-token end) reuses
    a compiled program instead of tracing one per residual length; the
    page-aligned scratch-extent variant is
    ``serve.engine.build_bucket_ladder``."""
    if cap < 1:
        raise ValueError(f"ladder cap must be >= 1, got {cap}")
    rungs = []
    r = 1
    while r < cap:
        rungs.append(r)
        r *= 2
    rungs.append(cap)
    return rungs


def bucket_down(ladder: list[int], value: int) -> int:
    """Largest rung <= ``value`` (``ladder`` ascending, ``value >=
    ladder[0]``).  Static trace parameters bucket DOWN, not up: a rung
    above the need would run dead iterations that still pay full compute
    (a scan step is a whole batched forward), while a rung below just
    costs one more dispatch for the residual."""
    if value < ladder[0]:
        raise ValueError(f"value {value} below ladder base {ladder[0]}")
    best = ladder[0]
    for r in ladder:
        if r > value:
            break
        best = r
    return best


def abstract_signature(args: tuple, kwargs: dict) -> tuple:
    """(args, kwargs) with every array leaf abstracted to a
    ``jax.ShapeDtypeStruct`` (non-array leaves pass through) — exactly
    what ``jax.make_jaxpr`` needs to re-trace the call device-free.
    The jaxpr auditor (``analysis/jaxpr_audit.py``) replays captured
    signatures through this to audit compiled programs without holding
    live buffers."""
    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x

    return jax.tree_util.tree_map(leaf, (args, kwargs))


#: Most trace signatures one CountingJit retains for the auditor; the
#: ladders bound real programs far below this — hitting the cap would
#: itself be a retrace hazard the audit should surface.
MAX_CAPTURED_SIGNATURES = 64


def cache_stats() -> dict:
    """Hit/miss/size counters of the process-wide shard-jit memo cache
    (``functools.lru_cache`` on :func:`_build`).  A *miss* here means a
    fresh ``jax.jit(shard_map(...))`` closure was built — i.e. a new
    program family entered the process."""
    info = _build.cache_info()
    return {"hits": info.hits, "misses": info.misses,
            "currsize": info.currsize, "maxsize": info.maxsize}


class CountingJit:
    """Wrap a jitted callable with trace-cache hit/miss accounting.

    A *miss* is a call that grew the wrapped jit's executable cache
    (``_cache_size()`` — a new (shapes, dtypes, statics) signature was
    traced AND compiled); everything else is a hit.  The wall time of
    miss calls accumulates in ``compile_time`` — on the serving admission
    path that IS the compile stall a request would have eaten.  When the
    runtime lacks ``_cache_size`` the wrapper falls back to hashing the
    call signature host-side (shapes/dtypes of array leaves, ``repr`` of
    everything else), which over-counts only if an outer cache already
    held the executable.

    Transparent otherwise: ``__call__`` forwards args/kwargs verbatim, so
    donation and traced-kwarg behavior of the wrapped jit are unchanged.

    **Per-program wall-time attribution** (docs/observability.md
    "Kernel observability"): setting ``timer`` to a ``(label, ms)``
    callable reports every call's wall time under this wrapper's name —
    the serving engine wires ``ServeMetrics.observe_program`` here
    behind its ``trace_level`` knob, so engine step time decomposes by
    device program.  ``timed_statics`` names static kwargs whose values
    suffix the label (the horizon's ``H``, the spec round's ``K``), so
    a rung-laddered program attributes per rung
    (``decode_horizon[H=8]``).  ``timer=None`` (default) keeps the hot
    path at one attribute check.
    """

    def __init__(self, fn: Callable, name: str,
                 timer: Optional[Callable] = None,
                 timed_statics: tuple = ()):
        self.fn = fn
        self.name = name
        self.timer = timer
        self.timed_statics = tuple(timed_statics)
        self.hits = 0
        self.misses = 0
        self.compile_time = 0.0
        self._keys: set = set()
        self._sized = hasattr(fn, "_cache_size")
        #: sig-key -> abstracted (args, kwargs) of each distinct traced
        #: call (captured on miss only — zero steady-state overhead);
        #: the jaxpr auditor re-traces these via ``abstract_signature``.
        self.captured: dict = {}

    @staticmethod
    def _sig(args, kwargs) -> tuple:
        def leaf(x):
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return ("arr", tuple(x.shape), str(x.dtype))
            return ("obj", repr(x))

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        return (str(treedef), tuple(leaf(x) for x in leaves))

    def __call__(self, *args, **kwargs):
        before = self.fn._cache_size() if self._sized else None
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if self._sized:
            fresh = self.fn._cache_size() > before
        else:
            key = self._sig(args, kwargs)
            fresh = key not in self._keys
            self._keys.add(key)
        if fresh:
            self.misses += 1
            self.compile_time += dt
            if len(self.captured) < MAX_CAPTURED_SIGNATURES:
                self.captured.setdefault(
                    self._sig(args, kwargs),
                    abstract_signature(args, kwargs))
        else:
            self.hits += 1
        timer = self.timer
        if timer is not None and not fresh:
            # miss calls are compile stalls — already accounted in
            # compile_time, and they must never pollute the per-program
            # wall-time distributions (a no-warmup engine's first call
            # of each program would otherwise dominate its p99/max)
            label = self.name
            for k in self.timed_statics:
                v = kwargs.get(k)
                if v is not None:
                    label = f"{label}[{k}={v}]"
            timer(label, dt * 1e3)
        return out

    @property
    def cache_size(self) -> Optional[int]:
        """Distinct compiled programs behind this wrapper (None when the
        runtime can't report it)."""
        return self.fn._cache_size() if self._sized else None

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "compile_time_s": self.compile_time,
                "cache_size": self.cache_size}


@functools.lru_cache(maxsize=256)
def _build(builder: Callable, mesh, in_specs, out_specs, opts: tuple, _noise_key):
    from triton_dist_tpu.runtime import dump

    fn = functools.partial(builder, **dict(opts))
    jitted = jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    )
    # TDT_DUMP_IR=<dir>: write this program's StableHLO + optimized HLO on
    # first call (the reference's per-kernel dump_ir hook; dump.py).  The
    # name carries a program discriminator (two programs from one builder
    # must not overwrite each other) and the rank (shared dump dirs).
    import hashlib

    disc = hashlib.sha1(repr((str(mesh), in_specs, out_specs,
                              opts)).encode()).hexdigest()[:8]
    name = f"{builder.__name__}.{disc}.r{jax.process_index()}"
    return dump.wrap_for_dump(jitted, name)


def cached_shard_jit(builder: Callable, mesh, in_specs, out_specs, **opts):
    """Return a cached ``jit(shard_map(partial(builder, **opts)))``.

    ``builder`` must be a module-level function (stable identity) and every
    opt value hashable.  The key includes ``race.trace_key()`` so ops traced
    inside ``for_correctness()`` (comm-noise injection) never share an
    executable with production traces.
    """
    from triton_dist_tpu.language import race

    return _build(builder, mesh, in_specs, out_specs,
                  tuple(sorted(opts.items())), race.trace_key())
