"""Symmetric memory: the TPU-native replacement for the NVSHMEM symmetric heap.

Reference analog: ``pynvshmem`` (`shmem/nvshmem_bind/pynvshmem/python/
pynvshmem/__init__.py:94-167`) — ``nvshmem_create_tensor`` allocates a buffer
at the same virtual offset on every PE so device code can address peers'
copies (``nvshmem_ptr``).

TPU-native design: under SPMD (shard_map over a Mesh) every device executes
the same program on identically-shaped shards, so **symmetry is a property of
the programming model, not of an allocator**.  A "symmetric tensor" is simply
a sharded ``jax.Array`` whose per-device shard plays the role of the PE-local
symmetric buffer; remote access is Mosaic async remote DMA addressed by
logical device id (`triton_dist_tpu.language.putmem_*` / `symm_at` analog).

What still needs managing is *workspace lifetime*: overlapped kernels need
persistent scratch (signal arrays, staging buffers) that survives across
steps and can be donated in-place.  ``SymmetricWorkspace`` provides that.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def create_symm_tensor(
    mesh: Mesh,
    axis: str,
    per_device_shape: Sequence[int],
    dtype=jnp.bfloat16,
    init: float | None = 0.0,
) -> jax.Array:
    """Allocate a sharded array whose per-device shard has ``per_device_shape``.

    Reference analog: ``nvshmem_create_tensor(shape, dtype)`` — every PE gets
    a same-shape buffer.  Here the global array has leading dim
    ``n_ranks * per_device_shape[0]`` sharded over ``axis``.
    """
    n = mesh.shape[axis]
    global_shape = (n * per_device_shape[0], *per_device_shape[1:])
    sharding = NamedSharding(mesh, P(axis, *([None] * (len(per_device_shape) - 1))))
    if init is None:
        return jax.device_put(
            jnp.empty(global_shape, dtype), sharding
        )
    return jax.device_put(jnp.full(global_shape, init, dtype), sharding)


@dataclass
class SymmetricWorkspace:
    """Persistent per-op scratch buffers, donated in-place across calls.

    Reference analog: the ``*Context`` dataclasses
    (e.g. ``AllGatherGEMMTensorParallelContext``, allgather_gemm.py:407-489)
    that own symm workspace + signal arrays + streams.  TPU has no streams;
    the workspace here is only buffers.  Buffers are keyed by name.
    """

    mesh: Mesh
    axis: str
    buffers: dict = field(default_factory=dict)

    def get(self, name: str, per_device_shape: Sequence[int], dtype=jnp.bfloat16):
        key = (name, tuple(per_device_shape), jnp.dtype(dtype).name)
        if key not in self.buffers:
            self.buffers[key] = create_symm_tensor(
                self.mesh, self.axis, per_device_shape, dtype
            )
        return self.buffers[key]

    def reset(self):
        self.buffers.clear()


def replicate(mesh: Mesh, x) -> jax.Array:
    """Put an array fully-replicated over ``mesh``."""
    x = jnp.asarray(x)
    return jax.device_put(x, NamedSharding(mesh, P(*([None] * x.ndim))))


def shard_along(mesh: Mesh, x, axis: str, dim: int = 0) -> jax.Array:
    """Shard array ``x`` along dim ``dim`` over mesh axis ``axis``."""
    x = jnp.asarray(x)
    spec = [None] * x.ndim
    spec[dim] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))
