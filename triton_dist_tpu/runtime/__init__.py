"""Host runtime: bootstrap, mesh, symmetric memory, topology, benchmarking.

Reference analog: ``python/triton_dist/utils.py`` (initialize_distributed,
perf_func, dist_print, assert_allclose, topology detection) and
``shmem/nvshmem_bind/pynvshmem`` (symmetric tensors).
"""

from triton_dist_tpu.runtime.bootstrap import (  # noqa: F401
    initialize_distributed,
    finalize_distributed,
    get_mesh,
    set_mesh,
    default_mesh,
    rank,
    num_ranks,
    init_seed,
)
from triton_dist_tpu.runtime.utils import (  # noqa: F401
    assert_allclose,
    dist_print,
    perf_func,
    make_tensor,
    generate_data,
)
from triton_dist_tpu.runtime.symm_mem import (  # noqa: F401
    create_symm_tensor,
    SymmetricWorkspace,
)
from triton_dist_tpu.runtime.topology import (  # noqa: F401
    TopologyInfo,
    detect_topology,
    is_tpu,
    device_kind,
    ici_bandwidth_gbps,
    hbm_bandwidth_gbps,
    peak_bf16_tflops,
)
from triton_dist_tpu.runtime.profiling import group_profile  # noqa: F401
from triton_dist_tpu.runtime.checkpoint import (  # noqa: F401
    CheckpointManager,
)
from triton_dist_tpu.runtime.watchdog import (  # noqa: F401
    Heartbeat,
    WatchdogTimeout,
    block_until_ready_with_timeout,
    run_with_watchdog,
)
from triton_dist_tpu.runtime.faults import (  # noqa: F401
    FaultInjector,
    InjectedFault,
)
