"""Interpret-mode configuration for CPU-mesh testing of distributed kernels.

The Mosaic TPU interpreter (``pltpu.InterpretParams``) simulates multi-device
Pallas — including cross-chip remote DMA and semaphores — on a virtual CPU
mesh.  This is the framework's "fake cluster" test backend (SURVEY.md §4: the
reference has no such thing; every reference test needs real GPUs).

We default to ``dma_execution_mode="eager"``: data movement happens at
``.start()``, matching the hardware guarantee that a receive-semaphore
increment implies the data has landed.  The default ``"on_wait"`` mode defers
DMA execution to semaphore waits, which breaks chained-RDMA patterns (ring
collectives forwarding a just-received chunk) that are correct on hardware.

Race detection (reference analog: the deliberate comm-stream slowdown
``_add_noise_workload_debug``, allgather.py:72-77) is available by running a
kernel with ``interpret_params(detect_races=True)`` — the interpreter's
vector-clock race detector reports unsynchronized accesses.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu


def _register_virtual_tpu_info() -> None:
    """Teach Pallas's hardware-info query about the CPU interpreter.

    ``pltpu.emit_pipeline`` (and other Mosaic helpers) query
    ``tpu_info.get_tpu_info()`` for tiling decisions; on the virtual CPU mesh
    there is no TPU device kind, so we register a virtual chip — modeled on
    TPU v5p (the bench target) — via the module's public ``registry`` hook.
    """
    try:
        from jax._src.pallas.mosaic import tpu_info as _ti
    except ImportError:  # pragma: no cover - jax internals moved
        return
    reg = getattr(_ti, "registry", None)
    if reg is None or "cpu" in reg:
        return

    def _virtual_v5p() -> "_ti.TpuInfo":
        return _ti.TpuInfo(
            chip_version=_ti.ChipVersion.TPU_V5P,
            generation=5,
            num_cores=1,
            num_lanes=128,
            num_sublanes=8,
            mxu_column_size=128,
            vmem_capacity_bytes=64 * 1024 * 1024,
            cmem_capacity_bytes=0,
            smem_capacity_bytes=1024 * 1024,
            hbm_capacity_bytes=95_000_000_000 // 2,
            mem_bw_bytes_per_second=int(2.76e12) // 2,
            bf16_ops_per_second=int(4.59e14) // 2,
            int8_ops_per_second=int(9.18e14) // 2,
            fp8_ops_per_second=0,
            int4_ops_per_second=0,
        )

    reg["cpu"] = _virtual_v5p


_register_virtual_tpu_info()


def interpret_params(detect_races: bool = False) -> "pltpu.InterpretParams":
    if not hasattr(pltpu, "InterpretParams"):
        # Pre-Mosaic-interpreter jax (< 0.5): the generic Pallas
        # interpreter still runs single-device kernels (scalar prefetch,
        # grids, VMEM scratch); kernels that touch device semaphores or
        # remote DMA fail loudly there instead of here.  Race detection
        # has no generic-interpreter equivalent — a silent True would
        # turn race tests into vacuous passes, so refuse loudly.
        if detect_races:
            raise NotImplementedError(
                "detect_races needs the Mosaic TPU interpreter "
                "(pltpu.InterpretParams, jax >= 0.5); this jax only has "
                "the generic Pallas interpreter")
        return True
    return pltpu.InterpretParams(
        dma_execution_mode="eager",
        detect_races=detect_races,
    )


def maybe_interpret(interpret: bool, detect_races: bool = False):
    """The value to pass to ``pallas_call(interpret=...)``."""
    return interpret_params(detect_races) if interpret else False
