"""Interpret-mode configuration for CPU-mesh testing of distributed kernels.

The Mosaic TPU interpreter (``pltpu.InterpretParams``) simulates multi-device
Pallas — including cross-chip remote DMA and semaphores — on a virtual CPU
mesh.  This is the framework's "fake cluster" test backend (SURVEY.md §4: the
reference has no such thing; every reference test needs real GPUs).

We default to ``dma_execution_mode="eager"``: data movement happens at
``.start()``, matching the hardware guarantee that a receive-semaphore
increment implies the data has landed.  The default ``"on_wait"`` mode defers
DMA execution to semaphore waits, which breaks chained-RDMA patterns (ring
collectives forwarding a just-received chunk) that are correct on hardware.

Race detection (reference analog: the deliberate comm-stream slowdown
``_add_noise_workload_debug``, allgather.py:72-77) is available by running a
kernel with ``interpret_params(detect_races=True)`` — the interpreter's
vector-clock race detector reports unsynchronized accesses.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu


def interpret_params(detect_races: bool = False) -> pltpu.InterpretParams:
    return pltpu.InterpretParams(
        dma_execution_mode="eager",
        detect_races=detect_races,
    )


def maybe_interpret(interpret: bool, detect_races: bool = False):
    """The value to pass to ``pallas_call(interpret=...)``."""
    return interpret_params(detect_races) if interpret else False
