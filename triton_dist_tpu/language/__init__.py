"""The distributed primitive toolkit usable inside Pallas TPU kernels.

This module is the TPU-native equivalent of three reference layers at once:

1. the MLIR ``distributed`` dialect's 7 ops
   (/root/reference/dialect/include/Dialect/Distributed/IR/DistributedOps.td:45-189),
2. the Python frontend ``triton_dist.language``
   (/root/reference/python/triton_dist/language.py),
3. the NVSHMEM device API façade ``libshmem_device``
   (/root/reference/patches/triton/third_party/nvidia/language/cuda/libnvshmem_device.py).

On TPU there is no separate compiler patch: Mosaic already exposes device
semaphores and one-sided remote DMA as first-class kernel primitives, so the
whole dialect + lowering + bitcode-linking stack collapses into this thin
Python layer.  Mapping table:

================================  =============================================
reference primitive                TPU-native implementation
================================  =============================================
``dl.rank()`` / ``num_ranks()``    ``rank(axis)`` / ``num_ranks(axis)``
                                   (lax.axis_index / axis_size inside shard_map)
``dl.wait(barrier, n, scope,       ``wait(sem, n)`` — pltpu.semaphore_wait;
  semantic)``                      acquire semantics are implied (Mosaic DMA
                                   completion orders the data before the wait
                                   returns — no separate consume_token needed)
``dl.consume_token``               not needed: semaphore waits order
                                   subsequent ref reads in Mosaic's effect
                                   system (SSA token dance is a Triton-ism)
``dl.notify(ptr, rank, sig_op)``   ``notify(sem, axis=a, device_id=pe,
                                   inc=v)`` — pltpu.semaphore_signal (always
                                   ADD; SET is not exposed by hardware).
                                   ``device_id`` indexes along mesh axis ``a``
``dl.symm_at(ptr, rank)``          remote refs are addressed *per-copy* by
                                   ``device_id`` (symm_at returns no pointer —
                                   see ``remote_copy``'s dst semantics)
``libshmem_device.putmem_block``   ``putmem(src, dst, send_sem, recv_sem,
                                   axis, device_id)`` —
                                   pltpu.make_async_remote_copy (+.start)
``putmem_signal[_nbi]_block``      ``putmem_signal(...)`` — remote DMA whose
                                   recv semaphore IS the signal (fused, like
                                   put-with-completion-event; no separate flag
                                   write needed, and it is ordered correctly
                                   by hardware)
``getmem_*``                       ``getmem(...)`` — pulls are realized by
                                   SPMD mirror pushes (TPU RDMA is
                                   push-only); rank-relative peers only
``broadcast{8,16,...}_block``      ``broadcast(src, dst, ..., root)`` — the
                                   ~10 granularity variants collapse: one
                                   remote DMA moves any ref shape/dtype
``fcollect{8,16,...}``             ``fcollect(src, dst, ...)`` — in-kernel
                                   all-gather round (full-mesh push into
                                   per-rank slots)
``signal_op(sig, val, ADD, pe)``   ``notify(sem, axis=a, device_id=pe,
                                   inc=val)``
``signal_wait_until(sig, GE, v)``  ``wait(sem, v)`` (decrements; see note)
``fence()`` / ``quiet()``          ``fence()`` — wait on outstanding send
                                   semaphores (explicit, per-copy on TPU)
``barrier_all()``                  ``barrier_all(axis)`` — barrier-semaphore
                                   round with all peers
``atomic_add/cas`` (peer mem)      no remote atomics on ICI: use semaphore
                                   increments (which ARE remote atomic adds)
                                   or restructure to owner-computes (docs)
``tid/ntid/__syncthreads`` etc.    no user-visible threads in Mosaic; the
  (language_extra.py)              VPU/MXU are programmed as whole-core vector
                                   ops, ``pl.program_id`` plays blockIdx's role
``multimem_st/ld_reduce``          no NVLink-SHARP analog; ICI all-reduce is
                                   done in software rings (see kernels/)
================================  =============================================

Semantics note (wait): NVSHMEM ``signal_wait_until(GE, v)`` leaves the flag
set; Mosaic ``semaphore_wait(sem, v)`` *decrements* by ``v`` when satisfied.
Kernels here are written in the decrement style (each producer signal is
consumed exactly once), which also gives generation-counter reuse for free —
the double-buffer ``call_count`` parity trick of low_latency_all_to_all.py:35-119
is unnecessary.
"""

from triton_dist_tpu.language.race import for_correctness, maybe_noise  # noqa: F401
from triton_dist_tpu.language.primitives import (  # noqa: F401
    rank,
    num_ranks,
    wait,
    notify,
    putmem,
    putmem_signal,
    getmem,
    broadcast,
    fcollect,
    remote_copy,
    wait_arrival,
    local_copy,
    fence,
    barrier_all,
    collective_compiler_params,
    SIGNAL_DTYPE,
)
