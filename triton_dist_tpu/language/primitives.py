"""Distributed device primitives for Pallas TPU kernels.

See package docstring (`triton_dist_tpu/language/__init__.py`) for the full
mapping to the reference's dialect ops / libshmem_device API.
All functions here must be called from *inside* a Pallas kernel body that is
itself traced under ``shard_map`` (so ``lax.axis_index`` resolves).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Signals are semaphore counts (uint32 internally); exposed for buffers that
# pack flags into data words (LL protocol, low_latency_allgather.py:549-568).
SIGNAL_DTYPE = jnp.int32


def rank(axis: str | Sequence[str]) -> jax.Array:
    """My logical device index along the mesh axis.

    Reference: ``dl.rank()`` → GetRankOp → ``nvshmem_my_pe``
    (DistributedOps.td:113-121).
    """
    return jax.lax.axis_index(axis)


def num_ranks(axis: str | Sequence[str]):
    """World size along the mesh axis (reference: GetNumRanksOp)."""
    return jax.lax.axis_size(axis)


def wait(sem, value=1):
    """Block until ``sem >= value``, then decrement by ``value``.

    Reference: ``dl.wait(barrierPtrs, numBarriers, scope, semantic)``
    (DistributedOps.td:45-77; PTX spin-loop lowering
    DistributedOpToLLVM.cpp:144-217).  On TPU the scope/semantic knobs
    disappear: semaphore waits are full acquire barriers for DMA'd data, and
    there is no separate ``consume_token`` — Mosaic's effect system orders
    subsequent reads of the destination ref after the wait.
    """
    pltpu.semaphore_wait(sem, value)


def notify(sem, *, axis=None, device_id=None, inc=1):
    """Signal (atomically add to) a semaphore, optionally on a remote device.

    Reference: ``dl.notify(ptr, rank, signal_op=ADD, comm_scope)``
    (DistributedOps.td:151-164) and ``libshmem_device.signal_op``.
    ``device_id`` is the peer's index *along the mesh axis* ``axis`` (other
    mesh axes default to the caller's own coordinates, so addressing stays
    correct on multi-axis dp x tp meshes); ``device_id=None`` signals the
    local semaphore.
    """
    if device_id is None:
        pltpu.semaphore_signal(sem, inc=inc)
    else:
        if not isinstance(axis, str):
            raise TypeError(
                f"notify(device_id=...) needs axis=<mesh axis name>, got {axis!r}")
        pltpu.semaphore_signal(
            sem,
            inc=inc,
            device_id={axis: device_id},
            device_id_type=pltpu.DeviceIdType.MESH,
        )


def remote_copy(src_ref, dst_ref, send_sem, recv_sem, axis, device_id):
    """Build (not start) an async remote copy: local ``src_ref`` → ``dst_ref``
    on the peer at index ``device_id`` along mesh axis ``axis`` (other mesh
    axes keep the caller's own coordinates).

    Reference: the ``symm_at`` + ``putmem`` pair (DistributedOps.td:135-149 +
    libnvshmem_device putmem family).  NVSHMEM's model is "translate a
    symmetric address then store through it"; the TPU model is "issue a DMA
    descriptor naming the target device" — the symmetric-address translation
    is implicit in SPMD (every device's ``dst_ref`` is the same buffer).
    Returns the copy object: ``.start()`` / ``.wait()`` /
    ``.wait_send()`` / ``.wait_recv()``.
    """
    from triton_dist_tpu.language import race

    race.maybe_noise(axis)
    return pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id={axis: device_id},
        device_id_type=pltpu.DeviceIdType.MESH,
    )


def putmem(src_ref, dst_ref, send_sem, recv_sem, axis, device_id):
    """Start a non-blocking put (reference: ``putmem_nbi_block``).

    Returns the in-flight copy; call ``.wait_send()`` before reusing
    ``src_ref`` (NVSHMEM's ``quiet``), and the *receiver* waits on
    ``recv_sem`` for arrival.
    """
    cp = remote_copy(src_ref, dst_ref, send_sem, recv_sem, axis, device_id)
    cp.start()
    return cp


def putmem_signal(src_ref, dst_ref, send_sem, recv_sem, axis, device_id):
    """Put + arrival signal, fused (reference: ``putmem_signal_nbi_block``).

    On TPU the recv semaphore *is* the signal and is hardware-ordered after
    the data, so the reference's separate flag-store + memory-fence dance
    (NotifyOpConversion, DistributedOpToLLVM.cpp:231-340) is unnecessary.
    The receiver does ``wait(recv_sem)`` then reads ``dst_ref`` directly.
    """
    return putmem(src_ref, dst_ref, send_sem, recv_sem, axis, device_id)


def getmem(src_ref, dst_ref, send_sem, recv_sem, axis, *, offset):
    """Non-blocking pull: ``src_ref`` AS HELD BY the peer → local
    ``dst_ref`` (reference: ``getmem_nbi_block``; pull-style AG variants,
    allgather.py full-mesh *pull*).

    TPU RDMA is push-only (``make_async_remote_copy`` writes the remote
    dst), so the pull is realized by SPMD mirroring: every device pushes
    its ``src_ref`` to the peer that wants it.  The caller's ``.wait()``
    (or ``wait_arrival`` on ``recv_sem``) observes the data that lands
    locally, exactly like a completed get.

    Addressing is ``offset`` ONLY: a CONCRETE Python int ``k`` meaning
    "pull from ``(me + k) mod world``".  This form is safe by
    construction (the mirror peer is exactly ``me - k``) and covers every
    use in the reference (ring neighbors, fixed strides).  The retired
    traced ``device_id=`` form could not be validated — a
    traced-but-rank-invariant expression (e.g. a replicated routing-table
    entry) passed its best-effort guard and silently landed wrong shards
    (round-2 VERDICT weak #5).  A uniform "everyone pulls rank r" idiom
    cannot be mirrored into a push at all — use ``broadcast``/``putmem``
    from the owning rank instead.
    """
    me = jax.lax.axis_index(axis)
    world = jax.lax.axis_size(axis)
    if isinstance(offset, jax.core.Tracer):
        raise TypeError(
            "getmem offset= must be a concrete Python int (the statically "
            "rank-relative form, safe by construction).  Traced peer "
            "expressions are not supported: a traced-but-rank-invariant "
            "value cannot be mirrored into a push and silently lands wrong "
            "shards — restructure as broadcast/putmem from the owner.")
    offset %= world  # any magnitude/sign normalizes (world is static)
    mirror = jax.lax.rem(me - offset + 2 * world, world)
    cp = remote_copy(src_ref, dst_ref, send_sem, recv_sem, axis, mirror)
    cp.start()
    return cp


def broadcast(src_ref, dst_ref, send_sem, recv_sem, axis, root=0):
    """One-to-all, blocking: ``root``'s ``src_ref`` lands in every device's
    ``dst_ref`` (same shape) along ``axis``.

    Reference: the ``libnvshmem_device`` broadcast family
    (``broadcastmem_block`` / ``broadcast{8,16,32,64}...``, ~10 variants) —
    granularity variants collapse on TPU because one remote DMA moves any
    ref shape.  Owner-push formulation: the root streams its buffer to each
    peer (ICI routes the hops), peers block on arrival.  Like every
    collective verb, the caller must ensure all peers have entered the
    kernel first (``barrier_all`` — see its docstring contract).
    """
    world = num_ranks(axis)
    if not isinstance(root, jax.core.Tracer) and not 0 <= root < world:
        raise ValueError(
            f"broadcast root={root} outside [0, {world}): no rank would "
            "push and every device would hang on arrival")
    if world == 1:  # degenerate mesh: plain local copy
        cp = pltpu.make_async_copy(src_ref, dst_ref, send_sem)
        cp.start()
        cp.wait()
        return
    me = rank(axis)
    is_root = me == root

    @pl.when(is_root)
    def _():
        # Peer pushes source from src_ref, so they are independent of the
        # local src→dst copy — fire them first, overlap the local copy.
        for i in range(1, world):
            peer = jax.lax.rem(root + i, world)
            remote_copy(src_ref, dst_ref, send_sem, recv_sem, axis,
                        peer).start()
        cp = pltpu.make_async_copy(src_ref, dst_ref, send_sem)
        cp.start()
        cp.wait()
        for _ in range(1, world):  # drain sends (quiet)
            pltpu.make_async_copy(src_ref, src_ref, send_sem).wait()

    @pl.when(jnp.logical_not(is_root))
    def _():
        pltpu.make_async_copy(dst_ref, dst_ref, recv_sem).wait()


def fcollect(src_ref, dst_ref, send_sem, recv_sem, axis, *, copy_sem=None,
             stage_local=True):
    """All-gather, blocking: every device's ``src_ref`` [rows, ...] lands at
    slot ``rank`` of every device's ``dst_ref`` [world*rows, ...].

    Reference: NVSHMEM ``fcollect{8,16,32,...}`` (libnvshmem_device.py) —
    the in-kernel gather round the hierarchy/AG kernels otherwise re-derive.
    Full-mesh push: stage my shard into my slot of ``dst_ref``, push that
    slot to every peer, drain sends, then wait for the ``world-1`` incoming
    slots.  ``stage_local=False`` skips the staging copy when the caller
    already placed its shard (lets a kernel overlap the stage with its entry
    barrier).  Same entry-barrier contract as :func:`broadcast`.
    """
    world = num_ranks(axis)
    rows = src_ref.shape[0]
    me = rank(axis)
    mine = dst_ref.at[pl.ds(me * rows, rows)]
    # Remote pushes source from src_ref (not the dst slot), so they do not
    # depend on the staging copy — fire all of them first, then overlap the
    # local stage with the fan-out.
    for i in range(1, world):
        peer = jax.lax.rem(me + i, world)
        remote_copy(src_ref, mine, send_sem, recv_sem, axis, peer).start()
    if stage_local:
        cp = pltpu.make_async_copy(
            src_ref, mine, send_sem if copy_sem is None else copy_sem)
        cp.start()
        cp.wait()
    if world == 1:
        return
    for _ in range(1, world):  # drain sends (quiet)
        pltpu.make_async_copy(mine, mine, send_sem).wait()
    for _ in range(1, world):  # arrival of every peer slot
        pltpu.make_async_copy(mine, mine, recv_sem).wait()


def wait_arrival(ref, recv_sem):
    """Receiver-side wait for a sender-initiated put into ``ref``.

    Reference: ``signal_wait_until(sig_addr, NVSHMEM_CMP_GE, v)`` paired
    with ``putmem_signal`` (low_latency_all_to_all.py:35-119).  On TPU the
    recv semaphore of the sender's DMA is signaled on *this* device when
    the data lands; waiting for "one ``ref``-sized DMA worth" of completion
    consumes that signal.  (DMA semaphores count bytes, not events, so this
    wraps the make_async_copy descriptor trick.)
    """
    pltpu.make_async_copy(ref, ref, recv_sem).wait()


def local_copy(src_ref, dst_ref, sem):
    """Async local (same-chip) DMA; reference analog: cudaMemcpyAsync /
    ``dst.copy_(src)`` on the copy engine (allgather.py:122-135)."""
    cp = pltpu.make_async_copy(src_ref, dst_ref, sem)
    cp.start()
    return cp


def fence(*copies):
    """Complete outstanding sends (reference: ``libshmem_device.fence`` /
    ``quiet``).  TPU DMAs are tracked per-copy by their send semaphore, so the
    fence is explicit: pass the in-flight copies to drain."""
    for cp in copies:
        cp.wait_send()


def barrier_all(axis: str, sem=None):
    """Full barrier over the mesh axis.

    Reference: ``barrier_all_intra_node_atomic_cas_block``
    (common_ops.py:87-101) — a sys-scope CAS round over symm_at peers.
    TPU-native: signal every peer's barrier semaphore, then wait for
    ``n-1`` signals.  Uses the dedicated hardware barrier semaphore unless a
    regular semaphore is passed.  Kernels using this must set a
    ``collective_id`` in their CompilerParams.

    **Every collective kernel must call this before its first remote DMA or
    remote semaphore signal** (the reference's ``local_copy_and_barrier_all``
    preamble, allgather_gemm.py:100-116): a peer that has not yet entered the
    kernel may still be using its buffers (on hardware), and in interpret
    mode its buffers/semaphores may not exist yet — setting a
    ``collective_id`` suppresses the interpreter's implicit start barrier, so
    an eager remote DMA into a not-yet-allocated peer buffer kills that
    device thread and deadlocks the rest.  The barrier semaphore itself is
    exempt (it pre-exists all kernels, fixed-id), which is what makes this
    barrier the safe entry point.
    """
    n = jax.lax.axis_size(axis)
    if n == 1:
        # Degenerate mesh: a barrier touch (get_barrier_semaphore /
        # wait-for-zero) aborts the Mosaic hardware compiler, and there is
        # nobody to synchronize with.  Pair with
        # :func:`collective_compiler_params` so no collective_id is claimed.
        return
    me = jax.lax.axis_index(axis)
    bsem = pltpu.get_barrier_semaphore() if sem is None else sem

    def body(i, _):
        peer = jax.lax.rem(me + i, n)
        pltpu.semaphore_signal(
            bsem, inc=1, device_id={axis: peer},
            device_id_type=pltpu.DeviceIdType.MESH,
        )
        return 0

    jax.lax.fori_loop(1, n, body, 0)
    pltpu.semaphore_wait(bsem, n - 1)


def collective_compiler_params(world: int, collective_id: int, **kwargs):
    """CompilerParams for a collective Pallas kernel.

    Claims the barrier semaphore only on a real (world > 1) mesh: Mosaic
    rejects (or aborts on) a ``collective_id`` when the kernel never
    touches the barrier, and every kernel here guards its barrier/remote
    ops with ``world > 1`` (``barrier_all`` self-guards).  One helper so
    new kernels cannot forget the degenerate case.
    """
    return pltpu.CompilerParams(
        has_side_effects=True,
        collective_id=collective_id if world > 1 else None,
        **kwargs,
    )
