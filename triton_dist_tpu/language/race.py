"""Race-detection aid: randomized comm-path delays.

Reference analog: the ``for_correctness`` context flag —
``_add_noise_workload_debug`` injects random multi-second sleeps into the
comm stream so missing dependencies surface as wrong results instead of
lucky timing (allgather.py:72-77, used at :118-121; SURVEY.md §5 "race
detection").

TPU-native design: there is no comm stream to sleep on — delays are dummy
VPU work executed *before a remote copy is issued*.  Shifting issuance
order is exactly what breaks kernels that read data without waiting on its
semaphore: in interpret mode (eager DMA) data lands when the producer
issues, so a consumer that skips its ``wait``/``wait_arrival`` reads stale
buffer contents once the producer is delayed; on hardware the same shift
widens real race windows.  The delay length is pseudorandom per (rank,
call-site) so every run exercises a different interleaving.

Usage::

    with for_correctness():           # host-side, around tracing
        out = my_distributed_op(x)    # primitives now inject noise

Kernels built on ``triton_dist_tpu.language`` primitives get this for free
(putmem/getmem/remote_copy consult the flag at trace time); hand-rolled
kernels can call ``maybe_noise(axis, salt)`` before issuing DMAs.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ENABLED = False
_MAX_ITERS = 512
_callsite_counter = 0

# Hardware nanoseconds of extra sleep per spin iteration (so the injected
# skew is macroscopic on a real chip, like the reference's multi-second
# comm-stream sleeps scaled down to kernel timescales).
_NANOS_PER_ITER = 1000


def enabled() -> bool:
    return _ENABLED


def trace_key():
    """Hashable state that must participate in any trace-cache key.

    ``for_correctness`` changes what gets *traced*; a jit/shard-jit cache
    that ignores this flag silently serves the noise-free executable.
    ``runtime.jit_cache`` keys on this; plain ``jax.jit`` users are covered
    by the cache clears in ``for_correctness``.
    """
    return (_ENABLED, _MAX_ITERS)


@contextlib.contextmanager
def for_correctness(max_iters: int = 512):
    """Enable comm-noise injection while tracing ops under this context.

    Clears jax's trace caches on entry (so ops jitted before the context
    re-trace WITH noise) and on exit (so noisy executables don't leak into
    production calls).  This is a debug tool; the recompiles are the cost.
    """
    global _ENABLED, _MAX_ITERS, _callsite_counter
    prev, prev_iters = _ENABLED, _MAX_ITERS
    _ENABLED, _MAX_ITERS = True, max_iters
    _callsite_counter = 0
    jax.clear_caches()
    try:
        yield
    finally:
        _ENABLED, _MAX_ITERS = prev, prev_iters
        jax.clear_caches()


def delay(iters):
    """Delay of roughly ``iters`` noise units; survives compilation.

    Two mechanisms, because the two execution paths eliminate work
    differently:

    * a VPU spin loop — in interpret mode the kernel jaxpr is *evaluated*
      eqn-by-eqn (no DCE), so the loop burns real wall-clock on the device
      thread and staggers the simulated devices;
    * ``pl.delay`` (an effectful Mosaic primitive, a no-op in interpret
      mode) — on hardware it sleeps ``iters * _NANOS_PER_ITER`` ns, and its
      operand *consumes the spin result*, so Mosaic/XLA cannot DCE the loop
      as dead code (a pure unconsumed loop would be eliminated).
    """
    def body(_, acc):
        return acc * 1.000001 + 1.0

    acc = jax.lax.fori_loop(0, iters, body, jnp.float32(1.0))
    # (acc < 0) is always False but unprovable at compile time; feeding it
    # into the effectful delay anchors the spin against DCE.
    pl.delay(iters * _NANOS_PER_ITER + (acc < 0).astype(jnp.int32))


def maybe_noise(axis: str, salt: int = 0):
    """Insert a per-rank pseudorandom delay when ``for_correctness`` is on.

    Call before issuing a remote DMA in hand-rolled kernels.  Cheap no-op
    (trace-time constant False) when disabled.
    """
    global _callsite_counter
    if not _ENABLED:
        return
    _callsite_counter += 1
    me = jax.lax.axis_index(axis)
    # xorshift-style mix of rank and call site -> [0, _MAX_ITERS)
    h = (me.astype(jnp.uint32) * jnp.uint32(2654435761)
         + jnp.uint32(salt * 40503 + _callsite_counter * 9176))
    h = h ^ (h >> 13)
    delay((h % jnp.uint32(_MAX_ITERS)).astype(jnp.int32))
