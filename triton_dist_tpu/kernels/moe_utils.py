"""MoE routing + token sort/align — feeder for the grouped GEMM.

Reference analog: ``csrc/moe_utils.cu`` — the CUDA kernel
``moe_ag_scatter_align_block_size`` (serial + parallel variants, :61-356)
sorts gathered tokens by expert and pads each expert's row range to the
GEMM block size so every tile is single-expert; plus the host-side topk
preprocessing in ``create_moe_rs_context`` (moe_reduce_rs.py:278+).

TPU-native design: the sort/align runs **on device** as XLA ops (argsort +
cumsum — no host round trip, where the reference needs a custom CUDA kernel
and a pinned-memory readback).  Shapes stay static: the padded total is the
worst-case ``round_up(T*topk + E*(block_m-1), block_m)``, the TPU answer to
dynamic expert loads (SURVEY.md §7 hard part 2).

Data flow (matching the reference's GroupGEMM contract):

  tokens [T, D], router logits [T, E]
  -> topk_routing: weights/experts [T, topk]
  -> sort_align(block_m): dest row for every (token, k) pair, per-tile
     expert map, padded row count
  -> gather_sorted: x_sorted [M_pad, D] (padding rows zero)
  -> group_gemm (kernels/group_gemm.py): y_sorted [M_pad, F]
  -> combine_topk: out [T, F] = sum_k w[t,k] * y_sorted[dest[t,k]]
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def round_up(x, m: int):
    """Round up to a multiple of ``m`` (works on ints and jnp arrays)."""
    return (x + m - 1) // m * m


def padded_rows(n_assignments: int, n_experts: int, block_m: int) -> int:
    """Static worst-case row count after per-expert padding."""
    return round_up(n_assignments + n_experts * (block_m - 1), block_m)


def stable_rank_in_group(keys, n_groups: int):
    """Rank of each element among same-key elements, stable by position.

    Returns ``(rank [n] int32, counts [n_groups])``.  This is the scatter-slot
    idiom shared by the expert sort (group GEMM feeder, below) and the EP
    dispatch slot allocation (layers/ep_a2a.py) — the reference computes the
    same thing with atomic counters (moe_utils.cu:61-356 /
    ep_a2a.py:35-146 ``atomic_add_per_warp``).
    """
    n = keys.shape[0]
    counts = jnp.bincount(keys, length=n_groups)
    seg_starts = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)[:-1]])
    order = jnp.argsort(keys, stable=True)
    rank_sorted = (jnp.arange(n, dtype=jnp.int32)
                   - seg_starts[keys[order]].astype(jnp.int32))
    rank = jnp.zeros((n,), jnp.int32).at[order].set(rank_sorted)
    return rank, counts


def topk_routing(logits, topk: int):
    """Softmax-then-topk router (the reference tests' torch preprocessing).

    Returns (weights [T, topk] normalized, experts [T, topk] int32).
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    weights, experts = jax.lax.top_k(probs, topk)
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, experts.astype(jnp.int32)


def sort_align(experts, n_experts: int, block_m: int):
    """Stable-sort (token, k) pairs by expert and align groups to block_m.

    experts: [T, topk] int32.  Returns a dict:
      dest      [T*topk]  destination row of each assignment in the sorted buf
      tile_expert [M_pad // block_m] expert id of every row tile
      valid_rows  [M_pad] bool — False for padding rows
      m_pad     int (static)

    Reference: moe_ag_scatter_align_block_size (moe_utils.cu:61-356) —
    same outputs (sorted ids, expert offsets, padded sizes), computed with
    argsort+cumsum instead of a hand-written counting kernel.
    """
    T, topk = experts.shape
    n = T * topk
    flat = experts.reshape(-1)
    m_pad = padded_rows(n, n_experts, block_m)

    # Stable rank within each expert group (original (token, k) order).
    rank, counts = stable_rank_in_group(flat, n_experts)
    padded_counts = round_up(counts, block_m)
    group_starts = jnp.concatenate(
        [jnp.zeros((1,), counts.dtype), jnp.cumsum(padded_counts)[:-1]])
    dest = (group_starts[flat].astype(jnp.int32) + rank)

    n_tiles = m_pad // block_m
    tile_rows = jnp.arange(n_tiles) * block_m
    group_ends = jnp.cumsum(padded_counts)
    tile_expert = jnp.searchsorted(group_ends, tile_rows, side="right")
    tile_expert = jnp.minimum(tile_expert, n_experts - 1).astype(jnp.int32)

    valid = jnp.zeros((m_pad,), bool).at[dest].set(True)
    return {"dest": dest, "tile_expert": tile_expert,
            "valid_rows": valid, "m_pad": m_pad}


def gather_sorted(x, dest, m_pad: int):
    """Scatter token rows into the expert-sorted padded buffer.

    x: [T, D]; dest: [T*topk] rows.  Padding rows stay zero so they
    contribute nothing downstream.
    """
    T, D = x.shape
    topk = dest.shape[0] // T
    token_of = jnp.arange(dest.shape[0]) // topk
    return jnp.zeros((m_pad, D), x.dtype).at[dest].set(x[token_of])


def combine_topk(y_sorted, dest, weights, out_dtype=None):
    """out[t] = sum_k weights[t, k] * y_sorted[dest[t, k]].

    Reference: the topk-reduce in consumer_reduce_scatter_reduce_2d
    (moe_reduce_rs.py:817+).
    """
    T, topk = weights.shape
    gathered = y_sorted[dest.reshape(T, topk)]          # [T, topk, F]
    out = jnp.einsum("tk,tkf->tf", weights.astype(jnp.float32),
                     gathered.astype(jnp.float32))
    return out.astype(out_dtype or y_sorted.dtype)
