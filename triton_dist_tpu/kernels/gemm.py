"""Base Pallas TPU matmul — the MXU workhorse under every overlapped kernel.

Reference analog: the persistent TMA GEMM inner loops of
``allgather_gemm.py:133-254`` / ``gemm_reduce_scatter.py:125-188`` (Triton
``tl.dot`` over K with TMA descriptor loads).

TPU-native design: Pallas ``pallas_call`` with a (m, n, k) grid; the Mosaic
pipeline plays the role of both the TMA prefetch and the software pipeliner
(no hand-written double buffering needed for HBM→VMEM streaming).  A float32
VMEM accumulator carries partial sums across the K grid dimension
(TPU grids are sequential-by-default, minormost-last — the k axis revisits
the same output block, which is exactly the reference's K-loop).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


@dataclass(frozen=True)
class MatmulConfig:
    # Swept on a real v5 chip at the bench shape (M=8192 K=8192 N=3584
    # bf16): (2048, 512, 512) with parallel/arbitrary dimension semantics
    # reaches ~190 TFLOPS (96% of nominal peak, equal to XLA's dot), vs
    # ~167 for (1024, 1024, 512) and ~146-155 for 512-row blocks.  Taller
    # M blocks win: fewer accumulator revisits per output column strip.
    # (2048, 1024, 512) and (4096, 512, 512) exceed VMEM and fail to
    # compile.  Small shapes clamp via for_shape.
    block_m: int = 2048
    block_n: int = 512
    block_k: int = 512

    def for_shape(self, m: int, n: int, k: int) -> "MatmulConfig":
        """Clamp blocks to the problem (keeps small/test shapes legal)."""
        return MatmulConfig(
            block_m=min(self.block_m, max(_round_up(m, 8), 8)),
            block_n=min(self.block_n, max(_round_up(n, 128), 128)),
            block_k=min(self.block_k, max(_round_up(k, 128), 128)),
        )


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def largest_divisor_block(dim: int, want: int, align: int) -> int:
    """Largest multiple of ``align`` that divides ``dim`` and is <= ``want``.

    Callers must first check ``pallas_shapes_ok`` (so ``dim % align == 0``),
    which guarantees a legal result exists (at worst ``align`` itself).
    """
    assert dim % align == 0, (dim, align)
    if dim <= want:
        return dim
    best = align
    b = align
    while b <= want:
        if dim % b == 0:
            best = b
        b += align
    return best


def pallas_shapes_ok(m_loc: int, n_loc: int, k: int) -> bool:
    """Whether the per-device problem tiles legally onto the MXU (sublane /
    lane alignment).  Ragged shapes fall back to the XLA impl — the analog of
    the reference's dispatcher choosing a non-TMA path for odd shapes."""
    return m_loc % 8 == 0 and n_loc % 128 == 0 and k % 128 == 0


def resolve_impl(impl: str, interpret: bool) -> str:
    """Shared auto-dispatch: pallas on TPU hardware or under the interpreter,
    XLA collectives elsewhere (reference analog: the per-op dispatchers)."""
    from triton_dist_tpu.runtime import topology

    if impl == "auto":
        if interpret:
            return "pallas"
        return "pallas" if topology.is_tpu() else "xla"
    return impl


def apply_soft_cap(logits, soft_cap):
    """Gemma-2-style logit soft-capping: ``cap * tanh(logits / cap)``.
    ``soft_cap`` is a STATIC float; 0/None is the identity (compile-time
    branch — no tanh in the hot loop unless capping is on).  Reference
    analog: the ``soft_cap`` argument threaded through its decode stack
    (sp_flash_decode_layer.py:46, flash_decode.py:103)."""
    if not soft_cap:
        return logits
    return soft_cap * jnp.tanh(logits / soft_cap)


class PallasShapeError(ValueError):
    """Raised when ``impl='pallas'`` is requested explicitly but a shape
    guard would silently reroute to the XLA fallback."""


def use_fallback(raw_impl: str, resolved_impl: str, ok: bool, what: str,
                 detail: str = "") -> bool:
    """Shared dispatcher gate: True -> take the XLA fallback path.

    Under EXPLICIT ``impl='pallas'`` a failing shape guard RAISES instead
    of rerouting (VERDICT r3 #2): the reference cannot have this bug class
    — its tests run the Triton kernel or crash — whereas a silent
    fallback once hid a fused-kernel deadlock behind green tests.  With
    this gate, every ``impl='pallas'`` test IS a kernel-reach assertion:
    shrinking its shapes below ``pallas_shapes_ok`` fails loudly.
    ``impl='auto'`` keeps its fallback freedom (that is its purpose).
    """
    if raw_impl == "pallas" and not ok:
        # The specific alignment contract varies by caller (dense GEMMs:
        # per-shard m%8/n%128/k%128; matmul_i8: m%32 + block divisors;
        # flash_decode: D%128/S%128) — ``detail`` carries it.
        raise PallasShapeError(
            f"{what}: impl='pallas' requested but {detail or 'the shape'} "
            f"fails this kernel's MXU tiling contract; pass impl='auto' "
            f"to permit the XLA fallback")
    return resolved_impl == "xla" or not ok


def gemm_pipeline_body(a_blk, b_blk, out_blk, acc_ref, *, n_k, out_dtype):
    """Shared emit_pipeline body for nested MXU matmuls inside overlapped
    kernels: one (bm, bn, bk) tile accumulated over the k grid.  The
    accumulator dtype follows the scratch ref: f32 for float inputs, exact
    i32 for int8 inputs (the MXU double-rate path)."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_blk[:], b_blk[:],
                          preferred_element_type=acc_ref.dtype)

    @pl.when(k == n_k - 1)
    def _():
        out_blk[:] = acc_ref[:].astype(out_dtype)


def wire_gemm_pipeline_body(a_blk, s_blk, b_blk, out_blk, acc_ref, *,
                            n_k, out_dtype):
    """int8-WIRE variant of :func:`gemm_pipeline_body`: the A block
    arrives as the int8 wire payload plus a per-row scale plane (column 0
    of a 128-lane f32 block — the minimum Mosaic wire unit), and is
    dequantized at the MXU feed; the math stays in B's dtype with f32
    accumulation.  (Reference ships fp8 payloads in its headline kernel,
    low_latency_all_to_all.py:76-88; on this chip int8 is the 2x wire
    format and fp8 would run the MXU at bf16 rate anyway — docs/perf.md
    fp8 probe.)"""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    a_deq = (a_blk[:].astype(jnp.float32) * s_blk[:, :1]).astype(
        b_blk.dtype)
    acc_ref[:] += jnp.dot(a_deq, b_blk[:],
                          preferred_element_type=acc_ref.dtype)

    @pl.when(k == n_k - 1)
    def _():
        out_blk[:] = acc_ref[:].astype(out_dtype)


def group_gemm_pipeline_body(x_blk, w_blk, out_blk, acc_ref, *, n_k, out_dtype):
    """Grouped-GEMM variant of :func:`gemm_pipeline_body`: the weight block
    arrives with a leading singleton expert dim (BlockSpec (1, bk, bn) steered
    by a tile→expert map), so the MXU contraction reads ``w_blk[0]``.  The
    accumulator dtype follows the scratch ref (f32 float / exact i32 int8)."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(x_blk[:], w_blk[0],
                          preferred_element_type=acc_ref.dtype)

    @pl.when(k == n_k - 1)
    def _():
        out_blk[:] = acc_ref[:].astype(out_dtype)


def _matmul_kernel(a_ref, b_ref, out_ref, acc_ref, *, n_k: int, k_rem: int, out_dtype):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    a = a_ref[:]
    if k_rem:
        # K not divisible by block_k: the last K block reads past the array
        # end and Pallas pads with unspecified values, which — unlike M/N
        # padding — would be folded into every output element.  Mask the
        # tail columns to zero on the final block.
        @pl.when(k == n_k - 1)
        def _():
            col = jax.lax.broadcasted_iota(jnp.int32, a_ref.shape, 1)
            row = jax.lax.broadcasted_iota(jnp.int32, b_ref.shape, 0)
            acc_ref[:] += jnp.dot(
                jnp.where(col < k_rem, a_ref[:], 0).astype(a_ref.dtype),
                jnp.where(row < k_rem, b_ref[:], 0).astype(b_ref.dtype),
                preferred_element_type=jnp.float32,
            )

        @pl.when(k < n_k - 1)
        def _():
            acc_ref[:] += jnp.dot(a, b_ref[:], preferred_element_type=jnp.float32)
    else:
        acc_ref[:] += jnp.dot(a, b_ref[:], preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _():
        out_ref[:] = acc_ref[:].astype(out_dtype)


@functools.partial(
    jax.jit, static_argnames=("config", "out_dtype", "interpret")
)
def matmul(
    a: jax.Array,
    b: jax.Array,
    config: MatmulConfig | None = None,
    out_dtype=None,
    interpret: bool = False,
) -> jax.Array:
    """C[m, n] = A[m, k] @ B[k, n] on the MXU with f32 accumulation."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, f"contraction mismatch {a.shape} @ {b.shape}"
    out_dtype = out_dtype or a.dtype
    cfg = (config or MatmulConfig()).for_shape(m, n, k)
    bm, bn, bk = cfg.block_m, cfg.block_n, cfg.block_k
    n_k = pl.cdiv(k, bk)

    grid = (pl.cdiv(m, bm), pl.cdiv(n, bn), n_k)
    return pl.pallas_call(
        functools.partial(
            _matmul_kernel, n_k=n_k, k_rem=k % bk, out_dtype=out_dtype
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (bm, bn), lambda i, j, kk: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=(m * k + k * n) * a.dtype.itemsize + m * n * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        # m/n blocks write disjoint outputs; only k is a sequential
        # accumulation.  Telling Mosaic so is worth ~5% at the bench shape
        # (189.6 vs 180.8 TFLOPS, real-chip sweep).
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b)


def matmul_kernel_tflops(m: int, n: int, k: int, ms: float) -> float:
    """Achieved TFLOPS for a (m, n, k) matmul that took ``ms`` milliseconds."""
    return 2.0 * m * n * k / (ms * 1e-3) / 1e12


def _register_gemm_aot():
    """AOT spaces for the base GEMM (LLaMA-70B FFN shard shapes)."""
    from triton_dist_tpu.tools.compile_aot import aot_compile_spaces

    return aot_compile_spaces({
        "matmul": {
            "signature": [
                [((8192, 8192), "bfloat16"), ((8192, 3584), "bfloat16")],
                [((1024, 1024), "float32"), ((1024, 512), "float32")],
            ],
            "algo_infos": [
                {"bm": 2048, "bn": 512, "bk": 512},  # real-chip sweep winner
                {"bm": 1024, "bn": 1024, "bk": 512},
                {"bm": 512, "bn": 512, "bk": 512},
                {"bm": 256, "bn": 512, "bk": 512},
            ],
        },
    })


@_register_gemm_aot()
def matmul_with_blocks(a, b, *, bm, bn, bk, impl="auto", out_dtype=None,
                       interpret=False):
    """``matmul`` with block sizes as flat kwargs — the AOT entry point
    (algo-info values must be manifest-serializable primitives).  ``auto``
    resolves to the Pallas MXU kernel on TPU and plain XLA dot elsewhere,
    so exports work on whichever platform is doing the exporting."""
    if resolve_impl(impl, interpret) == "pallas":
        return matmul(a, b, config=MatmulConfig(bm, bn, bk),
                      out_dtype=out_dtype, interpret=interpret)
    return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(
        out_dtype or a.dtype)


def _make_matmul_autotuned():
    from triton_dist_tpu.autotuner import Config, autotune

    configs = [
        Config(bm=bm, bn=bn, bk=bk)
        for bm in (256, 512, 1024, 2048)
        for bn in (512, 1024) for bk in (512, 1024)
    ]

    def dedupe_clamped(cfgs, args, kwargs):
        # Small shapes clamp many block configs to the same effective
        # kernel; sweep each effective config once.
        a, b = args[0], args[1]
        m, k = a.shape
        n = b.shape[1]
        seen = {}
        for c in cfgs:
            eff = MatmulConfig(c["bm"], c["bn"], c["bk"]).for_shape(m, n, k)
            seen.setdefault((eff.block_m, eff.block_n, eff.block_k), c)
        return list(seen.values())

    @autotune(configs=configs, prune=dedupe_clamped)
    def matmul_autotuned(a, b, *, bm, bn, bk, out_dtype=None,
                         interpret=False):
        return matmul(a, b, config=MatmulConfig(bm, bn, bk),
                      out_dtype=out_dtype, interpret=interpret)

    return matmul_autotuned


# Autotuned matmul: sweeps MXU block sizes per input shape/dtype; usable
# standalone or inside a ``contextual_autotune`` region (autotuner.py).
matmul_autotuned = _make_matmul_autotuned()
