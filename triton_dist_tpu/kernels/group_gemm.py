"""Grouped (expert-blocked) Pallas GEMM — the MoE MXU workhorse.

Reference analog: the token-sorted GroupGEMM producers in
``python/triton_dist/kernels/nvidia/moe_reduce_rs.py`` (tile loop keyed by
``gather_a_index``/``expert_idx`` tables) and
``allgather_group_gemm.py:200-330`` — every ``block_m``-row tile of the
expert-sorted token buffer belongs to exactly ONE expert, so each row tile
loads that expert's weight slab and runs a dense matmul.  The CUDA side gets
its tile→expert map from ``csrc/moe_utils.cu``; ours comes from
``moe_utils.sort_align`` (same contract: sorted rows padded per expert to the
tile size).

TPU-native design: a scalar-prefetch grid spec carries the ``tile_expert``
map into SMEM ahead of the grid, and the weight BlockSpec's index map reads
it to steer each row tile's slab to ``w[tile_expert[i]]``.  The Mosaic
pipeline then streams tokens and the selected expert slab HBM→VMEM onto the
MXU exactly like the dense matmul — no gathered copy of the weights is ever
materialized (the reference needs neither, and neither do we).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.kernels.gemm import (
    group_gemm_pipeline_body,
    largest_divisor_block,
    pallas_shapes_ok,
    resolve_impl,
    use_fallback,
)
from triton_dist_tpu.language.interpret import maybe_interpret


def group_gemm_xla(x_sorted, w_stack, tile_expert, block_m: int, out_dtype=None):
    """Dense-einsum fallback: gather one weight slab per row tile.

    Keeps shapes static (n_tiles × [block_m, K] @ [K, N]); XLA turns the
    weight gather into per-tile dynamic slices.  Runs everywhere — the
    correctness baseline for the pallas path.
    """
    quantized = x_sorted.dtype == jnp.int8
    out_dtype = out_dtype or (jnp.int32 if quantized else x_sorted.dtype)
    m_pad, k_dim = x_sorted.shape
    n_tiles = m_pad // block_m
    xt = x_sorted.reshape(n_tiles, block_m, k_dim)
    wt = w_stack[tile_expert]  # [n_tiles, K, N]
    yt = jnp.einsum("tbk,tkn->tbn", xt, wt,
                    preferred_element_type=(jnp.int32 if quantized
                                            else jnp.float32))
    return yt.astype(out_dtype).reshape(m_pad, w_stack.shape[-1])


def load_aware_block_m(total_rows: int, n_experts: int,
                       floor: int = 128) -> int:
    """Load-aware sort/GEMM row-tile size (VERDICT r3 #4).

    The real-chip sweep (docs/perf.md "Grouped GEMM MFU") says tile
    height is the whole game: 128-row tiles reach 42-54% MFU, 512-row
    tiles ~87% — but a 512 tile on a sparsely-loaded expert is mostly
    sort padding (wasted rows ≈ E * block_m/2).  Rule: the largest of
    {128, 256, 512} not exceeding the *balanced* per-expert load
    ``total_rows / n_experts`` — dense prefill gets the 512 MFU winner,
    sparse serving degrades toward the padding-lean 128.
    """
    per_expert = max(total_rows // max(n_experts, 1), 1)
    best = floor
    for b in (256, 512):
        if per_expert >= b:
            best = b
    return best


@functools.partial(
    jax.jit,
    static_argnames=("block_m", "bn", "bk", "out_dtype", "impl", "interpret"),
)
def group_gemm(
    x_sorted: jax.Array,     # [M_pad, K] expert-sorted tokens (padding rows 0)
    w_stack: jax.Array,      # [E, K, N] per-expert weights
    tile_expert: jax.Array,  # [M_pad // block_m] int32 expert of each row tile
    *,
    block_m: int,
    bn: int | None = None,
    bk: int | None = None,
    out_dtype=None,
    impl: str = "auto",
    interpret: bool = False,
) -> jax.Array:
    """y[M_pad, N] where row tile i is ``x_tile @ w_stack[tile_expert[i]]``.

    ``block_m`` must be the block size given to ``moe_utils.sort_align`` (it
    defines the tile→expert granularity).  Larger row tiles feed the MXU
    better (real-chip grouped-only MFU at the DeepSeek serving shape:
    block_m 128 → ~54%, 512 → ~87% bf16; ~46% → ~87% int8 — docs/perf.md)
    at the cost of more
    per-expert sort padding; callers with dense expert loads should raise
    it.  ``bn``/``bk`` default to the swept winners per dtype (bf16
    (512, 1024); int8 (1024, 1024) — int8 wants double-depth k just like
    the dense kernel).  Differentiable: see :func:`_group_gemm_core` (dx is
    a grouped GEMM against transposed slabs; dW segment-sums per-tile outer
    products by expert).
    """
    if bn is None:
        bn = 1024 if x_sorted.dtype == jnp.int8 else 512
    if bk is None:
        bk = 1024
    # Launch metadata (profiling.annotate contract): every padded row
    # tile runs one [block_m, K] x [K, N] expert GEMM.
    from triton_dist_tpu.runtime.profiling import annotate

    M_pad, K = x_sorted.shape
    N = w_stack.shape[2]
    el = jnp.dtype(x_sorted.dtype).itemsize
    with annotate("group_gemm", flops=2 * M_pad * K * N,
                  bytes_accessed=(M_pad * K + M_pad * N) * el
                  + w_stack.size * el):
        return _group_gemm_core(x_sorted, w_stack, tile_expert, block_m,
                                bn, bk, out_dtype, impl, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _group_gemm_core(x_sorted, w_stack, tile_expert, block_m, bn, bk,
                     out_dtype, impl, interpret):
    return _group_gemm_fwd_impl(x_sorted, w_stack, tile_expert, block_m, bn,
                                bk, out_dtype, impl, interpret)


def _group_gemm_vjp_fwd(x_sorted, w_stack, tile_expert, block_m, bn, bk,
                        out_dtype, impl, interpret):
    y = _group_gemm_fwd_impl(x_sorted, w_stack, tile_expert, block_m, bn, bk,
                             out_dtype, impl, interpret)
    return y, (x_sorted, w_stack, tile_expert)


def _group_gemm_vjp_bwd(block_m, bn, bk, out_dtype, impl, interpret,
                        res, dy):
    x_sorted, w_stack, tile_expert = res
    # dx tile i = dy tile i @ W[te[i]]^T — the same grouped GEMM shape.
    dx = _group_gemm_core(
        dy.astype(x_sorted.dtype), jnp.swapaxes(w_stack, 1, 2), tile_expert,
        block_m, bk, bn, x_sorted.dtype, impl, interpret)
    # dW[e] = Σ_{i: te[i]=e} x_tile_i^T @ dy_tile_i (padding rows are zero in
    # x_sorted, so they contribute nothing).  Contract tiles directly into
    # expert slots via a one-hot factor: peak memory E*K*N, not the
    # n_tiles*K*N a per-tile outer-product + scatter-add would materialize
    # (which is GBs at Mixtral shapes).
    n_tiles = tile_expert.shape[0]
    n_experts = w_stack.shape[0]
    xt = x_sorted.reshape(n_tiles, block_m, -1)
    dyt = dy.reshape(n_tiles, block_m, -1)
    onehot = jax.nn.one_hot(tile_expert, n_experts, dtype=jnp.float32)
    dw = jnp.einsum("te,tbk,tbn->ekn", onehot, xt, dyt,
                    preferred_element_type=jnp.float32).astype(w_stack.dtype)
    return dx, dw, np.zeros(tile_expert.shape, jax.dtypes.float0)


_group_gemm_core.defvjp(_group_gemm_vjp_fwd, _group_gemm_vjp_bwd)


def _group_gemm_fwd_impl(x_sorted, w_stack, tile_expert, block_m, bn, bk,
                         out_dtype, impl, interpret):
    m_pad, k_dim = x_sorted.shape
    n_experts, k2, n_dim = w_stack.shape
    assert k_dim == k2, (x_sorted.shape, w_stack.shape)
    assert m_pad % block_m == 0, (m_pad, block_m)
    # A block_m mismatched with the sort_align plan would silently steer
    # tiles to garbage expert slabs on the pallas path (te[i] read OOB).
    assert tile_expert.shape == (m_pad // block_m,), (
        tile_expert.shape, m_pad, block_m)
    # int8 inputs: exact i32 accumulation/output on the MXU double-rate
    # path (W8A8 expert compute; dequant happens at the caller).
    quantized = x_sorted.dtype == jnp.int8
    out_dtype = out_dtype or (jnp.int32 if quantized else x_sorted.dtype)
    acc_dtype = jnp.int32 if quantized else jnp.float32

    raw_impl = impl
    impl = resolve_impl(impl, interpret)
    if use_fallback(raw_impl, impl, pallas_shapes_ok(block_m, n_dim, k_dim),
                    "group_gemm", f"(block_m={block_m}, N={n_dim}, K={k_dim}); needs m%8, n%128, k%128"):
        return group_gemm_xla(x_sorted, w_stack, tile_expert, block_m, out_dtype)

    bn = largest_divisor_block(n_dim, bn, 128)
    bk = largest_divisor_block(k_dim, bk, 128)
    n_tiles, n_n, n_k = m_pad // block_m, n_dim // bn, k_dim // bk

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles, n_n, n_k),
        in_specs=[
            pl.BlockSpec((block_m, bk), lambda i, j, k, te: (i, k)),
            pl.BlockSpec((1, bk, bn), lambda i, j, k, te: (te[i], k, j)),
        ],
        out_specs=pl.BlockSpec((block_m, bn), lambda i, j, k, te: (i, j)),
        scratch_shapes=[pltpu.VMEM((block_m, bn), acc_dtype)],
    )

    def _kernel(te_ref, x_ref, w_ref, out_ref, acc_ref):
        group_gemm_pipeline_body(x_ref, w_ref, out_ref, acc_ref,
                                 n_k=n_k, out_dtype=out_dtype)

    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m_pad, n_dim), out_dtype),
        # Row tiles and n-blocks are independent; only k accumulates.
        # Same knob as the dense matmul's 96%-MXU config (gemm.py).
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=2 * m_pad * n_dim * k_dim,
            bytes_accessed=(m_pad * k_dim + n_experts * k_dim * n_dim)
            * x_sorted.dtype.itemsize
            + m_pad * n_dim * jnp.dtype(out_dtype).itemsize,
            transcendentals=0,
        ),
        interpret=maybe_interpret(interpret),
    )(tile_expert, x_sorted, w_stack)


def moe_ffn_sorted(x_sorted, w_gate, w_up, w_down, tile_expert, *,
                   block_m: int, impl: str = "auto", interpret: bool = False):
    """SwiGLU expert FFN over the sorted buffer: three grouped GEMMs.

    y = (silu(x @ Wg[e]) * (x @ Wu[e])) @ Wd[e] per expert tile — the
    per-expert MLP the reference's MoE tests build from its GroupGEMM.
    """
    gg = functools.partial(group_gemm, tile_expert=tile_expert,
                           block_m=block_m, impl=impl, interpret=interpret)
    gate = gg(x_sorted, w_gate)
    up = gg(x_sorted, w_up)
    hidden = (jax.nn.silu(gate.astype(jnp.float32))
              * up.astype(jnp.float32)).astype(x_sorted.dtype)
    return gg(hidden, w_down)
