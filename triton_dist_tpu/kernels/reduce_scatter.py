"""ReduceScatter kernels: ring reduce-scatter + XLA path.

Reference analog: ``python/triton_dist/kernels/nvidia/reduce_scatter.py`` —
hierarchical 2-D RS (intra-node scatter via copy engine :604-637, local ring
reduce on a reduction stream :828, inter-node NVSHMEM P2P :525-544, final
cross-node ring reduce :842-860), SM-budgeted (:133-139).

TPU-native design: a single-level **ring reduce-scatter** is bandwidth-optimal
on an ICI torus axis: at step s each device adds its local contribution to the
in-flight partial sum and forwards it.  After ``world-1`` steps every device
holds the fully-reduced chunk it owns.  The reference's two-level (NUMA/node)
hierarchy maps to two mesh axes (ICI × DCN) — compose two ring passes via
``reduce_scatter_shard`` per axis.  There is no "reduction stream": the adds
run on the VPU between DMA waits inside the same kernel, which is exactly the
compute/comm overlap the reference builds with multiple streams.

Flow control: the in-flight partial lands in a single ``recv_buf``; a credit
semaphore provides backpressure (the sender may not overwrite the receiver's
landing buffer until the receiver has folded it into its accumulator).  This
replaces the reference's ``wait_eq`` scatter signals (reduce_scatter.py:604-637).
"""

from __future__ import annotations

import enum
import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime import topology
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


class ReduceScatterMethod(enum.Enum):
    AUTO = "auto"
    XLA = "xla"
    RING_1D = "ring_1d"
    RING_BIDIR = "ring_bidir"  # both link directions, ~2x RING_1D


@dataclass
class ReduceScatterContext:
    mesh: Mesh
    axis: str = "tp"
    method: ReduceScatterMethod = ReduceScatterMethod.AUTO
    interpret: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_reduce_scatter_context(mesh, axis="tp", method=ReduceScatterMethod.AUTO,
                                  interpret=False):
    return ReduceScatterContext(mesh=mesh, axis=axis, method=method, interpret=interpret)


def resolve_method(interpret: bool) -> ReduceScatterMethod:
    """AUTO → the bidirectional pallas ring on TPU (or in interpret-test
    mode), XLA else."""
    if topology.is_tpu() or interpret:
        return ReduceScatterMethod.RING_BIDIR
    return ReduceScatterMethod.XLA


def _ring_rs_kernel(
    x_hbm, out_ref, local_buf, acc_buf, recv_buf,
    send_sem, recv_sem, credit_sem, copy_sem,
    *, axis, world, rows,
):
    """Ring RS over chunks of ``rows`` rows.

    Outgoing chunk at step s is ``(me - 1 - s) mod world``; the partial sum
    received at step s (from the left neighbor) is for chunk
    ``(me - 2 - s) mod world`` and is folded in at step s+1.  After
    ``world - 1`` steps the last received partial is for chunk ``me``.
    """
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)

    def load_chunk(slot, dst):
        cp = pltpu.make_async_copy(x_hbm.at[pl.ds(slot * rows, rows)], dst, copy_sem)
        cp.start()
        cp.wait()

    def step(s, _):
        slot = jax.lax.rem(me + 2 * world - 1 - s, world)  # (me - 1 - s) mod world
        load_chunk(slot, local_buf)

        @pl.when(s == 0)
        def _():
            acc_buf[:] = local_buf[:]

        @pl.when(s > 0)
        def _():
            acc_buf[:] = local_buf[:] + recv_buf[:]
            # recv_buf consumed → give the left neighbor its send credit.
            pltpu.semaphore_signal(
                credit_sem, inc=1, device_id={axis: left},
                device_id_type=pltpu.DeviceIdType.MESH,
            )

        @pl.when(s > 0)
        def _():
            # Wait until the right neighbor consumed our previous partial.
            pltpu.semaphore_wait(credit_sem, 1)

        rdma = dl.remote_copy(acc_buf, recv_buf, send_sem, recv_sem, axis, right)
        rdma.start()
        rdma.wait()
        return 0

    jax.lax.fori_loop(0, world - 1, step, 0)

    load_chunk(me, local_buf)
    out_ref[:] = local_buf[:] + recv_buf[:]


def _bidir_ring_rs_kernel(
    x_hbm, out_ref, local_buf, acc_buf, recv_buf,
    send_sem, recv_sem, credit_sem, copy_sem,
    *, axis, world, rows, ra,
):
    """Bidirectional ring RS: each chunk's rows split in two — half A
    ([0, ra)) reduces along the rightward ring while half B ([ra, rows))
    reduces leftward, so both ICI link directions carry ~half the bytes
    concurrently (~2x RING_1D; the RS twin of the bidirectional AG).

    Per direction the schedule IS the 1-D ring RS (see ``_ring_rs_kernel``'s
    derivation); the two instances are interleaved per step — start both
    remote DMAs, then wait both — with per-direction buffers, DMA
    semaphores ([2]-arrays indexed by direction) and credit semaphores.
    """
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)

    rb = rows - ra  # rb >= ra >= 1 (dispatch gates rows >= 2)
    # (direction d, half-slice (off, ln), peer, upstream) per path.
    paths = ((1, 0, ra, right, left), (-1, ra, rb, left, right))

    def load_half(slot, off, ln, dst):
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(slot * rows + off, ln)], dst, copy_sem)
        cp.start()
        cp.wait()

    def step(s, _):
        for p, (d, off, ln, peer, prev) in enumerate(paths):
            slot = jax.lax.rem(me - d * (1 + s) + (1 + s) * world + world,
                               world)
            load_half(slot, off, ln, local_buf.at[p, :ln])

            @pl.when(s == 0)
            def _(p=p, ln=ln):
                acc_buf[p, :ln] = local_buf[p, :ln]

            @pl.when(s > 0)
            def _(p=p, ln=ln, prev=prev):
                acc_buf[p, :ln] = local_buf[p, :ln] + recv_buf[p, :ln]
                # landing slot consumed → credit the upstream sender
                pltpu.semaphore_signal(
                    credit_sem.at[p], inc=1, device_id={axis: prev},
                    device_id_type=pltpu.DeviceIdType.MESH)

            @pl.when(s > 0)
            def _(p=p):
                pltpu.semaphore_wait(credit_sem.at[p], 1)

            dl.remote_copy(acc_buf.at[p, :ln], recv_buf.at[p, :ln],
                           send_sem.at[p], recv_sem.at[p], axis,
                           peer).start()
        for p, (d, off, ln, peer, prev) in enumerate(paths):
            blk = acc_buf.at[p, :ln]
            pltpu.make_async_copy(blk, blk, send_sem.at[p]).wait()
            pltpu.make_async_copy(blk, blk, recv_sem.at[p]).wait()
        return 0

    jax.lax.fori_loop(0, world - 1, step, 0)

    # Final fold: the last arrival in each direction is MY chunk's half.
    for p, (d, off, ln, peer, prev) in enumerate(paths):
        load_half(me, off, ln, local_buf.at[p, :ln])
        out_ref[pl.ds(off, ln)] = local_buf[p, :ln] + recv_buf[p, :ln]


def reduce_scatter_shard(x_shard, axis: str, method=ReduceScatterMethod.AUTO,
                         interpret=False, collective_id=2):
    """Per-shard RS: input (world*rows, ...) → output (rows, ...) summed.

    Matches ``lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)``.
    ``axis`` may be a tuple of 2-3 mesh axes — a multi-axis RS routes to
    the fused torus schedule (``kernels/torus.py``).
    """
    if isinstance(axis, (tuple, list)) and len(axis) > 1:
        from triton_dist_tpu.kernels.torus import torus_reduce_scatter_shard

        if method is ReduceScatterMethod.AUTO:
            method = resolve_method(interpret)
        if method is ReduceScatterMethod.XLA:
            return jax.lax.psum_scatter(x_shard, tuple(axis),
                                        scatter_dimension=0, tiled=True)
        return torus_reduce_scatter_shard(x_shard, tuple(axis),
                                          interpret=interpret,
                                          collective_id=collective_id)
    axis = axis[0] if isinstance(axis, (tuple, list)) else axis
    world = jax.lax.axis_size(axis)
    if method is ReduceScatterMethod.AUTO:
        method = resolve_method(interpret)
    if method is ReduceScatterMethod.XLA:
        return jax.lax.psum_scatter(x_shard, axis, scatter_dimension=0, tiled=True)
    if world == 1:
        return x_shard
    total_rows = x_shard.shape[0]
    assert total_rows % world == 0, (total_rows, world)
    rows = total_rows // world
    tail = x_shard.shape[1:]
    if method is ReduceScatterMethod.RING_BIDIR and rows >= 2:
        ra = rows // 2  # invariant: rb = rows - ra >= ra >= 1
        half = pltpu.VMEM((2, rows - ra, *tail), x_shard.dtype)
        return pl.pallas_call(
            functools.partial(_bidir_ring_rs_kernel, axis=axis, world=world,
                              rows=rows, ra=ra),
            out_shape=jax.ShapeDtypeStruct((rows, *tail), x_shard.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                half,  # local_buf [2, max_half, ...]
                half,  # acc_buf
                half,  # recv_buf (remote landing zone)
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR((2,)),  # credits per direction
                pltpu.SemaphoreType.DMA,
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True, collective_id=collective_id
            ),
            interpret=maybe_interpret(interpret),
        )(x_shard)
    chunk = pltpu.VMEM((rows, *tail), x_shard.dtype)
    return pl.pallas_call(
        functools.partial(_ring_rs_kernel, axis=axis, world=world, rows=rows),
        out_shape=jax.ShapeDtypeStruct((rows, *tail), x_shard.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            chunk,  # local_buf
            chunk,  # acc_buf
            chunk,  # recv_buf (remote landing zone)
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.REGULAR,  # credit
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=maybe_interpret(interpret),
    )(x_shard)


def _rs_stacked_shard(stacked, *, axis, method, interpret):
    return reduce_scatter_shard(stacked[0], axis, method=method, interpret=interpret)


def reduce_scatter(x, ctx: ReduceScatterContext):
    """Host-level entry: reduce (+) over ``ctx.axis`` and scatter dim 0.

    Input: the per-device partial sums **stacked** on a leading axis —
    shape ``(world, world*rows, ...)``, sharded (or shardable) over
    ``ctx.axis`` on dim 0, so device i contributes partial ``x[i]``.
    Output: ``(world*rows, ...)`` sharded over ``ctx.axis``; device i's shard
    is ``sum_j x[j, i*rows:(i+1)*rows]``.  Reference analog:
    ``reduce_scatter_2d_op`` (reduce_scatter.py:863) where each rank passes
    its own full-size partial.

    Inside a model, call ``reduce_scatter_shard`` directly from your own
    shard_map region instead (no stacking needed — each device passes its
    local partial).
    """
    world = ctx.world
    if x.shape[0] != world:
        raise ValueError(
            f"expected stacked partials with leading dim {world}, got {x.shape}"
        )
    method = ctx.method
    if method is ReduceScatterMethod.AUTO:
        method = resolve_method(ctx.interpret)

    fn = cached_shard_jit(
        _rs_stacked_shard,
        ctx.mesh,
        P(ctx.axis),
        P(ctx.axis),
        axis=ctx.axis,
        method=method,
        interpret=ctx.interpret,
    )
    # Launch metadata (profiling.annotate contract): ring RS moves
    # ~(world-1)/world of one full partial across the wire per device.
    from triton_dist_tpu.runtime.profiling import annotate

    with annotate("reduce_scatter",
                  bytes_accessed=x.nbytes // max(world, 1)):
        return fn(x)
