"""Device-side synchronization library.

Reference analog: ``python/triton_dist/kernels/nvidia/common_ops.py`` —
``barrier_on_this_grid`` (:61-84), ``barrier_all_intra_node_atomic_cas_block``
(:87-101), ``BarrierAllContext`` (:163-193), host ``wait_eq``/``set_signal``
via cuStreamWriteValue (:196-229).

TPU-native notes:

* There is no cooperative-grid barrier to build: a Pallas grid on TPU is a
  sequential loop on the core (megacore partitioning aside), so
  ``barrier_on_this_grid`` has no analog — cross-"block" ordering is free.
* Host-side stream signals (``cuStreamWriteValue``) have no analog because
  there are no user streams; ordering between kernels is XLA data flow.
* What remains is the cross-device barrier, exposed both as an in-kernel
  primitive (``language.barrier_all``) and as a host-level op here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.language import primitives as dl
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


def _barrier_kernel(x_ref, o_ref, *, axis):
    dl.barrier_all(axis)
    o_ref[0] = x_ref[0]


def _barrier_shard(x, *, axis, interpret):
    return pl.pallas_call(
        functools.partial(_barrier_kernel, axis=axis),
        out_shape=jax.ShapeDtypeStruct((1,), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=0
        ),
        interpret=maybe_interpret(interpret),
    )(x)


def barrier_all_on_mesh(mesh: Mesh, axis: str = "tp", interpret: bool = False):
    """Host-level barrier over ``axis`` (reference: barrier_all_on_stream).

    Returns a tiny array; blocking on it (``jax.block_until_ready``) means
    every device reached the barrier kernel.
    """
    x = jnp.zeros((mesh.shape[axis],), jnp.int32)
    fn = cached_shard_jit(
        _barrier_shard, mesh, P(axis), P(axis), axis=axis, interpret=interpret
    )
    return fn(x)
