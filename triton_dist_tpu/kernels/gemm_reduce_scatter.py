"""Overlapped GEMM-ReduceScatter — the tensor-parallel backward-half kernel.

Reference analog: ``python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py``
— a persistent producer GEMM writes output tiles, counts per-segment
completions with ``tl.atomic_add`` and fires ``dl.notify`` when a rank's
segment is done (:226-235), while a reduce-scatter consumer on a second
stream (``rs_stream``) scatters + ring-reduces the segments
(``reduce_scatter.py:604-860``); a rank-offset tile swizzle makes segment
``i`` of rank ``r`` finish early (:190-200).

TPU-native design (NOT a port): no streams, no atomics — ONE Pallas kernel
runs a ring reduce-scatter whose per-chunk partial GEMM overlaps the
in-flight partial-sum DMA:

* Sharding (row-parallel linear): A [M, K] is K-sharded, B [K, N] K-sharded,
  so each device's GEMM ``A_loc @ B_loc`` is a *partial sum* of C [M, N];
  the reduce-scatter sums partials and leaves M-chunk ``d`` on device ``d``.
* Ring schedule: the partial for chunk ``c`` starts at device ``c+1`` and
  travels right, accumulating each device's local contribution; after
  ``world-1`` hops it reaches its owner ``c`` fully reduced.  Device ``d``
  therefore computes chunks ``(d-1), (d-2), ..., (d+1) mod world`` and
  finally its own chunk ``d`` — the reference's rank-offset swizzle
  (gemm_rs_threadblock_swizzle.py) is this schedule's natural order.
* Overlap: at step ``s`` the inner MXU pipeline computes ``A[c_s] @ B_loc``
  while the previous partial (sent by the left neighbor during *its* step
  ``s-1``) is still in flight; the recv wait happens only before the cheap
  VPU add pass that folds the received partial in.  The add pass is the
  analog of the reference's ``ring_reduce`` on the reduction stream.
* Flow control: double-buffered landing slots + a credit semaphore replace
  the reference's ``wait_eq`` scatter signals (reduce_scatter.py:604-637).

Sharding contract (1-D TP over ``axis``):
  A: [M, K]   sharded P(None, axis)  (per-device [M, k_loc])
  B: [K, N]   sharded P(axis, None)  (per-device [k_loc, N])
  C: [M, N]   sharded P(axis, None)  (per-device [m_loc, N], fully reduced)
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels.gemm import (
    MatmulConfig,
    gemm_pipeline_body,
    largest_divisor_block,
    matmul,
    pallas_shapes_ok,
    resolve_impl,
    use_fallback,
)
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

from triton_dist_tpu.kernels.collective_ids import (
    GEMM_RS as GEMM_RS_COLLECTIVE_ID,
)


@dataclass
class GEMMReduceScatterContext:
    """Reference analog: ``GEMMReduceScatterTensorParallelContext``
    (gemm_reduce_scatter.py:240+) minus streams/symm workspace."""

    mesh: Mesh
    axis: str = "tp"
    impl: str = "auto"
    config: MatmulConfig = field(default_factory=MatmulConfig)
    # "bidir" (r5): mirrored half-column rings in both link directions.
    ring_mode: str = "uni"
    interpret: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_gemm_rs_context(mesh, axis="tp", impl="auto", config=None,
                           ring_mode="uni",
                           interpret=False) -> GEMMReduceScatterContext:
    return GEMMReduceScatterContext(
        mesh=mesh, axis=axis, impl=impl,
        config=config or MatmulConfig(), ring_mode=ring_mode,
        interpret=interpret,
    )


def _add_body(recv_blk, dst_in_blk, dst_out_blk):
    """dst += recv fold of the in-flight ring partial (the reference's
    ring_reduce add kernel, reduce_scatter.py:828)."""
    dst_out_blk[:] = dst_in_blk[:] + recv_blk[:]


def _gemm_rs_kernel(
    a_ref,       # [M, k_loc]        ANY
    b_ref,       # [k_loc, N]        ANY
    out_ref,     # [m_loc, N]        ANY, output: reduced C chunk
    send_ref,    # [2, m_loc, N]     ANY, output (scratch): partial staging
    recv_ref,    # [2, m_loc, N]     ANY, output (scratch): landing slots
    send_sem, recv_sem, credit_sem,
    acc_ref,     # VMEM (bm, bn) f32
    *,
    axis, world, m_loc, bm, bn, bk,
):
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)
    dtype_ref = out_ref

    k_loc = a_ref.shape[1]
    N = b_ref.shape[1]
    n_m, n_n, n_k = m_loc // bm, N // bn, k_loc // bk

    inner_gemm = pltpu.emit_pipeline(
        functools.partial(gemm_pipeline_body, n_k=n_k, out_dtype=dtype_ref.dtype),
        grid=(n_m, n_n, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))],
    )
    inner_add = pltpu.emit_pipeline(
        _add_body,
        grid=(n_m, n_n),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
    )

    if world > 1:
        # Entry barrier with ring neighbors before any remote write.
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(barrier, 2)

    for s in range(world):
        p = s % 2
        last = s == world - 1
        # Chunk schedule: (me-1-s) mod world, except the final step reduces
        # our own chunk (see module docstring for the ring derivation).
        if last:
            chunk = me
        else:
            chunk = jax.lax.rem(me - 1 - s + 2 * world, world)
        dst = out_ref if last else send_ref.at[p]

        if s >= 2:
            # send_ref slot p was last DMA'd at step s-2; drain before reuse.
            # Semaphores are per-slot: with two sends in flight, a shared
            # semaphore could let the *other* slot's completion satisfy this
            # wait and the GEMM would overwrite a buffer still being read.
            pltpu.make_async_copy(send_ref.at[p], send_ref.at[p],
                                  send_sem.at[p]).wait()

        # Partial GEMM for this chunk — overlaps the in-flight recv DMA.
        inner_gemm(a_ref.at[pl.ds(chunk * m_loc, m_loc)], b_ref, dst,
                   scratches=(acc_ref,))

        if s >= 1:
            # Fold in the partial received from the left (landed in slot p).
            pltpu.make_async_copy(recv_ref.at[p], recv_ref.at[p],
                                  recv_sem.at[p]).wait()
            inner_add(recv_ref.at[p], dst, dst)
            # Slot p is now free for the left neighbor's step-(s+1) send.
            pltpu.semaphore_signal(credit_sem, inc=1, device_id={axis: left},
                                   device_id_type=pltpu.DeviceIdType.MESH)

        if not last:
            if s >= 2:
                # Right's landing slot (s+1)%2 is reused from step s-2; wait
                # for the credit it issued after consuming it at step s-1.
                pltpu.semaphore_wait(credit_sem, 1)
            dl.remote_copy(send_ref.at[p], recv_ref.at[(s + 1) % 2],
                           send_sem.at[p], recv_sem.at[(s + 1) % 2],
                           axis, right).start()

    if world > 1:
        # Drain the final outstanding send (issued at step world-2).
        pfin = (world - 2) % 2
        pltpu.make_async_copy(send_ref.at[pfin], send_ref.at[pfin],
                              send_sem.at[pfin]).wait()
        # Unconsumed credits: the right neighbor signals one credit per fold
        # (world-1 total) but we only wait world-3 times; drain the rest so
        # the semaphore is zero at kernel exit.
        n_credit_waits = max(world - 3, 0)
        pltpu.semaphore_wait(credit_sem, (world - 1) - n_credit_waits)



def _gemm_rs_bidir_kernel(
    a_ref,        # [M, k_loc]            ANY
    b_ref,        # [k_loc, N]            ANY
    out_ref,      # [m_loc, N]            ANY, output: reduced C chunk
    send_r_ref,   # [2, m_loc, N/2]       ANY, scratch (rightward ring)
    recv_r_ref,   # [2, m_loc, N/2]
    send_l_ref,   # [2, m_loc, N/2]       (leftward ring)
    recv_l_ref,
    send_sem_r, recv_sem_r, send_sem_l, recv_sem_l,
    credit_r, credit_l,
    acc_ref,      # VMEM (bm, bn) f32
    *,
    axis, world, m_loc, bm, bn, bk,
):
    """Bidirectional ring GEMM-RS (r5, VERDICT r4 next#5): the N columns
    split in half and each half runs the proven 1-D ring-RS schedule in
    OPPOSITE directions — column half 0's partials travel rightward
    (chunk (me-1-s), fold from the left) and half 1's leftward (the
    mirror: chunk (me+1+s), fold from the right) — so both ICI link
    directions carry [m_loc, N/2] per step: per-step wire halves on a
    1-axis mesh.  Per-direction staging/landing slots, DMA semaphores,
    and credit semaphores keep the two rings' flow control independent
    (a shared semaphore could let one direction's completion satisfy the
    other's wait).  Reference analog: its bidirectional/2D producer
    variants (allgather.py:194-258) applied to the RS consumer.
    """
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)

    k_loc = a_ref.shape[1]
    N = b_ref.shape[1]
    nh = N // 2
    n_m, n_n, n_k = m_loc // bm, nh // bn, k_loc // bk

    inner_gemm = pltpu.emit_pipeline(
        functools.partial(gemm_pipeline_body, n_k=n_k,
                          out_dtype=out_ref.dtype),
        grid=(n_m, n_n, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))],
    )
    inner_add = pltpu.emit_pipeline(
        _add_body,
        grid=(n_m, n_n),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
    )

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)

    # Per-direction ring state: (send_ref, recv_ref, send_sem, recv_sem,
    # credit_sem, dst neighbor, credit peer, column offset, chunk sign).
    dirs = (
        (send_r_ref, recv_r_ref, send_sem_r, recv_sem_r, credit_r,
         right, left, 0, -1),
        (send_l_ref, recv_l_ref, send_sem_l, recv_sem_l, credit_l,
         left, right, nh, +1),
    )

    def chunk_of(s, sign):
        # sign -1: rightward schedule (me-1-s); +1: leftward (me+1+s).
        if s == world - 1:
            return me
        return jax.lax.rem(me + sign * (1 + s) + 2 * world, world)

    for s in range(world):
        p = s % 2
        last = s == world - 1

        dsts = []
        for (snd, rcv, ssem, rsem, credit, nbr, peer, coff, sign) in dirs:
            if s >= 2:
                pltpu.make_async_copy(snd.at[p], snd.at[p],
                                      ssem.at[p]).wait()
            chunk = chunk_of(s, sign)
            dst = (out_ref.at[:, pl.ds(coff, nh)] if last
                   else snd.at[p])
            # Partial GEMM for this direction's chunk and column half —
            # overlaps both directions' in-flight recv DMAs.
            inner_gemm(a_ref.at[pl.ds(chunk * m_loc, m_loc)],
                       b_ref.at[:, pl.ds(coff, nh)], dst,
                       scratches=(acc_ref,))
            dsts.append(dst)

        for di, (snd, rcv, ssem, rsem, credit, nbr, peer, coff,
                 sign) in enumerate(dirs):
            if s >= 1:
                pltpu.make_async_copy(rcv.at[p], rcv.at[p],
                                      rsem.at[p]).wait()
                inner_add(rcv.at[p], dsts[di], dsts[di])
                pltpu.semaphore_signal(
                    credit, inc=1, device_id={axis: peer},
                    device_id_type=pltpu.DeviceIdType.MESH)
            if not last:
                if s >= 2:
                    pltpu.semaphore_wait(credit, 1)
                dl.remote_copy(snd.at[p], rcv.at[(s + 1) % 2],
                               ssem.at[p], rsem.at[(s + 1) % 2],
                               axis, nbr).start()

    # Final drains, per direction (mirrors _gemm_rs_kernel's epilogue).
    pfin = (world - 2) % 2
    n_credit_waits = max(world - 3, 0)
    for (snd, rcv, ssem, rsem, credit, nbr, peer, coff, sign) in dirs:
        pltpu.make_async_copy(snd.at[pfin], snd.at[pfin],
                              ssem.at[pfin]).wait()
        pltpu.semaphore_wait(credit, (world - 1) - n_credit_waits)


def _torus_gemm_rs_kernel(
    a_ref,      # [M, k_loc]                   ANY
    b_ref,      # [k_loc, N]                   ANY
    out_ref,    # [rows, N]                    ANY: my band, flat axes-major
    *bufs_and_sems,
    axes, sizes, rows, paths, bm, bn, bk,
):
    """Fused 2-/3-axis torus GEMM-ReduceScatter: the MXU pipeline is the
    PRODUCER inside the 2n-path torus RS schedule, so every axis's link
    directions stay busy through the whole epilogue (VERDICT r2 missing
    #3: the round-2 2-axis path ran the fused ring on one axis and a
    wire-only second ring on the other, idling half the links; 3-axis
    meshes get the six-path cyclic schedule).

    Reference analog: the multi-node threadblock swizzle that makes the
    reference's RS fabric-matched end-to-end
    (gemm_rs_threadblock_swizzle.py).

    Paths split the N COLUMNS into 2n parts with the torus flavor set
    (cyclic axis orders × directions) — column parts keep every ring
    group a set of whole C row-blocks, so the producer is a clean
    [rows, cln] GEMM per slot.  Per path (order, d):

    * Phase 0 rings, along order[0], the row-groups of slots sharing an
      order[0] coordinate: at step s the path GEMMs its partial for ring
      group ``(my - d(1+s)) mod w`` (one [rows, cln] GEMM per free
      slot), folds the partial arriving from upstream, and forwards —
      the GEMMs hide the in-flight DMAs exactly like the 1-axis kernel.
    * Phase l >= 1 rings, along order[l], the order-major sub-bands of
      the previous phase's accumulator (free-slot index space is
      order-major, so each sub-band is one contiguous ``pl.ds`` slice);
      the final phase's last fold writes my fully-reduced [rows, cln]
      stripe of ``out_ref`` directly.

    Output band = flat AXES-MAJOR rank, so the host reassembles C with
    natural-order out_specs ``P(axes)``.  Flow control per (path,
    phase): single landing buffer + credit semaphore (ring depth 1),
    sends drained before their acc is reused.
    """
    from triton_dist_tpu.kernels.torus import _LBL

    n = len(axes)
    lbls = _LBL[:n]
    # bufs: (acc_l, rcv_l) for l in 0..n-1, then sems + gacc.
    accs = bufs_and_sems[0:2 * n:2]
    rcvs = bufs_and_sems[1:2 * n:2]
    (send_sem, recv_sem, credit_sem, copy_sem,
     gacc) = bufs_and_sems[2 * n:]
    coords = {l: jax.lax.axis_index(a) for l, a in zip(lbls, axes)}
    size = dict(zip(lbls, sizes))
    mesh_ax = dict(zip(lbls, axes))
    stride = {lbls[i]: int(np.prod(sizes[i + 1:])) for i in range(n)}
    k_loc = a_ref.shape[1]

    for a in axes:
        dl.barrier_all(a)

    # Per-path pipelines (grids depend on cln).
    def make_pipes(cln):
        n_m, n_n, n_k = rows // bm, cln // bn, k_loc // bk
        gemm = pltpu.emit_pipeline(
            functools.partial(gemm_pipeline_body, n_k=n_k,
                              out_dtype=out_ref.dtype),
            grid=(n_m, n_n, n_k),
            in_specs=[pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                      pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))],
            out_specs=[pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))],
        )
        add = pltpu.emit_pipeline(
            _add_body,
            grid=(n_m, n_n),
            in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
                      pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
            out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
        )
        return gemm, add

    pipes = {q: make_pipes(cln) for q, (_, cln, _, _) in enumerate(paths)
             if cln > 0}
    active = [(q, pa) for q, pa in enumerate(paths) if pa[1] > 0]

    from triton_dist_tpu.kernels.torus import free_slot_count

    def gsize(order, l):
        return free_slot_count(order, size, l)

    # ------------------------------------------------------------------
    # Phase 0: ring-RS of order[0] row-groups, GEMM as the producer.
    # ------------------------------------------------------------------
    n0 = max(size[pa[2][0]] for _, pa in active)

    def p0_step(s, _):
        for q, (coff, cln, order, d) in active:
            r = order[0]
            w = size[r]
            gs = gsize(order, 0)
            my = coords[r]
            peer = jax.lax.rem(my + d + w, w)
            prev = jax.lax.rem(my - d + w, w)
            gemm, add = pipes[q]
            grp = accs[0].at[q, pl.ds(0, gs), :, pl.ds(0, cln)]

            @pl.when(s < w)
            def _(q=q, coff=coff, cln=cln, order=order, d=d, r=r, w=w,
                  gs=gs, my=my, peer=peer, prev=prev, gemm=gemm, add=add,
                  grp=grp):
                # Drain my previous send before overwriting the group.
                @pl.when(s > 0)
                def _():
                    pltpu.make_async_copy(grp, grp, send_sem.at[q, 0]).wait()

                # Producer: one [rows, cln] partial GEMM per free slot of
                # ring group (my - d(1+s)) — final step s = w-1 lands on
                # my own group.
                idx = jax.lax.rem(my - d * (1 + s) + (1 + s) * w + w, w)
                for f in range(gs):
                    # Decompose the order-major free index into pending-
                    # axis coords, then flatten to the storage rank.
                    flat = idx * stride[r]
                    rem_f = f
                    for a in reversed(order[1:]):
                        rem_f, c = divmod(rem_f, size[a])
                        flat = flat + c * stride[a]
                    gemm(a_ref.at[pl.ds(flat * rows, rows)],
                         b_ref.at[:, pl.ds(coff, cln)],
                         accs[0].at[q, f, :, pl.ds(0, cln)],
                         scratches=(gacc,))

                @pl.when(s > 0)
                def _():
                    # Fold the upstream partial that rode under the GEMMs.
                    pltpu.make_async_copy(grp, grp, recv_sem.at[q, 0]).wait()
                    for f in range(gs):
                        add(rcvs[0].at[q, f, :, pl.ds(0, cln)],
                            accs[0].at[q, f, :, pl.ds(0, cln)],
                            accs[0].at[q, f, :, pl.ds(0, cln)])
                    pltpu.semaphore_signal(
                        credit_sem.at[q, 0], inc=1,
                        device_id={mesh_ax[r]: prev},
                        device_id_type=pltpu.DeviceIdType.MESH)

                @pl.when(s < w - 1)
                def _():
                    @pl.when(s > 0)
                    def _():
                        pltpu.semaphore_wait(credit_sem.at[q, 0], 1)
                    dl.remote_copy(grp,
                                   rcvs[0].at[q, pl.ds(0, gs), :,
                                              pl.ds(0, cln)],
                                   send_sem.at[q, 0], recv_sem.at[q, 0],
                                   mesh_ax[r], peer).start()
        return 0

    jax.lax.fori_loop(0, n0, p0_step, 0)

    # ------------------------------------------------------------------
    # Phases 1..n-1: ring-RS of order-major sub-bands of the previous
    # accumulator; the final phase's last fold writes out_ref.
    # ------------------------------------------------------------------
    for l in range(1, n):
        final = l == n - 1
        n_l = max(size[pa[2][l]] for _, pa in active)

        def pl_step(t, _, l=l, final=final):
            for q, (coff, cln, order, d) in active:
                r = order[l]
                w = size[r]
                gs = gsize(order, l)
                my = coords[r]
                peer = jax.lax.rem(my + d + w, w)
                prev = jax.lax.rem(my - d + w, w)
                _, add = pipes[q]
                band = accs[l].at[q, pl.ds(0, gs), :, pl.ds(0, cln)]

                @pl.when(t < w)
                def _(q=q, coff=coff, cln=cln, order=order, d=d, r=r, w=w,
                      gs=gs, my=my, peer=peer, prev=prev, add=add,
                      band=band):
                    @pl.when(t > 0)
                    def _():
                        pltpu.make_async_copy(band, band,
                                              send_sem.at[q, l]).wait()

                    idx = jax.lax.rem(my - d * (1 + t) + (1 + t) * w + w, w)
                    src = accs[l - 1].at[q, pl.ds(idx * gs, gs), :,
                                         pl.ds(0, cln)]

                    @pl.when(t == 0)
                    def _():
                        # First hop: my contribution alone.
                        cp = pltpu.make_async_copy(src, band, copy_sem)
                        cp.start()
                        cp.wait()

                    def fold(dst_f):
                        pltpu.make_async_copy(band, band,
                                              recv_sem.at[q, l]).wait()
                        for f in range(gs):
                            add(accs[l - 1].at[q, idx * gs + f, :,
                                               pl.ds(0, cln)],
                                rcvs[l].at[q, f, :, pl.ds(0, cln)],
                                dst_f(f))
                        pltpu.semaphore_signal(
                            credit_sem.at[q, l], inc=1,
                            device_id={mesh_ax[r]: prev},
                            device_id_type=pltpu.DeviceIdType.MESH)

                    if final:
                        @pl.when(jnp.logical_and(t > 0, t < w - 1))
                        def _():
                            fold(lambda f: accs[l].at[q, f, :,
                                                      pl.ds(0, cln)])

                        @pl.when(t == w - 1)
                        def _():
                            # Last fold writes my output stripe directly.
                            fold(lambda f: out_ref.at[:, pl.ds(coff, cln)])
                    else:
                        @pl.when(t > 0)
                        def _():
                            fold(lambda f: accs[l].at[q, f, :,
                                                      pl.ds(0, cln)])

                    @pl.when(t < w - 1)
                    def _():
                        @pl.when(t > 0)
                        def _():
                            pltpu.semaphore_wait(credit_sem.at[q, l], 1)
                        dl.remote_copy(band,
                                       rcvs[l].at[q, pl.ds(0, gs), :,
                                                  pl.ds(0, cln)],
                                       send_sem.at[q, l], recv_sem.at[q, l],
                                       mesh_ax[r], peer).start()
            return 0

        jax.lax.fori_loop(0, n_l, pl_step, 0)

    # Zero the leftover credit (one un-waited signal per path per phase).
    # Sends are already drained: every phase posts w-1 and waits at steps
    # 1..w-1 — an extra drain here would deadlock.
    for q, (coff, cln, order, d) in active:
        for l in range(n):
            pltpu.semaphore_wait(credit_sem.at[q, l], 1)


def _torus_gemm_rs_shard(a_shard, b_shard, *, axes, impl, bm, bn, bk,
                         interpret):
    """2-/3-axis fused torus GEMM-RS (see kernel docstring).  Output band
    = flat AXES-MAJOR rank; host out_specs = P(axes)."""
    from triton_dist_tpu.kernels.torus import _path_flavors, _split_parts

    n = len(axes)
    sizes = tuple(jax.lax.axis_size(a) for a in axes)
    world = int(np.prod(sizes))
    M, k_loc = a_shard.shape
    N = b_shard.shape[1]
    assert M % world == 0, (M, world)
    rows = M // world
    quantized = a_shard.dtype == jnp.int8
    out_dtype = jnp.int32 if quantized else a_shard.dtype
    acc_dtype = jnp.int32 if quantized else jnp.float32
    impl = resolve_impl(impl, interpret)
    npaths = 2 * n

    # Column parts in 128-lane units with the 2n torus flavors.
    ok = (N % 128 == 0 and impl != "xla"
          and pallas_shapes_ok(rows, min(N, 128), k_loc))
    if ok:
        units = _split_parts(N // 128, npaths)
        paths = tuple((off * 128, ln * 128, order, d)
                      for (off, ln), (order, d) in zip(
                          units, _path_flavors(n)))
        clns = [ln for _, ln, _, _ in paths if ln > 0]
        cgcd = math.gcd(*clns)
        bm = largest_divisor_block(rows, bm, 8)
        bn = largest_divisor_block(cgcd, bn, 128)
        bk = largest_divisor_block(k_loc, bk, 128)
    if not ok:
        # Shapes the fused kernel cannot tile: fall back to the
        # overlapped composition — the 1-axis fused GEMM-RS over axes[0]
        # then ring RS over the rest (internals degrade further to XLA
        # where even 1-axis tiling fails).  axes[0]-first keeps the band
        # order flat AXES-MAJOR, matching the fused kernel's contract.
        from triton_dist_tpu.kernels.collective_ids import (
            GEMM_RS_SECOND,
            TORUS_RS_FALLBACK,
        )
        from triton_dist_tpu.kernels.reduce_scatter import (
            reduce_scatter_shard,
        )

        part = gemm_rs_shard(a_shard, b_shard, axis=axes[0], impl=impl,
                             bm=bm, bn=bn, bk=bk, interpret=interpret)
        for a, fid in zip(axes[1:], (GEMM_RS_SECOND, TORUS_RS_FALLBACK)):
            part = reduce_scatter_shard(part, a, interpret=interpret,
                                        collective_id=fid)
        return part

    from triton_dist_tpu.kernels.torus import _LBL, free_slot_count

    cmax = max(clns)
    flavors = _path_flavors(n)
    size_by_lbl = dict(zip(_LBL[:n], sizes))
    gmaxes = [max(free_slot_count(order, size_by_lbl, l)
                  for order, _ in flavors) for l in range(n)]
    buf_shapes = []
    for l in range(n):
        shp = jax.ShapeDtypeStruct((npaths, gmaxes[l], rows, cmax),
                                   out_dtype)
        buf_shapes += [shp, shp]  # acc_l, rcv_l
    out, *_scratch = pl.pallas_call(
        functools.partial(_torus_gemm_rs_kernel, axes=axes,
                          sizes=sizes, rows=rows, paths=paths,
                          bm=bm, bn=bn, bk=bk),
        out_shape=[jax.ShapeDtypeStruct((rows, N), out_dtype)] + buf_shapes,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (1 + 2 * n),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((npaths, n)),
            pltpu.SemaphoreType.DMA((npaths, n)),
            pltpu.SemaphoreType.REGULAR((npaths, n)),
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((bm, bn), acc_dtype),
        ],
        compiler_params=dl.collective_compiler_params(
            world, GEMM_RS_COLLECTIVE_ID),
        interpret=maybe_interpret(interpret),
    )(a_shard, b_shard)
    return out


def gemm_rs_shard(a_shard, b_shard, *, axis, impl, bm=None, bn=None,
                  bk=None, ring_mode="uni", interpret=False):
    """Per-device GEMM-RS; call inside shard_map.  Returns the reduced chunk.
    Block sizes default to the swept MatmulConfig (gemm.py).

    ``axis`` may be a tuple (ax, ay) of mesh axes (K sharded over the
    joint axes): the fused four-path torus kernel then runs — the MXU
    producer inside the 2-axis RS schedule, both axes' links busy through
    the whole epilogue (_torus_gemm_rs_kernel; the round-2 wire-only
    second ring idled half the links).  Device (i, j) ends with flat band
    ``i * wy + j`` (axes-major), so the host reassembles C with natural
    ``P(axes)`` out_specs (see :func:`gemm_rs`).

    ``ring_mode="bidir"`` (r5): the two column halves run mirrored ring
    reductions in opposite directions — both 1-axis link directions busy,
    ~2x per-step wire (``_gemm_rs_bidir_kernel``).  Falls back to the
    uni/torus schedule SILENTLY when the mode cannot apply: N/2 not
    lane-tileable (% 128), multi-axis meshes (the torus schedule already
    drives every link direction), and world 1.
    """
    _cfg = MatmulConfig()
    bm, bn, bk = bm or _cfg.block_m, bn or _cfg.block_n, bk or _cfg.block_k
    if isinstance(axis, (tuple, list)) and len(axis) > 1:
        axes = tuple(axis)
        if len(axes) not in (2, 3):
            raise ValueError(f"gemm_rs supports 1-3 axes, got {axes}")
        real = tuple(a for a in axes if jax.lax.axis_size(a) > 1)
        if len(real) <= 1:
            axis = real[0] if real else axes[0]
        else:
            return _torus_gemm_rs_shard(a_shard, b_shard, axes=real,
                                        impl=impl, bm=bm, bn=bn, bk=bk,
                                        interpret=interpret)
    axis = axis[0] if isinstance(axis, (tuple, list)) else axis
    raw_impl = impl
    impl = resolve_impl(impl, interpret)
    world = jax.lax.axis_size(axis)
    M, k_loc = a_shard.shape
    N = b_shard.shape[1]
    assert M % world == 0, (M, world)
    m_loc = M // world
    # int8: exact i32 partials; the ring adds stay exact (i32 + i32), so
    # the reduced output is bit-equal to an unquantized int accumulation.
    quantized = a_shard.dtype == jnp.int8
    out_dtype = jnp.int32 if quantized else a_shard.dtype
    acc_dtype = jnp.int32 if quantized else jnp.float32

    if use_fallback(raw_impl, impl, pallas_shapes_ok(m_loc, N, k_loc),
                    "gemm_rs", f"per-shard ({m_loc}, {N}, {k_loc}); needs m%8, n%128, k%128"):
        pref = jnp.int32 if quantized else jnp.float32
        partial = jnp.dot(a_shard, b_shard, preferred_element_type=pref)
        return jax.lax.psum_scatter(
            partial, axis, scatter_dimension=0, tiled=True
        ).astype(out_dtype)

    if world == 1 and raw_impl == "auto" and not interpret:
        # Degenerate world under auto dispatch: no scatter, no partial
        # rotation — XLA's dot for float (chain-fusion win, see
        # ag_gemm_shard's twin path), the pallas double-rate kernel for
        # int8.
        if quantized:
            from triton_dist_tpu.kernels.quant import matmul_i8
            return matmul_i8(a_shard, b_shard)
        return jnp.dot(a_shard, b_shard,
                       preferred_element_type=jnp.float32).astype(out_dtype)

    if (ring_mode == "bidir" and world > 1
            and N % 2 == 0 and (N // 2) % 128 == 0):
        nh = N // 2
        bm_h = largest_divisor_block(m_loc, bm, 8)
        bn_h = largest_divisor_block(nh, bn, 128)
        bk_h = largest_divisor_block(k_loc, bk, 128)
        out, _, _, _, _ = pl.pallas_call(
            functools.partial(
                _gemm_rs_bidir_kernel, axis=axis, world=world,
                m_loc=m_loc, bm=bm_h, bn=bn_h, bk=bk_h,
            ),
            out_shape=[
                jax.ShapeDtypeStruct((m_loc, N), out_dtype),
                jax.ShapeDtypeStruct((2, m_loc, nh), out_dtype),
                jax.ShapeDtypeStruct((2, m_loc, nh), out_dtype),
                jax.ShapeDtypeStruct((2, m_loc, nh), out_dtype),
                jax.ShapeDtypeStruct((2, m_loc, nh), out_dtype),
            ],
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 5,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
                pltpu.SemaphoreType.REGULAR,
                pltpu.VMEM((bm_h, bn_h), acc_dtype),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=GEMM_RS_COLLECTIVE_ID,
            ),
            interpret=maybe_interpret(interpret),
        )(a_shard, b_shard)
        return out

    bm = largest_divisor_block(m_loc, bm, 8)
    bn = largest_divisor_block(N, bn, 128)
    bk = largest_divisor_block(k_loc, bk, 128)

    out, _, _ = pl.pallas_call(
        functools.partial(
            _gemm_rs_kernel, axis=axis, world=world, m_loc=m_loc,
            bm=bm, bn=bn, bk=bk,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((m_loc, N), out_dtype),
            jax.ShapeDtypeStruct((2, m_loc, N), out_dtype),
            jax.ShapeDtypeStruct((2, m_loc, N), out_dtype),
        ],
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
            pltpu.VMEM((bm, bn), acc_dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            # Mosaic rejects a collective_id when the kernel never touches
            # the barrier semaphore (the world-1 degenerate path).
            collective_id=GEMM_RS_COLLECTIVE_ID if world > 1 else None,
        ),
        interpret=maybe_interpret(interpret),
    )(a_shard, b_shard)
    return out


def gemm_rs(a, b, ctx: GEMMReduceScatterContext):
    """C = reduce_scatter(A_loc @ B_loc, axis), overlapped.  Host entry
    (reference: ``gemm_rs`` gemm_reduce_scatter.py:547).  With a 2- or
    3-tuple ``ctx.axis`` the fused 2n-path torus kernel runs (four paths
    on 2 axes, six on 3); bands come out flat axes-major, so natural
    ``P(axes)`` out_specs reassemble C in row order."""
    cfg = ctx.config
    axis = ctx.axis
    if isinstance(axis, (tuple, list)) and len(axis) > 1:
        out_spec = P(tuple(axis), None)
    else:
        out_spec = P(axis, None)
    fn = cached_shard_jit(
        gemm_rs_shard,
        ctx.mesh,
        (P(None, ctx.axis), P(ctx.axis, None)),
        out_spec,
        axis=tuple(axis) if isinstance(axis, list) else axis, impl=ctx.impl,
        bm=cfg.block_m, bn=cfg.block_n, bk=cfg.block_k,
        ring_mode=ctx.ring_mode, interpret=ctx.interpret,
    )
    # Launch metadata (reference: launch_metadata hooks report flops/bytes,
    # gemm_reduce_scatter.py).  Per-device: [M, k_loc] x [k_loc, N] MXU
    # work; bytes = A/B reads + ring partial traffic (~M*N through HBM).
    from triton_dist_tpu.runtime.profiling import annotate

    axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    world = int(np.prod([ctx.mesh.shape[ax] for ax in axes]))
    M = a.shape[0]
    N = b.shape[1]
    k_loc = a.shape[1] // max(world, 1)
    el = jnp.dtype(a.dtype).itemsize
    with annotate("gemm_rs", flops=2 * M * N * k_loc,
                  bytes_accessed=(M * k_loc + k_loc * N + M * N) * el):
        return fn(a, b)


# ---------------------------------------------------------------------------
# Autotuned entry (VERDICT r2 #5).
# ---------------------------------------------------------------------------

from triton_dist_tpu.autotuner import autotune as _autotune
# One shared block space for both overlapped kernels: a new winner from
# the next on-chip session lands in both sweeps.  (The AG side
# additionally crosses in its ring-forward chunk axis, which GEMM-RS
# does not have.)
from triton_dist_tpu.kernels.allgather_gemm import (
    OVERLAP_BLOCK_SPACE as _OVERLAP_BLOCK_SPACE,
)
from triton_dist_tpu.autotuner import Config as _RsCfg

# The shared block space plus the r5 bidirectional ring alternative.
GEMM_RS_TUNE_SPACE = (
    list(_OVERLAP_BLOCK_SPACE)
    + [_RsCfg(bm=1024, bn=512, bk=512, ring_mode="bidir"),
       _RsCfg(bm=512, bn=512, bk=512, ring_mode="bidir")]
)


@_autotune(configs=GEMM_RS_TUNE_SPACE, key=())
def _gemm_rs_tunable(a, b, *, ctx, bm=None, bn=None, bk=None,
                     ring_mode="uni"):
    tuned = GEMMReduceScatterContext(
        mesh=ctx.mesh, axis=ctx.axis, impl=ctx.impl,
        config=MatmulConfig(bm, bn, bk), ring_mode=ring_mode,
        interpret=ctx.interpret)
    return gemm_rs(a, b, tuned)


def gemm_rs_autotuned(a, b, ctx: GEMMReduceScatterContext):
    """:func:`gemm_rs` with blocks selected by the autotuner — each config
    jits the whole overlapped collective program (ring or fused torus
    schedule included), winners cached per (shape, dtype, ctx).  See
    ``ag_gemm_autotuned`` for the tuning-protocol notes."""
    return _gemm_rs_tunable(a, b, ctx=ctx)
