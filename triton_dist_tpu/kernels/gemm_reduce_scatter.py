"""Overlapped GEMM-ReduceScatter — the tensor-parallel backward-half kernel.

Reference analog: ``python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py``
— a persistent producer GEMM writes output tiles, counts per-segment
completions with ``tl.atomic_add`` and fires ``dl.notify`` when a rank's
segment is done (:226-235), while a reduce-scatter consumer on a second
stream (``rs_stream``) scatters + ring-reduces the segments
(``reduce_scatter.py:604-860``); a rank-offset tile swizzle makes segment
``i`` of rank ``r`` finish early (:190-200).

TPU-native design (NOT a port): no streams, no atomics — ONE Pallas kernel
runs a ring reduce-scatter whose per-chunk partial GEMM overlaps the
in-flight partial-sum DMA:

* Sharding (row-parallel linear): A [M, K] is K-sharded, B [K, N] K-sharded,
  so each device's GEMM ``A_loc @ B_loc`` is a *partial sum* of C [M, N];
  the reduce-scatter sums partials and leaves M-chunk ``d`` on device ``d``.
* Ring schedule: the partial for chunk ``c`` starts at device ``c+1`` and
  travels right, accumulating each device's local contribution; after
  ``world-1`` hops it reaches its owner ``c`` fully reduced.  Device ``d``
  therefore computes chunks ``(d-1), (d-2), ..., (d+1) mod world`` and
  finally its own chunk ``d`` — the reference's rank-offset swizzle
  (gemm_rs_threadblock_swizzle.py) is this schedule's natural order.
* Overlap: at step ``s`` the inner MXU pipeline computes ``A[c_s] @ B_loc``
  while the previous partial (sent by the left neighbor during *its* step
  ``s-1``) is still in flight; the recv wait happens only before the cheap
  VPU add pass that folds the received partial in.  The add pass is the
  analog of the reference's ``ring_reduce`` on the reduction stream.
* Flow control: double-buffered landing slots + a credit semaphore replace
  the reference's ``wait_eq`` scatter signals (reduce_scatter.py:604-637).

Sharding contract (1-D TP over ``axis``):
  A: [M, K]   sharded P(None, axis)  (per-device [M, k_loc])
  B: [K, N]   sharded P(axis, None)  (per-device [k_loc, N])
  C: [M, N]   sharded P(axis, None)  (per-device [m_loc, N], fully reduced)
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels.gemm import (
    MatmulConfig,
    gemm_pipeline_body,
    largest_divisor_block,
    matmul,
    pallas_shapes_ok,
    resolve_impl,
)
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

from triton_dist_tpu.kernels.collective_ids import (
    GEMM_RS as GEMM_RS_COLLECTIVE_ID,
    GEMM_RS_SECOND,
)


@dataclass
class GEMMReduceScatterContext:
    """Reference analog: ``GEMMReduceScatterTensorParallelContext``
    (gemm_reduce_scatter.py:240+) minus streams/symm workspace."""

    mesh: Mesh
    axis: str = "tp"
    impl: str = "auto"
    config: MatmulConfig = field(default_factory=MatmulConfig)
    interpret: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_gemm_rs_context(mesh, axis="tp", impl="auto", config=None,
                           interpret=False) -> GEMMReduceScatterContext:
    return GEMMReduceScatterContext(
        mesh=mesh, axis=axis, impl=impl,
        config=config or MatmulConfig(), interpret=interpret,
    )


def _add_body(recv_blk, dst_in_blk, dst_out_blk):
    """dst += recv fold of the in-flight ring partial (the reference's
    ring_reduce add kernel, reduce_scatter.py:828)."""
    dst_out_blk[:] = dst_in_blk[:] + recv_blk[:]


def _gemm_rs_kernel(
    a_ref,       # [M, k_loc]        ANY
    b_ref,       # [k_loc, N]        ANY
    out_ref,     # [m_loc, N]        ANY, output: reduced C chunk
    send_ref,    # [2, m_loc, N]     ANY, output (scratch): partial staging
    recv_ref,    # [2, m_loc, N]     ANY, output (scratch): landing slots
    send_sem, recv_sem, credit_sem,
    acc_ref,     # VMEM (bm, bn) f32
    *,
    axis, world, m_loc, bm, bn, bk,
):
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)
    dtype_ref = out_ref

    k_loc = a_ref.shape[1]
    N = b_ref.shape[1]
    n_m, n_n, n_k = m_loc // bm, N // bn, k_loc // bk

    inner_gemm = pltpu.emit_pipeline(
        functools.partial(gemm_pipeline_body, n_k=n_k, out_dtype=dtype_ref.dtype),
        grid=(n_m, n_n, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))],
    )
    inner_add = pltpu.emit_pipeline(
        _add_body,
        grid=(n_m, n_n),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
    )

    if world > 1:
        # Entry barrier with ring neighbors before any remote write.
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(barrier, 2)

    for s in range(world):
        p = s % 2
        last = s == world - 1
        # Chunk schedule: (me-1-s) mod world, except the final step reduces
        # our own chunk (see module docstring for the ring derivation).
        if last:
            chunk = me
        else:
            chunk = jax.lax.rem(me - 1 - s + 2 * world, world)
        dst = out_ref if last else send_ref.at[p]

        if s >= 2:
            # send_ref slot p was last DMA'd at step s-2; drain before reuse.
            # Semaphores are per-slot: with two sends in flight, a shared
            # semaphore could let the *other* slot's completion satisfy this
            # wait and the GEMM would overwrite a buffer still being read.
            pltpu.make_async_copy(send_ref.at[p], send_ref.at[p],
                                  send_sem.at[p]).wait()

        # Partial GEMM for this chunk — overlaps the in-flight recv DMA.
        inner_gemm(a_ref.at[pl.ds(chunk * m_loc, m_loc)], b_ref, dst,
                   scratches=(acc_ref,))

        if s >= 1:
            # Fold in the partial received from the left (landed in slot p).
            pltpu.make_async_copy(recv_ref.at[p], recv_ref.at[p],
                                  recv_sem.at[p]).wait()
            inner_add(recv_ref.at[p], dst, dst)
            # Slot p is now free for the left neighbor's step-(s+1) send.
            pltpu.semaphore_signal(credit_sem, inc=1, device_id={axis: left},
                                   device_id_type=pltpu.DeviceIdType.MESH)

        if not last:
            if s >= 2:
                # Right's landing slot (s+1)%2 is reused from step s-2; wait
                # for the credit it issued after consuming it at step s-1.
                pltpu.semaphore_wait(credit_sem, 1)
            dl.remote_copy(send_ref.at[p], recv_ref.at[(s + 1) % 2],
                           send_sem.at[p], recv_sem.at[(s + 1) % 2],
                           axis, right).start()

    if world > 1:
        # Drain the final outstanding send (issued at step world-2).
        pfin = (world - 2) % 2
        pltpu.make_async_copy(send_ref.at[pfin], send_ref.at[pfin],
                              send_sem.at[pfin]).wait()
        # Unconsumed credits: the right neighbor signals one credit per fold
        # (world-1 total) but we only wait world-3 times; drain the rest so
        # the semaphore is zero at kernel exit.
        n_credit_waits = max(world - 3, 0)
        pltpu.semaphore_wait(credit_sem, (world - 1) - n_credit_waits)


def gemm_rs_shard(a_shard, b_shard, *, axis, impl, bm=None, bn=None,
                  bk=None, interpret=False):
    """Per-device GEMM-RS; call inside shard_map.  Returns the reduced chunk.
    Block sizes default to the swept MatmulConfig (gemm.py).

    ``axis`` may be a tuple (ax, ay) of mesh axes (K sharded over the joint
    axes): the fused overlapped kernel then runs over ``ay`` — GEMM hidden
    under the first, wy-fold heavier ring — and a second wire-only ring RS
    over ``ax`` moves only 1/wy of the data (reductions shrink: same phase
    order as ``hierarchical.hier_reduce_scatter_shard``).  Device (i, j)
    ends with flat band ``j * wx + i``, so a host wrapper using out_specs
    ``P((ay, ax))`` reassembles C in natural order (see :func:`gemm_rs`).
    """
    _cfg = MatmulConfig()
    bm, bn, bk = bm or _cfg.block_m, bn or _cfg.block_n, bk or _cfg.block_k
    if isinstance(axis, (tuple, list)) and len(axis) > 1:
        from triton_dist_tpu.kernels.reduce_scatter import (
            reduce_scatter_shard,
        )

        axes = tuple(axis)
        if len(axes) != 2:
            raise ValueError(f"gemm_rs supports 1 or 2 axes, got {axes}")
        ax, ay = axes
        sizes = (jax.lax.axis_size(ax), jax.lax.axis_size(ay))
        if 1 in sizes:
            axis = axes[sizes.index(max(sizes))]
        else:
            part = gemm_rs_shard(a_shard, b_shard, axis=ay, impl=impl,
                                 bm=bm, bn=bn, bk=bk, interpret=interpret)
            return reduce_scatter_shard(
                part, ax, interpret=interpret,
                collective_id=GEMM_RS_SECOND)
    axis = axis[0] if isinstance(axis, (tuple, list)) else axis
    raw_impl = impl
    impl = resolve_impl(impl, interpret)
    world = jax.lax.axis_size(axis)
    M, k_loc = a_shard.shape
    N = b_shard.shape[1]
    assert M % world == 0, (M, world)
    m_loc = M // world
    # int8: exact i32 partials; the ring adds stay exact (i32 + i32), so
    # the reduced output is bit-equal to an unquantized int accumulation.
    quantized = a_shard.dtype == jnp.int8
    out_dtype = jnp.int32 if quantized else a_shard.dtype
    acc_dtype = jnp.int32 if quantized else jnp.float32

    if impl == "xla" or not pallas_shapes_ok(m_loc, N, k_loc):
        pref = jnp.int32 if quantized else jnp.float32
        partial = jnp.dot(a_shard, b_shard, preferred_element_type=pref)
        return jax.lax.psum_scatter(
            partial, axis, scatter_dimension=0, tiled=True
        ).astype(out_dtype)

    if world == 1 and raw_impl == "auto" and not interpret:
        # Degenerate world under auto dispatch: no scatter, no partial
        # rotation — the plain MXU matmul (see ag_gemm_shard's twin path).
        if quantized:
            from triton_dist_tpu.kernels.quant import matmul_i8
            return matmul_i8(a_shard, b_shard)
        return matmul(a_shard, b_shard, config=MatmulConfig(bm, bn, bk),
                      out_dtype=out_dtype)

    bm = largest_divisor_block(m_loc, bm, 8)
    bn = largest_divisor_block(N, bn, 128)
    bk = largest_divisor_block(k_loc, bk, 128)

    out, _, _ = pl.pallas_call(
        functools.partial(
            _gemm_rs_kernel, axis=axis, world=world, m_loc=m_loc,
            bm=bm, bn=bn, bk=bk,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((m_loc, N), out_dtype),
            jax.ShapeDtypeStruct((2, m_loc, N), out_dtype),
            jax.ShapeDtypeStruct((2, m_loc, N), out_dtype),
        ],
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR,
            pltpu.VMEM((bm, bn), acc_dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            # Mosaic rejects a collective_id when the kernel never touches
            # the barrier semaphore (the world-1 degenerate path).
            collective_id=GEMM_RS_COLLECTIVE_ID if world > 1 else None,
        ),
        interpret=maybe_interpret(interpret),
    )(a_shard, b_shard)
    return out


def gemm_rs(a, b, ctx: GEMMReduceScatterContext):
    """C = reduce_scatter(A_loc @ B_loc, axis), overlapped.  Host entry
    (reference: ``gemm_rs`` gemm_reduce_scatter.py:547).  With a 2-tuple
    ``ctx.axis`` the two-tier torus schedule runs; the shard bands come out
    fast-major, so ``out_specs`` swaps the axes to reassemble C in natural
    row order."""
    cfg = ctx.config
    axis = ctx.axis
    if isinstance(axis, (tuple, list)) and len(axis) > 1:
        out_spec = P(tuple(reversed(tuple(axis))), None)
    else:
        out_spec = P(axis, None)
    fn = cached_shard_jit(
        gemm_rs_shard,
        ctx.mesh,
        (P(None, ctx.axis), P(ctx.axis, None)),
        out_spec,
        axis=tuple(axis) if isinstance(axis, list) else axis, impl=ctx.impl,
        bm=cfg.block_m, bn=cfg.block_n, bk=cfg.block_k,
        interpret=ctx.interpret,
    )
    return fn(a, b)
