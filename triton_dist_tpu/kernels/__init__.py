"""Distributed kernel library (TPU-native).

Reference analog: ``python/triton_dist/kernels/nvidia/__init__.py:25-38``
which exports ``ag_gemm``, ``gemm_rs``, ``moe_reduce_rs``, ``ag_group_gemm``,
``fast_allgather``, ``fast_all_to_all``, ``gqa_fwd_batch_decode*`` and their
``create_*_context`` factories.

Every collective op here accepts ``impl="auto"|"xla"|"pallas"``:

* ``xla`` — lax collectives under shard_map; XLA's latency-hiding scheduler
  overlaps them with compute.  Runs everywhere (CPU test meshes included) and
  is the performance baseline the pallas path must beat.
* ``pallas`` — hand-scheduled Mosaic kernels: remote DMA + semaphores, with
  communication pipelined against MXU compute inside one kernel.
* ``auto`` — pallas on TPU when shapes qualify, else xla.
"""

from triton_dist_tpu.kernels.gemm import matmul, matmul_kernel_tflops  # noqa: F401
from triton_dist_tpu.kernels.quant import (  # noqa: F401
    Int8MatmulConfig,
    matmul_i8,
    quantize_channelwise,
    quantize_rowwise,
    w8a8_linear,
)
from triton_dist_tpu.kernels.allgather import (  # noqa: F401
    all_gather,
    create_allgather_context,
    AllGatherMethod,
)
from triton_dist_tpu.kernels.reduce_scatter import (  # noqa: F401
    reduce_scatter,
    create_reduce_scatter_context,
)
from triton_dist_tpu.kernels.common_ops import barrier_all_on_mesh  # noqa: F401
from triton_dist_tpu.kernels.allgather_gemm import (  # noqa: F401
    ag_gemm,
    ag_gemm_gathered,
    create_ag_gemm_context,
)
from triton_dist_tpu.kernels.gemm_reduce_scatter import (  # noqa: F401
    gemm_rs,
    create_gemm_rs_context,
)
from triton_dist_tpu.kernels.low_latency_allgather import (  # noqa: F401
    fast_allgather,
    create_fast_ag_context,
)
from triton_dist_tpu.kernels.all_to_all import (  # noqa: F401
    fast_all_to_all,
    all_to_all_post_process,
    create_all_to_all_context,
)
from triton_dist_tpu.kernels.flash_decode import (  # noqa: F401
    gqa_decode_shard,
    gqa_decode_paged_shard,
    sp_gqa_decode,
    sp_gqa_decode_paged_shard,
    create_sp_decode_context,
)
from triton_dist_tpu.kernels.flash_attention import (  # noqa: F401
    flash_attention,
    flash_gqa_attention,
)
from triton_dist_tpu.kernels.moe_utils import (  # noqa: F401
    topk_routing,
    sort_align,
    gather_sorted,
    combine_topk,
)
from triton_dist_tpu.kernels.group_gemm import (  # noqa: F401
    group_gemm,
    moe_ffn_sorted,
)
from triton_dist_tpu.kernels.allgather_group_gemm import (  # noqa: F401
    ag_group_gemm,
    create_ag_group_gemm_context,
)
from triton_dist_tpu.kernels.moe_reduce_rs import (  # noqa: F401
    moe_reduce_rs,
    create_moe_rs_context,
)
from triton_dist_tpu.kernels.ring_attention import (  # noqa: F401
    RingAttentionContext,
    create_ring_attention_context,
    ring_attention,
    ring_attention_shard,
)
from triton_dist_tpu.kernels.ulysses_attention import (  # noqa: F401
    UlyssesContext,
    create_ulysses_context,
    ulysses_attention,
    ulysses_attention_shard,
)
