"""Overlapped AllGather-GroupGEMM — MoE tensor-parallel forward (AG side).

Reference analog: ``python/triton_dist/kernels/nvidia/allgather_group_gemm.py``
(499 LoC) — tokens are allgathered across the TP group while a grouped GEMM
consumes them; each GEMM tile spins on the barrier of the source rank whose
tokens it needs (``dl.wait(block_barrier_ptr + offs_barrier, 1, "gpu",
"acquire")`` :482); the host pre-sorts gathered tokens by expert (:106-188).

TPU-native design (NOT a port):

* The reference sorts the *full* gathered buffer, so one tile can mix tokens
  from several source ranks and must wait on several barriers.  We instead
  sort **per source segment**: every device pre-sorts its own tokens by
  expert (static-padded via ``moe_utils.sort_align``), the sorted segments
  ride the same ring schedule as ``allgather_gemm.py``, and each ring step
  runs a grouped GEMM over exactly one segment.  Expert math is unchanged
  (a token's topk contributions never cross segments) and each tile depends
  on exactly one recv-semaphore — the multi-barrier wait disappears by
  construction.
* Routing metadata (topk expert ids + weights) is tiny, so it goes through
  one XLA allgather up front; every device then derives the *same* per-
  segment sort plans (the reference ships precomputed index tables to all
  ranks for the same reason, :106-188).
* Tile→expert weight steering inside the ring kernel reads the per-segment
  ``tile_expert`` map from SMEM in the inner pipeline's BlockSpec index map
  — the Mosaic analog of the scalar-prefetch steering in
  ``kernels/group_gemm.py`` (same contract, one map per ring slot).

Sharding contract (1-D TP over ``axis``; E experts, topk assignments):
  x:       [T, D]        P(axis, None)   tokens (per-device [t_loc, D])
  weights: [T, topk]     P(axis, None)   routing weights
  experts: [T, topk]     P(axis, None)   routing expert ids (int32)
  w_stack: [E, D, F]     P(None, None, axis)  expert weights (per-dev F_loc)
  out:     [T, F]        P(None, axis)   combined expert outputs
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels.gemm import (
    MatmulConfig,
    group_gemm_pipeline_body,
    largest_divisor_block,
    pallas_shapes_ok,
    resolve_impl,
    use_fallback,
)
from triton_dist_tpu.kernels.group_gemm import group_gemm_xla
from triton_dist_tpu.kernels.moe_utils import (
    combine_topk,
    gather_sorted,
    padded_rows,
    sort_align,
)
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

from triton_dist_tpu.kernels.collective_ids import AG_GROUP_GEMM as AG_GROUP_GEMM_COLLECTIVE_ID


@dataclass
class AGGroupGEMMContext:
    """Reference analog: the context of ``create_ag_group_gemm_context``
    (allgather_group_gemm.py) — symm workspace/streams replaced by the
    kernel's own output buffer and DMA queues."""

    mesh: Mesh
    n_experts: int
    topk: int
    axis: str = "tp"
    # sort_align tile granularity == GEMM row-tile size.  None = derive
    # load-aware at the host entry (dense loads get the measured 512 MFU
    # winner, sparse loads stay padding-lean; group_gemm.load_aware_block_m).
    block_m: int | None = None
    impl: str = "auto"
    config: MatmulConfig = field(default_factory=MatmulConfig)
    interpret: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_ag_group_gemm_context(mesh, n_experts, topk, axis="tp",
                                 block_m=None, impl="auto", config=None,
                                 interpret=False) -> AGGroupGEMMContext:
    return AGGroupGEMMContext(
        mesh=mesh, n_experts=n_experts, topk=topk, axis=axis,
        block_m=block_m, impl=impl, config=config or MatmulConfig(),
        interpret=interpret,
    )


def _ag_group_gemm_kernel(
    te_ref,     # [world, n_tiles] SMEM: per-segment tile→expert maps
    x_ref,      # [m_pad, D]       ANY: local expert-sorted segment
    w_ref,      # [E, D, f_loc]    ANY: expert weight slabs (local F shard)
    ag_ref,     # [world*m_pad, D] ANY out: gathered sorted segments
    out_ref,    # [world*m_pad, f_loc] ANY out: grouped-GEMM outputs
    send_sem, recv_sem, copy_sem,
    acc_ref,    # VMEM (block_m, bn) f32
    *,
    axis, world, m_pad, block_m, bn, bk, out_dtype,
):
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)

    cp = pltpu.make_async_copy(x_ref, ag_ref.at[pl.ds(me * m_pad, m_pad)], copy_sem)
    cp.start()
    cp.wait()

    if world > 1:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(barrier, 2)

    D = x_ref.shape[1]
    f_loc = w_ref.shape[2]
    n_tiles, n_n, n_k = m_pad // block_m, f_loc // bn, D // bk

    for s in range(world):
        slot = jax.lax.rem(me - s + world, world)
        seg = ag_ref.at[pl.ds(slot * m_pad, m_pad)]
        if s > 0:
            pltpu.make_async_copy(seg, seg, recv_sem).wait()
        if s < world - 1:
            dl.remote_copy(seg, seg, send_sem, recv_sem, axis, right).start()

        # Grouped GEMM over this segment: row tile i uses expert slab
        # te[slot, i].  The SMEM read in the index map is the scalar-prefetch
        # steering (group_gemm.py) adapted to the in-kernel pipeline.
        inner = pltpu.emit_pipeline(
            functools.partial(group_gemm_pipeline_body, n_k=n_k,
                              out_dtype=out_dtype),
            grid=(n_tiles, n_n, n_k),
            in_specs=[
                pl.BlockSpec((block_m, bk), lambda i, j, k: (i, k)),
                pl.BlockSpec(
                    (1, bk, bn),
                    lambda i, j, k, slot=slot: (te_ref[slot, i], k, j)),
            ],
            out_specs=[pl.BlockSpec((block_m, bn), lambda i, j, k: (i, j))],
        )
        inner(seg, w_ref, out_ref.at[pl.ds(slot * m_pad, m_pad)],
              scratches=(acc_ref,))

        if s < world - 1:
            pltpu.make_async_copy(seg, seg, send_sem).wait()


def _segment_plans(experts_all, n_experts: int, block_m: int):
    """Identical-on-every-device per-segment sort plans.

    experts_all: [world, t_loc, topk].  Returns (dest [world, t_loc*topk],
    tile_expert [world, n_tiles], m_pad).
    """

    def plan(e):
        p = sort_align(e, n_experts, block_m)
        return p["dest"], p["tile_expert"]

    dest, te = jax.vmap(plan)(experts_all)
    _, t_loc, topk = experts_all.shape
    m_pad = padded_rows(t_loc * topk, n_experts, block_m)
    return dest, te, m_pad


def ag_group_gemm_shard(x_loc, weights_loc, experts_loc, w_stack, *,
                        axis, n_experts, topk, block_m, bn, bk, impl,
                        interpret):
    """Per-device AG-GroupGEMM; call inside shard_map.

    Returns out [T, f_loc]: token-major combined expert outputs for the FULL
    gathered token set (every device computes all tokens against its local
    slice of every expert — standard MoE TP, reference allgather_group_gemm).
    """
    raw_impl = impl
    impl = resolve_impl(impl, interpret)
    world = jax.lax.axis_size(axis)
    t_loc, d_model = x_loc.shape
    f_loc = w_stack.shape[2]
    me = jax.lax.axis_index(axis)

    # Small metadata gather: routing for every segment, identical everywhere.
    experts_all = jax.lax.all_gather(experts_loc, axis, axis=0)   # [w,t,topk]
    weights_all = jax.lax.all_gather(weights_loc, axis, axis=0)
    dest_all, te_all, m_pad = _segment_plans(experts_all, n_experts, block_m)

    # Pre-sort the local segment (reference host-side sort, :106-188).
    dest_me = jax.lax.dynamic_index_in_dim(dest_all, me, keepdims=False)
    xs_loc = gather_sorted(x_loc, dest_me, m_pad)

    if use_fallback(raw_impl, impl, pallas_shapes_ok(block_m, f_loc, d_model),
                    "ag_group_gemm",
                    f"(block_m={block_m}, f_loc={f_loc}, d={d_model}); needs m%8, n%128, k%128"):
        xs_all = jax.lax.all_gather(xs_loc, axis, axis=0, tiled=True)
        ys = group_gemm_xla(xs_all, w_stack, te_all.reshape(-1), block_m)
    else:
        bn_ = largest_divisor_block(f_loc, bn, 128)
        bk_ = largest_divisor_block(d_model, bk, 128)
        _, ys = pl.pallas_call(
            functools.partial(
                _ag_group_gemm_kernel, axis=axis, world=world, m_pad=m_pad,
                block_m=block_m, bn=bn_, bk=bk_, out_dtype=x_loc.dtype,
            ),
            out_shape=[
                jax.ShapeDtypeStruct((world * m_pad, d_model), x_loc.dtype),
                jax.ShapeDtypeStruct((world * m_pad, f_loc), x_loc.dtype),
            ],
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)],
            scratch_shapes=[
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.VMEM((block_m, bn_), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=AG_GROUP_GEMM_COLLECTIVE_ID if world > 1 else None,
            ),
            interpret=maybe_interpret(interpret),
        )(te_all, xs_loc, w_stack)

    # Per-segment topk combine back to token order (reference: the topk
    # scatter/reduce epilogue).  Segment s's tokens land at rows
    # [s*t_loc, (s+1)*t_loc).
    ys_seg = ys.reshape(world, m_pad, f_loc)
    out = jax.vmap(combine_topk)(ys_seg, dest_all, weights_all)
    return out.reshape(world * t_loc, f_loc)


def ag_group_gemm(x, weights, experts, w_stack, ctx: AGGroupGEMMContext):
    """out[T, F] = MoE-FFN(allgather(x)) with AG overlapped into the grouped
    GEMM.  Host entry (reference ``ag_group_gemm``)."""
    from triton_dist_tpu.kernels.group_gemm import load_aware_block_m

    cfg = ctx.config
    T = x.shape[0]
    block_m = ctx.block_m or load_aware_block_m(T * ctx.topk, ctx.n_experts)
    fn = cached_shard_jit(
        ag_group_gemm_shard,
        ctx.mesh,
        (P(ctx.axis, None), P(ctx.axis, None), P(ctx.axis, None),
         P(None, None, ctx.axis)),
        P(None, ctx.axis),
        axis=ctx.axis, n_experts=ctx.n_experts, topk=ctx.topk,
        block_m=block_m, bn=cfg.block_n, bk=cfg.block_k,
        impl=ctx.impl, interpret=ctx.interpret,
    )
    # Launch metadata: every device multiplies all T*topk (padded) rows
    # against its F shard of every expert.
    from triton_dist_tpu.runtime.profiling import annotate

    d_model = x.shape[1]
    f_loc = w_stack.shape[2] // max(ctx.world, 1)
    el = jnp.dtype(x.dtype).itemsize
    with annotate("ag_group_gemm",
                  flops=2 * T * ctx.topk * d_model * f_loc,
                  bytes_accessed=(T * d_model + T * ctx.topk * f_loc) * el
                  + w_stack.size // max(ctx.world, 1) * el):
        return fn(x, weights, experts, w_stack)


# ---------------------------------------------------------------------------
# Autotuned entry (VERDICT r3 #4: the grouped overlapped kernels sweep too,
# as round 3 did for the dense ag_gemm/gemm_rs pair).
# ---------------------------------------------------------------------------

from triton_dist_tpu.autotuner import Config as _Cfg, autotune as _autotune

# Row-tile height is the dominant knob (128 → 42-54% MFU, 512 → ~87%;
# docs/perf.md "Grouped GEMM MFU"); (bn, bk) pairs are the measured bf16
# and int8 winners plus the old defaults for contrast.
AG_GROUP_GEMM_TUNE_SPACE = [
    _Cfg(block_m=128, bn=512, bk=512),
    _Cfg(block_m=256, bn=512, bk=1024),
    _Cfg(block_m=512, bn=512, bk=1024),   # bf16 sweep winner
    _Cfg(block_m=512, bn=1024, bk=1024),  # int8 sweep winner
]


@_autotune(configs=AG_GROUP_GEMM_TUNE_SPACE, key=())
def _ag_group_gemm_tunable(x, weights, experts, w_stack, *, ctx,
                           block_m=None, bn=None, bk=None):
    tuned = AGGroupGEMMContext(
        mesh=ctx.mesh, n_experts=ctx.n_experts, topk=ctx.topk,
        axis=ctx.axis, block_m=block_m, impl=ctx.impl,
        config=MatmulConfig(ctx.config.block_m, bn, bk),
        interpret=ctx.interpret)
    return ag_group_gemm(x, weights, experts, w_stack, tuned)


def ag_group_gemm_autotuned(x, weights, experts, w_stack,
                            ctx: AGGroupGEMMContext):
    """:func:`ag_group_gemm` with (block_m, bn, bk) selected by the
    autotuner.  Each config re-traces the WHOLE overlapped op — the sort
    plans change with block_m, so the measurement covers the real cost of
    a tile height, padding included.  Same lockstep/is_dist rules as
    ``ag_gemm_autotuned``; on the tunnel chip use
    scripts/autotune_onchip.py's chain measure instead."""
    return _ag_group_gemm_tunable(x, weights, experts, w_stack, ctx=ctx)
