"""Analytic roofline models for comm and GEMM time on TPU.

Reference analog: ``kernels/nvidia/comm_perf_model.py`` (NIC discovery +
``estimate_reduce_scatter_time`` :91-110) and ``gemm_perf_model.py``
(tensor-core TFLOPS tables :158-204, ``estimate_gemm_sol_time_ms`` :233-237).
The reference uses these to budget SMs between GEMM and communication; on
TPU there is no SM budget — instead the models budget the *chunking factor*
of overlapped kernels (how many ring steps / DMA chunks per tile loop) and
provide speed-of-light baselines for the benchmarks.

TPU mapping:
- tensor-core TFLOPS table      -> per-generation MXU TFLOPS (topology.py)
- DRAM GB/s table               -> per-generation HBM GB/s
- NVLink / PCIe bandwidth       -> ICI per-link bandwidth x links on an axis
- NIC bandwidth (sysfs/ethtool) -> DCN bandwidth, same sysfs discovery
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from triton_dist_tpu.runtime import topology


# ---------------------------------------------------------------------------
# Peak-rate lookups
# ---------------------------------------------------------------------------

# Relative MXU throughput per dtype vs bf16 (TPU MXUs run int8/fp8 at 2x
# bf16 on generations that support it; fp32 runs ~1/4 via passes).
_DTYPE_SPEEDUP = {
    jnp.bfloat16.dtype: 1.0,
    jnp.float16.dtype: 1.0,
    jnp.float32.dtype: 0.25,
    jnp.int8.dtype: 2.0,
    jnp.float8_e4m3fn.dtype: 2.0,
    jnp.float8_e5m2.dtype: 2.0,
}


def get_mxu_tflops(dtype=jnp.bfloat16) -> float:
    """Peak dense matmul TFLOPS for the local chip at ``dtype``.

    Analog of ``get_tensorcore_tflops`` (gemm_perf_model.py:200-204).
    """
    base = topology.peak_bf16_tflops()
    return base * _DTYPE_SPEEDUP.get(jnp.dtype(dtype), 1.0)


def get_hbm_gbps() -> float:
    """Analog of ``get_dram_gbps`` (gemm_perf_model.py:226-230)."""
    return topology.hbm_bandwidth_gbps()


@functools.lru_cache()
def _nic_speed_gbps(interface: str) -> float:
    path = f"/sys/class/net/{interface}/speed"
    try:
        with open(path) as f:
            return int(f.read().strip()) / 1000.0  # Mbps -> Gbps
    except (OSError, ValueError):
        return -1.0


@functools.lru_cache()
def get_dcn_bandwidth_gbps_per_host() -> float:
    """DCN (data-center network) bandwidth per host, GB/s.

    Same sysfs discovery as the reference's ``get_nic_bandwidth_per_gpu``
    (comm_perf_model.py:83-91): enumerate non-virtual NICs, take all NICs at
    the max line rate, sum them.  Falls back to 100 GbE when sysfs gives
    nothing (common in sandboxes).
    """
    virtual_prefixes = ("lo", "docker", "veth", "br-", "tun", "lxc", "qemu")
    try:
        nics = [n for n in os.listdir("/sys/class/net/")
                if not n.startswith(virtual_prefixes)]
    except OSError:
        nics = []
    speeds = [s for s in (_nic_speed_gbps(n) for n in nics) if s > 0]
    if not speeds:
        return 100.0 / 8.0  # assume 100 GbE
    mx = max(speeds)
    return sum(s for s in speeds if s == mx) / 8.0  # Gbps -> GB/s


def get_ici_axis_bandwidth_gbps(mesh=None, axis: str | None = None) -> float:
    """Per-chip bandwidth available to a ring over one mesh axis, GB/s.

    A TPU torus axis gives a ring two links (both directions usable by a
    bidirectional ring); DCN-crossing axes get the per-host NIC share.
    """
    topo = topology.detect_topology()
    if mesh is not None and axis is not None and topology.axis_is_dcn(mesh, axis):
        n_local = max(1, topo.n_devices // max(1, topo.n_processes))
        return get_dcn_bandwidth_gbps_per_host() / n_local
    return topo.ici_gbps_per_link * 2.0


# ---------------------------------------------------------------------------
# Comm time estimates (ms)
# ---------------------------------------------------------------------------

def estimate_allgather_time_ms(nbytes_per_shard: int, world_size: int,
                               bw_gbps: float | None = None) -> float:
    """Ring allgather: each chip receives (world-1) shards over the axis."""
    if world_size <= 1:
        return 0.0
    bw = bw_gbps if bw_gbps is not None else get_ici_axis_bandwidth_gbps()
    return nbytes_per_shard * (world_size - 1) / 1e9 / bw * 1e3


def estimate_reduce_scatter_time_ms(nbytes_full: int, world_size: int,
                                    local_world_size: int | None = None,
                                    intra_bw_gbps: float | None = None,
                                    inter_bw_gbps: float | None = None) -> float:
    """Hierarchical RS estimate, analog of comm_perf_model.py:91-110.

    Two-tier: intra-slice ring over ICI, cross-slice exchange over DCN.
    On a TPU torus the two tiers overlap (like the reference's full-mesh
    NVLink case), so the slower tier dominates the per-node term.
    """
    if world_size <= 1:
        return 0.0
    local = local_world_size or world_size
    intra = intra_bw_gbps if intra_bw_gbps is not None else get_ici_axis_bandwidth_gbps()
    if world_size == local:
        return nbytes_full / 1e9 / local * (local - 1) / intra * 1e3
    assert world_size % local == 0
    nnodes = world_size // local
    inter = inter_bw_gbps if inter_bw_gbps is not None else (
        get_dcn_bandwidth_gbps_per_host())
    intra_ms = nbytes_full / world_size * (local - 1) / 1e9 / intra * 1e3
    inter_ms = nbytes_full / world_size / 1e9 / inter * 1e3
    # ICI and DCN are independent fabrics: the tiers pipeline, so each
    # round costs the slower (bottleneck) tier.
    return max(intra_ms, inter_ms) * (nnodes - 1) + intra_ms


def estimate_torus_allgather_time_ms(nbytes_per_shard: int,
                                     axis_sizes: tuple[int, ...],
                                     bw_gbps: float | None = None) -> float:
    """Fused multi-axis torus AG (``kernels/torus.py``).

    The four-path 2D schedule keeps all four link directions of the plane
    busy in both phases, so the plane's time is the per-link bytes of the
    BUSIEST path divided by one link's bandwidth — ~2x faster than a
    sequential per-axis composition and ~2x faster than one bidirectional
    ring carrying the same total bytes on 2 of the 4 directions.

    Derivation (per path, wx x wy plane, quarter bytes q = S/4 where S =
    ``nbytes_per_shard``): phase 1 moves (w1-1) slot-quarters, phase 2
    moves (w2-1) first-axis lines of w1 slot-quarters each → per-link
    bytes = q*(w1-1) + q*w1*(w2-1) = q*(w1*w2 - 1).  Every path carries
    the same total, so time = q*(W-1)/bw — W = wx*wy.  A 3-axis torus
    runs the fused SIX-path schedule (round 3): sixths s = S/6, per-link
    bytes s*(W-1) per path (the same telescoping sum over three phases),
    all 6 link directions busy — 3x the bidirectional ring, ~2.3x the
    old plane+sequential-third composition.
    """
    sizes = [s for s in axis_sizes if s > 1]
    world = 1
    for s in sizes:
        world *= s
    if world <= 1:
        return 0.0
    bw = bw_gbps if bw_gbps is not None else get_ici_axis_bandwidth_gbps()
    # bw is the axis bandwidth (both directions); a single direction is
    # bw/2, and the quarter/half splits are per-direction streams.
    link = bw / 2.0
    if len(sizes) == 1:
        # bidirectional ring: halves on each direction.
        return (nbytes_per_shard / 2) * (sizes[0] - 1) / 1e9 / link * 1e3
    if len(sizes) == 2:
        plane = sizes[0] * sizes[1]
        return (nbytes_per_shard / 4) * (plane - 1) / 1e9 / link * 1e3
    # Fused six-path 3D: each sixth telescopes to (W-1) sixth-bytes per
    # link across its three phases, identical for every cyclic order.
    return (nbytes_per_shard / 6) * (world - 1) / 1e9 / link * 1e3


def estimate_torus_reduce_scatter_time_ms(nbytes_full: int,
                                          axis_sizes: tuple[int, ...],
                                          bw_gbps: float | None = None
                                          ) -> float:
    """Fused 2D torus RS (``kernels/torus.py``): FOUR concurrent quarter
    paths (x→y and y→x orders, each bidirectional — all four link
    directions reduce at once).  Per path (quarter bytes q = F/4 with
    F = ``nbytes_full``): phase 1 rings (w1-1) line groups of q/w1 bytes,
    phase 2 (w2-1) slots of q/(w1*w2) → per-link time q*(w1-1)/w1 +
    q*(w2-1)/(w1*w2); wall time = max over the two orders (equal on
    square tori).  ~2x the bidirectional 1-axis ring (the AUTO default),
    ~4x the unidirectional ring.
    """
    sizes = [s for s in axis_sizes if s > 1]
    world = 1
    for s in sizes:
        world *= s
    if world <= 1:
        return 0.0
    bw = bw_gbps if bw_gbps is not None else get_ici_axis_bandwidth_gbps()
    link = bw / 2.0
    if len(sizes) == 1:
        # AUTO now selects the bidirectional ring (RING_BIDIR): halves on
        # each link direction.
        return (nbytes_full / 2 * (sizes[0] - 1) / sizes[0]) / 1e9 / link \
            * 1e3
    part = nbytes_full / (2 * len(sizes))

    def path_ms(order):
        t, denom = 0.0, 1
        for w in order:
            denom *= w
            t += part * (w - 1) / denom / 1e9 / link * 1e3
        return t

    if len(sizes) == 3:
        # Fused six-path 3D (round 3): cyclic reduction orders; wall time
        # = the slowest order (they differ on asymmetric tori).
        w1, w2, w3 = sizes
        return max(path_ms((w1, w2, w3)), path_ms((w2, w3, w1)),
                   path_ms((w3, w1, w2)))
    w1, w2 = sizes
    return max(path_ms((w1, w2)), path_ms((w2, w1)))


def estimate_all_to_all_time_ms(nbytes_per_chip: int, world_size: int,
                                bw_gbps: float | None = None) -> float:
    """All-to-all: each chip sends (world-1)/world of its payload."""
    if world_size <= 1:
        return 0.0
    bw = bw_gbps if bw_gbps is not None else get_ici_axis_bandwidth_gbps()
    return nbytes_per_chip * (world_size - 1) / world_size / 1e9 / bw * 1e3


def estimate_ep_a2a_time_ms(tokens_per_chip: int, topk: int, hidden: int,
                            world_size: int, itemsize: int = 1,
                            bw_gbps: float | None = None,
                            block: int = 128) -> float:
    """EP dispatch wire time under the splits-PROPORTIONAL kernel.

    Bytes follow the ACTUAL (token, k) assignment count — ``tokens_per_chip
    * topk`` rows, of which ``(world-1)/world`` leave the chip at balanced
    routing — plus the per-segment ceil-to-``block`` rounding, NOT the
    ``max_tokens``-padded worst case (which at the lossless default
    ``max_tokens = t_loc*topk`` would be ~world_size x larger).  Matches
    ``_a2a_kernel``'s dynamic-count block-DMA scheme (all_to_all.py).
    """
    if world_size <= 1:
        return 0.0
    rows_per_seg = tokens_per_chip * topk / world_size  # balanced routing
    shipped_per_seg = -(-rows_per_seg // block) * block  # ceil to block
    rows_offchip = shipped_per_seg * (world_size - 1)
    nbytes = rows_offchip * hidden * itemsize
    bw = bw_gbps if bw_gbps is not None else get_ici_axis_bandwidth_gbps()
    return nbytes / 1e9 / bw * 1e3


# ---------------------------------------------------------------------------
# GEMM time estimate (ms)
# ---------------------------------------------------------------------------

def estimate_gemm_sol_time_ms(M: int, N: int, K: int, dtype=jnp.bfloat16) -> float:
    """Speed-of-light GEMM time: max of MXU-bound and HBM-bound terms.

    Analog of gemm_perf_model.py:233-237, plus a memory-roofline term the
    reference omits (matters for the skinny-N TP shards we run).
    """
    flops = 2.0 * M * N * K
    compute_ms = flops / (get_mxu_tflops(dtype) * 1e12) * 1e3
    itemsize = jnp.dtype(dtype).itemsize
    nbytes = (M * K + K * N) * itemsize + M * N * itemsize
    memory_ms = nbytes / (get_hbm_gbps() * 1e9) * 1e3
    return max(compute_ms, memory_ms)


# ---------------------------------------------------------------------------
# Overlap budgeting
# ---------------------------------------------------------------------------

def overlap_chunk_budget(M: int, N: int, K: int, world_size: int,
                         dtype=jnp.bfloat16, mesh=None, axis: str | None = None,
                         max_chunks: int = 8) -> int:
    """How many ring/DMA chunks an overlapped AG-GEMM should use.

    The reference budgets SMs between GEMM and comm using the two models
    (SURVEY §2.5 comm_perf_model row); on TPU the analogous knob is the
    chunk count: enough chunks that per-chunk comm hides under per-chunk
    compute, but no more (each chunk re-primes the MXU pipeline).
    """
    if world_size <= 1:
        return 1
    gemm_ms = estimate_gemm_sol_time_ms(M // world_size, N, K, dtype)
    ag_ms = estimate_allgather_time_ms(
        M // world_size * K * jnp.dtype(dtype).itemsize, world_size,
        get_ici_axis_bandwidth_gbps(mesh, axis) if mesh is not None else None)
    if ag_ms <= 0:
        return 1
    # comm-bound: one chunk per ring step; compute-bound: fewer chunks OK.
    ratio = ag_ms / max(gemm_ms, 1e-6)
    chunks = world_size if ratio >= 1.0 else max(2, round(world_size * ratio))
    return int(min(max_chunks, max(1, chunks)))


# ---------------------------------------------------------------------------
# Causal ring-attention schedules (zigzag balance, r5)
# ---------------------------------------------------------------------------

def ring_causal_step_work(world: int, zigzag: bool) -> list:
    """Per-ring-step MXU work of the SLOWEST device (step time is the max
    across devices — the ring is bulk-synchronous), in units of one full
    S_loc x S_loc block pair.  Causal masking only; brute-force count of
    (q-chunk, kv-chunk) visibility.

    Contiguous layout: shard i = chunk i; at step s > 0 every device with
    me >= s holds a strictly-past block -> full work 1.0, so EVERY step
    costs a full block while the mean useful work is (w+1)/2w.

    Zigzag layout: shard i = chunks (i, 2w-1-i) of half size; late
    chunks are invisible to every early q chunk (2w-1-j >= w > i), and
    exactly two of the remaining pair classes are live at every
    (device, step) -> constant 0.5 per step, 100% chunk-granular balance.
    """
    chunks = ([(i, 2 * world - 1 - i) for i in range(world)] if zigzag
              else [(i,) for i in range(world)])
    per = len(chunks[0])
    unit = 1.0 / per ** 2
    out = []
    for s in range(world):
        worst = 0.0
        for i in range(world):
            j = (i - s) % world
            w = 0.0
            for qc in chunks[i]:
                for kc in chunks[j]:
                    if qc > kc:
                        w += unit
                    elif qc == kc:
                        w += unit / 2
            worst = max(worst, w)
        out.append(worst)
    return out


def ring_causal_speedup(world: int) -> float:
    """Predicted causal ring step-time speedup of zigzag over contiguous
    (compute-bound regime): sum of per-step maxima.  Closed form
    (w - 1/2) / (w/2) = 2 - 1/w -> 2x asymptotically."""
    naive = sum(ring_causal_step_work(world, False))
    zig = sum(ring_causal_step_work(world, True))
    return naive / zig
