"""Distributed GQA flash-decode — sequence-parallel attention over sharded KV.

Reference analog: ``python/triton_dist/kernels/nvidia/flash_decode.py`` — the
reference's long-context scaling story (SURVEY.md §5): each rank runs split-KV
flash-decode on its KV shard (:129-280), combines its own splits (:392-480),
then the ranks' partial (out, lse) pairs are allgathered and merged by an
LSE-weighted online-softmax combine (`kernel_inter_rank_gqa_fwd_batch_decode_
combine_kv`, :481-532).

TPU-native design (NOT a port):

* **Split-KV + intra-rank combine collapse into one kernel.**  The GPU
  version launches parallel KV splits and then a combine kernel because CUDA
  blocks run concurrently.  TPU Pallas grids are *sequential* per core, so
  the split dimension becomes the KV-chunk grid axis with an online-softmax
  accumulator carried in VMEM scratch across iterations — the Mosaic pipeline
  overlaps the next chunk's HBM→VMEM DMA with the current chunk's compute,
  which is exactly the latency-hiding the GPU gets from parallel splits
  (decode is HBM-bandwidth-bound; the MXU is never the bottleneck).
* **Inter-rank combine is comm-fused** (``sp_combine_shard``): each rank
  remote-DMAs its packed (out ⊕ lse) partial plane into every peer's VMEM
  (the ``dl.fcollect`` verb) and the LSE merge runs on the VPU in the SAME
  Pallas kernel — the reference's LL-gather + combine kernel pair in one
  launch.  Explicit ``impl="xla"`` (or a head_dim not lane-divisible)
  keeps the latency gather + fused XLA epilogue instead; note int8-KV
  under ``auto`` runs an XLA *local* decode but still the fused combine
  (the partials are f32 either way).
* The (out ⊕ lse) payload packing of the reference's decode layer
  (sp_flash_decode_layer.py:135-137) is kept in both paths: one plane/
  gather moves both.
* Per-batch KV lengths ride as **scalar-prefetch** arguments (SMEM), the
  Pallas analog of the reference's ``gqa_fwd_batch_decode`` kv_lens tensor.

Layout contract (shard level, inside shard_map over ``axis``):
  q:        [B, Hq, D]        replicated (decode queries are tiny)
  k/v:      [B, Hkv, S_loc, D] sequence-sharded KV cache (head-major so a
                               KV chunk is one contiguous DMA)
  kv_lens:  [B] int32          *global* sequence lengths
  out:      [B, Hq, D]
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels.gemm import (
    PallasShapeError,
    apply_soft_cap,
    resolve_impl,
    use_fallback,
)
from triton_dist_tpu.kernels.low_latency_allgather import (
    fast_allgather_shard,
    pack_payload,
    unpack_payload,
)
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

NEG_INF = -1.0e30  # finite -inf proxy: survives exp/log without NaNs

from triton_dist_tpu.kernels.collective_ids import SP_DECODE as SP_DECODE_COLLECTIVE_ID


# ---------------------------------------------------------------------------
# Local shard kernel: online-softmax split-KV decode
# ---------------------------------------------------------------------------


def _read_lens(lens_ref, b, *, window, use_qlens):
    """Decode the lens prefetch operand (layout depends on the STATIC
    window/use_qlens flags):

    * plain decode — [B]: clipped local lens only;
    * windowed (r5 SP window) — [2, B]: + the UNCLIPPED local end
      position (kv_len - shard offset), whose last ``window`` rows are
      visible: the global window rule in shard coordinates;
    * q_lens mode (r5 multi-token verify, incl. T == 1 with dead batch
      slots) — [3, B]: + the per-batch live query count (q rows
      t >= qlen are dead padding).

    Returns (llen, wlen, qlen); qlen is None unless use_qlens.
    """
    if use_qlens:
        return lens_ref[0, b], lens_ref[1, b], lens_ref[2, b]
    if window:
        return lens_ref[0, b], lens_ref[1, b], None
    llen = lens_ref[b]
    return llen, llen, None


def _chunk_valid(pos, llen, wlen, qlen, *, window, group):
    """Visibility of cache position ``pos`` [R, bs] to decode-query row
    r = t * group + g (token t's query sits at global end - (qlen-1-t)):
    THE masking rule shared by the bf16/int8 kernels and the XLA
    fallback.  Without q_lens (qlen None) this degenerates to the
    classic decode rule; dead rows (t >= qlen) mask everything and
    surface lse = NEG_INF."""
    valid = pos < llen
    if qlen is not None:
        t = jax.lax.broadcasted_iota(jnp.int32, pos.shape, 0) // group
        d = qlen - 1 - t                       # distance from the last q
        valid = valid & (d >= 0) & (pos < wlen - d)
        if window:
            valid = valid & (pos >= wlen - d - window)
    elif window:
        valid = valid & (pos >= wlen - window)
    return valid


def _pack_lens_arg(local_lens, window_lens, q_lens, *, n_tok, window):
    """Build the lens prefetch operand — THE one place the [B]/[2,B]/
    [3,B] layout is encoded (``_read_lens`` is its reader); shared by the
    contiguous and paged wrappers so they can never desynchronize.
    Returns (lens_arg, use_qlens)."""
    wl = local_lens if window_lens is None else window_lens
    use_qlens = n_tok > 1 or q_lens is not None
    if use_qlens:
        ql = (jnp.full(local_lens.shape, n_tok, jnp.int32)
              if q_lens is None else q_lens.astype(jnp.int32))
        return jnp.stack([local_lens.astype(jnp.int32),
                          wl.astype(jnp.int32), ql]), True     # [3, B]
    if window:
        return jnp.stack([local_lens.astype(jnp.int32),
                          wl.astype(jnp.int32)]), False        # [2, B]
    return local_lens, False


def _fold_q_rows(q, n_tok, Hkv):
    """[B, (T,) Hq, D] → [B, Hkv, T*g, D], row r = t*g + head-group g —
    the kernel's q-block layout (its inverse is :func:`_unfold_out`)."""
    B, Hq, D = q.shape[0], q.shape[-2], q.shape[-1]
    g = Hq // Hkv
    if q.ndim == 4:
        return (q.reshape(B, n_tok, Hkv, g, D).transpose(0, 2, 1, 3, 4)
                .reshape(B, Hkv, n_tok * g, D))
    return q.reshape(B, Hkv, g, D)


def _unfold_out(out, lse, multi, n_tok, Hq):
    """Kernel outputs [B, Hkv, T*g, D] / [B, Hkv, T*g, 128] → the public
    (out, lse) shapes ([B, T, Hq, D]/[B, T, Hq] when multi)."""
    B, Hkv = out.shape[0], out.shape[1]
    D = out.shape[-1]
    g = Hq // Hkv
    if multi:
        o = (out.reshape(B, Hkv, n_tok, g, D).transpose(0, 2, 1, 3, 4)
             .reshape(B, n_tok, Hq, D))
        s = (lse[..., 0].reshape(B, Hkv, n_tok, g)
             .transpose(0, 2, 1, 3).reshape(B, n_tok, Hq))
        return o, s
    return out.reshape(B, Hq, D), lse[..., 0].reshape(B, Hq)


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, out_ref, lse_ref,
                   acc_ref, m_ref, l_ref, *, block_s, n_s, scale,
                   soft_cap=0.0, window=0, n_tok=1, use_qlens=False):
    """Grid (B, Hkv, n_s); one (batch, kv-head) pair accumulates across the
    sequential KV-chunk axis.

    Reference analog: ``kernel_gqa_fwd_batch_decode_split_kv``
    (flash_decode.py:129-280) — the Triton version parallelizes over splits
    and re-merges; here the s axis is sequential so the merge is the loop.

    ``n_tok`` > 1 (r5): the q block carries T tokens' queries as
    R = T * G rows (reference analog: the ``q_lens`` batch-verify entry,
    flash_decode.py:763,847) — mixed speculative-verify/decode batches
    ride ONE kernel with the causal rule ``pos < wlen - (qlen-1-t)``.
    """
    b = pl.program_id(0)
    s = pl.program_id(2)

    @pl.when(s == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    llen, wlen, qlen = _read_lens(lens_ref, b, window=window,
                                  use_qlens=use_qlens)

    # Chunks entirely past the valid length — or, with a sliding window,
    # entirely before it — are compute-skipped (their DMAs still stream
    # in; the pipeline cannot be shortened data-dependently).  The window
    # tail bound is conservative for multi-token (earliest query's
    # window reaches back n_tok-1 more rows).
    live = s * block_s < llen
    if window:
        live = live & ((s + 1) * block_s > wlen - (n_tok - 1) - window)

    @pl.when(live)
    def _():
        # K/V stay in their storage dtype: the MXU multiplies bf16 natively
        # with f32 accumulation, and skipping the per-chunk [bs, D] VPU
        # casts is worth ~10% at S=8192 (the cast traffic used to rival
        # the exp math).  P is cast DOWN to the V dtype for the PV matmul
        # — the standard flash-attention practice, and what keeps both
        # matmuls on the MXU's double-rate path.
        q = q_ref[0, 0]                              # [R, D], R = n_tok*G
        k = k_ref[0, 0]                              # [bs, D]
        v = v_ref[0, 0]                              # [bs, D]

        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale        # [R, bs]
        logits = apply_soft_cap(logits, soft_cap)
        pos = s * block_s + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        valid = _chunk_valid(pos, llen, wlen, qlen, window=window,
                             group=q.shape[0] // n_tok)
        logits = jnp.where(valid, logits, NEG_INF)

        m_cur = m_ref[:]                                        # [R, 128]
        row_max = jnp.max(logits, axis=-1, keepdims=True)       # [R, 1]
        m_new = jnp.maximum(m_cur, row_max)                     # [R, 128]
        alpha = jnp.exp(m_cur[:, :1] - m_new[:, :1])            # [R, 1]
        p = jnp.where(valid, jnp.exp(logits - m_new[:, :1]), 0.0)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(s == n_s - 1)
    def _():
        l = l_ref[:]                                            # [R, 128]
        nonempty = l > 0.0  # rank's shard may be wholly past kv_len
        out_ref[0, 0] = jnp.where(nonempty[:, :1], acc_ref[:] / jnp.where(
            nonempty[:, :1], l[:, :1], 1.0), 0.0)
        # lse rides a full-lane [R, 128] buffer (every lane the same value):
        # Mosaic requires output block lane dims of 128 or the full array dim.
        lse_ref[0, 0] = jnp.where(
            nonempty, m_ref[:] + jnp.log(jnp.where(nonempty, l, 1.0)),
            NEG_INF)


def _decode_kernel_i8(lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                      out_ref, lse_ref, acc_ref, m_ref, l_ref,
                      *, block_s, n_s, scale, soft_cap=0.0, window=0,
                      n_tok=1, use_qlens=False):
    """int8-KV twin of :func:`_decode_kernel` (VERDICT r3 #5): the cache
    streams from HBM as int8 (half the bytes — decode is bandwidth-bound,
    so that is the whole win) with per-position f32 scales riding as two
    extra [B, Hkv, S] prefetch planes.  Dequant fuses into the chunk
    loop: K's scale applies AFTER the QK matmul (a column rescale of the
    logits), V's scale folds into P BEFORE the PV matmul — both matmuls
    stay on the MXU in bf16 (int8 values cast exactly), no f32 cast
    traffic.  Reference bar: its decode kernel IS the serving path
    (flash_decode.py:129-280).
    """
    b = pl.program_id(0)
    s = pl.program_id(2)

    @pl.when(s == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    llen, wlen, qlen = _read_lens(lens_ref, b, window=window,
                                  use_qlens=use_qlens)
    live = s * block_s < llen
    if window:
        live = live & ((s + 1) * block_s > wlen - (n_tok - 1) - window)

    @pl.when(live)
    def _():
        q = q_ref[0, 0]                          # [R, D] bf16/f32, R=n_tok*G
        k = k_ref[0, 0].astype(q.dtype)                  # [bs, D] i8→q dtype
        # Scales ride LANE-PACKED [B, Hkv, S//128, 128] (row r, lane l =
        # position r*128+l): each chunk's bs scales are ONE dense
        # [bs//128, 128] f32 transfer.  A [bs, 1] layout instead DMAs
        # thousands of 4-byte strided rows per chunk and ran 9x slower
        # than XLA on hardware (r4 measurement).
        ksc = ks_ref[0, 0].reshape(-1)                   # [bs] f32
        vsc = vs_ref[0, 0].reshape(-1)                   # [bs] f32

        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        logits = logits * (ksc[None, :] * scale)         # [G, bs]
        logits = apply_soft_cap(logits, soft_cap)
        pos = s * block_s + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        valid = _chunk_valid(pos, llen, wlen, qlen, window=window,
                             group=q.shape[0] // n_tok)
        logits = jnp.where(valid, logits, NEG_INF)

        m_cur = m_ref[:]
        row_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_cur, row_max)
        alpha = jnp.exp(m_cur[:, :1] - m_new[:, :1])
        p = jnp.where(valid, jnp.exp(logits - m_new[:, :1]), 0.0)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0].astype(q.dtype)                  # [bs, D]
        pv = (p * vsc[None, :]).astype(q.dtype)          # fold V's scale
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(s == n_s - 1)
    def _():
        l = l_ref[:]
        nonempty = l > 0.0
        out_ref[0, 0] = jnp.where(nonempty[:, :1], acc_ref[:] / jnp.where(
            nonempty[:, :1], l[:, :1], 1.0), 0.0)
        lse_ref[0, 0] = jnp.where(
            nonempty, m_ref[:] + jnp.log(jnp.where(nonempty, l, 1.0)),
            NEG_INF)


def _local_decode_xla(q, k, v, local_lens, *, scale, k_scale=None,
                      v_scale=None, soft_cap=0.0, window=0,
                      window_lens=None, q_lens=None):
    """Dense fallback for ragged shapes / non-TPU (reference analog: the
    non-TMA dispatch path).  Same (out, lse) contract as the Pallas kernel.

    ``k_scale``/``v_scale`` [B, Hkv, S] dequantize an int8 KV cache
    (kernels-level int8-KV support; see layers/sp_flash_decode.py).  The
    scale applies *after* the QK matmul / *before* the PV matmul, so XLA
    streams the cache from HBM as int8 — decode is bandwidth-bound, and
    halving the cache bytes is the point.

    ``q`` may be [B, Hq, D] (one decode token) or [B, T, Hq, D]
    (multi-token verify; ``q_lens`` [B] live query counts, default T).
    """
    multi = q.ndim == 4
    if not multi:
        q = q[:, None]                                 # T = 1
    B, T, Hq, D = q.shape
    _, Hkv, S, _ = k.shape
    g = Hq // Hkv
    qf = q.astype(jnp.float32).reshape(B, T, Hkv, g, D)
    logits = jnp.einsum("bthgd,bhsd->bhtgs", qf,
                        k.astype(jnp.float32)) * scale
    if k_scale is not None:
        logits = logits * k_scale[:, :, None, None, :]
    logits = apply_soft_cap(logits, soft_cap)
    wl = local_lens if window_lens is None else window_lens
    ql = (jnp.full((B,), T, jnp.int32) if q_lens is None
          else q_lens.astype(jnp.int32))
    pos = jnp.arange(S)[None, None, :]                          # [1, 1, S]
    d = ql[:, None] - 1 - jnp.arange(T)[None, :]                # [B, T]
    valid = ((pos < local_lens[:, None, None])
             & (d[..., None] >= 0)
             & (pos < (wl[:, None] - d)[..., None]))            # [B, T, S]
    if window:
        valid = valid & (pos >= (wl[:, None] - d)[..., None] - window)
    logits = jnp.where(valid[:, None, :, None, :], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                             # [B,Hkv,T,g]
    # All-masked rows: keep everything finite, flag via lse = NEG_INF.
    nonempty = m > NEG_INF / 2
    p = jnp.where(valid[:, None, :, None, :],
                  jnp.exp(logits - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    pv = p if v_scale is None else p * v_scale[:, :, None, None, :]
    out = jnp.einsum("bhtgs,bhsd->bthgd", pv, v.astype(jnp.float32))
    out = jnp.where(nonempty.transpose(0, 2, 1, 3)[..., None],
                    out / jnp.where(nonempty, l, 1.0)
                    .transpose(0, 2, 1, 3)[..., None], 0.0)
    lse = jnp.where(nonempty, m + jnp.log(jnp.where(nonempty, l, 1.0)),
                    NEG_INF).transpose(0, 2, 1, 3)              # [B,T,Hkv,g]
    out = out.reshape(B, T, Hq, D)
    lse = lse.reshape(B, T, Hq)
    if not multi:
        return out[:, 0], lse[:, 0]
    return out, lse


def _register_aot():
    """AOT export spaces for the decode kernels.

    Reference: ``scripts/aot_kernels.txt`` lists 5 flash-decode kernels as
    the AOT surface; signatures/algo-infos live in the
    ``@aot_compile_spaces`` tables (flash_decode.py:534-585).  Shapes below
    are the decode-serving points the reference tests use (GQA 32/4,
    head_dim 128).
    """
    from triton_dist_tpu.tools.compile_aot import aot_compile_spaces

    b, hq, hkv, d, s = 4, 32, 4, 128, 4096
    sig = [
        [((b, hq, d), "bfloat16"), ((b, hkv, s, d), "bfloat16"),
         ((b, hkv, s, d), "bfloat16"), ((b,), "int32")],
        [((b, hq, d), "float32"), ((b, hkv, s, d), "float32"),
         ((b, hkv, s, d), "float32"), ((b,), "int32")],
    ]
    # The pallas split-KV variants can only be exported for a platform
    # that can lower them (TPU; the CPU backend lowers pallas_call in
    # interpret mode only).  Resolved at
    # export time from the target platforms: registration runs at import,
    # which must never initialize the JAX backend (a ``jax.devices()``
    # probe here would break a later ``jax.distributed.initialize``).
    def algos(platforms):
        out = [{"impl": "xla"}]
        if "tpu" in platforms:
            out += [{"block_s": 2048, "impl": "pallas"},
                    {"block_s": 1024, "impl": "pallas"}]
        return out

    return aot_compile_spaces({
        "gqa_decode": {
            "signature": sig,
            "algo_infos": algos,
        },
    })


def quantize_kv(x):
    """[..., S, D] float → ([..., S, D] int8, [..., S] f32 scales):
    symmetric per-position row quant (the standard int8-KV layout; shares
    the one recipe in kernels/quant.py)."""
    from triton_dist_tpu.kernels.quant import symmetric_quantize

    return symmetric_quantize(x, -1)


@_register_aot()
def gqa_decode_shard(q, k, v, local_lens, *, block_s=None, impl="auto",
                     interpret=False, k_scale=None, v_scale=None,
                     soft_cap=0.0, window=0, window_lens=None,
                     q_lens=None):
    """Single-shard GQA decode: q [B, Hq, D], k/v [B, Hkv, S_loc, D],
    local_lens [B] (valid rows in this shard).  Returns float32 partials
    (out [B, Hq, D], lse [B, Hq]).

    MULTI-TOKEN (r5): q may be [B, T, Hq, D] — T query tokens per request
    whose K/V already sit in the cache at the last T valid positions
    (speculative verify / mixed decode-verify batches; reference analog:
    the per-request ``q_lens`` of its decode entry, flash_decode.py:763,
    847).  ``q_lens`` [B] (optional, <= T, default T) gives each
    request's LIVE query count: rows t >= q_lens[b] are padding and
    return lse = NEG_INF.  Query t of request b sits at global position
    ``end_b - (q_lens[b] - t)`` where end_b is the cache length.
    Returns (out [B, T, Hq, D], lse [B, T, Hq]).  The queries ride the
    kernel as T*G extra block rows — decode stays HBM-bound, so a
    k-token verify costs ~the same cache stream as one decode step
    (vs the chunked-prefill verify's 128-row padded q blocks).

    Reference analog: ``gqa_fwd_batch_decode_intra_rank``
    (flash_decode.py:763-860) minus the separate combine launch.

    ``window`` (sliding-window attention, Mistral-style): only the last
    ``window`` keys are visible to the decode query; chunks wholly
    outside the window are compute-skipped.  ``window_lens`` [B] gives
    the UNCLIPPED local end position (kv_len - shard offset) so an SP
    caller evaluates the GLOBAL window in shard coordinates (rows
    >= window_lens - window are visible; default: local_lens — the
    world-1 rule).  A shard wholly outside the window reports
    lse = NEG_INF partials, which the inter-rank combine ignores.

    ``impl`` note: decode is HBM-bandwidth-bound (stream the KV cache
    once).  Since round 2's kernel tuning (K/V fed to the MXU in their
    storage dtype, P cast down for the PV matmul, parallel (b, h)
    dimension semantics) the Pallas split-KV kernel matches-or-beats XLA's
    fused attention at the serving shapes (measured table: docs/perf.md,
    protocol: scripts/bench_decode.py), so ``auto`` selects the Pallas
    kernel whenever the shapes allow it — including, since round 4,
    int8-KV caches: the fused int8 split-KV kernel (dequant in the chunk
    loop, lane-packed scale planes) reads 168 µs vs XLA's ~200 at the
    serving shape.  ``impl='xla'`` keeps the XLA program (dequant fused
    into the attention stream).
    """
    multi = q.ndim == 4
    n_tok = q.shape[1] if multi else 1
    B, Hq, D = q.shape[0], q.shape[-2], q.shape[-1]
    _, Hkv, S, _ = k.shape
    assert Hq % Hkv == 0, (Hq, Hkv)
    g = Hq // Hkv
    scale = 1.0 / math.sqrt(D)
    raw_impl = impl
    impl = resolve_impl(impl, interpret)

    def shapes_ok():
        return D % 128 == 0 and S % 128 == 0

    quantized = k_scale is not None
    if use_fallback(raw_impl, impl, shapes_ok(), "flash_decode",
                    f"(D={D}, S={S}) needs D%128 == S%128 == 0") or (
            quantized and impl != "pallas"):
        # int8-KV under resolved-XLA dispatch: dequant fuses into the XLA
        # attention stream.  ``impl='pallas'`` (explicit OR auto-on-TPU)
        # runs the fused int8 split-KV kernel below (r4; it was an XLA
        # reroute before the kernel existed).
        return _local_decode_xla(q, k, v, local_lens, scale=scale,
                                 k_scale=k_scale, v_scale=v_scale,
                                 soft_cap=soft_cap, window=window,
                                 window_lens=window_lens, q_lens=q_lens)

    defaulted = block_s is None
    if defaulted:
        # Full-shard default, both dtypes (real-chip sweeps, docs/perf.md):
        # fewer online-softmax chunk boundaries and one long MXU stream
        # put the kernel at the HBM floor — int8 168 µs vs 208 at bs=2048;
        # bf16 B=8 ~285-319 µs vs ~354-361 at bs=2048 across two sessions
        # (B=32 is a wash — the r4 re-sweep that retired the old 2048
        # bf16 default).  VMEM fit-shrink below handles large D.
        block_s = min(S, 8192)
    bs = block_s
    while S % bs:
        bs //= 2
    bs = max(bs, 128)
    if quantized and (bs // 128) % 8 and bs != S:
        # Lane-packed scale planes (below) need the (1, 1, bs//128, 128)
        # block's sublane dim bs//128 to be %8 — or the block to span
        # all of S.  Bump to the smallest DIVISOR of S that satisfies
        # it (a non-divisor bs would truncate n_s = S//bs and silently
        # drop the cache tail — e.g. S=1152 with a flat min(S, 1024)
        # bump attended only the first 1024 positions), falling back to
        # bs = S when no such divisor exists (S/128 with no multiple-
        # of-8 factor).  Any legal divisor is >= 1024, the int8
        # kernel's measured sweet spot anyway (docs/perf.md).
        bs = next((c for c in range(bs, S, 128)
                   if S % c == 0 and (c // 128) % 8 == 0), S)
    # Double-buffered K+V blocks: 4 * bs * D * itemsize must fit VMEM.
    # Only a DEFAULTED block shrinks for PERF reasons; an explicit
    # block_s that does not fit keeps its loud failure (a sweep must
    # never report a block size the kernel didn't run for tuning
    # reasons).  The LEGALITY normalizations above (divisor halving,
    # int8 scale-plane snap-up) still apply to explicit values — they
    # are documented contracts, not silent tuning.
    vmem_budget = 12 * 2 ** 20
    itemsize = jnp.dtype(k.dtype).itemsize
    if defaulted and 4 * bs * D * itemsize > vmem_budget:
        # Over budget (large D and/or bs == S): try the LARGEST legal
        # smaller divisor that fits (e.g. int8 S=8192 D=512: 8192 -> 1024)
        # before concluding this shape cannot tile the kernel.  int8
        # additionally needs the lane-packed scale-plane constraint.
        def legal(c):
            return S % c == 0 and (not quantized or (c // 128) % 8 == 0)

        # int8's lane-packed scale planes need (c//128)%8 == 0, i.e. a
        # multiple of 1024; plain caches may shrink all the way to 128.
        floor = 1024 if quantized else 128
        fit = max((c for c in range(floor, bs, 128)
                   if legal(c) and 4 * c * D * itemsize <= vmem_budget),
                  default=None)
        if fit is None:
            if raw_impl == "pallas":
                need = ("a multiple-of-1024 divisor of S"
                        if quantized else "a multiple-of-128 divisor of S")
                raise PallasShapeError(
                    f"flash_decode{' int8-KV' if quantized else ''}: S={S},"
                    f" D={D} has no legal KV block that fits VMEM (needs "
                    f"{need} with 4*bs*D*itemsize <= 12 MiB)")
            return _local_decode_xla(q, k, v, local_lens, scale=scale,
                                     k_scale=k_scale, v_scale=v_scale,
                                     soft_cap=soft_cap, window=window,
                                     window_lens=window_lens,
                                     q_lens=q_lens)
        bs = fit
    n_s = S // bs

    lens_arg, use_qlens = _pack_lens_arg(local_lens, window_lens, q_lens,
                                         n_tok=n_tok, window=window)
    rows = n_tok * g
    qg = _fold_q_rows(q, n_tok, Hkv)
    grid = (B, Hkv, n_s)
    q_spec = pl.BlockSpec((1, 1, rows, D),
                          lambda b, h, s, lens: (b, h, 0, 0))
    kv_spec = pl.BlockSpec((1, 1, bs, D), lambda b, h, s, lens: (b, h, s, 0))
    if quantized:
        # Scale layout: position p lives at (row p//128, lane p%128) —
        # each chunk's bs scales are ONE dense [bs//128, 128] transfer.
        sc_spec = pl.BlockSpec((1, 1, bs // 128, 128),
                               lambda b, h, s, lens: (b, h, s, 0))
        kern = functools.partial(_decode_kernel_i8, block_s=bs, n_s=n_s,
                                 scale=scale, soft_cap=soft_cap,
                                 window=window, n_tok=n_tok,
                                 use_qlens=use_qlens)
        in_specs = [q_spec, kv_spec, kv_spec, sc_spec, sc_spec]
        args = (lens_arg, qg, k, v,
                k_scale.reshape(B, Hkv, S // 128, 128),
                v_scale.reshape(B, Hkv, S // 128, 128))
    else:
        kern = functools.partial(_decode_kernel, block_s=bs, n_s=n_s,
                                 scale=scale, soft_cap=soft_cap,
                                 window=window, n_tok=n_tok,
                                 use_qlens=use_qlens)
        in_specs = [q_spec, kv_spec, kv_spec]
        args = (lens_arg, qg, k, v)
    out, lse = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, rows, D),
                             lambda b, h, s, lens: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, rows, 128),
                             lambda b, h, s, lens: (b, h, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((rows, D), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, rows, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, rows, 128), jnp.float32),
        ],
        # (b, h) blocks are independent; only the KV-chunk axis carries the
        # online-softmax accumulator.  Telling Mosaic so lets it pipeline
        # across (b, h) boundaries (same knob as the 96%-MXU GEMM config).
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=maybe_interpret(interpret),
    )(*args)
    return _unfold_out(out, lse, multi, n_tok, Hq)


# ---------------------------------------------------------------------------
# Paged KV cache (block_table) decode
# ---------------------------------------------------------------------------
#
# Reference analog: the decode layer's ``block_table`` argument
# (sp_flash_decode_layer.py:78-103 — its kernel reads the KV cache through
# a page table).  TPU-native design: the page table rides as a SECOND
# scalar-prefetch operand and the KV pool's BlockSpec index_map reads the
# physical page id from it — the kernel body is _decode_kernel verbatim
# (the logical position base is still ``page * page_size``; only the HBM
# address of each page block changes).  Dead table entries (pages past a
# sequence's length) must hold any in-range pool index — their compute is
# skipped by the length mask, but their DMA still streams.


def _paged_gather(pool, table):
    """[N, Hkv, P, D] pool + [B, n] table → [B, Hkv, n*P, D] contiguous
    view (the XLA fallback materializes it; the pallas path never does)."""
    g = pool[table]                                   # [B, n, Hkv, P, D]
    B, n, Hkv, Pg, D = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, n * Pg, D)


def _paged_gather_scale(scale_pool, table):
    """[N, Hkv, P] per-position scale pool + [B, n] table →
    [B, Hkv, n*P] contiguous scale view (the twin of
    :func:`_paged_gather` for an int8 pool's scale plane)."""
    g = scale_pool[table]                             # [B, n, Hkv, P]
    B, n, Hkv, Pg = g.shape
    return g.transpose(0, 2, 1, 3).reshape(B, Hkv, n * Pg)


def gqa_decode_paged_shard(q, k_pool, v_pool, block_table, local_lens, *,
                           impl="auto", interpret=False, soft_cap=0.0,
                           window=0, window_lens=None, q_lens=None,
                           k_scale=None, v_scale=None):
    """Single-shard GQA decode over a PAGED KV cache.

    q [B, Hq, D]; k/v_pool [N_pages, Hkv, page, D] (the physical page
    pool); block_table [B, n_pages] int32 — logical page i of batch b
    lives at pool row ``block_table[b, i]``; local_lens [B] valid rows.
    Returns float32 partials (out [B, Hq, D], lse [B, Hq]).

    INT8 POOLS: ``k_scale``/``v_scale`` [N_pages, Hkv, page] float32
    per-position scale pools dequantize int8 k/v pools (the paged twin
    of :func:`gqa_decode_shard`'s contiguous int8 path — scales ride
    the same page indirection as their pages).  The quantized paged
    attend runs the fused-dequant XLA path: the dedicated Pallas
    paged-int8 kernel (lane-packed scale planes through the table
    index_map) is a recorded debt — on a 128-aligned-page TPU layout
    the float kernel's gate would apply unchanged.

    MULTI-TOKEN (r5, same contract as :func:`gqa_decode_shard`): q may
    be [B, T, Hq, D] with optional per-request ``q_lens`` [B] — the
    k-token verify over a PAGED cache (mixed decode/verify batches);
    returns (out [B, T, Hq, D], lse [B, T, Hq]).
    """
    multi = q.ndim == 4
    n_tok = q.shape[1] if multi else 1
    B, Hq, D = q.shape[0], q.shape[-2], q.shape[-1]
    N, Hkv, Pg, _ = k_pool.shape
    n_pages = block_table.shape[1]
    assert Hq % Hkv == 0, (Hq, Hkv)
    g = Hq // Hkv
    scale = 1.0 / math.sqrt(D)
    raw_impl = impl
    impl = resolve_impl(impl, interpret)

    if k_scale is not None or v_scale is not None:
        assert k_scale is not None and v_scale is not None, (
            "int8 paged pools carry BOTH scale planes")
        return _local_decode_xla(
            q, _paged_gather(k_pool, block_table),
            _paged_gather(v_pool, block_table), local_lens, scale=scale,
            k_scale=_paged_gather_scale(k_scale, block_table),
            v_scale=_paged_gather_scale(v_scale, block_table),
            soft_cap=soft_cap, window=window, window_lens=window_lens,
            q_lens=q_lens)

    # A page is the kernel's KV block — it cannot shrink (it IS the cache
    # layout), so an over-budget page must reroute/raise, not reach
    # Mosaic's opaque VMEM failure.
    fits = 4 * Pg * D * jnp.dtype(k_pool.dtype).itemsize <= 12 * 2 ** 20
    if use_fallback(raw_impl, impl,
                    D % 128 == 0 and Pg % 128 == 0 and fits,
                    "paged_decode",
                    f"(page={Pg}, D={D}) needs page%128 == D%128 == 0 and "
                    f"double-buffered K+V page blocks within 12 MiB VMEM"):
        return _local_decode_xla(q, _paged_gather(k_pool, block_table),
                                 _paged_gather(v_pool, block_table),
                                 local_lens, scale=scale,
                                 soft_cap=soft_cap, window=window,
                                 window_lens=window_lens, q_lens=q_lens)

    lens_arg, use_qlens = _pack_lens_arg(local_lens, window_lens, q_lens,
                                         n_tok=n_tok, window=window)
    rows = n_tok * g
    qg = _fold_q_rows(q, n_tok, Hkv)
    grid = (B, Hkv, n_pages)
    kern = functools.partial(_decode_kernel_paged, block_s=Pg,
                             n_s=n_pages, scale=scale, soft_cap=soft_cap,
                             window=window, n_tok=n_tok,
                             use_qlens=use_qlens)
    out, lse = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # (lens, block_table)
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, rows, D),
                             lambda b, h, s, lens, tab: (b, h, 0, 0)),
                # THE paging trick: the pool block's leading index comes
                # from the prefetched table — logical page s of batch b
                # streams from physical pool row tab[b, s].
                pl.BlockSpec((1, 1, Pg, D),
                             lambda b, h, s, lens, tab: (tab[b, s], h, 0, 0)),
                pl.BlockSpec((1, 1, Pg, D),
                             lambda b, h, s, lens, tab: (tab[b, s], h, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, rows, D),
                             lambda b, h, s, lens, tab: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, rows, 128),
                             lambda b, h, s, lens, tab: (b, h, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((rows, D), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
                pltpu.VMEM((rows, 128), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, rows, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, rows, 128), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=maybe_interpret(interpret),
    )(lens_arg, block_table, qg, k_pool, v_pool)
    return _unfold_out(out, lse, multi, n_tok, Hq)


def _decode_kernel_paged(lens_ref, table_ref, q_ref, k_ref, v_ref, out_ref,
                         lse_ref, acc_ref, m_ref, l_ref, *, block_s, n_s,
                         scale, soft_cap=0.0, window=0, n_tok=1,
                         use_qlens=False):
    """Thin shim: the paged kernel IS :func:`_decode_kernel` — paging
    lives entirely in the BlockSpec index maps; ``table_ref`` is consumed
    there, not in the body."""
    del table_ref
    return _decode_kernel(lens_ref, q_ref, k_ref, v_ref, out_ref, lse_ref,
                          acc_ref, m_ref, l_ref, block_s=block_s, n_s=n_s,
                          scale=scale, soft_cap=soft_cap, window=window,
                          n_tok=n_tok, use_qlens=use_qlens)


def sp_gqa_decode_paged_shard(q, k_pool, v_pool, block_table, kv_lens, *,
                              axis, impl="auto", interpret=False,
                              soft_cap=0.0, window=0, q_lens=None,
                              k_scale=None, v_scale=None):
    """Per-device SP decode over a paged cache: each rank's pool holds
    the pages of ITS sequence shard and ``block_table`` [B, n_local]
    holds local pool indices for the rank's logical pages.  ``kv_lens``
    are GLOBAL lengths; shard ownership follows n_local * page rows per
    rank (the contiguous-cache rule with S_loc = n_local * page).
    ``k_scale``/``v_scale`` [N, Hkv, page] dequantize int8 pools — each
    rank's scale plane shards with its pages, the combine is unchanged
    (partials are float either way).

    MULTI-TOKEN (ISSUE 19 debt (a)): q may be [B, T, Hq, D] with optional
    per-request ``q_lens`` [B] — the k-token verify over a sharded paged
    cache.  Per-token causality under SP uses the unclipped local ``ends``
    as ``window_lens`` (the same device kernel contract as the contiguous
    path); [B, T, ...] partials combine like a B*T batch — dead rows carry
    lse = NEG on every rank and merge to 0."""
    multi = q.ndim == 4
    B, Hq, D = q.shape[0], q.shape[-2], q.shape[-1]
    n_local = block_table.shape[1]
    s_loc = n_local * k_pool.shape[2]
    me = jax.lax.axis_index(axis)
    ends = (kv_lens - me * s_loc).astype(jnp.int32)
    local_lens = jnp.clip(ends, 0, s_loc)

    out, lse = gqa_decode_paged_shard(q, k_pool, v_pool, block_table,
                                      local_lens, impl=impl,
                                      interpret=interpret,
                                      soft_cap=soft_cap, window=window,
                                      window_lens=ends if (window or multi)
                                      else None,
                                      q_lens=q_lens,
                                      k_scale=k_scale, v_scale=v_scale)
    if multi:
        T = out.shape[1]
        c = _combine_across_ranks(out.reshape(B * T, Hq, D),
                                  lse.reshape(B * T, Hq), q.dtype,
                                  axis=axis, impl=impl, interpret=interpret)
        return c.reshape(B, T, Hq, D)
    return _combine_across_ranks(out, lse, q.dtype, axis=axis, impl=impl,
                                 interpret=interpret)


def _combine_across_ranks(out, lse, out_dtype, *, axis, impl, interpret):
    """The one inter-rank combine dispatch, shared by the contiguous and
    paged SP decodes: comm-fused pallas combine by default; packed
    LL-gather + XLA epilogue for xla mode / non-lane-divisible head_dim;
    world-1 passthrough."""
    world = jax.lax.axis_size(axis)
    B, Hq, D = out.shape
    if world == 1:
        return out.astype(out_dtype)
    if resolve_impl(impl, interpret) == "xla" or D % 128:
        packed = pack_payload(out, lse)
        gathered = fast_allgather_shard(
            packed, axis=axis, impl=impl, interpret=interpret,
            collective_id=SP_DECODE_COLLECTIVE_ID)
        gathered = gathered.reshape(world, B, Hq, D + 1)
        outs, lses = unpack_payload(gathered)
        return combine_partials(outs, lses).astype(out_dtype)
    return sp_combine_shard(out, lse, axis=axis,
                            interpret=interpret).astype(out_dtype)


# ---------------------------------------------------------------------------
# Inter-rank combine
# ---------------------------------------------------------------------------


def _sp_combine_kernel(plane_in, final_ref, gath, send_sem, recv_sem,
                       copy_sem, *, axis, world, d):
    """Comm-fused inter-rank combine: each rank pushes its packed
    (out ⊕ lse) partial plane to every peer's VMEM slot and LSE-merges the
    arrivals in-kernel — the remote DMA and the combine live in ONE Pallas
    kernel, no host-level gather + XLA epilogue remains.

    Reference analog: the dedicated LL-gather + inter-rank combine pair
    (``low_latency_allgather.py:700-779`` + ``flash_decode.py:481-532``),
    collapsed into a single kernel because a Mosaic kernel can both move
    and compute.  ``plane_in`` [BH, d+128] packs out rows with the
    lane-broadcast lse (one DMA per peer, one semaphore stream — the
    [BH, d] ⊕ [BH, 128] split costs one extra 128-lane block but halves
    the descriptor count vs two planes).
    """
    dl.barrier_all(axis)  # nobody lands data in a peer still outside

    # The gather round IS the fcollect verb: stage my slot (overlapped
    # with the peer fan-out, which reads the input ref), push to every
    # peer, drain, wait arrivals.
    dl.fcollect(plane_in, gath, send_sem, recv_sem, axis,
                copy_sem=copy_sem)

    # LSE-weighted merge on the VPU (combine_partials' math, in-kernel).
    bh = plane_in.shape[0]
    planes = gath[:].reshape(world, bh, d + 128)
    lses = planes[:, :, d:]                             # [W, BH, 128]
    m = jnp.max(lses, axis=0)                           # [BH, 128]
    w = jnp.exp(lses - m[None])                         # [W, BH, 128]
    denom = jnp.sum(w, axis=0)                          # [BH, 128]
    out = jnp.sum(planes[:, :, :d] * w[:, :, :1], axis=0)  # [BH, D]
    final_ref[:] = out / denom[:, :1]


def sp_combine_shard(out, lse, *, axis, interpret=False,
                     collective_id=SP_DECODE_COLLECTIVE_ID):
    """Fused gather+combine of per-rank decode partials; call inside
    shard_map.  out [B, Hq, D] f32, lse [B, Hq] f32 → [B, Hq, D] f32."""
    world = jax.lax.axis_size(axis)
    if world == 1:
        return out
    B, Hq, D = out.shape
    BH = B * Hq
    plane = jnp.concatenate(
        [out.reshape(BH, D),
         jnp.broadcast_to(lse.reshape(BH, 1), (BH, 128))], axis=1)
    final = pl.pallas_call(
        functools.partial(_sp_combine_kernel, axis=axis, world=world, d=D),
        out_shape=jax.ShapeDtypeStruct((BH, D), jnp.float32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            # flat [world*BH, D+128]: dl.fcollect's slot layout
            pltpu.VMEM((world * BH, D + 128), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=dl.collective_compiler_params(world, collective_id),
        interpret=maybe_interpret(interpret),
    )(plane)
    return final.reshape(B, Hq, D)


def combine_partials(outs, lses):
    """LSE-weighted merge of per-rank partials: outs [W, B, H, D] f32,
    lses [W, B, H] f32 -> [B, H, D] f32.

    Reference analog: ``kernel_inter_rank_gqa_fwd_batch_decode_combine_kv``
    (flash_decode.py:481-532) — the same online-softmax rescale, as a fused
    XLA elementwise pass instead of a hand kernel (decode partials are KB).
    """
    m = jnp.max(lses, axis=0, keepdims=True)                    # [1, B, H]
    w = jnp.exp(lses - m)                                       # [W, B, H]
    denom = jnp.sum(w, axis=0)                                  # [B, H]
    out = jnp.sum(outs * w[..., None], axis=0)                  # [B, H, D]
    return out / denom[..., None]


# ---------------------------------------------------------------------------
# Sequence-parallel decode (shard + host entries)
# ---------------------------------------------------------------------------


def sp_gqa_decode_shard(q, k_shard, v_shard, kv_lens, *, axis, block_s=None,
                        impl="auto", interpret=False, k_scale=None,
                        v_scale=None, soft_cap=0.0, window=0, q_lens=None):
    """Per-device SP decode: local split-KV partials -> comm-fused combine
    (``sp_combine_shard``; the XLA-only mode falls back to LL gather +
    epilogue).  ``kv_lens`` are GLOBAL lengths; the shard
    owns global rows [me*S_loc, (me+1)*S_loc).  Optional ``k/v_scale``
    [B, Hkv, S_loc] dequantize an int8 cache shard.

    Reference analog: ``SpGQAFlashDecodeAttention.forward``
    (sp_flash_decode_layer.py:78-184).
    """
    B, Hq, D = q.shape[0], q.shape[-2], q.shape[-1]
    multi = q.ndim == 4
    S_loc = k_shard.shape[2]
    me = jax.lax.axis_index(axis)
    world = jax.lax.axis_size(axis)
    ends = (kv_lens - me * S_loc).astype(jnp.int32)  # unclipped local end
    local_lens = jnp.clip(ends, 0, S_loc)

    out, lse = gqa_decode_shard(q, k_shard, v_shard, local_lens,
                                block_s=block_s, impl=impl,
                                interpret=interpret, k_scale=k_scale,
                                v_scale=v_scale, soft_cap=soft_cap,
                                window=window,
                                window_lens=ends if (window or multi)
                                else None,
                                q_lens=q_lens)
    # Comm-fused combine kernel by default — remote DMA of the (out, lse)
    # partial planes and the LSE merge in ONE Pallas kernel (VERDICT
    # round-1 missing #2); xla mode keeps the packed LL gather + epilogue.
    if multi:
        # [B, T, ...] partials combine like a B*T batch; dead rows carry
        # lse = NEG on every rank and merge to 0.
        T = out.shape[1]
        c = _combine_across_ranks(out.reshape(B * T, Hq, D),
                                  lse.reshape(B * T, Hq), q.dtype,
                                  axis=axis, impl=impl, interpret=interpret)
        return c.reshape(B, T, Hq, D)
    return _combine_across_ranks(out, lse, q.dtype, axis=axis, impl=impl,
                                 interpret=interpret)


@dataclass
class SpDecodeContext:
    """Sizing/mesh context (reference analog: the create_*_context factories,
    flash_decode.py:534-585)."""

    mesh: Mesh
    axis: str = "sp"
    block_s: int | None = None  # None = full-shard chunk (min(S, 8192))
    impl: str = "auto"
    interpret: bool = False
    soft_cap: float = 0.0  # Gemma-2 logit capping; 0 = off
    window: int = 0  # sliding window (global rule, any world; 0 = off)

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_sp_decode_context(mesh, axis="sp", block_s=None, impl="auto",
                             interpret=False, soft_cap=0.0,
                             window=0) -> SpDecodeContext:
    # ``window`` composes with SP sharding (r5): each shard intersects
    # the global window [kv_len - window, kv_len) with its own range via
    # the unclipped ``window_lens``; shards wholly outside contribute
    # lse = NEG_INF partials that the combine ignores.
    return SpDecodeContext(mesh=mesh, axis=axis, block_s=block_s, impl=impl,
                           interpret=interpret, soft_cap=soft_cap,
                           window=window)


def sp_gqa_decode(q, k_cache, v_cache, kv_lens, ctx: SpDecodeContext):
    """Host entry.  q [B, Hq, D] replicated; k/v_cache [B, Hkv, S, D] sharded
    on the sequence dim over ``ctx.axis``; kv_lens [B] global lengths.
    Returns [B, Hq, D] replicated.

    Reference analog: ``gqa_fwd_batch_decode`` host wrappers
    (flash_decode.py:763-1160).
    """
    fn = cached_shard_jit(
        sp_gqa_decode_shard,
        ctx.mesh,
        (P(), P(None, None, ctx.axis), P(None, None, ctx.axis), P()),
        P(),
        axis=ctx.axis, block_s=ctx.block_s, impl=ctx.impl,
        interpret=ctx.interpret, soft_cap=ctx.soft_cap, window=ctx.window,
    )
    # Launch metadata (profiling.annotate contract): decode is the
    # HBM-bound KV-shard read per rank; wire = the packed (out ⊕ lse)
    # partial planes every rank exchanges for the combine.
    from triton_dist_tpu.runtime.profiling import annotate

    B, Hq, D = q.shape[0], q.shape[-2], q.shape[-1]
    world = max(ctx.world, 1)
    el = jnp.dtype(k_cache.dtype).itemsize
    with annotate("sp_gqa_decode",
                  flops=4 * B * Hq * (k_cache.shape[2] // world) * D,
                  bytes_accessed=(k_cache.nbytes + v_cache.nbytes)
                  // world
                  + B * Hq * (D + 1) * 4 * (world - 1)):
        return fn(q, k_cache, v_cache, kv_lens)
