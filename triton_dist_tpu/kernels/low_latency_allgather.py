"""Latency-optimized AllGather — the SP-decode communication primitive.

Reference analog: ``python/triton_dist/kernels/nvidia/low_latency_allgather.py``
— pull / push-2D / push-3D / NUMA-2D variants plus the **LL protocol**: values
packed with flags as int2 pairs so the receiver spins on the data itself with
no separate signal (:549-568, `_recv_ll_block` :531-547), double-buffered by a
generation counter; dispatcher ``fast_allgather`` (:971+).

TPU-native design (NOT a port — see SURVEY.md §7 hard part 5):

* The LL trick exists because on NVLink a signal is a *second* transaction;
  packing flag-with-value makes arrival self-announcing.  On TPU the recv
  semaphore update is part of the same DMA transaction — arrival is already
  self-announcing.  So the TPU "LL protocol" is simply: one-shot full-mesh
  push of the whole (small) payload, recv-semaphore gated, which is the
  ``FULL_MESH_PUSH`` kernel.  No flags, no generation counters (fresh
  semaphores per invocation), no reset kernels.
* The reference's push-2D/3D hierarchy (intra-node staged + inter-node)
  maps to two mesh axes: gather along the minor (ICI) axis first, then the
  major axis — ``fast_allgather_2d``.
* The payload-packing *use* of LL buffers (flash-decode's (out ⊕ lse) in
  one buffer, sp_flash_decode_layer.py:135-137) is kept: ``pack_payload`` /
  ``unpack_payload`` below, consumed by ``kernels/flash_decode.py``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.kernels.allgather import (
    AllGatherMethod,
    all_gather_shard,
)
from triton_dist_tpu.kernels.gemm import resolve_impl
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


@dataclass
class FastAllGatherContext:
    """Reference analog: ``FastAllGatherContext`` (:781-820)."""

    mesh: Mesh
    axis: str = "tp"
    inter_axis: str | None = None  # 2-level gather (DCN/multi-slice tier)
    impl: str = "auto"
    interpret: bool = False

    @property
    def world(self) -> int:
        w = self.mesh.shape[self.axis]
        if self.inter_axis:
            w *= self.mesh.shape[self.inter_axis]
        return w


def create_fast_ag_context(mesh, axis="tp", inter_axis=None, impl="auto",
                           interpret=False) -> FastAllGatherContext:
    return FastAllGatherContext(mesh=mesh, axis=axis, inter_axis=inter_axis,
                                impl=impl, interpret=interpret)


def fast_allgather_shard(x_shard, *, axis, inter_axis=None, impl="auto",
                         interpret=False, collective_id=None):
    """Latency-tuned gather of a small per-device shard (leading dim).

    1-level: one-shot full-mesh push.  2-level: minor (ICI) axis first, then
    major — the reference's push-2D staging (:612-698) without the staging
    buffers (ICI routes multi-hop natively).  This is THE latency-gather
    policy: ops gathering small payloads (flash-decode partials etc.) call
    this rather than picking a method themselves.
    """
    from triton_dist_tpu.kernels.collective_ids import LL_AG, LL_AG_INTER

    if collective_id is None:
        collective_id = LL_AG
    impl = resolve_impl(impl, interpret)
    method = (AllGatherMethod.XLA if impl == "xla"
              else AllGatherMethod.FULL_MESH_PUSH)
    out = all_gather_shard(x_shard, axis, method=method, interpret=interpret,
                           collective_id=collective_id)
    if inter_axis is not None:
        # Distinct collective_id: a second barrier semaphore for the second
        # device set (the DCN/major tier).
        out = all_gather_shard(out, inter_axis, method=method,
                               interpret=interpret,
                               collective_id=LL_AG_INTER)
    return out


def fast_allgather(x, ctx: FastAllGatherContext):
    """Host entry (reference dispatcher ``fast_allgather`` :971+)."""
    in_spec = (P((ctx.inter_axis, ctx.axis)) if ctx.inter_axis
               else P(ctx.axis))
    fn = cached_shard_jit(
        fast_allgather_shard,
        ctx.mesh,
        in_spec,
        P(),
        axis=ctx.axis, inter_axis=ctx.inter_axis, impl=ctx.impl,
        interpret=ctx.interpret,
    )
    # Launch metadata (profiling.annotate contract): push-AG wire =
    # every device broadcasts its shard to (world - 1) peers.
    from triton_dist_tpu.runtime.profiling import annotate

    world = int(ctx.mesh.shape[ctx.axis])
    if ctx.inter_axis:
        world *= int(ctx.mesh.shape[ctx.inter_axis])
    with annotate("fast_allgather",
                  bytes_accessed=x.nbytes // max(world, 1)
                  * max(world - 1, 0)):
        return fn(x)


# ---------------------------------------------------------------------------
# Payload packing (flash-decode partials: out ⊕ lse in one gather)
# ---------------------------------------------------------------------------


def pack_payload(out, lse):
    """[B, H, D] f32 partials + [B, H] lse -> [B, H, D+1] single buffer.

    Reference: the decode layer packs lse into the last column of the AG
    buffer so one LL gather moves both (sp_flash_decode_layer.py:135-137).
    """
    return jnp.concatenate([out, lse[..., None]], axis=-1)


def unpack_payload(buf):
    """[W, B, H, D+1] -> ([W, B, H, D], [W, B, H])."""
    return buf[..., :-1], buf[..., -1]
