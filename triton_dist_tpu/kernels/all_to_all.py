"""Low-latency EP AllToAll — MoE inference token dispatch/combine.

Reference analog: ``python/triton_dist/kernels/nvidia/low_latency_all_to_all.py``
— the README's headline 137 µs kernel (vs DeepEP's 182 µs): a single kernel
where each PE ``putmem_nbi_block``s its token segment + split counts to every
peer, with ``fence`` + ``signal_op``/``signal_wait_until`` handshakes and
double-buffering by ``call_count`` parity (:35-119); host wrapper
``fast_all_to_all`` (:189+), ``all_to_all_post_process`` (:251+) compacts.

TPU-native design (NOT a port):

* **Static max-token padding** (SURVEY.md §7 hard part 2): segment sizes are
  data-dependent, but TPU DMAs need static sizes; each (src→dst) segment is
  padded to ``max_tokens`` rows, like the reference's own symm-buffer layout
  (`AllToAllContext.max_m`, :125-165).  Split counts travel as a second tiny
  DMA posted back-to-back with (and overlapping) the payload DMA; the recv
  semaphore supplies the arrival ordering that the reference builds from the
  LL flag-in-data trick + NVLink 8-byte store atomicity (:549-568).
* **No parity/double-buffering**: each ``pallas_call`` invocation gets fresh
  buffers and zeroed semaphores (Mosaic guarantees), so the reference's
  ``call_count`` parity machinery (:92-101) has no TPU equivalent to need.
* fp8 payloads: pass an fp8 array; the DMA is dtype-agnostic.  (The
  reference's separate scale putmem (:76-88) becomes "stack scales as extra
  hidden columns" at the caller.)

Layout contract (shard-level, inside shard_map over ``axis``):
  send:  [world, max_tokens, H]  — row block p goes to peer p
  splits: [world] int32          — valid rows per destination
  recv:  [world, max_tokens, H]  — row block p arrived from peer p
  recv_splits: [world] int32

Wire-byte contract (pallas impl): transfers are PROPORTIONAL to the
actual splits — each (src→dst) segment moves ``ceil(split/block)*block``
rows (block = largest power of two <= 128 dividing max_tokens), not
``max_tokens``.  Consequently recv rows past that point are UNDEFINED and
must be masked by ``recv_splits`` (``all_to_all_post_process`` returns
the mask; the XLA impl still moves full segments and leaves the send
padding in place).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels.gemm import resolve_impl
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

from triton_dist_tpu.kernels.collective_ids import (
    A2A as A2A_COLLECTIVE_ID,
    HIER_A2A_FAST,
    HIER_A2A_SLOW,
)


@dataclass
class AllToAllContext:
    """Reference analog: ``AllToAllContext`` (low_latency_all_to_all.py:125-165)
    — max_m/hidden/world sizing of the symmetric buffers."""

    mesh: Mesh
    # None = "size for the lossless worst case at dispatch time" — only
    # meaningful for EP dispatch/combine (layers/ep_a2a.py), where t_loc and
    # topk fix the bound; the raw fast_all_to_all entry needs a number.
    max_tokens: int | None
    hidden: int
    axis: str = "ep"
    impl: str = "auto"
    interpret: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_all_to_all_context(mesh, max_tokens, hidden, axis="ep",
                              impl="auto", interpret=False) -> AllToAllContext:
    return AllToAllContext(mesh=mesh, max_tokens=max_tokens, hidden=hidden,
                           axis=axis, impl=impl, interpret=interpret)


def _a2a_wire_block(max_tokens: int, cap: int | None = None) -> int:
    """Largest power-of-two row-block <= min(128, cap) dividing
    ``max_tokens``.

    Uniform block sizes keep the semaphore byte-accounting trivial (every
    payload DMA moves exactly ``block`` rows); 128 rows is deep enough to
    amortize DMA issue overhead at serving hidden sizes.  ``cap`` bounds
    the block by the caller's expected per-segment load (EP dispatch:
    ``t_loc*topk/world`` at balanced routing) — block padding beyond the
    expected load is pure wire waste."""
    limit = 128 if cap is None else max(1, min(128, cap))
    for b in (128, 64, 32, 16, 8, 4, 2):
        if b <= limit and max_tokens % b == 0:
            return b
    return 1


def _a2a_kernel(send_ref, splits_any, splits_smem, recv_ref, recv_splits_ref,
                send_sem, recv_sem, ssend_sem, srecv_sem, copy_sem,
                rsplit_smem, *poison_ref,
                axis, world, block):
    """One-shot full-mesh token shuffle with splits-PROPORTIONAL transfers.

    Wire bytes scale with the actual token counts, not the worst-case
    buffer sizing (reference: ``kernel_dispatch_token`` puts per-token
    segments for the actual counts, ep_a2a.py:74-146; its buffers are
    worst-case sized but its *transfers* are not).  Mosaic cannot issue a
    dynamic-LENGTH DMA, but it can issue a dynamic COUNT of fixed-size
    block DMAs: per peer, a static loop over ``ceil(max_tokens/block)``
    blocks posts block ``b`` under ``@pl.when(b*block < split[peer])`` —
    so a segment with ``s`` valid rows costs ``ceil(s/block)*block`` rows
    of wire traffic instead of ``max_tokens``.

    Receive-side accounting: split counts travel on their own semaphore
    pair ahead of the payload; after the ``world-1`` split rows land they
    are staged into SMEM, and the receiver waits for exactly
    ``sum_p ceil(recv_splits[p]/block)`` payload-block arrivals (a traced
    fori_loop — the arrival count is data-dependent by design).

    CONTRACT CHANGE vs the old full-segment kernel: recv rows at index
    >= ceil(recv_splits[p]/block)*block are UNDEFINED (never written) —
    consumers must mask by ``recv_splits`` (all_to_all_post_process
    returns exactly that mask; ep_combine zeroes invalid slots).

    splits travel as [world, 128] int32 rows (count in column 0): Mosaic
    cannot DMA a sub-lane 1-D int32 slice on hardware, a full 128-lane row
    is the minimum wire unit.  ``splits_smem`` is the same array routed
    into SMEM so the sender can read its own counts as scalars.
    """
    me = jax.lax.axis_index(axis)
    max_tokens = send_ref.shape[1]
    nblk = max_tokens // block

    # Local segment: ours lands in recv[me] without touching the wire
    # (reference: the pe==rank branch of the dispatch loop).
    cp = pltpu.make_async_copy(send_ref.at[me], recv_ref.at[me], copy_sem)
    cp.start()
    sp = pltpu.make_async_copy(splits_any.at[pl.ds(me, 1)],
                               recv_splits_ref.at[pl.ds(me, 1)], copy_sem)
    sp.start()
    cp.wait()
    sp.wait()

    if world == 1:
        # Degenerate mesh: recv == send, including padding rows (nothing
        # is elided locally — the full segment is one HBM copy).
        return

    # Entry barrier: nobody writes into a peer still outside the kernel.
    dl.barrier_all(axis)

    # Split counts first, on their own semaphore pair (their arrival
    # gates the receiver's payload accounting).
    for i in range(1, world):
        peer = jax.lax.rem(me + i, world)
        dl.remote_copy(splits_any.at[pl.ds(peer, 1)],
                       recv_splits_ref.at[pl.ds(me, 1)],
                       ssend_sem, srecv_sem, axis, peer).start()

    # Payload: dynamic COUNT of fixed-size block DMAs per peer.  The
    # sender reads its own split counts from SMEM — no waiting needed.
    for i in range(1, world):
        peer = jax.lax.rem(me + i, world)
        # Clamp: a split above max_tokens would otherwise make the drain
        # loops below expect more block DMAs than the nblk-bounded send
        # loop posts — a distributed hang, not an error.
        split_p = jnp.minimum(splits_smem[peer, 0], max_tokens)
        for b in range(nblk):

            @pl.when(b * block < split_p)
            def _(b=b, peer=peer):
                dl.remote_copy(
                    send_ref.at[peer, pl.ds(b * block, block)],
                    recv_ref.at[me, pl.ds(b * block, block)],
                    send_sem, recv_sem, axis, peer).start()

    # Outgoing drains.  Splits rows: exactly world-1.  Payload blocks:
    # sum over peers of ceil(split/block) — data-dependent trip count.
    srow = splits_any.at[pl.ds(0, 1)]
    for _ in range(1, world):
        pltpu.make_async_copy(srow, srow, ssend_sem).wait()
    nblocks_out = jnp.int32(0)
    for i in range(1, world):
        peer = jax.lax.rem(me + i, world)
        sp_c = jnp.minimum(splits_smem[peer, 0], max_tokens)
        nblocks_out += (sp_c + block - 1) // block
    blk_tpl = send_ref.at[0, pl.ds(0, block)]

    def _drain_send(_, c):
        pltpu.make_async_copy(blk_tpl, blk_tpl, send_sem).wait()
        return c

    jax.lax.fori_loop(0, nblocks_out, _drain_send, 0)

    # Incoming: wait for all split rows, stage them to SMEM, then wait
    # for exactly the advertised number of payload blocks.
    for _ in range(1, world):
        pltpu.make_async_copy(srow, srow, srecv_sem).wait()
    st = pltpu.make_async_copy(recv_splits_ref, rsplit_smem, copy_sem)
    st.start()
    st.wait()
    nblocks_in = jnp.int32(0)
    for i in range(1, world):
        peer = jax.lax.rem(me + i, world)
        rs_c = jnp.minimum(rsplit_smem[peer, 0], max_tokens)
        nblocks_in += (rs_c + block - 1) // block

    def _drain_recv(_, c):
        pltpu.make_async_copy(blk_tpl, blk_tpl, recv_sem).wait()
        return c

    jax.lax.fori_loop(0, nblocks_in, _drain_recv, 0)

    if poison_ref:
        # Debug poison (VERDICT r3 #7): never-shipped recv blocks (rows
        # >= ceil(recv_splits[p]/block)*block, remote peers) are written
        # with a sentinel — NaN for float payloads, iinfo.max for ints —
        # so a consumer that misses the recv_splits mask fails as loudly
        # on hardware as interpret-mode NaN-fill makes it fail on the
        # CPU mesh (where unwritten buffer rows are NaN already; for int
        # payloads the sentinel is observable under interpret too).
        # Enabled via debug_poison=True / TDT_A2A_POISON=1; costs extra
        # HBM writes, debug only.
        (pz,) = poison_ref
        dt = recv_ref.dtype
        val = jnp.nan if jnp.issubdtype(dt, jnp.inexact) else jnp.iinfo(dt).max
        pz[...] = jnp.full(pz.shape, val, dt)
        for i in range(1, world):
            peer = jax.lax.rem(me + i, world)
            rs_c = jnp.minimum(rsplit_smem[peer, 0], max_tokens)
            shipped = ((rs_c + block - 1) // block) * block
            for b in range(nblk):

                @pl.when(jnp.int32(b * block) >= shipped)
                def _(b=b, peer=peer):
                    w = pltpu.make_async_copy(
                        pz, recv_ref.at[peer, pl.ds(b * block, block)],
                        copy_sem)
                    w.start()
                    w.wait()


def fast_all_to_all_shard(send, splits, *, axis, impl, interpret,
                          collective_id=A2A_COLLECTIVE_ID, wire_block=None,
                          debug_poison=None):
    """Shard-level entry.  send: [world, max_tokens, H]; splits: [world] i32.
    Returns (recv [world, max_tokens, H], recv_splits [world]).
    ``collective_id`` must differ between a2a kernels composed in one
    program (the hierarchical two-stage path).

    ``wire_block``: row granularity of the splits-proportional transfers
    (must divide max_tokens).  Default: largest power of two <= 128
    dividing max_tokens.  Callers that know the expected per-segment load
    (EP dispatch: ``t_loc*topk/world`` at balanced routing) should pass a
    block no larger than it — block padding is pure wire waste.

    A 2-tuple ``axis`` (slow, fast — e.g. ("dcn", "ici")) routes the
    pallas impl through the hierarchical two-stage kernel (every token
    crosses the slow wire once); the XLA impl hands the tuple to
    ``jax.lax.all_to_all`` directly.  Flat rank order is slow-major
    either way."""
    impl = resolve_impl(impl, interpret)
    world, max_tokens, hidden = send.shape

    if impl != "xla" and isinstance(axis, (tuple, list)) and len(axis) == 2:
        from triton_dist_tpu.kernels.hierarchical import (
            hier_all_to_all_shard)

        # Two-stage path uses the hierarchical kernels' reserved id pair
        # (collective_ids.py registry).
        return hier_all_to_all_shard(
            send, splits, slow_axis=axis[0], fast_axis=axis[1], impl=impl,
            interpret=interpret,
            collective_ids=(HIER_A2A_SLOW, HIER_A2A_FAST))

    if impl == "xla":
        recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
        recv_splits = jax.lax.all_to_all(splits.reshape(world, 1), axis,
                                         split_axis=0, concat_axis=0,
                                         tiled=False).reshape(world)
        return recv, recv_splits

    splits_row = jnp.zeros((world, 128), jnp.int32).at[:, 0].set(splits)
    block = wire_block if wire_block is not None else _a2a_wire_block(max_tokens)
    if max_tokens % block:
        raise ValueError(f"wire_block={block} must divide max_tokens="
                         f"{max_tokens} (uniform blocks keep the DMA "
                         "byte-accounting exact)")
    if debug_poison is None:
        import os

        debug_poison = os.environ.get("TDT_A2A_POISON", "0") == "1"
    poison_scratch = (
        [pltpu.VMEM((block, hidden), send.dtype)] if debug_poison else [])
    recv, recv_splits_row = pl.pallas_call(
        functools.partial(_a2a_kernel, axis=axis, world=world, block=block),
        out_shape=[
            jax.ShapeDtypeStruct((world, max_tokens, hidden), send.dtype),
            jax.ShapeDtypeStruct((world, 128), jnp.int32),
        ],
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,   # payload send
            pltpu.SemaphoreType.DMA,   # payload recv
            pltpu.SemaphoreType.DMA,   # splits send
            pltpu.SemaphoreType.DMA,   # splits recv
            pltpu.SemaphoreType.DMA,   # local copies / SMEM staging
            pltpu.SMEM((world, 128), jnp.int32),
        ] + poison_scratch,
        compiler_params=dl.collective_compiler_params(
            world, collective_id),
        interpret=maybe_interpret(interpret),
    )(send, splits_row, splits_row)
    return recv, recv_splits_row[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def fast_all_to_all_shard_diff(send, splits, axis, impl, interpret,
                               wire_block=None):
    """Differentiable :func:`fast_all_to_all_shard`.

    The global token shuffle is a permutation, and its transpose is the
    inverse shuffle — which for this symmetric (block p ↔ peer p) layout is
    the *same* AllToAll applied to the cotangent.  This is what lets MoE EP
    layers train through the dispatch/combine path (the reference is
    inference-only here; no backward exists to compare against).
    """
    return fast_all_to_all_shard(send, splits, axis=axis, impl=impl,
                                 interpret=interpret, wire_block=wire_block)


def _a2a_diff_fwd(send, splits, axis, impl, interpret, wire_block=None):
    recv, recv_splits = fast_all_to_all_shard(
        send, splits, axis=axis, impl=impl, interpret=interpret,
        wire_block=wire_block)
    return (recv, recv_splits), recv_splits


def _a2a_diff_bwd(axis, impl, interpret, wire_block, recv_splits, cts):
    d_recv, _ = cts
    d_send, d_splits = fast_all_to_all_shard(
        d_recv, recv_splits, axis=axis, impl=impl, interpret=interpret,
        wire_block=wire_block)
    # The true cotangent of a send row that never shipped is ZERO (the
    # outputs don't depend on it), but the proportional reverse shuffle
    # leaves those rows undefined — mask them, or downstream weight
    # gradients contract NaN garbage against zero cotangents.
    world, max_tokens, _ = d_send.shape
    row = jax.lax.broadcasted_iota(jnp.int32, (world, max_tokens), 1)
    d_send = jnp.where((row < d_splits[:, None])[..., None], d_send, 0)
    return d_send, np.zeros(recv_splits.shape, jax.dtypes.float0)


fast_all_to_all_shard_diff.defvjp(_a2a_diff_fwd, _a2a_diff_bwd)


def fast_all_to_all(send, splits, ctx: AllToAllContext):
    """Host entry (reference: ``fast_all_to_all``, :189+).

    send: [world*world, max_tokens, H] sharded P(axis) so each device holds
    its [world, max_tokens, H] outgoing block; splits likewise.
    """
    w = ctx.world
    if ctx.max_tokens is None:
        raise ValueError(
            "fast_all_to_all needs an explicit ctx.max_tokens (it sizes the "
            "symmetric buffers); max_tokens=None is only meaningful for the "
            "EP dispatch path, which derives the worst case itself")
    expected = (w * w, ctx.max_tokens, ctx.hidden)
    if tuple(send.shape) != expected:
        raise ValueError(
            f"send shape {tuple(send.shape)} != ctx sizing {expected} "
            f"(world={w}, max_tokens={ctx.max_tokens}, hidden={ctx.hidden})")
    fn = cached_shard_jit(
        fast_all_to_all_shard,
        ctx.mesh,
        (P(ctx.axis), P(ctx.axis)),
        (P(ctx.axis), P(ctx.axis)),
        axis=ctx.axis, impl=ctx.impl, interpret=ctx.interpret,
    )
    # Launch metadata (profiling.annotate contract): each device ships
    # (world - 1) of its world outgoing [max_tokens, H] segments.
    from triton_dist_tpu.runtime.profiling import annotate

    el = jnp.dtype(send.dtype).itemsize
    with annotate("fast_all_to_all",
                  bytes_accessed=max(w - 1, 0) * ctx.max_tokens
                  * ctx.hidden * el):
        return fn(send, splits)


def all_to_all_post_process(recv, recv_splits):
    """Flatten the padded receive buffer and compute the validity mask.

    Reference analog: ``all_to_all_post_process`` (:251+), which compacts to
    a dense [sum(splits), H] matrix — a dynamic shape, deliberately avoided
    on TPU.  Instead returns (tokens [world*max_tokens, H] with padding rows
    left in place, mask [world*max_tokens] bool aligned with the token rows);
    downstream group-GEMM / reductions consume the mask.
    """
    world, max_tokens, hidden = recv.shape
    idx = jax.lax.broadcasted_iota(jnp.int32, (world, max_tokens), 1)
    mask = idx < recv_splits[:, None]
    return recv.reshape(world * max_tokens, hidden), mask.reshape(-1)
