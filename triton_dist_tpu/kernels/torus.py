"""Torus-native multi-axis collectives: concurrent per-axis ring schedules.

Reference analog: the topology-specialized AllGather variants of
``python/triton_dist/kernels/nvidia/allgather.py`` — the NUMA-aware 2D ring
(:194-258) and the inter-node 2D variants (:470-591).  The reference earns
its performance by matching the schedule to the fabric; on TPU the fabric is
a 2D/3D ICI torus, and the matching schedule is *concurrent bidirectional
rings on every axis*.

Why not compose per-axis kernels (``hierarchical.py``)?  Composition is
sequential: during the axis-0 phase every axis-1 link idles and vice versa —
on a torus whose axes have equal bandwidth that wastes half (2D) or two
thirds (3D) of the injection bandwidth.  The fused kernel here keeps every
link direction busy in both phases:

* The shard is split into four **quarters**, each assigned one of the four
  (first-axis, direction) path flavors: x→y forward, x→y backward, y→x
  forward, y→x backward.
* Phase 1: each quarter rings its slot along its first axis — the four
  concurrent streams ride x+, x-, y+, y- simultaneously.
* Phase 2: each quarter forwards its gathered first-axis *lines* along the
  other axis, again on four disjoint link directions (x quarters move to y±,
  y quarters to x±).

Per-(quarter, phase) DMA semaphore pairs keep the byte accounting of the
four streams and two phases independent (a fast path may enter phase 2
while a neighbor still drains phase 1; distinct semaphores make the early
arrival invisible to the neighbor's phase-1 waits).

Expected bandwidth: one bidirectional ring saturates 2 of a 2D torus's 4
link directions; this schedule drives all 4 → ~2× the 1-axis bidir ring,
~4× the unidirectional ring (see ``perf_model.py:torus_ag_time``).

3-axis tori compose: gather the fused 2D plane, then a bidirectional ring
on the third axis (``torus_all_gather_shard`` with a 3-tuple) — the third
axis moves plane-fold more bytes, so it dominates and still overlaps
nothing; a fully fused 3D six-path schedule is the natural extension once
an axis-3 mesh is the deployment target.

Output order: flat ``axes``-major (axes[0] slowest), matching
``hierarchical.hier_all_gather_shard`` — the two are drop-in replacements
for each other (ICI-only mesh → this module; ICI×DCN → hierarchical, where
sequencing is *correct* because the slow wire must move the minimum bytes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels import collective_ids as cid
from triton_dist_tpu.language.interpret import maybe_interpret

__all__ = ["torus_all_gather_shard", "torus_reduce_scatter_shard"]


def _split_quarters(rows: int):
    """Split ``rows`` into 4 contiguous (offset, length) quarters; lengths
    may be 0 for tiny shards (those path flavors simply do not run)."""
    base, rem = divmod(rows, 4)
    lens = [base + (1 if q < rem else 0) for q in range(4)]
    offs, o = [], 0
    for ln in lens:
        offs.append(o)
        o += ln
    return list(zip(offs, lens))


def _torus2d_ag_kernel(x_ref, out_ref, send_sem, recv_sem, copy_sem,
                       *, ax, ay, wx, wy, quarters):
    """Fused 2D torus AllGather.  ``out_ref`` is [wx, wy, R, C]; slot (i, j)
    is device (ax=i, ay=j)'s shard.  ``quarters``: 4 tuples
    (row_offset, row_len, first_axis ('x'|'y'), direction (+1|-1)).

    Semaphore layout: ``send_sem``/``recv_sem`` are [4, 2] DMA semaphore
    arrays indexed (quarter, phase).
    """
    i = jax.lax.axis_index(ax)
    j = jax.lax.axis_index(ay)

    # Stage my slot, then make sure every device in the plane entered the
    # kernel before any remote DMA (barrier_all contract; the two-axis
    # barrier is transitive: after ax all (*, j) entered, after ay all
    # (i', *) finished their ax barrier → the whole plane is in).
    cp = pltpu.make_async_copy(x_ref, out_ref.at[i, j], copy_sem)
    cp.start()
    cp.wait()
    dl.barrier_all(ax)
    dl.barrier_all(ay)

    def p1_block(q, s, first, d, off, ln):
        """Quarter q's phase-1 ring block at step s: the slot it forwards."""
        if first == "x":
            idx = jax.lax.rem(i - d * s + s * wx + wx, wx)
            return out_ref.at[idx, j, pl.ds(off, ln)]
        idx = jax.lax.rem(j - d * s + s * wy + wy, wy)
        return out_ref.at[i, idx, pl.ds(off, ln)]

    def p2_block(q, t, first, d, off, ln):
        """Quarter q's phase-2 ring block at step t: the first-axis line it
        forwards along the second axis."""
        if first == "x":  # second axis y: forward x-lines (all i', fixed j')
            jsrc = jax.lax.rem(j - d * t + t * wy + wy, wy)
            return out_ref.at[:, jsrc, pl.ds(off, ln)]
        isrc = jax.lax.rem(i - d * t + t * wx + wx, wx)
        return out_ref.at[isrc, :, pl.ds(off, ln)]

    def ring_meta(first, d, phase):
        """(axis name, my coord, axis size, peer) for a quarter's phase."""
        axis_is_x = (first == "x") == (phase == 0)
        if axis_is_x:
            return ax, wx, jax.lax.rem(i + d + wx, wx)
        return ay, wy, jax.lax.rem(j + d + wy, wy)

    def run_phase(phase, block_fn, n_steps_of):
        n_max = max(n_steps_of(q) for q in range(4))

        def step(s, _):
            # Start every active quarter's DMA first (concurrency), then
            # wait them all (descriptor trick on the same-shaped block).
            for q, (off, ln, first, d) in enumerate(quarters):
                if ln == 0 or n_steps_of(q) == 0:
                    continue
                axis, _, peer = ring_meta(first, d, phase)

                @pl.when(s < n_steps_of(q))
                def _(q=q, off=off, ln=ln, first=first, d=d, axis=axis,
                      peer=peer):
                    blk = block_fn(q, s, first, d, off, ln)
                    dl.remote_copy(blk, blk, send_sem.at[q, phase],
                                   recv_sem.at[q, phase], axis, peer).start()
            for q, (off, ln, first, d) in enumerate(quarters):
                if ln == 0 or n_steps_of(q) == 0:
                    continue

                @pl.when(s < n_steps_of(q))
                def _(q=q, off=off, ln=ln, first=first, d=d):
                    blk = block_fn(q, s, first, d, off, ln)
                    pltpu.make_async_copy(blk, blk,
                                          send_sem.at[q, phase]).wait()
                    pltpu.make_async_copy(blk, blk,
                                          recv_sem.at[q, phase]).wait()
            return 0

        if n_max > 0:
            jax.lax.fori_loop(0, n_max, step, 0)

    # Phase 1: ring each quarter's slots along its first axis.
    run_phase(0, p1_block,
              lambda q: (wx if quarters[q][2] == "x" else wy) - 1)
    # Phase 2: ring the gathered first-axis lines along the second axis.
    run_phase(1, p2_block,
              lambda q: (wy if quarters[q][2] == "x" else wx) - 1)


_QUARTER_FLAVORS = (("x", 1), ("x", -1), ("y", 1), ("y", -1))


def _torus2d_ag(x_shard, *, ax, ay, wx, wy, interpret, collective_id):
    rows = x_shard.shape[0]
    orig_shape = x_shard.shape
    x2 = x_shard.reshape(rows, -1)
    cols = x2.shape[1]
    quarters = tuple(
        (off, ln, first, d)
        for (off, ln), (first, d) in zip(_split_quarters(rows),
                                         _QUARTER_FLAVORS))
    out4 = pl.pallas_call(
        functools.partial(_torus2d_ag_kernel, ax=ax, ay=ay, wx=wx, wy=wy,
                          quarters=quarters),
        out_shape=jax.ShapeDtypeStruct((wx, wy, rows, cols), x2.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((4, 2)),
                        pltpu.SemaphoreType.DMA((4, 2)),
                        pltpu.SemaphoreType.DMA],
        compiler_params=dl.collective_compiler_params(wx * wy, collective_id),
        interpret=maybe_interpret(interpret),
    )(x2)
    return out4.reshape((wx * wy * rows,) + orig_shape[1:])


def torus_all_gather_shard(x_shard, axes, *, interpret=False,
                           collective_id=cid.TORUS_AG):
    """AllGather a shard over a 2- or 3-axis ICI torus; call inside
    shard_map.  Output is flat ``axes``-major (axes[0] slowest), i.e. the
    row block of flat rank ``r`` is the shard of the device whose axes
    coordinates spell ``r`` in mixed radix — the same order
    ``lax.all_gather`` over the joint axes and ``hier_all_gather_shard``
    produce.

    2 axes → the fused four-path kernel (all four ICI link directions busy
    every phase).  3 axes → the fused 2D plane over ``axes[1:]`` then a
    bidirectional ring on ``axes[0]`` (the dominant, plane-fold heavier
    phase; see module docstring).
    """
    from triton_dist_tpu.kernels.allgather import (
        AllGatherMethod,
        all_gather_shard,
    )

    axes = tuple(axes)
    if len(axes) == 1:
        return all_gather_shard(x_shard, axes[0],
                                method=AllGatherMethod.AUTO,
                                interpret=interpret,
                                collective_id=collective_id)
    if len(axes) == 3:
        a0 = axes[0]
        plane = torus_all_gather_shard(x_shard, axes[1:],
                                       interpret=interpret,
                                       collective_id=collective_id)
        return all_gather_shard(plane, a0, method=AllGatherMethod.AUTO,
                                interpret=interpret,
                                collective_id=cid.TORUS_AG_THIRD)
    if len(axes) != 2:
        raise ValueError(f"torus_all_gather_shard supports 1-3 axes, "
                         f"got {axes}")
    ax, ay = axes
    wx = jax.lax.axis_size(ax)
    wy = jax.lax.axis_size(ay)
    if wx * wy == 1:
        return x_shard
    if wx == 1 or wy == 1:  # degenerate torus: one real axis
        axis = ax if wx > 1 else ay
        return all_gather_shard(x_shard, axis, method=AllGatherMethod.AUTO,
                                interpret=interpret,
                                collective_id=collective_id)
    return _torus2d_ag(x_shard, ax=ax, ay=ay, wx=wx, wy=wy,
                       interpret=interpret, collective_id=collective_id)


# ---------------------------------------------------------------------------
# ReduceScatter
# ---------------------------------------------------------------------------


def _fold_tiles(dst, a_src, b_src, va, vb, copy_sem, *, cols, tile_c):
    """dst <- a_src + b_src, streamed through VMEM in column tiles.

    All three operands are HBM(ANY) refs of identical shape [..., cols];
    ``va``/``vb`` are VMEM tiles with a leading DOUBLE-BUFFER dim [2] and
    ``tile_c`` columns.  Staging through VMEM keeps the kernel's
    scoped-VMEM need at four half-size tiles regardless of the
    line-buffer size — the all-VMEM round-2 layout needed ~3x the full
    per-path line and failed to compile above ~16 MiB (ADVICE r2
    medium).  Tiles are software-pipelined on parity: tile t+1's loads
    are issued before tile t's store is waited, so HBM loads overlap the
    VPU add + store instead of serializing the whole round trip.
    ``b_src=None`` is a plain tiled copy."""
    tiles = [(c0, min(tile_c, cols - c0)) for c0 in range(0, cols, tile_c)]
    n = len(tiles)

    def start_loads(t):
        c0, cw = tiles[t]
        s = t % 2
        cpa = pltpu.make_async_copy(a_src.at[..., pl.ds(c0, cw)],
                                    va.at[s].at[..., pl.ds(0, cw)], copy_sem)
        cpa.start()
        cpb = None
        if b_src is not None:
            cpb = pltpu.make_async_copy(b_src.at[..., pl.ds(c0, cw)],
                                        vb.at[s].at[..., pl.ds(0, cw)],
                                        copy_sem)
            cpb.start()
        return cpa, cpb

    stores = [None, None]  # in-flight store per buffer parity
    pend = start_loads(0)
    for t, (c0, cw) in enumerate(tiles):
        s = t % 2
        cpa, cpb = pend
        cpa.wait()
        if cpb is not None:
            cpb.wait()
            va[s, ..., :cw] = va[s, ..., :cw] + vb[s, ..., :cw]
        if t + 1 < n:
            # Buffer (t+1)%2 was last read by tile t-1's store: drain it
            # before overwriting, then overlap the loads with OUR store.
            if stores[(t + 1) % 2] is not None:
                stores[(t + 1) % 2].wait()
                stores[(t + 1) % 2] = None
            pend = start_loads(t + 1)
        cpo = pltpu.make_async_copy(va.at[s].at[..., pl.ds(0, cw)],
                                    dst.at[..., pl.ds(c0, cw)], copy_sem)
        cpo.start()
        stores[s] = cpo
    for cp in stores:
        if cp is not None:
            cp.wait()


def _torus2d_rs_kernel(x_hbm, out_ref, line_acc, line_recv, slot_acc,
                       slot_recv, work_buf, va, vb, send_sem, recv_sem,
                       credit_sem, copy_sem, *, ax, ay, wx, wy, halves,
                       tile_c):
    # line_acc..work_buf are ANY-space OUTPUTS used as HBM scratch (the
    # interpreter's DMA model requires one side of a local copy to be an
    # input or output buffer; true ANY scratch would trip it).
    """Fused 2D torus ReduceScatter, four concurrent paths on row-quarters.

    Input ``x_hbm`` [wx, wy, R, C]: this device's partial for every slot.
    Output ``out_ref`` [R, C]: my slot (i, j), summed over all wx*wy
    devices.  ``halves``: the path tuples (row_offset, row_len,
    first_axis, direction) — four quarters with the same flavor set as
    the AG kernel (x→y ±, y→x ±), so ALL FOUR link directions reduce
    concurrently in both phases.  The paths' steps are interleaved in ONE
    loop per phase (start every path's remote DMA, then wait them all) —
    that concurrency is the point of the fused kernel.

    Memory layout (round 3): every line/slot buffer lives in HBM(ANY);
    VMEM holds only two [lmax, ln_max, tile_c] fold tiles (_fold_tiles),
    so the kernel compiles at arbitrarily large partials — the round-2
    all-VMEM layout blew the ~16 MiB Mosaic scoped-VMEM limit at its own
    documented target shapes (ADVICE r2 medium).  Remote DMAs move
    HBM→HBM, exactly like the a2a kernel's segments.

    Phase-1 ring item for path A = the x-line group {slots (i, j'') for all
    j''} = [wy, ln, C]; after wx-1 steps device (i, j) holds line (i, *)
    summed over its ax-ring (devices (i', j)).  Phase 2 rings the [ln, C]
    slots of that line along ay, finishing the global sum.  Path B mirrors
    with axes swapped.  Flow control mirrors the 1-D ring RS: a credit
    semaphore per (path, phase) stops a sender overwriting a landing buffer
    the receiver has not folded yet.
    """
    i = jax.lax.axis_index(ax)
    j = jax.lax.axis_index(ay)
    cols = x_hbm.shape[-1]

    dl.barrier_all(ax)
    dl.barrier_all(ay)

    def coords(first):
        """(my ring coord, ring size, ring axis) for phase 1 and phase 2,
        plus the LINE length (number of slots the phase-1 item holds)."""
        if first == "x":
            return (i, wx, ax), (j, wy, ay), wy
        return (j, wy, ay), (i, wx, ax), wx

    def load_line(first, off, ln, idx, dst):
        """dst <- my partial for line group ``idx``: x-path lines are
        x_hbm[idx, :, off:off+ln] ([wy, ln, C]); y-path x_hbm[:, idx, ...]
        ([wx, ln, C]).  Scalar indexing squeezes the ring dim."""
        if first == "x":
            src = x_hbm.at[idx, :, pl.ds(off, ln)]
        else:
            src = x_hbm.at[:, idx, pl.ds(off, ln)]
        cp = pltpu.make_async_copy(src, dst, copy_sem)
        cp.start()
        cp.wait()

    # ------------------------------------------------------------------
    # Phase 1: ring-RS of first-axis line groups, paths interleaved.
    # ------------------------------------------------------------------
    n1 = max(wx, wy) - 1

    def step1(s, _):
        for p, (off, ln, first, d) in enumerate(halves):
            if ln == 0:
                continue
            (my1, w1, a1), _, nline = coords(first)
            peer = jax.lax.rem(my1 + d + w1, w1)
            prev = jax.lax.rem(my1 - d + w1, w1)

            @pl.when(s < w1 - 1)
            def _(p=p, off=off, ln=ln, first=first, d=d, my1=my1, w1=w1,
                  a1=a1, nline=nline, peer=peer, prev=prev):
                # Outgoing line group at step s: (my1 - d*(1+s)) mod w1.
                idx = jax.lax.rem(my1 - d * (1 + s) + (1 + s) * w1 + w1, w1)
                load_line(first, off, ln, idx,
                          work_buf.at[p, pl.ds(0, nline), pl.ds(0, ln)])

                @pl.when(s == 0)
                def _():
                    _fold_tiles(line_acc.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                                work_buf.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                                None,
                                va.at[:, pl.ds(0, nline), pl.ds(0, ln)],
                                vb.at[:, pl.ds(0, nline), pl.ds(0, ln)],
                                copy_sem, cols=cols, tile_c=tile_c)

                @pl.when(s > 0)
                def _():
                    _fold_tiles(line_acc.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                                work_buf.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                                line_recv.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                                va.at[:, pl.ds(0, nline), pl.ds(0, ln)],
                                vb.at[:, pl.ds(0, nline), pl.ds(0, ln)],
                                copy_sem, cols=cols, tile_c=tile_c)
                    # recv consumed → give the upstream sender its credit.
                    pltpu.semaphore_signal(
                        credit_sem.at[p, 0], inc=1, device_id={a1: prev},
                        device_id_type=pltpu.DeviceIdType.MESH)

                @pl.when(s > 0)
                def _():
                    pltpu.semaphore_wait(credit_sem.at[p, 0], 1)

                dl.remote_copy(line_acc.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                               line_recv.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                               send_sem.at[p, 0], recv_sem.at[p, 0],
                               a1, peer).start()
        for p, (off, ln, first, d) in enumerate(halves):
            if ln == 0:
                continue
            (my1, w1, a1), _, nline = coords(first)

            @pl.when(s < w1 - 1)
            def _(p=p, ln=ln, nline=nline):
                blk = line_acc.at[p, pl.ds(0, nline), pl.ds(0, ln)]
                pltpu.make_async_copy(blk, blk, send_sem.at[p, 0]).wait()
                pltpu.make_async_copy(blk, blk, recv_sem.at[p, 0]).wait()
        return 0

    jax.lax.fori_loop(0, n1, step1, 0)

    # Final phase-1 fold: the last arrival is the partial for MY line.
    for p, (off, ln, first, d) in enumerate(halves):
        if ln == 0:
            continue
        (my1, w1, a1), _, nline = coords(first)
        load_line(first, off, ln, my1,
                  work_buf.at[p, pl.ds(0, nline), pl.ds(0, ln)])
        _fold_tiles(line_acc.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                    work_buf.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                    line_recv.at[p, pl.ds(0, nline), pl.ds(0, ln)],
                    va.at[:, pl.ds(0, nline), pl.ds(0, ln)],
                    vb.at[:, pl.ds(0, nline), pl.ds(0, ln)],
                    copy_sem, cols=cols, tile_c=tile_c)

    # ------------------------------------------------------------------
    # Phase 2: ring-RS of the slots within my reduced line, interleaved.
    # Slot index within the line = my second-axis ring coordinate.
    # ------------------------------------------------------------------
    def step2(t, _):
        for p, (off, ln, first, d) in enumerate(halves):
            if ln == 0:
                continue
            _, (my2, w2, a2), _ = coords(first)
            peer = jax.lax.rem(my2 + d + w2, w2)
            prev = jax.lax.rem(my2 - d + w2, w2)

            @pl.when(t < w2 - 1)
            def _(p=p, ln=ln, my2=my2, w2=w2, a2=a2, d=d, peer=peer,
                  prev=prev):
                idx = jax.lax.rem(my2 - d * (1 + t) + (1 + t) * w2 + w2, w2)

                @pl.when(t == 0)
                def _():
                    _fold_tiles(slot_acc.at[p, :, pl.ds(0, ln)],
                                line_acc.at[p, pl.ds(idx, 1), pl.ds(0, ln)],
                                None,
                                va.at[:, pl.ds(0, 1), pl.ds(0, ln)],
                                vb.at[:, pl.ds(0, 1), pl.ds(0, ln)],
                                copy_sem, cols=cols, tile_c=tile_c)

                @pl.when(t > 0)
                def _():
                    _fold_tiles(slot_acc.at[p, :, pl.ds(0, ln)],
                                line_acc.at[p, pl.ds(idx, 1), pl.ds(0, ln)],
                                slot_recv.at[p, :, pl.ds(0, ln)],
                                va.at[:, pl.ds(0, 1), pl.ds(0, ln)],
                                vb.at[:, pl.ds(0, 1), pl.ds(0, ln)],
                                copy_sem, cols=cols, tile_c=tile_c)
                    pltpu.semaphore_signal(
                        credit_sem.at[p, 1], inc=1, device_id={a2: prev},
                        device_id_type=pltpu.DeviceIdType.MESH)

                @pl.when(t > 0)
                def _():
                    pltpu.semaphore_wait(credit_sem.at[p, 1], 1)

                dl.remote_copy(slot_acc.at[p, :, pl.ds(0, ln)],
                               slot_recv.at[p, :, pl.ds(0, ln)],
                               send_sem.at[p, 1], recv_sem.at[p, 1],
                               a2, peer).start()
        for p, (off, ln, first, d) in enumerate(halves):
            if ln == 0:
                continue
            _, (my2, w2, a2), _ = coords(first)

            @pl.when(t < w2 - 1)
            def _(p=p, ln=ln):
                blk = slot_acc.at[p, :, pl.ds(0, ln)]
                pltpu.make_async_copy(blk, blk, send_sem.at[p, 1]).wait()
                pltpu.make_async_copy(blk, blk, recv_sem.at[p, 1]).wait()
        return 0

    jax.lax.fori_loop(0, max(wx, wy) - 1, step2, 0)

    for p, (off, ln, first, d) in enumerate(halves):
        if ln == 0:
            continue
        _, (my2, w2, a2), _ = coords(first)
        _fold_tiles(out_ref.at[pl.ds(off, ln)],
                    line_acc.at[p, pl.ds(my2, 1), pl.ds(0, ln)].at[0],
                    slot_recv.at[p, :, pl.ds(0, ln)].at[0],
                    va.at[:, 0, pl.ds(0, ln)], vb.at[:, 0, pl.ds(0, ln)],
                    copy_sem, cols=cols, tile_c=tile_c)


def _split_rs_quarters(rows: int):
    """Four (offset, len, first_axis, direction) paths for the fused RS —
    the same flavor set as the AG quarters: x→y and y→x orders, each
    bidirectional, so all four link directions reduce concurrently."""
    return tuple(
        (off, ln, first, d)
        for (off, ln), (first, d) in zip(_split_quarters(rows),
                                         _QUARTER_FLAVORS))


def _torus2d_rs(x_shard, *, ax, ay, wx, wy, interpret, collective_id):
    wxy = wx * wy
    assert x_shard.shape[0] % wxy == 0, (x_shard.shape, wx, wy)
    rows = x_shard.shape[0] // wxy
    orig_trailing = x_shard.shape[1:]
    x4 = x_shard.reshape(wx, wy, rows, -1)
    cols = x4.shape[-1]
    halves = _split_rs_quarters(rows)
    n_paths = len(halves)
    lmax = max(wx, wy)
    ln_max = max(ln for _, ln, _, _ in halves)
    itemsize = jnp.dtype(x4.dtype).itemsize
    # VMEM = two fold tiles [lmax, ln_max, tile_c]; size tile_c to the
    # budget (line buffers themselves live in HBM — see kernel docstring).
    budget = 10 * 2 ** 20
    tile_c = max(budget // max(4 * lmax * ln_max * itemsize, 1), 1)
    tile_c = min(cols, max(128 * (tile_c // 128), min(cols, 128)))
    if 4 * lmax * ln_max * tile_c * itemsize > 2 * budget:
        # Even one 128-column tile over budget (enormous rows): compose
        # the per-axis ring RS kernels sequentially — correct at any
        # shape, loses the four-path fusion.
        from triton_dist_tpu.kernels.reduce_scatter import (
            ReduceScatterMethod,
            reduce_scatter_shard,
        )

        x = reduce_scatter_shard(x_shard, ax,
                                 method=ReduceScatterMethod.AUTO,
                                 interpret=interpret,
                                 collective_id=collective_id)
        # Distinct reserved id: the 3-axis path already used
        # TORUS_RS_THIRD for its first leg in this same program.
        return reduce_scatter_shard(x, ay,
                                    method=ReduceScatterMethod.AUTO,
                                    interpret=interpret,
                                    collective_id=cid.TORUS_RS_FALLBACK)
    line_shape = jax.ShapeDtypeStruct((n_paths, lmax, ln_max, cols),
                                      x4.dtype)
    slot_shape = jax.ShapeDtypeStruct((n_paths, 1, ln_max, cols), x4.dtype)
    out, *_hbm_scratch = pl.pallas_call(
        functools.partial(_torus2d_rs_kernel, ax=ax, ay=ay, wx=wx, wy=wy,
                          halves=halves, tile_c=tile_c),
        out_shape=[jax.ShapeDtypeStruct((rows, cols), x4.dtype),
                   line_shape, line_shape,     # line_acc / line_recv
                   slot_shape, slot_shape,     # slot_acc / slot_recv
                   line_shape],                # work_buf
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 6,
        scratch_shapes=[
            pltpu.VMEM((2, lmax, ln_max, tile_c), x4.dtype),     # fold tiles a
            pltpu.VMEM((2, lmax, ln_max, tile_c), x4.dtype),     # fold tiles b
            pltpu.SemaphoreType.DMA((n_paths, 2)),          # send per path
            pltpu.SemaphoreType.DMA((n_paths, 2)),          # recv per path
            pltpu.SemaphoreType.REGULAR((n_paths, 2)),      # credits
            pltpu.SemaphoreType.DMA,                        # copy
        ],
        compiler_params=dl.collective_compiler_params(wxy, collective_id),
        interpret=maybe_interpret(interpret),
    )(x4)
    return out.reshape((rows,) + orig_trailing)


def torus_reduce_scatter_shard(x_shard, axes, *, interpret=False,
                               collective_id=cid.TORUS_RS):
    """ReduceScatter over a 2- or 3-axis torus; call inside shard_map.

    Input: this device's [W*rows, ...] partial (W = product of axes sizes),
    flat ``axes``-major like :func:`torus_all_gather_shard`'s output.
    Output: this device's fully-summed [rows, ...] band — matching
    ``lax.psum_scatter(tiled=True)`` over the joint axes.

    2 axes → the fused four-quarter kernel (x→y and y→x reduction
    orders, each bidirectional: all four link directions busy).  3 axes →
    the bidirectional ring RS on ``axes[0]`` first (reductions SHRINK
    data: do the plane-fold heavier axis first), then the fused 2D plane.
    """
    from triton_dist_tpu.kernels.reduce_scatter import (
        ReduceScatterMethod,
        reduce_scatter_shard,
    )

    axes = tuple(axes)
    if len(axes) == 1:
        return reduce_scatter_shard(x_shard, axes[0],
                                    method=ReduceScatterMethod.AUTO,
                                    interpret=interpret,
                                    collective_id=collective_id)
    if len(axes) == 3:
        x = reduce_scatter_shard(x_shard, axes[0],
                                 method=ReduceScatterMethod.AUTO,
                                 interpret=interpret,
                                 collective_id=cid.TORUS_RS_THIRD)
        return torus_reduce_scatter_shard(x, axes[1:], interpret=interpret,
                                          collective_id=collective_id)
    if len(axes) != 2:
        raise ValueError(f"torus_reduce_scatter_shard supports 1-3 axes, "
                         f"got {axes}")
    ax, ay = axes
    wx = jax.lax.axis_size(ax)
    wy = jax.lax.axis_size(ay)
    if wx * wy == 1:
        return x_shard
    if wx == 1 or wy == 1:
        axis = ax if wx > 1 else ay
        return reduce_scatter_shard(x_shard, axis,
                                    method=ReduceScatterMethod.AUTO,
                                    interpret=interpret,
                                    collective_id=collective_id)
    return _torus2d_rs(x_shard, ax=ax, ay=ay, wx=wx, wy=wy,
                       interpret=interpret, collective_id=collective_id)
