"""Torus-native multi-axis collectives: concurrent per-axis ring schedules.

Reference analog: the topology-specialized AllGather variants of
``python/triton_dist/kernels/nvidia/allgather.py`` — the NUMA-aware 2D ring
(:194-258) and the inter-node 2D/3D variants (:470-591; push-3D
warp-specialized AG, low_latency_allgather.py:570-607).  The reference earns
its performance by matching the schedule to the fabric; on TPU the fabric is
a 2D/3D ICI torus, and the matching schedule is *concurrent bidirectional
rings on every axis*.

Why not compose per-axis kernels (``hierarchical.py``)?  Composition is
sequential: during the axis-0 phase every axis-1 link idles and vice versa —
on a torus whose axes have equal bandwidth that wastes half (2D) or two
thirds (3D) of the injection bandwidth.  The fused kernel here keeps every
link direction busy in every phase:

* The shard is split into ``2 * n_axes`` contiguous **parts** (quarters on
  a 2D torus, sixths on 3D), each assigned a path flavor
  ``(cyclic axis order, direction)``: 2D = x→y ±, y→x ±; 3D = x→y→z ±,
  y→z→x ±, z→x→y ±.
* Phase ``p``: each part rings what it has gathered so far along axis
  ``order[p]`` in its direction — at any moment the 4 (2D) or 6 (3D)
  concurrent streams ride every (axis, direction) link of the torus.
* After phase ``p`` a part holds the full ``order[:p+1]`` sub-torus of its
  slice; after the last phase, the whole torus.

Per-(path, phase) DMA semaphore pairs keep the byte accounting of the
streams and phases independent (a fast path may enter phase ``p+1`` while a
neighbor still drains phase ``p``; distinct semaphores make the early
arrival invisible to the neighbor's phase-``p`` waits).

Expected bandwidth: one bidirectional ring saturates 2 of a torus's 2n link
directions; this schedule drives all 2n → ~n× the 1-axis bidir ring (~2x on
2D, ~3x on 3D — ``perf_model.estimate_torus_allgather_time_ms``).

Output order: flat ``axes``-major (axes[0] slowest), matching
``hierarchical.hier_all_gather_shard`` — the two are drop-in replacements
for each other (ICI-only mesh → this module; ICI×DCN → hierarchical, where
sequencing is *correct* because the slow wire must move the minimum bytes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels import collective_ids as cid
from triton_dist_tpu.language.interpret import maybe_interpret

__all__ = ["torus_all_gather_shard", "torus_reduce_scatter_shard"]

_LBL = ("x", "y", "z")  # internal storage-order labels for up to 3 axes


def _split_parts(rows: int, k: int):
    """Split ``rows`` into ``k`` contiguous (offset, length) parts; lengths
    may be 0 for tiny shards (those path flavors simply do not run)."""
    base, rem = divmod(rows, k)
    lens = [base + (1 if q < rem else 0) for q in range(k)]
    offs, o = [], 0
    for ln in lens:
        offs.append(o)
        o += ln
    return list(zip(offs, lens))


def _path_flavors(n: int):
    """``2n`` (cyclic axis order, direction) flavors: every (axis, dir)
    link of the torus is the phase-p ring of exactly one path, for every
    phase p."""
    orders = [tuple(_LBL[(s + t) % n] for t in range(n)) for s in range(n)]
    return tuple((order, d) for order in orders for d in (1, -1))


def free_slot_count(order, sizes_by_lbl, l):
    """Free-slot count after phase ``l`` of a cyclic-order path: the
    product of the pending axes' sizes.  Used by the fused torus GEMM-RS
    kernel and its host's buffer sizing (gemm_reduce_scatter.py) — one
    rule, one place for THAT pair.  The torus RS kernel here does NOT
    call it: ``_torus_rs_kernel`` folds over full-rank group dims
    instead of shrinking per phase."""
    g = 1
    for a in order[l + 1:]:
        g *= sizes_by_lbl[a]
    return g


def _paths_for(rows: int, n: int):
    return tuple((off, ln, order, d)
                 for (off, ln), (order, d) in zip(_split_parts(rows, 2 * n),
                                                  _path_flavors(n)))


# ---------------------------------------------------------------------------
# AllGather
# ---------------------------------------------------------------------------


def _torus_ag_kernel(x_ref, out_ref, send_sem, recv_sem, copy_sem,
                     *, axis_names, sizes, paths):
    """Fused 2D/3D torus AllGather.  ``out_ref`` is [*sizes, R, C]; slot
    (i, j[, k]) is that device's shard.  ``paths``: 2n tuples
    (row_offset, row_len, cyclic axis order, direction).

    Phase p forwards, for each path, the ``order[:p]`` sub-torus gathered
    so far along axis ``order[p]``: e.g. a 3D x→y→z path rings its sixth's
    slots on x±, then the gathered x-lines on y±, then the (x, y)-planes
    on z±.  ``send_sem``/``recv_sem`` are [2n, n] DMA semaphore arrays
    indexed (path, phase).
    """
    n = len(axis_names)
    lbls = _LBL[:n]
    coords = {l: jax.lax.axis_index(a) for l, a in zip(lbls, axis_names)}
    size = dict(zip(lbls, sizes))
    mesh_ax = dict(zip(lbls, axis_names))

    # Stage my slot, then make sure every device in the torus entered the
    # kernel before any remote DMA (barrier_all contract; the per-axis
    # barrier chain is transitive across axes).
    own = tuple(coords[l] for l in lbls)
    cp = pltpu.make_async_copy(x_ref, out_ref.at[own], copy_sem)
    cp.start()
    cp.wait()
    for a in axis_names:
        dl.barrier_all(a)

    def blk_ref(order, d, off, ln, p, s):
        """The block path (order, d) forwards at phase p step s: ring-axis
        index (my - d*s), gathered axes full, pending axes at my coords."""
        r = order[p]
        w = size[r]
        idx = jax.lax.rem(coords[r] - d * s + s * w + w, w)
        sel = tuple(
            idx if l == r else (slice(None) if l in order[:p] else coords[l])
            for l in lbls)
        return out_ref.at[sel + (pl.ds(off, ln),)]

    def run_phase(p):
        active = [(q, pa) for q, pa in enumerate(paths) if pa[1] > 0]
        if not active:
            return
        n_max = max(size[pa[2][p]] for _, pa in active) - 1

        def step(s, _):
            # Start every active path's DMA first (concurrency), then
            # wait them all (descriptor trick on the same-shaped block).
            for q, (off, ln, order, d) in active:
                r = order[p]
                w = size[r]
                peer = jax.lax.rem(coords[r] + d + w, w)

                @pl.when(s < w - 1)
                def _(q=q, off=off, ln=ln, order=order, d=d, r=r, peer=peer):
                    blk = blk_ref(order, d, off, ln, p, s)
                    dl.remote_copy(blk, blk, send_sem.at[q, p],
                                   recv_sem.at[q, p], mesh_ax[r],
                                   peer).start()
            for q, (off, ln, order, d) in active:
                w = size[order[p]]

                @pl.when(s < w - 1)
                def _(q=q, off=off, ln=ln, order=order, d=d):
                    blk = blk_ref(order, d, off, ln, p, s)
                    pltpu.make_async_copy(blk, blk,
                                          send_sem.at[q, p]).wait()
                    pltpu.make_async_copy(blk, blk,
                                          recv_sem.at[q, p]).wait()
            return 0

        if n_max > 0:
            jax.lax.fori_loop(0, n_max, step, 0)

    for p in range(n):
        run_phase(p)


def _torus_ag(x_shard, *, axis_names, sizes, interpret, collective_id):
    n = len(axis_names)
    rows = x_shard.shape[0]
    orig_shape = x_shard.shape
    x2 = x_shard.reshape(rows, -1)
    cols = x2.shape[1]
    paths = _paths_for(rows, n)
    world = 1
    for w in sizes:
        world *= w
    out = pl.pallas_call(
        functools.partial(_torus_ag_kernel, axis_names=axis_names,
                          sizes=sizes, paths=paths),
        out_shape=jax.ShapeDtypeStruct(tuple(sizes) + (rows, cols),
                                       x2.dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2 * n, n)),
                        pltpu.SemaphoreType.DMA((2 * n, n)),
                        pltpu.SemaphoreType.DMA],
        compiler_params=dl.collective_compiler_params(world, collective_id),
        interpret=maybe_interpret(interpret),
    )(x2)
    return out.reshape((world * rows,) + orig_shape[1:])


def torus_all_gather_shard(x_shard, axes, *, interpret=False,
                           collective_id=cid.TORUS_AG):
    """AllGather a shard over a 2- or 3-axis ICI torus; call inside
    shard_map.  Output is flat ``axes``-major (axes[0] slowest), i.e. the
    row block of flat rank ``r`` is the shard of the device whose axes
    coordinates spell ``r`` in mixed radix — the same order
    ``lax.all_gather`` over the joint axes and ``hier_all_gather_shard``
    produce.

    2 axes → the fused four-path kernel; 3 axes → the fused SIX-path
    kernel (x→y→z / y→z→x / z→x→y cyclic orders, each bidirectional): all
    2n ICI link directions busy in every phase.  Size-1 axes are dropped;
    a single real axis falls back to the 1-axis ring dispatch.
    """
    from triton_dist_tpu.kernels.allgather import (
        AllGatherMethod,
        all_gather_shard,
    )

    axes = tuple(axes)
    if len(axes) > 3:
        raise ValueError(f"torus_all_gather_shard supports 1-3 axes, "
                         f"got {axes}")
    sizes = {a: jax.lax.axis_size(a) for a in axes}
    # Gathering over a size-1 axis is the identity: drop degenerate axes
    # (the flat axes-major output order is unaffected).
    real = tuple(a for a in axes if sizes[a] > 1)
    if not real:
        return x_shard
    if len(real) == 1:
        return all_gather_shard(x_shard, real[0],
                                method=AllGatherMethod.AUTO,
                                interpret=interpret,
                                collective_id=collective_id)
    return _torus_ag(x_shard, axis_names=real,
                     sizes=tuple(sizes[a] for a in real),
                     interpret=interpret, collective_id=collective_id)


# ---------------------------------------------------------------------------
# ReduceScatter
# ---------------------------------------------------------------------------


def _fold_tiles(dst, a_src, b_src, va, vb, load_sem, store_sem, *, cols,
                tile_c):
    """dst <- a_src + b_src, streamed through VMEM in column tiles.

    All three operands are HBM(ANY) refs of identical shape [..., cols];
    ``va``/``vb`` are VMEM tiles with a leading DOUBLE-BUFFER dim [2] and
    ``tile_c`` columns.  Staging through VMEM keeps the kernel's
    scoped-VMEM need at four tiles regardless of the line-buffer size —
    the all-VMEM round-2 layout needed ~3x the full per-path line and
    failed to compile above ~16 MiB (ADVICE r2 medium).  Tiles are
    software-pipelined on parity: tile t+1's loads are issued before tile
    t's store is waited, so HBM loads overlap the VPU add + store instead
    of serializing the whole round trip.  Loads and stores use SEPARATE
    semaphores: they move identical byte counts, so on one shared
    semaphore a load's wait could be satisfied by the concurrent store's
    completion while the load is still in flight (stale-tile reads).
    ``b_src=None`` is a plain tiled copy."""
    tiles = [(c0, min(tile_c, cols - c0)) for c0 in range(0, cols, tile_c)]
    n = len(tiles)

    def start_loads(t):
        c0, cw = tiles[t]
        s = t % 2
        cpa = pltpu.make_async_copy(a_src.at[..., pl.ds(c0, cw)],
                                    va.at[s].at[..., pl.ds(0, cw)], load_sem)
        cpa.start()
        cpb = None
        if b_src is not None:
            cpb = pltpu.make_async_copy(b_src.at[..., pl.ds(c0, cw)],
                                        vb.at[s].at[..., pl.ds(0, cw)],
                                        load_sem)
            cpb.start()
        return cpa, cpb

    stores = [None, None]  # in-flight store per buffer parity
    pend = start_loads(0)
    for t, (c0, cw) in enumerate(tiles):
        s = t % 2
        cpa, cpb = pend
        cpa.wait()
        if cpb is not None:
            cpb.wait()
            va[s, ..., :cw] = va[s, ..., :cw] + vb[s, ..., :cw]
        if t + 1 < n:
            # Buffer (t+1)%2 was last read by tile t-1's store: drain it
            # before overwriting, then overlap the loads with OUR store.
            if stores[(t + 1) % 2] is not None:
                stores[(t + 1) % 2].wait()
                stores[(t + 1) % 2] = None
            pend = start_loads(t + 1)
        cpo = pltpu.make_async_copy(va.at[s].at[..., pl.ds(0, cw)],
                                    dst.at[..., pl.ds(c0, cw)], store_sem)
        cpo.start()
        stores[s] = cpo
    for cp in stores:
        if cp is not None:
            cp.wait()


def _torus_rs_kernel(x_hbm, out_ref, *bufs_and_sems, axis_names, sizes,
                     paths, tile_c):
    """Fused 2D/3D torus ReduceScatter, 2n concurrent paths on row parts.

    Input ``x_hbm`` [*sizes, R, C]: this device's partial for every slot.
    Output ``out_ref`` [R, C]: my slot, summed over all devices.
    ``paths``: the (row_offset, row_len, cyclic axis order, direction)
    tuples — the same flavor set as the AG kernel, so ALL 2n link
    directions reduce concurrently in every phase.  The paths' steps are
    interleaved in ONE loop per phase (start every path's remote DMA,
    then wait them all) — that concurrency is the point of the fused
    kernel.

    Phase ``l`` ring-reduces, along axis ``order[l]``, the groups of
    slots spanning the not-yet-reduced axes ``order[l+1:]``: a 3D x→y→z
    path rings (y, z)-plane groups on x±, then z-line groups on y±, then
    single slots on z±; after phase ``l`` the device holds its
    ``order[:l+1]``-coordinates' group summed over the reduced sub-torus.
    Flow control mirrors the 1-D ring RS: a credit semaphore per
    (path, phase) stops a sender overwriting a landing buffer the
    receiver has not folded yet.

    Memory layout (round 3): per-level acc/recv buffers and the load
    staging buffer live in HBM — they are ANY-space OUTPUTS, because the
    interpreter's DMA model requires one side of a local copy to be an
    input or output buffer — with full-rank group dims (consumed axes
    kept at extent 1, ``pl.ds`` slicing).  VMEM holds only the
    double-buffered fold tiles (_fold_tiles), so the kernel compiles at
    arbitrarily large partials — the round-2 all-VMEM layout blew the
    ~16 MiB Mosaic scoped-VMEM limit at its own documented target shapes
    (ADVICE r2 medium).  Remote DMAs move HBM→HBM, exactly like the a2a
    kernel's segments.
    """
    n = len(axis_names)
    # bufs: acc[0..n-1], rcv[0..n-1], work; scratch: va, vb, send, recv,
    # credit, copy.
    accs = bufs_and_sems[:n]
    rcvs = bufs_and_sems[n:2 * n]
    work = bufs_and_sems[2 * n]
    (va, vb, send_sem, recv_sem, credit_sem, copy_sem,
     store_sem) = bufs_and_sems[2 * n + 1:]
    lbls = _LBL[:n]
    coords = {l: jax.lax.axis_index(a) for l, a in zip(lbls, axis_names)}
    size = dict(zip(lbls, sizes))
    mesh_ax = dict(zip(lbls, axis_names))
    cols = x_hbm.shape[-1]

    for a in axis_names:
        dl.barrier_all(a)

    def group_sel(order, l, ring_idx_ds):
        """Index tuple over the n group dims for the phase-l item whose
        ring index slice is ``ring_idx_ds``: consumed axes pinned to
        extent 1, pending axes full extent."""
        r = order[l]
        sel = []
        for lbl in lbls:
            if lbl == r:
                sel.append(ring_idx_ds)
            elif lbl in order[:l]:
                sel.append(pl.ds(0, 1))
            else:
                sel.append(pl.ds(0, size[lbl]))
        return tuple(sel)

    def src_ref(q, order, off, ln, l, idx):
        """The phase-l input group at ring index ``idx``: the raw input
        for l=0, else the previous level's accumulator."""
        if l == 0:
            sel = tuple(pl.ds(idx, 1) if lbl == order[0]
                        else slice(None) for lbl in lbls)
            return x_hbm.at[sel + (pl.ds(off, ln),)]
        return accs[l - 1].at[(q,) + group_sel(order, l, pl.ds(idx, 1))
                              + (pl.ds(0, ln),)]

    def acc_sel(q, order, l, ln):
        return (q,) + group_sel(order, l, pl.ds(0, 1)) + (pl.ds(0, ln),)

    def va_sel(order, l, ln):
        return (slice(None),) + group_sel(order, l, pl.ds(0, 1)) \
            + (pl.ds(0, ln),)

    def run_phase(l):
        active = [(q, pa) for q, pa in enumerate(paths) if pa[1] > 0]
        if not active:
            return
        n_max = max(size[pa[2][l]] for _, pa in active) - 1

        def step(s, _):
            for q, (off, ln, order, d) in active:
                r = order[l]
                w = size[r]
                my = coords[r]
                peer = jax.lax.rem(my + d + w, w)
                prev = jax.lax.rem(my - d + w, w)

                @pl.when(s < w - 1)
                def _(q=q, off=off, ln=ln, order=order, d=d, r=r, w=w,
                      my=my, peer=peer, prev=prev):
                    # Outgoing group at step s: (my - d*(1+s)) mod w.
                    idx = jax.lax.rem(my - d * (1 + s) + (1 + s) * w + w, w)
                    wsel = (q,) + group_sel(order, l, pl.ds(0, 1)) \
                        + (pl.ds(0, ln),)
                    ld = pltpu.make_async_copy(
                        src_ref(q, order, off, ln, l, idx), work.at[wsel],
                        copy_sem)
                    ld.start()
                    ld.wait()

                    @pl.when(s == 0)
                    def _():
                        _fold_tiles(accs[l].at[acc_sel(q, order, l, ln)],
                                    work.at[wsel], None,
                                    va.at[va_sel(order, l, ln)],
                                    vb.at[va_sel(order, l, ln)],
                                    copy_sem, store_sem, cols=cols, tile_c=tile_c)

                    @pl.when(s > 0)
                    def _():
                        _fold_tiles(accs[l].at[acc_sel(q, order, l, ln)],
                                    work.at[wsel],
                                    rcvs[l].at[acc_sel(q, order, l, ln)],
                                    va.at[va_sel(order, l, ln)],
                                    vb.at[va_sel(order, l, ln)],
                                    copy_sem, store_sem, cols=cols, tile_c=tile_c)
                        # recv consumed → upstream sender gets its credit.
                        pltpu.semaphore_signal(
                            credit_sem.at[q, l], inc=1, device_id={
                                mesh_ax[r]: prev},
                            device_id_type=pltpu.DeviceIdType.MESH)

                    @pl.when(s > 0)
                    def _():
                        pltpu.semaphore_wait(credit_sem.at[q, l], 1)

                    dl.remote_copy(accs[l].at[acc_sel(q, order, l, ln)],
                                   rcvs[l].at[acc_sel(q, order, l, ln)],
                                   send_sem.at[q, l], recv_sem.at[q, l],
                                   mesh_ax[r], peer).start()
            for q, (off, ln, order, d) in active:
                w = size[order[l]]

                @pl.when(s < w - 1)
                def _(q=q, ln=ln, order=order):
                    blk = accs[l].at[acc_sel(q, order, l, ln)]
                    pltpu.make_async_copy(blk, blk,
                                          send_sem.at[q, l]).wait()
                    pltpu.make_async_copy(blk, blk,
                                          recv_sem.at[q, l]).wait()
            return 0

        if n_max > 0:
            jax.lax.fori_loop(0, n_max, step, 0)

        # Final fold: the last arrival is the partial for MY group.
        for q, (off, ln, order, d) in active:
            r = order[l]
            my = coords[r]
            wsel = (q,) + group_sel(order, l, pl.ds(0, 1)) + (pl.ds(0, ln),)
            ld = pltpu.make_async_copy(src_ref(q, order, off, ln, l, my),
                                       work.at[wsel], copy_sem)
            ld.start()
            ld.wait()
            _fold_tiles(accs[l].at[acc_sel(q, order, l, ln)],
                        work.at[wsel], rcvs[l].at[acc_sel(q, order, l, ln)],
                        va.at[va_sel(order, l, ln)],
                        vb.at[va_sel(order, l, ln)],
                        copy_sem, store_sem, cols=cols, tile_c=tile_c)

    for l in range(n):
        run_phase(l)

    # My band: the last level's accumulator, squeezed of its unit dims.
    for q, (off, ln, order, d) in enumerate(paths):
        if ln == 0:
            continue
        src = accs[n - 1].at[(q,) + (0,) * n + (pl.ds(0, ln),)]
        cp = pltpu.make_async_copy(src, out_ref.at[pl.ds(off, ln)],
                                   copy_sem)
        cp.start()
        cp.wait()


def _torus_rs(x_shard, *, axis_names, sizes, interpret, collective_id):
    n = len(axis_names)
    world = 1
    for w in sizes:
        world *= w
    assert x_shard.shape[0] % world == 0, (x_shard.shape, sizes)
    rows = x_shard.shape[0] // world
    orig_trailing = x_shard.shape[1:]
    xnd = x_shard.reshape(tuple(sizes) + (rows, -1))
    cols = xnd.shape[-1]
    paths = _paths_for(rows, n)
    ln_max = max((ln for _, ln, _, _ in paths), default=0)
    itemsize = jnp.dtype(xnd.dtype).itemsize
    # VMEM = four fold tiles whose group dims span the whole slot grid
    # (consumed dims are ds(0,1)-sliced); size tile_c to the budget.
    budget = 10 * 2 ** 20
    cells = world
    tile_c = max(budget // max(4 * cells * ln_max * itemsize, 1), 1)
    tile_c = min(cols, max(128 * (tile_c // 128), min(cols, 128)))
    # Mosaic's scoped-VMEM compile ceiling is ~16 MiB per kernel
    # invocation (the round-2 failure the HBM-staged rewrite fixed).
    # tile_c is normally sized inside ``budget``, but line above forces
    # at least one 128-column tile — shapes that land in the
    # (budget, ceiling] window would previously compile only by luck, and
    # anything above the ceiling must route to the fallback, not fail on
    # hardware (ADVICE r3: the old ``2 * budget`` guard left a
    # (16, 20] MiB window that interpret-mode tests cannot catch).
    mosaic_vmem_ceiling = 15 * 2 ** 20
    if 4 * cells * ln_max * tile_c * itemsize > mosaic_vmem_ceiling:
        # Even one 128-column tile over the ceiling (enormous rows):
        # compose the per-axis ring RS kernels sequentially — correct at
        # any shape, loses the 2n-path fusion.
        from triton_dist_tpu.kernels.reduce_scatter import (
            ReduceScatterMethod,
            reduce_scatter_shard,
        )

        fallback_ids = (collective_id, cid.TORUS_RS_THIRD,
                        cid.TORUS_RS_FALLBACK)
        x = x_shard
        for a, fid in zip(axis_names, fallback_ids):
            x = reduce_scatter_shard(x, a, method=ReduceScatterMethod.AUTO,
                                     interpret=interpret, collective_id=fid)
        return x
    npaths = 2 * n
    buf_shape = jax.ShapeDtypeStruct(
        (npaths,) + tuple(sizes) + (ln_max, cols), xnd.dtype)
    out, *_hbm_scratch = pl.pallas_call(
        functools.partial(_torus_rs_kernel, axis_names=axis_names,
                          sizes=sizes, paths=paths, tile_c=tile_c),
        out_shape=[jax.ShapeDtypeStruct((rows, cols), xnd.dtype)]
        + [buf_shape] * (2 * n + 1),  # acc[l], rcv[l], work
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (2 * n + 2),
        scratch_shapes=[
            pltpu.VMEM((2,) + tuple(sizes) + (ln_max, tile_c), xnd.dtype),
            pltpu.VMEM((2,) + tuple(sizes) + (ln_max, tile_c), xnd.dtype),
            pltpu.SemaphoreType.DMA((npaths, n)),       # send per path
            pltpu.SemaphoreType.DMA((npaths, n)),       # recv per path
            pltpu.SemaphoreType.REGULAR((npaths, n)),   # credits
            pltpu.SemaphoreType.DMA,                    # copy/loads
            pltpu.SemaphoreType.DMA,                    # fold stores
        ],
        compiler_params=dl.collective_compiler_params(world, collective_id),
        interpret=maybe_interpret(interpret),
    )(xnd)
    return out.reshape((rows,) + orig_trailing)


def torus_reduce_scatter_shard(x_shard, axes, *, interpret=False,
                               collective_id=cid.TORUS_RS):
    """ReduceScatter over a 2- or 3-axis torus; call inside shard_map.

    Input: this device's [W*rows, ...] partial (W = product of axes sizes),
    flat ``axes``-major like :func:`torus_all_gather_shard`'s output.
    Output: this device's fully-summed [rows, ...] band — matching
    ``lax.psum_scatter(tiled=True)`` over the joint axes.

    2 axes → the fused four-path kernel; 3 axes → the fused SIX-path
    kernel (cyclic reduction orders x→y→z / y→z→x / z→x→y, each
    bidirectional: all 2n link directions reduce concurrently in every
    phase).  Size-1 axes are dropped; a single real axis falls back to
    the 1-axis ring dispatch.
    """
    from triton_dist_tpu.kernels.reduce_scatter import (
        ReduceScatterMethod,
        reduce_scatter_shard,
    )

    axes = tuple(axes)
    if len(axes) > 3:
        raise ValueError(f"torus_reduce_scatter_shard supports 1-3 axes, "
                         f"got {axes}")
    sizes = {a: jax.lax.axis_size(a) for a in axes}
    real = tuple(a for a in axes if sizes[a] > 1)
    if not real:
        return x_shard
    if len(real) == 1:
        return reduce_scatter_shard(x_shard, real[0],
                                    method=ReduceScatterMethod.AUTO,
                                    interpret=interpret,
                                    collective_id=collective_id)
    return _torus_rs(x_shard, axis_names=real,
                     sizes=tuple(sizes[a] for a in real),
                     interpret=interpret, collective_id=collective_id)
