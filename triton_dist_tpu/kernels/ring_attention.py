"""Ring attention — training-side sequence/context parallelism.

Reference analog: none, by design.  The reference's long-context story is
decode-side SP only (sharded-KV flash-decode + LL allgather + LSE combine,
flash_decode.py:481-532; SURVEY.md §5 "ring/Ulysses are natural TPU
extensions").  This module supplies the training-side half: Q/K/V stay
sequence-sharded, KV blocks rotate around the mesh-axis ring, and each
device folds every block into a running online-softmax accumulator (the
same LSE-merge math as the reference's inter-rank decode combine, applied
blockwise instead of once).

Three implementations:

* ``flash`` (r4; the ``auto`` choice when S_loc % 128 == hd % 128 == 0) —
  ``lax.scan`` ring whose per-step block update is the flash-attention
  KERNEL (kernels/flash_attention.py) and whose backward is a reverse
  ring over the flash backward kernels — O(block) memory on both passes,
  the only impl that scales to arbitrary S_loc.
* ``xla`` — ``lax.scan`` over ring steps with ``jax.lax.ppermute`` KV
  rotation and a dense per-step block update ([G, S_loc, S_loc] logits).
  XLA overlaps the collective-permute with the next block's compute on
  TPU, and the whole thing is differentiable (the backward pipeline is
  scan+ppermute transposed — a reverse-direction ring).  The
  differentiation-golden reference.
* ``pallas`` — one kernel per device: double-buffered KV slots in HBM;
  at step s the kernel remote-DMAs the current block to the right
  neighbor's next slot while the MXU computes this block's flash update
  (the ag_gemm overlap structure applied to attention).  Whole [S_loc]
  blocks are staged through VMEM, so S_loc × (B·H·hd) must fit VMEM —
  the low-latency choice for moderate S_loc.  Differentiable via custom
  VJP whose backward is the VJP of the (numerically identical) xla path.

Causality: KV block from rank j attends to local queries with the global
positions mask; blocks entirely in the future contribute nothing (their
exp-weights are 0) but still ride the ring — SPMD uniformity.

``soft_cap``/``window`` (the Gemma-2 / Mistral knobs) thread through all
three impls: the flash impl forwards them to the flash kernels (which
already own the masking rule), and the xla/pallas impls apply the same
rule in ``_block_update`` — key at kpos visible iff (not causal or
qpos >= kpos) and (not window or qpos - kpos < window), logits capped by
``soft_cap * tanh(logits / soft_cap)`` before masking.  Dead ring steps
(blocks wholly outside every query's window) contribute lse = NEG
partials, which the LSE merge treats as exact no-ops.

ZIGZAG layout (``zigzag=True``, r5): causal ring attention with the
naive contiguous layout is ~2x unbalanced — at ring step s, devices
me >= s do FULL-block work while devices me < s consume wholly-future
(dead) blocks, so every step costs a full block and utilization is
(w+1)/2w.  The zigzag layout splits the global sequence into 2w chunks
and gives rank i chunks (i, 2w-1-i) — one early, one late.  Late chunks
are never visible to any early query chunk (2w-1-j >= w > i), and of
the remaining three (q-chunk, kv-chunk) pair classes exactly two are
live at EVERY (device, step): per-step work is a constant half-block,
step time halves, and chunk-granular utilization is 100% for all
world >= 2 (the standard zigzag/striped CP schedule; see
docs/multichip_predictions.md).  Implemented via the flash kernels'
segmented-offset support (each shard is two position runs riding the
scalar-prefetch block-offset vectors) — same math, re-indexed.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels.gemm import apply_soft_cap, resolve_impl
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

from triton_dist_tpu.kernels.collective_ids import RING_ATTN as RING_ATTN_COLLECTIVE_ID
_NEG = -1e30


@dataclass
class RingAttentionContext:
    mesh: Mesh
    axis: str = "sp"
    causal: bool = True
    impl: str = "auto"
    interpret: bool = False
    window: int = 0
    soft_cap: float = 0.0
    zigzag: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_ring_attention_context(mesh, axis="sp", causal=True, impl="auto",
                                  interpret=False, window=0, soft_cap=0.0,
                                  zigzag=False) -> RingAttentionContext:
    return RingAttentionContext(mesh=mesh, axis=axis, causal=causal,
                                impl=impl, interpret=interpret,
                                window=window, soft_cap=soft_cap,
                                zigzag=zigzag)


def _seg_positions(starts, idx, total):
    """Global positions for row indices ``idx`` (int32 array, any shape)
    of an axis made of len(starts) equal runs.  Pure arithmetic + where —
    Mosaic-safe inside the fused ring kernel (no rank-1 iota, no gathers).
    """
    starts = starts if isinstance(starts, (tuple, list)) else (starts,)
    run = total // len(starts)
    pos = starts[0] + idx
    for t in range(1, len(starts)):
        pos = jnp.where(idx >= t * run, starts[t] + (idx - t * run), pos)
    return pos


def _block_update(q, k_blk, v_blk, m, l, acc, q_off, k_off, *, causal,
                  scale, group, window=0, soft_cap=0.0):
    """One flash/online-softmax fold of a KV block into the running stats.

    GROUPED, batch-LEADING layout — (batch, head) folded into one axis
    because Mosaic's matmul supports at most one batch dim, and placed
    first because it must be the leading dim: q [G, Sq, hd] with G = B*Hq;
    k/v [Gk, Sk, hd] (G = group*Gk); m/l [G, Sq]; acc [G, Sq, hd] f32;
    q_off/k_off: global position of the first query/key row — a scalar
    (contiguous) or a tuple of run starts (zigzag: two runs per shard).

    Returns updated (m, l, acc).  This is the same merge the reference's
    decode combine does with per-rank LSEs (flash_decode.py:512-526), done
    blockwise.  ``window``/``soft_cap`` follow the flash kernels' rule
    (flash_attention._visibility_mask / apply_soft_cap) exactly.
    """
    kr = jnp.repeat(k_blk, group, axis=0)
    vr = jnp.repeat(v_blk, group, axis=0)
    logits = jnp.einsum("gsd,gtd->gst", q, kr,
                        preferred_element_type=jnp.float32) * scale
    logits = apply_soft_cap(logits, soft_cap)
    masked = causal or window
    if masked:
        # 2-D iota (Mosaic rejects rank-1 iota on hardware; fine under XLA).
        sq, sk = q.shape[1], k_blk.shape[1]
        rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        qpos = _seg_positions(q_off, rows, sq)
        kpos = _seg_positions(k_off, cols, sk)
        # Three static branches, mirroring _visibility_mask (no all-true
        # bool array through Mosaic).
        if causal and window:
            mask = (qpos >= kpos) & (qpos - kpos < window)
        elif causal:
            mask = qpos >= kpos
        else:
            mask = qpos - kpos < window
        logits = jnp.where(mask[None], logits, _NEG)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    # Rows with no visible keys yet keep m = _NEG; exp(logits - m) would be
    # exp(0) = 1 for masked entries, so clamp the rescale instead.
    p = jnp.exp(logits - m_new[..., None])
    if masked:
        p = jnp.where(mask[None], p, 0.0)
    rescale = jnp.exp(jnp.minimum(m - m_new, 0.0))
    l_new = l * rescale + jnp.sum(p, axis=-1)
    acc_new = (acc * rescale[..., None]
               + jnp.einsum("gst,gtd->gsd", p.astype(q.dtype), vr,
                            preferred_element_type=jnp.float32))
    return m_new, l_new, acc_new


def _ring_attention_xla(q, k, v, *, axis, causal, scale, window=0,
                        soft_cap=0.0, zigzag=False):
    world = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    s_loc = q.shape[0]
    b, hq, hd = q.shape[1], q.shape[2], q.shape[3]
    group = hq // k.shape[2]
    q_off = _shard_starts(me, s_loc, world, zigzag)
    perm = _ring_perm(world)
    upd = functools.partial(_block_update, causal=causal, scale=scale,
                            group=group, window=window, soft_cap=soft_cap)

    qg = q.transpose(1, 2, 0, 3).reshape(b * hq, s_loc, hd)
    kg = k.transpose(1, 2, 0, 3).reshape(b * k.shape[2], s_loc, hd)
    vg = v.transpose(1, 2, 0, 3).reshape(b * k.shape[2], s_loc, hd)

    m0 = jnp.full((b * hq, s_loc), _NEG, jnp.float32)
    l0 = jnp.zeros((b * hq, s_loc), jnp.float32)
    a0 = jnp.zeros((b * hq, s_loc, hd), jnp.float32)

    # Local block first (outside the scan), then world-1 steps that each
    # permute-then-consume — no wasted final permute (a collective inside
    # the scan body cannot be DCE'd by XLA).
    m, l, acc = upd(qg, kg, vg, m0, l0, a0, q_off, q_off)

    def step(carry, s):
        k_blk, v_blk, m, l, acc = carry
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        src = _src_rank(me, s, world)
        m, l, acc = upd(qg, k_blk, v_blk, m, l, acc, q_off,
                        _shard_starts(src, s_loc, world, zigzag))
        return (k_blk, v_blk, m, l, acc), None

    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (kg, vg, m, l, acc), jnp.arange(1, world))
    out = acc / jnp.maximum(l, 1e-30)[..., None]          # [G, S, hd]
    return (out.reshape(b, hq, s_loc, hd).transpose(2, 0, 1, 3)
            .astype(q.dtype))


# ---------------------------------------------------------------------------
# Flash ring — the scalable long-context path (r4)
# ---------------------------------------------------------------------------
#
# The two original impls both carry an S_loc^2 term: the xla ring
# materializes [G, S_loc, S_loc] logits per step, and the fused pallas
# kernel stages whole [G, S_loc, hd] KV blocks in VMEM (its own docstring's
# scalability bound).  The flash ring replaces the per-step dense update
# with the flash-attention kernel (O(block) memory, KV streamed from HBM)
# and its backward with the flash backward kernels — per-step partials
# (out_j, lse_j) LSE-merge across ring steps exactly like the decode
# combine, and the backward's per-block P-recompute against the GLOBAL lse
# is mathematically the full softmax gradient restricted to that block, so
# the second (reverse) ring just sums block contributions.  Every device
# runs the kernel every step, SPMD-uniform (same rule as the other impls:
# future blocks contribute nothing but still ride the ring) — the
# kernel's internal whole-block causal skip prunes the dead MXU work, and
# a per-device lax.cond around the call would deadlock the interpreter's
# cross-device pallas barrier anyway.


def _ring_perm(world):
    """The one ring direction, shared by every impl: device i → i + 1."""
    return [(i, (i + 1) % world) for i in range(world)]


def _shard_starts(rank, s_loc, world, zigzag):
    """Run starts of ``rank``'s sequence shard: one contiguous run, or
    the zigzag pair — chunks ``rank`` and ``2w-1-rank``, each of length
    s_loc//2.  ``rank`` may be traced (the tuple entries then are)."""
    if not zigzag:
        return (rank * s_loc,)
    c = s_loc // 2
    return (rank * c, (2 * world - 1 - rank) * c)


def zigzag_indices(S, world):
    """Global row permutation for the zigzag layout: position p of the
    returned index array names the natural-order row that lands at p
    when shards are laid out [shard0 | shard1 | ...] with shard i =
    [chunk i | chunk 2w-1-i].  ``x[zigzag_indices(S, w)]`` re-orders a
    natural-order array for zigzag sharding; the inverse permutation
    (argsort) restores natural order."""
    c = S // (2 * world)
    if 2 * world * c != S:
        raise ValueError(f"zigzag needs S % (2*world) == 0, got S={S}, "
                         f"world={world}")
    idx = []
    for i in range(world):
        idx.extend(range(i * c, (i + 1) * c))
        j = 2 * world - 1 - i
        idx.extend(range(j * c, (j + 1) * c))
    return np.asarray(idx, np.int32)


def to_zigzag(x, world, axis=0):
    """Re-order a natural-order global array for zigzag sharding."""
    return jnp.take(x, zigzag_indices(x.shape[axis], world), axis=axis)


def from_zigzag(x, world, axis=0):
    """Inverse of :func:`to_zigzag`."""
    inv = np.argsort(zigzag_indices(x.shape[axis], world)).astype(np.int32)
    return jnp.take(x, inv, axis=axis)


def _src_rank(me, s, world):
    """Owner of the block a device consumes at ring step ``s`` (blocks
    flow with the ring, so step s sees rank me - s's block)."""
    return jax.lax.rem(me - s + world, world)


def _merge_partial(acc, denom, m_run, o_j, l_j):
    """Fold one normalized partial (o_j, lse_j) into the running
    (acc, denom, m_run): true out = acc/denom, LSE = m_run + log(denom).
    Dead partials (lse = NEG) are exact no-ops."""
    m = jnp.maximum(m_run, l_j)
    r1 = jnp.exp(m_run - m)
    r2 = jnp.exp(l_j - m)
    acc = acc * r1[..., None] + o_j.astype(jnp.float32) * r2[..., None]
    return acc, denom * r1 + r2, m


def _ring_attention_flash_fwd(q, k, v, *, axis, causal, scale, interpret,
                              window=0, soft_cap=0.0, zigzag=False):
    """Returns (out [S_loc, B, Hq, hd] in q.dtype, lse [B, Hq, S_loc] f32)."""
    from triton_dist_tpu.kernels.flash_attention import flash_attention

    world = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    s_loc, b, hq, hd = q.shape
    q4 = q.transpose(1, 2, 0, 3)                       # [B, Hq, S, hd]
    k4 = k.transpose(1, 2, 0, 3)
    v4 = v.transpose(1, 2, 0, 3)
    q_off = _shard_starts(me, s_loc, world, zigzag)

    def partial_for(k_blk, v_blk, src):
        # Traced offsets -> the raw (non-diff) kernel path; the ring's own
        # custom VJP owns differentiation.  Zigzag shards ride the
        # kernels' segmented-offset vectors (two runs per side).
        return flash_attention(
            q4, k_blk, v_blk, causal=causal, scale=scale,
            q_offset=q_off,
            kv_offset=_shard_starts(src, s_loc, world, zigzag),
            impl="pallas", interpret=interpret, return_lse=True,
            window=window, soft_cap=soft_cap)

    o0, l0 = partial_for(k4, v4, me)                   # local block
    acc, denom, m_run = (o0.astype(jnp.float32),
                         jnp.ones_like(l0), l0)

    def step(carry, s):
        k_blk, v_blk, acc, denom, m_run = carry
        perm = _ring_perm(world)
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        o_j, l_j = partial_for(k_blk, v_blk, _src_rank(me, s, world))
        acc, denom, m_run = _merge_partial(acc, denom, m_run, o_j, l_j)
        return (k_blk, v_blk, acc, denom, m_run), None

    if world > 1:
        (_, _, acc, denom, m_run), _ = jax.lax.scan(
            step, (k4, v4, acc, denom, m_run), jnp.arange(1, world))
    out4 = (acc / denom[..., None]).astype(q.dtype)    # [B, Hq, S, hd]
    lse = m_run + jnp.log(denom)                       # [B, Hq, S]
    return out4.transpose(2, 0, 1, 3), lse


def _ring_attention_flash_bwd(q, k, v, out, lse, do, *, axis, causal,
                              scale, interpret, window=0, soft_cap=0.0,
                              zigzag=False):
    """Reverse ring: per visiting block run the flash backward kernels
    against the GLOBAL lse; dk/dv accumulators rotate with the blocks and
    take one final hop home."""
    from triton_dist_tpu.kernels.flash_attention import _flash_bwd_pallas

    world = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    s_loc = q.shape[0]
    q4 = q.transpose(1, 2, 0, 3)
    k4 = k.transpose(1, 2, 0, 3)
    v4 = v.transpose(1, 2, 0, 3)
    out4 = out.transpose(1, 2, 0, 3)
    do4 = do.transpose(1, 2, 0, 3)
    q_off = _shard_starts(me, s_loc, world, zigzag)

    def block_grads(k_blk, v_blk, src):
        # grad_dtype=f32: per-block summands stay f32 all the way into the
        # ring accumulation — casting to bf16 per block would round each
        # of the W contributions before the f32 sum.
        return _flash_bwd_pallas(q4, k_blk, v_blk, out4, lse, do4,
                                 q_off,
                                 _shard_starts(src, s_loc, world, zigzag),
                                 causal, scale, interpret, window=window,
                                 soft_cap=soft_cap,
                                 grad_dtype=jnp.float32)

    dq, dk_blk, dv_blk = block_grads(k4, v4, me)
    # All three accumulators (and every per-block summand, see
    # block_grads) carry f32 across the ring — rounding the partials to
    # the storage dtype would lose bits W times (the wire cost of the f32
    # rotation is the price of a consistent gradient).

    def step(carry, s):
        k_blk, v_blk, dk_blk, dv_blk, dq_acc = carry
        perm = _ring_perm(world)
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis, perm)
        dq_c, dk_c, dv_c = block_grads(k_blk, v_blk,
                                       _src_rank(me, s, world))
        return (k_blk, v_blk, dk_blk + dk_c, dv_blk + dv_c,
                dq_acc + dq_c), None

    if world > 1:
        (_, _, dk_blk, dv_blk, dq), _ = jax.lax.scan(
            step, (k4, v4, dk_blk, dv_blk, dq), jnp.arange(1, world))
        # After W-1 rotations the accumulators hold the gradients of rank
        # me+1's block; one more hop delivers them home.
        perm = _ring_perm(world)
        dk_blk = jax.lax.ppermute(dk_blk, axis, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis, perm)

    dq_out = dq.astype(q.dtype).transpose(2, 0, 1, 3)
    dk_out = dk_blk.astype(k.dtype).transpose(2, 0, 1, 3)
    dv_out = dv_blk.astype(v.dtype).transpose(2, 0, 1, 3)
    return dq_out, dk_out, dv_out


# ---------------------------------------------------------------------------
# Pallas overlapped kernel
# ---------------------------------------------------------------------------


def _ring_attn_kernel(q_ref, k_ref, v_ref, o_ref, kring_ref, vring_ref,
                      q_vmem, k_vmem, v_vmem,
                      send_sem, recv_sem, copy_sem, credit_sem,
                      *, axis, world, causal, scale, hq, hkv, hd,
                      window=0, soft_cap=0.0, zigzag=False):
    """Double-buffered ring: slot s%2 is consumed while being forwarded to
    the right neighbor's slot (s+1)%2.  kring/vring: [2, G_kv, S_loc*hd] HBM;
    blocks stage through VMEM scratch for the VPU/MXU compute.

    Two slots alone are NOT race-free: the left neighbor's step-s put
    targets my slot (s+1)%2 — the same slot my step s-1 is consuming.  The
    credit semaphore adds the missing backpressure (the gemm_rs pattern):
    after step s finishes with slot s%2 (staged to VMEM and its outbound
    send drained) I credit my LEFT neighbor, and nobody sends into a
    reused slot before collecting the matching credit."""
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)
    s_loc = q_ref.shape[1] // hd          # wire layout [G, S_loc*hd]
    group = hq // hkv

    # Stage local KV into slot 0 and Q into VMEM.
    c1 = pltpu.make_async_copy(k_ref, kring_ref.at[0], copy_sem)
    c2 = pltpu.make_async_copy(v_ref, vring_ref.at[0], copy_sem)
    c3 = pltpu.make_async_copy(q_ref, q_vmem, copy_sem)
    c1.start(); c2.start(); c3.start(); c1.wait(); c2.wait(); c3.wait()

    if world > 1:
        dl.barrier_all(axis)

    g_q = q_ref.shape[0]
    q = q_vmem[...].reshape(g_q, s_loc, hd)
    q_off = _shard_starts(me, s_loc, world, zigzag)

    m = jnp.full((g_q, s_loc), _NEG, jnp.float32)
    l = jnp.zeros((g_q, s_loc), jnp.float32)
    acc = jnp.zeros((g_q, s_loc, hd), jnp.float32)

    for s in range(world):
        cur, nxt = s % 2, (s + 1) % 2
        if s > 0:
            # Block for this step was DMA'd by the left neighbor during the
            # previous step's compute (two DMAs: k and v).
            pltpu.make_async_copy(kring_ref.at[cur], kring_ref.at[cur],
                                  recv_sem).wait()
            pltpu.make_async_copy(vring_ref.at[cur], vring_ref.at[cur],
                                  recv_sem).wait()
        if s < world - 1:
            if s >= 1:
                # Right's slot nxt was consumed at its step s-1; wait for
                # its credit before overwriting.
                pltpu.semaphore_wait(credit_sem, 1)
            dl.remote_copy(kring_ref.at[cur], kring_ref.at[nxt],
                           send_sem, recv_sem, axis, right).start()
            dl.remote_copy(vring_ref.at[cur], vring_ref.at[nxt],
                           send_sem, recv_sem, axis, right).start()

        ck = pltpu.make_async_copy(kring_ref.at[cur], k_vmem, copy_sem)
        cv = pltpu.make_async_copy(vring_ref.at[cur], v_vmem, copy_sem)
        ck.start(); cv.start(); ck.wait(); cv.wait()
        g_kv = k_ref.shape[0]
        k_blk = k_vmem[...].reshape(g_kv, s_loc, hd)
        v_blk = v_vmem[...].reshape(g_kv, s_loc, hd)
        src = _src_rank(me, s, world)
        m, l, acc = _block_update(q, k_blk, v_blk, m, l, acc, q_off,
                                  _shard_starts(src, s_loc, world, zigzag),
                                  causal=causal, scale=scale,
                                  group=group, window=window,
                                  soft_cap=soft_cap)

        if s < world - 1:
            # Drain both sends before overwriting/reusing the slot.
            pltpu.make_async_copy(kring_ref.at[cur], kring_ref.at[cur],
                                  send_sem).wait()
            pltpu.make_async_copy(vring_ref.at[cur], vring_ref.at[cur],
                                  send_sem).wait()
        if s < world - 2:
            # Slot cur is now free (staged + drained): left may overwrite it
            # at its step s+1.
            pltpu.semaphore_signal(credit_sem, inc=1, device_id={axis: left},
                                   device_id_type=pltpu.DeviceIdType.MESH)

    out = acc / jnp.maximum(l, 1e-30)[..., None]          # [G, S, hd]
    # o_ref lives in HBM (ANY): stage through VMEM (q_vmem is free now — q
    # was materialized as a value before the loop).
    q_vmem[...] = out.reshape(g_q, s_loc * hd).astype(q_vmem.dtype)
    co = pltpu.make_async_copy(q_vmem, o_ref, copy_sem)
    co.start(); co.wait()


def _ring_attention_pallas_fwd(q, k, v, *, axis, causal, scale, interpret,
                               window=0, soft_cap=0.0, zigzag=False):
    world = jax.lax.axis_size(axis)
    s_loc, b, hq, hd = q.shape
    hkv = k.shape[2]
    # Wire layout [G, S_loc*hd], G leading (matches the kernel's batch-
    # leading matmul layout; the transpose happens here under XLA, not in
    # the kernel).
    q2 = q.transpose(1, 2, 0, 3).reshape(b * hq, s_loc * hd)
    k2 = k.transpose(1, 2, 0, 3).reshape(b * hkv, s_loc * hd)
    v2 = v.transpose(1, 2, 0, 3).reshape(b * hkv, s_loc * hd)

    out, _, _ = pl.pallas_call(
        functools.partial(_ring_attn_kernel, axis=axis, world=world,
                          causal=causal, scale=scale, hq=hq, hkv=hkv,
                          hd=hd, window=window, soft_cap=soft_cap,
                          zigzag=zigzag),
        out_shape=[
            jax.ShapeDtypeStruct(q2.shape, q.dtype),
            jax.ShapeDtypeStruct((2,) + k2.shape, k.dtype),  # k ring slots
            jax.ShapeDtypeStruct((2,) + v2.shape, v.dtype),  # v ring slots
        ],
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        scratch_shapes=[
            pltpu.VMEM(q2.shape, q.dtype),
            pltpu.VMEM(k2.shape, k.dtype),
            pltpu.VMEM(v2.shape, v.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.REGULAR,
        ],
        compiler_params=dl.collective_compiler_params(
            world, RING_ATTN_COLLECTIVE_ID),
        interpret=maybe_interpret(interpret),
    )(q2, k2, v2)
    return out.reshape(b, hq, s_loc, hd).transpose(2, 0, 1, 3)


# ---------------------------------------------------------------------------
# Dispatch + differentiability
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _ring_attention_diff(q, k, v, axis, causal, scale, impl, interpret,
                         window, soft_cap, zigzag):
    if impl == "flash":
        return _ring_attention_flash_fwd(q, k, v, axis=axis, causal=causal,
                                         scale=scale, interpret=interpret,
                                         window=window, soft_cap=soft_cap,
                                         zigzag=zigzag)[0]
    if impl == "pallas":
        return _ring_attention_pallas_fwd(q, k, v, axis=axis, causal=causal,
                                          scale=scale, interpret=interpret,
                                          window=window, soft_cap=soft_cap,
                                          zigzag=zigzag)
    return _ring_attention_xla(q, k, v, axis=axis, causal=causal,
                               scale=scale, window=window,
                               soft_cap=soft_cap, zigzag=zigzag)


def _ring_diff_fwd(q, k, v, axis, causal, scale, impl, interpret, window,
                   soft_cap, zigzag):
    if impl == "flash":
        out, lse = _ring_attention_flash_fwd(
            q, k, v, axis=axis, causal=causal, scale=scale,
            interpret=interpret, window=window, soft_cap=soft_cap,
            zigzag=zigzag)
        return out, (q, k, v, out, lse)
    out = _ring_attention_diff(q, k, v, axis, causal, scale, impl,
                               interpret, window, soft_cap, zigzag)
    return out, (q, k, v, None, None)


def _ring_diff_bwd(axis, causal, scale, impl, interpret, window, soft_cap,
                   zigzag, res, dout):
    q, k, v, out, lse = res
    if impl == "flash":
        # Reverse ring over the flash backward kernels with the global
        # lse — O(block) memory end to end.
        return _ring_attention_flash_bwd(
            q, k, v, out, lse, dout, axis=axis, causal=causal, scale=scale,
            interpret=interpret, window=window, soft_cap=soft_cap,
            zigzag=zigzag)
    # Backward = VJP of the numerically-identical xla ring (flash-style
    # recompute; the transposed scan runs the ring in reverse).
    _, vjp = jax.vjp(
        functools.partial(_ring_attention_xla, axis=axis, causal=causal,
                          scale=scale, window=window, soft_cap=soft_cap,
                          zigzag=zigzag),
        q, k, v)
    return vjp(dout)


_ring_attention_diff.defvjp(_ring_diff_fwd, _ring_diff_bwd)


def ring_attention_shard(q, k, v, *, axis, causal=True, scale=None,
                         impl="auto", interpret=False, window=0,
                         soft_cap=0.0, zigzag=False):
    """Shard-level causal GQA ring attention; call inside shard_map.

    q [S_loc, B, Hq, hd]; k/v [S_loc, B, Hkv, hd] — sequence sharded over
    ``axis``.  Returns [S_loc, B, Hq, hd].  Differentiable on all impls.

    ``impl``: ``"flash"`` (the scalable default under ``auto`` when
    S_loc % 128 == hd % 128 == 0) rides the flash-attention kernels
    through the ring — O(block) memory both passes; ``"pallas"`` is the
    fused comm-overlap kernel (whole-shard VMEM staging — the
    low-latency choice for moderate S_loc); ``"xla"`` the dense scan
    reference.

    ``window``/``soft_cap`` (Mistral sliding window / Gemma-2 logit cap)
    apply the flash kernels' visibility rule across the ring; all impls
    and both passes honor them.

    ``zigzag=True``: the shard holds chunks ``me`` and ``2w-1-me`` of a
    2w-chunk global split (use :func:`to_zigzag` on the global sequence
    before sharding) — balances causal work so every ring step costs a
    half block (~2x step time at world >= 4; see module docstring).
    Requires ``causal=True`` and an even S_loc; flash legality then needs
    S_loc % 256 == 0 (each run tiles by 128).
    """
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    from triton_dist_tpu.kernels.flash_attention import flash_shapes_ok
    from triton_dist_tpu.kernels.gemm import PallasShapeError

    s_loc, hd = q.shape[0], q.shape[3]
    if zigzag:
        if not causal:
            raise ValueError("zigzag layout only balances CAUSAL ring "
                             "attention; use the contiguous layout")
        if s_loc % 2:
            raise ValueError(f"zigzag needs an even S_loc, got {s_loc}")
    n_runs = 2 if zigzag else 1
    legal = flash_shapes_ok(s_loc, s_loc, hd, n_runs, n_runs)
    raw = impl
    impl = resolve_impl(impl, interpret)
    if raw == "auto" and impl == "pallas" and legal:
        impl = "flash"
    if raw == "flash" and not legal:
        raise PallasShapeError(
            f"ring_attention impl='flash': (S_loc={s_loc}, hd={hd}, "
            f"zigzag={zigzag}) needs (S_loc/runs) % 128 == hd % 128 == 0")
    return _ring_attention_diff(q, k, v, axis, causal, float(scale), impl,
                                interpret, int(window), float(soft_cap),
                                bool(zigzag))


def ring_attention(q, k, v, ctx: RingAttentionContext):
    """Host entry: q/k/v [S, B, H, hd] sequence-sharded over ``ctx.axis``."""
    fn = cached_shard_jit(
        ring_attention_shard,
        ctx.mesh,
        (P(ctx.axis), P(ctx.axis), P(ctx.axis)),
        P(ctx.axis),
        axis=ctx.axis, causal=ctx.causal, impl=ctx.impl,
        interpret=ctx.interpret, window=ctx.window, soft_cap=ctx.soft_cap,
        zigzag=ctx.zigzag,
    )
    # Launch metadata (profiling.annotate contract): full attention
    # flops over the global sequence, causal halved.
    from triton_dist_tpu.runtime.profiling import annotate

    S, B, H, hd = q.shape
    flops = 4 * B * H * S * S * hd // (2 if ctx.causal else 1)
    with annotate("ring_attention", flops=flops,
                  bytes_accessed=(q.nbytes + k.nbytes + v.nbytes)
                  // max(ctx.world, 1)):
        return fn(q, k, v)
