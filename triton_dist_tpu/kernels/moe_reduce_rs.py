"""Overlapped GroupGEMM-Reduce-Scatter — MoE tensor-parallel down-proj side.

Reference analog: ``python/triton_dist/kernels/nvidia/moe_reduce_rs.py``
(1020 LoC) — the token-sorted GroupGEMM scatters its output by topk weight
into a symmetric buffer and signals per-rank segments via counter +
``dl.notify`` (:463-464), while a hierarchical reduce-scatter consumer
(``consumer_reduce_scatter_reduce_2d`` :817+) folds partials; the context
precomputes sorted token ids (``create_moe_rs_context`` :278+).

TPU-native design (NOT a port): the ring GEMM-RS schedule of
``gemm_reduce_scatter.py`` with the per-chunk dense GEMM replaced by the
expert-steered grouped GEMM of ``group_gemm.py``:

* Input ``h`` is in **per-segment expert-sorted layout** ([world, m_pad]
  rows): segment ``s`` holds rank ``s``'s tokens sorted by expert (the
  layout ``allgather_group_gemm.py`` gathers, and what the reference's
  precomputed ``gather_a_index`` tables encode).  Because the sort plans are
  derived from allgathered routing metadata, every device agrees on row
  semantics; each device's grouped GEMM output for segment ``s`` is a
  partial sum over its F shard — exactly the reduce-scatter precondition.
* Ring: the partial for segment ``c`` starts at device ``c+1`` and travels
  right accumulating; at each step the *next* chunk's grouped GEMM overlaps
  the in-flight partial-sum DMA (same credit-semaphore flow control as
  ``gemm_reduce_scatter.py``).
* The topk-weighted combine back to token order runs **after** the ring on
  the owner's reduced segment only (m_pad rows instead of world*m_pad) —
  the reference instead fuses its topk reduce into the RS consumer; the
  math is identical, ours just rides XLA's fused gather/einsum.

Sharding contract (1-D TP over ``axis``; E experts, topk assignments):
  h:       [world*m_pad, F]  P(None, axis)  sorted hidden states (F-sharded)
  w_stack: [E, F, D]         P(None, axis, None)  down-proj expert weights
  weights: [T, topk]         P(axis, None)  routing weights
  experts: [T, topk]         P(axis, None)  routing expert ids (int32)
  out:     [T, D]            P(axis, None)  reduced token outputs
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels.allgather_group_gemm import _segment_plans
from triton_dist_tpu.kernels.gemm import (
    MatmulConfig,
    group_gemm_pipeline_body,
    largest_divisor_block,
    pallas_shapes_ok,
    resolve_impl,
    use_fallback,
)
from triton_dist_tpu.kernels.group_gemm import group_gemm_xla
from triton_dist_tpu.kernels.moe_utils import combine_topk
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

from triton_dist_tpu.kernels.collective_ids import MOE_RS as MOE_RS_COLLECTIVE_ID


@dataclass
class MoEReduceRSContext:
    """Reference analog: ``create_moe_rs_context`` (moe_reduce_rs.py:278+) —
    the precomputed sort tables become `_segment_plans` recomputed under jit
    (cheap, and XLA CSEs them with the AG side's)."""

    mesh: Mesh
    n_experts: int
    topk: int
    axis: str = "tp"
    # None = derive load-aware at the host entry (dense loads get the
    # measured 512 MFU winner; group_gemm.load_aware_block_m).  NOTE the
    # input ``h`` must be built with the SAME block_m (its sorted layout
    # depends on it) — callers composing with ag_group_gemm should share
    # one context or one explicit block_m.
    block_m: int | None = None
    impl: str = "auto"
    config: MatmulConfig = field(default_factory=MatmulConfig)
    interpret: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_moe_rs_context(mesh, n_experts, topk, axis="tp", block_m=None,
                          impl="auto", config=None,
                          interpret=False) -> MoEReduceRSContext:
    return MoEReduceRSContext(
        mesh=mesh, n_experts=n_experts, topk=topk, axis=axis,
        block_m=block_m, impl=impl, config=config or MatmulConfig(),
        interpret=interpret,
    )


def _add_body(recv_blk, dst_in_blk, dst_out_blk):
    dst_out_blk[:] = dst_in_blk[:] + recv_blk[:]


def _moe_rs_kernel(
    te_ref,      # [world, n_tiles] SMEM: per-segment tile→expert maps
    h_ref,       # [world*m_pad, f_loc] ANY: sorted hidden states
    w_ref,       # [E, f_loc, D]    ANY: down-proj expert slabs
    out_ref,     # [m_pad, D]       ANY out: reduced own segment
    send_ref,    # [2, m_pad, D]    ANY out (scratch)
    recv_ref,    # [2, m_pad, D]    ANY out (scratch)
    send_sem, recv_sem, credit_sem,
    acc_ref,     # VMEM (block_m, bn) f32
    *,
    axis, world, m_pad, block_m, bn, bk,
):
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)

    f_loc = h_ref.shape[1]
    D = w_ref.shape[2]
    n_tiles, n_n, n_k = m_pad // block_m, D // bn, f_loc // bk

    inner_add = pltpu.emit_pipeline(
        _add_body,
        grid=(n_tiles, n_n),
        in_specs=[
            pl.BlockSpec((block_m, bn), lambda i, j: (i, j)),
            pl.BlockSpec((block_m, bn), lambda i, j: (i, j)),
        ],
        out_specs=[pl.BlockSpec((block_m, bn), lambda i, j: (i, j))],
    )

    if world > 1:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(barrier, 2)

    for s in range(world):
        p = s % 2
        last = s == world - 1
        # Ring chunk schedule (see gemm_reduce_scatter.py docstring).
        if last:
            chunk = me
        else:
            chunk = jax.lax.rem(me - 1 - s + 2 * world, world)
        dst = out_ref if last else send_ref.at[p]

        if s >= 2:
            pltpu.make_async_copy(send_ref.at[p], send_ref.at[p],
                                  send_sem.at[p]).wait()

        # Grouped partial GEMM for this segment — overlaps in-flight recv.
        inner_gemm = pltpu.emit_pipeline(
            functools.partial(group_gemm_pipeline_body, n_k=n_k,
                              out_dtype=out_ref.dtype),
            grid=(n_tiles, n_n, n_k),
            in_specs=[
                pl.BlockSpec((block_m, bk), lambda i, j, k: (i, k)),
                pl.BlockSpec(
                    (1, bk, bn),
                    lambda i, j, k, chunk=chunk: (te_ref[chunk, i], k, j)),
            ],
            out_specs=[pl.BlockSpec((block_m, bn), lambda i, j, k: (i, j))],
        )
        inner_gemm(h_ref.at[pl.ds(chunk * m_pad, m_pad)], w_ref, dst,
                   scratches=(acc_ref,))

        if s >= 1:
            pltpu.make_async_copy(recv_ref.at[p], recv_ref.at[p],
                                  recv_sem.at[p]).wait()
            inner_add(recv_ref.at[p], dst, dst)
            pltpu.semaphore_signal(credit_sem, inc=1, device_id={axis: left},
                                   device_id_type=pltpu.DeviceIdType.MESH)

        if not last:
            if s >= 2:
                pltpu.semaphore_wait(credit_sem, 1)
            dl.remote_copy(send_ref.at[p], recv_ref.at[(s + 1) % 2],
                           send_sem.at[p], recv_sem.at[(s + 1) % 2],
                           axis, right).start()

    if world > 1:
        pfin = (world - 2) % 2
        pltpu.make_async_copy(send_ref.at[pfin], send_ref.at[pfin],
                              send_sem.at[pfin]).wait()
        n_credit_waits = max(world - 3, 0)
        pltpu.semaphore_wait(credit_sem, (world - 1) - n_credit_waits)


def moe_reduce_rs_shard(h_loc, w_stack, weights_loc, experts_loc, *,
                        axis, n_experts, topk, block_m, bn, bk, impl,
                        interpret):
    """Per-device MoE GroupGEMM + ring reduce-scatter; call inside shard_map.

    Returns the local token shard's combined, fully-reduced outputs
    [t_loc, D].
    """
    raw_impl = impl
    impl = resolve_impl(impl, interpret)
    world = jax.lax.axis_size(axis)
    f_loc = h_loc.shape[1]
    D = w_stack.shape[2]
    me = jax.lax.axis_index(axis)

    experts_all = jax.lax.all_gather(experts_loc, axis, axis=0)
    dest_all, te_all, m_pad = _segment_plans(experts_all, n_experts, block_m)
    assert h_loc.shape[0] == world * m_pad, (h_loc.shape, world, m_pad)

    if use_fallback(raw_impl, impl, pallas_shapes_ok(block_m, D, f_loc),
                    "moe_reduce_rs",
                    f"(block_m={block_m}, D={D}, f_loc={f_loc}); needs m%8, n%128, k%128"):
        ys = group_gemm_xla(h_loc, w_stack, te_all.reshape(-1), block_m)
        ys_me = jax.lax.psum_scatter(ys, axis, scatter_dimension=0, tiled=True)
    else:
        bn_ = largest_divisor_block(D, bn, 128)
        bk_ = largest_divisor_block(f_loc, bk, 128)
        ys_me, _, _ = pl.pallas_call(
            functools.partial(
                _moe_rs_kernel, axis=axis, world=world, m_pad=m_pad,
                block_m=block_m, bn=bn_, bk=bk_,
            ),
            out_shape=[
                jax.ShapeDtypeStruct((m_pad, D), h_loc.dtype),
                jax.ShapeDtypeStruct((2, m_pad, D), h_loc.dtype),
                jax.ShapeDtypeStruct((2, m_pad, D), h_loc.dtype),
            ],
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
                pltpu.VMEM((block_m, bn_), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=MOE_RS_COLLECTIVE_ID if world > 1 else None,
            ),
            interpret=maybe_interpret(interpret),
        )(te_all, h_loc, w_stack)

    # Topk combine on the reduced own segment only (m_pad rows).
    dest_me = jax.lax.dynamic_index_in_dim(dest_all, me, keepdims=False)
    return combine_topk(ys_me, dest_me, weights_loc)


def moe_reduce_rs(h, w_stack, weights, experts, ctx: MoEReduceRSContext):
    """out[T, D] = reduce_scatter(GroupGEMM(h) topk-combined), overlapped.
    Host entry (reference ``moe_reduce_rs`` moe_reduce_rs.py:882-1020)."""
    from triton_dist_tpu.kernels.group_gemm import load_aware_block_m

    cfg = ctx.config
    block_m = ctx.block_m or load_aware_block_m(
        weights.shape[0] * ctx.topk, ctx.n_experts)
    fn = cached_shard_jit(
        moe_reduce_rs_shard,
        ctx.mesh,
        (P(None, ctx.axis), P(None, ctx.axis, None),
         P(ctx.axis, None), P(ctx.axis, None)),
        P(ctx.axis, None),
        axis=ctx.axis, n_experts=ctx.n_experts, topk=ctx.topk,
        block_m=block_m, bn=cfg.block_n, bk=cfg.block_k,
        impl=ctx.impl, interpret=ctx.interpret,
    )
    # Launch metadata: grouped GEMM over all sorted rows against the
    # local F shard, plus the ring partial traffic (~rows*D).
    from triton_dist_tpu.runtime.profiling import annotate

    rows = h.shape[0]
    f_loc = h.shape[1] // max(ctx.world, 1)
    D = w_stack.shape[2]
    el = jnp.dtype(h.dtype).itemsize
    with annotate("moe_reduce_rs", flops=2 * rows * f_loc * D,
                  bytes_accessed=(rows * f_loc + rows * D) * el
                  + w_stack.size // max(ctx.world, 1) * el):
        return fn(h, w_stack, weights, experts)


# ---------------------------------------------------------------------------
# Autotuned entry (VERDICT r3 #4, twin of ag_group_gemm_autotuned).
# ---------------------------------------------------------------------------

from triton_dist_tpu.autotuner import Config as _Cfg, autotune as _autotune

# NOTE: block_m is NOT swept here — the input ``h`` arrives already in the
# block_m-dependent sorted layout (its m_pad is fixed by the producer), so
# the tile height is chosen by the producer side (ag_group_gemm's sweep /
# load-aware default) and this sweep covers the MXU blocks.
MOE_RS_TUNE_SPACE = [
    _Cfg(bn=512, bk=512),
    _Cfg(bn=512, bk=1024),   # bf16 grouped winner
    _Cfg(bn=1024, bk=1024),  # int8 grouped winner
]


@_autotune(configs=MOE_RS_TUNE_SPACE, key=())
def _moe_reduce_rs_tunable(h, w_stack, weights, experts, *, ctx,
                           bn=None, bk=None):
    tuned = MoEReduceRSContext(
        mesh=ctx.mesh, n_experts=ctx.n_experts, topk=ctx.topk,
        axis=ctx.axis, block_m=ctx.block_m, impl=ctx.impl,
        config=MatmulConfig(ctx.config.block_m, bn, bk),
        interpret=ctx.interpret)
    return moe_reduce_rs(h, w_stack, weights, experts, tuned)


def moe_reduce_rs_autotuned(h, w_stack, weights, experts,
                            ctx: MoEReduceRSContext):
    """:func:`moe_reduce_rs` with (bn, bk) selected by the autotuner (each
    config re-traces the whole overlapped ring program).  Same
    lockstep/is_dist rules as ``ag_gemm_autotuned``; on the tunnel chip
    use scripts/autotune_onchip.py's chain measure instead."""
    return _moe_reduce_rs_tunable(h, w_stack, weights, experts, ctx=ctx)
