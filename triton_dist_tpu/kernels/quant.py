"""Quantized GEMM: int8 MXU matmul + W8A8 linear with per-channel scales.

Reference analog: the reference threads fp8/s8 dtypes through its kernel
library (``_make_tensor`` fp8/int8 factories utils.py:134-166, fp8 MoE
AllToAll payloads low_latency_all_to_all.py:76-88, s8 GEMM test dtypes).
On TPU the quantized story centers on the MXU's double-rate int8 path:
v5e peaks at ~394 int8 TOPS vs 197 bf16 TFLOPS.

Measured (real v5 chip, M=8192 K=8192 N=3584): 358 TOPS at block
(1024, 512, 1024) — 91% of nominal int8 peak and 1.9x the bf16 kernel's
190 TFLOPS.  int8 halves both HBM traffic and VMEM block bytes, which is
why the winning int8 block doubles ``bk`` relative to bf16's
(2048, 512, 512); larger blocks fail to compile (VMEM ceiling).

W8A8 scheme (the standard serving recipe):
- weights: static symmetric per-output-channel int8 (``quantize_channelwise``);
- activations: dynamic symmetric per-row int8 (``quantize_rowwise``);
- GEMM accumulates exact int32 on the MXU, dequant is one rank-1 f32
  rescale fused into the epilogue by XLA.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.kernels.gemm import resolve_impl, use_fallback


@dataclass(frozen=True)
class Int8MatmulConfig:
    # Real-chip sweep winners (module docstring).  int8 halves block
    # bytes, so bk doubles vs the bf16 config at the same VMEM budget.
    block_m: int = 1024
    block_n: int = 512
    block_k: int = 1024

    def for_shape(self, m: int, n: int, k: int) -> "Int8MatmulConfig":
        rnd = lambda x, a: (x + a - 1) // a * a
        return Int8MatmulConfig(
            block_m=min(self.block_m, max(rnd(m, 32), 32)),
            block_n=min(self.block_n, max(rnd(n, 128), 128)),
            block_k=min(self.block_k, max(rnd(k, 128), 128)),
        )


def _matmul_i8_kernel(a_ref, b_ref, out_ref, acc_ref, *, n_k: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.int32)

    @pl.when(k == n_k - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("config", "impl", "interpret"))
def matmul_i8(a: jax.Array, b: jax.Array,
              config: Int8MatmulConfig | None = None,
              impl: str = "auto", interpret: bool = False) -> jax.Array:
    """C[m, n] int32 = A[m, k] int8 @ B[k, n] int8, exact.

    Shapes must tile the MXU (m%32, n%128, k%128 == 0) for the pallas
    path; anything else (or ``impl="xla"``) uses lax.dot with int32
    accumulation — bit-identical, just not the double-rate kernel.
    """
    assert a.dtype == jnp.int8 and b.dtype == jnp.int8, (a.dtype, b.dtype)
    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    raw_impl = impl
    impl = resolve_impl(impl, interpret)
    cfg = (config or Int8MatmulConfig()).for_shape(m, n, k)
    bm, bn, bk = cfg.block_m, cfg.block_n, cfg.block_k
    ok = m % bm == 0 and n % bn == 0 and k % bk == 0 and m % 32 == 0

    if use_fallback(raw_impl, impl, ok, "matmul_i8",
                    f"({m}, {n}, {k}) vs blocks ({bm}, {bn}, {bk}), m%32"):
        return jax.lax.dot_general(
            a, b, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

    n_k = k // bk
    return pl.pallas_call(
        functools.partial(_matmul_i8_kernel, n_k=n_k),
        grid=(m // bm, n // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.int32),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(a, b)


def _register_quant_aot():
    """AOT export spaces for the quantized GEMM (joins matmul/gqa_decode in
    the registry; see tools/compile_aot.py and csrc/aot_runtime)."""
    from triton_dist_tpu.tools.compile_aot import aot_compile_spaces

    def algos(platforms):
        if "tpu" in platforms:
            return [{"bm": 1024, "bn": 512, "bk": 1024},  # sweep winner
                    {"bm": 256, "bn": 256, "bk": 256}]
        return [{"bm": 256, "bn": 256, "bk": 256}]

    return aot_compile_spaces({
        "matmul_i8": {
            "signature": [
                [((8192, 8192), "int8"), ((8192, 3584), "int8")],
                [((1024, 1024), "int8"), ((1024, 512), "int8")],
            ],
            "algo_infos": algos,
        },
    })


@_register_quant_aot()
def matmul_i8_with_blocks(a, b, *, bm, bn, bk, impl="auto",
                          interpret=False):
    """``matmul_i8`` with flat block kwargs — the AOT entry point (algo
    infos must be manifest-serializable primitives)."""
    return matmul_i8(a, b, config=Int8MatmulConfig(bm, bn, bk), impl=impl,
                     interpret=interpret)


def symmetric_quantize(x: jax.Array, axis: int) -> tuple[jax.Array,
                                                         jax.Array]:
    """Symmetric absmax int8 quant along ``axis``: x ≈ q * expand(scale).
    The single recipe behind every int8 surface (W8A8 rows/channels, the
    int8 KV cache) — change it here and everywhere changes together."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32)
                           / jnp.expand_dims(scale, axis)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def quantize_rowwise(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Dynamic symmetric per-row int8: x ≈ q * scale[:, None].
    x [m, k] float → (q [m, k] int8, scale [m] f32)."""
    return symmetric_quantize(x, 1)


def quantize_channelwise(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Static symmetric per-output-channel int8: w ≈ q * scale[None, :].
    w [k, n] float → (q [k, n] int8, scale [n] f32)."""
    return symmetric_quantize(w, 0)


def w8a8_linear(x: jax.Array, w_q: jax.Array, w_scale: jax.Array, *,
                out_dtype=None, config: Int8MatmulConfig | None = None,
                impl: str = "auto", interpret: bool = False) -> jax.Array:
    """y = x @ dequant(w): dynamic per-row activation quant → int8 MXU
    GEMM (exact int32) → rank-1 f32 dequant.

    x [m, k] bf16/f32; w_q [k, n] int8 with per-channel ``w_scale`` [n]
    (from :func:`quantize_channelwise`).
    """
    out_dtype = out_dtype or x.dtype
    x_q, x_scale = quantize_rowwise(x)
    acc = matmul_i8(x_q, w_q, config=config, impl=impl, interpret=interpret)
    y = acc.astype(jnp.float32) * x_scale[:, None] * w_scale[None, :]
    return y.astype(out_dtype)
