"""Central registry of Pallas ``collective_id`` values.

Each collectively-launched Mosaic kernel claims a barrier semaphore by
``collective_id``; two kernels that may be in flight in the same program
must not share one (aliased barrier semaphores can deadlock or race).
Keeping every id in one table makes collisions impossible to miss —
round-2 review caught two independent modules both deriving id 5.

Rule: every kernel module imports its id(s) from here; derived ids
(``base + 1`` arithmetic) are forbidden outside this file.
"""

BARRIER_ALL = 0          # kernels/common_ops.py mesh barrier
ALLGATHER = 1            # kernels/allgather.py default
REDUCE_SCATTER = 2       # kernels/reduce_scatter.py default
AG_GEMM = 3              # kernels/allgather_gemm.py (1-axis and torus)
GEMM_RS = 4              # kernels/gemm_reduce_scatter.py fused kernel
A2A = 5                  # kernels/all_to_all.py single-tier
RING_ATTN = 6            # kernels/ring_attention.py
SP_DECODE = 7            # kernels/flash_decode.py
LL_AG = 8                # kernels/low_latency_allgather.py intra tier
AG_GROUP_GEMM = 9        # kernels/allgather_group_gemm.py
MOE_RS = 10              # kernels/moe_reduce_rs.py
HIER_A2A_SLOW = 12       # kernels/hierarchical.py two-tier A2A stage 1
HIER_A2A_FAST = 13       # kernels/hierarchical.py two-tier A2A stage 2
HIER_STAGE1 = 14         # kernels/hierarchical.py AG slow / RS fast pass
HIER_STAGE2 = 15         # kernels/hierarchical.py AG fast / RS slow pass
TORUS_AG = 16            # kernels/torus.py fused 2D AG plane
TORUS_AG_THIRD = 17      # kernels/torus.py 3-axis third-axis ring
TORUS_RS = 18            # kernels/torus.py fused 2D RS plane
TORUS_RS_THIRD = 19      # kernels/torus.py 3-axis third-axis ring
GEMM_RS_SECOND = 20      # gemm_reduce_scatter.py 2-axis fallback 2nd leg
LL_AG_INTER = 21         # low_latency_allgather.py inter tier
TORUS_RS_FALLBACK = 22   # kernels/torus.py sequential fallback, 2nd/3rd leg
