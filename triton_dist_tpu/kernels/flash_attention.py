"""Pallas flash-attention prefill — blockwise causal GQA forward.

Reference analog: none file-for-file — the reference's attention story is
decode-side only (``flash_decode.py``); its prefill runs through whatever
dense attention the host model uses.  This module closes the gap the other
way round: the repo's model families (llama.py / moe.py / ulysses) computed
prefill attention as a dense XLA einsum that materializes the full
[B, H, S, S] logits tensor in HBM — at S = 8192, Hq = 32 that is 8.6 GB of
f32 score traffic *per layer*, which caps practical context length and
wastes the bandwidth the MXU needs.  Flash attention keeps the working set
at one [block_q, block_k] tile per step and carries online-softmax
statistics in VMEM — O(S) memory, one pass over K/V.

TPU-native design (the same shape as the repo's split-KV decode kernel,
``flash_decode.py:_decode_kernel``, applied to prefill):

* Grid ``(B, Hkv, nQ, nK)``; the KV axis is innermost and sequential
  ("arbitrary"), carrying the online-softmax accumulator (acc, m, l) in
  VMEM scratch across KV blocks; (B, Hkv, nQ) are ``parallel`` so Mosaic
  pipelines across block boundaries (the +14% knob from the GEMM sweep).
* GQA is folded into the q block: the q-head group dimension G = Hq//Hkv
  rides inside the block ([G, bq, D] per (batch, kv-head)), so the QK and
  PV matmuls are single MXU calls of [G*bq, D] x [D, bk] — no K/V
  ``jnp.repeat`` ever materializes (the dense path repeats K/V G times).
* K/V feed the MXU in their storage dtype; P casts down to V's dtype for
  the PV matmul (both matmuls stay on the MXU fast path — the round-2
  decode-kernel lesson).
* ``q_offset``/``kv_offset`` ride as **scalar prefetch** (SMEM), so the
  chunked-prefill caller (models/generate.py:_attend_prefix, whose
  ``prefix_len`` is a traced scalar) reuses ONE trace across chunks.
* Fully-masked causal blocks (k_start > q_end) skip their compute via
  ``pl.when`` — ~2x fewer MXU ops for causal prefill.  Their DMAs still
  stream (the rectangular grid cannot be shortened data-dependently), but
  prefill at real S is MXU-bound, not bandwidth-bound.
* ``return_lse`` exposes the per-row log-sum-exp in the same [G-packed]
  f32 layout the decode combine uses — the building block for ring /
  sequence-parallel prefill merging (the blockwise LSE-merge math of
  ``flash_decode.combine_partials``).
"""

from __future__ import annotations

import functools
import math
import operator

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.kernels.gemm import (
    apply_soft_cap,
    largest_divisor_block,
    resolve_impl,
    use_fallback,
)
from triton_dist_tpu.language.interpret import maybe_interpret

NEG_INF = -1.0e30  # finite -inf proxy: survives exp/log without NaNs


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _visibility_mask(q_start, k_start, *, causal, window, group, bq, bk):
    """THE masking rule, shared by the forward/int8/backward kernels so
    they can never diverge: key at kpos is visible to the query at qpos
    iff (not causal or qpos >= kpos) and (not window or
    qpos - kpos < window).  Returns a [G, bq, bk] bool mask (only called
    when causal or window is set)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (group, bq, bk), 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (group, bq, bk), 2)
    qpos = q_start + rows
    kpos = k_start + cols
    if causal and window:
        return (qpos >= kpos) & (qpos - kpos < window)
    if causal:
        return qpos >= kpos
    return qpos - kpos < window


def _block_live(q_start, k_start, *, causal, window, bq, bk):
    """Whole-block skip predicate matching :func:`_visibility_mask`:
    False when no (qpos, kpos) pair in the block is visible."""
    live = True
    if causal:
        # block entirely in the future of every q row
        live = k_start <= q_start + (bq - 1)
    if window:
        # block entirely past every q row's window
        live = live & (k_start + (bk - 1) > q_start - window)
    return live


def _block_full(q_start, k_start, *, causal, window, bq, bk):
    """Whole-block FULL-visibility predicate matching
    :func:`_visibility_mask`: True when EVERY (qpos, kpos) pair in the
    block is visible — such blocks route to a mask-free kernel body (r5:
    the ceiling experiment showed the per-element mask build, not the
    MXU feed, bounds the causal prefill; at bq=128/bk=1024 ~7 of 8 live
    causal blocks qualify).  Shared by the bf16/int8 kernels so the
    routing can never diverge from the mask itself."""
    full = True
    if causal:
        # every row's last visible key covers the whole block
        full = q_start >= k_start + (bk - 1)
    if window:
        # ...and the earliest row's window still reaches column 0
        full = full & ((q_start + (bq - 1)) - k_start < window)
    return full


def _flash_kernel(qoffs_ref, koffs_ref, q_ref, k_ref, v_ref, out_ref,
                  lse_ref, acc_ref, m_ref, l_ref, *, bq, bk, n_k, causal,
                  scale, group, soft_cap=0.0, window=0):
    """Grid (B, Hkv, nQ, nK); one (batch, kv-head, q-block) accumulates
    across the sequential KV-block axis.

    Block shapes: q/out [1, 1, G, bq, D]; k/v [1, 1, bk, D];
    lse [1, 1, G, bq] f32.  Scratch: acc [G, bq, D], m/l [G, bq] f32 —
    3D/2D per-row state so every reshape in the kernel only splits or
    collapses LEADING dims (free in Mosaic; lane-changing reshapes are
    relayouts).

    ``qoffs/koffs`` [nQ]/[nK] scalar-prefetch vectors give each BLOCK its
    global start position — contiguous layouts get an arithmetic ramp;
    segmented layouts (the zigzag CP shard: two position runs per device)
    get per-run ramps.  Rows within one block are always contiguous.
    """
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    iq = pl.program_id(2)
    q_start = qoffs_ref[iq]               # global position of q row 0
    k_start = koffs_ref[ik]               # global position of k row 0

    def body(masked):
        q = q_ref[0, 0].reshape(group * bq, -1)           # [G*bq, D]
        k = k_ref[0, 0]                                   # [bk, D]
        v = v_ref[0, 0]                                   # [bk, D]

        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32).reshape(
                group, bq, bk) * scale                    # [G, bq, bk]
        logits = apply_soft_cap(logits, soft_cap)
        # (A base-2 exp fold — exp2 with log2e in the scale — measured
        # NO gain here: Mosaic already lowers exp that way.  r5 ceiling
        # experiment, scripts/exp_prefill_ceiling.py.)

        if masked:
            mask = _visibility_mask(q_start, k_start, causal=causal,
                                    window=window, group=group, bq=bq,
                                    bk=bk)
            logits = jnp.where(mask, logits, NEG_INF)

        m_cur = m_ref[:]                                  # [G, bq]
        m_new = jnp.maximum(m_cur, jnp.max(logits, axis=-1))
        # m only grows; rows with nothing visible yet stay at NEG_INF and
        # exp(NEG - NEG) = 1 would poison them — mask p explicitly.
        p = jnp.exp(logits - m_new[..., None])            # [G, bq, bk]
        if masked:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_cur - m_new)                    # [G, bq]
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.reshape(group * bq, bk).astype(v.dtype), v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [G*bq, D]
        acc_ref[:] = (acc_ref[:] * alpha[..., None]
                      + pv.reshape(group, bq, -1))

    if causal or window:
        # Skip blocks with no visible (qpos, kpos) pair — their DMAs
        # already streamed; compute is the prefill bottleneck.  Among
        # the LIVE blocks, route fully-visible ones to the MASK-FREE
        # body (the r5 ceiling fix, scripts/exp_prefill_ceiling.py:
        # +7.5% paired; see _block_full).
        live = _block_live(q_start, k_start, causal=causal,
                           window=window, bq=bq, bk=bk)
        full = _block_full(q_start, k_start, causal=causal,
                           window=window, bq=bq, bk=bk)
        pl.when(live & full)(functools.partial(body, False))
        pl.when(live & jnp.logical_not(full))(functools.partial(body, True))
    else:
        body(False)

    @pl.when(ik == n_k - 1)
    def _():
        l = l_ref[:]                                      # [G, bq]
        # All-masked rows (ring: KV wholly in future) have acc == 0 and
        # l == 0: clamping the divisor yields 0/tiny = 0 without a bool
        # minor-dim insert (Mosaic only supports those for 32-bit types).
        out = acc_ref[:] / jnp.maximum(l, 1e-30)[..., None]
        out_ref[0, 0] = out.astype(out_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            l > 0.0, m_ref[:] + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)


def _flash_kernel_i8(qoffs_ref, koffs_ref, q_ref, k_ref, v_ref, ks_ref,
                     vs_ref, out_ref, lse_ref, acc_ref, m_ref, l_ref, *,
                     bq, bk, n_k, causal, scale, group, soft_cap=0.0,
                     window=0):
    """int8-KV twin of :func:`_flash_kernel` (the decode `_decode_kernel_i8`
    recipe applied to prefill): K/V stream as int8 with per-position f32
    scales riding LANE-PACKED [B, Hkv, Sk/128, 128] planes — K's scale
    rescales the logit columns after the QK matmul, V's folds into P
    before the PV matmul; both matmuls stay on the MXU in q's dtype."""
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    iq = pl.program_id(2)
    q_start = qoffs_ref[iq]
    k_start = koffs_ref[ik]

    def body(masked):
        q = q_ref[0, 0].reshape(group * bq, -1)           # [G*bq, D]
        k = k_ref[0, 0].astype(q.dtype)                   # [bk, D] i8→q
        v = v_ref[0, 0].astype(q.dtype)
        ksc = ks_ref[0, 0].reshape(-1)                    # [bk] f32
        vsc = vs_ref[0, 0].reshape(-1)

        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        logits = (logits * (ksc[None, :] * scale)).reshape(group, bq, bk)
        logits = apply_soft_cap(logits, soft_cap)

        if masked:
            mask = _visibility_mask(q_start, k_start, causal=causal,
                                    window=window, group=group, bq=bq,
                                    bk=bk)
            logits = jnp.where(mask, logits, NEG_INF)

        m_cur = m_ref[:]
        m_new = jnp.maximum(m_cur, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        if masked:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_cur - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            (p.reshape(group * bq, bk) * vsc[None, :]).astype(v.dtype), v,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = (acc_ref[:] * alpha[..., None]
                      + pv.reshape(group, bq, -1))

    if causal or window:
        # Mask-free routing for fully-visible blocks (see _block_full).
        live = _block_live(q_start, k_start, causal=causal,
                           window=window, bq=bq, bk=bk)
        full = _block_full(q_start, k_start, causal=causal,
                           window=window, bq=bq, bk=bk)
        pl.when(live & full)(functools.partial(body, False))
        pl.when(live & jnp.logical_not(full))(functools.partial(body, True))
    else:
        body(False)

    @pl.when(ik == n_k - 1)
    def _():
        l = l_ref[:]
        out = acc_ref[:] / jnp.maximum(l, 1e-30)[..., None]
        out_ref[0, 0] = out.astype(out_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            l > 0.0, m_ref[:] + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)


# ---------------------------------------------------------------------------
# Backward kernels (flash gradient — no S^2 materialization)
# ---------------------------------------------------------------------------
#
# Standard flash-attention backward split into two kernels so each output
# has one sequential accumulation axis:
#   dq kernel : grid (B, Hkv, nQ, nK) — KV innermost, dq block in scratch
#   dkv kernel: grid (B, Hkv, nK, nQ) — Q innermost, dk/dv blocks in scratch
# Both recompute P from (q, k, lse) blockwise:
#   p_ij  = exp(scale * q_i k_j - lse_i)          (0 where causally masked)
#   dv_j  = sum_i p_ij do_i
#   dp_ij = do_i . v_j
#   ds_ij = p_ij * (dp_ij - delta_i) * scale,  delta_i = sum(do_i * out_i)
#   dq_i  = sum_j ds_ij k_j ;  dk_j = sum_i ds_ij q_i
# delta is a cheap elementwise rowsum computed in XLA before the kernels.


def _recompute_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, q_start,
                    k_start, *, causal, scale, group, bq, bk,
                    soft_cap=0.0, window=0, masked=True):
    """Shared backward block math: recompute P from (q, k, lse) and form
    dS — the one place the masking/NEG_INF rules live for both backward
    kernels.  Returns (p, ds) [G, bq, bk] f32 plus the flat q/do views.

    exp may produce inf in lanes the mask discards (fully-masked rows
    carry lse = NEG_INF); the where keeps them out of the matmuls.
    ``masked=False`` (r5): the caller proved the whole block fully
    visible (`_block_full`) — skip the per-element mask build, the same
    routing as the forward kernels.
    """
    q = q_ref[0, 0].reshape(group * bq, -1)               # [G*bq, D]
    k = k_ref[0, 0]                                       # [bk, D]
    v = v_ref[0, 0]
    do = do_ref[0, 0].reshape(group * bq, -1)             # [G*bq, D]
    lse = lse_ref[0, 0]                                   # [G, bq]
    dl = dl_ref[0, 0]                                     # [G, bq]

    s_raw = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).reshape(group, bq, bk) * scale
    if soft_cap:
        t = jnp.tanh(s_raw / soft_cap)
        s = soft_cap * t
        dcap = 1.0 - t * t          # d(cap*tanh(x/cap))/dx
    else:
        s = s_raw
        dcap = None
    e = jnp.exp(s - lse[..., None])
    if masked and (causal or window):
        p = jnp.where(_visibility_mask(q_start, k_start, causal=causal,
                                       window=window, group=group, bq=bq,
                                       bk=bk), e, 0.0)
    else:
        p = e
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).reshape(group, bq, bk)
    ds = p * (dp - dl[..., None]) * scale                 # [G, bq, bk]
    if dcap is not None:
        ds = ds * dcap              # chain rule through the capping tanh
    return p, ds, q, do


def _flash_bwd_dq_kernel(qoffs_ref, koffs_ref, q_ref, k_ref, v_ref,
                         do_ref, lse_ref, dl_ref, dq_ref, acc_ref, *, bq,
                         bk, n_k, causal, scale, group, soft_cap=0.0,
                         window=0):
    ik = pl.program_id(3)

    @pl.when(ik == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    iq = pl.program_id(2)
    q_start = qoffs_ref[iq]
    k_start = koffs_ref[ik]

    def body(masked):
        k = k_ref[0, 0]                                   # [bk, D]
        _, ds, _, _ = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, q_start,
            k_start, causal=causal, scale=scale, group=group, bq=bq, bk=bk,
            soft_cap=soft_cap, window=window, masked=masked)
        upd = jax.lax.dot_general(
            ds.reshape(group * bq, bk).astype(k.dtype), k,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [G*bq, D]
        acc_ref[:] = acc_ref[:] + upd.reshape(group, bq, -1)

    if causal or window:
        live = _block_live(q_start, k_start, causal=causal,
                           window=window, bq=bq, bk=bk)
        full = _block_full(q_start, k_start, causal=causal,
                           window=window, bq=bq, bk=bk)
        pl.when(live & full)(functools.partial(body, False))
        pl.when(live & jnp.logical_not(full))(functools.partial(body, True))
    else:
        body(False)

    @pl.when(ik == n_k - 1)
    def _():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(qoffs_ref, koffs_ref, q_ref, k_ref, v_ref,
                          do_ref, lse_ref, dl_ref, dk_ref, dv_ref, dk_acc,
                          dv_acc, *, bq, bk, n_q, causal, scale, group,
                          soft_cap=0.0, window=0):
    iq = pl.program_id(3)

    @pl.when(iq == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    ikb = pl.program_id(2)
    q_start = qoffs_ref[iq]
    k_start = koffs_ref[ikb]

    def body(masked):
        p, ds, q, do = _recompute_p_ds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, q_start,
            k_start, causal=causal, scale=scale, group=group, bq=bq, bk=bk,
            soft_cap=soft_cap, window=window, masked=masked)
        # dv_j = sum_i p_ij do_i  — contract over the G*bq row axis.
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.reshape(group * bq, bk).astype(do.dtype), do,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, D]
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.reshape(group * bq, bk).astype(q.dtype), q,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bk, D]

    live = True
    if causal:
        # This KV block gets gradient only from q rows at positions
        # >= k_start; skip inner q blocks entirely before it.
        live = q_start + (bq - 1) >= k_start
    if window:
        # ...and only from q rows whose window still reaches it.
        live = live & (q_start < k_start + (bk - 1) + window)
    if causal or window:
        full = _block_full(q_start, k_start, causal=causal,
                           window=window, bq=bq, bk=bk)
        pl.when(live & full)(functools.partial(body, False))
        pl.when(live & jnp.logical_not(full))(functools.partial(body, True))
    else:
        body(False)

    @pl.when(iq == n_q - 1)
    def _():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _as_starts(starts_or_offset):
    """Normalize an offset-like argument to a tuple of run starts: a
    scalar offset means ONE contiguous run."""
    if isinstance(starts_or_offset, (tuple, list)):
        return tuple(starts_or_offset)
    return (starts_or_offset,)


def _block_starts(starts, total, blk):
    """[n_blocks] int32 per-block global start positions: ``total`` rows
    split evenly over ``len(starts)`` runs, each run split into ``blk``-row
    blocks.  Works for python ints and traced scalars alike (the result
    rides scalar prefetch)."""
    n_runs = len(starts)
    run = total // n_runs
    assert run % blk == 0, (total, n_runs, blk)
    ramp = jnp.arange(run // blk, dtype=jnp.int32) * blk
    return (jnp.stack([jnp.asarray(s, jnp.int32) for s in starts])[:, None]
            + ramp[None, :]).reshape(-1)


def _bwd_blocks(Sq, Sk, n_runs_q, n_runs_k, block_q, block_k):
    """Backward block sizes, clamped to the RUN length so every block's
    rows are position-contiguous (segmented layouts)."""
    bq = largest_divisor_block(Sq // n_runs_q, block_q or 128, 128)
    bk = largest_divisor_block(Sk // n_runs_k, block_k or 512, 128)
    return bq, bk


def _flash_bwd_pallas(q, k, v, out, lse, do, q_offset, kv_offset, causal,
                      scale, interpret, soft_cap=0.0, block_q=None,
                      block_k=None, window=0, grad_dtype=None):
    """Blockwise gradients (dq, dk, dv) in the primal dtypes, or in
    ``grad_dtype`` when set (the ring caller asks for f32 so its cross-ring
    accumulation never rounds per-block summands to bf16).

    ``q_offset``/``kv_offset`` may each be a scalar (one contiguous run)
    or a tuple of run starts (segmented layout — the zigzag CP shard).

    Default blocks (bq=128, bk=512) from the r4 chip sweep
    (bench_flash_prefill --grad --bwd-blocks); both kernels keep more
    operands resident than the forward (q, k, v, do + two accumulators),
    so the forward's bk=1024 does NOT transfer."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    g = Hq // Hkv
    q_starts = _as_starts(q_offset)
    kv_starts = _as_starts(kv_offset)
    bq, bk = _bwd_blocks(Sq, Sk, len(q_starts), len(kv_starts), block_q,
                         block_k)
    n_q, n_k = Sq // bq, Sk // bk
    dq_dtype = grad_dtype or q.dtype
    dk_dtype = grad_dtype or k.dtype
    dv_dtype = grad_dtype or v.dtype

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                               # [B, Hq, Sq]
    qg = q.reshape(B, Hkv, g, Sq, D)
    dog = do.reshape(B, Hkv, g, Sq, D)
    lseg = lse.reshape(B, Hkv, g, Sq)
    dlg = delta.reshape(B, Hkv, g, Sq)
    qoffs = _block_starts(q_starts, Sq, bq)
    koffs = _block_starts(kv_starts, Sk, bk)

    q_spec = pl.BlockSpec((1, 1, g, bq, D),
                          lambda b, h, i, j, qo, ko: (b, h, 0, i, 0))
    row_spec = pl.BlockSpec((1, 1, g, bq),
                            lambda b, h, i, j, qo, ko: (b, h, 0, i))
    kv_spec = pl.BlockSpec((1, 1, bk, D),
                           lambda b, h, i, j, qo, ko: (b, h, j, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, bq=bq, bk=bk, n_k=n_k,
                          causal=causal, scale=float(scale), group=g,
                          soft_cap=soft_cap, window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, Hkv, n_q, n_k),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
            out_specs=[q_spec],
            scratch_shapes=[pltpu.VMEM((g, bq, D), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((B, Hkv, g, Sq, D), dq_dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=maybe_interpret(interpret),
    )(qoffs, koffs, qg, k, v, dog, lseg, dlg)[0]

    # dkv: Q axis innermost/sequential; note the (i, j) grid roles swap.
    q_spec2 = pl.BlockSpec((1, 1, g, bq, D),
                           lambda b, h, j, i, qo, ko: (b, h, 0, i, 0))
    row_spec2 = pl.BlockSpec((1, 1, g, bq),
                             lambda b, h, j, i, qo, ko: (b, h, 0, i))
    kv_spec2 = pl.BlockSpec((1, 1, bk, D),
                            lambda b, h, j, i, qo, ko: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, bq=bq, bk=bk, n_q=n_q,
                          causal=causal, scale=float(scale), group=g,
                          soft_cap=soft_cap, window=window),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, Hkv, n_k, n_q),
            in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2,
                      row_spec2],
            out_specs=[kv_spec2, kv_spec2],
            scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                            pltpu.VMEM((bk, D), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((B, Hkv, Sk, D), dk_dtype),
                   jax.ShapeDtypeStruct((B, Hkv, Sk, D), dv_dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=maybe_interpret(interpret),
    )(qoffs, koffs, qg, k, v, dog, lseg, dlg)
    return dq.reshape(B, Hq, Sq, D), dk, dv


# ---------------------------------------------------------------------------
# Dense fallback (XLA) — same contract incl. offsets and lse
# ---------------------------------------------------------------------------


def _run_positions(starts, total):
    """[total] int32 global positions for ``total`` rows split evenly over
    the runs in ``starts`` (scalar offset ≡ one run)."""
    starts = _as_starts(starts)
    run = total // len(starts)
    ramp = jnp.arange(run, dtype=jnp.int32)
    return (jnp.stack([jnp.asarray(s, jnp.int32) for s in starts])[:, None]
            + ramp[None, :]).reshape(-1)


def _flash_xla(q, k, v, *, causal, scale, q_offset, kv_offset,
               k_scale=None, v_scale=None, soft_cap=0.0, window=0):
    """O(S^2)-memory reference path: out [B, Hq, Sq, D] in q.dtype,
    lse [B, Hq, Sq] f32.  Optional ``k/v_scale`` [B, Hkv, Sk] dequantize
    an int8 K/V (the decode `_local_decode_xla` recipe).  Offsets may be
    run-start tuples (segmented layouts)."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    g = Hq // Hkv
    qf = q.astype(jnp.float32).reshape(B, Hkv, g, Sq, D)
    logits = jnp.einsum("bhgsd,bhtd->bhgst", qf,
                        k.astype(jnp.float32)) * scale
    if k_scale is not None:
        logits = logits * k_scale[:, :, None, None, :]
    logits = apply_soft_cap(logits, soft_cap)
    if causal or window:
        rows = _run_positions(q_offset, Sq)[:, None]
        cols = _run_positions(kv_offset, Sk)[None, :]
        mask = (rows >= cols) if causal else jnp.ones(
            (Sq, Sk), bool)                               # [Sq, Sk]
        if window:
            mask = mask & (rows - cols < window)
        logits = jnp.where(mask[None, None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                          # [B,Hkv,g,Sq]
    nonempty = m > NEG_INF / 2
    p = jnp.exp(logits - m[..., None])
    if causal or window:
        p = jnp.where(mask[None, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    if v_scale is not None:
        p = p * v_scale[:, :, None, None, :]
    out = jnp.einsum("bhgst,bhtd->bhgsd", p, v.astype(jnp.float32))
    out = jnp.where(nonempty[..., None],
                    out / jnp.where(nonempty, l, 1.0)[..., None], 0.0)
    lse = jnp.where(nonempty, m + jnp.log(jnp.where(nonempty, l, 1.0)),
                    NEG_INF)
    return (out.reshape(B, Hq, Sq, D).astype(q.dtype),
            lse.reshape(B, Hq, Sq))


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------


def flash_shapes_ok(sq: int, sk: int, d: int, n_runs_q: int = 1,
                    n_runs_k: int = 1) -> bool:
    """Lane/sublane legality for the flash tiles: q/k blocks need 128-lane
    D, and the lse output block's lane dim is the q-block (so Sq must tile
    by 128); Sk tiles by 128 for the KV blocks.  Segmented layouts need
    each RUN to tile by 128 (blocks never straddle a run boundary)."""
    return (d % 128 == 0 and sq % n_runs_q == 0 and sk % n_runs_k == 0
            and (sq // n_runs_q) % 128 == 0 and (sk // n_runs_k) % 128 == 0)


def flash_attention(q, k, v, *, causal=True, scale=None, q_offset=0,
                    kv_offset=0, block_q=None, block_k=None, impl="auto",
                    interpret=False, return_lse=False, k_scale=None,
                    v_scale=None, soft_cap=0.0, window=0):
    """Public entry: :func:`_flash_attention_dispatch` under a
    ``profiling.annotate`` launch-metadata span (name/flops/bytes land
    in the profiler timeline — the contract every public kernel entry
    point keeps, enforced by the tests/test_observability.py
    annotation meta-test).  Causal masking halves the score flops."""
    from triton_dist_tpu.runtime.profiling import annotate

    B, Hq, Sq, D = q.shape
    Sk = k.shape[2]
    el = jnp.dtype(q.dtype).itemsize
    flops = 4 * B * Hq * Sq * Sk * D // (2 if causal else 1)
    with annotate("flash_attention", flops=flops,
                  bytes_accessed=(q.size + k.size + v.size
                                  + q.size) * el):
        return _flash_attention_dispatch(
            q, k, v, causal=causal, scale=scale, q_offset=q_offset,
            kv_offset=kv_offset, block_q=block_q, block_k=block_k,
            impl=impl, interpret=interpret, return_lse=return_lse,
            k_scale=k_scale, v_scale=v_scale, soft_cap=soft_cap,
            window=window)


def _flash_attention_dispatch(q, k, v, *, causal=True, scale=None,
                              q_offset=0, kv_offset=0, block_q=None,
                              block_k=None, impl="auto",
                              interpret=False, return_lse=False,
                              k_scale=None, v_scale=None, soft_cap=0.0,
                              window=0):
    """Blockwise GQA attention: q [B, Hq, Sq, D], k/v [B, Hkv, Sk, D] →
    out [B, Hq, Sq, D] in q.dtype (+ lse [B, Hq, Sq] f32 when
    ``return_lse``).

    ``q_offset``/``kv_offset`` are the global positions of q row 0 / k
    row 0 (python ints or traced scalars — they ride scalar prefetch, so
    chunked prefill reuses one trace across chunks).  The causal rule is
    ``q_offset + i >= kv_offset + j``.

    ``k_scale``/``v_scale`` [B, Hkv, Sk] f32 dequantize an int8 K/V
    (the serving int8-KV cache): the pallas path fuses the scales into
    the block loop (``_flash_kernel_i8``), the fallback into the dense
    stream.  The quantized path is forward-only (serving).

    ``window`` (sliding-window attention, Mistral-style): key at kpos is
    visible iff ``qpos - kpos < window`` (the current token counts, so
    position qpos attends to [qpos - window + 1, qpos]); composes with
    the offsets and with ``causal``, and blocks wholly outside the
    window skip their compute — differentiable like the causal path.

    SEGMENTED layouts: ``q_offset``/``kv_offset`` may each be a TUPLE of
    run starts — the rows then consist of len(tuple) equal-length
    position-contiguous runs (the zigzag CP shard holds chunks i and
    2w-1-i).  Blocks never straddle runs; each run must tile by 128 for
    the pallas path.
    """
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    assert Hq % Hkv == 0, (Hq, Hkv)
    g = Hq // Hkv
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    raw_impl = impl
    impl = resolve_impl(impl, interpret)
    quantized = k_scale is not None
    n_runs_q = len(_as_starts(q_offset))
    n_runs_k = len(_as_starts(kv_offset))
    seg_q, seg_k = Sq // max(n_runs_q, 1), Sk // max(n_runs_k, 1)

    if use_fallback(raw_impl, impl,
                    flash_shapes_ok(Sq, Sk, D, n_runs_q, n_runs_k),
                    "flash_attention",
                    f"(Sq={Sq}, Sk={Sk}, D={D}, runs={n_runs_q}/{n_runs_k})"
                    f" needs each run %128 == 0 and D%128 == 0"):
        out, lse = _flash_xla(q, k, v, causal=causal, scale=scale,
                              q_offset=q_offset, kv_offset=kv_offset,
                              k_scale=k_scale, v_scale=v_scale,
                              soft_cap=soft_cap, window=window)
        return (out, lse) if return_lse else out

    # Block defaults from the real-chip sweep (docs/perf.md): SMALL q
    # blocks win for causal prefill — bq=128 at G=4 runs ~107 TFLOPS vs
    # ~60 for bq=512/bk=512 (finer causal-skip granularity: the diagonal
    # blocks waste bq*bk/2 masked MXU ops, so shrinking bq cuts the waste
    # and the skip test prunes more k blocks per q row).  bk=1024 beats
    # 512 (longer MXU streams per grid step) and 2048+ (VMEM pressure
    # crowds the pipeline).  G*bq ~ 512 MXU rows balances group sizes.
    want_q = block_q or max(128, (512 // g) // 128 * 128)
    # Blocks fit the RUN (== the whole axis for contiguous layouts).
    bq = largest_divisor_block(seg_q, want_q, 128)
    bk = largest_divisor_block(seg_k, block_k or 1024, 128)

    if quantized:
        # Lane-packed scale planes need (bk//128) % 8 == 0 or bk == Sk
        # (the decode kernel's constraint — the bk == Sk escape is
        # WHOLE-ARRAY-block legality, so it does not apply to a segmented
        # run); bump to the smallest legal divisor of the run.
        # Forward-only — serving reads an int8 cache; training does not
        # quantize K/V.
        if (bk // 128) % 8 and bk != Sk:
            legal = next((c for c in range(bk, seg_k + 1, 128)
                          if seg_k % c == 0 and (c // 128) % 8 == 0), None)
            if legal is None and n_runs_k == 1:
                legal = Sk          # whole-array-block escape
            if legal is None:
                # Segmented run with no lane-pack-legal block: dense path.
                out, lse = _flash_xla(
                    q, k, v, causal=causal, scale=scale,
                    q_offset=q_offset, kv_offset=kv_offset,
                    k_scale=k_scale, v_scale=v_scale, soft_cap=soft_cap,
                    window=window)
                return (out, lse) if return_lse else out
            bk = legal
        out, lse = _flash_pallas(q, k, v, q_offset, kv_offset, causal,
                                 float(scale), bq, bk, interpret,
                                 k_scale=k_scale, v_scale=v_scale,
                                 soft_cap=soft_cap, window=window)
        return (out, lse) if return_lse else out

    def _static_int(x):
        """Any index-like (int, np.integer, concrete 0-d array) → int;
        run-start tuples → tuple of ints (hashable for the custom-VJP
        nondiff slot); traced offsets → None (they ride scalar prefetch,
        raw path)."""
        try:
            if isinstance(x, (tuple, list)):
                return tuple(operator.index(e) for e in x)
            return operator.index(x)
        except TypeError:
            return None

    qo, ko = _static_int(q_offset), _static_int(kv_offset)
    if not return_lse and qo is not None and ko is not None:
        # Static offsets (model forward paths): differentiable wrapper.
        # The backward is the blockwise flash gradient (dq + dkv pallas
        # kernels recomputing P from the saved lse) — O(S) memory on
        # both passes.
        return _flash_diff(q, k, v, qo, ko, causal,
                           float(scale), bq, bk, interpret, soft_cap,
                           window)
    out, lse = _flash_pallas(q, k, v, q_offset, kv_offset, causal,
                             float(scale), bq, bk, interpret,
                             soft_cap=soft_cap, window=window)
    return (out, lse) if return_lse else out


def _flash_pallas(q, k, v, q_offset, kv_offset, causal, scale, bq, bk,
                  interpret, k_scale=None, v_scale=None, soft_cap=0.0,
                  window=0):
    """The raw pallas_call: out [B, Hq, Sq, D] in q.dtype, lse f32.
    ``q_offset``/``kv_offset``: scalar or tuple of run starts (segmented
    layouts — the caller guarantees the run length divides by the block)."""
    B, Hq, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    g = Hq // Hkv
    n_q, n_k = Sq // bq, Sk // bk

    qg = q.reshape(B, Hkv, g, Sq, D)
    qoffs = _block_starts(_as_starts(q_offset), Sq, bq)
    koffs = _block_starts(_as_starts(kv_offset), Sk, bk)
    quantized = k_scale is not None
    if quantized:
        kern = functools.partial(_flash_kernel_i8, bq=bq, bk=bk, n_k=n_k,
                                 causal=causal, scale=float(scale), group=g,
                                 soft_cap=soft_cap, window=window)
    else:
        kern = functools.partial(_flash_kernel, bq=bq, bk=bk, n_k=n_k,
                                 causal=causal, scale=float(scale), group=g,
                                 soft_cap=soft_cap, window=window)
    in_specs = [
        pl.BlockSpec((1, 1, g, bq, D),
                     lambda b, h, i, j, qo, ko: (b, h, 0, i, 0)),
        pl.BlockSpec((1, 1, bk, D),
                     lambda b, h, i, j, qo, ko: (b, h, j, 0)),
        pl.BlockSpec((1, 1, bk, D),
                     lambda b, h, i, j, qo, ko: (b, h, j, 0)),
    ]
    args = [qoffs, koffs, qg, k, v]
    if quantized:
        # Lane-packed [B, Hkv, Sk//128, 128] scale planes: each block's
        # bk scales are ONE dense [bk//128, 128] f32 transfer (the
        # decode kernel's layout — a [bk, 1] plane DMAs thousands of
        # strided 4-byte rows and measured 9x slower).
        sc_spec = pl.BlockSpec((1, 1, bk // 128, 128),
                               lambda b, h, i, j, qo, ko: (b, h, j, 0))
        in_specs += [sc_spec, sc_spec]
        args += [k_scale.reshape(B, Hkv, Sk // 128, 128),
                 v_scale.reshape(B, Hkv, Sk // 128, 128)]
    out, lse = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, Hkv, n_q, n_k),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, 1, g, bq, D),
                             lambda b, h, i, j, qo, ko: (b, h, 0, i, 0)),
                pl.BlockSpec((1, 1, g, bq),
                             lambda b, h, i, j, qo, ko: (b, h, 0, i)),
            ],
            scratch_shapes=[
                pltpu.VMEM((g, bq, D), jnp.float32),
                pltpu.VMEM((g, bq), jnp.float32),
                pltpu.VMEM((g, bq), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, g, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hkv, g, Sq), jnp.float32),
        ],
        # Only the KV axis carries the accumulator; (b, h, iq) blocks are
        # independent — declaring them parallel lets Mosaic pipeline
        # across block boundaries (the 96%-MXU GEMM knob).
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=maybe_interpret(interpret),
    )(*args)
    return out.reshape(B, Hq, Sq, D), lse.reshape(B, Hq, Sq)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash_diff(q, k, v, q_offset, kv_offset, causal, scale, bq, bk,
                interpret, soft_cap=0.0, window=0):
    return _flash_pallas(q, k, v, q_offset, kv_offset, causal, scale, bq,
                         bk, interpret, soft_cap=soft_cap,
                         window=window)[0]


def _flash_diff_fwd(q, k, v, q_offset, kv_offset, causal, scale, bq, bk,
                    interpret, soft_cap=0.0, window=0):
    out, lse = _flash_pallas(q, k, v, q_offset, kv_offset, causal, scale,
                             bq, bk, interpret, soft_cap=soft_cap,
                             window=window)
    return out, (q, k, v, out, lse)


def _flash_diff_bwd(q_offset, kv_offset, causal, scale, bq, bk, interpret,
                    soft_cap, window, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, g, q_offset, kv_offset,
                             causal, scale, interpret, soft_cap=soft_cap,
                             window=window)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


# ---------------------------------------------------------------------------
# Autotuned entry + AOT registration (tooling parity with the GEMM family)
# ---------------------------------------------------------------------------

from triton_dist_tpu.autotuner import Config as _Cfg, autotune as _autotune

# Real-chip sweep (docs/perf.md): bq=128/bk=1024 wins causal prefill by
# ~25% over bq=512 (finer causal-skip granularity); the space brackets it.
FLASH_TUNE_SPACE = (
    _Cfg(block_q=128, block_k=1024),
    _Cfg(block_q=128, block_k=512),
    _Cfg(block_q=256, block_k=1024),
    _Cfg(block_q=512, block_k=512),
)


@_autotune(configs=FLASH_TUNE_SPACE, key=())
def _flash_tunable(q, k, v, *, causal, scale, interpret, block_q=None,
                   block_k=None):
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           impl="pallas", interpret=interpret)


def flash_attention_autotuned(q, k, v, *, causal=True, scale=None,
                              interpret=False):
    """:func:`flash_attention` with (block_q, block_k) selected by the
    autotuner — same lockstep/``is_dist`` rules as ``ag_gemm_autotuned``
    (winners cached per shape/dtype; on the tunnel chip use
    scripts/autotune_onchip.py's chain measure instead)."""
    return _flash_tunable(q, k, v, causal=causal, scale=scale,
                          interpret=interpret)


def _register_flash_aot():
    """AOT export spaces for the prefill kernel (serving shapes: GQA
    32/8, head_dim 128 — the bench/serving point of docs/perf.md)."""
    from triton_dist_tpu.tools.compile_aot import aot_compile_spaces

    b, hq, hkv, d = 1, 32, 8, 128
    sig = [
        [((b, hq, 4096, d), "bfloat16"), ((b, hkv, 4096, d), "bfloat16"),
         ((b, hkv, 4096, d), "bfloat16")],
        [((b, hq, 512, d), "float32"), ((b, hkv, 512, d), "float32"),
         ((b, hkv, 512, d), "float32")],
    ]

    def algos(platforms):
        out = [{"impl": "xla"}]
        if "tpu" in platforms:
            out += [{"block_q": 128, "block_k": 1024, "impl": "pallas"},
                    {"block_q": 512, "block_k": 512, "impl": "pallas"}]
        return out

    return aot_compile_spaces({
        "flash_prefill": {
            "signature": sig,
            "algo_infos": algos,
        },
    })


@_register_flash_aot()
def flash_prefill_aot(q, k, v, *, impl="auto", block_q=None, block_k=None,
                      interpret=False):
    """AOT-exportable causal prefill entry (fixed causal=True surface —
    the serving path; the full API is :func:`flash_attention`)."""
    return flash_attention(q, k, v, causal=True, block_q=block_q,
                           block_k=block_k, impl=impl, interpret=interpret)


def sp_flash_attention_shard(q, k_shard, v_shard, *, axis, causal=True,
                             scale=None, q_offset=0, impl="auto",
                             interpret=False, k_scale=None, v_scale=None,
                             soft_cap=0.0, window=0):
    """Sequence-parallel prefill attention; call inside shard_map.

    q [B, Hq, Sq, D] replicated (the current chunk's queries); k/v_shard
    [B, Hkv, S_loc, D] sequence-sharded over ``axis``.  Each device runs
    flash over its KV shard at its global offset, then the per-shard
    (out, lse) partials LSE-merge — the decode SP recipe
    (flash_decode.sp_gqa_decode_shard) applied to prefill.  ``q_offset``
    may be traced (chunked prefill's ``prefix_len``).

    Under ``impl="auto"`` each shard's local attention takes the flash
    kernel when shapes allow and the dense fallback otherwise — both
    yield (out, lse) partials, so the combine is impl-agnostic.
    """
    world = jax.lax.axis_size(axis)
    me = jax.lax.axis_index(axis)
    s_loc = k_shard.shape[2]
    out, lse = flash_attention(
        q, k_shard, v_shard, causal=causal, scale=scale,
        q_offset=q_offset, kv_offset=me * s_loc, impl=impl,
        interpret=interpret, return_lse=True, k_scale=k_scale,
        v_scale=v_scale, soft_cap=soft_cap, window=window)
    if world == 1:
        return out
    # Weighted-REDUCE combine (combine_partials' math as collectives):
    # pmax of the small lse plane, then two psums — the payload crosses
    # the wire once as a reduction instead of materializing W gathered
    # copies per device.  All-masked rows (lse = NEG_INF everywhere):
    # m = NEG, w = exp(0) = 1, out = 0 → psum(0)/W = 0, never NaN.
    m = jax.lax.pmax(lse, axis)                           # [B, Hq, Sq]
    w = jnp.exp(lse - m)
    num = jax.lax.psum(out.astype(jnp.float32) * w[..., None], axis)
    denom = jax.lax.psum(w, axis)
    return (num / denom[..., None]).astype(q.dtype)


def flash_gqa_attention(q, k, v, *, causal=True, scale=None, impl="auto",
                        interpret=False, window=0, soft_cap=0.0):
    """Drop-in for ``attention.dense_gqa_attention`` — the model families'
    [S, B, H, D] layout.  q [S, B, Hq, D]; k/v [S, B, Hkv, D]; returns
    [S, B, Hq, D] in q's dtype."""
    qt = q.transpose(1, 2, 0, 3)                          # [B, Hq, S, D]
    kt = k.transpose(1, 2, 0, 3)
    vt = v.transpose(1, 2, 0, 3)
    out = flash_attention(qt, kt, vt, causal=causal, scale=scale,
                          impl=impl, interpret=interpret, window=window,
                          soft_cap=soft_cap)
    return out.transpose(2, 0, 1, 3)
