"""Overlapped AllGather-GEMM — the flagship tensor-parallel forward kernel.

Reference analog: ``python/triton_dist/kernels/nvidia/allgather_gemm.py`` —
a copy-engine/NVSHMEM producer streams A segments between ranks while a
persistent consumer GEMM spins on per-rank signals before consuming each
segment (``dl.wait`` + ``dl.consume_token`` at :226-227), with a rank-swizzled
tile order so every rank starts on its local data (:206-219).

TPU-native design (NOT a port): TPU has no user streams and no cross-kernel
spin loops, so producer and consumer live in ONE Pallas kernel:

* Outer loop over ``world`` ring steps.  At step ``s`` the device computes the
  GEMM for the A segment it already holds (slot ``(me - s) mod world`` — the
  rank-swizzle falls out of the ring schedule for free: step 0 is always the
  local segment, exactly like the reference's swizzle) while the same segment
  is simultaneously forwarded to the right ICI neighbor via async remote DMA.
* The inner GEMM is a nested Mosaic pipeline (``pltpu.emit_pipeline``) that
  streams (block_m, block_k) × (block_k, block_n) tiles HBM→VMEM into the MXU
  with a float32 VMEM accumulator — this plays the role of the reference's
  persistent TMA GEMM (allgather_gemm.py:133-254), and the Mosaic double
  buffering plays the role of the Triton software pipeliner.
* Per-segment readiness = the remote-copy recv semaphore (the reference's
  per-rank signal array + PTX spin wait, DistributedOpToLLVM.cpp:144-217,
  becomes a single ``recv_sem`` wait sized to the segment).

The kernel also materializes the gathered A (the reference keeps it in the
context workspace for later reuse, allgather_gemm.py:407-489).

Sharding contract (1-D TP over ``axis``):
  A: [M, K]   sharded P(axis, None)   (per-device [m_loc, K])
  B: [K, N]   sharded P(None, axis)   (per-device [K, n_loc])
  C: [M, N]   sharded P(None, axis)   (per-device [M, n_loc])
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.kernels.gemm import (
    MatmulConfig,
    gemm_pipeline_body,
    largest_divisor_block,
    matmul,
    pallas_shapes_ok,
    resolve_impl,
    use_fallback,
    wire_gemm_pipeline_body,
)
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit

from triton_dist_tpu.kernels.collective_ids import AG_GEMM as AG_GEMM_COLLECTIVE_ID


@dataclass
class AllGatherGEMMContext:
    """Reference analog: ``AllGatherGEMMTensorParallelContext``
    (allgather_gemm.py:407-489) — minus the symm workspace/streams, which on
    TPU are the kernel's own output buffer and DMA queues."""

    mesh: Mesh
    axis: str = "tp"
    impl: str = "auto"  # "auto" | "xla" | "pallas"
    config: MatmulConfig = field(default_factory=MatmulConfig)
    # Ring-forward sub-chunking (VERDICT r3 #9): each segment's forward
    # DMA is split into ``chunks`` row-chunks.  The receiver's byte-
    # counted recv wait is unchanged (c chunk DMAs carry the same total
    # bytes), but chunked sends give the DMA scheduler smaller units to
    # interleave with the pipeline's own HBM streams — the TPU analog of
    # the reference's SM budgeting, which ``perf_model.
    # overlap_chunk_budget`` models and the autotune space now sweeps.
    chunks: int = 1
    # "int8" ships the ring's A segments per-row-quantized with an f32
    # scale plane and dequantizes at the MXU feed (VERDICT r3 #3): ~2x
    # fewer allgather wire bytes for bf16 models; the gathered A comes
    # back as the dequantized reconstruction.  None ships A verbatim.
    wire_dtype: str | None = None
    # "bidir" (r5): segments split into halves ringing BOTH directions —
    # 2x wire bandwidth on a 1-axis mesh (wire-bound shapes: small M,
    # decode-time TP).  "uni" is the single-direction ring.
    ring_mode: str = "uni"
    interpret: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_ag_gemm_context(mesh, axis="tp", impl="auto", config=None,
                           chunks=1, wire_dtype=None, ring_mode="uni",
                           interpret=False) -> AllGatherGEMMContext:
    return AllGatherGEMMContext(
        mesh=mesh, axis=axis, impl=impl,
        config=config or MatmulConfig(), chunks=chunks,
        wire_dtype=wire_dtype, ring_mode=ring_mode, interpret=interpret,
    )


def _ag_gemm_bidir_kernel(
    a_ref, b_ref, ag_ref, out_ref,
    send_r, recv_r, send_l, recv_l, copy_sem, acc_ref,
    *, axis, world, m_loc, bm, bn, bk, out_dtype,
):
    """Bidirectional ring producer (r5, VERDICT r4 next#5): each segment
    splits into a TOP half that rings rightward and a BOTTOM half that
    rings leftward — both ICI link directions carry m_loc/2 rows per
    step, halving per-step wire time on a 1-axis mesh (the standalone
    ``BIDIR_RING``'s schedule fused into the producer; reference analog:
    its 2D/bidirectional producer variants, allgather.py:194-258).

    Step s consumes the two newly arrived halves — top of slot
    ``me - s`` and bottom of slot ``me + s`` — as two chained half-GEMMs
    in the ONE persistent MXU pipeline (same persistence machinery as
    ``_ag_gemm_kernel``; the recv waits fold into the second half-cycle's
    prefetch).  Per-direction semaphore pairs keep a fast neighbor's
    counter-direction arrival from satisfying the wrong wait.

    Wire-bound shapes (small M, decode-time TP) are where this wins;
    compute-bound shapes see the same overlap either way.  World-1
    aliases A like the unidirectional kernel — zero overhead.
    """
    K = a_ref.shape[1]
    n_loc = b_ref.shape[1]
    half = m_loc // 2
    n_m, n_n, n_k = half // bm, n_loc // bn, K // bk
    grid = (n_m, n_n, n_k)
    in_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
        pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
    ]
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))]

    inner = pltpu.emit_pipeline(
        functools.partial(gemm_pipeline_body, n_k=n_k, out_dtype=out_dtype),
        grid=grid, in_specs=in_specs, out_specs=out_specs,
    )

    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)

    # Stage the local segment into the gathered output (waited at exit).
    cp = pltpu.make_async_copy(
        a_ref, ag_ref.at[pl.ds(me * m_loc, m_loc)], copy_sem)
    cp.start()

    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)

    def top(slot):
        return pl.ds(slot * m_loc, half)

    def bot(slot):
        return pl.ds(slot * m_loc + half, half)

    def halves(s):
        """(src_ref, out_rows) pairs consumed at step s: the top half of
        slot me-s and the bottom half of slot me+s (s=0: both local,
        read from the input — the staging copy may be in flight)."""
        slot_t = jax.lax.rem(me - s + world, world)
        slot_b = jax.lax.rem(me + s, world)
        if s == 0:
            return [(a_ref.at[pl.ds(0, half)], top(slot_t)),
                    (a_ref.at[pl.ds(half, half)], bot(slot_b))]
        return [(ag_ref.at[top(slot_t)], top(slot_t)),
                (ag_ref.at[bot(slot_b)], bot(slot_b))]

    def run(allocs):
        for s in range(world):
            pair = halves(s)
            if s < world - 1:
                # Forward this step's halves before its compute: top
                # rides the right link, bottom the left link —
                # concurrently (the 2x-wire claim; landing slots are the
                # same global indices on every device).
                slot_t = jax.lax.rem(me - s + world, world)
                slot_b = jax.lax.rem(me + s, world)
                dl.remote_copy(pair[0][0], ag_ref.at[top(slot_t)],
                               send_r, recv_r, axis, right).start()
                dl.remote_copy(pair[1][0], ag_ref.at[bot(slot_b)],
                               send_l, recv_l, axis, left).start()

            for h, (src, rows) in enumerate(pair):
                cyc = 2 * s + h

                def prefetch(lhs, rhs, o, scheduler, s=s, h=h):
                    del o
                    if h == 0:
                        # Second half of this step: already resident.
                        scheduler.prefetch(lhs, halves(s)[1][0])
                    else:
                        # Next step's halves: wait BOTH directions'
                        # arrivals (byte-counted per HALF segment — the
                        # wait ref must size the transfer), then fetch.
                        nt = ag_ref.at[top(jax.lax.rem(
                            me - (s + 1) + world, world))]
                        nb = ag_ref.at[bot(jax.lax.rem(
                            me + s + 1, world))]
                        pltpu.make_async_copy(nt, nt, recv_r).wait()
                        pltpu.make_async_copy(nb, nb, recv_l).wait()
                        scheduler.prefetch(lhs, nt)
                    scheduler.prefetch(rhs, b_ref)

                last = cyc == 2 * world - 1
                inner(src, b_ref, out_ref.at[rows], scratches=(acc_ref,),
                      allocations=allocs, first_cycle=cyc == 0,
                      last_cycle=last,
                      prefetch=None if last else prefetch)

            if s < world - 1:
                # Drain both directions' sends (byte-counted per half)
                # before the slots are read as next step's sources.
                hr = a_ref.at[pl.ds(0, half)]
                pltpu.make_async_copy(hr, hr, send_r).wait()
                pltpu.make_async_copy(hr, hr, send_l).wait()

    pl.run_scoped(
        run,
        pltpu.make_pipeline_allocations(
            a_ref.at[pl.ds(0, half)], b_ref, out_ref.at[pl.ds(0, half)],
            in_specs=in_specs, out_specs=out_specs,
            should_accumulate_out=(False,), grid=grid),
    )
    cp.wait()


def _ag_gemm_kernel(
    *refs,
    axis, world, m_loc, bm, bn, bk, out_dtype, chunks=1, wire=False,
):
    """Ring producer + ONE persistent MXU pipeline across all ring steps.

    refs (``wire=False``):
      a_ref [m_loc, K] ANY, b_ref [K, n_loc] ANY,
      ag_ref [world*m_loc, K] out, out_ref [world*m_loc, n_loc] out,
      send_sem, recv_sem, copy_sem, acc_ref (VMEM (bm, bn)).
    refs (``wire=True`` — int8 wire mode, VERDICT r3 #3): an int8
    payload ``a_ref`` plus a per-row scale plane ``s_ref`` [m_loc, 128]
    f32 (scale in column 0 — the minimum Mosaic wire unit) replace the
    bf16 A; both ride the ring, and the inner pipeline dequantizes at
    the MXU feed (``wire_gemm_pipeline_body``).  Wire bytes drop ~2x
    for bf16 models (plus a 128-lane scale plane, ~K/128 overhead).
    The gathered outputs are the RAW wire planes; the host
    reconstructs bf16 A lazily outside the kernel (XLA DCEs it when
    unused).  Reference: fp8 payloads in its headline kernel
    (low_latency_all_to_all.py:76-88); int8 here because v5e fp8
    matmuls run at bf16 rate (docs/perf.md fp8 probe).

    The inner Mosaic pipeline is invoked once per ring step but shares its
    VMEM allocations across steps (``make_pipeline_allocations`` +
    ``first_cycle``/``last_cycle``), and each step's LAST inner iteration
    prefetches the NEXT segment's first tiles — with the recv-semaphore
    wait folded into that prefetch callback.  This is the TPU rendering of
    the reference's persistent consumer GEMM spinning on per-rank signals
    (allgather_gemm.py:133-254): no pipeline fill/drain bubble between
    segments, the cross-step double buffering the per-step re-entry lost.

    The ring-forward DMA for the segment being consumed launches just
    before its pipeline cycle, so the wire transfer rides under that
    whole step's compute (not inside a postyeet callback — starting a
    remote DMA inside the pipeline callbacks deadlocks the Mosaic
    interpreter; a semaphore wait inside prefetch is fine).

    World-1: the host aliases A into the gathered-A output
    (``input_output_aliases``), so the kernel is a single pipeline cycle
    with no staging DMA and no semaphores — measured at parity with the
    dense kernel (scripts/exp_ring_schedule.py: ring-minus-dense delta
    +0.02..0.22 ms on an ~2.5 ms GEMM; the old per-step code's documented
    146 TFLOPS was protocol bias plus the staging DMA).
    """
    if wire:
        (a_ref, s_ref, b_ref, ag_ref, ag_s_ref, out_ref,
         send_sem, recv_sem, copy_sem, acc_ref) = refs
    else:
        (a_ref, b_ref, ag_ref, out_ref,
         send_sem, recv_sem, copy_sem, acc_ref) = refs
        s_ref = ag_s_ref = None

    K = a_ref.shape[1]
    n_loc = b_ref.shape[1]
    n_m, n_n, n_k = m_loc // bm, n_loc // bn, K // bk
    grid = (n_m, n_n, n_k)
    a_spec = pl.BlockSpec((bm, bk), lambda i, j, k: (i, k))
    s_spec = pl.BlockSpec((bm, 128), lambda i, j, k: (i, 0))
    b_spec = pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))
    in_specs = ([a_spec, s_spec, b_spec] if wire else [a_spec, b_spec])
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))]
    body = wire_gemm_pipeline_body if wire else gemm_pipeline_body

    inner = pltpu.emit_pipeline(
        functools.partial(body, n_k=n_k, out_dtype=out_dtype),
        grid=grid, in_specs=in_specs, out_specs=out_specs,
    )

    def planes(srcs):
        """A-plane refs for a cycle: payload [+ scale plane]."""
        return srcs if wire else srcs[:1]

    if world == 1:
        # Gathered A IS A (aliased by the host) — nothing to stage or
        # forward; run the one pipeline cycle.
        inner(*planes((a_ref, s_ref)), b_ref, out_ref,
              scratches=(acc_ref,))
        return

    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)

    # Stage local segment(s) into the gathered output (reference:
    # local_copy_and_barrier_all, allgather_gemm.py:100-116) — but only
    # START them: step 0 computes and ring-forwards directly from the
    # inputs, so the staging DMA hides behind the first segment's GEMM.
    # The wait is at kernel exit, for gathered-output validity.
    cps = [pltpu.make_async_copy(
        a_ref, ag_ref.at[pl.ds(me * m_loc, m_loc)], copy_sem)]
    if wire:
        cps.append(pltpu.make_async_copy(
            s_ref, ag_s_ref.at[pl.ds(me * m_loc, m_loc)], copy_sem))
    for cp in cps:
        cp.start()

    # Neighbor barrier before any remote write (same role as the entry
    # barrier_all: nobody writes into a peer that hasn't entered the
    # kernel).
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                           device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)

    def seg(s):
        slot = jax.lax.rem(me - s + world, world)
        sl = pl.ds(slot * m_loc, m_loc)
        return slot, ag_ref.at[sl], (ag_s_ref.at[sl] if wire else None)

    def run(allocs):
        for s in range(world):
            slot, sg, ssg = seg(s)
            # Step 0's segment is the local one — read it from the inputs
            # (the staging copies into the gathered buffers may still be
            # in flight).
            srcs = (a_ref, s_ref) if s == 0 else (sg, ssg)
            out = out_ref.at[pl.ds(slot * m_loc, m_loc)]

            if s < world - 1:
                # Launch the ring-forward of this step's segment before
                # entering its pipeline cycle, so the wire transfer rides
                # under the whole cycle's compute.  (Its recv wait
                # happened in the previous cycle's prefetch, so the data
                # is valid; issuing a remote DMA *inside* a
                # prefetch/postyeet callback deadlocks the Mosaic
                # interpreter, so it stays out here.)  sg/ssg are the
                # landing slots on the peer (SPMD addressing: slot(s) is
                # the same index on every device).  The payload goes as
                # ``chunks`` row-chunk DMAs; byte-counted send/recv
                # waits are chunk-agnostic.
                rows_c = m_loc // chunks
                for q in range(chunks):
                    dl.remote_copy(
                        srcs[0].at[pl.ds(q * rows_c, rows_c)],
                        sg.at[pl.ds(q * rows_c, rows_c)],
                        send_sem, recv_sem, axis, right).start()
                if wire:
                    dl.remote_copy(srcs[1], ssg, send_sem, recv_sem,
                                   axis, right).start()

            def prefetch(*brefs_and_sched, s=s):
                # Last inner iteration of step s: the reference's dl.wait
                # on the per-rank signal, folded into the prefetch of the
                # next segment's first tiles — recv_sem completion means
                # the left neighbor's forward landed.
                *in_brefs, _o, scheduler = brefs_and_sched
                _, nsg, nssg = seg(s + 1)
                pltpu.make_async_copy(nsg, nsg, recv_sem).wait()
                if wire:
                    pltpu.make_async_copy(nssg, nssg, recv_sem).wait()
                    scheduler.prefetch(in_brefs[0], nsg)
                    scheduler.prefetch(in_brefs[1], nssg)
                    scheduler.prefetch(in_brefs[2], b_ref)
                else:
                    scheduler.prefetch(in_brefs[0], nsg)
                    scheduler.prefetch(in_brefs[1], b_ref)

            inner(*planes(srcs), b_ref, out, scratches=(acc_ref,),
                  allocations=allocs,
                  first_cycle=s == 0, last_cycle=s == world - 1,
                  prefetch=prefetch if s < world - 1 else None)

            if s < world - 1:
                # Drain this cycle's forward(s) (completed during the
                # cycle's compute) so send_sem stays at zero per step.
                pltpu.make_async_copy(srcs[0], srcs[0], send_sem).wait()
                if wire:
                    pltpu.make_async_copy(srcs[1], srcs[1],
                                          send_sem).wait()

    alloc_refs = planes((a_ref, s_ref)) + (b_ref,)
    pl.run_scoped(
        run,
        pltpu.make_pipeline_allocations(
            *alloc_refs, out_ref.at[pl.ds(0, m_loc)],
            in_specs=in_specs, out_specs=out_specs,
            # must match out_specs' pytree structure (emit_pipeline
            # broadcasts this itself; the direct call does not)
            should_accumulate_out=(False,), grid=grid),
    )

    # Gathered-output validity (consumers read them after the kernel).
    for cp in cps:
        cp.wait()


def _torus_ag_gemm_kernel(
    a_ref,      # [m_loc, K]                    ANY (HBM)
    b_ref,      # [K, n_loc]                    ANY
    ag_ref,     # [wx, wy, wz, m_loc, K]        ANY, output: gathered A
    out_ref,    # [wx, wy, wz, m_loc, n_loc]    ANY, output: C shard
    send_x, recv_x, send_y, recv_y, send_z, recv_z, copy_sem,
    acc_ref,
    *,
    ax, ay, az, wx, wy, wz, m_loc, bm, bn, bk, out_dtype,
):
    """2-/3-axis torus AG-GEMM: the torus schedule as the segment producer.

    Phase 1 is the 1-D ring over ``ax`` (slot per step, GEMM consumes each
    as it arrives); phase 2 rings whole first-axis LINES (wx slots) over
    ``ay``, each line's forward DMA riding under the wx slot-GEMMs of the
    previously arrived line; phase 3 (3-axis meshes) rings whole
    (x, y)-PLANES over ``az``, each plane's DMA riding under wx*wy
    slot-GEMMs — the DMA:compute ratio improves every phase.  Per-phase
    semaphore pairs keep a fast neighbor's early next-phase arrival from
    satisfying an earlier-phase wait (cf. kernels/torus.py).  Consume
    order = arrival order, so step 0 is always the local segment — the
    reference's rank swizzle (allgather_gemm.py:206-219), inherited per
    axis; the reference's own 3D analog is the push-3D warp-specialized
    AG (low_latency_allgather.py:570-607).  ``wz == 1`` degenerates to
    the 2-axis schedule (phase 3 vanishes).

    r4: the MXU pipeline is persistent (shared allocations, as in
    ``_ag_gemm_kernel``) — phase 1 chains its wx cycles with the recv_x
    wait folded into the prefetch callback; each phase-2/3 step chains
    its wx (or wx*wy) slot-GEMMs into one pipeline run (all data
    resident after the line/plane recv, so those prefetches are pure
    next-slot fetches).  Chains break only at step boundaries, where
    the line/plane recv wait must precede the first tile fetch.
    """
    i = jax.lax.axis_index(ax)
    j = jax.lax.axis_index(ay)
    k = jax.lax.axis_index(az) if az is not None else 0
    right = jax.lax.rem(i + 1, wx)
    down = jax.lax.rem(j + 1, wy)
    back = jax.lax.rem(k + 1, wz) if az is not None else 0

    # Stage the local segment (hidden behind step 0's GEMM; waited before
    # phase 2 ships the line that contains it).
    cp = pltpu.make_async_copy(a_ref, ag_ref.at[i, j, k], copy_sem)
    cp.start()

    dl.barrier_all(ax)
    dl.barrier_all(ay)
    if az is not None:
        dl.barrier_all(az)

    K = a_ref.shape[1]
    n_loc = b_ref.shape[1]
    n_m, n_n, n_k = m_loc // bm, n_loc // bn, K // bk
    grid = (n_m, n_n, n_k)
    in_specs = [
        pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
        pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
    ]
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j))]

    inner = pltpu.emit_pipeline(
        functools.partial(gemm_pipeline_body, n_k=n_k, out_dtype=out_dtype),
        grid=grid, in_specs=in_specs, out_specs=out_specs,
    )

    def run(allocs):
        # ---- Phase 1: x-ring over my line (j, k), one slot per step,
        # chained into ONE persistent pipeline (as in _ag_gemm_kernel:
        # shared allocations, recv_x wait folded into the prefetch of
        # the last inner iteration; forwards launch outside the calls —
        # a DMA start inside the callbacks deadlocks the interpreter).
        def xseg(s):
            slot = jax.lax.rem(i - s + wx, wx)
            return slot, ag_ref.at[slot, j, k]

        for s in range(wx):
            slot, seg = xseg(s)
            src = a_ref if s == 0 else seg
            if s < wx - 1:
                dl.remote_copy(src, seg, send_x, recv_x, ax, right).start()

            def prefetch_x(lhs, rhs, o, scheduler, s=s):
                del o
                _, nseg = xseg(s + 1)
                pltpu.make_async_copy(nseg, nseg, recv_x).wait()
                scheduler.prefetch(lhs, nseg)
                scheduler.prefetch(rhs, b_ref)

            inner(src, b_ref, out_ref.at[slot, j, k], scratches=(acc_ref,),
                  allocations=allocs,
                  first_cycle=s == 0, last_cycle=s == wx - 1,
                  prefetch=prefetch_x if s < wx - 1 else None)
            if s < wx - 1:
                pltpu.make_async_copy(src, src, send_x).wait()

        # Phase 2's first shipped line (j) contains the staged slot, and
        # the gathered-A output must be valid at kernel exit either way —
        # the staging DMA has had phase 1's wx GEMMs to hide behind.
        cp.wait()

        def chained_slots(srcs_outs):
            """Run a step's slot-GEMMs as one persistent chain: all data
            is already resident (the step waited its line/plane recv), so
            the prefetch callbacks are pure next-slot prefetches and the
            per-slot fill/drain bubble disappears."""
            n = len(srcs_outs)
            for c, (sg, og) in enumerate(srcs_outs):

                def prefetch_c(lhs, rhs, o, scheduler, c=c):
                    del o
                    scheduler.prefetch(lhs, srcs_outs[c + 1][0])
                    scheduler.prefetch(rhs, b_ref)

                inner(sg, b_ref, og, scratches=(acc_ref,),
                      allocations=allocs,
                      first_cycle=c == 0, last_cycle=c == n - 1,
                      prefetch=prefetch_c if c < n - 1 else None)

        # ---- Phase 2: y-ring over whole lines, wx slot-GEMMs per step.
        for t in range(wy - 1):
            line_send = jax.lax.rem(j - t + wy, wy)
            blk = ag_ref.at[:, line_send, k]
            dl.remote_copy(blk, blk, send_y, recv_y, ay, down).start()

            line_recv = jax.lax.rem(j - t - 1 + wy, wy)
            rblk = ag_ref.at[:, line_recv, k]
            pltpu.make_async_copy(rblk, rblk, recv_y).wait()
            chained_slots([(ag_ref.at[ii, line_recv, k],
                            out_ref.at[ii, line_recv, k])
                           for ii in range(wx)])
            pltpu.make_async_copy(blk, blk, send_y).wait()

        # ---- Phase 3: z-ring over whole planes, wx*wy slot-GEMMs each.
        for u in range(wz - 1):
            plane_send = jax.lax.rem(k - u + wz, wz)
            blk = ag_ref.at[:, :, plane_send]
            dl.remote_copy(blk, blk, send_z, recv_z, az, back).start()

            plane_recv = jax.lax.rem(k - u - 1 + wz, wz)
            rblk = ag_ref.at[:, :, plane_recv]
            pltpu.make_async_copy(rblk, rblk, recv_z).wait()
            chained_slots([(ag_ref.at[ii, jj, plane_recv],
                            out_ref.at[ii, jj, plane_recv])
                           for ii in range(wx) for jj in range(wy)])
            pltpu.make_async_copy(blk, blk, send_z).wait()

    pl.run_scoped(
        run,
        pltpu.make_pipeline_allocations(
            a_ref, b_ref, out_ref.at[0, 0, 0],
            in_specs=in_specs, out_specs=out_specs,
            should_accumulate_out=(False,), grid=grid),
    )


def _torus_ag_gemm_shard(a_shard, b_shard, *, axes, impl, raw_impl, bm, bn,
                         bk, interpret):
    """Per-device 2-/3-axis torus AG-GEMM (see kernel docstring).  Gathered
    A comes back flat axes-major, C as the matching [W*m_loc, n_loc]."""
    ax, ay = axes[0], axes[1]
    az = axes[2] if len(axes) == 3 else None
    wx = jax.lax.axis_size(ax)
    wy = jax.lax.axis_size(ay)
    wz = jax.lax.axis_size(az) if az is not None else 1
    world = wx * wy * wz
    m_loc, K = a_shard.shape
    n_loc = b_shard.shape[1]
    quantized = a_shard.dtype == jnp.int8
    out_dtype = jnp.int32 if quantized else a_shard.dtype
    acc_dtype = jnp.int32 if quantized else jnp.float32

    if use_fallback(raw_impl, impl, pallas_shapes_ok(m_loc, n_loc, K),
                    "ag_gemm(torus)", f"per-shard ({m_loc}, {n_loc}, {K}); needs m%8, n%128, k%128"):
        a_full = jax.lax.all_gather(a_shard, axes, axis=0, tiled=True)
        pref = jnp.int32 if quantized else jnp.float32
        return a_full, jnp.dot(
            a_full, b_shard, preferred_element_type=pref).astype(out_dtype)

    bm = largest_divisor_block(m_loc, bm, 8)
    bn = largest_divisor_block(n_loc, bn, 128)
    bk = largest_divisor_block(K, bk, 128)

    ag5, c5 = pl.pallas_call(
        functools.partial(
            _torus_ag_gemm_kernel, ax=ax, ay=ay, az=az, wx=wx, wy=wy,
            wz=wz, m_loc=m_loc, bm=bm, bn=bn, bk=bk, out_dtype=out_dtype,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((wx, wy, wz, m_loc, K), a_shard.dtype),
            jax.ShapeDtypeStruct((wx, wy, wz, m_loc, n_loc), out_dtype),
        ],
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((bm, bn), acc_dtype),
        ],
        compiler_params=dl.collective_compiler_params(
            world, AG_GEMM_COLLECTIVE_ID),
        interpret=maybe_interpret(interpret),
    )(a_shard, b_shard)
    return (ag5.reshape(world * m_loc, K),
            c5.reshape(world * m_loc, n_loc))


def ag_gemm_shard(a_shard, b_shard, *, axis, impl, bm=None, bn=None,
                  bk=None, chunks=1, wire_dtype=None, ring_mode="uni",
                  interpret=False):
    """Per-device AG-GEMM; call inside shard_map.  Returns (A_full, C_shard).
    Block sizes default to the swept MatmulConfig (gemm.py).  ``axis`` may
    be a tuple of 2-3 mesh axes — A's rows sharded over the axes-major
    joint axes — routing to the torus schedule (phase-interleaved multi-
    axis ring producer, ``_torus_ag_gemm_kernel``).

    ``wire_dtype="int8"`` (float A only): the ring ships per-row-quantized
    int8 segments + an f32 scale plane and dequantizes at the MXU feed —
    ~2x fewer allgather wire bytes for unquantized models; the returned
    A_full is the dequantized reconstruction (quantization noise applies,
    so compare with tolerance).  Ignored on the XLA fallback path only in
    the sense that the same quantize→dequantize noise is applied locally
    there, keeping the two impls numerically equivalent.

    ``ring_mode="bidir"`` (r5): segment halves ring both directions
    concurrently (``_ag_gemm_bidir_kernel``) — ~2x per-step wire on a
    1-axis mesh.  Mutually exclusive with ``wire_dtype``/``chunks > 1``
    (loud ValueError: the half split IS the sub-chunking).  Falls back
    to the uni/torus schedule SILENTLY when the mode cannot apply:
    half-segment untileable (m_loc/2 % 8), int8 inputs (the i32 ring
    epilogue is not half-split), multi-axis meshes (the torus schedule
    already drives every link direction — bidir would be a downgrade),
    and world 1 (the aliased path; overhead nil)."""
    _cfg = MatmulConfig()
    bm, bn, bk = bm or _cfg.block_m, bn or _cfg.block_n, bk or _cfg.block_k
    if ring_mode == "bidir" and (wire_dtype is not None or chunks > 1):
        # Config conflict — reject unconditionally (before any shape/
        # world early return, so the error does not depend on the mesh).
        raise ValueError(
            "ring_mode='bidir' composes with neither wire_dtype nor "
            "chunks > 1 (the half split IS the sub-chunking; the int8 "
            "scale plane would need per-direction threading)")
    raw_impl = impl
    impl = resolve_impl(impl, interpret)
    if isinstance(axis, (tuple, list)) and len(axis) > 1:
        axes = tuple(axis)
        if len(axes) not in (2, 3):
            raise ValueError(f"ag_gemm supports 1-3 axes, got {axes}")
        real = tuple(a for a in axes if jax.lax.axis_size(a) > 1)
        if len(real) <= 1:  # degenerate: at most one real axis
            axis = real[0] if real else axes[0]
        else:
            if wire_dtype is not None:
                raise NotImplementedError(
                    "wire_dtype is implemented for the 1-D ring schedule; "
                    "the torus schedule ships bf16 (its per-phase "
                    "line/plane DMAs would each need the scale plane "
                    "threaded through — tracked for a future round)")
            return _torus_ag_gemm_shard(a_shard, b_shard, axes=real,
                                        impl=impl, raw_impl=raw_impl,
                                        bm=bm, bn=bn, bk=bk,
                                        interpret=interpret)
    axis = axis[0] if isinstance(axis, (tuple, list)) else axis
    world = jax.lax.axis_size(axis)
    m_loc, K = a_shard.shape
    n_loc = b_shard.shape[1]
    # int8 inputs take the MXU double-rate path: exact i32 accumulation
    # and output (the W8A8 caller dequants outside; see kernels/quant.py).
    quantized = a_shard.dtype == jnp.int8
    out_dtype = jnp.int32 if quantized else a_shard.dtype
    acc_dtype = jnp.int32 if quantized else jnp.float32
    wire = wire_dtype is not None
    if wire:
        if wire_dtype != "int8":
            raise ValueError(f"wire_dtype must be 'int8' or None, got "
                             f"{wire_dtype!r} (fp8 matmuls run at bf16 "
                             "rate on v5e — docs/perf.md fp8 probe)")
        if quantized:
            wire = False  # int8 A already IS the wire format

    if use_fallback(raw_impl, impl, pallas_shapes_ok(m_loc, n_loc, K),
                    "ag_gemm", f"per-shard ({m_loc}, {n_loc}, {K}); needs m%8, n%128, k%128"):
        if wire:
            # Same quantization noise as the wire kernel, applied
            # locally, so xla/pallas stay numerically equivalent.
            from triton_dist_tpu.kernels.quant import quantize_rowwise

            aq, ascale = quantize_rowwise(a_shard)
            a_shard = (aq.astype(jnp.float32)
                       * ascale[:, None]).astype(a_shard.dtype)
        a_full = jax.lax.all_gather(a_shard, axis, axis=0, tiled=True)
        pref = jnp.int32 if quantized else jnp.float32
        return a_full, jnp.dot(
            a_full, b_shard, preferred_element_type=pref).astype(out_dtype)

    if world == 1 and raw_impl == "auto" and not interpret and not wire:
        # Degenerate world under auto dispatch: there is nothing to
        # gather.  Float inputs take XLA's dot, NOT the pallas matmul:
        # in real op CHAINS XLA fuses the neighboring elementwise work
        # (casts, feedback transforms) into the dot's prologue/epilogue,
        # saving whole HBM passes that a custom-call pallas kernel
        # cannot — measured 0.7 ms/pair faster at the bench shape in the
        # same rotated trial loop (exp_ring_schedule.py 'xdot' vs
        # 'dense'; standalone rates are equal at ~190).  int8 keeps the
        # pallas double-rate kernel (358 vs ~280 TOPS through XLA's
        # path).  Explicit impl="pallas" still runs the ring kernel
        # (what the hardware smoke exercises); interpret mode keeps it
        # too.
        if quantized:
            from triton_dist_tpu.kernels.quant import matmul_i8
            return a_shard, matmul_i8(a_shard, b_shard)
        c = jnp.dot(a_shard, b_shard,
                    preferred_element_type=jnp.float32).astype(out_dtype)
        return a_shard, c

    bidir = ring_mode == "bidir"
    if bidir and (m_loc % 2 or (m_loc // 2) % 8 or quantized):
        bidir = False  # half-segment cannot tile; keep the uni ring

    if bidir and world > 1:
        bm_h = largest_divisor_block(m_loc // 2, bm, 8)
        bn_h = largest_divisor_block(n_loc, bn, 128)
        bk_h = largest_divisor_block(K, bk, 128)
        return pl.pallas_call(
            functools.partial(
                _ag_gemm_bidir_kernel, axis=axis, world=world,
                m_loc=m_loc, bm=bm_h, bn=bn_h, bk=bk_h,
                out_dtype=out_dtype,
            ),
            out_shape=[
                jax.ShapeDtypeStruct((world * m_loc, K), a_shard.dtype),
                jax.ShapeDtypeStruct((world * m_loc, n_loc), out_dtype),
            ],
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.VMEM((bm_h, bn_h), acc_dtype),
            ],
            compiler_params=dl.collective_compiler_params(
                world, AG_GEMM_COLLECTIVE_ID),
            interpret=maybe_interpret(interpret),
        )(a_shard, b_shard)

    bm = largest_divisor_block(m_loc, bm, 8)
    bn = largest_divisor_block(n_loc, bn, 128)
    bk = largest_divisor_block(K, bk, 128)
    # Sub-chunk rows must stay sublane-aligned; clamp to a divisor.
    while chunks > 1 and (m_loc % chunks or (m_loc // chunks) % 8):
        chunks -= 1

    if wire:
        from triton_dist_tpu.kernels.quant import quantize_rowwise

        aq, ascale = quantize_rowwise(a_shard)       # i8, [m_loc] f32
        s_plane = jnp.zeros((m_loc, 128), jnp.float32).at[:, 0].set(ascale)
        ag_w, ag_s, c = pl.pallas_call(
            functools.partial(
                _ag_gemm_kernel, axis=axis, world=world, m_loc=m_loc,
                bm=bm, bn=bn, bk=bk, out_dtype=out_dtype, chunks=chunks,
                wire=True,
            ),
            out_shape=[
                jax.ShapeDtypeStruct((world * m_loc, K), jnp.int8),
                jax.ShapeDtypeStruct((world * m_loc, 128), jnp.float32),
                jax.ShapeDtypeStruct((world * m_loc, n_loc), out_dtype),
            ],
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.VMEM((bm, bn), acc_dtype),
            ],
            # World-1: the wire planes ARE the inputs.
            input_output_aliases={0: 0, 1: 1} if world == 1 else {},
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=AG_GEMM_COLLECTIVE_ID if world > 1 else None,
            ),
            interpret=maybe_interpret(interpret),
        )(aq, s_plane, b_shard)
        # Lazy bf16 reconstruction of gathered A — XLA DCEs this when the
        # caller only uses C.
        a_full = (ag_w.astype(jnp.float32)
                  * ag_s[:, :1]).astype(a_shard.dtype)
        return a_full, c

    return pl.pallas_call(
        functools.partial(
            _ag_gemm_kernel, axis=axis, world=world, m_loc=m_loc,
            bm=bm, bn=bn, bk=bk, out_dtype=out_dtype, chunks=chunks,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((world * m_loc, K), a_shard.dtype),
            jax.ShapeDtypeStruct((world * m_loc, n_loc), out_dtype),
        ],
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)],
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((bm, bn), acc_dtype),
        ],
        # World-1: gathered A IS A — alias instead of staging (the
        # staging DMA's full [m_loc, K] read+write costs ~8% of the GEMM
        # at the bench shape; exp_ring_schedule.py).
        input_output_aliases={0: 0} if world == 1 else {},
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            collective_id=AG_GEMM_COLLECTIVE_ID if world > 1 else None,
        ),
        interpret=maybe_interpret(interpret),
    )(a_shard, b_shard)


def ag_gemm(a, b, ctx: AllGatherGEMMContext):
    """C = allgather(A, axis) @ B_local, overlapped.  Host-level entry
    (reference: ``ag_gemm`` allgather_gemm.py:539-583)."""
    return ag_gemm_gathered(a, b, ctx)[1]


def ag_gemm_gathered(a, b, ctx: AllGatherGEMMContext):
    """Like :func:`ag_gemm` but also returns the gathered A (the reference
    keeps it in ``ctx`` for reuse by subsequent ops)."""
    from triton_dist_tpu.runtime.profiling import annotate

    cfg = ctx.config
    fn = cached_shard_jit(
        ag_gemm_shard,
        ctx.mesh,
        (P(ctx.axis, None), P(None, ctx.axis)),
        (P(None, None), P(None, ctx.axis)),
        axis=ctx.axis, impl=ctx.impl,
        bm=cfg.block_m, bn=cfg.block_n, bk=cfg.block_k,
        chunks=ctx.chunks, wire_dtype=ctx.wire_dtype,
        ring_mode=ctx.ring_mode, interpret=ctx.interpret,
    )
    # Launch metadata (reference: GEMMs report name/flops/bytes to the
    # profiler, allgather_gemm.py:120-130).  Per-device: full [M, K] x
    # local [K, n_loc] MXU work; bytes = ring wire (the whole gathered A
    # arrives once) + B read + C write.
    axes = (tuple(ctx.axis) if isinstance(ctx.axis, (tuple, list))
            else (ctx.axis,))
    world = int(np.prod([ctx.mesh.shape[ax] for ax in axes]))
    M, K = a.shape
    n_loc = b.shape[1] // max(world, 1)
    el = jnp.dtype(a.dtype).itemsize
    with annotate("ag_gemm", flops=2 * M * n_loc * K,
                  bytes_accessed=(M * K + K * n_loc + M * n_loc) * el):
        return fn(a, b)


# ---------------------------------------------------------------------------
# Autotuned entry (VERDICT r2 #5: the overlapped kernels themselves sweep
# through contextual_autotune, not just the dense matmul).
# ---------------------------------------------------------------------------

from triton_dist_tpu.autotuner import Config as _Cfg, autotune as _autotune

# Block space shared with the GEMM-RS sweep (a new winner from the next
# on-chip session lands in both): the dense sweep's winners plus
# tall/deep alternatives.
OVERLAP_BLOCK_SPACE = [
    _Cfg(bm=512, bn=512, bk=512),
    _Cfg(bm=1024, bn=1024, bk=512),
    _Cfg(bm=1024, bn=512, bk=1024),
    _Cfg(bm=2048, bn=512, bk=512),
]

# AG-GEMM adds the ring-forward sub-chunk axis (VERDICT r3 #9 — the
# schedule knob ``perf_model.overlap_chunk_budget`` models; c > 1 splits
# each segment's wire DMA into c row-chunks) and, r5, the bidirectional
# ring (both link directions busy — the wire-bound-shape alternative).
AG_GEMM_TUNE_SPACE = (
    [_Cfg(**c, chunks=1) for c in OVERLAP_BLOCK_SPACE]
    + [_Cfg(bm=2048, bn=512, bk=512, chunks=2),
       _Cfg(bm=2048, bn=512, bk=512, chunks=4),
       _Cfg(bm=1024, bn=512, bk=512, chunks=1, ring_mode="bidir"),
       _Cfg(bm=512, bn=512, bk=512, chunks=1, ring_mode="bidir")]
)


@_autotune(configs=AG_GEMM_TUNE_SPACE, key=())
def _ag_gemm_tunable(a, b, *, ctx, bm=None, bn=None, bk=None, chunks=1,
                     ring_mode="uni"):
    tuned = AllGatherGEMMContext(
        mesh=ctx.mesh, axis=ctx.axis, impl=ctx.impl,
        config=MatmulConfig(bm, bn, bk), chunks=chunks,
        wire_dtype=ctx.wire_dtype, ring_mode=ring_mode,
        interpret=ctx.interpret)
    return ag_gemm(a, b, tuned)


def ag_gemm_autotuned(a, b, ctx: AllGatherGEMMContext):
    """:func:`ag_gemm` with blocks selected by the autotuner.

    Inside a ``contextual_autotune`` region the sweep advances in
    lockstep with any other tuners in the op; multi-process deployments
    MUST use ``contextual_autotune(is_dist=True)`` — that is what
    MAX-allreduces the timings so every rank caches the same winner
    (the default region and the eager path pick per-process).  Outside a
    region, the first call sweeps eagerly.
    Each config is a separate jit of the WHOLE overlapped collective
    program, so the measurement includes the ring schedule, not just the
    MXU inner loop.  Winners are cached per (shape, dtype, ctx).  On the
    tunnel-attached dev chip use scripts/autotune_onchip.py's chain
    measure instead (single-call timing lies there; docs/autotuner.md).
    """
    return _ag_gemm_tunable(a, b, ctx=ctx)
