"""Dense (single-device) GQA attention — the shared local building block.

Used wherever a full-sequence attention runs on local heads: the Llama TP
block (heads sharded, sequence gathered) and the Ulysses SP block (heads
scattered by the A2A).  The distributed schemes differ in how Q/K/V get to
the device; the math on arrival is this one function.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -1e30


def dense_gqa_attention(q, k, v, *, causal=True, scale=None):
    """q [S, B, Hq, hd]; k/v [S, B, Hkv, hd] (Hq % Hkv == 0).

    Returns [S, B, Hq, hd] in q's dtype; softmax statistics in f32.
    """
    S = q.shape[0]
    group = q.shape[2] // k.shape[2]
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    kr = jnp.repeat(k, group, axis=2)
    vr = jnp.repeat(v, group, axis=2)
    logits = jnp.einsum("sbhd,tbhd->bhst", q, kr,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, _NEG)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,tbhd->sbhd", p.astype(q.dtype), vr,
                      preferred_element_type=jnp.float32).astype(q.dtype)
