"""Ulysses attention — head-scatter AllToAll sequence parallelism.

Reference analog: none (SURVEY.md §5: "No Ulysses (head-scatter A2A) ...
exist[s] in the reference; ring/Ulysses are natural TPU extensions").  The
DeepSpeed-Ulysses scheme: activations arrive sequence-sharded; an AllToAll
re-shards them to head-sharded-with-full-sequence, attention runs locally
on each device's heads, and the inverse AllToAll restores sequence
sharding.  Communication is 2 AllToAlls of the QKV/O activations per
attention call — O(S·B·H·hd / world) per device, independent of world
size, vs the ring's (world-1) KV-block hops; Ulysses wins when heads are
plentiful and the sequence shard is large, ring wins when H < world or
memory for full-sequence scores is tight.

Exactly 2 AllToAlls per attention call: Q/K/V ride ONE fused scatter
(concatenated along the per-peer head chunk, the same trick as the Llama
block's fused-QKV allgather), and the output rides the inverse.
Implementations: ``xla`` (``jax.lax.all_to_all`` — differentiable, fused
by XLA) and ``pallas`` (the low-latency ``fast_all_to_all`` kernel with
its custom VJP).  GQA requires ``n_kv_heads % world == 0`` (the standard
Ulysses constraint); use ring attention otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.kernels.all_to_all import fast_all_to_all_shard_diff
from triton_dist_tpu.kernels.gemm import resolve_impl
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


@dataclass
class UlyssesContext:
    mesh: Mesh
    axis: str = "sp"
    causal: bool = True
    impl: str = "auto"
    interpret: bool = False
    window: int = 0
    soft_cap: float = 0.0

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_ulysses_context(mesh, axis="sp", causal=True, impl="auto",
                           interpret=False, window=0,
                           soft_cap=0.0) -> UlyssesContext:
    return UlyssesContext(mesh=mesh, axis=axis, causal=causal, impl=impl,
                          interpret=interpret, window=window,
                          soft_cap=soft_cap)


def _a2a_blocks(send, *, axis, impl, interpret):
    """Peer-block AllToAll: send[p] goes to peer p; recv[p] came from peer
    p.  send: [world, rows, cols]."""
    if impl == "xla":
        return jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                                  tiled=False)
    splits = jnp.full((send.shape[0],), send.shape[1], jnp.int32)
    recv, _ = fast_all_to_all_shard_diff(send, splits, axis, impl, interpret)
    return recv


def _a2a_heads_to_seq(x, *, axis, impl, interpret):
    """[S, B, H_loc, hd] head-sharded → [S_loc, B, H, hd] seq-sharded."""
    world = jax.lax.axis_size(axis)
    s, b, h_loc, hd = x.shape
    s_loc = s // world
    send = x.reshape(world, s_loc, b * h_loc * hd)
    recv = _a2a_blocks(send, axis=axis, impl=impl, interpret=interpret)
    return (recv.reshape(world, s_loc, b, h_loc, hd)
            .transpose(1, 2, 0, 3, 4)
            .reshape(s_loc, b, world * h_loc, hd))


def ulysses_attention_shard(q, k, v, *, axis, causal=True, scale=None,
                            impl="auto", interpret=False, window=0,
                            soft_cap=0.0):
    """Shard-level Ulysses attention; call inside shard_map.

    q [S_loc, B, Hq, hd]; k/v [S_loc, B, Hkv, hd], sequence sharded over
    ``axis``.  Returns [S_loc, B, Hq, hd].  Differentiable on both impls
    (the A2As carry custom VJPs / native transposes).  Q/K/V travel in ONE
    fused A2A (per-peer head chunks concatenated), the output in a second.

    ``window``/``soft_cap`` pass straight to the local full-sequence
    attention (after the head scatter each device sees the WHOLE sequence
    for its heads, so the Mistral/Gemma-2 rules need no cross-shard
    bookkeeping here).
    """
    world = jax.lax.axis_size(axis)
    s_loc, b, hq, hd = q.shape
    hkv = k.shape[2]
    assert hq % world == 0 and hkv % world == 0, (
        f"Ulysses needs heads divisible by world: Hq={hq} Hkv={hkv} "
        f"world={world}; use ring attention otherwise")
    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    impl = resolve_impl(impl, interpret)
    hq_loc, hkv_loc = hq // world, hkv // world
    tot_loc = hq_loc + 2 * hkv_loc

    # Fused scatter: peer p's chunk = my seq block of [q|k|v]'s p-th heads.
    per_peer = jnp.concatenate([
        q.reshape(s_loc, b, world, hq_loc, hd),
        k.reshape(s_loc, b, world, hkv_loc, hd),
        v.reshape(s_loc, b, world, hkv_loc, hd),
    ], axis=3)                                  # [S_loc, B, world, tot, hd]
    send = (per_peer.transpose(2, 0, 1, 3, 4)
            .reshape(world, s_loc, b * tot_loc * hd))
    recv = _a2a_blocks(send, axis=axis, impl=impl, interpret=interpret)
    full = recv.reshape(world * s_loc, b, tot_loc, hd)
    qh, kh, vh = jnp.split(full, [hq_loc, hq_loc + hkv_loc], axis=2)

    # Local attention on scattered heads rides the flash prefill kernel
    # when shapes allow (head_dim % 128 etc.); ``impl`` here is already
    # resolved and governs the A2As — explicit "xla" keeps attention
    # dense too (the differentiation-golden path).
    from triton_dist_tpu.kernels.flash_attention import flash_gqa_attention

    oh = flash_gqa_attention(qh, kh, vh, causal=causal, scale=float(scale),
                             impl="xla" if impl == "xla" else "auto",
                             interpret=interpret, window=window,
                             soft_cap=soft_cap)
    return _a2a_heads_to_seq(oh, axis=axis, impl=impl, interpret=interpret)


def ulysses_attention(q, k, v, ctx: UlyssesContext):
    """Host entry: q/k/v [S, B, H, hd] sequence-sharded over ``ctx.axis``."""
    fn = cached_shard_jit(
        ulysses_attention_shard,
        ctx.mesh,
        (P(ctx.axis), P(ctx.axis), P(ctx.axis)),
        P(ctx.axis),
        axis=ctx.axis, causal=ctx.causal, impl=ctx.impl,
        interpret=ctx.interpret, window=ctx.window, soft_cap=ctx.soft_cap,
    )
    # Launch metadata (profiling.annotate contract): full attention
    # flops over the global sequence, causal halved.
    from triton_dist_tpu.runtime.profiling import annotate

    S, B, H, hd = q.shape
    flops = 4 * B * H * S * S * hd // (2 if ctx.causal else 1)
    with annotate("ulysses_attention", flops=flops,
                  bytes_accessed=(q.nbytes + k.nbytes + v.nbytes)
                  // max(ctx.world, 1)):
        return fn(q, k, v)
