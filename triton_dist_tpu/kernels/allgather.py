"""AllGather kernels: ring / bidirectional-ring / full-mesh push + XLA path.

Reference analog: ``python/triton_dist/kernels/nvidia/allgather.py`` — six
copy-engine/NVSHMEM variants selected by topology (``AllGatherMethod`` enum
:44-51, auto-select :54-69, full-mesh pull :104-135, 1-D ring push :138-191,
NUMA-aware 2-D ring :194-258, inter-node variants :470-591).

TPU-native design: topology tiers differ (ICI torus links, not
NVLink-vs-PCIe), so the variant set is re-derived from ICI:

* ``RING_1D`` — neighbor-only hops; each step forwards the chunk received in
  the previous step.  Uses one link direction; bandwidth-optimal on a torus
  axis for large messages.
* ``RING_BIDIR`` — splits every chunk in half, streams halves clockwise +
  counter-clockwise simultaneously; 2× ring bandwidth (both link directions),
  the idiomatic TPU equivalent of the reference's NUMA-aware 2-D ring.
* ``FULL_MESH_PUSH`` — every device puts its chunk directly to all peers
  (ICI routes multi-hop in hardware); latency-optimal for small messages,
  analog of the reference's full-mesh push (allgather.py:138-191 intra-node).
* ``XLA`` — ``lax.all_gather`` under shard_map: the baseline.

All pallas variants run *inside* shard_map on the per-device shard and write
the gathered result into a (world, *shard) output.
"""

from __future__ import annotations

import enum
import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.language.interpret import maybe_interpret
from triton_dist_tpu.runtime import topology
from triton_dist_tpu.runtime.jit_cache import cached_shard_jit


class AllGatherMethod(enum.Enum):
    AUTO = "auto"
    XLA = "xla"
    RING_1D = "ring_1d"
    RING_BIDIR = "ring_bidir"
    FULL_MESH_PUSH = "full_mesh_push"
    TORUS_2D = "torus_2d"  # fused multi-axis schedule (kernels/torus.py)


def choose_allgather_method(nbytes_per_rank: int, n_ranks: int,
                            axis_sizes: tuple[int, ...] | None = None
                            ) -> AllGatherMethod:
    """Topology/size-based auto-selection (reference: allgather.py:54-69,
    which picks among six fabric-tuned variants by node topology).

    Dispatch here is on mesh shape + payload: a bandwidth-bound gather
    spanning >= 2 non-trivial torus axes routes to the fused torus
    schedule (all link directions of the plane busy, ~2x a single bidir
    ring), while a latency-bound (<= 64 KiB) multi-axis gather takes
    XLA's fused joint gather; on one axis, small messages are
    latency-bound → one-hop full-mesh push, large messages
    bandwidth-bound → bidirectional ring.
    """
    if axis_sizes is not None:
        real = [s for s in axis_sizes if s > 1]
        if len(real) >= 2:
            if nbytes_per_rank > 64 * 1024:
                return AllGatherMethod.TORUS_2D
            # Latency-bound joint-axis gather: the per-axis pallas ring
            # variants have no joint meaning and the torus schedule is a
            # bandwidth design — XLA's fused joint gather wins here
            # (ADVICE r2: FULL_MESH_PUSH was silently mapped to the
            # bandwidth torus kernel by the multi-axis branch).
            return AllGatherMethod.XLA
    if n_ranks <= 2:
        return AllGatherMethod.FULL_MESH_PUSH
    if nbytes_per_rank <= 256 * 1024:
        return AllGatherMethod.FULL_MESH_PUSH
    return AllGatherMethod.RING_BIDIR


@dataclass
class AllGatherContext:
    """Carries axis/mesh/method; analog of the reference ctx dataclasses."""

    mesh: Mesh
    axis: str = "tp"
    method: AllGatherMethod = AllGatherMethod.AUTO
    interpret: bool = False

    @property
    def world(self) -> int:
        return self.mesh.shape[self.axis]


def create_allgather_context(mesh, axis="tp", method=AllGatherMethod.AUTO, interpret=False):
    return AllGatherContext(mesh=mesh, axis=axis, method=method, interpret=interpret)


# ---------------------------------------------------------------------------
# Pallas kernel bodies (run per-device inside shard_map).
# ---------------------------------------------------------------------------


def _ring_ag_kernel(x_ref, out_ref, send_sem, recv_sem, copy_sem, *, axis, world, rows):
    """Unidirectional ring: step s forwards chunk (me - s) mod world to the
    right neighbor.  Reference analog: cp_engine_producer_all_gather_ring_push_1d
    (allgather.py:138-191), with Mosaic remote DMA in place of the copy engine
    + cuStreamWriteValue signals."""
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)

    cp = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * rows, rows)], copy_sem)
    cp.start()
    cp.wait()

    # Make sure every peer has entered the kernel before writing into its
    # output buffer (guards cross-invocation semaphore reuse; see JAX dist
    # docs).  Analog of barrier_all at op entry (allgather_gemm.py:100-116).
    # world 1 skips it (and passes no collective_id: a barrier touch with a
    # degenerate mesh aborts the hardware compiler).
    if world > 1:
        barrier = pltpu.get_barrier_semaphore()
        left = jax.lax.rem(me + world - 1, world)
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(barrier, 2)

    def step(s, _):
        slot = jax.lax.rem(me - s + world, world)
        src = out_ref.at[pl.ds(slot * rows, rows)]
        rdma = dl.remote_copy(src, src, send_sem, recv_sem, axis, right)
        rdma.start()
        rdma.wait()
        return 0

    jax.lax.fori_loop(0, world - 1, step, 0)


def _bidir_ring_ag_kernel(
    x_ref, out_ref, send_sem, recv_sem, copy_sem, *, axis, world, rows
):
    """Bidirectional ring: forward half-chunks travel right, backward halves
    travel left — both ICI directions active every step.  TPU-native analog
    of the 2-D NUMA-aware ring (allgather.py:194-258)."""
    me = jax.lax.axis_index(axis)
    right = jax.lax.rem(me + 1, world)
    left = jax.lax.rem(me + world - 1, world)
    half = rows // 2

    cp = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * rows, rows)], copy_sem)
    cp.start()
    cp.wait()

    if world > 1:
        barrier = pltpu.get_barrier_semaphore()
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: left},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_signal(barrier, inc=1, device_id={axis: right},
                               device_id_type=pltpu.DeviceIdType.MESH)
        pltpu.semaphore_wait(barrier, 2)

    def step(s, _):
        fwd_slot = jax.lax.rem(me - s + world, world)
        bwd_slot = jax.lax.rem(me + s, world)
        fwd = out_ref.at[pl.ds(fwd_slot * rows, half)]
        bwd = out_ref.at[pl.ds(bwd_slot * rows + half, half)]
        r_f = dl.remote_copy(fwd, fwd, send_sem.at[0], recv_sem.at[0], axis, right)
        r_b = dl.remote_copy(bwd, bwd, send_sem.at[1], recv_sem.at[1], axis, left)
        r_f.start()
        r_b.start()
        r_f.wait()
        r_b.wait()
        return 0

    jax.lax.fori_loop(0, world - 1, step, 0)


def _full_mesh_push_ag_kernel(
    x_ref, out_ref, send_sem, recv_sem, copy_sem, *, axis, world, rows
):
    """Every device pushes its chunk to all peers at once; ICI routes the
    hops.  Latency-optimal for small chunks.  Reference analog: full-mesh
    push (allgather.py:104-135) over NVLink.

    The body IS the ``fcollect`` verb: stage my slot (overlapped with kernel
    entry, hence ``stage_local=False`` below), barrier, gather round."""
    me = jax.lax.axis_index(axis)

    cp = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * rows, rows)], copy_sem)
    cp.start()
    cp.wait()

    dl.barrier_all(axis)  # self-guards the world-1 degenerate mesh

    dl.fcollect(x_ref, out_ref, send_sem, recv_sem, axis, stage_local=False)


_KERNELS = {
    AllGatherMethod.RING_1D: (_ring_ag_kernel, 1),
    AllGatherMethod.RING_BIDIR: (_bidir_ring_ag_kernel, 2),
    AllGatherMethod.FULL_MESH_PUSH: (_full_mesh_push_ag_kernel, 1),
}


def _ag_pallas_shard(x_shard, *, axis, world, method, interpret, collective_id=1):
    """Per-shard pallas allgather; call inside shard_map."""
    rows = x_shard.shape[0]
    kernel, n_sem = _KERNELS[method]
    if method is AllGatherMethod.RING_BIDIR and rows % 2:
        kernel, n_sem = _KERNELS[AllGatherMethod.RING_1D]
    out_shape = jax.ShapeDtypeStruct((world * rows, *x_shard.shape[1:]), x_shard.dtype)
    sem_shape = pltpu.SemaphoreType.DMA if n_sem == 1 else pltpu.SemaphoreType.DMA((n_sem,))
    return pl.pallas_call(
        functools.partial(kernel, axis=axis, world=world, rows=rows),
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[sem_shape, sem_shape, pltpu.SemaphoreType.DMA],
        compiler_params=dl.collective_compiler_params(world, collective_id),
        interpret=maybe_interpret(interpret),
    )(x_shard)


def all_gather_shard(x_shard, axis, method=AllGatherMethod.AUTO,
                     interpret=False, collective_id=1):
    """AllGather the leading dim of a per-device shard; use inside shard_map.

    Matches ``lax.all_gather(x, axis, tiled=True)`` semantics.  ``axis``
    may be one mesh axis name or a tuple of 2-3 — a multi-axis gather
    auto-routes to the fused torus schedule (``kernels/torus.py``) when the
    payload is bandwidth-bound, XLA's joint-axis gather when latency-bound.
    """
    if isinstance(axis, (tuple, list)) and len(axis) > 1:
        from triton_dist_tpu.kernels.torus import torus_all_gather_shard

        axes = tuple(axis)
        sizes = tuple(jax.lax.axis_size(a) for a in axes)
        real = [a for a, s in zip(axes, sizes) if s > 1]
        if len(real) <= 1:
            # Degenerate joint gather: recurse into the single-axis
            # dispatch below, honoring the caller's explicit method.
            if not real:
                return x_shard
            axis = real[0]
        else:
            if method is AllGatherMethod.AUTO:
                nbytes = int(np.prod(x_shard.shape)) * x_shard.dtype.itemsize
                method = choose_allgather_method(
                    nbytes, int(np.prod(sizes)), axis_sizes=sizes)
            if method is AllGatherMethod.XLA:
                return jax.lax.all_gather(x_shard, axes, axis=0, tiled=True)
            # Every pallas method on >= 2 real axes is the fused torus
            # schedule (the per-axis ring variants have no joint-axis
            # meaning).
            return torus_all_gather_shard(x_shard, axes,
                                          interpret=interpret,
                                          collective_id=collective_id)
    axis = axis[0] if isinstance(axis, (tuple, list)) else axis
    world = jax.lax.axis_size(axis)
    if world == 1:
        return x_shard
    if method is AllGatherMethod.AUTO:
        nbytes = int(np.prod(x_shard.shape)) * x_shard.dtype.itemsize
        method = choose_allgather_method(nbytes, world)
    if method is AllGatherMethod.TORUS_2D:
        method = AllGatherMethod.RING_BIDIR  # one axis: torus degenerates
    if method is AllGatherMethod.XLA:
        return jax.lax.all_gather(x_shard, axis, axis=0, tiled=True)
    return _ag_pallas_shard(
        x_shard, axis=axis, world=world, method=method, interpret=interpret,
        collective_id=collective_id,
    )


def all_gather(x, ctx: AllGatherContext):
    """Host-level entry: gather a sharded array along ``ctx.axis``.

    Reference analog: the host wrappers in allgather.py (§2.5) — takes the
    sharded input, returns the fully-gathered (replicated) array.
    """
    method = ctx.method
    if method is AllGatherMethod.AUTO and not topology.is_tpu() and not ctx.interpret:
        method = AllGatherMethod.XLA

    fn = cached_shard_jit(
        all_gather_shard,
        ctx.mesh,
        P(ctx.axis),
        P(),
        axis=ctx.axis,
        method=method,
        interpret=ctx.interpret,
    )
    # Launch metadata (reference: the proton launch-metadata hooks —
    # every kernel entry reports name/bytes to the profiler).  Pure
    # comm: per-device ring wire = (world - 1) shard payloads.
    from triton_dist_tpu.runtime.profiling import annotate

    world = int(ctx.mesh.shape[ctx.axis])
    with annotate("all_gather",
                  bytes_accessed=x.nbytes // max(world, 1)
                  * max(world - 1, 0)):
        return fn(x)
