"""Hierarchical (two-tier ICI x DCN) collectives.

Reference analog: the inter-node variants of allgather.py (:470-591,
2D rings with same-local-rank P2P over IB) and reduce_scatter.py
(:525-544, :842-860, per-node scatter + ring reduce + inter-node P2P).
The reference hand-places every transfer because NVLink and IB are
different APIs; on TPU both tiers are mesh axes, so the hierarchy is a
*composition of the per-axis kernels* with an order-restoring relayout —
each byte crosses the slow wire exactly once.

Conventions (see tutorials 03/06 for the derivations):
- AllGather: gather the SLOW axis first (only this chip's shard crosses
  DCN), then the fast axis; blocks come out tier-major and are restored to
  flat (slow, fast) rank order.
- ReduceScatter: reduce the FAST axis first (data shrinks fast-fold before
  touching DCN) — the opposite order, because reductions shrink data.
  Chip (i, j) ends up holding flat band j*D + i; ``band_index`` exposes
  that so callers can lay out downstream shards without a reshuffle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from triton_dist_tpu.kernels.allgather import AllGatherMethod, all_gather_shard
from triton_dist_tpu.kernels.reduce_scatter import (
    ReduceScatterMethod,
    reduce_scatter_shard,
)

__all__ = [
    "hier_all_gather_shard",
    "hier_reduce_scatter_shard",
    "hier_rs_band_index",
]


def hier_all_gather_shard(x, *, slow_axis: str, fast_axis: str,
                          slow_method=AllGatherMethod.RING_1D,
                          fast_method=AllGatherMethod.AUTO,
                          interpret: bool = False):
    """Two-tier AllGather of the leading dim; call inside shard_map.

    Input: this chip's shard [rows, ...] of an array sharded jointly over
    (slow_axis, fast_axis), slow-major.  Output: the full array, flat rank
    order, on every chip.
    """
    rows = x.shape[0]
    d = jax.lax.axis_size(slow_axis)
    t = jax.lax.axis_size(fast_axis)
    x = all_gather_shard(x, axis=slow_axis, method=slow_method,
                         interpret=interpret, collective_id=14)
    x = all_gather_shard(x, axis=fast_axis, method=fast_method,
                         interpret=interpret, collective_id=15)
    # blocks are [fast][slow]-major; restore flat (slow, fast) order
    x = x.reshape((t, d, rows) + x.shape[1:])
    x = jnp.moveaxis(x, 1, 0)
    return x.reshape((d * t * rows,) + x.shape[3:])


def hier_rs_band_index(slow_axis: str, fast_axis: str):
    """Flat band index this chip holds after ``hier_reduce_scatter_shard``:
    j * D + i for chip (i, j) — fast-major."""
    d = jax.lax.axis_size(slow_axis)
    i = jax.lax.axis_index(slow_axis)
    j = jax.lax.axis_index(fast_axis)
    return j * d + i


def hier_reduce_scatter_shard(x, *, slow_axis: str, fast_axis: str,
                              slow_method=ReduceScatterMethod.RING_1D,
                              fast_method=ReduceScatterMethod.AUTO,
                              interpret: bool = False):
    """Two-tier ReduceScatter of this chip's full-size partial.

    Output: this chip's band of the total sum (band ``hier_rs_band_index``
    of D*T bands).  DCN carries 1/T of the data it would in a flat RS.
    """
    x = reduce_scatter_shard(x, fast_axis, method=fast_method,
                             interpret=interpret, collective_id=14)
    x = reduce_scatter_shard(x, slow_axis, method=slow_method,
                             interpret=interpret, collective_id=15)
    return x
