"""Hierarchical (two-tier ICI x DCN) collectives.

Reference analog: the inter-node variants of allgather.py (:470-591,
2D rings with same-local-rank P2P over IB) and reduce_scatter.py
(:525-544, :842-860, per-node scatter + ring reduce + inter-node P2P).
The reference hand-places every transfer because NVLink and IB are
different APIs; on TPU both tiers are mesh axes, so the hierarchy is a
*composition of the per-axis kernels* with an order-restoring relayout —
each byte crosses the slow wire exactly once.

Conventions (see tutorials 03/06 for the derivations):
- AllGather: gather the SLOW axis first (only this chip's shard crosses
  DCN), then the fast axis; blocks come out tier-major and are restored to
  flat (slow, fast) rank order.
- ReduceScatter: reduce the FAST axis first (data shrinks fast-fold before
  touching DCN) — the opposite order, because reductions shrink data.
  Chip (i, j) ends up holding flat band j*D + i; ``band_index`` exposes
  that so callers can lay out downstream shards without a reshuffle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from triton_dist_tpu.kernels import collective_ids as cid
from triton_dist_tpu.kernels.allgather import AllGatherMethod, all_gather_shard
from triton_dist_tpu.kernels.reduce_scatter import (
    ReduceScatterMethod,
    reduce_scatter_shard,
)

__all__ = [
    "hier_all_gather_shard",
    "hier_all_reduce_shard",
    "hier_all_to_all_shard",
    "hier_grad_allreduce",
    "hier_reduce_scatter_shard",
    "hier_rs_band_index",
]


def hier_all_gather_shard(x, *, slow_axis: str, fast_axis: str,
                          slow_method=AllGatherMethod.RING_1D,
                          fast_method=AllGatherMethod.AUTO,
                          interpret: bool = False):
    """Two-tier AllGather of the leading dim; call inside shard_map.

    Input: this chip's shard [rows, ...] of an array sharded jointly over
    (slow_axis, fast_axis), slow-major.  Output: the full array, flat rank
    order, on every chip.
    """
    rows = x.shape[0]
    d = jax.lax.axis_size(slow_axis)
    t = jax.lax.axis_size(fast_axis)
    x = all_gather_shard(x, axis=slow_axis, method=slow_method,
                         interpret=interpret, collective_id=cid.HIER_STAGE1)
    x = all_gather_shard(x, axis=fast_axis, method=fast_method,
                         interpret=interpret, collective_id=cid.HIER_STAGE2)
    # blocks are [fast][slow]-major; restore flat (slow, fast) order
    x = x.reshape((t, d, rows) + x.shape[1:])
    x = jnp.moveaxis(x, 1, 0)
    return x.reshape((d * t * rows,) + x.shape[3:])


def hier_rs_band_index(slow_axis: str, fast_axis: str):
    """Flat band index this chip holds after ``hier_reduce_scatter_shard``:
    j * D + i for chip (i, j) — fast-major."""
    d = jax.lax.axis_size(slow_axis)
    i = jax.lax.axis_index(slow_axis)
    j = jax.lax.axis_index(fast_axis)
    return j * d + i


def _compact_bundles(bundle, inner_splits, tokens):
    """Pack each bundle's valid rows into a contiguous prefix.

    bundle [G, S, H] with S = L*tokens (L inner segments, lane-major);
    inner_splits [G, L] = valid rows per inner segment.  Returns
    (compacted bundle — valid rows first, lane-major order preserved;
    bundle_splits [G] = total valid rows), which is exactly what the
    splits-proportional flat kernel needs to move bytes ∝ tokens across
    the wire (a raw bundle interleaves padding, so its valid rows are
    not prefix-contiguous and the block DMAs could skip nothing).

    Linear-time scatter, the exact mirror of :func:`_uncompact_bundles`:
    the destination of padded row (lane, off) is cum_prev[lane] + off."""
    G, S, _ = bundle.shape
    lane = jnp.arange(S) // tokens
    off = jnp.arange(S) % tokens
    valid = off[None, :] < inner_splits[:, lane]            # [G, S]
    cum_prev = jnp.cumsum(inner_splits, axis=1) - inner_splits  # excl. scan
    pos = cum_prev[:, lane] + off[None, :]
    pos_safe = jnp.where(valid, pos, S)                     # OOB → dropped
    comp = jnp.zeros_like(bundle)
    g = jnp.broadcast_to(jnp.arange(G)[:, None], (G, S))
    comp = comp.at[g, pos_safe].set(bundle, mode="drop")
    return comp, valid.sum(axis=1).astype(jnp.int32)


def _uncompact_bundles(comp, inner_splits, tokens):
    """Inverse of :func:`_compact_bundles` at the receiver: scatter the
    valid prefix back into the padded lane-major layout (padding rows
    come out ZERO — a defined contract, unlike the flat kernel's
    undefined tail).  ``inner_splits`` are the RECEIVED per-segment
    counts."""
    G, S, H = comp.shape
    L = S // tokens
    cum = jnp.cumsum(inner_splits, axis=1)                  # [G, L]
    k = jnp.arange(S)
    # lane of compacted row k: number of cumulative boundaries <= k
    lane = jnp.sum(k[None, :, None] >= cum[:, None, :], axis=2)  # [G, S]
    prev = jnp.where(lane > 0,
                     jnp.take_along_axis(cum, jnp.maximum(lane - 1, 0),
                                         axis=1), 0)
    pos = jnp.minimum(lane, L - 1) * tokens + (k[None, :] - prev)
    valid_k = k[None, :] < cum[:, -1:]
    pos_safe = jnp.where(valid_k, pos, S)                   # OOB → dropped
    out = jnp.zeros_like(comp)
    g = jnp.broadcast_to(jnp.arange(G)[:, None], (G, S))
    return out.at[g, pos_safe].set(comp, mode="drop")


def hier_all_to_all_shard(send, splits, *, slow_axis: str, fast_axis: str,
                          impl="auto", interpret: bool = False,
                          collective_ids=(cid.HIER_A2A_SLOW, cid.HIER_A2A_FAST)):
    """Two-tier token AllToAll: every token crosses the slow wire at most
    once, then fans out inside its destination slice.

    Reference analog: ``kernel_dispatch_token`` (ep_a2a.py:35-146) — the
    DeepEP cross-node trick: tokens putmem to the *same-local-rank* peer
    on the target node first, then scatter locally to expert ranks.  Here
    the two hops are a slow-axis AllToAll of per-slice bundles followed by
    a fast-axis AllToAll within the slice.

    Contract matches the flat ``fast_all_to_all_shard`` with flat rank
    ``r = i * T_fast + j`` (slow-major): send [world, T, H] block ``d``
    goes to flat rank ``d``; recv block ``s`` arrived from flat rank
    ``s``; splits [world] i32 ride alongside.  Wire bytes are
    splits-PROPORTIONAL on both tiers (bundles are compacted before each
    hop); recv padding rows are ZERO (the flat pallas kernel leaves its
    tail undefined instead).
    """
    from triton_dist_tpu.kernels.all_to_all import fast_all_to_all_shard

    d_ = jax.lax.axis_size(slow_axis)
    t_ = jax.lax.axis_size(fast_axis)
    world, tokens, hidden = send.shape
    assert world == d_ * t_, (world, d_, t_)

    # Stage 1 (slow): bundle by destination slice; peer p along the slow
    # axis is chip (p, j_me) — the same-lane chip on slice p.  Bundled
    # rows interleave the inner segments' padding, so each bundle is
    # COMPACTED (valid rows to a prefix) before the shuffle: the flat
    # kernel's splits-proportional block DMAs then move bytes ∝ the
    # actual token counts across the slow wire (r3; round 2 shipped full
    # bundles).  The receiver scatters the prefix back into the padded
    # layout using the inner splits that ride the xla side-channel —
    # padding rows come out ZERO (defined, unlike the flat kernel's
    # undefined tail).
    inner1 = splits.reshape(d_, t_).astype(jnp.int32)
    bundles = send.reshape(d_, t_ * tokens, hidden)
    comp1, bsplits1 = _compact_bundles(bundles, inner1, tokens)
    s1c, _ = fast_all_to_all_shard(
        comp1, bsplits1, axis=slow_axis,
        impl=impl, interpret=interpret, collective_id=collective_ids[0])
    sp1, _ = fast_all_to_all_shard(
        splits.reshape(d_, t_, 1).astype(jnp.int32),
        jnp.zeros((d_,), jnp.int32), axis=slow_axis, impl="xla",
        interpret=interpret)
    s1 = _uncompact_bundles(s1c, sp1[:, :, 0], tokens)

    # s1[p] = tokens from chip (p, j_me) for every lane of MY slice:
    # [d_, t_lane, T, H] → regroup by destination lane for stage 2, and
    # compact again for the fast-axis hop.
    s1 = s1.reshape(d_, t_, tokens, hidden)
    stage2 = jnp.moveaxis(s1, 1, 0).reshape(t_, d_ * tokens, hidden)
    inner2 = jnp.moveaxis(sp1[:, :, 0], 1, 0)               # [t_, d_]
    comp2, bsplits2 = _compact_bundles(stage2, inner2, tokens)
    s2c, _ = fast_all_to_all_shard(
        comp2, bsplits2, axis=fast_axis,
        impl=impl, interpret=interpret, collective_id=collective_ids[1])
    sp2, _ = fast_all_to_all_shard(
        jnp.moveaxis(sp1, 1, 0), jnp.zeros((t_,), jnp.int32),
        axis=fast_axis, impl="xla", interpret=interpret)
    s2 = _uncompact_bundles(s2c, sp2[:, :, 0], tokens)

    # s2[q][p] = tokens from chip (p, q) → flat source order p * t_ + q.
    recv = jnp.moveaxis(s2.reshape(t_, d_, tokens, hidden), 1, 0)
    recv = recv.reshape(world, tokens, hidden)
    recv_splits = jnp.moveaxis(sp2, 1, 0).reshape(world)
    return recv, recv_splits


def hier_reduce_scatter_shard(x, *, slow_axis: str, fast_axis: str,
                              slow_method=ReduceScatterMethod.RING_1D,
                              fast_method=ReduceScatterMethod.AUTO,
                              interpret: bool = False):
    """Two-tier ReduceScatter of this chip's full-size partial.

    Output: this chip's band of the total sum (band ``hier_rs_band_index``
    of D*T bands).  DCN carries 1/T of the data it would in a flat RS.
    """
    x = reduce_scatter_shard(x, fast_axis, method=fast_method,
                             interpret=interpret, collective_id=cid.HIER_STAGE1)
    x = reduce_scatter_shard(x, slow_axis, method=slow_method,
                             interpret=interpret, collective_id=cid.HIER_STAGE2)
    return x


def hier_all_reduce_shard(x, *, slow_axis: str, fast_axis: str,
                          fast_rs=ReduceScatterMethod.AUTO,
                          fast_ag=AllGatherMethod.AUTO,
                          interpret: bool = False):
    """Two-tier AllReduce — the DCN-optimal gradient reduction.

    RS over the FAST (ICI) tier first, psum over the SLOW (DCN) tier on
    the 1/T band, AG over the fast tier: each chip ships rows/T bytes
    across DCN instead of the full tensor (reference analog: its
    inter-node gradient path reduces intra-node before touching IB,
    reduce_scatter.py:842-860).  ``x`` [rows, ...] with rows % T == 0 is
    every chip's full-size partial; returns the total sum, replicated.
    """
    from triton_dist_tpu.kernels.reduce_scatter import resolve_method
    from triton_dist_tpu.runtime import topology

    # Platform-resolve AUTO here: the shard-level kernels assume a Mosaic
    # target (or interpret mode); a plain-CPU jit (the multichip gate
    # without interpret) takes the XLA methods.
    on_mosaic = topology.is_tpu() or interpret
    if fast_rs is ReduceScatterMethod.AUTO:
        fast_rs = resolve_method(interpret)
    if fast_ag is AllGatherMethod.AUTO and not on_mosaic:
        fast_ag = AllGatherMethod.XLA

    t = jax.lax.axis_size(fast_axis)
    if t > 1:
        x = reduce_scatter_shard(x, fast_axis, method=fast_rs,
                                 interpret=interpret,
                                 collective_id=cid.HIER_STAGE1)
    x = jax.lax.psum(x, slow_axis)
    if t > 1:
        x = all_gather_shard(x, axis=fast_axis, method=fast_ag,
                             interpret=interpret,
                             collective_id=cid.HIER_STAGE2)
    return x


def hier_grad_allreduce(grads, *, slow_axis: str, fast_axis: str,
                        interpret: bool = False):
    """Tree-wide two-tier gradient allreduce for dp-over-DCN training.

    Leaves are flattened and concatenated into ONE [n, 128] plane (padded
    to T*128) so the whole tree crosses DCN as a single banded reduction
    — the bucketing every production DDP does, in two tiers.  Leaves keep
    their dtypes via a f32 wire plane (gradient sums want f32 anyway).
    """
    t = jax.lax.axis_size(fast_axis)
    leaves, treedef = jax.tree.flatten(grads)
    sizes = [int(l.size) for l in leaves]
    flat = jnp.concatenate(
        [l.reshape(-1).astype(jnp.float32) for l in leaves])
    n = flat.shape[0]
    row = 128 * t
    pad = (-n) % row
    plane = jnp.pad(flat, (0, pad)).reshape(-1, 128)
    plane = hier_all_reduce_shard(plane, slow_axis=slow_axis,
                                  fast_axis=fast_axis, interpret=interpret)
    flat = plane.reshape(-1)[:n]
    out, off = [], 0
    for leaf, size in zip(leaves, sizes):
        out.append(flat[off:off + size].reshape(leaf.shape)
                   .astype(leaf.dtype))
        off += size
    return jax.tree.unflatten(treedef, out)
